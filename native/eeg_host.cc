// Native host-side kernels for the TPU EEG framework.
//
// The TPU-native equivalent of the closed `eegloader-hdfs` jar's hot
// path (reference usage: OffLineDataProvider.java:167-196): demux of
// multiplexed int16 BrainVision samples into per-channel scaled
// float64 rows, and the stimulus-locked window gather + float32
// baseline correction (Baseline.java:29-42, EpochHolder.java:75-91).
// These are the host-side loops that feed device staging buffers;
// everything downstream is XLA.
//
// Bit-exactness contract with the Python/numpy fallback paths
// (io/brainvision.py, epochs/extractor.py):
//  - int16 -> float32, scaled by float32 resolution, widened to double;
//  - baseline = sequential float32 left-fold sum of the first `pre`
//    samples divided by float32(pre); subtraction in float32;
//  - windows running past the end of the recording zero-pad (Java's
//    Arrays.copyOfRange semantics); windows starting out of range are
//    marked invalid.
// Compiled without -ffast-math so float arithmetic is strict IEEE.

#include <cstdint>
#include <cstring>

extern "C" {

// Demux `n_sel` channels out of a multiplexed (n_samples, n_channels)
// int16 block: out[k][s] = (double)((float)raw[s*C + idx[k]] * res[k]).
// `out` is (n_sel, n_samples) row-major float64.
void eeg_demux_int16(const int16_t* raw, int64_t n_samples,
                     int64_t n_channels, const int64_t* sel_indices,
                     int64_t n_sel, const float* resolutions, double* out) {
  for (int64_t k = 0; k < n_sel; ++k) {
    const int64_t ch = sel_indices[k];
    const float res = resolutions[k];
    double* row = out + k * n_samples;
    const int16_t* base = raw + ch;
    for (int64_t s = 0; s < n_samples; ++s) {
      const float v = static_cast<float>(base[s * n_channels]) * res;
      row[s] = static_cast<double>(v);
    }
  }
}

// Same demux for VECTORIZED orientation: raw is (n_channels, n_samples).
void eeg_demux_int16_vectorized(const int16_t* raw, int64_t n_samples,
                                int64_t n_channels,
                                const int64_t* sel_indices, int64_t n_sel,
                                const float* resolutions, double* out) {
  for (int64_t k = 0; k < n_sel; ++k) {
    const int16_t* src = raw + sel_indices[k] * n_samples;
    const float res = resolutions[k];
    double* row = out + k * n_samples;
    for (int64_t s = 0; s < n_samples; ++s) {
      row[s] = static_cast<double>(static_cast<float>(src[s]) * res);
    }
  }
}

// Validity of marker windows [pos-pre, pos+post): a window is kept iff
// pos-pre >= 0 and pos-pre <= n_samples (Java copyOfRange throws only
// on a negative/overshooting *from*; a `to` past the end zero-pads —
// OffLineDataProvider.java:262-264). Returns the number of valid rows.
int64_t eeg_valid_windows(const int64_t* positions, int64_t n_pos,
                          int64_t pre, int64_t n_samples, uint8_t* valid) {
  int64_t n_valid = 0;
  for (int64_t i = 0; i < n_pos; ++i) {
    const int64_t start = positions[i] - pre;
    const bool ok = start >= 0 && start <= n_samples;
    valid[i] = ok ? 1 : 0;
    n_valid += ok ? 1 : 0;
  }
  return n_valid;
}

// Gather + float32 baseline-correct the valid windows.
//   channels: (n_channels, n_samples) float64 (demux output)
//   positions/valid: as produced by eeg_valid_windows
//   out: (n_valid, n_channels, post) float64 — the 750-sample epochs
//        with the pre-stimulus prefix dropped (EpochHolder offset).
void eeg_gather_baseline(const double* channels, int64_t n_channels,
                         int64_t n_samples, const int64_t* positions,
                         const uint8_t* valid, int64_t n_pos, int64_t pre,
                         int64_t post, double* out) {
  const int64_t win = pre + post;
  int64_t row = 0;
  for (int64_t i = 0; i < n_pos; ++i) {
    if (!valid[i]) continue;
    const int64_t start = positions[i] - pre;
    for (int64_t c = 0; c < n_channels; ++c) {
      const double* src = channels + c * n_samples;
      // narrow the window to float32 (DataProviderUtils.toFloatArray)
      float w32[4096];  // win <= 4096 enforced by the binding
      for (int64_t t = 0; t < win; ++t) {
        const int64_t idx = start + t;
        w32[t] = idx < n_samples ? static_cast<float>(src[idx]) : 0.0f;
      }
      // sequential float32 baseline fold (Baseline.java:29-42)
      float sum = 0.0f;
      for (int64_t t = 0; t < pre; ++t) sum += w32[t];
      const float baseline = sum / static_cast<float>(pre);
      double* dst = out + (row * n_channels + c) * post;
      for (int64_t t = 0; t < post; ++t) {
        dst[t] = static_cast<double>(w32[pre + t] - baseline);
      }
    }
    ++row;
  }
}

// The order-dependent class-balance scan
// (OffLineDataProvider.java:248-260). counters[0]=n_targets,
// counters[1]=n_nontargets persist across files of a run.
void eeg_balance_scan(const uint8_t* is_target, int64_t n, int64_t* counters,
                      uint8_t* keep) {
  int64_t n_t = counters[0], n_nt = counters[1];
  for (int64_t i = 0; i < n; ++i) {
    if (is_target[i]) {
      if (n_t <= n_nt) {
        keep[i] = 1;
        ++n_t;
      } else {
        keep[i] = 0;
      }
    } else {
      if (n_t >= n_nt) {
        keep[i] = 1;
        ++n_nt;
      } else {
        keep[i] = 0;
      }
    }
  }
  counters[0] = n_t;
  counters[1] = n_nt;
}

}  // extern "C"
