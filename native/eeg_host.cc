// Native host-side kernels for the TPU EEG framework.
//
// The TPU-native equivalent of the closed `eegloader-hdfs` jar's hot
// path (reference usage: OffLineDataProvider.java:167-196): demux of
// multiplexed int16 BrainVision samples into per-channel scaled
// float64 rows, and the stimulus-locked window gather + float32
// baseline correction (Baseline.java:29-42, EpochHolder.java:75-91).
// These are the host-side loops that feed device staging buffers;
// everything downstream is XLA.
//
// Bit-exactness contract with the Python/numpy fallback paths
// (io/brainvision.py, epochs/extractor.py):
//  - int16 -> float32, scaled by float32 resolution, widened to double;
//  - baseline = sequential float32 left-fold sum of the first `pre`
//    samples divided by float32(pre); subtraction in float32;
//  - windows running past the end of the recording zero-pad (Java's
//    Arrays.copyOfRange semantics); windows starting out of range are
//    marked invalid.
// Compiled without -ffast-math so float arithmetic is strict IEEE.

#include <algorithm>
#include <cctype>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <utility>
#include <vector>

// ---------------------------------------------------------------------------
// BrainVision .vhdr/.vmrk parsing (the header-file half of the closed
// eegloader-hdfs jar: getChannelInfo / readMarkerList,
// OffLineDataProvider.java:167-196). Semantics are kept in lockstep
// with the Python fallback parser (io/brainvision.py::_parse_ini /
// parse_vhdr / parse_vmrk); any input the C++ side cannot represent
// exactly (numeric parse failure, field overflow) returns a negative
// status so the binding falls back to Python instead of diverging.
// ---------------------------------------------------------------------------

namespace {

struct IniSection {
  std::string name;
  std::vector<std::pair<std::string, std::string>> kv;
};

bool is_space(char c) { return std::isspace(static_cast<unsigned char>(c)); }

std::string trim_ws(const std::string& s) {
  size_t b = 0, e = s.size();
  while (b < e && is_space(s[b])) ++b;
  while (e > b && is_space(s[e - 1])) --e;
  return s.substr(b, e - b);
}

IniSection* find_section(std::vector<IniSection>& secs, const std::string& n) {
  for (auto& s : secs)
    if (s.name == n) return &s;
  return nullptr;
}

const std::string* find_key(const IniSection* s, const std::string& key) {
  if (!s) return nullptr;
  for (const auto& p : s->kv)
    if (p.first == key) return &p.second;
  return nullptr;
}

// Mirrors io/brainvision.py::_parse_ini: sections, key=value with keys
// free of '=' and ';', ';'-led lines skipped, duplicate sections
// merged, duplicate keys overwritten in place (dict semantics).
void parse_ini(const char* text, int64_t len, std::vector<IniSection>& out) {
  IniSection* current = nullptr;
  int64_t i = 0;
  while (i < len) {
    int64_t j = i;
    while (j < len && text[j] != '\n') ++j;
    std::string line(text + i, text + j);
    i = j + 1;
    // strip('\r\n') on both ends
    size_t b = 0, e = line.size();
    while (b < e && (line[b] == '\r' || line[b] == '\n')) ++b;
    while (e > b && (line[e - 1] == '\r' || line[e - 1] == '\n')) --e;
    line = line.substr(b, e - b);

    // skip blank lines and ';' comments (after lstrip of whitespace)
    size_t first = 0;
    while (first < line.size() && is_space(line[first])) ++first;
    if (first == line.size() || line[first] == ';') continue;

    // section header: ^\[(.+)\]\s*$ on the whitespace-stripped line
    const std::string stripped = trim_ws(line);
    if (stripped.size() >= 3 && stripped.front() == '[' &&
        stripped.back() == ']') {
      const std::string name = stripped.substr(1, stripped.size() - 2);
      current = find_section(out, name);
      if (!current) {
        out.push_back(IniSection{name, {}});
        current = &out.back();
      }
      continue;
    }
    if (!current) continue;

    // key=value: ^([^=;]+)=(.*)$ — key up to the first '=', no ';'
    const size_t eq = line.find('=');
    if (eq == std::string::npos || eq == 0) continue;
    if (line.find(';') < eq) continue;
    const std::string key = trim_ws(line.substr(0, eq));
    if (key.empty()) continue;  // key was all whitespace
    std::string value = line.substr(eq + 1);
    bool replaced = false;
    for (auto& p : current->kv) {
      if (p.first == key) {
        p.second = std::move(value);
        replaced = true;
        break;
      }
    }
    if (!replaced) current->kv.emplace_back(key, std::move(value));
  }
}

void split_commas(const std::string& s, std::vector<std::string>& parts) {
  parts.clear();
  size_t start = 0;
  while (true) {
    const size_t c = s.find(',', start);
    if (c == std::string::npos) {
      parts.push_back(s.substr(start));
      return;
    }
    parts.push_back(s.substr(start, c - start));
    start = c + 1;
  }
}

// "\1" encodes ',' in channel/marker names per the format spec.
std::string unescape_name(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (size_t i = 0; i < s.size(); ++i) {
    if (s[i] == '\\' && i + 1 < s.size() && s[i + 1] == '1') {
      out.push_back(',');
      ++i;
    } else {
      out.push_back(s[i]);
    }
  }
  return out;
}

// Python float(): whitespace-trimmed decimal/scientific with optional
// digit-group underscores; rejects the hex floats and NAN(char-seq)
// forms strtod would accept. Inputs with underscores fall back to the
// Python parser (return false -> caller reports unrepresentable).
bool parse_float_py(const std::string& raw, double* out) {
  const std::string s = trim_ws(raw);
  if (s.empty()) return false;
  for (char c : s)
    if (c == 'x' || c == 'X' || c == '(' || c == '_') return false;
  errno = 0;
  char* end = nullptr;
  const double v = std::strtod(s.c_str(), &end);
  if (end != s.c_str() + s.size() || errno == ERANGE) return false;
  *out = v;
  return true;
}

// Python int(): whitespace-trimmed optional-sign digit run with
// optional single underscores between digits. Three-way result so
// callers can mirror Python exactly: kOk (value parsed), kBad (Python
// int() raises ValueError too), kUnrepresentable (Python would
// succeed but we cannot — int64 overflow — so the whole parse must
// fall back to Python).
enum class IntParse { kOk, kBad, kUnrepresentable };

IntParse parse_int_py(const std::string& raw, int64_t* out) {
  const std::string s = trim_ws(raw);
  size_t p = 0;
  if (p < s.size() && (s[p] == '+' || s[p] == '-')) ++p;
  if (p == s.size()) return IntParse::kBad;
  // grammar: digit (('_')? digit)* — no leading/trailing/double '_'
  std::string digits(s.substr(0, p));
  bool prev_digit = false;
  for (size_t q = p; q < s.size(); ++q) {
    const char c = s[q];
    if (std::isdigit(static_cast<unsigned char>(c))) {
      digits.push_back(c);
      prev_digit = true;
    } else if (c == '_') {
      if (!prev_digit || q + 1 == s.size()) return IntParse::kBad;
      prev_digit = false;
    } else {
      return IntParse::kBad;
    }
  }
  if (!prev_digit) return IntParse::kBad;
  errno = 0;
  char* end = nullptr;
  const long long v = std::strtoll(digits.c_str(), &end, 10);
  if (end != digits.c_str() + digits.size() || errno == ERANGE)
    return IntParse::kUnrepresentable;  // Python ints are unbounded
  *out = v;
  return IntParse::kOk;
}

// Keys like "Ch12" / "Mk3": prefix + all-digits remainder. kBad when
// the key is not of that shape (Python skips it too); kUnrepresentable
// when the number overflows int64 (Python would keep the key).
IntParse numbered_key(const std::string& key, const char* prefix,
                      int64_t* num) {
  const size_t plen = std::strlen(prefix);
  if (key.size() <= plen || key.compare(0, plen, prefix) != 0)
    return IntParse::kBad;
  for (size_t i = plen; i < key.size(); ++i)
    if (!std::isdigit(static_cast<unsigned char>(key[i])))
      return IntParse::kBad;
  return parse_int_py(key.substr(plen), num);
}

bool copy_str(const std::string& s, char* dst, size_t cap) {
  if (s.size() >= cap) return false;
  std::memcpy(dst, s.data(), s.size());
  dst[s.size()] = '\0';
  return true;
}

}  // namespace

extern "C" {

// Struct layouts mirror the ctypes.Structure definitions in
// io/native.py (wide fields first so there is no padding to disagree
// about).
typedef struct {
  double sampling_interval_us;
  int64_t num_channels;
  char data_file[256];
  char marker_file[256];
  char data_format[32];
  char orientation[32];
  char binary_format[32];
} EegHeaderInfo;

typedef struct {
  double resolution;
  int64_t number;
  char name[128];
  char reference[64];
  char units[32];
} EegChannelInfo;

typedef struct {
  int64_t position;
  char name[32];
  char kind[64];
  char stimulus[64];
} EegMarkerInfo;

// Parse a .vhdr header. Returns the number of channels written, or
// -1 if max_channels is too small, or -2 when the input needs the
// Python parser (numeric parse failure / oversized field).
int64_t eeg_parse_vhdr(const char* text, int64_t len, EegHeaderInfo* hdr,
                       EegChannelInfo* channels, int64_t max_channels) {
  std::vector<IniSection> secs;
  parse_ini(text, len, secs);
  const IniSection* common = find_section(secs, "Common Infos");
  const IniSection* binary = find_section(secs, "Binary Infos");
  const IniSection* chan = find_section(secs, "Channel Infos");

  struct ChEntry {
    int64_t number;
    const std::string* value;
  };
  std::vector<ChEntry> entries;
  if (chan) {
    for (const auto& p : chan->kv) {
      int64_t num;
      const IntParse r = numbered_key(p.first, "Ch", &num);
      if (r == IntParse::kUnrepresentable) return -2;
      if (r == IntParse::kOk) entries.push_back(ChEntry{num, &p.second});
    }
  }
  std::stable_sort(entries.begin(), entries.end(),
                   [](const ChEntry& a, const ChEntry& b) {
                     return a.number < b.number;
                   });
  if (static_cast<int64_t>(entries.size()) > max_channels) return -1;

  std::vector<std::string> parts;
  for (size_t k = 0; k < entries.size(); ++k) {
    split_commas(*entries[k].value, parts);
    EegChannelInfo* c = &channels[k];
    c->number = entries[k].number;
    double res = 1.0;
    if (parts.size() > 2 && !parts[2].empty() &&
        !parse_float_py(parts[2], &res))
      return -2;
    c->resolution = res;
    if (!copy_str(unescape_name(parts[0]), c->name, sizeof(c->name)) ||
        !copy_str(parts.size() > 1 ? parts[1] : "", c->reference,
                  sizeof(c->reference)) ||
        !copy_str(parts.size() > 3 ? parts[3] : "uV", c->units,
                  sizeof(c->units)))
      return -2;
  }

  const std::string* v;
  std::string data_file, marker_file;
  std::string data_format = "BINARY", orientation = "MULTIPLEXED";
  std::string binary_format = "INT_16";
  if ((v = find_key(common, "DataFile"))) data_file = *v;
  if ((v = find_key(common, "MarkerFile"))) marker_file = *v;
  if ((v = find_key(common, "DataFormat"))) data_format = *v;
  if ((v = find_key(common, "DataOrientation"))) orientation = *v;
  if ((v = find_key(binary, "BinaryFormat"))) binary_format = *v;

  int64_t num_channels =
      entries.empty() ? 1 : static_cast<int64_t>(entries.size());
  if ((v = find_key(common, "NumberOfChannels")) &&
      parse_int_py(*v, &num_channels) != IntParse::kOk)
    return -2;  // Python raises (kBad) or parses a bigint (kUnrepresentable)

  double interval = 1000.0;
  if ((v = find_key(common, "SamplingInterval")) &&
      !parse_float_py(*v, &interval))
    return -2;

  hdr->sampling_interval_us = interval;
  hdr->num_channels = num_channels;
  if (!copy_str(data_file, hdr->data_file, sizeof(hdr->data_file)) ||
      !copy_str(marker_file, hdr->marker_file, sizeof(hdr->marker_file)) ||
      !copy_str(data_format, hdr->data_format, sizeof(hdr->data_format)) ||
      !copy_str(orientation, hdr->orientation, sizeof(hdr->orientation)) ||
      !copy_str(binary_format, hdr->binary_format,
                sizeof(hdr->binary_format)))
    return -2;
  return static_cast<int64_t>(entries.size());
}

// Parse a .vmrk marker file. Returns the number of markers written,
// -1 if max_markers is too small, -2 when Python must take over.
int64_t eeg_parse_vmrk(const char* text, int64_t len, EegMarkerInfo* out,
                       int64_t max_markers) {
  std::vector<IniSection> secs;
  parse_ini(text, len, secs);
  const IniSection* infos = find_section(secs, "Marker Infos");
  if (!infos) return 0;

  struct MkEntry {
    int64_t number;
    const std::string* key;
    const std::string* value;
  };
  std::vector<MkEntry> entries;
  for (const auto& p : infos->kv) {
    int64_t num;
    const IntParse r = numbered_key(p.first, "Mk", &num);
    if (r == IntParse::kUnrepresentable) return -2;
    if (r == IntParse::kOk)
      entries.push_back(MkEntry{num, &p.first, &p.second});
  }
  std::stable_sort(entries.begin(), entries.end(),
                   [](const MkEntry& a, const MkEntry& b) {
                     return a.number < b.number;
                   });
  if (static_cast<int64_t>(entries.size()) > max_markers) return -1;

  std::vector<std::string> parts;
  for (size_t k = 0; k < entries.size(); ++k) {
    split_commas(*entries[k].value, parts);
    EegMarkerInfo* m = &out[k];
    int64_t pos = 0;
    if (parts.size() > 2) {
      const IntParse r = parse_int_py(parts[2], &pos);
      if (r == IntParse::kUnrepresentable) return -2;
      if (r == IntParse::kBad) pos = 0;  // int() ValueError -> 0
    }
    m->position = pos;
    if (!copy_str(*entries[k].key, m->name, sizeof(m->name)) ||
        !copy_str(parts[0], m->kind, sizeof(m->kind)) ||
        !copy_str(parts.size() > 1 ? unescape_name(parts[1]) : "",
                  m->stimulus, sizeof(m->stimulus)))
      return -2;
  }
  return static_cast<int64_t>(entries.size());
}

// Demux `n_sel` channels out of a multiplexed (n_samples, n_channels)
// int16 block: out[k][s] = (double)((float)raw[s*C + idx[k]] * res[k]).
// `out` is (n_sel, n_samples) row-major float64.
void eeg_demux_int16(const int16_t* raw, int64_t n_samples,
                     int64_t n_channels, const int64_t* sel_indices,
                     int64_t n_sel, const float* resolutions, double* out) {
  for (int64_t k = 0; k < n_sel; ++k) {
    const int64_t ch = sel_indices[k];
    const float res = resolutions[k];
    double* row = out + k * n_samples;
    const int16_t* base = raw + ch;
    for (int64_t s = 0; s < n_samples; ++s) {
      const float v = static_cast<float>(base[s * n_channels]) * res;
      row[s] = static_cast<double>(v);
    }
  }
}

// Same demux for VECTORIZED orientation: raw is (n_channels, n_samples).
void eeg_demux_int16_vectorized(const int16_t* raw, int64_t n_samples,
                                int64_t n_channels,
                                const int64_t* sel_indices, int64_t n_sel,
                                const float* resolutions, double* out) {
  for (int64_t k = 0; k < n_sel; ++k) {
    const int16_t* src = raw + sel_indices[k] * n_samples;
    const float res = resolutions[k];
    double* row = out + k * n_samples;
    for (int64_t s = 0; s < n_samples; ++s) {
      row[s] = static_cast<double>(static_cast<float>(src[s]) * res);
    }
  }
}

// Validity of marker windows [pos-pre, pos+post): a window is kept iff
// pos-pre >= 0 and pos-pre <= n_samples (Java copyOfRange throws only
// on a negative/overshooting *from*; a `to` past the end zero-pads —
// OffLineDataProvider.java:262-264). Returns the number of valid rows.
int64_t eeg_valid_windows(const int64_t* positions, int64_t n_pos,
                          int64_t pre, int64_t n_samples, uint8_t* valid) {
  int64_t n_valid = 0;
  for (int64_t i = 0; i < n_pos; ++i) {
    const int64_t start = positions[i] - pre;
    const bool ok = start >= 0 && start <= n_samples;
    valid[i] = ok ? 1 : 0;
    n_valid += ok ? 1 : 0;
  }
  return n_valid;
}

// Gather + float32 baseline-correct the valid windows.
//   channels: (n_channels, n_samples) float64 (demux output)
//   positions/valid: as produced by eeg_valid_windows
//   out: (n_valid, n_channels, post) float64 — the 750-sample epochs
//        with the pre-stimulus prefix dropped (EpochHolder offset).
void eeg_gather_baseline(const double* channels, int64_t n_channels,
                         int64_t n_samples, const int64_t* positions,
                         const uint8_t* valid, int64_t n_pos, int64_t pre,
                         int64_t post, double* out) {
  const int64_t win = pre + post;
  int64_t row = 0;
  for (int64_t i = 0; i < n_pos; ++i) {
    if (!valid[i]) continue;
    const int64_t start = positions[i] - pre;
    for (int64_t c = 0; c < n_channels; ++c) {
      const double* src = channels + c * n_samples;
      // narrow the window to float32 (DataProviderUtils.toFloatArray)
      float w32[4096];  // win <= 4096 enforced by the binding
      for (int64_t t = 0; t < win; ++t) {
        const int64_t idx = start + t;
        w32[t] = idx < n_samples ? static_cast<float>(src[idx]) : 0.0f;
      }
      // sequential float32 baseline fold (Baseline.java:29-42)
      float sum = 0.0f;
      for (int64_t t = 0; t < pre; ++t) sum += w32[t];
      const float baseline = sum / static_cast<float>(pre);
      double* dst = out + (row * n_channels + c) * post;
      for (int64_t t = 0; t < post; ++t) {
        dst[t] = static_cast<double>(w32[pre + t] - baseline);
      }
    }
    ++row;
  }
}

// The order-dependent class-balance scan
// (OffLineDataProvider.java:248-260). counters[0]=n_targets,
// counters[1]=n_nontargets persist across files of a run.
void eeg_balance_scan(const uint8_t* is_target, int64_t n, int64_t* counters,
                      uint8_t* keep) {
  int64_t n_t = counters[0], n_nt = counters[1];
  for (int64_t i = 0; i < n; ++i) {
    if (is_target[i]) {
      if (n_t <= n_nt) {
        keep[i] = 1;
        ++n_t;
      } else {
        keep[i] = 0;
      }
    } else {
      if (n_t >= n_nt) {
        keep[i] = 1;
        ++n_nt;
      } else {
        keep[i] = 0;
      }
    }
  }
  counters[0] = n_t;
  counters[1] = n_nt;
}

}  // extern "C"
