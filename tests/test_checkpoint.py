"""Checkpoint/resume subsystem tests (net-new vs the reference, which
only persists finished models — SURVEY.md section 5)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.checkpoint import CheckpointManager, run_resumable
from eeg_dataanalysispackage_tpu.parallel import mesh as pmesh, train as ptrain


def _tree_equal(a, b):
    flat_a = jax.tree_util.tree_leaves(a)
    flat_b = jax.tree_util.tree_leaves(b)
    assert len(flat_a) == len(flat_b)
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_save_restore_roundtrip(tmp_path):
    init_state, _ = ptrain.make_train_step()
    state = init_state(jax.random.PRNGKey(0))
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(3, state, extra={"note": "x"})

    restored, meta = mgr.restore(init_state(jax.random.PRNGKey(1)))
    assert meta["step"] == 3 and meta["extra"]["note"] == "x"
    _tree_equal(restored, state)


def test_latest_and_retention(tmp_path):
    mgr = CheckpointManager(str(tmp_path), max_to_keep=2)
    state = {"w": np.arange(4.0)}
    for s in (1, 2, 3, 4):
        mgr.save(s, {"w": np.arange(4.0) + s})
    assert mgr.all_steps() == [3, 4]
    assert mgr.latest_step() == 4
    restored, _ = mgr.restore(state, step=3)
    np.testing.assert_array_equal(restored["w"], np.arange(4.0) + 3)


def test_restore_empty_raises(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    with pytest.raises(FileNotFoundError):
        mgr.restore({"w": np.zeros(2)})


def test_sharded_state_roundtrips_with_sharding(tmp_path):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = pmesh.make_mesh(8)
    init_state, train_step = ptrain.make_train_step(mesh)
    state = init_state(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    ep, lb, mask = ptrain.stage_batch(
        rng.randn(17, 3, 750).astype(np.float32),
        (rng.rand(17) > 0.5).astype(np.float32),
        mesh,
    )
    state, _ = train_step(state, ep, lb, mask)

    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, state)
    restored, _ = mgr.restore(init_state(jax.random.PRNGKey(9)))
    _tree_equal(restored, state)
    # restored params adopt the template's (replicated) sharding and
    # keep training without recompilation surprises
    state2, loss = train_step(restored, ep, lb, mask)
    assert np.isfinite(float(loss))


def test_run_resumable_resumes_mid_run(tmp_path):
    """Simulate a crash after 5 steps; the rerun must continue from the
    checkpoint, not restart, and land on the same final state as an
    uninterrupted run."""
    init_state, train_step = ptrain.make_train_step()
    rng = np.random.RandomState(4)
    epochs = rng.randn(16, 3, 750).astype(np.float32)
    labels = (rng.rand(16) > 0.5).astype(np.float32)
    mask = np.ones(16, np.float32)
    batches = [(epochs, labels, mask)] * 9

    def init():
        return init_state(jax.random.PRNGKey(0))

    # uninterrupted reference run
    ref_state = init()
    for b in batches:
        ref_state, _ = train_step(ref_state, *b)

    mgr = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=3)
    seen = []

    class Crash(Exception):
        pass

    def crash_at_6(step, loss):
        seen.append(step)
        if step == 6:
            raise Crash

    with pytest.raises(Crash):
        run_resumable(mgr, init, train_step, batches, save_every=5,
                      on_step=crash_at_6)
    assert mgr.latest_step() == 5

    state, last = run_resumable(mgr, init, train_step, batches, save_every=5)
    assert last == 9
    _tree_equal(state, ref_state)
    # final partial step is also checkpointed
    assert mgr.latest_step() == 9


def test_atomic_write_leaves_no_tmp(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(7, {"w": np.ones(3)})
    assert not [n for n in os.listdir(tmp_path) if n.startswith(".tmp")]


def test_overwrite_same_step_never_loses_checkpoint(tmp_path):
    """Overwriting a step displaces the old dir instead of deleting it;
    a crash between the renames is repaired on the next manager init
    (code-review finding)."""
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(5, {"w": np.ones(3)})
    mgr.save(5, {"w": np.ones(3) * 2})  # clean overwrite works
    restored, _ = mgr.restore({"w": np.zeros(3)}, step=5)
    np.testing.assert_array_equal(restored["w"], np.ones(3) * 2)

    # simulate the crash window: final renamed away, .old left behind
    final = mgr._step_dir(5)
    os.rename(final, os.path.join(str(tmp_path), ".old-00000005"))
    assert CheckpointManager(str(tmp_path)).latest_step() == 5
    restored, _ = CheckpointManager(str(tmp_path)).restore(
        {"w": np.zeros(3)}, step=5
    )
    np.testing.assert_array_equal(restored["w"], np.ones(3) * 2)


def test_recover_discards_partial_tmp(tmp_path):
    os.makedirs(tmp_path / ".tmp-00000009")
    mgr = CheckpointManager(str(tmp_path))
    assert mgr.all_steps() == []
    assert not (tmp_path / ".tmp-00000009").exists()


def test_resumable_irregular_raw_stream_training(tmp_path):
    """The checkpoint/resume loop composes with the irregular
    raw-stream train step (parallel/train.make_irregular_train_step):
    crash after a few steps, resume, and land bit-identical to an
    uninterrupted run — the full int16-stream recovery story."""
    rng = np.random.RandomState(9)
    S = 60_000
    raw = jnp.asarray(
        rng.randint(-3000, 3000, size=(3, S)).astype(np.int16)
    )
    res = jnp.asarray(np.array([0.1, 0.1, 0.2], np.float32))
    cap = 64

    def batches():
        # each "batch" is a fresh marker plan over the same stream
        for k in range(7):
            r = np.random.RandomState(100 + k)
            pos = np.sort(
                r.choice(np.arange(200, S - 900), size=cap, replace=False)
            ).astype(np.int32)
            mask = np.ones(cap, bool)
            lbl = (r.rand(cap) > 0.5).astype(np.float32)
            yield (raw, res, jnp.asarray(pos), jnp.asarray(mask),
                   jnp.asarray(lbl))

    init_state, step = ptrain.make_irregular_train_step()

    def init():
        return init_state(jax.random.PRNGKey(3))

    # uninterrupted reference
    ref = CheckpointManager(str(tmp_path / "ref"))
    ref_state, ref_steps = run_resumable(ref, init, step, batches(),
                                         save_every=3)
    assert ref_steps == 7

    # crash after 4 steps, then resume
    crash = CheckpointManager(str(tmp_path / "crash"))

    class Boom(Exception):
        pass

    def exploding(n):
        for i, b in enumerate(batches()):
            if i == n:
                raise Boom()
            yield b

    with pytest.raises(Boom):
        run_resumable(crash, init, step, exploding(4), save_every=3)
    state, steps = run_resumable(crash, init, step, batches(),
                                 save_every=3)
    assert steps == 7
    _tree_equal(state, ref_state)  # params AND optimizer buffers


def test_atomic_write_bytes_replaces_whole_or_not_at_all(tmp_path):
    from eeg_dataanalysispackage_tpu.checkpoint.manager import (
        atomic_write_bytes,
        atomic_write_text,
    )

    target = tmp_path / "report.txt"
    atomic_write_text(str(target), "first version\n")
    assert target.read_text() == "first version\n"
    # overwrite goes through a tmp sibling + os.replace: the old
    # content survives any crash before the rename
    atomic_write_bytes(str(target), b"second version\n")
    assert target.read_bytes() == b"second version\n"
    # no tmp litter left behind
    assert [p.name for p in tmp_path.iterdir()] == ["report.txt"]


def test_atomic_write_failure_leaves_previous_content(tmp_path, monkeypatch):
    from eeg_dataanalysispackage_tpu.checkpoint import manager

    target = tmp_path / "report.txt"
    manager.atomic_write_text(str(target), "good\n")

    real_replace = os.replace

    def exploding_replace(src, dst):
        raise OSError("simulated crash at rename")

    monkeypatch.setattr(os, "replace", exploding_replace)
    with pytest.raises(OSError, match="simulated crash"):
        manager.atomic_write_text(str(target), "half-written garbage\n")
    monkeypatch.setattr(os, "replace", real_replace)
    # the target was never touched, and the tmp file was cleaned up
    assert target.read_text() == "good\n"
    assert [p.name for p in tmp_path.iterdir()] == ["report.txt"]


def test_atomic_write_durability_fsyncs_file_then_dir(tmp_path, monkeypatch):
    """ISSUE-6 satellite: the full durability recipe — fsync the tmp
    file BEFORE os.replace (data blocks on disk) and fsync the
    directory AFTER it (the rename on disk), so a crash right after a
    'successful' atomic write cannot replay as a zero-length
    artifact."""
    from eeg_dataanalysispackage_tpu.checkpoint import manager

    sequence = []
    real_fsync, real_replace = os.fsync, os.replace

    def spy_fsync(fd):
        sequence.append("fsync_file")
        real_fsync(fd)

    def spy_replace(src, dst):
        sequence.append("replace")
        real_replace(src, dst)

    def spy_fsync_dir(directory):
        sequence.append(("fsync_dir", directory))

    monkeypatch.setattr(os, "fsync", spy_fsync)
    monkeypatch.setattr(os, "replace", spy_replace)
    monkeypatch.setattr(manager, "_fsync_directory", spy_fsync_dir)

    target = tmp_path / "artifact.json"
    manager.atomic_write_bytes(str(target), b"payload")
    assert target.read_bytes() == b"payload"
    assert sequence == [
        "fsync_file", "replace", ("fsync_dir", str(tmp_path)),
    ]


def test_fsync_directory_survives_unsyncable_dirs(tmp_path):
    """Best-effort contract: platforms refusing directory fds degrade
    to the old (weaker) guarantee instead of failing the write."""
    from eeg_dataanalysispackage_tpu.checkpoint import manager

    manager._fsync_directory(str(tmp_path))  # real dir: no raise
    manager._fsync_directory(str(tmp_path / "does-not-exist"))
