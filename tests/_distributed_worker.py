"""Worker process for the two-process collective test.

Launched by tests/test_distributed.py with JAX_COORDINATOR_ADDRESS /
JAX_NUM_PROCESSES / JAX_PROCESS_ID in the environment. Runs the
framework's real multi-host path — distributed.initialize ->
hybrid_mesh -> stage_global_batch -> cross-process collectives over
gloo — and prints one JSON line of results for the parent to check.
"""

import json
import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp
import numpy as np

from eeg_dataanalysispackage_tpu.parallel import distributed


def main() -> None:
    distributed.initialize()  # env-driven bootstrap
    pid = jax.process_index()
    mesh = distributed.hybrid_mesh()

    # each process stages only its own shard of the global batch
    local = np.arange(6, dtype=np.float32).reshape(2, 3) + 10 * pid
    batch = distributed.stage_global_batch(local, mesh)
    assert batch.shape == (4, 3), batch.shape

    # cross-process reduction (gloo under XLA): global sum
    total = float(jax.jit(jnp.sum)(batch))

    # parameter broadcast + gradient that reduces over the DCN axis
    params = distributed.replicate_across_hosts(
        {"w": np.full(3, 2.0, dtype=np.float32)}, mesh
    )
    grad = jax.jit(
        lambda w, x: jax.grad(lambda w_: jnp.sum(x @ w_))(w)
    )(params["w"], batch)

    print(
        json.dumps(
            {
                "pid": pid,
                "procs": jax.process_count(),
                "devices": jax.device_count(),
                "mesh": dict(mesh.shape),
                "total": total,
                "wsum": float(jnp.sum(params["w"])),
                "grad": np.asarray(grad).tolist(),
            }
        )
    )


if __name__ == "__main__":
    main()
