"""Worker process for the two-process collective test.

Launched by tests/test_distributed.py with JAX_COORDINATOR_ADDRESS /
JAX_NUM_PROCESSES / JAX_PROCESS_ID in the environment. Runs the
framework's real multi-host path — distributed.initialize ->
hybrid_mesh -> stage_global_batch -> cross-process collectives over
gloo — and prints one JSON line of results for the parent to check.
"""

import json
import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp
import numpy as np

from eeg_dataanalysispackage_tpu.io.brainvision import Marker
from eeg_dataanalysispackage_tpu.ops import device_ingest
from eeg_dataanalysispackage_tpu.parallel import (
    distributed,
    mesh as pmesh,
    sharded_ingest,
    streaming,
    train as ptrain,
)


def main() -> None:
    distributed.initialize()  # env-driven bootstrap
    pid = jax.process_index()
    mesh = distributed.hybrid_mesh()

    # each process stages only its own shard of the global batch
    local = np.arange(6, dtype=np.float32).reshape(2, 3) + 10 * pid
    batch = distributed.stage_global_batch(local, mesh)
    assert batch.shape == (4, 3), batch.shape

    # cross-process reduction (gloo under XLA): global sum
    total = float(jax.jit(jnp.sum)(batch))

    # parameter broadcast + gradient that reduces over the DCN axis
    params = distributed.replicate_across_hosts(
        {"w": np.full(3, 2.0, dtype=np.float32)}, mesh
    )
    grad = jax.jit(
        lambda w, x: jax.grad(lambda w_: jnp.sum(x @ w_))(w)
    )(params["w"], batch)

    # ---- full flagship train step over the hybrid mesh --------------
    rng = np.random.RandomState(0)
    epochs_global = rng.randn(4, 3, 750).astype(np.float32)
    labels_global = (rng.rand(4) > 0.5).astype(np.float32)
    init_state, train_step = ptrain.make_train_step()
    state = distributed.replicate_across_hosts(
        jax.tree_util.tree_map(
            np.asarray, init_state(jax.random.PRNGKey(0))
        ),
        mesh,
    )
    ep = distributed.stage_global_batch(epochs_global[2 * pid : 2 * pid + 2], mesh)
    lb = distributed.stage_global_batch(labels_global[2 * pid : 2 * pid + 2], mesh)
    mk = distributed.stage_global_batch(np.ones(2, np.float32), mesh)
    _, loss = train_step(state, ep, lb, mk)
    loss = float(loss)

    # ---- sequence-parallel streaming: halo crosses the process
    # boundary over DCN ----------------------------------------------
    rng2 = np.random.RandomState(1)
    sig_global = rng2.randn(2, 2048).astype(np.float32) * 30.0
    tmesh = pmesh.make_mesh(4, axes=(pmesh.TIME_AXIS,))
    extract = streaming.make_streaming_extractor(tmesh, window=512, stride=256)
    staged = streaming.stage_recording_local(
        sig_global[:, 1024 * pid : 1024 * (pid + 1)], tmesh
    )
    feats = extract(staged)
    stream_sum = float(jax.jit(jnp.sum)(feats))

    # ---- sequence-parallel marker ingest: epoch windows straddling
    # the process boundary read their tail over DCN ------------------
    rng3 = np.random.RandomState(2)
    T = 4 * 2048  # 4 time shards x 2048; processes own 2 shards each
    raw_global = (rng3.randn(3, T) * 200).astype(np.int16)
    res = np.full(3, 0.1, np.float32)
    block = T // 4
    positions = [500, block - 30, 2 * block - 5, 3 * block + 40]
    markers = [
        Marker(f"Mk{i}", "Stimulus", f"S  {1 + i % 9}", p)
        for i, p in enumerate(positions)
    ]
    plan = sharded_ingest.plan_sharded_ingest(markers, 2, T, 4, block)
    ing_extract = sharded_ingest.make_sharded_ingest(tmesh)
    local_block = raw_global[:, 2 * block * pid : 2 * block * (pid + 1)]
    staged_i16 = sharded_ingest.stage_recording_local_int16(
        local_block, tmesh
    )
    ingest_feats = ing_extract(staged_i16, res, plan)
    # both processes hold the full synthetic recording, so each can
    # verify against the single-device block featurizer directly
    base = device_ingest.plan_ingest(markers, 2, T)
    ref = np.asarray(
        device_ingest.make_block_ingest_featurizer()(
            jnp.asarray(raw_global), jnp.asarray(res),
            jnp.asarray(base.positions), jnp.asarray(base.mask),
        )
    )[base.mask]
    ingest_dev = float(np.max(np.abs(ingest_feats - ref)))

    print(
        json.dumps(
            {
                "pid": pid,
                "procs": jax.process_count(),
                "devices": jax.device_count(),
                "mesh": dict(mesh.shape),
                "total": total,
                "wsum": float(jnp.sum(params["w"])),
                "grad": np.asarray(grad).tolist(),
                "loss": loss,
                "stream_sum": stream_sum,
                "stream_shape": list(feats.shape),
                "ingest_dev": ingest_dev,
                "ingest_rows": int(ingest_feats.shape[0]),
            }
        )
    )


if __name__ == "__main__":
    main()
