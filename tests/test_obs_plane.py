"""Fleet observability plane suite (ISSUE 19): metrics exposition
(obs/metrics_export.py), the gateway /metrics endpoint, trace
propagation plumbing, per-tenant SLOs, and the trace stitcher.

The pins:

- **exposition determinism** — render() over controlled inputs is
  byte-identical to a golden text (ordering, escaping, histogram
  series), and parse()/histogram_from_series() round-trip it exactly;
- **exact merge** — two replicas' histograms merged by integer
  addition equal the histogram of the union of observations, bit for
  bit, in either merge order;
- **SLO math** — availability/attainment/error-budget burn from the
  outcome counters plus the histogram, including the empty-service
  and burning-budget edges;
- **trace plumbing** — mint_trace_id honors a well-formed inbound id
  and re-mints hostile ones; a gateway journals the trace id through
  to the terminal record and echoes it on the response;
- **tenant eviction** — remove-side accounting: evict_tenant drops
  the reservoir, the histogram, and the per-tenant counters;
- **trace stitching** — plan_admin trace reassembles synthesized
  dead-holder + takeover segment files into one tree with the
  takeover boundary and the unfinished root visible.
"""

import json
import os
import sys
import urllib.request

import pytest

import _synthetic
from eeg_dataanalysispackage_tpu.gateway import GatewayServer
from eeg_dataanalysispackage_tpu.gateway.server import mint_trace_id
from eeg_dataanalysispackage_tpu.obs import metrics_export
from eeg_dataanalysispackage_tpu.scheduler.journal import PlanJournal

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def session(tmp_path):
    return _synthetic.write_session(str(tmp_path), n_markers=60)


def _q(info):
    return (
        f"info_file={info}&fe=dwt-8&train_clf=logreg"
        "&config_step_size=1.0&config_num_iterations=20"
        "&config_mini_batch_fraction=1.0"
    )


# -- LatencyHistogram ---------------------------------------------------


def test_histogram_buckets_and_quantiles():
    h = metrics_export.LatencyHistogram()
    for ms in (0.3, 0.5, 3.0, 40.0, 9000.0):
        h.observe(ms)
    assert h.count == 5
    # le-buckets: 0.5 lands IN the 0.5 bucket, 9000 in +Inf
    assert h.counts[0] == 2          # <= 0.5
    assert h.counts[-1] == 1         # +Inf
    assert h.quantile(50.0) == 5.0   # 3rd of 5 → the le=5ms bucket
    assert h.quantile(99.0) == metrics_export.BUCKET_BOUNDS_MS[-1]
    assert h.attainment(50.0) == pytest.approx(4 / 5)
    # sum is integer microseconds — exact accumulation
    assert h.sum_us == int(round((0.3 + 0.5 + 3.0 + 40.0 + 9000.0) * 1000))


def test_empty_histogram_edges():
    h = metrics_export.LatencyHistogram()
    assert h.quantile(99.0) is None
    assert h.attainment(50.0) == 1.0
    assert h.snapshot()["count"] == 0


def test_bounds_must_increase():
    with pytest.raises(ValueError):
        metrics_export.LatencyHistogram((1.0, 1.0, 2.0))


def test_two_replica_merge_is_exact():
    """The fleet aggregation contract: merging replica histograms is
    element-wise integer addition, so the merged histogram IS the
    histogram of the union of observations — same counts, same sum,
    any merge order."""
    obs_a = [0.2, 1.7, 30.0, 400.0]
    obs_b = [0.9, 2.5, 2.5, 8000.0, 12.0]
    a = metrics_export.LatencyHistogram()
    b = metrics_export.LatencyHistogram()
    union = metrics_export.LatencyHistogram()
    for ms in obs_a:
        a.observe(ms)
        union.observe(ms)
    for ms in obs_b:
        b.observe(ms)
        union.observe(ms)
    # merge through the snapshot round trip, exactly the path
    # fleet_top takes (scrape → snapshot → from_snapshot → merge)
    ab = metrics_export.merge_all(
        metrics_export.LatencyHistogram.from_snapshot(h.snapshot())
        for h in (a, b)
    )
    ba = metrics_export.merge_all(
        metrics_export.LatencyHistogram.from_snapshot(h.snapshot())
        for h in (b, a)
    )
    for merged in (ab, ba):
        assert merged.counts == union.counts
        assert merged.count == union.count
        assert merged.sum_us == union.sum_us
        assert merged.quantile(99.0) == union.quantile(99.0)


def test_merge_refuses_mismatched_bounds():
    a = metrics_export.LatencyHistogram()
    b = metrics_export.LatencyHistogram((1.0, 2.0))
    with pytest.raises(ValueError):
        a.merge(b)


# -- exposition text ----------------------------------------------------


def test_render_golden_text():
    """The golden pin: one controlled state renders to exactly this
    document — sorted series, deterministic floats, escaped labels.
    A renderer change that moves a byte shows up here first."""
    h = metrics_export.LatencyHistogram((1.0, 10.0))
    h.observe(0.5)
    h.observe(7.0)
    h.observe(99.0)
    text = metrics_export.render(
        counters={"scheduler.completed": 3, "serve.shed": 1},
        gauges={"gateway.queue_depth": 2},
        histograms=[
            ("serve_request_latency_ms", {}, h),
            ("serve_request_latency_ms", {"tenant": 'ten"a\n'}, h),
        ],
        info={"replica": "gw-a"},
    )
    assert text == (
        '# TYPE eeg_tpu_build_info gauge\n'
        'eeg_tpu_build_info{replica="gw-a"} 1\n'
        '# TYPE eeg_tpu_scheduler_completed_total counter\n'
        'eeg_tpu_scheduler_completed_total 3\n'
        '# TYPE eeg_tpu_serve_shed_total counter\n'
        'eeg_tpu_serve_shed_total 1\n'
        '# TYPE eeg_tpu_gateway_queue_depth gauge\n'
        'eeg_tpu_gateway_queue_depth 2\n'
        '# TYPE eeg_tpu_serve_request_latency_ms histogram\n'
        'eeg_tpu_serve_request_latency_ms_bucket{le="1"} 1\n'
        'eeg_tpu_serve_request_latency_ms_bucket{le="10"} 2\n'
        'eeg_tpu_serve_request_latency_ms_bucket{le="+Inf"} 3\n'
        'eeg_tpu_serve_request_latency_ms_sum 106.5\n'
        'eeg_tpu_serve_request_latency_ms_count 3\n'
        'eeg_tpu_serve_request_latency_ms_bucket'
        '{le="1",tenant="ten\\"a\\n"} 1\n'
        'eeg_tpu_serve_request_latency_ms_bucket'
        '{le="10",tenant="ten\\"a\\n"} 2\n'
        'eeg_tpu_serve_request_latency_ms_bucket'
        '{le="+Inf",tenant="ten\\"a\\n"} 3\n'
        'eeg_tpu_serve_request_latency_ms_sum{tenant="ten\\"a\\n"} 106.5\n'
        'eeg_tpu_serve_request_latency_ms_count{tenant="ten\\"a\\n"} 3\n'
    )


def test_render_is_deterministic_across_input_order():
    h = metrics_export.LatencyHistogram()
    h.observe(1.0)
    kw = dict(
        histograms=[("lat_ms", {}, h)], info={"replica": "r"},
    )
    a = metrics_export.render(
        counters={"b": 2, "a": 1}, gauges={"y": 0, "x": 9}, **kw
    )
    b = metrics_export.render(
        counters={"a": 1, "b": 2}, gauges={"x": 9, "y": 0}, **kw
    )
    assert a == b


def test_parse_histogram_round_trip():
    """Scrape-side exactness: parse() + histogram_from_series()
    rebuilds the rendered histogram bit for bit, and the tenant label
    selects the right series (match={'tenant': None} keeps only the
    unlabeled service-wide one)."""
    service = metrics_export.LatencyHistogram()
    tenant = metrics_export.LatencyHistogram()
    for ms in (0.4, 3.0, 77.0):
        service.observe(ms)
    tenant.observe(600.0)
    text = metrics_export.render(
        counters={"scheduler.completed": 41},
        histograms=[
            ("serve_request_latency_ms", {}, service),
            ("serve_request_latency_ms", {"tenant": "t0"}, tenant),
        ],
    )
    series = metrics_export.parse(text)
    assert series["eeg_tpu_scheduler_completed_total"] == [({}, 41.0)]
    got = metrics_export.histogram_from_series(
        series, "eeg_tpu_serve_request_latency_ms",
        match={"tenant": None},
    )
    assert got.counts == service.counts
    assert got.count == service.count
    assert got.sum_us == service.sum_us
    got_t = metrics_export.histogram_from_series(
        series, "eeg_tpu_serve_request_latency_ms",
        match={"tenant": "t0"},
    )
    assert got_t.counts == tenant.counts
    assert metrics_export.histogram_from_series(
        series, "eeg_tpu_nope"
    ) is None


# -- SLO math -----------------------------------------------------------


def test_slo_block_healthy_and_burning():
    h = metrics_export.LatencyHistogram()
    for _ in range(99):
        h.observe(5.0)
    h.observe(2000.0)
    ok = metrics_export.slo_block(
        h, {"completed": 100, "shed": 0, "failed": 0},
        objective_ms=50.0, availability_target=0.98,
    )
    assert ok["availability"] == 1.0
    assert ok["latency_attainment"] == pytest.approx(0.99)
    assert ok["ok"] is True
    # the same latencies against a 99.9% target: 1% bad burns 10x
    burn = metrics_export.slo_block(
        h, {"completed": 100, "shed": 0, "failed": 0},
        objective_ms=50.0, availability_target=0.999,
    )
    assert burn["error_budget_burn"] == pytest.approx(10.0)
    assert burn["ok"] is False
    # availability is the binding objective when sheds dominate
    shed = metrics_export.slo_block(
        metrics_export.LatencyHistogram(),
        {"completed": 50, "shed": 50, "failed": 0},
        objective_ms=50.0, availability_target=0.999,
    )
    assert shed["availability"] == pytest.approx(0.5)
    assert shed["ok"] is False


def test_slo_block_empty_service_is_healthy():
    block = metrics_export.slo_block(
        metrics_export.LatencyHistogram(), {},
        objective_ms=50.0, availability_target=0.999,
    )
    assert block["availability"] == 1.0
    assert block["latency_attainment"] == 1.0
    assert block["ok"] is True
    assert block["requests_observed"] == 0


# -- trace-id minting ---------------------------------------------------


def test_mint_trace_id_honors_wellformed_inbound():
    assert mint_trace_id("req-2026.08_07-a") == "req-2026.08_07-a"


@pytest.mark.parametrize("bad", [
    None, "", "has space", "semi;colon", "x" * 129, 'quo"te',
])
def test_mint_trace_id_remints_hostile_inbound(bad):
    minted = mint_trace_id(bad)
    assert minted != bad
    assert len(minted) == 32
    int(minted, 16)  # hex-shaped


def test_mint_trace_id_unique_per_mint():
    assert mint_trace_id(None) != mint_trace_id(None)


# -- the gateway surface ------------------------------------------------


def test_gateway_journals_and_echoes_trace_id(session, tmp_path):
    """The propagation root: an inbound X-Trace-Id rides the submit
    response, the journal's submit meta, AND the terminal record
    (re-journaled at completion — plan_admin trace resolves finished
    plans from exactly that field)."""
    journal_dir = str(tmp_path / "journal")
    with GatewayServer(journal_dir=journal_dir) as gw:
        req = urllib.request.Request(
            f"{gw.url}/plans", data=_q(session).encode(),
            method="POST", headers={"X-Trace-Id": "trace-pin-1"},
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            payload = json.loads(r.read())
        assert payload["trace_id"] == "trace-pin-1"
        plan_id = payload["plan_id"]
        gw.executor.handle(plan_id).result(120)
    entry = PlanJournal(journal_dir).entry(plan_id)
    assert entry["state"] == "completed"
    assert entry["meta"]["trace_id"] == "trace-pin-1"


def test_gateway_metrics_endpoint(session, tmp_path):
    """GET /metrics: Prometheus content type, the build-info series
    naming the replica, scheduler counters present after a completed
    plan. Structural, not golden — obs.metrics counters are process-
    global and accumulate across the suite."""
    with GatewayServer(journal_dir=str(tmp_path / "journal")) as gw:
        req = urllib.request.Request(
            f"{gw.url}/plans", data=_q(session).encode(), method="POST",
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            plan_id = json.loads(r.read())["plan_id"]
        gw.executor.handle(plan_id).result(120)
        with urllib.request.urlopen(
            f"{gw.url}/metrics", timeout=30
        ) as r:
            assert r.status == 200
            assert r.headers["Content-Type"] == \
                metrics_export.CONTENT_TYPE
            text = r.read().decode()
    series = metrics_export.parse(text)
    info = series["eeg_tpu_build_info"]
    assert info[0][0]["replica"] == gw.replica_id
    assert series["eeg_tpu_scheduler_completed_total"][0][1] >= 1
    assert "eeg_tpu_gateway_queue_depth" in series


def test_fleet_top_over_live_and_down_replicas(session, tmp_path,
                                               capsys):
    """tools/fleet_top.py against one live gateway plus one dead URL:
    the live row carries the scraped counters, the dead URL renders
    DOWN without failing the table, and --snapshot-style output stays
    strict-JSON-safe."""
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        import fleet_top
    finally:
        sys.path.pop(0)
    from eeg_dataanalysispackage_tpu.utils import strict_json

    with GatewayServer(journal_dir=str(tmp_path / "journal")) as gw:
        req = urllib.request.Request(
            f"{gw.url}/plans", data=_q(session).encode(), method="POST",
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            plan_id = json.loads(r.read())["plan_id"]
        gw.executor.handle(plan_id).result(120)
        snap = fleet_top.snapshot(
            [gw.url, "http://127.0.0.1:9"], timeout_s=5.0
        )
    up, down = snap["replicas"]
    assert up["replica"] == gw.replica_id
    assert up["plans_completed"] >= 1
    assert "error" in down
    assert snap["fleet"]["replicas_up"] == 1
    assert snap["fleet"]["replicas_total"] == 2
    strict_json.dumps(snap)  # JSON-safe end to end
    fleet_top.render(snap)
    out = capsys.readouterr().out
    assert gw.replica_id in out and "DOWN" in out
    assert "fleet: 1/2 up" in out


# -- tenant eviction ----------------------------------------------------


def test_evict_tenant_drops_all_accounting():
    """The remove_tenant leak fix: after eviction the reservoir, the
    histogram, and every ``tenant.<name>.*`` counter are gone, while
    other tenants' state is untouched."""
    from eeg_dataanalysispackage_tpu.serve import batcher as batcher_mod

    mb = batcher_mod.MicroBatcher(
        lambda windows, resolutions: (None, None),
        max_batch=4, queue_depth=8, tenant_aware=True,
    )
    for tenant in ("t0", "t1"):
        mb._count_tenant(tenant, "completed", 3)
        mb._tenant_latency(tenant, 0.004)
    assert set(mb.tenant_latency_snapshot()) == {"t0", "t1"}
    assert set(mb.tenant_histogram_snapshot()) == {"t0", "t1"}

    mb.evict_tenant("t0")
    counters, _ = mb.snapshot()
    assert not [k for k in counters if k.startswith("tenant.t0.")]
    assert counters["tenant.t1.completed"] == 3
    assert set(mb.tenant_latency_snapshot()) == {"t1"}
    assert set(mb.tenant_histogram_snapshot()) == {"t1"}
    # idempotent — a double remove must not raise
    mb.evict_tenant("t0")


# -- the trace stitcher -------------------------------------------------


def _segment_line(**kw):
    return json.dumps(kw, sort_keys=True)


def test_plan_admin_trace_stitches_takeover(tmp_path, capsys):
    """Synthesized two-segment trace: the dead holder's segment (a
    header whose root span never closed, plus one finished child) and
    the survivor's takeover segment. The stitcher must render ONE
    tree — both segments under one trace id — with the TAKEOVER
    boundary named and the dead root UNFINISHED."""
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        import plan_admin
    finally:
        sys.path.pop(0)
    journal_dir = str(tmp_path / "journal")
    journal = PlanJournal(journal_dir)
    journal.record_submitted(
        "p0001", "q", meta={"trace_id": "trace-x"}
    )
    trace_dir = tmp_path / "traces"
    trace_dir.mkdir()
    victim = [
        _segment_line(
            kind="segment", trace_id="trace-x", segment="gw-a",
            root_span_id="gw-a:1", wall_start=100.0,
            attrs={"plan_id": "p0001"},
        ),
        _segment_line(
            kind="span", trace_id="trace-x", segment="gw-a",
            span_id="gw-a:2", parent_id="gw-a:1", name="stage.ingest",
            wall_start=100.1, wall_end=100.4, thread="w0", attrs={},
        ),
        # the SIGKILL tore the final line mid-write — skipped, never
        # fatal
        '{"kind": "span", "trace_id": "trace-x", "seg',
    ]
    survivor = [
        _segment_line(
            kind="segment", trace_id="trace-x", segment="gw-b",
            root_span_id="gw-b:1", wall_start=103.0,
            attrs={"plan_id": "p0001", "takeover": True},
        ),
        _segment_line(
            kind="span", trace_id="trace-x", segment="gw-b",
            span_id="gw-b:2", parent_id="gw-b:1", name="stage.train",
            wall_start=103.1, wall_end=104.0, thread="w0", attrs={},
        ),
        _segment_line(
            kind="span", trace_id="trace-x", segment="gw-b",
            span_id="gw-b:1", parent_id=None, name="plan",
            wall_start=103.0, wall_end=104.2, thread="w0",
            attrs={"plan_id": "p0001", "takeover": True},
        ),
    ]
    (trace_dir / "trace-gw-a.jsonl").write_text(
        "\n".join(victim) + "\n"
    )
    (trace_dir / "trace-gw-b.jsonl").write_text(
        "\n".join(survivor) + "\n"
    )

    rc = plan_admin.main([
        "trace", "p0001", "--journal", journal_dir,
        "--trace-dir", str(trace_dir),
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "trace trace-x" in out and "2 segment(s)" in out
    # segment order is wall-start order: the victim first
    assert out.index("segment gw-a") < out.index("segment gw-b")
    assert "TAKEOVER boundary: continued after gw-a died" in out
    # the dead holder's root was synthesized from the header and
    # rendered unfinished, with its completed child nested under it
    assert "UNFINISHED (holder died mid-span)" in out
    assert "stage.ingest" in out and "stage.train" in out


def test_plan_admin_trace_without_trace_id(session, tmp_path, capsys):
    """A record journaled without a trace id (pre-observability
    submit) is reported, not crashed on."""
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        import plan_admin
    finally:
        sys.path.pop(0)
    journal_dir = str(tmp_path / "journal")
    PlanJournal(journal_dir).record_submitted("p0009", "q", meta={})
    rc = plan_admin.main([
        "trace", "p0009", "--journal", journal_dir,
        "--trace-dir", str(tmp_path / "traces"),
    ])
    out = capsys.readouterr().out
    assert rc == 1
    assert "no journaled trace id" in out
