"""Observability subsystem tests (net-new vs the reference, whose only
observability was log4j timestamps — SURVEY.md section 5)."""

import json
import logging
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from eeg_dataanalysispackage_tpu import obs


def test_stage_timer_accumulates():
    t = obs.StageTimer()
    with t.stage("a"):
        time.sleep(0.01)
    with t.stage("a"):
        pass
    with t.stage("b"):
        pass
    d = t.as_dict()
    assert d["a"]["count"] == 2 and d["b"]["count"] == 1
    assert t.total("a") >= 0.01
    report = t.report()
    assert "a" in report and "x2" in report


def test_metrics_counters_and_gauges():
    m = obs.Metrics()
    m.count("epochs", 5)
    m.count("epochs", 3)
    m.gauge("throughput", 123.4)
    snap = json.loads(m.to_json())
    assert snap["counters"]["epochs"] == 8
    assert snap["gauges"]["throughput"] == 123.4


def test_trace_produces_profile_artifacts(tmp_path):
    log_dir = str(tmp_path / "trace")
    with obs.trace(log_dir):
        with obs.annotate("square"):
            x = jnp.arange(128.0)
            jax.jit(lambda v: (v * v).sum())(x).block_until_ready()
    found = []
    for root, _, files in os.walk(log_dir):
        found.extend(files)
    assert any(f.endswith((".pb", ".json.gz", ".xplane.pb")) for f in found), found


def test_configure_logging_file_handler(tmp_path):
    logfile = str(tmp_path / "logs" / "run.log")
    obs.configure_logging(logfile=logfile)
    logging.getLogger("obs-test").info("hello obs")
    for h in logging.getLogger().handlers:
        h.flush()
    assert os.path.exists(logfile)
    assert "hello obs" in open(logfile).read()
    # reset to console-only so later tests don't write to tmp_path
    obs.configure_logging()


def test_pipeline_records_stage_timings(fixture_dir, tmp_path):
    from eeg_dataanalysispackage_tpu.pipeline import builder

    q = (
        f"info_file={fixture_dir}/infoTrain.txt&fe=dwt-8&train_clf=logreg"
        f"&config_num_iterations=5&config_step_size=1.0"
        f"&config_mini_batch_fraction=1.0"
    )
    pb = builder.PipelineBuilder(q)
    pb.execute()
    d = pb.timers.as_dict()
    assert {"ingest", "train", "test"} <= set(d)
    assert all(v["seconds"] > 0 for v in d.values())


def test_save_memory_profile(tmp_path):
    import jax.numpy as jnp

    from eeg_dataanalysispackage_tpu import obs

    _ = jnp.ones(128) + 1  # ensure a live allocation
    path = tmp_path / "mem.prof"
    ok = obs.save_memory_profile(str(path))
    if not ok:
        pytest.skip("backend lacks device memory profiling")
    assert path.exists() and path.stat().st_size > 0
