"""DT/RF and NN classifier tests."""

import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.models import nn, registry, trees


def make_data(n=300, d=10, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d)
    # axis-aligned rule so trees can nail it
    y = ((x[:, 0] > 0.2) & (x[:, 3] < 0.5)).astype(np.float64)
    return x, y


NN_BASE_CONFIG = {
    "config_seed": "7",
    "config_num_iterations": "300",
    "config_learning_rate": "0.1",
    "config_momentum": "0.9",
    "config_weight_init": "xavier",
    "config_updater": "nesterovs",
    "config_optimization_algo": "stochastic_gradient_descent",
    "config_pretrain": "false",
    "config_backprop": "true",
    "config_loss_function": "xent",
    "config_layer1_layer_type": "dense",
    "config_layer1_n_out": "16",
    "config_layer1_drop_out": "0.0",
    "config_layer1_activation_function": "relu",
    "config_layer2_layer_type": "output",
    "config_layer2_n_out": "2",
    "config_layer2_drop_out": "0.0",
    "config_layer2_activation_function": "softmax",
}


def test_decision_tree_learns_rule():
    x, y = make_data()
    clf = trees.DecisionTreeClassifier()
    clf.set_config(
        {
            "config_max_bins": "32",
            "config_impurity": "gini",
            "config_max_depth": "5",
            "config_min_instances_per_node": "1",
        }
    )
    clf.fit(x, y)
    assert (clf.predict(x) == y).mean() > 0.95


def test_decision_tree_default_config():
    x, y = make_data(seed=2)
    clf = trees.DecisionTreeClassifier()
    clf.set_config({})
    clf.fit(x, y)
    assert (clf.predict(x) == y).mean() > 0.9


def test_random_forest_learns_rule():
    x, y = make_data(seed=3)
    clf = trees.RandomForestClassifier()
    clf.set_config(
        {
            "config_max_bins": "32",
            "config_impurity": "entropy",
            "config_max_depth": "6",
            "config_min_instances_per_node": "1",
            "config_num_trees": "20",
            "config_feature_subset": "auto",
        }
    )
    clf.fit(x, y)
    assert (clf.predict(x) == y).mean() > 0.93


def test_rf_deterministic_seed():
    x, y = make_data(seed=4)

    def train():
        clf = trees.RandomForestClassifier()
        clf.set_config(
            {
                "config_max_bins": "16",
                "config_impurity": "gini",
                "config_max_depth": "4",
                "config_min_instances_per_node": "1",
                "config_num_trees": "5",
                "config_feature_subset": "sqrt",
            }
        )
        clf.fit(x, y)
        return clf.predict(x)

    np.testing.assert_array_equal(train(), train())


def test_tree_save_load_roundtrip(tmp_path):
    x, y = make_data(seed=5)
    clf = trees.RandomForestClassifier()
    clf.set_config(
        {
            "config_max_bins": "16",
            "config_impurity": "gini",
            "config_max_depth": "4",
            "config_min_instances_per_node": "1",
            "config_num_trees": "3",
            "config_feature_subset": "auto",
        }
    )
    clf.fit(x, y)
    # file:// prefix tolerated like the reference DT/RF save paths
    clf.save("file://" + str(tmp_path / "rf_model"))
    clf2 = trees.RandomForestClassifier()
    clf2.load("file://" + str(tmp_path / "rf_model"))
    np.testing.assert_array_equal(clf.predict(x), clf2.predict(x))


def test_nn_learns(capfd):
    x, y = make_data(n=200, d=8, seed=6)
    clf = nn.NeuralNetworkClassifier()
    clf.set_config(dict(NN_BASE_CONFIG))
    clf.fit(x, y)
    acc = ((clf.predict(x) > 0.5).astype(float) == y).mean()
    assert acc > 0.85


def test_nn_missing_config_raises():
    clf = nn.NeuralNetworkClassifier()
    clf.set_config({})
    with pytest.raises(ValueError, match="config_seed"):
        clf.fit(np.zeros((4, 8)), np.zeros(4))


def test_nn_save_load_roundtrip(tmp_path):
    x, y = make_data(n=100, d=6, seed=8)
    clf = nn.NeuralNetworkClassifier()
    clf.set_config(dict(NN_BASE_CONFIG, config_num_iterations="50"))
    clf.fit(x, y)
    path = str(tmp_path / "nn_model")
    clf.save(path)
    clf2 = nn.NeuralNetworkClassifier()
    clf2.load(path)
    np.testing.assert_allclose(clf.predict(x), clf2.predict(x), atol=1e-6)


def test_nn_dropout_path():
    x, y = make_data(n=100, d=6, seed=9)
    cfg = dict(NN_BASE_CONFIG, config_num_iterations="30")
    cfg["config_layer1_drop_out"] = "0.3"
    clf = nn.NeuralNetworkClassifier()
    clf.set_config(cfg)
    clf.fit(x, y)  # must not crash; dropout only active in training
    p1 = clf.predict(x)
    p2 = clf.predict(x)
    np.testing.assert_array_equal(p1, p2)  # deterministic at test time


def test_all_classifier_families_registered():
    # the reference's five (PipelineBuilder.java:156-169) plus the
    # restored gbt (ClassifierTest.java:213) and the device-forest
    # -tpu variants
    assert registry.names() == [
        "dt", "dt-tpu", "gbt", "gbt-tpu", "logreg", "nn", "rf",
        "rf-tpu", "svm",
    ]
