"""Multi-device scale-out (ISSUE 9): the population member axis and
the ingest batch sharded over a device mesh, end to end.

Tier-1 exercises the REAL multi-device code on every run via the
conftest-forced 8-device virtual CPU platform (the same
``--xla_force_host_platform_device_count`` mechanism the MULTICHIP
dryrun and the bench children use), plus one explicit subprocess pin
that sets the flag itself. Contracts:

- the sharded linear-population engine matches the vmapped
  single-device engine member for member (weights to float32
  roundoff; thresholded statistics byte-equal — the established
  vmap==looped margin-band contract, extended to the mesh);
- member padding is INERT: a member count that does not divide the
  mesh pads with zero-mask members whose updates never fire, and the
  padded rows never reach the caller;
- pipeline-level ``devices=N`` produces ClassificationStatistics
  byte-identical to the unmeshed run, with the mesh rung/shape/
  per-device member counts in ``run_report.json``;
- ``devices=1`` is the degenerate mesh — byte-identical to today's
  path;
- mesh-unavailable degrades to the single-device rung (the ladder's
  new top rung), recorded, never fatal;
- the mesh-sharded fused ingest produces the same targets and
  rung-tolerance-identical features as the unsharded rung.
"""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

import _synthetic
from eeg_dataanalysispackage_tpu.models import sgd
from eeg_dataanalysispackage_tpu.parallel import (
    mesh as pmesh,
    population as engines,
)
from eeg_dataanalysispackage_tpu.pipeline import builder


@pytest.fixture(scope="module")
def mesh8():
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    return pmesh.make_mesh(8)


def _session(directory, n_files=2, n_markers=60):
    lines = []
    for i in range(n_files):
        name = f"synth_{i:02d}"
        guessed = 2 + i
        _synthetic.write_recording(
            str(directory), name=name, n_markers=n_markers,
            guessed=guessed, seed=i,
        )
        lines.append(f"{name}.eeg {guessed}")
    info = os.path.join(str(directory), "info.txt")
    with open(info, "w") as f:
        f.write("\n".join(lines) + "\n")
    return info


@pytest.fixture(scope="module")
def info(tmp_path_factory):
    return _session(tmp_path_factory.mktemp("mesh_session"))


_POP_QUERY = (
    "train_clf=logreg&cv=2&sweep=lr:1.0,0.5&cache=false"
    "&config_num_iterations=12&config_step_size=1.0"
    "&config_mini_batch_fraction=1.0"
)


def _q(info, *parts):
    return "&".join([f"info_file={info}", "fe=dwt-8-fused", *parts])


def _toy(P, n=48, d=10, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d).astype(np.float32)
    y = (rng.rand(n) > 0.5).astype(np.float32)
    steps = [1.0 + 0.05 * i for i in range(P)]
    regs = [0.0, 0.01] * (P // 2) + [0.0] * (P % 2)
    seeds = list(range(P))
    return x, y, steps, regs, seeds


# ------------------------------------------------ engine parity


def test_sharded_engine_matches_vmapped_with_padding(mesh8):
    """P=11 members over 8 devices: 5 inert padded members, real
    members bit-for-bit the vmapped engine's trajectories (full-batch
    is deterministic, so the weights agree exactly here)."""
    x, y, steps, regs, seeds = _toy(11)
    cfg = sgd.SGDConfig(num_iterations=8)
    got = engines.train_linear_population_sharded(
        x, y, cfg, steps, regs, seeds, masks=None, mesh=mesh8
    )
    want = engines.train_linear_population(
        x, y, cfg, steps, regs, seeds, masks=None
    )
    assert got.shape == np.asarray(want).shape == (11, 10)
    np.testing.assert_allclose(got, np.asarray(want), rtol=0, atol=5e-6)


def test_sharded_engine_matches_vmapped_multi_fold_minibatch(mesh8):
    """Fold masks + Bernoulli minibatch sampling: the mask formulation
    (and therefore the per-member sample stream) matches the vmapped
    engine member for member."""
    x, y, steps, regs, seeds = _toy(6, n=40)
    masks = (np.random.RandomState(3).rand(6, 40) > 0.3).astype(
        np.float32
    )
    cfg = sgd.SGDConfig(num_iterations=6, mini_batch_fraction=0.7)
    got = engines.train_linear_population_sharded(
        x, y, cfg, steps, regs, seeds, masks=masks, mesh=mesh8
    )
    want = engines.train_linear_population(
        x, y, cfg, steps, regs, seeds, masks=masks
    )
    np.testing.assert_allclose(got, np.asarray(want), rtol=0, atol=5e-6)


def test_padded_member_masks_are_inert(mesh8):
    """The padding seam itself: an all-zero sample mask freezes a
    member at zero weights (``_run_sgd``'s empty-sample rule), which
    is exactly what the engine pads with — so padding can never leak
    signal, and the sliced result is unchanged by the pad width."""
    x, y, steps, regs, seeds = _toy(3)
    cfg = sgd.SGDConfig(num_iterations=5)
    # engine-level: P=3 on an 8-way mesh pads 5 inert members
    assert engines.pad_members(3, 8) == 8
    got = engines.train_linear_population_sharded(
        x, y, cfg, steps, regs, seeds, masks=None, mesh=mesh8
    )
    want = engines.train_linear_population(
        x, y, cfg, steps, regs, seeds, masks=None
    )
    np.testing.assert_allclose(got, np.asarray(want), rtol=0, atol=5e-6)
    # the mask semantics the pad relies on, pinned directly
    import jax.numpy as jnp

    w = sgd._run_sgd(
        jnp.asarray(x), jnp.asarray(y), 1.0, 1.0, 0.0, 1, 0.001,
        sample_mask=jnp.zeros_like(jnp.asarray(y)),
        num_iterations=5, loss="logistic", full_batch=True,
    )
    assert float(np.abs(np.asarray(w)).sum()) == 0.0


# ------------------------------------------------ pipeline-level


def test_pipeline_devices8_statistics_byte_identical(info, tmp_path):
    report_dir = tmp_path / "report"
    unmeshed = builder.PipelineBuilder(_q(info, _POP_QUERY)).execute()
    pb = builder.PipelineBuilder(
        _q(info, _POP_QUERY, "devices=8", f"report={report_dir}")
    )
    meshed = pb.execute()
    assert str(meshed) == str(unmeshed)
    resolved = pb.mesh_resolved
    assert resolved["rung"] == "mesh"
    assert resolved["shape"] == {"data": 8}
    pop_block = resolved["population"]
    assert pop_block["rung"] == "mesh"
    # cv=2 x 2 lr values = 4 members, padded to the 8-way mesh
    assert pop_block["members_per_device"] == 1
    assert pop_block["padded_members"] == 4
    with open(report_dir / "run_report.json") as f:
        report = json.load(f)
    assert report["mesh"]["rung"] == "mesh"
    assert report["mesh"]["shape"] == {"data": 8}
    assert (
        report["mesh"]["population"]["members_per_device"] == 1
    )
    assert report["population"]["mode"] == "sharded"


def test_pipeline_devices1_degenerate_byte_identical(info):
    unmeshed = builder.PipelineBuilder(_q(info, _POP_QUERY)).execute()
    pb = builder.PipelineBuilder(_q(info, _POP_QUERY, "devices=1"))
    meshed = pb.execute()
    assert str(meshed) == str(unmeshed)
    assert pb.mesh_resolved["rung"] == "mesh"
    assert pb.mesh_resolved["shape"] == {"data": 1}


def test_mesh_unavailable_degrades_to_single_device(info):
    from eeg_dataanalysispackage_tpu import obs

    unmeshed = builder.PipelineBuilder(_q(info, _POP_QUERY)).execute()
    before = obs.metrics.snapshot()["counters"].get(
        "pipeline.mesh_unavailable", 0.0
    )
    pb = builder.PipelineBuilder(_q(info, _POP_QUERY, "devices=64"))
    statistics = pb.execute()
    after = obs.metrics.snapshot()["counters"].get(
        "pipeline.mesh_unavailable", 0.0
    )
    assert str(statistics) == str(unmeshed)  # the run survived, same result
    assert pb.mesh_resolved["rung"] == "single_device"
    assert "only" in pb.mesh_resolved["error"]
    assert after == before + 1
    assert {"from": "mesh"}.items() <= pb.degradation_history[0].items() \
        or pb.degradation_history[0]["from"] == "mesh"


def test_mesh_grammar_errors(info):
    for bad in (
        "devices=0",
        "mesh_axes=data:x",
        "mesh_axes=data,data",
        "mesh_axes=data,time",  # multi-axis needs extents
        "mesh_axes=data:2,time:2&devices=8",  # extents disagree
        "devices=2&serve=true",
    ):
        with pytest.raises(ValueError):
            builder.PipelineBuilder(_q(info, _POP_QUERY, bad)).execute()


def test_mesh_axes_2d_layout(info):
    """A 2-D data x time mesh: population shards over data, ingest
    over time — statistics still byte-identical."""
    if jax.device_count() < 4:
        pytest.skip("needs 4 virtual devices")
    unmeshed = builder.PipelineBuilder(_q(info, _POP_QUERY)).execute()
    pb = builder.PipelineBuilder(
        _q(info, _POP_QUERY, "mesh_axes=data:2,time:2")
    )
    meshed = pb.execute()
    assert str(meshed) == str(unmeshed)
    assert pb.mesh_resolved["rung"] == "mesh"
    assert pb.mesh_resolved["shape"] == {"data": 2, "time": 2}
    assert pb.mesh_resolved["population"]["axis"] == "data"


# ------------------------------------------------ sharded ingest


def test_fused_ingest_mesh_sharded_matches_unsharded(info, mesh8):
    from eeg_dataanalysispackage_tpu import obs
    from eeg_dataanalysispackage_tpu.io import provider

    f0, t0 = provider.OfflineDataProvider([info]).load_features_device(
        backend="decode"
    )
    before = obs.metrics.snapshot()["counters"]
    f1, t1 = provider.OfflineDataProvider([info]).load_features_device(
        backend="decode", mesh=mesh8
    )
    after = obs.metrics.snapshot()["counters"]
    assert np.array_equal(t0, t1)
    assert f1.shape == f0.shape
    # rung-tolerance-identical features (the ladder's f32 contract)
    assert float(np.max(np.abs(f0 - f1))) <= 1e-5
    assert (
        after.get("ingest.sharded_recordings", 0)
        - before.get("ingest.sharded_recordings", 0)
    ) == 2
    assert (
        after.get("ingest.sharded_fallback", 0)
        - before.get("ingest.sharded_fallback", 0)
    ) == 0


# ------------------------------------------------ subprocess pin


def test_forced_host_device_subprocess_parity(tmp_path):
    """The forced-host-device harness itself: a fresh interpreter with
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` set by THIS
    test (not conftest) pins sharded-vs-single-device statistics byte
    equality end to end, plus the padded-member mask semantics."""
    script = tmp_path / "worker.py"
    script.write_text(
        """
import json, os, sys
sys.path.insert(0, {repo!r})
sys.path.insert(0, os.path.join({repo!r}, "tests"))
import numpy as np
import _synthetic
from eeg_dataanalysispackage_tpu.pipeline import builder

tmp = {tmp!r}
_synthetic.write_recording(tmp, name="w0", n_markers=60, guessed=3,
                           seed=0)
with open(os.path.join(tmp, "info.txt"), "w") as f:
    f.write("w0.eeg 3\\n")
q = ("info_file=" + os.path.join(tmp, "info.txt")
     + "&fe=dwt-8-fused&train_clf=logreg&cv=2&sweep=lr:1.0,0.5"
     + "&cache=false&config_num_iterations=10&config_step_size=1.0"
     + "&config_mini_batch_fraction=1.0")
unmeshed = builder.PipelineBuilder(q).execute()
pb = builder.PipelineBuilder(q + "&devices=8")
meshed = pb.execute()

from eeg_dataanalysispackage_tpu.models import sgd
from eeg_dataanalysispackage_tpu.parallel import population as engines
import jax.numpy as jnp
import jax
x = np.random.RandomState(0).randn(32, 6).astype(np.float32)
y = (np.random.RandomState(1).rand(32) > 0.5).astype(np.float32)
w = sgd._run_sgd(jnp.asarray(x), jnp.asarray(y), 1.0, 1.0, 0.0, 1,
                 0.001, sample_mask=jnp.zeros(32, jnp.float32),
                 num_iterations=4, loss="logistic", full_batch=True)
print(json.dumps({{
    "device_count": jax.device_count(),
    "identical": str(meshed) == str(unmeshed),
    "rung": pb.mesh_resolved["rung"],
    "shape": pb.mesh_resolved["shape"],
    "zero_mask_weights_sum": float(np.abs(np.asarray(w)).sum()),
}}))
""".format(repo=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), tmp=str(tmp_path))
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["EEG_TPU_NO_FEATURE_CACHE"] = "1"
    proc = subprocess.run(
        [sys.executable, str(script)],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["device_count"] == 8
    assert out["identical"] is True
    assert out["rung"] == "mesh"
    assert out["shape"] == {"data": 8}
    assert out["zero_mask_weights_sum"] == 0.0
