"""Chaos suite: deterministic fault injection (obs/chaos.py), the
fused-backend degradation ladder, elastic pipeline training, and the
end-to-end chaos-parity contract.

The acceptance bar (ISSUE 2): a full pipeline query run under injected
faults — remote request drops, a forced fused-backend failure, a
mid-train step error — completes via retry/degradation/elastic restart
and produces ClassificationStatistics identical to the fault-free
run, with every event visible in obs.metrics; with faults unset the
injection points are no-ops.
"""

import functools
import os
import threading
from http.server import SimpleHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

import _synthetic
from eeg_dataanalysispackage_tpu import obs
from eeg_dataanalysispackage_tpu.io import provider, remote, staging
from eeg_dataanalysispackage_tpu.obs import chaos
from eeg_dataanalysispackage_tpu.pipeline import builder


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    assert chaos.active_plan() is None
    yield
    chaos.uninstall()


def _counter_delta(before, name):
    after = obs.metrics.snapshot()["counters"]
    return after.get(name, 0.0) - before.get(name, 0.0)


# -- spec parsing ------------------------------------------------------


def test_parse_spec_full_grammar():
    plan = chaos.parse_fault_spec(
        "seed=7;remote.request:p=0.2;ingest.fused:once@1;"
        "device.step:err@7;staging.producer:every@3"
    )
    assert plan.seed == 7
    assert plan.rules["remote.request"].mode == "p"
    assert plan.rules["remote.request"].value == 0.2
    assert plan.rules["ingest.fused"].mode == "once"
    # err@N is an alias of once@N
    assert plan.rules["device.step"].mode == "once"
    assert plan.rules["device.step"].value == 7
    assert plan.rules["staging.producer"].mode == "every"


@pytest.mark.parametrize(
    "bad",
    [
        "remote.request",  # no directive
        "remote.request:p=1.5",  # probability out of range
        "remote.request:sometimes",  # unknown directive
        "remote.request:once@0",  # 1-based call index
        "seed=abc",  # unparseable seed
    ],
)
def test_parse_spec_rejects(bad):
    with pytest.raises(chaos.FaultSpecError):
        chaos.parse_fault_spec(bad)


def test_probabilistic_rule_is_deterministic_per_seed():
    fires = []
    for _ in range(2):
        plan = chaos.parse_fault_spec("x:p=0.3", seed=11)
        fires.append(
            [plan.should_fire("x") for _ in range(50)]
        )
    assert fires[0] == fires[1]
    assert 0 < sum(fires[0]) < 50  # actually probabilistic
    other = chaos.parse_fault_spec("x:p=0.3", seed=12)
    assert [other.should_fire("x") for _ in range(50)] != fires[0]


def test_once_and_every_rules():
    plan = chaos.parse_fault_spec("a:once@3;b:every@2")
    assert [plan.should_fire("a") for _ in range(6)] == [
        False, False, True, False, False, False
    ]
    assert [plan.should_fire("b") for _ in range(6)] == [
        False, True, False, True, False, True
    ]


# -- injection-point mechanics -----------------------------------------


def test_maybe_fire_is_noop_without_plan():
    before = obs.metrics.snapshot()["counters"]
    for _ in range(100):
        chaos.maybe_fire("remote.request")
    assert _counter_delta(before, "chaos.fired.remote.request") == 0


def test_maybe_fire_raises_requested_type_and_counts():
    before = obs.metrics.snapshot()["counters"]
    with chaos.faults("pt:once@1"):
        with pytest.raises(remote.RemoteIOError, match="injected fault"):
            chaos.maybe_fire("pt", remote.RemoteIOError)
        chaos.maybe_fire("pt")  # call 2: no further firing
    assert _counter_delta(before, "chaos.fired.pt") == 1


def test_faults_context_restores_previous_plan():
    outer = chaos.install("a:once@1")
    try:
        with chaos.faults("b:once@1") as inner:
            assert chaos.active_plan() is inner
        assert chaos.active_plan() is outer
    finally:
        chaos.uninstall()


# -- staging producer faults -------------------------------------------


@pytest.mark.chaos
def test_staging_producer_fault_surfaces_at_consumer():
    with chaos.faults("staging.producer:once@2"):
        it = staging.prefetch(
            staging.minibatches(np.ones((8, 2), np.float32), batch_size=2)
        )
        next(it)  # batch 1 stages fine
        with pytest.raises(chaos.ChaosInjectedError, match="staging.producer"):
            for _ in it:
                pass


# -- remote retry absorbs request-level faults -------------------------


@pytest.fixture()
def http_dir(tmp_path):
    handler = functools.partial(
        SimpleHTTPRequestHandler, directory=str(tmp_path)
    )
    handler.log_message = lambda *a, **k: None
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        yield f"http://127.0.0.1:{httpd.server_address[1]}", tmp_path
    finally:
        httpd.shutdown()
        httpd.server_close()


def _fast_fs():
    return remote.HttpFileSystem(
        retry=remote.RetryPolicy(max_attempts=4, timeout_s=5.0, backoff_s=0.01)
    )


@pytest.mark.chaos
def test_remote_request_drops_are_retried(http_dir):
    base, tmp = http_dir
    (tmp / "obj.bin").write_bytes(b"payload" * 100)
    before = obs.metrics.snapshot()["counters"]
    with chaos.faults("remote.request:p=0.3", seed=5):
        got = _fast_fs().read_bytes(f"{base}/obj.bin")
    assert got == b"payload" * 100
    assert _counter_delta(before, "chaos.fired.remote.request") >= 1


# -- degradation ladder ------------------------------------------------


def test_degradation_ladder_shape():
    assert provider.degradation_ladder("pallas") == [
        "pallas", "block", "xla", "host"
    ]
    assert provider.degradation_ladder("xla") == ["xla", "host"]
    with pytest.raises(ValueError, match="unknown device-ingest backend"):
        provider.degradation_ladder("host")


@pytest.fixture()
def session(tmp_path):
    return _synthetic.write_session(str(tmp_path), n_markers=90)


def _logreg_query(info, extra=""):
    return (
        f"info_file={info}&train_clf=logreg&config_step_size=1.0"
        "&config_num_iterations=40&config_mini_batch_fraction=1.0" + extra
    )


@pytest.mark.chaos
def test_fused_backend_failure_degrades_one_rung(session):
    baseline = builder.PipelineBuilder(
        _logreg_query(session, "&fe=dwt-8-fused-block")
    ).execute()
    before = obs.metrics.snapshot()["counters"]
    stats = builder.PipelineBuilder(
        _logreg_query(
            session, "&fe=dwt-8-fused-block&faults=ingest.fused:once@1"
        )
    ).execute()
    assert str(stats) == str(baseline)
    assert _counter_delta(before, "pipeline.degraded") == 1
    assert _counter_delta(before, "pipeline.degraded.from.block") == 1
    assert _counter_delta(before, "chaos.fired.ingest.fused") == 1


@pytest.mark.chaos
def test_all_device_backends_failing_degrades_to_host(session):
    host_stats = builder.PipelineBuilder(
        _logreg_query(session, "&fe=dwt-8")
    ).execute()
    before = obs.metrics.snapshot()["counters"]
    # every@1 fires on every load_features_device attempt: pallas,
    # block, and xla all die -> the ladder lands on the host floor
    stats = builder.PipelineBuilder(
        _logreg_query(
            session, "&fe=dwt-8-fused-pallas&faults=ingest.fused:every@1"
        )
    ).execute()
    assert str(stats) == str(host_stats)
    assert _counter_delta(before, "pipeline.degraded") == 3
    assert _counter_delta(before, "pipeline.degraded.to_host") == 1


@pytest.mark.chaos
def test_degrade_false_fails_fast(session):
    with pytest.raises(chaos.ChaosInjectedError):
        builder.PipelineBuilder(
            _logreg_query(
                session,
                "&fe=dwt-8-fused-xla&degrade=false"
                "&faults=ingest.fused:once@1",
            )
        ).execute()


@pytest.mark.chaos
def test_input_errors_do_not_degrade(tmp_path):
    """A missing input fails every rung identically: the root cause
    surfaces at once instead of being masked by backend retries."""
    before = obs.metrics.snapshot()["counters"]
    with pytest.raises(FileNotFoundError):
        builder.PipelineBuilder(
            _logreg_query(
                f"{tmp_path}/does_not_exist.txt", "&fe=dwt-8-fused-pallas"
            )
        ).execute()
    assert _counter_delta(before, "pipeline.degraded") == 0


# -- elastic pipeline training -----------------------------------------


@pytest.mark.chaos
def test_elastic_requires_checkpoint_path(session):
    with pytest.raises(ValueError, match="checkpoint_path"):
        builder.PipelineBuilder(
            _logreg_query(session, "&fe=dwt-8&elastic=true")
        ).execute()


@pytest.mark.chaos
def test_midtrain_fault_recovers_via_elastic_restart(session, tmp_path):
    q = _logreg_query(session, "&fe=dwt-8&elastic=true&save_every=1")
    baseline = builder.PipelineBuilder(
        q + f"&checkpoint_path={tmp_path}/ck_base"
    ).execute()
    before = obs.metrics.snapshot()["counters"]
    stats = builder.PipelineBuilder(
        q
        + f"&checkpoint_path={tmp_path}/ck_chaos"
        + "&faults=device.step:err@2"
    ).execute()
    assert str(stats) == str(baseline)
    assert _counter_delta(before, "chaos.fired.device.step") == 1
    assert _counter_delta(before, "elastic.restarts") == 1


@pytest.mark.chaos
def test_elastic_matches_monolithic_training(session, tmp_path):
    mono = builder.PipelineBuilder(
        _logreg_query(session, "&fe=dwt-8")
    ).execute()
    elastic = builder.PipelineBuilder(
        _logreg_query(
            session,
            f"&fe=dwt-8&elastic=true&checkpoint_path={tmp_path}/ck",
        )
    ).execute()
    assert str(elastic) == str(mono)


@pytest.mark.chaos
def test_elastic_completed_run_clears_checkpoints(session, tmp_path):
    """A completed elastic run clears its checkpoints — a re-run under
    the same checkpoint_path must train fresh, not silently restore
    the finished trajectory."""
    ck = tmp_path / "ck"
    q = _logreg_query(
        session, f"&fe=dwt-8&elastic=true&checkpoint_path={ck}"
    )
    first = builder.PipelineBuilder(q).execute()
    assert not [p for p in os.listdir(ck) if p.startswith("step_")]
    second = builder.PipelineBuilder(q).execute()
    assert str(second) == str(first)


@pytest.mark.chaos
def test_elastic_nn_midtrain_fault_parity(session, tmp_path):
    nn_cfg = (
        "&train_clf=nn&config_seed=5&config_num_iterations=30"
        "&config_learning_rate=0.05&config_momentum=0.9"
        "&config_weight_init=xavier&config_updater=nesterovs"
        "&config_optimization_algo=stochastic_gradient_descent"
        "&config_pretrain=false&config_backprop=true"
        "&config_layer1_layer_type=dense&config_layer1_n_out=8"
        "&config_layer1_drop_out=0&config_layer1_activation_function=relu"
        "&config_layer2_layer_type=output&config_layer2_n_out=2"
        "&config_layer2_drop_out=0"
        "&config_layer2_activation_function=softmax"
        "&config_loss_function=negativeloglikelihood"
    )
    q = (
        f"info_file={session}&fe=dwt-8{nn_cfg}"
        "&elastic=true&save_every=1"
    )
    baseline = builder.PipelineBuilder(
        q + f"&checkpoint_path={tmp_path}/nn_base"
    ).execute()
    stats = builder.PipelineBuilder(
        q
        + f"&checkpoint_path={tmp_path}/nn_chaos"
        + "&faults=device.step:err@2"
    ).execute()
    assert str(stats) == str(baseline)


# -- the acceptance criterion: full chaos parity -----------------------


@pytest.mark.chaos
def test_chaos_parity_end_to_end(http_dir, tmp_path):
    """Remote drops (p=0.2) + one fused-backend failure + one
    mid-train step error: the run completes via retry + degradation +
    elastic restart, statistics identical to the fault-free run,
    every event visible in obs.metrics."""
    base, serve_dir = http_dir
    _synthetic.write_session(str(serve_dir), n_markers=90)
    q = (
        f"info_file={base}/info.txt&fe=dwt-8-fused-pallas"
        "&train_clf=logreg&config_step_size=1.0"
        "&config_num_iterations=40&config_mini_batch_fraction=1.0"
        "&elastic=true&save_every=1"
    )
    result = tmp_path / "report.txt"
    baseline = builder.PipelineBuilder(
        q + f"&checkpoint_path={tmp_path}/ck_base", filesystem=_fast_fs()
    ).execute()

    before = obs.metrics.snapshot()["counters"]
    stats = builder.PipelineBuilder(
        q
        + f"&checkpoint_path={tmp_path}/ck_chaos&result_path={result}"
        + "&faults=remote.request:p=0.2;ingest.fused:once@1;"
        + "device.step:err@2&faults_seed=3",
        filesystem=_fast_fs(),
    ).execute()

    assert str(stats) == str(baseline)
    # the atomic report write landed, whole
    assert result.read_text() == str(stats) + "\n"
    for counter in (
        "chaos.fired.remote.request",
        "chaos.fired.ingest.fused",
        "chaos.fired.device.step",
        "pipeline.degraded.from.pallas",
        "elastic.restarts",
    ):
        assert _counter_delta(before, counter) >= 1, counter
    # the faults= scope ended with the run: later work is unaffected
    assert chaos.active_plan() is None


def test_get_raw_param_keeps_equals_signs():
    q = "a=1&faults=remote.request:p=0.2;x:once@1&b=2"
    # the parser no longer truncates at the second '=': the map and
    # the raw extraction agree on the full chaos spec
    assert (
        builder.get_query_map(q)["faults"]
        == "remote.request:p=0.2;x:once@1"
    )
    assert (
        builder.get_raw_param(q, "faults")
        == "remote.request:p=0.2;x:once@1"
    )
    assert builder.get_raw_param(q, "missing") is None
