"""Test configuration.

Tests run hermetically on CPU with a virtual 8-device mesh so
multi-chip sharding is exercised without TPU hardware (the reference
never tested multi-node at all — SURVEY.md section 4). Must run before
jax initializes its backends, hence the env mutation at import time.
"""

import os
import sys

# The axon site hook (sitecustomize) pre-imports jax before this file
# runs, so env vars alone are too late; jax.config.update before the
# first backend touch still works. XLA_FLAGS is read at CPU client
# creation, so setting it here (pre-backend) is effective.
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Hermeticity: the content-addressed feature cache (io/feature_cache)
# defaults to a per-user scratch directory, which would couple test
# runs to each other (a warm entry from a previous session would skip
# the ingest/degradation paths chaos and ladder tests pin). Tests that
# exercise the cache opt back in with monkeypatch (delenv + a tmp dir).
os.environ.setdefault("EEG_TPU_NO_FEATURE_CACHE", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402

REFERENCE_TEST_DATA = "/root/reference/test-data"


@pytest.fixture(scope="session")
def fixture_dir():
    if not os.path.isdir(REFERENCE_TEST_DATA):
        pytest.skip("reference fixture data not available")
    return REFERENCE_TEST_DATA
