"""The decode rung (ops/decode_ingest.py): the gather-free
decode+window kernel, its ladder position, its plan-cache reuse, and
the accuracy-gated bf16 feature path.

Parity contract: the slice formulation is subtract-first like the XLA
element gather, so the two rungs agree to the f32 ladder tolerance;
the bf16 twin carries its own documented gate (BF16_GATE_TOL) and is
never silently on — the pipeline records every decision.
"""

import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.io import provider
from eeg_dataanalysispackage_tpu.ops import decode_ingest, device_ingest
from eeg_dataanalysispackage_tpu.ops import plan_cache

import _synthetic  # noqa: E402  (tests/ is on sys.path via conftest)
from eeg_dataanalysispackage_tpu.pipeline import builder


def _irregular_case(n=300, stride=750, seed=0, dc=0):
    rng = np.random.RandomState(seed)
    S = 200 + n * stride + 1000
    raw = (
        rng.randint(-3000, 3000, size=(3, S)) + dc
    ).astype(np.int16)
    positions = np.clip(
        np.arange(n, dtype=np.int64) * stride + 200
        + rng.randint(-200, 200, size=n),
        100, S - 800,
    )
    cap = ((n + 63) // 64) * 64
    pos = np.zeros(cap, np.int32)
    pos[:n] = positions
    mask = np.zeros(cap, bool)
    mask[:n] = True
    res = np.array([0.1, 0.1, 0.2], np.float32)
    return raw, res, pos, mask, n


def test_slice_parity_with_gather_rung():
    raw, res, pos, mask, n = _irregular_case()
    got = np.asarray(
        decode_ingest.make_decode_ingest_featurizer(
            formulation="slice"
        )(raw, res, pos, mask)
    )
    want = np.asarray(
        device_ingest.make_device_ingest_featurizer()(
            raw, res, pos, mask
        )
    )
    # subtract-first on both sides: f32-tolerance-class agreement
    assert np.max(np.abs(got[:n] - want[:n])) < 5e-6
    # padded rows zeroed (the mask contract every rung shares)
    assert np.all(got[n:] == 0.0)


def test_splits_do_not_change_output():
    """The split-scans CPU parallelization is scheduling only: any
    split count produces bitwise-identical features."""
    raw, res, pos, mask, n = _irregular_case(n=128)
    pre, win = 100, 787
    tiles = decode_ingest.plan_decode_windows(
        pos, mask, raw.shape[1], pre=pre, window=win,
        tile=decode_ingest.DEFAULT_TILE,
    )
    outs = []
    for splits in (1, 2, 4):
        run = decode_ingest._slice_program(
            8, 512, 175, 16, pre, decode_ingest.DEFAULT_TILE,
            False, False, splits=splits,
        )
        outs.append(np.asarray(run(raw, res, tiles, mask)))
    assert np.array_equal(outs[0], outs[1])
    assert np.array_equal(outs[0], outs[2])


def test_window_overhang_reads_zeros_not_shifted():
    """A marker whose window runs past the end of the recording must
    read zeros (Java copyOfRange), never be silently SHIFTED by
    dynamic_slice's clamp — the host wrapper pads the staged tail
    when the bucket slack is thinner than a window."""
    # recording sized so the last marker's window overhangs: S chosen
    # with < 787 samples of slack past the final position
    S = 2048
    rng = np.random.RandomState(1)
    raw = rng.randint(-3000, 3000, size=(3, S)).astype(np.int16)
    pos = np.zeros(64, np.int32)
    pos[:2] = [500, S - 50]  # second window overhangs by 637 samples
    mask = np.zeros(64, bool)
    mask[:2] = True
    res = np.array([0.1, 0.1, 0.2], np.float32)
    got = np.asarray(
        decode_ingest.make_decode_ingest_featurizer(
            formulation="slice"
        )(raw, res, pos, mask)
    )
    want = np.asarray(
        device_ingest.make_device_ingest_featurizer()(
            np.pad(raw, ((0, 0), (0, 1000))), res, pos, mask
        )
    )
    assert np.max(np.abs(got[:2] - want[:2])) < 5e-6


def test_plan_cache_reuse():
    """Re-planning an unchanged layout is a cache hit (the
    zero-re-planning contract the block/Pallas planners carry)."""
    raw, res, pos, mask, _ = _irregular_case(n=64, seed=3)
    before = plan_cache.stats()
    t1 = decode_ingest.plan_decode_windows(pos, mask, raw.shape[1])
    mid = plan_cache.stats()
    t2 = decode_ingest.plan_decode_windows(pos, mask, raw.shape[1])
    after = plan_cache.stats()
    assert mid["misses"] >= before["misses"]  # first call may miss
    assert after["hits"] == mid["hits"] + 1
    assert np.array_equal(t1, t2)


def test_degradation_ladder_starts_at_decode():
    assert provider.degradation_ladder("decode") == [
        "decode", "pallas", "block", "xla", "host"
    ]
    # existing entry points unchanged
    assert provider.degradation_ladder("pallas") == [
        "pallas", "block", "xla", "host"
    ]


def test_fused_extractor_id_precision_class():
    """The f32 key tuple is byte-unchanged from PR 3 (warm caches
    survive); every non-f32 rung keys its own entries — the
    precision-class rule, now a 4-way ladder."""
    f32 = provider.fused_extractor_id(8)
    assert f32 == ("dwt-fused", 8, 512, 175, 16)
    assert provider.fused_extractor_id(8, "f32") == f32
    bf16 = provider.fused_extractor_id(8, "bf16")
    assert bf16 == f32 + ("bf16",)
    ids = {
        p: provider.fused_extractor_id(8, p)
        for p in ("f32", "bf16", "int8", "int4")
    }
    assert ids["int4"] == f32 + ("int4",)
    # 4 distinct classes: no rung's entry can ever serve another's
    assert len(set(ids.values())) == 4


def test_precisions_ladder_registry():
    """The grammar is the registry: decode_ingest.PRECISIONS is what
    plan validation, the builder, and the serve engine all accept."""
    assert decode_ingest.PRECISIONS == ("f32", "bf16", "int8", "int4")


def test_bf16_within_gate_on_dc_offset_signal():
    """The bf16 twin on the cancellation-stressing shape (full-range
    DC offsets): deviations stay inside the documented gate because
    mean-centering happens in f32 before the cast."""
    raw, res, pos, mask, n = _irregular_case(n=128, dc=15000)
    f32 = np.asarray(
        decode_ingest.make_decode_ingest_featurizer(
            formulation="slice", precision="f32"
        )(raw, res, pos, mask)
    )
    bf16 = np.asarray(
        decode_ingest.make_decode_ingest_featurizer(
            formulation="slice", precision="bf16"
        )(raw, res, pos, mask)
    )
    gate = decode_ingest.bf16_feature_gate(bf16[:n], f32[:n])
    assert gate["ok"], gate
    assert gate["max_abs_dev"] <= decode_ingest.BF16_GATE_TOL
    # and it genuinely differs from f32 (the path actually ran bf16)
    assert gate["max_abs_dev"] > 1e-6


def test_bf16_gate_judges_against_tolerance():
    rows = np.ones((4, 48), np.float32)
    drifted = rows + 1e-2
    bad = decode_ingest.bf16_feature_gate(drifted, rows)
    assert not bad["ok"] and bad["rows_checked"] == 4
    good = decode_ingest.bf16_feature_gate(rows, rows)
    assert good["ok"] and good["max_abs_dev"] == 0.0
    with pytest.raises(ValueError, match="misaligned"):
        decode_ingest.bf16_feature_gate(rows[:2], rows)


def test_precision_validation():
    with pytest.raises(ValueError, match="precision"):
        decode_ingest.make_decode_ingest_featurizer(precision="f16")
    with pytest.raises(ValueError, match="decode-rung"):
        odp = provider.OfflineDataProvider(["x.txt"])
        odp.load_features_device(backend="block", precision="bf16")


# -- pipeline integration ----------------------------------------------


@pytest.fixture()
def session(tmp_path):
    return _synthetic.write_session(str(tmp_path), n_markers=90)


def _query(info, extra=""):
    return (
        f"info_file={info}&train_clf=logreg&cache=false"
        "&config_step_size=1.0&config_num_iterations=40"
        "&config_mini_batch_fraction=1.0" + extra
    )


def test_decode_backend_statistics_match_other_rungs(session):
    s_decode = builder.PipelineBuilder(
        _query(session, "&fe=dwt-8-fused-decode")
    ).execute()
    s_xla = builder.PipelineBuilder(
        _query(session, "&fe=dwt-8-fused-xla")
    ).execute()
    assert str(s_decode) == str(s_xla)


def test_bf16_with_explicit_other_backend_is_an_error(session):
    with pytest.raises(ValueError, match="decode rung"):
        builder.PipelineBuilder(
            _query(session, "&fe=dwt-8-fused-block&precision=bf16")
        ).execute()
    with pytest.raises(ValueError, match="f32, bf16, int8, or int4"):
        builder.PipelineBuilder(
            _query(session, "&fe=dwt-8-fused&precision=f16")
        ).execute()
    with pytest.raises(ValueError, match="fused"):
        builder.PipelineBuilder(
            _query(session, "&fe=dwt-8&precision=bf16")
        ).execute()


def test_bf16_gate_auto_disable_pins_f32_statistics(
    session, monkeypatch
):
    """The gated-off path IS the f32 path: with an impossible
    tolerance the run auto-disables and produces byte-identical
    statistics — and records the decision on the builder."""
    pb_f32 = builder.PipelineBuilder(
        _query(session, "&fe=dwt-8-fused-decode")
    )
    s_f32 = pb_f32.execute()
    assert pb_f32.precision_resolved is None

    monkeypatch.setenv("EEG_TPU_BF16_GATE_TOL", "0")
    pb_off = builder.PipelineBuilder(
        _query(session, "&fe=dwt-8-fused&precision=bf16")
    )
    s_off = pb_off.execute()
    assert str(s_off) == str(s_f32)
    rec = pb_off.precision_resolved
    assert rec["requested"] == "bf16" and rec["used"] == "f32"
    assert rec["gate"]["ok"] is False

    monkeypatch.delenv("EEG_TPU_BF16_GATE_TOL")
    pb_on = builder.PipelineBuilder(
        _query(session, "&fe=dwt-8-fused&precision=bf16")
    )
    pb_on.execute()
    rec = pb_on.precision_resolved
    assert rec["used"] == "bf16" and rec["gate"]["ok"] is True
    assert rec["gate"]["max_abs_dev"] <= rec["gate"]["tolerance"]


def test_bf16_cache_entries_key_separately(session, tmp_path,
                                           monkeypatch):
    """A bf16 run's cached features can never serve an f32 request:
    the extractor id carries the precision class, so the second run
    below must MISS (and vice versa would too)."""
    from eeg_dataanalysispackage_tpu.io import feature_cache

    monkeypatch.setenv(
        "EEG_TPU_FEATURE_CACHE_DIR", str(tmp_path / "fc")
    )
    monkeypatch.delenv("EEG_TPU_NO_FEATURE_CACHE", raising=False)
    q_bf16 = _query(session, "&fe=dwt-8-fused&precision=bf16").replace(
        "&cache=false", ""
    )
    q_f32 = _query(session, "&fe=dwt-8-fused-decode").replace(
        "&cache=false", ""
    )
    builder.PipelineBuilder(q_bf16).execute()
    before = feature_cache.stats()
    builder.PipelineBuilder(q_f32).execute()
    after = feature_cache.stats()
    assert after["misses"] == before["misses"] + 1
    assert after["hits"] == before["hits"]
    # and each precision class hits its OWN entry on a re-run
    before = feature_cache.stats()
    builder.PipelineBuilder(q_bf16).execute()
    builder.PipelineBuilder(q_f32).execute()
    after = feature_cache.stats()
    assert after["hits"] == before["hits"] + 2


@pytest.mark.slow
def test_bank128_formulation_parity_interpret():
    """The accelerator formulation (bank128 routing) against the
    slice twin — interpret mode, so CPU-hermetic but slow; the
    block-class two-term correction's 5e-5 envelope applies."""
    raw, res, pos, mask, n = _irregular_case(n=48)
    slice_rows = np.asarray(
        decode_ingest.make_decode_ingest_featurizer(
            formulation="slice"
        )(raw, res, pos, mask)
    )
    bank_rows = np.asarray(
        decode_ingest.make_decode_ingest_featurizer(
            formulation="bank128"
        )(raw, res, pos, mask)
    )
    assert np.max(np.abs(slice_rows[:n] - bank_rows[:n])) < 5e-5
    assert np.all(bank_rows[n:] == 0.0)


# ------------------------------------------------ accelerator decision


def _write_artifact(root, rnd, name, payload):
    import json
    import os

    d = os.path.join(str(root), rnd)
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, name), "w") as f:
        f.write(json.dumps(payload) + "\n")


def test_accelerator_decision_without_chip_timing(tmp_path):
    """No bank128 chip artifact -> block stands, with the absence as
    the recorded reason (the PR 8 remainder: the default can only
    flip on measured silicon)."""
    decision = decode_ingest.accelerator_decision(root=str(tmp_path))
    assert decision["backend"] == "block"
    assert decision["bank128_eps"] is None
    assert "no on-chip bank128 timing" in decision["reason"]


def test_accelerator_decision_flips_on_chip_evidence(tmp_path):
    """A measured bank128 timing >= the pre-registered 2x block
    threshold flips the accelerator default to the decode rung; below
    it, block stands — both with the evidence in the record."""
    _write_artifact(
        tmp_path, "r9", "bank128_131k.json",
        {"variant": "pallas_ingest", "epochs_per_s": 3.0e6,
         "platform": "tpu"},
    )
    decision = decode_ingest.accelerator_decision(root=str(tmp_path))
    assert decision["backend"] == "decode"
    assert decision["bank128_eps"] == 3.0e6
    assert decision["source"].endswith("bank128_131k.json")
    # sub-threshold: block stands
    _write_artifact(
        tmp_path, "r9", "bank128_131k.json",
        {"variant": "pallas_ingest",
         "epochs_per_s": decode_ingest.CHIP_BLOCK_EPS * 1.5,
         "platform": "tpu"},
    )
    assert (
        decode_ingest.accelerator_decision(root=str(tmp_path))["backend"]
        == "block"
    )


def test_accelerator_decision_ignores_cpu_and_corrupt(tmp_path):
    """cpu_fallback payloads and unparseable artifacts never decide
    an accelerator default."""
    _write_artifact(
        tmp_path, "r9", "bank128_32k.json",
        {"epochs_per_s": 9.9e6, "platform": "cpu"},
    )
    import os

    d = os.path.join(str(tmp_path), "r9")
    with open(os.path.join(d, "pallas_ingest.json"), "w") as f:
        f.write("")  # the real r4 artifact: empty (helper crash)
    decision = decode_ingest.accelerator_decision(root=str(tmp_path))
    assert decision["backend"] == "block"
    assert decision["bank128_eps"] is None
