"""Golden-value parity for the reverse-engineered eegdsp DWT.

The contract is the reference's FeatureExtractionTest
(FeatureExtractionTest.java:63-106): 11 x 48 features from the fixture
with sum == -24.861844096031625, checked *bitwise* for the host
backend and to float32 tolerance for the XLA backend.
"""

import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.features import registry, wavelet
from eeg_dataanalysispackage_tpu.io import provider
from eeg_dataanalysispackage_tpu.ops import daubechies, dwt_host, eegdsp_compat


def java_feature_sum(features: np.ndarray) -> float:
    """Sequential per-epoch then total fold (FeatureExtractionTest.java:94-103)."""
    per_epoch = np.cumsum(features, axis=1)[:, -1]
    return float(np.cumsum(per_epoch)[-1])


@pytest.fixture(scope="module")
def fixture_epochs(fixture_dir):
    return provider.OfflineDataProvider([fixture_dir + "/infoTrain.txt"]).load()


def test_golden_feature_sum_bitwise(fixture_epochs):
    fe = registry.create("dwt-8")
    feats = fe.extract_batch(fixture_epochs.epochs)
    assert feats.shape == (11, 48)
    assert java_feature_sum(feats) == -24.861844096031625


def test_compact_backend_matches_host(fixture_epochs):
    """fe=dwt-8-tpu-compact (host-sliced (B, C, 512) residency,
    honest 6144 B/epoch — the einsum_512 headline candidate) must
    match the host features to the f32 contraction envelope, and the
    full-width xla backend to near-identity (identical math, only
    the 488 zero-row columns removed)."""
    host = registry.create("dwt-8").extract_batch(fixture_epochs.epochs)
    compact = registry.create("dwt-8-tpu-compact").extract_batch(
        fixture_epochs.epochs
    )
    assert compact.shape == (11, 48)
    np.testing.assert_allclose(compact, host, rtol=0, atol=5e-6)
    xla = registry.create("dwt-8-tpu").extract_batch(fixture_epochs.epochs)
    np.testing.assert_allclose(compact, xla, rtol=0, atol=1e-6)


def test_compact_bf16_backend_matches_bf16_tier(fixture_epochs):
    """fe=dwt-8-tpu-compact-bf16 (3072 B/epoch residency) stays
    inside the bf16 feature tier's envelope vs host, and the fixture
    classification outcome is unchanged (same gate the full-width
    bf16 backend passes)."""
    host = registry.create("dwt-8").extract_batch(fixture_epochs.epochs)
    compact = registry.create("dwt-8-tpu-compact-bf16").extract_batch(
        fixture_epochs.epochs
    )
    assert compact.shape == (11, 48)
    np.testing.assert_allclose(compact, host, rtol=0, atol=5e-3)


def test_compact_backend_respects_geometry_setters(fixture_epochs):
    from eeg_dataanalysispackage_tpu.features import wavelet

    host = wavelet.WaveletTransform(
        8, 256, 100, 8, backend="host"
    ).extract_batch(fixture_epochs.epochs)
    compact = wavelet.WaveletTransform(
        8, 256, 100, 8, backend="xla-compact"
    ).extract_batch(fixture_epochs.epochs)
    assert compact.shape == host.shape == (11, 24)
    np.testing.assert_allclose(compact, host, rtol=0, atol=5e-6)


def test_xla_backend_matches_host(fixture_epochs):
    host = registry.create("dwt-8").extract_batch(fixture_epochs.epochs)
    xla = registry.create("dwt-8-tpu").extract_batch(fixture_epochs.epochs)
    assert xla.shape == (11, 48)
    np.testing.assert_allclose(xla, host, rtol=0, atol=5e-6)


def test_single_epoch_adapter(fixture_epochs):
    fe = registry.create("dwt-8")
    one = fe.extract_features(fixture_epochs.epochs[0])
    batch = fe.extract_batch(fixture_epochs.epochs)
    np.testing.assert_array_equal(one, batch[0])
    assert fe.feature_dimension == 48


def test_feature_vectors_unit_norm(fixture_epochs):
    feats = registry.create("dwt-8").extract_batch(fixture_epochs.epochs)
    np.testing.assert_allclose((feats**2).sum(axis=1), 1.0, atol=1e-12)


def test_wavelet_registry_indices():
    # index 8 is the golden-pinned 10-tap table
    h8 = eegdsp_compat.scaling_filter(8)
    np.testing.assert_array_equal(h8, eegdsp_compat.DAUB10_H)
    # even indices exist, odd tap counts don't
    eegdsp_compat.scaling_filter(2)  # Daubechies4
    with pytest.raises(ValueError):
        eegdsp_compat.scaling_filter(1)  # Daubechies3: no such filter
    with pytest.raises(ValueError):
        eegdsp_compat.scaling_filter(18)


def test_daubechies_generator_matches_textbook_db2():
    h = daubechies.daubechies_scaling(2)
    ref = np.array(
        [-0.12940952255092145, 0.22414386804185735, 0.8365163037378079, 0.48296291314469025]
    )
    np.testing.assert_allclose(h, ref, atol=1e-15)


def test_daub10_table_is_truncated_spectral_factorization():
    """The 12-digit table must equal the computed filter rounded to 12
    decimals — guards against typos in the golden constants."""
    computed = np.round(daubechies.daubechies_scaling(5)[::-1], 12)
    np.testing.assert_array_equal(computed, eegdsp_compat.DAUB10_H)


def test_setter_validation_ranges():
    fe = wavelet.WaveletTransform()
    with pytest.raises(ValueError):
        fe.set_wavelet_name(18)
    with pytest.raises(ValueError):
        fe.set_epoch_size(751)
    with pytest.raises(ValueError):
        fe.set_skip_samples(0)
    with pytest.raises(ValueError):
        fe.set_feature_size(1025)
    fe2 = wavelet.WaveletTransform(8, 512, 175, 16)
    assert fe2 == wavelet.WaveletTransform(8, 512, 175, 16)
    assert fe2 != wavelet.WaveletTransform(8, 512, 175, 32)


def test_unknown_fe_name_raises():
    with pytest.raises(ValueError, match="Unsupported feature extraction"):
        registry.create("pca")


def test_dwt_layout_structure(fixture_epochs):
    """512 samples with a 10-tap filter run 6 levels; the first 16
    coefficients are a6(8) ++ d6(8), NOT 'level-5 approximation' as the
    reference's comments claim."""
    sig = fixture_epochs.epochs[0, 0, 175:687]
    full = dwt_host.fwt_periodic(sig, *eegdsp_compat.filter_pair(8))
    assert full.shape == (512,)
    coeffs = dwt_host.dwt_coefficients(sig, 8, 16)
    np.testing.assert_array_equal(coeffs, full[:16])


def test_setters_invalidate_xla_cache(fixture_epochs):
    fe = registry.create("dwt-8-tpu")
    out1 = fe.extract_batch(fixture_epochs.epochs)
    assert out1.shape == (11, 48)
    fe.set_feature_size(8)
    out2 = fe.extract_batch(fixture_epochs.epochs)
    assert out2.shape == (11, 24)


def test_window_exceeding_epoch_raises(fixture_epochs):
    fe = wavelet.WaveletTransform(8, 750, 750, 16)
    with pytest.raises(ValueError, match="exceeds the epoch length"):
        fe.extract_batch(fixture_epochs.epochs)


def test_xla_backend_non_power_of_two_epoch_size():
    """epoch_size=750 is allowed by the setter range (0, 750]; the
    matmul cascade must handle the odd intermediate lengths
    (750 -> 375 -> 187 ...) instead of crashing, and agree with the
    host path and the conv formulation (on 1000-sample inputs so the
    analysis window fits past the default 175-sample skip)."""
    epochs = np.random.RandomState(3).randn(4, 3, 1000) * 40.0
    host = wavelet.WaveletTransform(epoch_size=750, backend="host")
    xla = wavelet.WaveletTransform(epoch_size=750, backend="xla")
    f_host = host.extract_batch(epochs)
    f_xla = xla.extract_batch(epochs)
    assert f_host.shape == f_xla.shape == (4, 48)
    np.testing.assert_allclose(f_xla, f_host, atol=5e-5)


def test_sliced_contraction_matches_full_operator():
    """The bench's einsum_sliced/einsum_512 formulation — static
    slice to the live [skip, skip+size) columns + the 512-row cascade
    operator — must equal the full 1000-row zero-padded contraction
    (the r4b chip A/B is only honest if the two are the same math)."""
    import jax
    import jax.numpy as jnp

    from eeg_dataanalysispackage_tpu.ops import dwt as dwt_xla

    x = np.random.RandomState(11).randn(16, 3, 1000).astype(np.float32) * 50
    full = np.asarray(dwt_xla.make_batched_extractor()(jnp.asarray(x)))
    k512 = jnp.asarray(
        np.asarray(dwt_xla.cascade_matrix(8, 512, 16), np.float32)
    )
    z = jnp.asarray(x)[:, :, 175 : 175 + 512]
    y = jnp.einsum(
        "bct,tk->bck", z, k512, precision=jax.lax.Precision.HIGHEST
    )
    sliced = np.asarray(dwt_xla.safe_l2_normalize(y.reshape(16, 48)))
    np.testing.assert_allclose(sliced, full, rtol=0, atol=1e-6)


def test_unknown_extractor_method_raises():
    from eeg_dataanalysispackage_tpu.ops import dwt as dwt_xla

    with pytest.raises(ValueError, match="unknown method"):
        dwt_xla.make_batched_extractor(method="Matmul")


def test_bf16_backend_bounded_deviation_and_same_classification(
    fixture_epochs,
):
    """fe=dwt-8-tpu-bf16: half the HBM bytes for a bounded feature
    deviation; on the reference fixture the default-logreg
    classification outcome is identical to f32."""
    from eeg_dataanalysispackage_tpu.models import mllib_oracle
    from eeg_dataanalysispackage_tpu.utils import java_compat

    f32 = registry.create("dwt-8-tpu").extract_batch(fixture_epochs.epochs)
    bf16 = registry.create("dwt-8-tpu-bf16").extract_batch(
        fixture_epochs.epochs
    )
    assert bf16.dtype == np.float32  # returned widened for classifiers
    assert bf16.shape == f32.shape == (11, 48)
    dev = np.abs(bf16.astype(np.float64) - f32.astype(np.float64)).max()
    assert dev < 5e-3  # bf16 rounding on unit-normalized features
    perm = java_compat.java_shuffle_indices(11, seed=1)
    targets = np.asarray(fixture_epochs.targets)[perm]
    preds = {}
    for name, feats in (("f32", f32), ("bf16", bf16)):
        f = feats.astype(np.float64)[perm]
        w, _, _ = mllib_oracle.run_gradient_descent(
            f[:7], targets[:7], loss="logistic"
        )
        preds[name] = mllib_oracle.predict_logreg(f[7:], w).tolist()
    assert preds["bf16"] == preds["f32"]


def test_registry_bf16_name_family():
    fe = registry.create("dwt-5-tpu-bf16")
    assert fe.backend == "xla-bf16" and fe.name == 5
    with pytest.raises(ValueError):
        registry.create("dwt-8-tpu-bf32")


def test_backend_switch_invalidates_jit_cache(fixture_epochs):
    """backend is a property: reassigning it must drop the cached
    jitted extractor (which is backend/dtype-specific)."""
    fe = wavelet.WaveletTransform(8, 512, 175, 16, backend="xla")
    a = fe.extract_batch(fixture_epochs.epochs)
    assert fe._jit_cache is not None
    fe.backend = "xla-bf16"
    assert fe._jit_cache is None
    b = fe.extract_batch(fixture_epochs.epochs)
    assert not np.array_equal(a, b)  # bf16 path really ran
