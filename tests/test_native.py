"""Bit-parity tests: native C++ host kernels vs the numpy fallbacks.

The C++ library (native/eeg_host.cc) replaces the reference's closed
``eegloader-hdfs`` demux and the per-marker epoching loop
(OffLineDataProvider.java:167-196, 200-265). Every kernel must be
bit-identical to the numpy path, which is itself pinned against the
Java reference's golden sums (test_epoch_parity.py).
"""

import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.epochs import extractor
from eeg_dataanalysispackage_tpu.io import native, provider


needs_native = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


@needs_native
def test_demux_matches_numpy():
    rng = np.random.RandomState(7)
    raw = rng.randint(-32768, 32768, size=(5000, 8), dtype=np.int16)
    indices = [3, 0, 5]
    res = [0.1, 1.0, 0.0488281]

    out = native.demux_int16(raw, indices, res)
    res32 = np.asarray(res, dtype=np.float32)
    expect = (
        raw[:, indices].T.astype(np.float32) * res32[:, None]
    ).astype(np.float64)
    np.testing.assert_array_equal(out, expect)


@needs_native
def test_demux_vectorized_matches_numpy():
    rng = np.random.RandomState(8)
    raw = rng.randint(-32768, 32768, size=(4, 3000), dtype=np.int16)
    out = native.demux_int16(raw, [2, 1], [0.5, 0.25], vectorized=True)
    res32 = np.asarray([0.5, 0.25], dtype=np.float32)
    expect = (raw[[2, 1]].astype(np.float32) * res32[:, None]).astype(
        np.float64
    )
    np.testing.assert_array_equal(out, expect)


@needs_native
def test_gather_baseline_matches_numpy():
    rng = np.random.RandomState(9)
    channels = rng.randn(3, 2000) * 1000.0
    # include out-of-range starts (negative, > n) and a tail overhang
    positions = np.array([-50, 100, 150, 1990, 1500, 2150, 2090], dtype=np.int64)
    pre, post = 100, 750

    out = native.gather_baseline(channels, positions, pre, post)
    assert out is not None
    epochs_native, valid_native = out

    windows, valid_np = extractor.gather_windows(channels, positions, pre, post)
    corrected = extractor.baseline_correct_f32(windows, pre)
    epochs_np = corrected[..., pre:].astype(np.float64)

    np.testing.assert_array_equal(valid_native, valid_np)
    np.testing.assert_array_equal(epochs_native, epochs_np)


@needs_native
def test_balance_scan_matches_python():
    rng = np.random.RandomState(10)
    is_target = rng.rand(500) > 0.8

    counters = np.array([0, 0], dtype=np.int64)
    keep_native = native.balance_scan(is_target, counters)
    assert keep_native is not None

    state = extractor.BalanceState()
    keep_py = np.zeros(len(is_target), dtype=bool)
    n_t = n_nt = 0
    for i, t in enumerate(is_target):
        if t and n_t <= n_nt:
            keep_py[i] = True
            n_t += 1
        elif not t and n_t >= n_nt:
            keep_py[i] = True
            n_nt += 1
    np.testing.assert_array_equal(keep_native, keep_py)
    assert counters[0] == n_t and counters[1] == n_nt

    # BalanceState routes through the native kernel when available and
    # must land on the same counters.
    state.scan(is_target)
    assert (state.n_targets, state.n_nontargets) == (n_t, n_nt)


@needs_native
def test_native_pipeline_hits_golden_sums(fixture_dir):
    """The full ingest through the native kernels still reproduces the
    reference's golden epoch sums (OfflineDataProviderTest.java:81,88)."""
    from tests.test_epoch_parity import java_epoch_sum

    odp = provider.OfflineDataProvider([fixture_dir + "/infoTrain.txt"])
    batch = odp.load()
    assert batch.epochs.shape == (11, 3, 750)
    assert java_epoch_sum(batch.epochs) == -253772.18676757812
    assert int(batch.targets.sum()) == 5


def test_numpy_fallback_forced(fixture_dir, monkeypatch):
    """EEG_TPU_NATIVE=0 must force the numpy paths and produce the
    same golden sums (the two paths are interchangeable)."""
    from tests.test_epoch_parity import java_epoch_sum

    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", True)
    assert native.demux_int16(
        np.zeros((4, 2), np.int16), [0], [1.0]
    ) is None

    odp = provider.OfflineDataProvider([fixture_dir + "/infoTrain.txt"])
    batch = odp.load()
    assert java_epoch_sum(batch.epochs) == -253772.18676757812


# ---------------------------------------------------------------------------
# C++ BrainVision .vhdr/.vmrk parser vs the Python parser
# (native/eeg_host.cc::eeg_parse_vhdr/eeg_parse_vmrk vs
# io/brainvision.py::parse_vhdr_py/parse_vmrk_py)
# ---------------------------------------------------------------------------

import glob
import os

from eeg_dataanalysispackage_tpu.io import brainvision


@needs_native
def test_parse_vhdr_fixture_parity(fixture_dir):
    paths = sorted(glob.glob(os.path.join(fixture_dir, "DoD", "*.vhdr")))
    assert paths, "no .vhdr fixtures found"
    for p in paths:
        with open(p, "r", errors="replace") as f:
            text = f.read()
        got = native.parse_vhdr(text)
        assert got is not None, f"native parser unavailable for {p}"
        assert got == brainvision.parse_vhdr_py(text)


@needs_native
def test_parse_vmrk_fixture_parity(fixture_dir):
    paths = sorted(glob.glob(os.path.join(fixture_dir, "DoD", "*.vmrk")))
    assert paths, "no .vmrk fixtures found"
    for p in paths:
        with open(p, "r", errors="replace") as f:
            text = f.read()
        got = native.parse_vmrk(text)
        assert got is not None, f"native parser unavailable for {p}"
        assert got == brainvision.parse_vmrk_py(text)


@needs_native
def test_parse_vhdr_edge_semantics():
    """Duplicate sections merge, duplicate keys overwrite in place,
    comments/blank lines skip, escaped commas, empty resolution
    defaults, numeric channel ordering (Ch10 after Ch2)."""
    text = (
        "; comment line\n"
        "Brain Vision Data Exchange Header File Version 1.0\n"
        "[Common Infos]\r\n"
        "DataFile=a.eeg\n"
        "MarkerFile=a.vmrk\n"
        "  ; indented comment\n"
        "DataOrientation=VECTORIZED\n"
        "NumberOfChannels= 12 \n"
        "SamplingInterval=500\n"
        "[Binary Infos]\n"
        "BinaryFormat=IEEE_FLOAT_32\n"
        "[Channel Infos]\n"
        "Ch10=Late,,0.5,uV\n"
        "Ch2=Cz,REF,,mV\n"
        "Ch1=Fp\\1z,,0.1\n"
        "Ch2=Cz2,REF2,2.0,mV\n"
        "[Common Infos]\n"
        "DataFile=b.eeg\n"
    )
    got = native.parse_vhdr(text)
    want = brainvision.parse_vhdr_py(text)
    assert got is not None
    assert got == want
    assert want.data_file == "b.eeg"  # later dup key wins
    assert [c.name for c in want.channels] == ["Fp,z", "Cz2", "Late"]
    assert want.channels[1].resolution == 2.0  # in-place overwrite
    assert want.num_channels == 12
    assert want.orientation == "VECTORIZED"


@needs_native
def test_parse_vmrk_edge_semantics():
    text = (
        "[Marker Infos]\n"
        "Mk2=Stimulus,S  2,2000,1,0\n"
        "Mk1=New Segment,,1,1,0,20130611104808482924\n"
        "Mk10=Stimulus,S10,9000,1,0\n"
        "Mk3=Stimulus,S\\1x,notanint,1,0\n"
        "Codepage=UTF-8\n"
    )
    got = native.parse_vmrk(text)
    want = brainvision.parse_vmrk_py(text)
    assert got is not None
    assert got == want
    assert [m.name for m in want] == ["Mk1", "Mk2", "Mk3", "Mk10"]
    assert want[3].position == 9000
    assert want[2].position == 0  # unparseable position -> 0
    assert want[2].stimulus == "S,x"
    assert [m.stimulus_index() for m in want] == [-1, 1, -1, 9]


@needs_native
def test_parse_fallback_on_exotic_input():
    """Inputs the C++ side cannot represent exactly return None so the
    Python parser defines behavior."""
    # oversized channel name (>127 bytes) forces fallback
    big = "[Channel Infos]\nCh1=" + "x" * 400 + ",,0.1,uV\n"
    assert native.parse_vhdr(big) is None
    assert len(brainvision.parse_vhdr(big).channels[0].name) == 400

    # bad resolution float: native refuses; Python raises ValueError
    bad = "[Channel Infos]\nCh1=Fz,,zzz,uV\n"
    assert native.parse_vhdr(bad) is None
    with pytest.raises(ValueError):
        brainvision.parse_vhdr(bad)


@needs_native
def test_parse_divergence_guards():
    """Inputs where a byte-wise C++ parse would silently diverge from
    Python (exotic line terminators, Unicode, underscore numerals,
    int64 overflow, NAN(char-seq)) must route to the Python parser."""
    # lone-\r line terminators (classic-Mac export)
    mac = "[Common Infos]\rDataFile=x.eeg\r"
    assert native.parse_vhdr(mac) is None
    assert brainvision.parse_vhdr(mac).data_file == "x.eeg"

    # \v / \f are splitlines() terminators in Python
    assert native.parse_vhdr("[Common Infos]\vDataFile=y.eeg\n") is None

    # non-ASCII: Unicode digits in keys, U+00A0 around keys
    uni = "[Channel Infos]\nCh١=Fz,,0.1,uV\n"
    assert native.parse_vhdr(uni) is None
    assert len(brainvision.parse_vhdr(uni).channels) == 1
    nbsp = "[Common Infos]\nDataFile =x.eeg\n"
    assert native.parse_vhdr(nbsp) is None
    assert brainvision.parse_vhdr(nbsp).data_file == "x.eeg"

    # underscore numerals: Python int("1_000") == 1000
    und = "[Marker Infos]\nMk1=Stimulus,S  1,1_000,1,0\n"
    got = brainvision.parse_vmrk(und)
    assert got[0].position == 1000
    native_got = native.parse_vmrk(und)
    assert native_got is None or native_got == got

    # int64 overflow in a marker position: Python bigint succeeds
    big = "[Marker Infos]\nMk1=Stimulus,S  1,99999999999999999999,1,0\n"
    assert native.parse_vmrk(big) is None
    assert brainvision.parse_vmrk(big)[0].position == 10**20 - 1

    # Ch key number overflowing int64 keeps the channel in Python
    bigch = "[Channel Infos]\nCh99999999999999999999=Fz,,0.1,uV\n"
    assert native.parse_vhdr(bigch) is None
    assert len(brainvision.parse_vhdr(bigch).channels) == 1

    # glibc strtod accepts "nan(123)"; Python float() raises
    nanish = "[Common Infos]\nSamplingInterval=nan(123)\n"
    assert native.parse_vhdr(nanish) is None
    with pytest.raises(ValueError):
        brainvision.parse_vhdr(nanish)


@needs_native
def test_parse_nul_and_surrogates_fall_back():
    """NUL bytes (c_char truncation) and lone surrogates
    (surrogateescape reads) must route to the Python parser."""
    nul = "[Common Infos]\nDataFile=a\x00b.eeg\n"
    assert native.parse_vhdr(nul) is None
    assert brainvision.parse_vhdr(nul).data_file == "a\x00b.eeg"

    surr = "[Common Infos]\nDataFile=a\udcffb.eeg\n"
    assert native.parse_vhdr(surr) is None
    assert brainvision.parse_vhdr(surr).data_file == "a\udcffb.eeg"
    assert native.parse_vmrk(surr) is None


@needs_native
def test_parser_differential_fuzz():
    """Deterministic differential fuzz: on random structured inputs the
    native parse must either equal the Python parse or decline (None).
    Alphabet stresses the INI edge cases: '=', ';', '[', ']', commas,
    backslash-escapes, whitespace, CRLF, digits."""
    import random

    rng = random.Random(42)
    # no bare "\r" in line bodies — it would make _native_parseable
    # decline the whole input and skip the comparison; CRLF coverage
    # comes from the per-line terminator choice below
    tokens = list("ab=;[]\\,.0123456789 \t") + ["Ch", "Mk", "_", "#"]

    def rand_line():
        return "".join(
            rng.choice(tokens) for _ in range(rng.randrange(0, 30))
        )

    sections = ["[Common Infos]", "[Channel Infos]", "[Marker Infos]",
                "[Binary Infos]", "[junk]"]
    native_parses = vmrk_parses = 0
    for trial in range(300):
        n = rng.randrange(0, 12)
        lines = []
        for _ in range(n):
            r = rng.random()
            if r < 0.2:
                lines.append(rng.choice(sections))
            elif r < 0.5:
                lines.append(
                    f"Ch{rng.randrange(0, 20)}=" + rand_line()
                    if rng.random() < 0.5
                    else f"Mk{rng.randrange(0, 20)}=" + rand_line()
                )
            else:
                lines.append(rand_line())
        text = "".join(
            line + rng.choice(["\n", "\r\n"]) for line in lines
        ) + rng.choice(["", "trailing no-newline"])

        try:
            want_h = brainvision.parse_vhdr_py(text)
            err_h = None
        except Exception as e:
            want_h, err_h = None, e
        got_h = native.parse_vhdr(text)
        if got_h is not None:
            native_parses += 1
            assert err_h is None, (
                f"trial {trial}: native parsed what Python rejects: "
                f"{text!r} ({err_h})"
            )
            assert got_h == want_h, f"trial {trial}: vhdr mismatch on {text!r}"

        try:
            want_m = brainvision.parse_vmrk_py(text)
            err_m = None
        except Exception as e:
            want_m, err_m = None, e
        got_m = native.parse_vmrk(text)
        if got_m is not None:
            vmrk_parses += 1
            assert err_m is None, (
                f"trial {trial}: native parsed what Python rejects: "
                f"{text!r} ({err_m})"
            )
            assert got_m == want_m, f"trial {trial}: vmrk mismatch on {text!r}"

    # the differential comparison must actually run — if the native
    # side declines most inputs the test is vacuous
    assert native_parses >= 200, f"only {native_parses}/300 vhdr parses"
    assert vmrk_parses >= 200, f"only {vmrk_parses}/300 vmrk parses"
