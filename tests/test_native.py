"""Bit-parity tests: native C++ host kernels vs the numpy fallbacks.

The C++ library (native/eeg_host.cc) replaces the reference's closed
``eegloader-hdfs`` demux and the per-marker epoching loop
(OffLineDataProvider.java:167-196, 200-265). Every kernel must be
bit-identical to the numpy path, which is itself pinned against the
Java reference's golden sums (test_epoch_parity.py).
"""

import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.epochs import extractor
from eeg_dataanalysispackage_tpu.io import native, provider


needs_native = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


@needs_native
def test_demux_matches_numpy():
    rng = np.random.RandomState(7)
    raw = rng.randint(-32768, 32768, size=(5000, 8), dtype=np.int16)
    indices = [3, 0, 5]
    res = [0.1, 1.0, 0.0488281]

    out = native.demux_int16(raw, indices, res)
    res32 = np.asarray(res, dtype=np.float32)
    expect = (
        raw[:, indices].T.astype(np.float32) * res32[:, None]
    ).astype(np.float64)
    np.testing.assert_array_equal(out, expect)


@needs_native
def test_demux_vectorized_matches_numpy():
    rng = np.random.RandomState(8)
    raw = rng.randint(-32768, 32768, size=(4, 3000), dtype=np.int16)
    out = native.demux_int16(raw, [2, 1], [0.5, 0.25], vectorized=True)
    res32 = np.asarray([0.5, 0.25], dtype=np.float32)
    expect = (raw[[2, 1]].astype(np.float32) * res32[:, None]).astype(
        np.float64
    )
    np.testing.assert_array_equal(out, expect)


@needs_native
def test_gather_baseline_matches_numpy():
    rng = np.random.RandomState(9)
    channels = rng.randn(3, 2000) * 1000.0
    # include out-of-range starts (negative, > n) and a tail overhang
    positions = np.array([-50, 100, 150, 1990, 1500, 2150, 2090], dtype=np.int64)
    pre, post = 100, 750

    out = native.gather_baseline(channels, positions, pre, post)
    assert out is not None
    epochs_native, valid_native = out

    windows, valid_np = extractor.gather_windows(channels, positions, pre, post)
    corrected = extractor.baseline_correct_f32(windows, pre)
    epochs_np = corrected[..., pre:].astype(np.float64)

    np.testing.assert_array_equal(valid_native, valid_np)
    np.testing.assert_array_equal(epochs_native, epochs_np)


@needs_native
def test_balance_scan_matches_python():
    rng = np.random.RandomState(10)
    is_target = rng.rand(500) > 0.8

    counters = np.array([0, 0], dtype=np.int64)
    keep_native = native.balance_scan(is_target, counters)
    assert keep_native is not None

    state = extractor.BalanceState()
    keep_py = np.zeros(len(is_target), dtype=bool)
    n_t = n_nt = 0
    for i, t in enumerate(is_target):
        if t and n_t <= n_nt:
            keep_py[i] = True
            n_t += 1
        elif not t and n_t >= n_nt:
            keep_py[i] = True
            n_nt += 1
    np.testing.assert_array_equal(keep_native, keep_py)
    assert counters[0] == n_t and counters[1] == n_nt

    # BalanceState routes through the native kernel when available and
    # must land on the same counters.
    state.scan(is_target)
    assert (state.n_targets, state.n_nontargets) == (n_t, n_nt)


@needs_native
def test_native_pipeline_hits_golden_sums(fixture_dir):
    """The full ingest through the native kernels still reproduces the
    reference's golden epoch sums (OfflineDataProviderTest.java:81,88)."""
    from tests.test_epoch_parity import java_epoch_sum

    odp = provider.OfflineDataProvider([fixture_dir + "/infoTrain.txt"])
    batch = odp.load()
    assert batch.epochs.shape == (11, 3, 750)
    assert java_epoch_sum(batch.epochs) == -253772.18676757812
    assert int(batch.targets.sum()) == 5


def test_numpy_fallback_forced(fixture_dir, monkeypatch):
    """EEG_TPU_NATIVE=0 must force the numpy paths and produce the
    same golden sums (the two paths are interchangeable)."""
    from tests.test_epoch_parity import java_epoch_sum

    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", True)
    assert native.demux_int16(
        np.zeros((4, 2), np.int16), [0], [1.0]
    ) is None

    odp = provider.OfflineDataProvider([fixture_dir + "/infoTrain.txt"])
    batch = odp.load()
    assert java_epoch_sum(batch.epochs) == -253772.18676757812
