"""Classifier, stats, and Java-compat shuffle tests."""

import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.models import linear, registry, sgd, stats
from eeg_dataanalysispackage_tpu.utils import java_compat


# -- java.util.Random parity ------------------------------------------


def test_java_random_golden_values():
    # Famous java.util.Random outputs: these pin the 48-bit LCG.
    assert java_compat.JavaRandom(1).next_int32() == -1155869325
    assert java_compat.JavaRandom(0).next_int32() == -1155484576


def test_java_shuffle_is_permutation_and_deterministic():
    a = java_compat.java_shuffle_indices(11, seed=1)
    b = java_compat.java_shuffle_indices(11, seed=1)
    assert a == b
    assert sorted(a) == list(range(11))
    assert a != list(range(11))


def test_split_matches_reference_shape():
    train, test = java_compat.train_test_split_indices(11, seed=1)
    assert len(train) == 7  # (int)(11*0.7)
    assert len(test) == 4
    assert sorted(train + test) == list(range(11))


# -- ClassificationStatistics -----------------------------------------


def test_stats_report_format():
    s = stats.ClassificationStatistics(tp=3, tn=4, fp=2, fn=1)
    text = str(s)
    assert "Number of patterns: 10" in text
    assert "True positives: 3" in text
    assert "Accuracy: 70.0%" in text
    assert text.endswith("Targets: 0.0\n")


def test_stats_incremental_matches_batched():
    rng = np.random.RandomState(0)
    real = rng.rand(50)
    exp = (rng.rand(50) > 0.5).astype(float)
    s1 = stats.ClassificationStatistics()
    for r, e in zip(real, exp):
        s1.add(r, e)
    s2 = stats.ClassificationStatistics.from_arrays(real, exp)
    assert (
        s1.true_positives,
        s1.true_negatives,
        s1.false_positives,
        s1.false_negatives,
    ) == (
        s2.true_positives,
        s2.true_negatives,
        s2.false_positives,
        s2.false_negatives,
    )
    assert s1.mse == pytest.approx(s2.mse)
    assert s1.class1_sum == pytest.approx(s2.class1_sum)


def test_stats_java_round_half_up():
    s = stats.ClassificationStatistics.from_arrays(
        np.array([0.5]), np.array([1.0])
    )  # Math.round(0.5) == 1 (half-up; Python's round() would give 0)
    assert s.true_positives == 1


# -- linear classifiers ------------------------------------------------


def make_separable(n=200, d=8, seed=0):
    rng = np.random.RandomState(seed)
    w_true = rng.randn(d)
    x = rng.randn(n, d)
    y = (x @ w_true > 0).astype(np.float64)
    return x, y


def test_logreg_learns_separable():
    x, y = make_separable()
    clf = linear.LogisticRegressionClassifier()
    clf.set_config({})
    clf.fit(x, y)
    acc = (clf.predict(x) == y).mean()
    assert acc > 0.95


def test_svm_learns_separable():
    x, y = make_separable(seed=3)
    clf = linear.SVMClassifier()
    clf.set_config(
        {
            "config_num_iterations": "100",
            "config_step_size": "1.0",
            "config_reg_param": "0.01",
            "config_mini_batch_fraction": "1.0",
        }
    )
    clf.fit(x, y)
    assert (clf.predict(x) == y).mean() > 0.95


def test_minibatch_sampling_path():
    x, y = make_separable(seed=5)
    cfg = sgd.SGDConfig(num_iterations=50, mini_batch_fraction=0.5)
    w = sgd.train_linear(x, y, cfg)
    acc = ((x @ w >= 0) == y).mean()
    assert acc > 0.9


def test_sgd_deterministic():
    x, y = make_separable(seed=7)
    cfg = sgd.SGDConfig(num_iterations=20, mini_batch_fraction=0.3)
    np.testing.assert_array_equal(
        sgd.train_linear(x, y, cfg), sgd.train_linear(x, y, cfg)
    )


def test_save_load_roundtrip(tmp_path):
    x, y = make_separable()
    clf = linear.LogisticRegressionClassifier()
    clf.set_config({})
    clf.fit(x, y)
    path = str(tmp_path / "model")
    clf.save(path)
    clf2 = linear.LogisticRegressionClassifier()
    clf2.load(path)
    np.testing.assert_array_equal(clf.weights, clf2.weights)


def test_save_load_file_uri(tmp_path):
    """file:// URIs are tolerated like the reference's path handling
    (DecisionTreeClassifier.java:157-165 prefixes them itself)."""
    x, y = make_separable()
    clf = linear.LogisticRegressionClassifier()
    clf.set_config({})
    clf.fit(x, y)
    clf.save(f"file://{tmp_path}/model")
    assert (tmp_path / "model.npz").exists()
    clf2 = linear.LogisticRegressionClassifier()
    clf2.load(str(tmp_path / "model"))
    np.testing.assert_array_equal(clf.weights, clf2.weights)


def test_save_deletes_stale_directory_target(tmp_path):
    """Reference parity: the MLlib savers delete an existing
    directory at the raw save target first
    (LogisticRegressionClassifier.java:144-147)."""
    x, y = make_separable()
    clf = linear.LogisticRegressionClassifier()
    clf.set_config({})
    clf.fit(x, y)
    stale = tmp_path / "model"
    stale.mkdir()
    (stale / "old-part").write_text("stale directory-format model")
    clf.save(str(stale))
    assert not stale.is_dir()
    assert (tmp_path / "model.npz").exists()


def test_nn_save_onto_directory_errors(tmp_path):
    """The NN saver must NOT inherit the MLlib delete-directory
    quirk: writing onto an existing directory errors loudly instead
    of destroying it."""
    from eeg_dataanalysispackage_tpu.models import nn as nn_mod

    target = tmp_path / "models"
    target.mkdir()
    (target / "other").write_text("another model")
    clf = nn_mod.NeuralNetworkClassifier()
    clf.params = {}  # minimal state; failure happens at write time
    clf._arch = {"n_in": 1, "n_outs": [2], "layer_types": ["output"],
                 "activations": ["softmax"], "dropouts": [0.0],
                 "weight_init": "xavier"}
    with pytest.raises(IsADirectoryError):
        clf.save(str(target))
    assert (target / "other").exists()


def test_registry_unknown_raises():
    with pytest.raises(ValueError, match="Unsupported classifier"):
        registry.create("xgboost")


def test_predict_before_fit_raises():
    with pytest.raises(ValueError, match="not trained"):
        linear.SVMClassifier().predict(np.zeros((1, 4)))


def test_confusion_only_swaps_fp_fn():
    """Reference bug-as-behavior: MLlib-path reports read Spark's
    column-major confusion matrix as [tn,fp,fn,tp] when it is actually
    [tn,fn,fp,tp], swapping FP/FN in every report."""
    real = np.array([0.0, 0.0, 0.0])  # all predicted negative
    exp = np.array([1.0, 1.0, 0.0])  # two actual positives
    s = stats.ClassificationStatistics.from_arrays(real, exp, confusion_only=True)
    assert (s.false_positives, s.false_negatives) == (2, 0)  # swapped
    s2 = stats.ClassificationStatistics.from_arrays(real, exp)
    assert (s2.false_positives, s2.false_negatives) == (0, 2)  # true labels


def test_empty_stats_prints_nan():
    s = stats.ClassificationStatistics.from_arrays(np.zeros(0), np.zeros(0))
    assert "Accuracy: nan%" in str(s)
