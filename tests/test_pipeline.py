"""End-to-end pipeline tests (reference: PipelineTest.java:52-97).

Whole query-string runs: train+save then load+test, via the same
query-parameter surface as the reference.
"""

import os

import pytest

from eeg_dataanalysispackage_tpu.pipeline import builder


def test_query_map_parse():
    q = builder.get_query_map("a=1&b=&c=x=y&d")
    assert q["a"] == "1"
    assert q["b"] == ""
    # first-'='-split: option values with embedded '=' survive (the
    # reference's split('=')[1] truncation quirk is fixed at the
    # parser — PR 7's per-key re-extraction workaround is gone)
    assert q["c"] == "x=y"
    assert q["d"] == ""


def test_query_map_embedded_equals_round_trips():
    """The option grammars that legitimately carry '=' must survive
    the parser everywhere — fe=, fe_sweep=, sweep=, faults= — and
    agree with the raw-param extraction."""
    q = (
        "fe=dwt-4:level=4:stats=energy,std"
        "&fe_sweep=dwt-4:level=2|dwt-8:stats=mean"
        "&sweep=lr:1.0,0.5;reg:0.0,0.01"
        "&faults=remote.request:p=0.2;staging.producer:once@2"
    )
    m = builder.get_query_map(q)
    assert m["fe"] == "dwt-4:level=4:stats=energy,std"
    assert m["fe_sweep"] == "dwt-4:level=2|dwt-8:stats=mean"
    assert m["sweep"] == "lr:1.0,0.5;reg:0.0,0.01"
    assert m["faults"] == "remote.request:p=0.2;staging.producer:once@2"
    for key, want in m.items():
        assert builder.get_raw_param(q, key) == want


def test_percent_decode_roundtrips_escaped_option_values():
    """Network-submitted query strings arrive URL-encoded (gateway/):
    the decode shim must round-trip the '='/':'/','-bearing option
    grammars through %3A/%3D/%2C escapes into exactly the string
    get_query_map already parses."""
    from urllib.parse import quote

    decoded = (
        "fe=dwt-8:level=5:stats=energy,mean"
        "&sweep=lr:1.0,0.5;reg:0.0,0.01"
        "&faults=remote.request:p=0.2;seed=3"
    )
    encoded = "&".join(
        f"{name}={quote(value, safe='')}"
        for name, value in (
            param.split("=", 1) for param in decoded.split("&")
        )
    )
    assert "%3A" in encoded and "%3D" in encoded and "%2C" in encoded
    assert builder.decode_percent_query(encoded) == decoded
    m = builder.get_query_map(builder.decode_percent_query(encoded))
    assert m["fe"] == "dwt-8:level=5:stats=energy,mean"
    assert m["sweep"] == "lr:1.0,0.5;reg:0.0,0.01"
    assert m["faults"] == "remote.request:p=0.2;seed=3"


def test_percent_decode_passthrough_and_rejection():
    # no '%': byte-identical passthrough — every query ever written
    # is unchanged
    q = "info_file=/a/b.txt&fe=dwt-8&train_clf=logreg"
    assert builder.decode_percent_query(q) is q
    # literal '%' that is not an escape survives unquote unchanged
    assert builder.decode_percent_query("a=50%25") == "a=50%"
    # a decoded '&' (or '=' in a name) cannot be represented in the
    # k=v&k=v surface: loud error, never a silent re-split
    with pytest.raises(ValueError):
        builder.decode_percent_query("a=x%26y=1")
    with pytest.raises(ValueError):
        builder.decode_percent_query("a%3Db=1")


def test_logreg_train_pipeline(fixture_dir, tmp_path):
    result = str(tmp_path / "result.txt")
    stats = builder.PipelineBuilder(
        f"info_file={fixture_dir}/infoTrain.txt"
        "&fe=dwt-8"
        "&train_clf=logreg"
        f"&result_path={result}"
    ).execute()
    assert stats.num_patterns == 4  # 30% of 11
    assert os.path.exists(result)
    text = open(result).read()
    assert text.startswith("Number of patterns: 4\n")
    assert "Accuracy: " in text


def test_svm_train_save_then_load_pipeline(fixture_dir, tmp_path):
    model = str(tmp_path / "svm_model")
    builder.PipelineBuilder(
        f"info_file={fixture_dir}/infoTrain.txt"
        "&fe=dwt-8"
        "&train_clf=svm"
        "&config_step_size=1.0"
        "&config_num_iterations=10"
        "&config_reg_param=0.01"
        "&config_mini_batch_fraction=1.0"
        "&save_clf=true"
        f"&save_name={model}"
    ).execute()
    assert os.path.exists(model + ".npz")

    stats = builder.PipelineBuilder(
        f"info_file={fixture_dir}/infoTrain.txt"
        "&fe=dwt-8"
        "&load_clf=svm"
        f"&load_name={model}"
    ).execute()
    # load mode tests on ALL shuffled data (PipelineBuilder.java:278)
    assert stats.num_patterns == 11


def test_eeg_file_input_pipeline(fixture_dir):
    stats = builder.PipelineBuilder(
        f"eeg_file={fixture_dir}/DoD/DoD_2015_02.eeg"
        "&guessed_num=4"
        "&fe=dwt-8"
        "&train_clf=logreg"
    ).execute()
    assert stats.num_patterns == 9  # 27 - (int)(27*0.7)


def test_missing_input_raises():
    with pytest.raises(ValueError, match="Missing the input file argument"):
        builder.PipelineBuilder("fe=dwt-8&train_clf=logreg").execute()


def test_missing_fe_raises(fixture_dir):
    with pytest.raises(ValueError, match="Missing the feature extraction"):
        builder.PipelineBuilder(
            f"info_file={fixture_dir}/infoTrain.txt&train_clf=logreg"
        ).execute()


def test_missing_classifier_raises(fixture_dir):
    with pytest.raises(ValueError, match="Missing classifier argument"):
        builder.PipelineBuilder(
            f"info_file={fixture_dir}/infoTrain.txt&fe=dwt-8"
        ).execute()


def test_save_without_name_raises(fixture_dir):
    with pytest.raises(ValueError, match="save_name"):
        builder.PipelineBuilder(
            f"info_file={fixture_dir}/infoTrain.txt"
            "&fe=dwt-8&train_clf=logreg&save_clf=true"
        ).execute()


def test_load_without_name_raises(fixture_dir):
    with pytest.raises(ValueError, match="location not provided"):
        builder.PipelineBuilder(
            f"info_file={fixture_dir}/infoTrain.txt&fe=dwt-8&load_clf=svm"
        ).execute()


def test_cli_main(fixture_dir, tmp_path, capsys):
    from eeg_dataanalysispackage_tpu.pipeline import cli

    result = str(tmp_path / "r.txt")
    rc = cli.main(
        [
            f"info_file={fixture_dir}/infoTrain.txt&fe=dwt-8"
            f"&train_clf=logreg&result_path={result}"
        ]
    )
    assert rc == 0
    assert "Number of patterns" in capsys.readouterr().out
    assert rc == 0


def test_cli_no_args():
    from eeg_dataanalysispackage_tpu.pipeline import cli

    assert cli.main([]) == 2


def test_cli_bad_query():
    from eeg_dataanalysispackage_tpu.pipeline import cli

    assert cli.main(["garbage"]) == 1


def test_dt_and_rf_pipelines(fixture_dir):
    for clf in ("dt", "rf"):
        stats = builder.PipelineBuilder(
            f"info_file={fixture_dir}/infoTrain.txt"
            f"&fe=dwt-8&train_clf={clf}"
            "&config_max_bins=16&config_impurity=gini&config_max_depth=4"
            "&config_min_instances_per_node=1&config_num_trees=5"
            "&config_feature_subset=auto"
        ).execute()
        assert stats.num_patterns == 4


def test_nn_pipeline(fixture_dir):
    stats = builder.PipelineBuilder(
        f"info_file={fixture_dir}/infoTrain.txt"
        "&fe=dwt-8&train_clf=nn"
        "&config_seed=1&config_num_iterations=50&config_learning_rate=0.1"
        "&config_momentum=0.9&config_weight_init=xavier"
        "&config_updater=nesterovs"
        "&config_optimization_algo=stochastic_gradient_descent"
        "&config_pretrain=false&config_backprop=true"
        "&config_loss_function=xent"
        "&config_layer1_layer_type=dense&config_layer1_n_out=8"
        "&config_layer1_drop_out=0.0&config_layer1_activation_function=relu"
        "&config_layer2_layer_type=output&config_layer2_n_out=2"
        "&config_layer2_drop_out=0.0&config_layer2_activation_function=softmax"
    ).execute()
    assert stats.num_patterns == 4
    # NN stats use the incremental path: MSE/class sums are populated
    assert stats.mse >= 0.0


def test_trace_path_query_param(fixture_dir, tmp_path):
    """trace_path wraps the run in a jax.profiler trace directory."""
    trace_dir = tmp_path / "trace"
    q = (
        f"info_file={fixture_dir}/infoTrain.txt&fe=dwt-8-tpu"
        f"&train_clf=logreg&trace_path={trace_dir}"
    )
    stats = builder.PipelineBuilder(q).execute()
    assert stats.num_patterns > 0
    # jax writes plugins/profile/<ts>/ under the trace dir
    assert trace_dir.exists() and any(trace_dir.rglob("*"))
