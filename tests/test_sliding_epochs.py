"""Continuous sliding-window epocher (epochs/sliding.py) + the
synthetic continuous generator + the provider seam."""

import numpy as np
import pytest

import _synthetic
from eeg_dataanalysispackage_tpu.epochs import sliding
from eeg_dataanalysispackage_tpu.io import provider
from eeg_dataanalysispackage_tpu.io.brainvision import Marker


def mk(kind, stim, pos):
    return Marker(name="MkX", kind=kind, stimulus=stim, position=pos)


# ------------------------------------------------ interval pairing


def test_on_off_pairs():
    markers = [
        mk("Seizure", "on", 100), mk("Seizure", "off", 200),
        mk("Stimulus", "S  3", 150),  # ignored
        mk("Seizure", "on", 500), mk("Seizure", "off", 650),
    ]
    assert sliding.seizure_intervals(markers, 1000) == [
        (100, 200), (500, 650)
    ]


def test_dangling_on_runs_to_end_and_orphan_off_ignored():
    markers = [
        mk("Seizure", "off", 50),        # orphan: no open interval
        mk("Seizure", "on", 700),        # cut short by recording end
    ]
    assert sliding.seizure_intervals(markers, 1000) == [(700, 1000)]


def test_unordered_markers_pair_by_position():
    markers = [
        mk("Seizure", "off", 300), mk("Seizure", "on", 100),
    ]
    assert sliding.seizure_intervals(markers, 1000) == [(100, 300)]


def test_no_seizure_markers():
    assert sliding.seizure_intervals([mk("Stimulus", "S 1", 10)], 500) == []


# ------------------------------------------------ window geometry


def test_window_starts_full_windows_only():
    starts = sliding.window_starts(1000, 512, 256)
    assert starts.tolist() == [0, 256]  # 512@512 ends at 1024 > 1000
    assert sliding.window_starts(300, 512, 256).tolist() == []
    assert sliding.window_starts(512, 512, 256).tolist() == [0]


def test_overlap_fractions():
    starts = np.array([0, 100, 200])
    fr = sliding.overlap_fractions(starts, 100, [(150, 250)])
    assert fr.tolist() == [0.0, 0.5, 0.5]
    # two disjoint intervals accumulate
    fr2 = sliding.overlap_fractions(
        np.array([0]), 100, [(0, 25), (50, 75)]
    )
    assert fr2.tolist() == [0.5]


def test_config_validation():
    with pytest.raises(ValueError, match="window"):
        sliding.SlidingConfig(window=0)
    with pytest.raises(ValueError, match="stride"):
        sliding.SlidingConfig(stride=0)
    with pytest.raises(ValueError, match="label_overlap"):
        sliding.SlidingConfig(label_overlap=0.0)
    with pytest.raises(ValueError, match="label_overlap"):
        sliding.SlidingConfig(label_overlap=1.5)


# ------------------------------------------------ extraction


def test_extract_sliding_epochs_contract():
    """EpochBatch contract: float64 (n, C, window) slices of the
    channel matrix, interval-overlap labels, start-sample indices."""
    rng = np.random.RandomState(0)
    channels = rng.randn(2, 2000)
    markers = [mk("Seizure", "on", 512), mk("Seizure", "off", 1024)]
    cfg = sliding.SlidingConfig(window=512, stride=256, label_overlap=0.5)
    batch = sliding.extract_sliding_epochs(channels, markers, cfg)
    assert batch.epochs.shape == (len(batch), 2, 512)
    assert batch.epochs.dtype == np.float64
    # window i is exactly the channel slice at its recorded start
    for i, start in enumerate(batch.stimulus_indices):
        np.testing.assert_array_equal(
            batch.epochs[i], channels[:, start:start + 512]
        )
    # labels: windows fully inside [512, 1024) are positive; the
    # window at 256 overlaps half (>= 0.5) so it labels positive too
    expected = {0: 0.0, 256: 1.0, 512: 1.0, 768: 1.0, 1024: 0.0}
    for start, want in expected.items():
        idx = batch.stimulus_indices.tolist().index(start)
        assert batch.targets[idx] == want, start


def test_short_recording_yields_empty_batch():
    batch = sliding.extract_sliding_epochs(
        np.zeros((3, 100)), [], sliding.SlidingConfig(window=512)
    )
    assert len(batch) == 0
    assert batch.epochs.shape == (0, 3, 512)


# ------------------------------------------------ provider + generator


def test_provider_load_sliding_imbalanced_and_pool_invariant(tmp_path):
    info = _synthetic.write_seizure_session(
        str(tmp_path), n_files=2, n_samples=30000
    )
    cfg = sliding.SlidingConfig(window=512, stride=256)
    b1 = provider.OfflineDataProvider([info], workers=1).load_sliding(cfg)
    b4 = provider.OfflineDataProvider([info], workers=4).load_sliding(cfg)
    # the hermetic generator produces a genuinely imbalanced set
    ratio = b1.targets.mean()
    assert 0.0 < ratio < 0.35, ratio
    # order-preserving pool merge: bit-identical at any pool size
    np.testing.assert_array_equal(b1.epochs, b4.epochs)
    np.testing.assert_array_equal(b1.targets, b4.targets)
    np.testing.assert_array_equal(b1.stimulus_indices, b4.stimulus_indices)


def test_generator_intervals_match_annotations(tmp_path):
    from eeg_dataanalysispackage_tpu.io import brainvision

    eeg = _synthetic.write_continuous_recording(
        str(tmp_path), n_samples=20000,
        seizure_intervals=((4000, 6000),),
    )
    rec = brainvision.load_recording(eeg)
    assert sliding.seizure_intervals(rec.markers, rec.num_samples) == [
        (4000, 6000)
    ]
