"""Pod-scale multi-process execution (ISSUE 14), hermetic half.

What is testable without real peer processes: the deterministic
recording partition and its parity contract (the partitioned ingest's
rows concatenate to the single-process run's rows, bit for bit — the
balance scan, stale-channel-index reuse, and epoch order all survive
partitioning because the metadata pass is global), the bootstrap
latch/reset seam, the resolved-values return, and the pipeline-level
degradation: a pod that cannot assemble (coordinator unreachable, peer
host missing — the preflight turns both into a catchable error before
XLA's fatal path) lands the single-host rung with the evidence in the
mesh block, and ``processes=1`` is byte-identical to today. The live
two-process half is tests/test_pod_pipeline.py.
"""

import os
import socket

import numpy as np
import pytest

import _synthetic
from eeg_dataanalysispackage_tpu import obs
from eeg_dataanalysispackage_tpu.io import provider
from eeg_dataanalysispackage_tpu.parallel import distributed, pod
from eeg_dataanalysispackage_tpu.pipeline import builder


def _session(directory, n_files=3, n_markers=40):
    lines = []
    for i in range(n_files):
        name = f"pod_{i:02d}"
        guessed = 2 + i
        _synthetic.write_recording(
            str(directory), name=name, n_markers=n_markers,
            guessed=guessed, seed=i,
        )
        lines.append(f"{name}.eeg {guessed}")
    info = os.path.join(str(directory), "info.txt")
    with open(info, "w") as f:
        f.write("\n".join(lines) + "\n")
    return info


@pytest.fixture(scope="module")
def info(tmp_path_factory):
    return _session(tmp_path_factory.mktemp("pod_session"))


_POP_QUERY = (
    "fe=dwt-8-fused&train_clf=logreg&cv=2&sweep=lr:1.0,0.5&cache=false"
    "&config_num_iterations=12&config_step_size=1.0"
    "&config_mini_batch_fraction=1.0"
)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ------------------------------------------------ partition


def test_partition_disjoint_exhaustive_order_stable():
    for n in range(0, 14):
        for procs in range(1, 8):
            ranges = partitioned = pod.partition(n, procs)
            assert len(ranges) == procs
            flat = [
                i for lo, hi in partitioned for i in range(lo, hi)
            ]
            # exhaustive + order-stable: concatenating the blocks in
            # process order reproduces the original index order
            assert flat == list(range(n))
            # disjoint + contiguous
            assert all(lo <= hi for lo, hi in ranges)
            assert all(
                ranges[p][1] == ranges[p + 1][0]
                for p in range(procs - 1)
            )
            # balanced: block sizes differ by at most one
            sizes = [hi - lo for lo, hi in ranges]
            assert max(sizes) - min(sizes) <= 1


def test_partition_empty_host_edge():
    # more processes than recordings: trailing hosts own nothing
    ranges = pod.partition(2, 5)
    sizes = [hi - lo for lo, hi in ranges]
    assert sizes == [1, 1, 0, 0, 0]
    with pytest.raises(ValueError, match=">= 1"):
        pod.partition(3, 0)


# ------------------------------------------------ bootstrap seam


def test_initialize_returns_resolved_single_process_noop():
    assert distributed.initialize() == (None, 1, 0)
    assert not distributed.is_initialized()


def test_shutdown_resets_the_latch():
    """The one-way latch, fixed: a (simulated) live bootstrap can be
    shut down and the process can initialize again — what a test
    harness or a restarted resident gateway needs."""
    assert not distributed.is_initialized()
    distributed._initialized = True
    distributed._resolution = ("127.0.0.1:1", 2, 0)
    try:
        # the latched resolution is what repeat initialize reports
        assert distributed.initialize() == ("127.0.0.1:1", 2, 0)
        distributed.shutdown()
        assert not distributed.is_initialized()
        # and the no-op path works again after the reset
        assert distributed.initialize() == (None, 1, 0)
    finally:
        distributed._initialized = False
        distributed._resolution = None


def test_preflight_unreachable_coordinator_raises_catchably():
    port = _free_port()
    with pytest.raises(distributed.PodBootstrapError, match="unreachable"):
        distributed._preflight_rendezvous(
            f"127.0.0.1:{port}", 2, 1, timeout_s=1.0
        )


def test_preflight_missing_peer_raises_catchably():
    port = _free_port()
    with pytest.raises(distributed.PodBootstrapError, match="peer"):
        distributed._preflight_rendezvous(
            f"127.0.0.1:{port - 1}", 2, 0, timeout_s=1.0
        )


# ------------------------------------------------ partitioned ingest parity


def _partitioned_rows(info, num_processes):
    """Simulate every host of an N-process pod sequentially in this
    process: the global metadata pass + each host's owned-block
    featurize, concatenated in process order."""
    parts = []
    plan = None
    for pid in range(num_processes):
        odp = provider.OfflineDataProvider([info])
        plan = pod.plan_pod_ingest(odp)
        local = pod.local_features(
            odp, plan, num_processes, pid,
            odp.planned_featurizer(backend="decode"),
            n_feat=48,
        )
        parts.append(local)
    return np.concatenate(parts), plan


def test_partitioned_ingest_bit_identical_to_single_process(info):
    f_ref, t_ref = provider.OfflineDataProvider(
        [info]
    ).load_features_device(backend="decode")
    for procs in (1, 2, 3):
        rows, plan = _partitioned_rows(info, procs)
        # bit-for-bit: the same per-recording program ran with the
        # same globally planned positions/mask, whoever owned the file
        assert np.array_equal(rows, f_ref), f"procs={procs}"
        assert np.array_equal(plan.targets, t_ref)


def test_partitioned_ingest_empty_host_contributes_zero_rows(info):
    # 5 processes over 3 recordings: hosts 3 and 4 own nothing
    rows, plan = _partitioned_rows(info, 5)
    f_ref, _ = provider.OfflineDataProvider(
        [info]
    ).load_features_device(backend="decode")
    assert np.array_equal(rows, f_ref)
    counts = plan.host_row_counts(5)
    assert counts[3] == counts[4] == 0
    assert sum(counts) == len(f_ref)


def test_pod_plan_balance_and_order_survive_partitioning(info):
    """The metadata pass IS the single-process plan: per-recording
    kept counts, targets, and the global row order all match the
    unpartitioned run (the balance scan ran over every recording's
    markers in load order, on every simulated host)."""
    odp = provider.OfflineDataProvider([info])
    plan = pod.plan_pod_ingest(odp)
    batch = provider.OfflineDataProvider([info]).load()
    assert int(sum(plan.row_counts())) == len(batch)
    assert np.array_equal(plan.targets, np.asarray(batch.targets))


def test_host_row_counts_match_partition(info):
    odp = provider.OfflineDataProvider([info])
    plan = pod.plan_pod_ingest(odp)
    per_rec = plan.row_counts()
    for procs in (1, 2, 4):
        counts = plan.host_row_counts(procs)
        assert sum(counts) == sum(per_rec)
        for (lo, hi), c in zip(pod.partition(len(per_rec), procs), counts):
            assert c == sum(per_rec[lo:hi])


# ------------------------------------------------ pipeline degradation


def _q(info, *parts):
    return "&".join([f"info_file={info}", _POP_QUERY, *parts])


def test_processes1_byte_identical_with_pod_block(info):
    baseline = builder.PipelineBuilder(_q(info)).execute()
    pb = builder.PipelineBuilder(_q(info, "processes=1"))
    got = pb.execute()
    assert str(got) == str(baseline)
    assert pb.mesh_resolved["pod"]["processes"] == 1
    assert pb.mesh_resolved["pod"]["rung"] == "single_host"
    assert pb.degradation_history == []


def test_unreachable_coordinator_degrades_to_single_host(info, monkeypatch):
    """The acceptance scenario, client side: the coordinator host
    never answers, the preflight times out within the bootstrap
    budget, and the plan completes on the single-host rung with the
    evidence in the mesh block — it does not fail, and it does not
    hit XLA's fatal-abort path."""
    monkeypatch.setenv(distributed.ENV_BOOTSTRAP_TIMEOUT, "1.5")
    baseline = builder.PipelineBuilder(_q(info)).execute()
    before = obs.metrics.snapshot()["counters"].get(
        "pipeline.pod_unavailable", 0.0
    )
    port = _free_port()
    pb = builder.PipelineBuilder(
        _q(
            info,
            f"processes=2&coordinator=127.0.0.1:{port}&process_id=1",
        )
    )
    got = pb.execute()
    after = obs.metrics.snapshot()["counters"].get(
        "pipeline.pod_unavailable", 0.0
    )
    assert str(got) == str(baseline)
    assert after == before + 1
    block = pb.mesh_resolved["pod"]
    assert block["processes"] == 2
    assert block["rung"] == "single_host"
    assert "unreachable" in block["error"]
    assert pb.mesh_resolved["rung"] == "single_device"
    assert pb.degradation_history[0]["from"] == "pod"


def test_missing_peer_degrades_coordinator_side(info, monkeypatch):
    """The acceptance scenario, coordinator side: process 0 is alive
    but its peer never arrives; the preflight barrier times out and
    the run degrades instead of aborting inside the coordination
    service."""
    monkeypatch.setenv(distributed.ENV_BOOTSTRAP_TIMEOUT, "1.5")
    baseline = builder.PipelineBuilder(_q(info)).execute()
    port = _free_port()
    pb = builder.PipelineBuilder(
        _q(
            info,
            f"processes=2&coordinator=127.0.0.1:{port}&process_id=0",
        )
    )
    got = pb.execute()
    assert str(got) == str(baseline)
    assert "peer" in pb.mesh_resolved["pod"]["error"]


def test_pod_degradation_falls_to_devices_mesh(info, monkeypatch):
    """The ladder's middle rung: pod fails, devices= still shards the
    run over the single-host mesh (pod -> single-host mesh), and both
    records land in one mesh block."""
    monkeypatch.setenv(distributed.ENV_BOOTSTRAP_TIMEOUT, "1.5")
    baseline = builder.PipelineBuilder(_q(info)).execute()
    port = _free_port()
    pb = builder.PipelineBuilder(
        _q(
            info,
            "devices=8",
            f"processes=2&coordinator=127.0.0.1:{port}&process_id=1",
        )
    )
    got = pb.execute()
    assert str(got) == str(baseline)
    assert pb.mesh_resolved["rung"] == "mesh"  # the single-host mesh
    assert pb.mesh_resolved["shape"] == {"data": 8}
    assert pb.mesh_resolved["pod"]["rung"] == "single_host"
    assert "error" in pb.mesh_resolved["pod"]


def test_pod_grammar_errors(info):
    for bad in (
        "processes=0",
        "process_id=1",  # without processes=
        "processes=2&process_id=2",
        "processes=2&coordinator=nocolon",
        "processes=2&coordinator=host:notaport",
        "processes=2&serve=true",
    ):
        with pytest.raises(ValueError):
            builder.PipelineBuilder(_q(info, bad)).execute()


def test_precision_refused_on_pod_runs(info):
    """Non-f32 precision rides a per-run f32-reference gate the
    partitioned ingest cannot stage; the conflict is loud, not a
    silently ungated rung."""
    from eeg_dataanalysispackage_tpu.parallel import pod as pod_mod

    pb = builder.PipelineBuilder(
        _q(info).replace("fe=dwt-8-fused", "fe=dwt-8-fused-decode")
        + "&precision=bf16"
    )
    fake = pod_mod.PodRuntime(mesh=None, num_processes=2, process_id=0)
    monkey_resolved = {"called": False}

    original = builder.PipelineBuilder._resolve_pod

    def fake_resolve(self, request):
        monkey_resolved["called"] = True
        return fake

    builder.PipelineBuilder._resolve_pod = fake_resolve
    try:
        with pytest.raises(ValueError, match="pod runs compute f32"):
            pb.execute()
    finally:
        builder.PipelineBuilder._resolve_pod = original
    assert monkey_resolved["called"]
