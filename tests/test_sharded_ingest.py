"""Sequence-parallel irregular-marker ingest (parallel/sharded_ingest):
time-sharded epoching with ring halo on the virtual 8-device mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from eeg_dataanalysispackage_tpu.io.brainvision import Marker
from eeg_dataanalysispackage_tpu.ops import device_ingest
from eeg_dataanalysispackage_tpu.parallel import (
    mesh as pmesh,
    sharded_ingest,
)


@pytest.fixture(scope="module")
def tmesh():
    if jax.device_count() < 8:
        pytest.skip("needs 8 virtual devices")
    return pmesh.make_mesh(8, axes=(pmesh.TIME_AXIS,))


def _markers(positions, stimuli):
    return [
        Marker(f"Mk{i}", "Stimulus", f"S  {s}", int(p))
        for i, (p, s) in enumerate(zip(positions, stimuli))
    ]


def _recording(T, seed=0):
    rng = np.random.RandomState(seed)
    dc = np.array([[1500], [-900], [400]], np.int16)
    raw = (rng.randint(-3000, 3000, size=(3, T)) + dc).astype(np.int16)
    res = np.array([0.1, 0.1, 0.2], np.float32)
    return raw, res


def test_sharded_ingest_matches_single_device(tmesh):
    """Features from the time-sharded extractor == the single-device
    block featurizer on the same kept markers, in the same order —
    including windows that straddle shard boundaries."""
    T = 8 * 4096
    raw, res = _recording(T)
    block = T // 8
    # markers everywhere, several right before shard boundaries so
    # their windows cross into the neighbor via the halo
    positions = [500, 3000, block - 50, block + 200, 2 * block - 10,
                 3 * block + 77, 5 * block - 100, 7 * block + 900,
                 6 * block + 123, 4 * block + 1]
    stimuli = [1, 2, 3, 4, 5, 6, 7, 8, 9, 1]
    markers = _markers(positions, stimuli)

    plan = sharded_ingest.plan_sharded_ingest(
        markers, guessed_number=4, n_samples=T, n_shards=8, block=block
    )
    extract = sharded_ingest.make_sharded_ingest(tmesh)
    staged = sharded_ingest.stage_recording_int16(raw, tmesh)
    got = extract(staged, res, plan)

    base = device_ingest.plan_ingest(markers, 4, T)
    feat = device_ingest.make_block_ingest_featurizer()
    want = np.asarray(
        feat(jnp.asarray(raw), jnp.asarray(res),
             jnp.asarray(base.positions), jnp.asarray(base.mask))
    )[base.mask]
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=0, atol=5e-6)
    np.testing.assert_array_equal(plan.targets, base.targets)


def test_sharded_ingest_backward_reach_and_exact_boundaries(tmesh):
    """Markers in [k*block, k*block + PRESTIMULUS) have windows that
    START in the previous shard (the backward pre-stimulus reach),
    and markers exactly on a boundary start PRESTIMULUS samples
    before it — both must match the single-device featurizer
    bit-for-bit with the ring halo in play."""
    T = 8 * 4096
    raw, res = _recording(T, seed=7)
    block = T // 8
    positions = [
        block,            # exactly on a boundary
        block + 10,       # window starts 90 samples into shard 0
        3 * block + 99,   # last backward-reaching offset (pre=100)
        5 * block + 100,  # first NON-reaching offset (window starts at 5*block)
        7 * block,        # boundary of the last shard
    ]
    stimuli = [1, 2, 3, 4, 5]
    markers = _markers(positions, stimuli)

    plan = sharded_ingest.plan_sharded_ingest(
        markers, guessed_number=4, n_samples=T, n_shards=8, block=block
    )
    extract = sharded_ingest.make_sharded_ingest(tmesh)
    staged = sharded_ingest.stage_recording_int16(raw, tmesh)
    got = extract(staged, res, plan)

    base = device_ingest.plan_ingest(markers, 4, T)
    feat = device_ingest.make_block_ingest_featurizer()
    want = np.asarray(
        feat(jnp.asarray(raw), jnp.asarray(res),
             jnp.asarray(base.positions), jnp.asarray(base.mask))
    )[base.mask]
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=0, atol=5e-6)


def test_sharded_ingest_end_overhang_zero_pads(tmesh):
    """A window overhanging the global recording end reads zeros
    (Java copyOfRange), NOT the ring-wrapped head of shard 0."""
    T = 8 * 4096
    raw, res = _recording(T, seed=3)
    block = T // 8
    positions = [1000, T - 200]  # second window overhangs the end
    markers = _markers(positions, [1, 2])
    plan = sharded_ingest.plan_sharded_ingest(
        markers, guessed_number=2, n_samples=T, n_shards=8, block=block
    )
    extract = sharded_ingest.make_sharded_ingest(tmesh)
    got = extract(sharded_ingest.stage_recording_int16(raw, tmesh), res, plan)

    base = device_ingest.plan_ingest(markers, 2, T)
    feat = device_ingest.make_block_ingest_featurizer()
    want = np.asarray(
        feat(jnp.asarray(raw), jnp.asarray(res),
             jnp.asarray(base.positions), jnp.asarray(base.mask))
    )[base.mask]
    np.testing.assert_allclose(got, want, rtol=0, atol=5e-6)


def test_sharded_ingest_balance_scan_matches_reference_semantics(tmesh):
    """The order-dependent class-balance scan runs globally on the
    host before sharding, so kept markers and targets are identical
    to the single-device plan."""
    T = 8 * 4096
    raw, res = _recording(T, seed=5)
    block = T // 8
    positions = list(range(500, T - 1000, 2500))
    stimuli = [(i % 9) + 1 for i in range(len(positions))]
    markers = _markers(positions, stimuli)
    plan = sharded_ingest.plan_sharded_ingest(
        markers, guessed_number=3, n_samples=T, n_shards=8, block=block
    )
    base = device_ingest.plan_ingest(markers, 3, T)
    np.testing.assert_array_equal(plan.targets, base.targets)
    np.testing.assert_array_equal(
        plan.stimulus_indices, base.stimulus_indices
    )


def test_sharded_ingest_rejects_bad_layouts(tmesh):
    T = 8 * 4096
    raw, res = _recording(T, seed=1)
    extract = sharded_ingest.make_sharded_ingest(tmesh)
    plan = sharded_ingest.plan_sharded_ingest(
        _markers([1000], [1]), 1, T, 8, T // 8
    )
    with pytest.raises(ValueError, match="divisible"):
        extract(jnp.asarray(raw[:, : T - 4]), res, plan)
    small = np.zeros((3, 8 * 512), np.int16)
    with pytest.raises(ValueError, match="halo"):
        extract(jnp.asarray(small), res, plan)
