"""Multi-device tests on the virtual 8-device CPU mesh.

The reference never tested beyond ``local[*]`` threads (SURVEY.md
section 4); these tests exercise real mesh sharding: data-parallel
SGD whose gradient reduction crosses shards, and the time-sharded
streaming extractor with its ppermute halo exchange.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.models import sgd
from eeg_dataanalysispackage_tpu.parallel import mesh as pmesh
from eeg_dataanalysispackage_tpu.parallel import streaming


@pytest.fixture(scope="module")
def mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    return pmesh.make_mesh(8)


def test_mesh_construction(mesh8):
    assert mesh8.shape == {"data": 8}


def test_mesh_construction_2d(mesh8):
    mesh2d = pmesh.make_mesh(
        8, axes=(pmesh.DATA_AXIS, pmesh.TIME_AXIS), shape=(2, 4)
    )
    assert mesh2d.shape == {"data": 2, "time": 4}
    assert mesh2d.axis_names == (pmesh.DATA_AXIS, pmesh.TIME_AXIS)


def test_mesh_rejects_too_many_devices():
    with pytest.raises(ValueError, match="only .* present"):
        pmesh.make_mesh(len(jax.devices()) + 1)


def test_mesh_rejects_shape_device_mismatch(mesh8):
    """A multi-axis shape whose product != the device count is a
    clear error naming the arithmetic, not a bare reshape
    ValueError."""
    with pytest.raises(ValueError, match="multiply to the device count"):
        pmesh.make_mesh(
            8, axes=(pmesh.DATA_AXIS, pmesh.TIME_AXIS), shape=(3, 2)
        )
    with pytest.raises(ValueError, match="one extent per axis"):
        pmesh.make_mesh(
            8, axes=(pmesh.DATA_AXIS, pmesh.TIME_AXIS), shape=(8,)
        )
    with pytest.raises(ValueError, match="shape required"):
        pmesh.make_mesh(8, axes=(pmesh.DATA_AXIS, pmesh.TIME_AXIS))


def test_pad_to_multiple():
    x = np.ones((11, 3))
    padded, n = pmesh.pad_to_multiple(x, 8)
    assert padded.shape == (16, 3)
    assert n == 11
    same, n2 = pmesh.pad_to_multiple(np.ones((16, 3)), 8)
    assert same.shape == (16, 3) and n2 == 16


def test_data_parallel_sgd_matches_single_device(mesh8):
    rng = np.random.RandomState(0)
    x = rng.randn(203, 16).astype(np.float32)  # deliberately not /8
    y = (x @ rng.randn(16) > 0).astype(np.float32)
    cfg = sgd.SGDConfig(num_iterations=40)
    w_single = sgd.train_linear(x, y, cfg)
    w_dist = sgd.train_linear(x, y, cfg, mesh=mesh8)
    np.testing.assert_allclose(w_dist, w_single, rtol=0, atol=2e-5)
    acc = ((x @ w_dist >= 0) == y).mean()
    assert acc > 0.9


def test_data_parallel_sgd_minibatch_path(mesh8):
    rng = np.random.RandomState(1)
    x = rng.randn(64, 8).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.float32)
    cfg = sgd.SGDConfig(num_iterations=30, mini_batch_fraction=0.5)
    w = sgd.train_linear(x, y, cfg, mesh=mesh8)
    assert ((x @ w >= 0) == y).mean() > 0.85


def test_streaming_extractor_matches_single_device(mesh8):
    tmesh = pmesh.make_mesh(8, axes=(pmesh.TIME_AXIS,))
    C, T = 3, 8 * 1024
    rng = np.random.RandomState(2)
    signal = rng.randn(C, T).astype(np.float32)

    extract = streaming.make_streaming_extractor(tmesh, window=512, stride=256)
    staged = streaming.stage_recording(signal, tmesh)
    feats = np.asarray(extract(staged))
    assert feats.shape == (T // 256, 3 * 16)

    # single-device reference: same windows, wrapping at the end
    mesh1 = pmesh.make_mesh(1, axes=(pmesh.TIME_AXIS,))
    extract1 = streaming.make_streaming_extractor(mesh1, window=512, stride=256)
    feats1 = np.asarray(extract1(streaming.stage_recording(signal, mesh1)))
    np.testing.assert_allclose(feats, feats1, rtol=0, atol=2e-5)


def test_streaming_halo_windows_cross_shard_boundaries(mesh8):
    """A window starting near the end of shard i must read shard i+1's
    head through the halo exchange — check against a host computation."""
    tmesh = pmesh.make_mesh(8, axes=(pmesh.TIME_AXIS,))
    C, T = 2, 8 * 512
    rng = np.random.RandomState(3)
    signal = rng.randn(C, T).astype(np.float32)
    extract = streaming.make_streaming_extractor(
        tmesh, window=512, stride=256, band=(0.0, 500.0)
    )
    feats = np.asarray(extract(streaming.stage_recording(signal, tmesh)))

    # host check for a boundary-straddling window: start = 512-256=256
    # within block 0 extends into block 1 (blocks are 512 long)
    from eeg_dataanalysispackage_tpu.ops import dwt_host

    win = signal[:, 256 : 256 + 512].astype(np.float64)
    # band (0,500) keeps all rfft bins: bandpass is identity up to f32
    coeffs = dwt_host.dwt_coefficients(win, 8, 16).reshape(-1)
    expected = coeffs / np.sqrt((coeffs**2).sum())
    np.testing.assert_allclose(feats[1], expected, rtol=0, atol=2e-4)


def test_streaming_rejects_bad_block_layout(mesh8):
    """Block length not divisible by stride must raise loudly — JAX's
    clamped out-of-bounds gather would otherwise return silently wrong
    windows (code-review finding)."""
    tmesh = pmesh.make_mesh(8, axes=(pmesh.TIME_AXIS,))
    extract = streaming.make_streaming_extractor(tmesh, window=512, stride=256)
    signal = np.random.RandomState(0).randn(2, 8 * 600).astype(np.float32)
    staged = streaming.stage_recording(signal, tmesh)
    with pytest.raises(ValueError, match="not a multiple of"):
        extract(staged)
    with pytest.raises(ValueError, match="not divisible by"):
        # unstaged on purpose: the length check fires before sharding
        extract(jnp.asarray(signal[:, : 8 * 600 - 3]))


def test_streaming_extractor_int16_staging_matches_f32(mesh8):
    """int16-staged recording + on-device resolutions == pre-scaled
    f32 staging, through the mesh extractor's halo ring."""
    import jax.numpy as jnp

    rng = np.random.RandomState(2)
    raw = (rng.randn(3, 4096) * 500).astype(np.int16)
    res = np.array([0.1, 0.1, 0.2], np.float32)
    tmesh = pmesh.make_mesh(8, axes=(pmesh.TIME_AXIS,))

    ext16 = streaming.make_streaming_extractor(
        tmesh, window=512, stride=256, resolutions=res
    )
    f16 = np.asarray(
        ext16(streaming.stage_recording(raw, tmesh, dtype=jnp.int16))
    )

    extf = streaming.make_streaming_extractor(tmesh, window=512, stride=256)
    scaled = raw.astype(np.float32) * res[:, None]
    ff = np.asarray(extf(streaming.stage_recording(scaled, tmesh)))
    np.testing.assert_allclose(f16, ff, rtol=0, atol=2e-5)


def test_raw_train_step_matches_feature_step_composition():
    """make_raw_train_step == fused ingest + make_feature_train_step:
    identical state updates and losses, and the loss moves."""
    import jax
    import jax.numpy as jnp
    from eeg_dataanalysispackage_tpu.ops import device_ingest
    from eeg_dataanalysispackage_tpu.parallel import train as ptrain

    rng = np.random.RandomState(0)
    n, stride, first = 32, 800, 150
    S = 200 + n * stride + 8192
    raw = rng.randint(-3000, 3000, size=(3, S)).astype(np.int16)
    res = np.array([0.1, 0.1, 0.2], np.float32)
    labels = jnp.asarray(rng.randint(0, 2, size=n).astype(np.float32))
    mask = jnp.ones((n,), jnp.float32)

    init_raw, raw_step = ptrain.make_raw_train_step(stride, n)
    state = init_raw(jax.random.PRNGKey(0))
    losses = []
    for _ in range(5):
        state, loss = raw_step(
            state, jnp.asarray(raw), jnp.asarray(res), labels, mask, first
        )
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]

    ing = device_ingest.make_regular_ingest_featurizer(stride, n)
    feats = ing(jnp.asarray(raw), jnp.asarray(res), first)
    init_f, feat_step = ptrain.make_feature_train_step()
    state_f = init_f(jax.random.PRNGKey(0))
    for i in range(5):
        state_f, loss_f = feat_step(state_f, feats, labels, mask)
        np.testing.assert_allclose(float(loss_f), losses[i], rtol=1e-6)


def test_windowed_pipeline_aligned_slab_matches_gather():
    """The tile-aligned slab decomposition (stride % 128 == 0) must
    agree with the index-gather formulation — same windows, same
    kernel, different contraction grouping."""
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    window, stride = 512, 256
    kernel = jnp.asarray(
        streaming.filtered_cascade_kernel(
            window, 8, 16, 1000.0, (0.5, 40.0)
        ),
        dtype=jnp.float32,
    )
    ext = jnp.asarray(
        rng.randn(3, 2048 + window - stride).astype(np.float32) * 40
    )
    fast = np.asarray(
        streaming._windowed_pipeline(ext, window, stride, kernel)
    )
    # oracle: hand-rolled numpy re-windowing of the same geometry
    # (independent of both in-module formulations)
    starts = np.arange(0, 2048, stride)
    idx = starts[:, None] + np.arange(window)[None, :]
    wins = np.asarray(ext)[:, idx]
    flat = wins.transpose(1, 0, 2).reshape(len(starts) * 3, window)
    coeffs = flat @ np.asarray(kernel)
    want = coeffs.reshape(len(starts), 3 * 16)
    want /= np.maximum(
        np.linalg.norm(want, axis=1, keepdims=True), 1e-30
    )
    np.testing.assert_allclose(fast, want, rtol=0, atol=2e-5)


def test_streaming_rejects_bad_stride():
    with pytest.raises(ValueError, match="stride"):
        streaming.make_streaming_extractor(
            pmesh.make_mesh(1, axes=(pmesh.TIME_AXIS,)), window=256, stride=512
        )


class TestBlockedStreaming:
    """Single-device bounded-memory streaming (iter_blocked_features)."""

    def _signal(self, C=3, T=4096 + 128, seed=11):
        return (
            np.random.RandomState(seed).randn(C, T).astype(np.float32) * 25.0
        )

    def test_block_size_invariance(self):
        sig = self._signal()
        whole = streaming.blocked_features(sig, block=8192)
        small = streaming.blocked_features(sig, block=1024)
        tiny = streaming.blocked_features(sig, block=256)
        n_expected = (sig.shape[1] - 512) // 256 + 1
        assert whole.shape == (n_expected, 48)
        np.testing.assert_allclose(small, whole, rtol=0, atol=1e-6)
        np.testing.assert_allclose(tiny, whole, rtol=0, atol=1e-6)

    def test_first_window_matches_direct_math(self):
        from eeg_dataanalysispackage_tpu.ops import dwt as dwt_xla
        from eeg_dataanalysispackage_tpu.ops.signal import bandpass_mask

        sig = self._signal(C=2)
        got = streaming.blocked_features(sig, block=1024)[0]

        win = sig[:, :512]
        mask = np.asarray(bandpass_mask(512, 1000.0, 0.5, 40.0))
        spec = np.fft.rfft(win, axis=-1)
        filt = np.fft.irfft(spec * mask, n=512, axis=-1).astype(np.float32)
        coeffs = np.asarray(
            dwt_xla.windowed_features(jnp.asarray(filt), 8, 16)
        ).reshape(-1)
        want = coeffs / np.linalg.norm(coeffs)
        np.testing.assert_allclose(got, want, rtol=0, atol=2e-5)

    def test_short_and_invalid_inputs(self):
        assert streaming.blocked_features(
            np.zeros((2, 100), np.float32)
        ).shape == (0, 32)
        with pytest.raises(ValueError, match="multiple of stride"):
            list(
                streaming.iter_blocked_features(
                    np.zeros((1, 2048), np.float32), block=1000
                )
            )

    def test_int16_source_ships_raw_and_scales_on_device(self):
        rng = np.random.RandomState(2)
        raw = rng.randint(-3000, 3000, size=(3, 2048 + 64)).astype(np.int16)
        res = np.array([0.1, 0.5, 1.0], dtype=np.float32)
        got = streaming.blocked_features(raw, block=1024, resolutions=res)
        want = streaming.blocked_features(
            raw.astype(np.float32) * res[:, None], block=1024
        )
        np.testing.assert_allclose(got, want, rtol=0, atol=2e-6)


class TestIrregularTrainStep:
    """make_irregular_train_step: training straight from the int16
    stream with irregular markers (block-gather fused ingest)."""

    def _case(self, n=70):
        rng = np.random.RandomState(5)
        S = 80_000
        raw = rng.randint(-3000, 3000, size=(3, S)).astype(np.int16)
        res = np.array([0.1, 0.1, 0.2], np.float32)
        positions = np.sort(
            rng.choice(np.arange(200, S - 900), size=n, replace=False)
        )
        cap = ((n + 63) // 64) * 64
        pos_pad = np.zeros(cap, np.int32)
        pos_pad[:n] = positions
        mask = np.zeros(cap, bool)
        mask[:n] = True
        labels = np.pad(
            rng.randint(0, 2, size=n).astype(np.float32), (0, cap - n)
        )
        return raw, res, pos_pad, mask, labels

    def test_matches_precomputed_feature_step(self):
        from eeg_dataanalysispackage_tpu.ops import device_ingest
        from eeg_dataanalysispackage_tpu.parallel import train as ptrain

        raw, res, pos, mask, labels = self._case()
        init_state, step = ptrain.make_irregular_train_step()
        state = init_state(jax.random.PRNGKey(0))
        state2, loss = step(
            state, jnp.asarray(raw), jnp.asarray(res),
            jnp.asarray(pos), jnp.asarray(mask), jnp.asarray(labels),
        )
        assert np.isfinite(float(loss))

        # the same update from precomputed block-ingest features
        feats = device_ingest.make_block_ingest_featurizer()(
            jnp.asarray(raw), jnp.asarray(res),
            jnp.asarray(pos), jnp.asarray(mask),
        )
        init2, feat_step = ptrain.make_feature_train_step()
        ref_state = init2(jax.random.PRNGKey(0))
        ref_state2, ref_loss = feat_step(
            ref_state, feats, jnp.asarray(labels),
            jnp.asarray(mask, jnp.float32),
        )
        np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
        for k in state2["params"]:
            np.testing.assert_allclose(
                np.asarray(state2["params"][k]),
                np.asarray(ref_state2["params"][k]),
                rtol=0, atol=1e-6,
            )

    def test_bank_step_matches_block_step(self):
        """make_irregular_bank_train_step (bank128 Pallas featurizer,
        positions concrete at build) must produce the same update as
        the block-gather step to the feature-parity envelope."""
        from eeg_dataanalysispackage_tpu.parallel import train as ptrain

        raw, res, pos, mask, labels = self._case()
        n = int(mask.sum())
        positions = np.asarray(pos)[:n]

        init_b, step_b = ptrain.make_irregular_train_step()
        state_b = init_b(jax.random.PRNGKey(0))
        _, loss_block = step_b(
            state_b, jnp.asarray(raw), jnp.asarray(res),
            jnp.asarray(pos), jnp.asarray(mask), jnp.asarray(labels),
        )

        init_k, step_k = ptrain.make_irregular_bank_train_step(
            positions
        )
        state_k = init_k(jax.random.PRNGKey(0))
        state_k2, loss_bank = step_k(
            state_k, jnp.asarray(raw), jnp.asarray(res),
            jnp.asarray(labels[:n]),
        )
        # both paths are 5e-5-class vs the gather reference, so their
        # one-step losses agree to ~1e-4
        np.testing.assert_allclose(
            float(loss_bank), float(loss_block), rtol=0, atol=1e-4
        )
        assert np.isfinite(float(loss_bank))
        for k in state_k2["params"]:
            assert np.all(np.isfinite(np.asarray(state_k2["params"][k])))

    def test_compact_train_step_matches_full_width(self):
        """make_compact_train_step over the host-sliced (B, C, 512)
        window must produce the same one-step loss as make_train_step
        over the full (B, C, 1000) layout (identical contraction, the
        488 dead columns removed) — the honest-bytes training twin."""
        from eeg_dataanalysispackage_tpu.parallel import train as ptrain

        rng = np.random.RandomState(3)
        n = 32
        epochs = rng.randn(n, 3, 1000).astype(np.float32) * 40.0
        labels = rng.randint(0, 2, size=n).astype(np.float32)
        mask = np.ones(n, np.float32)

        init_f, step_f = ptrain.make_train_step()
        state = init_f(jax.random.PRNGKey(0))
        _, loss_full = step_f(
            state, jnp.asarray(epochs), jnp.asarray(labels),
            jnp.asarray(mask),
        )

        skip = 175
        sliced = np.ascontiguousarray(epochs[:, :, skip : skip + 512])
        init_c, step_c = ptrain.make_compact_train_step()
        state_c = init_c(jax.random.PRNGKey(0))
        _, loss_compact = step_c(
            state_c, jnp.asarray(sliced), jnp.asarray(labels),
            jnp.asarray(mask),
        )
        np.testing.assert_allclose(
            float(loss_compact), float(loss_full), rtol=0, atol=1e-6
        )
        # wrong window width fails loudly at trace time
        with pytest.raises(ValueError, match="epoch_size"):
            step_c(
                state_c, jnp.asarray(epochs), jnp.asarray(labels),
                jnp.asarray(mask),
            )

    def test_bank_step_nondefault_feature_size_sizes_the_mlp(self):
        """A non-default feature_size must size the MLP input to
        C*feature_size (review finding: the geometry knob crashed at
        the first step against the fixed 48-input network)."""
        from eeg_dataanalysispackage_tpu.parallel import train as ptrain

        raw, res, pos, mask, labels = self._case()
        n = int(mask.sum())
        positions = np.asarray(pos)[:n]
        init_k, step_k = ptrain.make_irregular_bank_train_step(
            positions, feature_size=8
        )
        state = init_k(jax.random.PRNGKey(0))
        assert state["params"]["w0"].shape[0] == 3 * 8
        _, loss = step_k(
            state, jnp.asarray(raw), jnp.asarray(res),
            jnp.asarray(labels[:n]),
        )
        assert np.isfinite(float(loss))

    def test_masked_rows_do_not_affect_the_update(self):
        from eeg_dataanalysispackage_tpu.parallel import train as ptrain

        raw, res, pos, mask, labels = self._case()
        # the A/B comparison feeds the SAME state to two independent
        # steps — the documented donate_state=False case (the default
        # donates the state's buffers to the update)
        init_state, step = ptrain.make_irregular_train_step(
            donate_state=False
        )
        state = init_state(jax.random.PRNGKey(1))
        _, loss_a = step(
            state, jnp.asarray(raw), jnp.asarray(res),
            jnp.asarray(pos), jnp.asarray(mask), jnp.asarray(labels),
        )
        # flip the labels of masked-out rows: nothing may change
        labels_b = labels.copy()
        labels_b[~mask] = 1.0 - labels_b[~mask]
        _, loss_b = step(
            state, jnp.asarray(raw), jnp.asarray(res),
            jnp.asarray(pos), jnp.asarray(mask), jnp.asarray(labels_b),
        )
        assert float(loss_a) == float(loss_b)

    def test_on_mesh(self):
        from eeg_dataanalysispackage_tpu.parallel import (
            mesh as pmesh,
            train as ptrain,
        )

        raw, res, pos, mask, labels = self._case()
        mesh = pmesh.make_mesh(8, axes=(pmesh.DATA_AXIS,))
        init_state, step = ptrain.make_irregular_train_step(mesh)
        state = init_state(jax.random.PRNGKey(0))
        _, loss = step(
            state, jnp.asarray(raw), jnp.asarray(res),
            jnp.asarray(pos), jnp.asarray(mask), jnp.asarray(labels),
        )
        assert np.isfinite(float(loss))
