"""Bit-parity tests for BrainVision ingest + epoching.

Golden values come from the reference's integration tests
(OfflineDataProviderTest.java:65-129), converted here into hermetic
local-filesystem tests (no HDFS daemon). The summation replays Java's
exact left-to-right double folds, so equality is checked bitwise.
"""

import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.io import brainvision, provider, sources


def java_epoch_sum(epochs: np.ndarray) -> float:
    """Sequential rowSum-then-total fold (OfflineDataProviderTest.java:70-80)."""
    row_sums = np.cumsum(epochs, axis=-1)[..., -1]  # sequential per row
    return float(np.cumsum(row_sums.reshape(-1))[-1])


def test_info_txt_fixture(fixture_dir):
    odp = provider.OfflineDataProvider([fixture_dir + "/infoTrain.txt"])
    batch = odp.load()
    assert batch.epochs.shape == (11, 3, 750)
    assert java_epoch_sum(batch.epochs) == -253772.18676757812
    assert int(batch.targets.sum()) == 5


def test_info_txt_fixture_parallel_pool(fixture_dir):
    """The parallel parse pool must not move a single bit: the pinned
    golden epoch sum survives any worker count (order-preserving
    merge, io/provider._iter_recordings)."""
    for workers in (2, 4):
        odp = provider.OfflineDataProvider(
            [fixture_dir + "/infoTrain.txt"], workers=workers
        )
        batch = odp.load()
        assert batch.epochs.shape == (11, 3, 750)
        assert java_epoch_sum(batch.epochs) == -253772.18676757812
        assert int(batch.targets.sum()) == 5


def test_single_eeg_with_guess(fixture_dir):
    odp = provider.OfflineDataProvider(
        [fixture_dir + "/DoD/DoD_2015_02.eeg", "4"]
    )
    batch = odp.load()
    assert batch.epochs.shape == (27, 3, 750)
    assert int(batch.targets.sum()) == 13


def test_pz_rows_match_reference_epochs_csv(fixture_dir):
    """The reference repo root carries an Epochs.csv dump of channel Pz
    of every fixture epoch (DataProviderUtils.writeEpochsToCSV,
    DataProviderUtils.java:30-47). Our Pz rows must match exactly."""
    import os

    csv_path = "/root/reference/Epochs.csv"
    if not os.path.exists(csv_path):
        pytest.skip("Epochs.csv artifact not present")
    with open(csv_path) as f:
        ref = np.array(
            [[float(x) for x in line.strip().rstrip(",").split(",")] for line in f]
        )
    odp = provider.OfflineDataProvider([fixture_dir + "/infoTrain.txt"])
    batch = odp.load()
    assert ref.shape == (11, 750)
    np.testing.assert_array_equal(batch.epochs[:, 2, :], ref)


def test_duplicate_info_lines_collapse(fixture_dir):
    """infoTrain.txt lists the same file twice; LinkedHashMap semantics
    collapse it to one load (OffLineDataProvider.java:53)."""
    text = sources.LocalFileSystem().read_text(fixture_dir + "/infoTrain.txt")
    files = sources.parse_info_txt(text)
    assert len(files) == 1


def test_info_txt_comments_and_blanks():
    files = sources.parse_info_txt(
        "# comment\n\nA/a.eeg 3 1\n\nB/b.eeg 5\nA/a.eeg 7\nsolo_field_line\n"
    )
    assert list(files.items()) == [("A/a.eeg", 7), ("B/b.eeg", 5)]


def test_info_txt_bad_number_raises():
    with pytest.raises(ValueError):
        sources.parse_info_txt("A/a.eeg x\n")


def test_missing_sibling_files_are_skipped(fixture_dir, tmp_path):
    """DoD/info.txt lists 9 recordings of which only DoD_2015_02.eeg has
    its full triplet present; the others must be skipped non-fatally
    (OffLineDataProvider.java:154-161)."""
    odp = provider.OfflineDataProvider([fixture_dir + "/DoD/info.txt"])
    batch = odp.load()
    assert len(batch) > 0


def test_out_of_range_markers_skipped(fixture_dir):
    """Mk1 'New Segment' sits at position 1; its window [−99, 751) is
    out of range and must be dropped (OffLineDataProvider.java:262-264)."""
    rec = brainvision.load_recording(fixture_dir + "/DoD/DoD2015_01.vhdr"[:-5] + ".eeg")
    positions = [m.position for m in rec.markers]
    assert positions[0] == 1
    from eeg_dataanalysispackage_tpu.epochs import extractor

    channels = rec.read_channels([0, 1, 2])
    _, valid = extractor.gather_windows(channels, np.array(positions))
    assert not valid[0]
    assert valid[1:].all()


def test_vhdr_parse(fixture_dir):
    hdr = brainvision.parse_vhdr(
        sources.LocalFileSystem().read_text(fixture_dir + "/DoD/DoD2015_01.vhdr")
    )
    assert hdr.num_channels == 3
    assert hdr.binary_format == "INT_16"
    assert [c.name for c in hdr.channels] == ["Fz", "Cz", "Pz"]
    assert hdr.channels[0].resolution == 0.1
    assert hdr.sampling_rate_hz == 1000.0


def test_vmrk_parse(fixture_dir):
    markers = brainvision.parse_vmrk(
        sources.LocalFileSystem().read_text(fixture_dir + "/DoD/DoD2015_01.vmrk")
    )
    assert markers[0].kind == "New Segment"
    assert markers[1].stimulus == "S  2"
    assert markers[1].position == 12016
    assert markers[1].stimulus_index() == 1
    assert markers[0].stimulus_index() == -1
