"""ExecutionPlan IR suite (pipeline/plan.py).

The parse/validate half of the ISSUE-10 split: every query knob
becomes a typed field, every statically decidable conflict raises the
EXACT legacy builder message (PlanValidationError is a ValueError, so
callers and pinned tests cannot tell the paths apart), and parsing is
pure — no I/O, no env reads, equal plans from equal queries.
"""

import pytest

from eeg_dataanalysispackage_tpu.pipeline.plan import (
    ExecutionPlan,
    PlanValidationError,
)


def test_typed_fields_round_trip():
    q = (
        "info_file=/data/info.txt&fe=dwt-8-fused-decode&precision=bf16"
        "&overlap=true&train_clf=logreg&cache=false&degrade=false"
        "&config_step_size=1.0&config_num_iterations=40"
        "&ingest_workers=3&prefetch=2&result_path=/tmp/r.txt"
        "&faults=remote.request:p=0.2&faults_seed=7&devices=4"
    )
    plan = ExecutionPlan.parse(q)
    assert plan.query == q
    assert plan.input_files == ("/data/info.txt",)
    assert plan.task == "p300" and not plan.serve
    assert plan.fused and plan.fused_wavelet == 8
    assert plan.fused_backend == "decode"
    assert plan.precision == "bf16"
    assert plan.overlap is True
    assert not plan.cache and not plan.degrade
    assert plan.train_clf == "logreg" and plan.load_clf is None
    assert plan.config == {
        "config_step_size": "1.0", "config_num_iterations": "40",
    }
    assert plan.ingest_workers == 3 and plan.prefetch == 2
    assert plan.result_path == "/tmp/r.txt"
    assert plan.faults == "remote.request:p=0.2"
    assert plan.faults_seed == 7
    assert plan.mesh is not None and plan.mesh.devices == 4
    assert plan.mesh.axes == ("data",) and plan.mesh.shape is None
    assert not plan.population_active


def test_seizure_fields_and_population():
    q = (
        "info_file=i.txt&task=seizure&fe=dwt-4:level=4:stats=energy"
        "&window=512&stride=256&label_overlap=0.4&train_clf=logreg"
        "&cost_fp=1&cost_fn=8&class_weight=balanced"
        "&sweep=cost_fn:1,8"
    )
    plan = ExecutionPlan.parse(q)
    assert plan.task == "seizure"
    assert plan.window == 512 and plan.stride == 256
    assert plan.label_overlap == 0.4
    assert (plan.cost_fp, plan.cost_fn) == (1.0, 8.0)
    assert plan.class_weight == "balanced"
    assert plan.population_active
    assert plan.population.sweep


def test_parse_is_pure_and_deterministic():
    q = "info_file=i.txt&fe=dwt-8&train_clf=logreg&cv=4"
    a, b = ExecutionPlan.parse(q), ExecutionPlan.parse(q)
    # frozen value semantics: equal queries -> equal plans (what lets
    # the journal replay a plan by re-parsing its recorded query)
    assert a.query_map == b.query_map
    assert a.input_files == b.input_files
    assert a.population == b.population
    assert a.mesh == b.mesh


def test_validation_error_is_value_error():
    with pytest.raises(ValueError):
        ExecutionPlan.parse("fe=dwt-8&train_clf=logreg")
    assert issubclass(PlanValidationError, ValueError)


@pytest.mark.parametrize(
    "query, match",
    [
        ("fe=dwt-8&train_clf=logreg", "Missing the input file argument"),
        ("info_file=i.txt&task=ecg&fe=dwt-8&train_clf=logreg",
         "unknown task"),
        ("info_file=i.txt&fe=dwt-8", "Missing classifier argument"),
        ("info_file=i.txt&train_clf=logreg",
         "Missing the feature extraction"),
        ("info_file=i.txt&fe=dwt-8&load_clf=svm",
         "location not provided"),
        ("info_file=i.txt&fe=dwt-8&train_clf=logreg&save_clf=true",
         "save_name"),
        ("info_file=i.txt&fe=dwt-8&train_clf=logreg&elastic=true",
         "checkpoint_path"),
        ("info_file=i.txt&fe=dwt-8&classifiers=logreg&train_clf=svm",
         "pass exactly one of them"),
        ("info_file=i.txt&fe=dwt-8&classifiers=logreg&elastic=true",
         "does not support elastic"),
        ("info_file=i.txt&fe=dwt-8&classifiers=,",
         "comma-separated"),
        ("info_file=i.txt&fe=dwt-8&cv=4&load_clf=svm&load_name=m",
         "cannot combine with load_clf"),
        ("info_file=i.txt&fe=dwt-8&cv=4&train_clf=dt",
         "SGD family"),
        ("info_file=i.txt&fe=dwt-8&train_clf=logreg&cv=0",
         "cv= must be >= 1"),
        ("info_file=i.txt&fe=dwt-8&train_clf=logreg&precision=bf16",
         "applies to the fused fe= modes"),
        ("info_file=i.txt&fe=dwt-8-fused-block&train_clf=logreg"
         "&precision=bf16", "rides the decode rung"),
        ("info_file=i.txt&fe=dwt-8-fused&train_clf=logreg"
         "&overlap=maybe", "overlap= must be true or false"),
        ("info_file=i.txt&fe=dwt-8&train_clf=logreg&devices=zero",
         "must be an integer"),
        ("info_file=i.txt&fe=dwt-8&train_clf=logreg&devices=0",
         "devices= must be >= 1"),
        ("info_file=i.txt&fe=dwt-8&train_clf=logreg"
         "&mesh_axes=data,data", "repeats an axis name"),
        ("info_file=i.txt&fe=dwt-8&train_clf=logreg&devices=4"
         "&mesh_axes=data:2,time:4", "drop one or make them agree"),
        ("info_file=i.txt&fe=dwt-8&train_clf=logreg&devices=2"
         "&serve=true", "cannot combine with serve=true"),
        ("info_file=i.txt&fe=dwt-8&train_clf=logreg"
         "&fe_sweep=dwt-4|dwt-8", "requires task=seizure"),
        ("info_file=i.txt&task=seizure&fe=dwt-8-fused&train_clf=logreg",
         "not a -fused mode"),
        ("info_file=i.txt&task=seizure&fe=dwt-4&train_clf=logreg"
         "&cost_fn=-1", "must be > 0"),
        ("info_file=i.txt&task=seizure&fe=dwt-4&train_clf=logreg"
         "&class_weight=heavy", "'balanced' or a float"),
        ("info_file=i.txt&fe=dwt-8&train_clf=logreg"
         "&faults=remote.request:maybe", "bad directive"),
    ],
)
def test_legacy_conflict_messages(query, match):
    """Every statically decidable conflict raises from parse with the
    monolithic builder's message, so pinned error-matching tests (and
    operators' muscle memory) survive the split."""
    with pytest.raises(ValueError, match=match):
        ExecutionPlan.parse(query)


def test_serve_mode_skips_batch_only_validation():
    """The monolith routed serve=true before the batch-side checks:
    population axes and missing classifier args are serving-layer
    concerns there, not parse errors."""
    plan = ExecutionPlan.parse(
        "info_file=i.txt&serve=true&fe=dwt-8&load_clf=logreg"
        "&load_name=m&cv=4"
    )
    assert plan.serve
    assert plan.population is None  # never parsed, like the monolith


def test_mesh_grammar_extents():
    plan = ExecutionPlan.parse(
        "info_file=i.txt&fe=dwt-8&train_clf=logreg"
        "&mesh_axes=data:2,time:4"
    )
    assert plan.mesh.axes == ("data", "time")
    assert plan.mesh.shape == (2, 4)
    assert plan.mesh.devices is None


def test_non_batch_routes_ignore_overlap_precision_values():
    """The monolith's overlap=/precision= value checks lived on the
    p300 batch branch only — seizure and serve queries with stray
    values ran (the knobs ignored), and must keep parsing."""
    plan = ExecutionPlan.parse(
        "info_file=i.txt&task=seizure&fe=dwt-4&train_clf=logreg"
        "&overlap=junk&precision=fp8"
    )
    assert plan.task == "seizure"
    assert plan.overlap is None
    serve_plan = ExecutionPlan.parse(
        "info_file=i.txt&serve=true&fe=dwt-8-fused&load_clf=logreg"
        "&load_name=m&overlap=junk&precision=fp8"
    )
    assert serve_plan.serve


# ------------------------------------------------ pod (multi-process)


def test_pod_typed_fields_round_trip():
    plan = ExecutionPlan.parse(
        "info_file=i.txt&fe=dwt-8&train_clf=logreg"
        "&processes=4&coordinator=10.0.0.1:1234&process_id=2"
    )
    assert plan.pod is not None
    assert plan.pod.processes == 4
    assert plan.pod.coordinator == "10.0.0.1:1234"
    assert plan.pod.process_id == 2
    # absent entirely -> None, the byte-identical default path
    assert ExecutionPlan.parse(
        "info_file=i.txt&fe=dwt-8&train_clf=logreg"
    ).pod is None
    # partial: processes alone parses (coordinator/process_id resolve
    # from the env twins at execution — parse purity)
    partial = ExecutionPlan.parse(
        "info_file=i.txt&fe=dwt-8&train_clf=logreg&processes=2"
    )
    assert partial.pod.processes == 2
    assert partial.pod.coordinator is None
    assert partial.pod.process_id is None


def test_pod_canonical_key_covers_the_family():
    base = ExecutionPlan.parse(
        "info_file=i.txt&fe=dwt-8&train_clf=logreg"
    )
    podded = ExecutionPlan.parse(
        "info_file=i.txt&fe=dwt-8&train_clf=logreg"
        "&processes=2&coordinator=c:1&process_id=0"
    )
    assert base.canonical_key() != podded.canonical_key()
    reordered = ExecutionPlan.parse(
        "processes=2&coordinator=c:1&process_id=0"
        "&train_clf=logreg&fe=dwt-8&info_file=i.txt"
    )
    assert reordered.canonical_key() == podded.canonical_key()


@pytest.mark.parametrize(
    "knobs,match",
    [
        ("processes=0", "processes= must be >= 1"),
        ("processes=x", "must be an integer"),
        ("process_id=1", "identifies this process within"),
        ("processes=2&process_id=2", "must be < processes"),
        ("processes=2&process_id=-1", "process_id= must be >= 0"),
        ("processes=2&coordinator=nocolon", "must be host:port"),
        ("processes=2&coordinator=h:xyz", "port must be an integer"),
        ("processes=2&coordinator=h:99999", "port must be in"),
    ],
)
def test_pod_grammar_errors(knobs, match):
    with pytest.raises(PlanValidationError, match=match):
        ExecutionPlan.parse(
            f"info_file=i.txt&fe=dwt-8&train_clf=logreg&{knobs}"
        )


def test_pod_conflicts_with_serve():
    """processes= with serve=true is a loud error — the resident
    serving engine is single-process; silently ignoring the pod
    family would be worse."""
    with pytest.raises(
        PlanValidationError, match="cannot combine with serve=true"
    ):
        ExecutionPlan.parse(
            "info_file=i.txt&serve=true&fe=dwt-8-fused&load_clf=logreg"
            "&load_name=m&processes=2"
        )


def test_pod_conflicts_with_seizure_and_precision():
    """Statically decidable pod conflicts: the seizure workload has
    no partitioned pod path (every process would redo the full
    ingest under a rung that claims otherwise), and reduced
    precision needs an f32 reference the partitioned ingest never
    stages — both refuse at parse, not after a full pod assembly."""
    with pytest.raises(
        PlanValidationError, match="no pod path yet"
    ):
        ExecutionPlan.parse(
            "info_file=i.txt&task=seizure&fe=dwt-4&train_clf=logreg"
            "&processes=2&coordinator=c:1&process_id=0"
        )
    with pytest.raises(
        PlanValidationError, match="pod runs compute f32"
    ):
        ExecutionPlan.parse(
            "info_file=i.txt&fe=dwt-8-fused-decode&train_clf=logreg"
            "&precision=bf16&processes=2&coordinator=c:1&process_id=0"
        )
