"""The end-to-end pipeline smoke gate (tools/e2e_smoke.py), wired as
a slow-marked test so tier-1 stays fast while CI can run the full
cold -> warm -> fan-out -> population ladder. The gates: warm-cache
faster than cold, cache hit/miss attribution correct,
cached-vs-uncached and fan-out-vs-single statistics bit-identical,
fan-out amortized, fan-out compiling fewer programs than 5x single,
the 16-member vmapped population beating its looped twin's train
stage with byte-identical statistics — and every timed run must
produce a well-formed ``run_report.json`` (obs/report.py schema,
nonzero stage spans, cache attribution matching the bench line)."""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

_SMOKE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools",
    "e2e_smoke.py",
)


def _report_checks() -> tuple:
    """The smoke tool's own report-check registry
    (e2e_smoke.REPORT_CHECKS) — the pin below derives from it, so
    growing the checked set is one edit in the tool, not a
    hand-maintained integer here."""
    spec = importlib.util.spec_from_file_location("e2e_smoke", _SMOKE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.REPORT_CHECKS


@pytest.mark.slow
def test_e2e_smoke_trio():
    proc = subprocess.run(
        [sys.executable, _SMOKE],  # tool defaults: 2000 markers x 4 files
        capture_output=True,
        text=True,
        # the ladder grew the serve_mega + int8 children in PR 12, the
        # 3-replica gateway_fleet child in ISSUE 17, and the int4 +
        # quantized-stack children in ISSUE 18; headroom over the
        # measured full-run wall, not a schedule
        timeout=2700,
    )
    assert proc.returncode == 0, (
        f"smoke gate failed:\n{proc.stdout}\n{proc.stderr[-2000:]}"
    )
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary["ok"], summary["failures"]
    assert summary["warm_speedup"] > 1.0
    # the run-report gate ran for exactly the registered variants —
    # the pin IS the tool's registry, never a drifting literal
    assert summary["reports_checked"] == len(_report_checks())
    assert summary["cold_stages"]["ingest"] > 0
    # the population engine's headline: vmapped members trained
    # faster than the looped twin, on identical statistics
    assert summary["population_train_speedup"] > 1.0
