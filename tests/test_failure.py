"""Failure detection + elastic recovery (obs/failure.py).

Fault-injection coverage the reference never had (SURVEY.md section 5:
its only policy is 'log and continue'): crash mid-run and resume from
the checkpoint store, detect divergence at the offending step, probe
device health.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.checkpoint.manager import (
    CheckpointManager,
    run_resumable,
)
from eeg_dataanalysispackage_tpu.obs import failure


def test_probe_devices_all_healthy():
    probe = failure.probe_devices()
    assert probe.all_healthy
    assert len(probe.healthy) == len(jax.devices())
    assert all(t >= 0 for t in probe.latencies_s)


def test_sentinel_nonfinite_raises_at_step():
    s = failure.DivergenceSentinel()
    s.check(1, 0.5)
    with pytest.raises(failure.TrainingDiverged, match="step 2"):
        s.check(2, float("nan"))


def test_sentinel_explosion_needs_patience():
    s = failure.DivergenceSentinel(window=5, explode_factor=10.0, patience=2)
    for i in range(5):
        s.check(i, 1.0)
    s.check(5, 100.0)  # first strike: tolerated
    with pytest.raises(failure.TrainingDiverged, match="exploded"):
        s.check(6, 100.0)  # second consecutive strike


def test_sentinel_single_spike_tolerated():
    s = failure.DivergenceSentinel(window=5, explode_factor=10.0, patience=2)
    for i in range(5):
        s.check(i, 1.0)
    s.check(5, 100.0)
    s.check(6, 1.0)  # recovery resets strikes
    s.check(7, 100.0)  # a lone spike later is fine again


def _sgd_step(state, x, y):
    """Deterministic toy step: state is a weight vector."""
    w = state["w"]
    grad = 2 * (w @ x - y) * x
    return {"w": w - 0.01 * grad}, jnp.abs(w @ x - y)


def test_elastic_train_survives_transient_crashes(tmp_path):
    rng = np.random.RandomState(0)
    xs = rng.randn(30, 4).astype(np.float32)
    ys = (xs @ np.array([1.0, -2.0, 0.5, 0.0])).astype(np.float32)
    batches = [(jnp.asarray(x), jnp.asarray(y)) for x, y in zip(xs, ys)]
    init = lambda: {"w": jnp.zeros(4)}

    # uninterrupted reference run
    ref_mgr = CheckpointManager(str(tmp_path / "ref"))
    ref_state, ref_last = run_resumable(
        ref_mgr, init, _sgd_step, list(batches), save_every=1
    )

    # faulty run: the 7th and 19th train-step calls crash, once each
    crashed = set()
    calls = {"n": 0}
    crash_points = {7, 19}

    def flaky_step(state, x, y):
        calls["n"] += 1
        # crash the first time each crash-point call count is reached
        if calls["n"] in crash_points and calls["n"] not in crashed:
            crashed.add(calls["n"])
            raise RuntimeError(f"injected fault at call {calls['n']}")
        return _sgd_step(state, x, y)

    mgr = CheckpointManager(str(tmp_path / "flaky"))
    state, last, restarts = failure.elastic_train(
        mgr,
        init,
        flaky_step,
        lambda: list(batches),
        max_restarts=5,
        save_every=1,
        probe_on_failure=False,
    )
    assert restarts == 2
    assert last == ref_last == 30
    np.testing.assert_allclose(
        np.asarray(state["w"]), np.asarray(ref_state["w"]), atol=1e-6
    )


def test_elastic_train_deterministic_fault_surfaces_without_replay(tmp_path):
    calls = {"n": 0}

    def always_nan(state, x, y):
        calls["n"] += 1
        return state, jnp.float32(float("nan"))

    mgr = CheckpointManager(str(tmp_path / "nan"))
    with pytest.raises(failure.TrainingDiverged):
        failure.elastic_train(
            mgr,
            lambda: {"w": jnp.zeros(2)},
            always_nan,
            lambda: [(jnp.ones(2), jnp.float32(0.0))] * 3,
            max_restarts=2,
            save_every=1,
            sentinel=failure.DivergenceSentinel(),
            probe_on_failure=False,
        )
    # divergence replays identically, so it must NOT be retried
    assert calls["n"] == 1


def test_sentinel_reset_on_restart(tmp_path):
    """Replayed steps must not double-count in the sentinel window."""
    sentinel = failure.DivergenceSentinel(window=4, explode_factor=10.0)
    for i in range(4):
        sentinel.check(i, 1.0)
    sentinel._strikes = 1
    sentinel.reset()
    assert len(sentinel._history) == 0 and sentinel._strikes == 0
    # after reset, a big loss is not judged against stale history
    sentinel.check(10, 500.0)


def test_elastic_train_restarts_skip_checkpointed_steps(tmp_path):
    """After a crash, the replay covers only un-checkpointed steps."""
    executed = []

    fail_once = {"armed": True}

    def step(state, x, y):
        executed.append(float(np.asarray(x).sum()))
        if len(executed) == 6 and fail_once["armed"]:
            fail_once["armed"] = False
            raise RuntimeError("injected")
        return _sgd_step(state, x, y)

    batches = [
        (jnp.full(2, float(i)), jnp.float32(i)) for i in range(8)
    ]
    mgr = CheckpointManager(str(tmp_path / "skip"))
    _, last, restarts = failure.elastic_train(
        mgr,
        lambda: {"w": jnp.zeros(2)},
        step,
        lambda: list(batches),
        max_restarts=2,
        save_every=2,  # checkpoints at steps 2 and 4 before the crash
        probe_on_failure=False,
    )
    assert restarts == 1 and last == 8
    # 5 good calls + 1 crashing call, then resume from step 4: steps
    # 5..8 replay (4 calls) — total 10, not 14
    assert len(executed) == 10


class _FakeManager:
    """In-memory stand-in for CheckpointManager: just enough of the
    save/restore/latest_step contract for run_resumable, with full
    visibility into what elastic_train saved and restored."""

    def __init__(self):
        self.saved = {}
        self.save_calls = []
        self.restore_calls = []

    def latest_step(self):
        return max(self.saved) if self.saved else None

    def save(self, step, state, extra=None):
        self.saved[step] = jax.tree_util.tree_map(np.asarray, state)
        self.save_calls.append(step)

    def restore(self, template, step=None):
        step = step if step is not None else self.latest_step()
        return self.saved[step], {"step": step, "extra": {}}


def test_elastic_train_restart_accounting_fake_manager():
    """Scripted failing train_step: restarts counts exactly the
    failed incarnations, checkpoints drive the replay skip, and
    exceeding max_restarts re-raises the scripted error."""
    calls = {"n": 0}
    fail_at_calls = {3, 5}

    def scripted_step(state, x):
        calls["n"] += 1
        if calls["n"] in fail_at_calls:
            raise RuntimeError(f"scripted fault (call {calls['n']})")
        return {"w": state["w"] + x}, jnp.float32(1.0)

    mgr = _FakeManager()
    state, last, restarts = failure.elastic_train(
        mgr,
        lambda: {"w": jnp.zeros(())},
        scripted_step,
        lambda: [(jnp.float32(i),) for i in range(1, 6)],
        max_restarts=3,
        save_every=1,
        probe_on_failure=False,
    )
    assert restarts == 2
    assert last == 5
    assert float(np.asarray(state["w"])) == 15.0  # 1+2+3+4+5, no replays lost
    # incarnation 1: steps 1-2 checkpoint, call 3 (step 3) fails;
    # incarnation 2: step 3 replays (call 4), call 5 (step 4) fails;
    # incarnation 3: steps 4-5 (calls 6-7). 5 good + 2 failed = 7.
    assert calls["n"] == 7
    assert mgr.save_calls == [1, 2, 3, 4, 5]


def test_elastic_train_exhausted_restarts_reraises():
    def always_fail(state, x):
        raise RuntimeError("permanent fault")

    mgr = _FakeManager()
    with pytest.raises(RuntimeError, match="permanent fault"):
        failure.elastic_train(
            mgr,
            lambda: {"w": jnp.zeros(())},
            always_fail,
            lambda: [(jnp.float32(1.0),)],
            max_restarts=2,
            save_every=1,
            probe_on_failure=False,
        )
    assert mgr.save_calls == []  # nothing ever succeeded


def test_elastic_train_probe_on_failure_fails_fast(monkeypatch):
    """probe_on_failure=True + an unhealthy probe: no restart happens
    — the run aborts at once with the probe evidence chained to the
    training failure (obs/failure.py:210-236)."""
    calls = {"n": 0}

    def crash_once(state, x):
        calls["n"] += 1
        raise RuntimeError("device went away")

    monkeypatch.setattr(
        failure,
        "probe_devices",
        lambda *a, **k: failure.DeviceProbeResult(
            healthy=[], failed=[("fake-dev", "no response")], latencies_s=[]
        ),
    )
    mgr = _FakeManager()
    with pytest.raises(RuntimeError, match="unhealthy after training") as ei:
        failure.elastic_train(
            mgr,
            lambda: {"w": jnp.zeros(())},
            crash_once,
            lambda: [(jnp.float32(1.0),)] * 4,
            max_restarts=5,
            save_every=1,
            probe_on_failure=True,
        )
    # the scripted failure is chained as the cause, and the step was
    # NOT retried onto dead hardware
    assert isinstance(ei.value.__cause__, RuntimeError)
    assert "device went away" in str(ei.value.__cause__)
    assert calls["n"] == 1


def test_elastic_train_healthy_probe_allows_restart(monkeypatch):
    probes = {"n": 0}

    def healthy_probe(*a, **k):
        probes["n"] += 1
        return failure.DeviceProbeResult(
            healthy=["fake-dev"], failed=[], latencies_s=[0.01]
        )

    monkeypatch.setattr(failure, "probe_devices", healthy_probe)
    armed = {"fail": True}

    def step(state, x):
        if armed["fail"]:
            armed["fail"] = False
            raise RuntimeError("transient")
        return {"w": state["w"] + x}, jnp.float32(0.5)

    mgr = _FakeManager()
    _, last, restarts = failure.elastic_train(
        mgr,
        lambda: {"w": jnp.zeros(())},
        step,
        lambda: [(jnp.float32(1.0),)] * 3,
        max_restarts=2,
        save_every=1,
        probe_on_failure=True,
    )
    assert restarts == 1 and last == 3
    assert probes["n"] == 1


def test_elastic_raw_stream_training_end_to_end(tmp_path):
    """The subsystems compose: elastic_train drives
    make_raw_train_step (fused int16 ingest -> MLP update) across an
    injected transient crash, resuming from checkpoints, and lands on
    the same state as an uninterrupted run."""
    import jax

    from eeg_dataanalysispackage_tpu.parallel import train as ptrain

    rng = np.random.RandomState(0)
    n, stride, first = 16, 800, 150
    S = 200 + n * stride + 8192
    res = np.array([0.1, 0.1, 0.2], np.float32)
    batches = []
    for b in range(6):
        raw = rng.randint(-3000, 3000, size=(3, S)).astype(np.int16)
        labels = rng.randint(0, 2, size=n).astype(np.float32)
        batches.append(
            (
                jnp.asarray(raw),
                jnp.asarray(res),
                jnp.asarray(labels),
                jnp.ones((n,), jnp.float32),
                first,
            )
        )

    init_state, raw_step = ptrain.make_raw_train_step(stride, n)
    init = lambda: init_state(jax.random.PRNGKey(0))

    ref_mgr = CheckpointManager(str(tmp_path / "ref"))
    ref_state, ref_last = run_resumable(
        ref_mgr, init, raw_step, list(batches), save_every=1
    )

    calls = {"n": 0}
    crashed = set()

    def flaky_step(state, *batch):
        calls["n"] += 1
        if calls["n"] == 4 and 4 not in crashed:
            crashed.add(4)
            raise RuntimeError("injected fault")
        return raw_step(state, *batch)

    mgr = CheckpointManager(str(tmp_path / "flaky"))
    state, last, restarts = failure.elastic_train(
        mgr,
        init,
        flaky_step,
        lambda: list(batches),
        max_restarts=3,
        save_every=1,
        probe_on_failure=False,
    )
    assert restarts == 1
    assert last == ref_last == 6
    for k in ("w0", "b0", "w1", "b1"):
        np.testing.assert_allclose(
            np.asarray(state["params"][k]),
            np.asarray(ref_state["params"][k]),
            atol=1e-6,
        )
