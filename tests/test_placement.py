"""Device-aware fleet placement suite (scheduler/placement.py +
ExecutionPlan.device_footprint + the executor's gang/backfill loop).

The acceptance pins:

- **footprint matrix** — the IR derives ``{devices, hosts,
  memory_class}`` purely from the parsed knobs (devices/mesh/pod/
  population/serve rows), no environment and no backend;
- **device-lease race** — two replica identities race the same
  ordinals through the shared lease directory and every ordinal lands
  with exactly one holder; the losses are counted;
- **backfill** — a gang whose footprint cannot be satisfied waits
  (journal record stays ``submitted``) while a smaller plan backfills
  past it and completes first; the freed pool then grants the gang,
  with the leased ordinals attributed in its journal meta;
- **no-starvation promotion** — once the oldest waiting footprint has
  starved past ``EEG_TPU_GANG_PROMOTION_S``, no other plan is granted
  new ordinals until the promoted gang fits.
"""

import json
import os
import threading
import time

import pytest

import _synthetic
from eeg_dataanalysispackage_tpu import obs
from eeg_dataanalysispackage_tpu.pipeline.plan import ExecutionPlan
from eeg_dataanalysispackage_tpu.scheduler import lease as lease_mod
from eeg_dataanalysispackage_tpu.scheduler import placement
from eeg_dataanalysispackage_tpu.scheduler.executor import PlanExecutor
from eeg_dataanalysispackage_tpu.scheduler.journal import PlanJournal


@pytest.fixture(autouse=True)
def _fast_lease(monkeypatch):
    monkeypatch.setenv(lease_mod.ENV_LEASE_TIMEOUT, "1")


@pytest.fixture()
def session(tmp_path):
    return _synthetic.write_session(str(tmp_path), n_markers=60)


def _q(info, extra=""):
    return (
        f"info_file={info}&fe=dwt-8&train_clf=logreg"
        "&config_step_size=1.0&config_num_iterations=20"
        "&config_mini_batch_fraction=1.0&dedup=false" + extra
    )


def _counters():
    return obs.metrics.snapshot()["counters"]


# -- footprint matrix --------------------------------------------------


_BASE = "info_file=/tmp/x/info.txt&fe=dwt-8&train_clf=logreg"


@pytest.mark.parametrize("extra,expected", [
    # a plain single-model run is one capacity token
    ("", {"devices": 1, "hosts": 1, "memory_class": "light"}),
    # explicit mesh size = the gang size, all-or-nothing
    ("&devices=4", {"devices": 4, "hosts": 1, "memory_class": "heavy"}),
    # multi-axis extents multiply out to the gang size
    ("&mesh_axes=data:2,time:2",
     {"devices": 4, "hosts": 1, "memory_class": "heavy"}),
    # axes-only mesh sizes itself to the host at execution: devices=0
    # means "every ordinal present"
    ("&mesh_axes=data",
     {"devices": 0, "hosts": 1, "memory_class": "heavy"}),
    # pod plans: hosts = processes, one local ordinal — the fleet
    # routes them through pod-assist, not the local pool
    ("&processes=2", {"devices": 1, "hosts": 2, "memory_class": "heavy"}),
    # population stacks classify by member count: < 32 standard,
    # >= 32 heavy
    ("&cv=4&seeds=2",
     {"devices": 1, "hosts": 1, "memory_class": "standard"}),
    ("&cv=8&seeds=4",
     {"devices": 1, "hosts": 1, "memory_class": "heavy"}),
    # serve plans are their own class (resident; exempt from the pool)
    ("&serve=true", {"devices": 1, "hosts": 1, "memory_class": "serve"}),
])
def test_footprint_matrix(extra, expected):
    assert ExecutionPlan.parse(_BASE + extra).device_footprint() \
        == expected


def test_footprint_is_pure_and_repeatable():
    plan = ExecutionPlan.parse(_BASE + "&devices=4")
    assert plan.device_footprint() == plan.device_footprint()


# -- two-replica device-lease race -------------------------------------


def test_two_replicas_race_ordinals_exactly_one_holder(tmp_path):
    """Two replica identities hammer a 4-ordinal pool with competing
    2-device gangs from 8 threads: whatever lands, every ordinal has
    exactly ONE holder (the O_EXCL claim is the arbiter), the two
    pools' granted sets never overlap, and the losers' contended
    claims are counted in lease.stats()."""
    a = lease_mod.LeaseDir(str(tmp_path), holder="gw-a")
    b = lease_mod.LeaseDir(str(tmp_path), holder="gw-b")
    pool_a = placement.DevicePool(a, size=4)
    pool_b = placement.DevicePool(b, size=4)
    before = lease_mod.stats()
    footprint = {"devices": 2, "hosts": 1, "memory_class": "heavy"}
    grants, lock = [], threading.Lock()
    barrier = threading.Barrier(8)

    def race(pool, plan_id):
        barrier.wait()
        got = pool.admit(plan_id, footprint)
        if isinstance(got, placement.DeviceGrant):
            with lock:
                grants.append((pool, got))

    threads = [
        threading.Thread(
            target=race,
            args=(pool_a if i % 2 == 0 else pool_b, f"p{i:04d}"),
        )
        for i in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    # a 4-ordinal pool fits exactly two 2-device gangs
    assert len(grants) == 2
    held = [tuple(g.ordinals) for _, g in grants]
    flat = [o for ordinals in held for o in ordinals]
    assert sorted(flat) == sorted(set(flat)), (
        f"ordinal granted twice: {held}"
    )
    # the on-disk view agrees: each held ordinal names one holder
    table = placement.device_table(str(tmp_path))
    assert sorted(r["ordinal"] for r in table) == sorted(flat)
    assert all(r["holder"] in ("gw-a", "gw-b") for r in table)
    after = lease_mod.stats()
    assert after["device_claims"] - before["device_claims"] >= 4
    # 8 threads x 2-ordinal wants over 4 ordinals: somebody lost a
    # contended O_EXCL create and the loss was counted
    assert after["device_claim_losses"] > before["device_claim_losses"]

    for _, g in grants:
        g.release()
    assert placement.device_table(str(tmp_path)) == []
    assert lease_mod.stats()["device_releases"] \
        > before["device_releases"]


def test_all_or_nothing_no_partial_gang_held(tmp_path):
    """A gang that cannot fully fit releases every partial claim
    immediately — two half-holding replicas must never deadlock."""
    a = lease_mod.LeaseDir(str(tmp_path), holder="gw-a")
    b = lease_mod.LeaseDir(str(tmp_path), holder="gw-b")
    pool_a = placement.DevicePool(a, size=4)
    # gw-b pins ordinals 2 and 3 out from under the gang
    assert isinstance(b.try_claim("device:2"), lease_mod.PlanLease)
    assert isinstance(b.try_claim("device:3"), lease_mod.PlanLease)
    got = pool_a.admit(
        "gang", {"devices": 3, "hosts": 1, "memory_class": "heavy"}
    )
    assert got is None  # wait — and crucially, hold NOTHING
    table = placement.device_table(str(tmp_path))
    assert sorted(r["ordinal"] for r in table) == [2, 3]
    assert all(r["holder"] == "gw-b" for r in table)
    # the unsatisfied footprint is advertised for the operator surface
    waiting = placement.waiting_entries(str(tmp_path))
    assert [e["plan_id"] for e in waiting] == ["gang"]
    assert waiting[0]["footprint"]["devices"] == 3


def test_exempt_and_oversize_run_unplaced(tmp_path):
    """Serve plans, pod plans, and footprints larger than the pool
    return UNPLACED — the builder's availability ladder governs, the
    pool holds nothing, and nobody waits forever on the impossible."""
    pool = placement.DevicePool(
        lease_mod.LeaseDir(str(tmp_path), holder="gw-a"), size=2,
    )
    for footprint in (
        {"devices": 1, "hosts": 1, "memory_class": "serve"},
        {"devices": 1, "hosts": 2, "memory_class": "heavy"},
        {"devices": 3, "hosts": 1, "memory_class": "heavy"},
    ):
        assert pool.admit("px", footprint) is placement.UNPLACED
    assert placement.device_table(str(tmp_path)) == []
    assert placement.waiting_entries(str(tmp_path)) == []


# -- gang scheduling with backfill (the executor loop) -----------------


def test_small_plan_backfills_past_blocked_gang(session, tmp_path,
                                                monkeypatch):
    """A 2-device gang blocked on a peer-held ordinal waits with its
    journal record still ``submitted`` while a 1-device plan submitted
    AFTER it backfills past and completes first. Freeing the ordinal
    then grants the gang, and the leased ordinals land in its journal
    meta."""
    monkeypatch.setenv(placement.ENV_GANG_PROMOTION, "600")
    journal_dir = str(tmp_path / "journal")
    os.makedirs(journal_dir)
    peer = lease_mod.LeaseDir(journal_dir, holder="gw-peer")
    assert isinstance(peer.try_claim("device:1"), lease_mod.PlanLease)

    before = _counters()
    ex = PlanExecutor(journal_dir=journal_dir, max_concurrent=1)
    ex.placement = placement.DevicePool(
        lease_mod.LeaseDir(journal_dir, holder="gw-a"), size=2,
    )
    journal = PlanJournal(journal_dir)
    try:
        gang = ex.submit(_q(session, "&devices=2"))
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if placement.waiting_entries(journal_dir):
                break
            time.sleep(0.02)
        waiting = placement.waiting_entries(journal_dir)
        assert [e["plan_id"] for e in waiting] == [gang.plan_id]

        small = ex.submit(_q(session))
        small.result(timeout=300)
        # the backfill evidence: the small plan is terminal while the
        # gang is still write-ahead-only, and the pass was counted
        assert journal.entry(small.plan_id)["state"] == "completed"
        assert journal.entry(gang.plan_id)["state"] == "submitted"
        assert _counters().get("placement.backfills", 0) \
            > before.get("placement.backfills", 0)

        peer.release("device:1")
        gang.result(timeout=300)
        entry = journal.entry(gang.plan_id)
        assert entry["state"] == "completed"
        # the granted ordinals are the mesh the builder was handed
        assert entry["meta"]["fleet"]["devices"] == [0, 1]
        assert placement.waiting_entries(journal_dir) == []
    finally:
        ex.close()
    # nothing left held: grants released on the execution path
    assert placement.device_table(journal_dir) == []


def test_promotion_blocks_other_grants_until_gang_fits(tmp_path,
                                                       monkeypatch):
    """The no-starvation bound: once the oldest waiting footprint has
    starved past EEG_TPU_GANG_PROMOTION_S, a freed ordinal goes to the
    promoted gang — a smaller plan that would previously have
    backfilled is refused until the gang runs."""
    monkeypatch.setenv(placement.ENV_GANG_PROMOTION, "0.2")
    peer = lease_mod.LeaseDir(str(tmp_path), holder="gw-peer")
    assert isinstance(peer.try_claim("device:0"), lease_mod.PlanLease)
    pool = placement.DevicePool(
        lease_mod.LeaseDir(str(tmp_path), holder="gw-a"), size=1,
    )
    one = {"devices": 1, "hosts": 1, "memory_class": "light"}

    before = _counters()
    assert pool.admit("gang", one) is None  # waits, clock starts
    time.sleep(0.3)  # starve past the promotion age
    peer.release("device:0")

    # the ordinal is free, but the promoted gang owns everything that
    # frees up: the backfill candidate is refused
    assert pool.admit("small", one) is None
    after = _counters()
    assert after.get("placement.promotion_blocked", 0) \
        > before.get("placement.promotion_blocked", 0)

    granted = pool.admit("gang", one)
    assert isinstance(granted, placement.DeviceGrant)
    assert granted.ordinals == (0,)
    assert _counters().get("placement.promotions", 0) \
        > before.get("placement.promotions", 0)
    # the gang's record is gone; the refused backfiller still waits
    assert [
        e["plan_id"]
        for e in placement.waiting_entries(str(tmp_path))
    ] == ["small"]

    granted.release()
    small = pool.admit("small", one)
    assert isinstance(small, placement.DeviceGrant)
    small.release()
    assert placement.waiting_entries(str(tmp_path)) == []


def test_dead_holders_waiting_record_cleared(tmp_path):
    """A SIGKILLed replica's waiting record must not promote forever
    and wedge the whole fleet: a provably dead advertiser (pid + start
    token) is skipped and unlinked on the next read."""
    path = os.path.join(str(tmp_path), "waiting-p0001.json")
    with open(path, "w") as f:
        json.dump({
            "schema": "eeg-tpu-placement-wait/v1",
            "plan_id": "p0001",
            "footprint": {"devices": 2, "hosts": 1,
                          "memory_class": "heavy"},
            "since": time.time() - 100.0,
            "holder": "gw-dead",
            "pid": 999999,
            "start_token": "",
        }, f)
    assert placement.waiting_entries(str(tmp_path)) == []
    assert placement.waiting_entries(
        str(tmp_path), clear_dead=True
    ) == []
    assert not os.path.exists(path)


def test_pool_disabled_by_default(tmp_path, monkeypatch):
    """EEG_TPU_DEVICE_POOL unset/0 = placement off: from_env returns
    None and the executor path stays byte-identical to PR 17."""
    leases = lease_mod.LeaseDir(str(tmp_path), holder="gw-a")
    monkeypatch.delenv(placement.ENV_DEVICE_POOL, raising=False)
    assert placement.DevicePool.from_env(leases) is None
    monkeypatch.setenv(placement.ENV_DEVICE_POOL, "0")
    assert placement.DevicePool.from_env(leases) is None
    monkeypatch.setenv(placement.ENV_DEVICE_POOL, "3")
    pool = placement.DevicePool.from_env(leases)
    assert pool is not None and pool.size == 3
    # the marker advertises the size for offline observers
    assert placement.pool_size_marker(str(tmp_path)) == 3
