"""Pod-scale multi-process execution (ISSUE 14), live half.

Two OS processes over a loopback coordinator run ONE population query
through the real pipeline: ``_resolve_pod`` bootstraps via the
preflight + ``jax.distributed.initialize``, each process ingests its
disjoint recording block, the feature exchange all-gathers the global
matrix over the gloo-backed DCN stand-in, and
``train_linear_population_sharded`` trains the member axis over the
hybrid (hosts x data) mesh. The pinned contract: both processes'
``ClassificationStatistics`` are byte-identical to the single-process
run of the same query, the mesh block records
{processes, process_id, coordinator, dcn_shape}, and the compiled HLO
of both the exchange and the weight gather carries the cross-process
all-gather (asserted inside the workers, where the multi-process
programs exist).
"""

import hashlib
import json
import os
import socket
import subprocess
import sys

import pytest

import _synthetic
from eeg_dataanalysispackage_tpu.pipeline import builder

_POP_QUERY = (
    "fe=dwt-8-fused-decode&train_clf=logreg&cv=2&sweep=lr:1.0,0.5"
    "&cache=false&dedup=false&config_num_iterations=12"
    "&config_step_size=1.0&config_mini_batch_fraction=1.0"
)


@pytest.fixture(scope="module")
def info(tmp_path_factory):
    directory = tmp_path_factory.mktemp("pod_pipe")
    lines = []
    for i in range(2):
        name = f"podp_{i}"
        guessed = 2 + i
        _synthetic.write_recording(
            str(directory), name=name, n_markers=60, guessed=guessed,
            seed=i,
        )
        lines.append(f"{name}.eeg {guessed}")
    info = os.path.join(str(directory), "info.txt")
    with open(info, "w") as f:
        f.write("\n".join(lines) + "\n")
    return info


def test_two_process_pipeline_statistics_byte_identical(info):
    baseline = builder.PipelineBuilder(
        f"info_file={info}&{_POP_QUERY}"
    ).execute()
    baseline_sha = hashlib.sha256(str(baseline).encode()).hexdigest()

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    worker = os.path.join(os.path.dirname(__file__), "_pod_worker.py")
    procs = []
    for pid in range(2):
        query = (
            f"info_file={info}&{_POP_QUERY}"
            f"&processes=2&coordinator=127.0.0.1:{port}"
            f"&process_id={pid}"
        )
        env = dict(os.environ)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env["EEG_TPU_NO_FEATURE_CACHE"] = "1"
        env.pop("EEG_TPU_FAULTS", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, worker, query],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        for p in procs:  # reap stragglers if a peer failed or hung
            if p.poll() is None:
                p.kill()
                p.wait()

    for pid, o in enumerate(outs):
        # the pinned byte-identity: 2-process == single-process
        assert o["sha"] == baseline_sha, o
        assert o["procs"] == 2 and o["devices"] == 4
        mesh = o["mesh"]
        assert mesh["rung"] == "pod"
        assert mesh["processes"] == 2
        assert mesh["process_id"] == pid
        assert mesh["coordinator"] == f"127.0.0.1:{port}"
        assert mesh["dcn_shape"] == {"hosts": 2}
        assert mesh["shape"] == {"hosts": 2, "data": 2}
        # the population trained SHARDED over the pod's member axis
        assert mesh["population"]["rung"] == "mesh"
        assert mesh["population"]["axis"] == "hosts,data"
        assert o["degradation"] == []
        # the cross-process collectives exist in the compiled HLO
        assert o["exchange_allgather"] is True
        assert o["weight_allgather"] is True
