"""Pluggable subband wavelet features (features/subband.py) + the
extended ``fe=`` grammar (features/registry.py)."""

import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.features import registry, subband, wavelet
from eeg_dataanalysispackage_tpu.ops import dwt_host, eegdsp_compat


# ------------------------------------------------ grammar


def test_plain_names_resolve_exactly_as_before():
    fe = registry.create("dwt-8")
    assert isinstance(fe, wavelet.WaveletTransform)
    assert (fe.name, fe.epoch_size, fe.skip_samples, fe.feature_size) == (
        8, 512, 175, 16
    )
    assert isinstance(registry.create("dwt-4-tpu"), wavelet.WaveletTransform)
    with pytest.raises(ValueError, match="Unsupported feature extraction"):
        registry.create("nope")


def test_extended_grammar_builds_subband_extractor():
    fe = registry.create("dwt-4:level=4:stats=energy,std")
    assert isinstance(fe, subband.SubbandWaveletFeatures)
    assert fe.name == 4 and fe.level == 4
    assert fe.stats == ("energy", "std")
    # stats defaults to energy
    fe2 = registry.create("dwt-8:level=3")
    assert fe2.stats == ("energy",)


def test_grammar_errors():
    with pytest.raises(ValueError, match="level must be an integer"):
        registry.create("dwt-4:level=x")
    with pytest.raises(ValueError, match="unknown fe= option"):
        registry.create("dwt-4:depth=3")
    with pytest.raises(ValueError, match="malformed fe= option"):
        registry.create("dwt-4:level=")
    with pytest.raises(ValueError, match="plain dwt-<family> form"):
        registry.create("dwt-4-tpu:level=3")
    with pytest.raises(ValueError, match="unknown subband stat"):
        registry.create("dwt-4:stats=zap")
    with pytest.raises(ValueError, match="repeats an entry"):
        registry.create("dwt-4:stats=energy,energy")
    with pytest.raises(ValueError, match="Wavelet Name"):
        registry.create("dwt-99:level=2")


# ------------------------------------------------ extraction semantics


def test_feature_dimension_and_shape():
    fe = subband.SubbandWaveletFeatures(name=4, level=4,
                                        stats=("energy", "mean", "std"))
    assert fe.feature_dimension == 3 * 5 * 3
    x = np.random.RandomState(0).randn(6, 3, 512)
    out = fe.extract_batch(x)
    assert out.shape == (6, fe.feature_dimension)
    # the final vector is L2-normalized
    np.testing.assert_allclose(np.linalg.norm(out, axis=1), 1.0)


def test_subband_energies_match_full_cascade_prefix():
    """Level-L subband coefficients must be the same numbers the full
    eegdsp cascade produces (the a_L prefix of fwt_periodic's
    layout): the subband extractor is a re-grouping of the pinned
    transform, not a new transform."""
    rng = np.random.RandomState(1)
    sig = rng.randn(512)
    h, g = eegdsp_compat.filter_pair(8)
    full = dwt_host.fwt_periodic(sig, h, g)  # 6 levels for 10 taps
    fe = subband.SubbandWaveletFeatures(name=8, level=6,
                                        stats=("energy",), channels=(1,))
    bands = fe._decompose(sig[None, None, :])
    # [a6 | d6 | d5 | ... | d1] is exactly the full-cascade layout
    flat = np.concatenate([b[0, 0] for b in bands])
    np.testing.assert_allclose(flat, full, rtol=0, atol=0)


def test_deterministic_and_dtype():
    x = np.random.RandomState(2).randn(4, 3, 512)
    fe = registry.create("dwt-4:level=3:stats=energy,mean")
    a = fe.extract_batch(x)
    b = fe.extract_batch(x)
    np.testing.assert_array_equal(a, b)
    assert a.dtype == np.float64


def test_level_too_deep_raises():
    fe = subband.SubbandWaveletFeatures(name=8, level=9)
    with pytest.raises(ValueError, match="supports only"):
        fe.extract_batch(np.zeros((1, 3, 512)))


def test_stat_values_hand_checked():
    """Constant signal through the Daubechies scaling filter: detail
    coefficients vanish, so detail-band energies are ~0 and the
    approximation band carries everything."""
    x = np.ones((1, 1, 64))
    fe = subband.SubbandWaveletFeatures(name=8, level=2,
                                        stats=("energy",), channels=(1,))
    out = fe.extract_batch(x)[0]  # [a2, d2, d1] energies, normalized
    assert out[0] == pytest.approx(1.0, abs=1e-10)
    assert abs(out[1]) < 1e-10 and abs(out[2]) < 1e-10


# ------------------------------------------------ cache identity


def test_cache_ids_are_config_complete():
    a = registry.create("dwt-4:level=4:stats=energy")
    b = registry.create("dwt-4:level=4:stats=energy,std")
    c = registry.create("dwt-4:level=3:stats=energy")
    d = registry.create("dwt-8:level=4:stats=energy")
    ids = {a.cache_id(), b.cache_id(), c.cache_id(), d.cache_id()}
    assert len(ids) == 4  # family, level, stat set all distinguish
    # the raw-coefficient extractor is distinct too
    assert registry.create("dwt-8").cache_id() not in ids
    # backend does NOT distinguish (the rung contract)
    assert (
        registry.create("dwt-8").cache_id()
        == registry.create("dwt-8-tpu").cache_id()
    )
