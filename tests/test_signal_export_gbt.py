"""Tests for the parity leftovers: SignalProcessing.decimate, the
Epochs.csv writer (DataProviderUtils.writeEpochsToCSV), and the
restored GradientBoostedTrees classifier."""

import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.io import export, provider
from eeg_dataanalysispackage_tpu.models import registry, trees
from eeg_dataanalysispackage_tpu.ops import signal as ops_signal


def test_decimate_stride_semantics():
    x = np.arange(10.0)
    np.testing.assert_array_equal(ops_signal.decimate(x, 3), [0.0, 3.0, 6.0])
    np.testing.assert_array_equal(ops_signal.decimate(x, 1), x)
    # batched over leading axes
    b = np.arange(20.0).reshape(2, 10)
    assert ops_signal.decimate(b, 4).shape == (2, 2)
    with pytest.raises(ValueError):
        ops_signal.decimate(x, 0)


def test_normalize_matches_reference_arithmetic():
    v = np.array([3.0, 4.0])
    np.testing.assert_allclose(ops_signal.normalize(v), [0.6, 0.8], rtol=1e-15)


def test_fft_bandpass_removes_out_of_band_tone():
    fs, n = 1000.0, 1024
    t = np.arange(n) / fs
    keep = np.sin(2 * np.pi * 10 * t)
    kill = np.sin(2 * np.pi * 200 * t)
    out = np.asarray(ops_signal.fft_bandpass(keep + kill, fs, 0.5, 40.0))
    # the 10 Hz tone survives, the 200 Hz tone is suppressed
    spec = np.abs(np.fft.rfft(out))
    f = np.fft.rfftfreq(n, 1 / fs)
    assert spec[np.argmin(np.abs(f - 10))] > 100
    assert spec[np.argmin(np.abs(f - 200))] < 1e-6


def test_epochs_csv_roundtrip(tmp_path, fixture_dir):
    batch = provider.OfflineDataProvider(
        [fixture_dir + "/infoTrain.txt"]
    ).load()
    path = str(tmp_path / "Epochs.csv")
    export.write_epochs_to_csv(batch.epochs, path)
    back = export.read_epochs_csv(path)
    np.testing.assert_array_equal(back, batch.epochs[:, 2, :])
    # format parity: rows end with a trailing comma (DataProviderUtils)
    first = open(path).readline().rstrip("\n")
    assert first.endswith(",")


def test_csv_reader_parses_reference_artifact():
    import os

    if not os.path.exists("/root/reference/Epochs.csv"):
        pytest.skip("reference artifact absent")
    ref = export.read_epochs_csv("/root/reference/Epochs.csv")
    assert ref.shape == (11, 750)


def test_gbt_separates_blobs():
    rng = np.random.RandomState(0)
    x = np.concatenate([rng.randn(80, 4) + 2.0, rng.randn(80, 4) - 2.0])
    y = np.concatenate([np.ones(80), np.zeros(80)])
    clf = trees.GradientBoostedTreesClassifier()
    clf.set_config({
        "config_num_iterations": "20",
        "config_learning_rate": "0.3",
        "config_max_depth": "3",
    })
    clf.fit(x, y)
    assert (clf.predict(x) == y).mean() > 0.95


def test_gbt_save_load_roundtrip(tmp_path):
    rng = np.random.RandomState(1)
    x = rng.randn(60, 5)
    y = (x[:, 0] + 0.3 * x[:, 1] > 0).astype(float)
    clf = trees.GradientBoostedTreesClassifier()
    clf.set_config({})  # default MLlib boosting params
    clf.fit(x, y)
    pred = clf.predict(x)

    path = str(tmp_path / "gbt_model")
    clf.save(path)
    clf2 = trees.GradientBoostedTreesClassifier()
    clf2.load(path)
    np.testing.assert_array_equal(clf2.predict(x), pred)


def test_gbt_registered():
    clf = registry.create("gbt")
    assert isinstance(clf, trees.GradientBoostedTreesClassifier)
    assert "gbt" in registry.names()


def test_gbt_through_pipeline(fixture_dir, tmp_path):
    from eeg_dataanalysispackage_tpu.pipeline import builder

    result = str(tmp_path / "res.txt")
    q = (
        f"info_file={fixture_dir}/infoTrain.txt&fe=dwt-8&train_clf=gbt"
        f"&config_num_iterations=10&config_learning_rate=0.2"
        f"&config_max_depth=2&result_path={result}"
    )
    stats = builder.PipelineBuilder(q).execute()
    assert 0.0 <= stats.calc_accuracy() <= 1.0
    assert "Accuracy" in open(result).read()


def test_write_channel_text_round_trip(tmp_path):
    from eeg_dataanalysispackage_tpu.io import export, sources

    ch = np.array([1.5, -2.25, 0.1], dtype=np.float64)
    path = str(tmp_path / "raw.txt")
    export.write_channel_text(ch, path)
    lines = open(path).read().splitlines()
    assert [float(x) for x in lines] == list(ch)

    fs = sources.InMemoryFileSystem()
    export.write_channel_text(ch, "out/raw.txt", filesystem=fs)
    assert fs.exists("out/raw.txt")


def test_java_double_to_string_formatting():
    from eeg_dataanalysispackage_tpu.utils.java_compat import (
        java_double_to_string as j,
    )

    assert j(0.0) == "0.0"
    assert j(-0.0) == "-0.0"
    assert j(float("nan")) == "NaN"
    assert j(float("inf")) == "Infinity"
    assert j(float("-inf")) == "-Infinity"
    assert j(1.0) == "1.0"
    assert j(100.0) == "100.0"
    assert j(123.456) == "123.456"
    assert j(0.001) == "0.001"
    assert j(0.0001) == "1.0E-4"     # below 1e-3: scientific
    assert j(9999999.0) == "9999999.0"
    assert j(1e7) == "1.0E7"         # at 1e7: scientific
    assert j(12345678.0) == "1.2345678E7"
    assert j(1e22) == "1.0E22"
    assert j(-3.75) == "-3.75"
    assert j(7.2e-43) == "7.2E-43"
    # round-trip: every formatted string parses back to the same bits
    rng = np.random.RandomState(0)
    for v in rng.randn(200) * 10.0 ** rng.randint(-8, 8, 200):
        assert float(j(v)) == v


def test_epochs_csv_byte_parity_with_reference_artifact(tmp_path):
    """Re-emit the reference's own Java-written Epochs.csv through our
    writer: Double.toString-compatible formatting + trailing commas
    must reproduce the artifact byte-for-byte."""
    import os

    ref_path = "/root/reference/Epochs.csv"
    if not os.path.exists(ref_path):
        pytest.skip("reference artifact absent")
    vals = export.read_epochs_csv(ref_path)  # (11, 750)
    epochs = np.zeros((vals.shape[0], 3, vals.shape[1]))
    epochs[:, 2, :] = vals
    out = str(tmp_path / "Epochs.csv")
    export.write_epochs_to_csv(epochs, out)
    ours = open(out, "rb").read()
    theirs = open(ref_path, "rb").read()
    # normalize line endings only (Java println on the build host)
    assert ours.replace(b"\r\n", b"\n") == theirs.replace(b"\r\n", b"\n")
