"""One test per BASELINE.json "configs" entry.

The driver's BASELINE.json names five benchmark configurations the new
framework must support; each gets a scaled-down hermetic test here
(full-size numbers run in bench.py on real hardware). Shapes are tiny
because conftest pins tests to an 8-device virtual CPU mesh.
"""

import os

import numpy as np

from eeg_dataanalysispackage_tpu.features import registry as fe_registry
from eeg_dataanalysispackage_tpu.features import wavelet
from eeg_dataanalysispackage_tpu.io import provider, staging
from eeg_dataanalysispackage_tpu.models import registry as clf_registry
from eeg_dataanalysispackage_tpu.parallel import mesh as pmesh, streaming
from eeg_dataanalysispackage_tpu.pipeline import builder


def test_config1_info_txt_dwt8_logreg_cpu_reference(fixture_dir, tmp_path):
    """Config 1: test-data/info.txt (3-token lines), fe=dwt-8, logreg."""
    result = tmp_path / "result.txt"
    query = (
        f"info_file={fixture_dir}/info.txt&fe=dwt-8"
        f"&train_clf=logreg&result_path={result}"
    )
    builder.PipelineBuilder(query).execute()
    text = result.read_text()
    assert "Accuracy:" in text and "Number of patterns:" in text


def test_config2_p300_corpus_dwt8_tpu_logreg(fixture_dir):
    """Config 2: P300 corpus (Fz/Cz/Pz, 1000ms epochs), fe=dwt-8-tpu."""
    batch = provider.OfflineDataProvider(
        [fixture_dir + "/infoTrain.txt"]
    ).load()
    assert batch.epochs.shape == (11, 3, 750)
    fe = fe_registry.create("dwt-8-tpu")
    clf = clf_registry.create("logreg")
    clf.train(batch.epochs, batch.targets, fe)
    stats = clf.test(batch.epochs, batch.targets)
    assert 0.0 <= stats.calc_accuracy() <= 1.0


def test_config3_synthetic_64ch_stream_db8_svm():
    """Config 3: synthetic 64-channel epoch stream, batched db8 DWT, svm."""
    rng = np.random.RandomState(3)
    n, n_ch = 96, 64
    epochs = rng.randn(n, n_ch, 750).astype(np.float64) * 20.0
    labels = (rng.rand(n) > 0.5).astype(np.float64)

    fe = wavelet.WaveletTransform(
        8, 512, 175, 16, channels=tuple(range(1, n_ch + 1)), backend="xla"
    )
    assert fe.feature_dimension == n_ch * 16
    feats = fe.extract_batch(epochs)
    assert feats.shape == (n, n_ch * 16)
    norms = np.linalg.norm(feats, axis=1)
    np.testing.assert_allclose(norms, 1.0, rtol=1e-5)

    clf = clf_registry.create("svm")
    clf.train(epochs, labels, fe)
    stats = clf.test(epochs, labels)
    assert stats.num_patterns == n


def test_config4_multi_subject_info_shard_rf(fixture_dir, tmp_path):
    """Config 4: multi-subject info.txt shard -> host batches, rf."""
    os.symlink(os.path.join(fixture_dir, "DoD"), tmp_path / "DoD")
    info = tmp_path / "info.txt"
    info.write_text(
        "# multi-subject shard\n"
        "DoD/DoD2015_01.eeg 1\n"
        "DoD/DoD_2015_02.eeg 4\n"
        "DoD/missing_subject.eeg 2\n"  # skipped with a log, not fatal
        "\n"
    )
    batch = provider.OfflineDataProvider([str(info)]).load()
    # both recordings contribute; balance counters span the whole run
    assert batch.epochs.shape[0] > 11
    assert batch.epochs.shape[1:] == (3, 750)

    fe = fe_registry.create("dwt-8-tpu")
    feats = fe.extract_batch(batch.epochs)
    # host->device staging in minibatches feeds the classifier
    staged = [
        np.asarray(fx)
        for fx, _ in staging.prefetch(
            staging.minibatches(feats, batch.targets, batch_size=16)
        )
    ]
    assert sum(s.shape[0] for s in staged) == batch.epochs.shape[0]

    clf = clf_registry.create("rf")
    # all six keys must be present or the reference-parity all-or-
    # nothing branch falls back to the 100-tree defaults
    clf.set_config(
        {
            "config_num_trees": "8",
            "config_max_depth": "4",
            "config_max_bins": "16",
            "config_impurity": "gini",
            "config_min_instances_per_node": "1",
            "config_feature_subset": "auto",
        }
    )
    clf.train(batch.epochs, batch.targets, fe)
    stats = clf.test(batch.epochs, batch.targets)
    assert stats.num_patterns == batch.epochs.shape[0]


def test_config5_streaming_bandpass_dwt_nn_8dev():
    """Config 5: streaming FFT bandpass + DWT on continuous EEG, nn,
    time axis sharded over an 8-device mesh (v5e-8 stand-in)."""
    n_ch, T = 16, 8 * 1024
    rng = np.random.RandomState(5)
    signal = rng.randn(n_ch, T).astype(np.float32) * 30.0

    mesh = pmesh.make_mesh(8, axes=(pmesh.TIME_AXIS,))
    extract = streaming.make_streaming_extractor(
        mesh, window=512, stride=256, fs=1000.0
    )
    feats = np.asarray(extract(streaming.stage_recording(signal, mesh)))
    assert feats.shape == (T // 256, n_ch * 16)
    assert np.isfinite(feats).all()

    labels = (rng.rand(feats.shape[0]) > 0.5).astype(np.float64)
    clf = clf_registry.create("nn")
    clf.set_config(
        {
            "config_seed": "1",
            "config_num_iterations": "30",
            "config_learning_rate": "0.05",
            "config_momentum": "0.9",
            "config_weight_init": "xavier",
            "config_updater": "nesterovs",
            "config_optimization_algo": "sgd",
            "config_pretrain": "false",
            "config_backprop": "true",
            "config_layer1_layer_type": "dense",
            "config_layer1_n_out": "32",
            "config_layer1_activation_function": "relu",
            "config_layer1_drop_out": "0",
            "config_layer2_layer_type": "output",
            "config_layer2_n_out": "2",
            "config_layer2_activation_function": "softmax",
            "config_layer2_drop_out": "0",
        }
    )
    clf.fit(feats, labels)
    preds = clf.predict(feats)
    assert preds.shape == (feats.shape[0],)
    assert np.isfinite(preds).all()
