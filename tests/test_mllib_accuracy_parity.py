"""MLlib accuracy parity on the reference fixture (ClassifierTest.java).

Reproduces ``ClassifierTest.java:98-105`` exactly with the shipped
fixture: ``infoTrain.txt`` -> 11 epochs, ``WaveletTransform(8, 512,
175, 16)`` features, ``Collections.shuffle(new Random(1))``, 70/30
split (7 train / 4 test), then the default-constructor MLlib paths
(``new LogisticRegressionWithSGD().run(rdd)`` /
``new SVMWithSGD().run(rdd)``: step 1.0, 100 iterations, regParam
0.01, full batch, convergenceTol 1e-3, zero init, no intercept).

About the reference's informal pin 0.6415094339622641
(``ClassifierTest.java:105``, commented out in the reference itself):
that value is 34/53, which requires a 53-point test split — i.e. a
~177-epoch corpus. The corpus shipped in ``test-data/`` yields 11
epochs, so the largest reachable test split is 4 points and every
achievable accuracy is a multiple of 0.25; 0.6415... is unreachable
from the shipped data under ANY classifier. The assert was written
against a private corpus (per ``Const.java`` the disabled
``DIRECTORIES`` lists of school recordings) that the reference does
not distribute. The reproducible contract is therefore the exact
float64 trajectory of MLlib's deterministic full-batch path on the
shipped fixture (``models/mllib_oracle.py``), pinned below, with the
production f32 XLA engine asserted to agree prediction-for-prediction.
"""

import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.features import wavelet
from eeg_dataanalysispackage_tpu.io import provider
from eeg_dataanalysispackage_tpu.models import linear, mllib_oracle, sgd
from eeg_dataanalysispackage_tpu.utils import java_compat


@pytest.fixture(scope="module")
def fixture_split(fixture_dir):
    batch = provider.OfflineDataProvider(
        [fixture_dir + "/infoTrain.txt"]
    ).load()
    fe = wavelet.WaveletTransform(8, 512, 175, 16, backend="host")
    feats = fe.extract_batch(batch.epochs)  # float64 bit-parity path
    perm = java_compat.java_shuffle_indices(len(batch.targets), seed=1)
    f = feats[perm]
    t = np.asarray(batch.targets, dtype=np.float64)[perm]
    n_train = int(len(t) * 0.7)  # (int)(11*0.7) == 7
    return f[:n_train], t[:n_train], f[n_train:], t[n_train:]


def test_logreg_default_path_oracle_accuracy(fixture_split):
    ftr, ttr, fte, tte = fixture_split
    assert ftr.shape == (7, 48) and fte.shape == (4, 48)
    w, _, iters = mllib_oracle.run_gradient_descent(ftr, ttr, loss="logistic")
    # no early convergence on this fixture: all 100 iterations run
    assert iters == 100
    preds = mllib_oracle.predict_logreg(fte, w)
    acc = float((preds == tte).mean())
    # The deterministic full-batch trajectory on the SHIPPED corpus:
    # all four test points predicted 0.0 -> accuracy 2/4. The
    # reference's 0.6415094339622641 (= 34/53) needs a 53-point test
    # split and is unreachable from the shipped 11-epoch fixture.
    assert preds.tolist() == [0.0, 0.0, 0.0, 0.0]
    assert acc == 0.5
    # trajectory fingerprint, full f64 precision
    assert float(np.linalg.norm(w)) == pytest.approx(
        1.0861711073763858, abs=1e-15
    )


def test_svm_default_path_oracle_accuracy(fixture_split):
    ftr, ttr, fte, tte = fixture_split
    w, _, iters = mllib_oracle.run_gradient_descent(ftr, ttr, loss="hinge")
    assert iters == 100
    preds = mllib_oracle.predict_svm(fte, w)
    assert preds.tolist() == [0.0, 0.0, 0.0, 0.0]
    assert float((preds == tte).mean()) == 0.5
    assert float(np.linalg.norm(w)) == pytest.approx(
        1.9602503911207547, abs=1e-15
    )


@pytest.mark.parametrize(
    "cls,oracle_pred,loss",
    [
        (linear.LogisticRegressionClassifier, mllib_oracle.predict_logreg,
         "logistic"),
        (linear.SVMClassifier, mllib_oracle.predict_svm, "hinge"),
    ],
)
def test_device_f32_path_agrees_with_oracle(fixture_split, cls, oracle_pred,
                                            loss):
    """The production one-scan XLA engine (f32) must reproduce the
    oracle's predictions and weights on the fixture."""
    ftr, ttr, fte, tte = fixture_split
    w64, _, _ = mllib_oracle.run_gradient_descent(ftr, ttr, loss=loss)

    clf = cls()
    clf.set_config({})  # default branch, like ClassifierTest
    clf.fit(ftr, ttr)
    np.testing.assert_allclose(clf.weights, w64, rtol=0, atol=5e-5)
    preds = clf.predict(fte)
    assert preds.tolist() == oracle_pred(fte, w64).tolist()
    assert float((preds == tte).mean()) == 0.5


def test_convergence_early_stop_matches_oracle():
    """MLlib's convergenceTol early stop: engineered data where the
    trajectory converges before num_iterations; the f32 engine must
    freeze at the same iteration as the f64 oracle."""
    rng = np.random.RandomState(7)
    x = rng.randn(32, 4) * 0.01  # tiny margins -> tiny steps
    y = (rng.rand(32) > 0.5).astype(np.float64)
    w64, _, iters = mllib_oracle.run_gradient_descent(
        x, y, loss="logistic", step_size=0.01, num_iterations=50,
        reg_param=0.01,
    )
    assert iters < 50  # the early stop actually fired
    cfg = sgd.SGDConfig(
        num_iterations=50, step_size=0.01, mini_batch_fraction=1.0,
        reg_param=0.01, loss="logistic",
    )
    w32 = sgd.train_linear(x.astype(np.float32), y.astype(np.float32), cfg)
    np.testing.assert_allclose(w32, w64, rtol=0, atol=1e-6)


def test_convergence_tol_zero_disables_early_stop():
    rng = np.random.RandomState(7)
    x = rng.randn(32, 4) * 0.01
    y = (rng.rand(32) > 0.5).astype(np.float64)
    w_stop, _, iters = mllib_oracle.run_gradient_descent(
        x, y, loss="logistic", step_size=0.01, num_iterations=50,
    )
    w_full, _, iters_full = mllib_oracle.run_gradient_descent(
        x, y, loss="logistic", step_size=0.01, num_iterations=50,
        convergence_tol=0.0,
    )
    assert iters < iters_full == 50
    cfg = sgd.SGDConfig(
        num_iterations=50, step_size=0.01, loss="logistic",
        convergence_tol=0.0,
    )
    w32 = sgd.train_linear(x.astype(np.float32), y.astype(np.float32), cfg)
    np.testing.assert_allclose(w32, w_full, rtol=0, atol=1e-6)
    assert float(np.linalg.norm(w_stop - w_full)) > 0


def test_strict_threshold_at_zero_margin():
    """MLlib predicts 0.0 at exactly the threshold (strict >): an
    all-zero weight vector classifies everything as 0.0."""
    f = np.eye(3, dtype=np.float64)
    assert mllib_oracle.predict_logreg(f, np.zeros(3)).tolist() == [0, 0, 0]
    assert mllib_oracle.predict_svm(f, np.zeros(3)).tolist() == [0, 0, 0]
    clf = linear.LogisticRegressionClassifier()
    clf.weights = np.zeros(3, dtype=np.float32)
    assert clf.predict(f).tolist() == [0.0, 0.0, 0.0]


@pytest.mark.parametrize(
    "name,expected_preds,expected_acc",
    [
        ("dt", [0.0, 1.0, 1.0, 1.0], 0.75),
        ("dt-tpu", [0.0, 1.0, 1.0, 1.0], 0.75),
        ("rf", [0.0, 0.0, 0.0, 0.0], 0.5),
        ("rf-tpu", [0.0, 0.0, 0.0, 0.0], 0.5),
        ("gbt", [0.0, 1.0, 1.0, 1.0], 0.75),
    ],
)
def test_tree_families_fixture_regression(fixture_split, name,
                                          expected_preds, expected_acc):
    """The reference's commented-out ClassifierTest test3/test4 shape
    (default-config tree classifiers on the fixture split): no
    reference accuracy exists to match, so these pin OUR deterministic
    results as regression goldens — and the device-native tree
    implementations must agree with the host ones."""
    from eeg_dataanalysispackage_tpu.models import registry

    ftr, ttr, fte, tte = fixture_split
    clf = registry.create(name)
    clf.set_config({})
    clf.fit(ftr, ttr)
    preds = (np.asarray(clf.predict(fte)) > 0.5).astype(np.float64)
    assert preds.tolist() == expected_preds
    assert float((preds == tte).mean()) == expected_acc


# -- sampled-path statistical equivalence (miniBatchFraction < 1) -----
#
# The device engine folds the iteration into a JAX PRNG key while
# Spark seeds a per-partition XORShift with 42+t (models/sgd.py), so
# individual sampled trajectories are not bit-comparable — the claim
# carried on trust until round 3 was that they are *statistically*
# equivalent. These tests quantify it: a 20-seed sweep of the device
# engine vs the f64 oracle's sampled emulation (numpy PRNG, same
# Bernoulli process) must produce the same outcome distribution.


def _sweep_dataset():
    rng = np.random.RandomState(0)
    n, d = 200, 48
    w_true = rng.randn(d)
    x = rng.randn(n, d).astype(np.float32)
    margin = x @ w_true * 0.3
    y = (1.0 / (1.0 + np.exp(-margin)) > rng.rand(n)).astype(np.float64)
    return x, y


@pytest.mark.parametrize("loss", ["logistic", "hinge"])
def test_sampled_sgd_seed_sweep_matches_oracle_distribution(loss):
    """mini_batch_fraction=0.5, 20 seeds each: final weight-norm and
    accuracy distributions of the device engine and the oracle's
    sampled emulation agree in mean (2% / 0.03) and spread (std ratio
    within [0.4, 2.5]). Calibrated against measured agreement of
    ~0.1% mean-norm and ~0.3% mean-accuracy deviation."""
    x, y = _sweep_dataset()
    seeds = range(20)

    dev_norms, dev_accs, ora_norms, ora_accs = [], [], [], []
    for s in seeds:
        w_dev = sgd.train_linear(
            x, y,
            sgd.SGDConfig(
                num_iterations=30, mini_batch_fraction=0.5, seed=s,
                reg_param=0.01, loss=loss,
            ),
        )
        dev_norms.append(float(np.linalg.norm(w_dev)))
        dev_accs.append(float(((x @ w_dev > 0) == (y > 0.5)).mean()))

        w_ora, _, _ = mllib_oracle.run_gradient_descent(
            x, y, loss=loss, num_iterations=30,
            mini_batch_fraction=0.5, seed=s, reg_param=0.01,
        )
        ora_norms.append(float(np.linalg.norm(w_ora)))
        ora_accs.append(float(((x @ w_ora > 0) == (y > 0.5)).mean()))

    dev_norms, ora_norms = np.array(dev_norms), np.array(ora_norms)
    dev_accs, ora_accs = np.array(dev_accs), np.array(ora_accs)

    norm_rel = abs(dev_norms.mean() - ora_norms.mean()) / ora_norms.mean()
    assert norm_rel < 0.02, (
        f"mean weight-norm diverges: device {dev_norms.mean():.4f} vs "
        f"oracle {ora_norms.mean():.4f} ({norm_rel:.1%})"
    )
    assert abs(dev_accs.mean() - ora_accs.mean()) < 0.03, (
        f"mean accuracy diverges: device {dev_accs.mean():.4f} vs "
        f"oracle {ora_accs.mean():.4f}"
    )
    # same spread scale: the engines sample the same Bernoulli process
    ratio = (dev_norms.std() + 1e-12) / (ora_norms.std() + 1e-12)
    assert 0.4 < ratio < 2.5, (
        f"weight-norm spread mismatch: device std {dev_norms.std():.5f} "
        f"vs oracle std {ora_norms.std():.5f}"
    )
    # sampling must actually vary the outcome (guards against a
    # vacuous pass where both paths silently run full-batch)
    assert dev_norms.std() > 0 and ora_norms.std() > 0


def test_sampled_oracle_empty_iterations_leave_weights_unchanged():
    """MLlib semantics: a sampled-empty iteration performs no update.
    With a fraction tiny enough that every draw over 8 rows is empty,
    the oracle must return zero weights (and run all iterations)."""
    x = np.ones((8, 4))
    y = np.ones(8)
    w, history, it = mllib_oracle.run_gradient_descent(
        x, y, loss="logistic", num_iterations=5,
        mini_batch_fraction=1e-12, seed=3, reg_param=0.0,
    )
    assert np.all(w == 0.0)
    assert history == []
    assert it == 5


def test_full_batch_path_is_seed_invariant():
    """fraction=1.0 must ignore the seed entirely (deterministic
    treeAggregate order) — on device and in the oracle."""
    x, y = _sweep_dataset()
    w_a = sgd.train_linear(
        x, y, sgd.SGDConfig(num_iterations=10, seed=1)
    )
    w_b = sgd.train_linear(
        x, y, sgd.SGDConfig(num_iterations=10, seed=99)
    )
    np.testing.assert_array_equal(w_a, w_b)
    o_a, _, _ = mllib_oracle.run_gradient_descent(
        x, y, loss="logistic", num_iterations=10, reg_param=0.0, seed=1
    )
    o_b, _, _ = mllib_oracle.run_gradient_descent(
        x, y, loss="logistic", num_iterations=10, reg_param=0.0, seed=99
    )
    np.testing.assert_array_equal(o_a, o_b)
