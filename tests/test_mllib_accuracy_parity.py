"""MLlib accuracy parity on the reference fixture (ClassifierTest.java).

Reproduces ``ClassifierTest.java:98-105`` exactly with the shipped
fixture: ``infoTrain.txt`` -> 11 epochs, ``WaveletTransform(8, 512,
175, 16)`` features, ``Collections.shuffle(new Random(1))``, 70/30
split (7 train / 4 test), then the default-constructor MLlib paths
(``new LogisticRegressionWithSGD().run(rdd)`` /
``new SVMWithSGD().run(rdd)``: step 1.0, 100 iterations, regParam
0.01, full batch, convergenceTol 1e-3, zero init, no intercept).

About the reference's informal pin 0.6415094339622641
(``ClassifierTest.java:105``, commented out in the reference itself):
that value is 34/53, which requires a 53-point test split — i.e. a
~177-epoch corpus. The corpus shipped in ``test-data/`` yields 11
epochs, so the largest reachable test split is 4 points and every
achievable accuracy is a multiple of 0.25; 0.6415... is unreachable
from the shipped data under ANY classifier. The assert was written
against a private corpus (per ``Const.java`` the disabled
``DIRECTORIES`` lists of school recordings) that the reference does
not distribute. The reproducible contract is therefore the exact
float64 trajectory of MLlib's deterministic full-batch path on the
shipped fixture (``models/mllib_oracle.py``), pinned below, with the
production f32 XLA engine asserted to agree prediction-for-prediction.
"""

import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.features import wavelet
from eeg_dataanalysispackage_tpu.io import provider
from eeg_dataanalysispackage_tpu.models import linear, mllib_oracle, sgd
from eeg_dataanalysispackage_tpu.utils import java_compat


@pytest.fixture(scope="module")
def fixture_split(fixture_dir):
    batch = provider.OfflineDataProvider(
        [fixture_dir + "/infoTrain.txt"]
    ).load()
    fe = wavelet.WaveletTransform(8, 512, 175, 16, backend="host")
    feats = fe.extract_batch(batch.epochs)  # float64 bit-parity path
    perm = java_compat.java_shuffle_indices(len(batch.targets), seed=1)
    f = feats[perm]
    t = np.asarray(batch.targets, dtype=np.float64)[perm]
    n_train = int(len(t) * 0.7)  # (int)(11*0.7) == 7
    return f[:n_train], t[:n_train], f[n_train:], t[n_train:]


def test_logreg_default_path_oracle_accuracy(fixture_split):
    ftr, ttr, fte, tte = fixture_split
    assert ftr.shape == (7, 48) and fte.shape == (4, 48)
    w, _, iters = mllib_oracle.run_gradient_descent(ftr, ttr, loss="logistic")
    # no early convergence on this fixture: all 100 iterations run
    assert iters == 100
    preds = mllib_oracle.predict_logreg(fte, w)
    acc = float((preds == tte).mean())
    # The deterministic full-batch trajectory on the SHIPPED corpus:
    # all four test points predicted 0.0 -> accuracy 2/4. The
    # reference's 0.6415094339622641 (= 34/53) needs a 53-point test
    # split and is unreachable from the shipped 11-epoch fixture.
    assert preds.tolist() == [0.0, 0.0, 0.0, 0.0]
    assert acc == 0.5
    # trajectory fingerprint, full f64 precision
    assert float(np.linalg.norm(w)) == pytest.approx(
        1.0861711073763858, abs=1e-15
    )


def test_svm_default_path_oracle_accuracy(fixture_split):
    ftr, ttr, fte, tte = fixture_split
    w, _, iters = mllib_oracle.run_gradient_descent(ftr, ttr, loss="hinge")
    assert iters == 100
    preds = mllib_oracle.predict_svm(fte, w)
    assert preds.tolist() == [0.0, 0.0, 0.0, 0.0]
    assert float((preds == tte).mean()) == 0.5
    assert float(np.linalg.norm(w)) == pytest.approx(
        1.9602503911207547, abs=1e-15
    )


@pytest.mark.parametrize(
    "cls,oracle_pred,loss",
    [
        (linear.LogisticRegressionClassifier, mllib_oracle.predict_logreg,
         "logistic"),
        (linear.SVMClassifier, mllib_oracle.predict_svm, "hinge"),
    ],
)
def test_device_f32_path_agrees_with_oracle(fixture_split, cls, oracle_pred,
                                            loss):
    """The production one-scan XLA engine (f32) must reproduce the
    oracle's predictions and weights on the fixture."""
    ftr, ttr, fte, tte = fixture_split
    w64, _, _ = mllib_oracle.run_gradient_descent(ftr, ttr, loss=loss)

    clf = cls()
    clf.set_config({})  # default branch, like ClassifierTest
    clf.fit(ftr, ttr)
    np.testing.assert_allclose(clf.weights, w64, rtol=0, atol=5e-5)
    preds = clf.predict(fte)
    assert preds.tolist() == oracle_pred(fte, w64).tolist()
    assert float((preds == tte).mean()) == 0.5


def test_convergence_early_stop_matches_oracle():
    """MLlib's convergenceTol early stop: engineered data where the
    trajectory converges before num_iterations; the f32 engine must
    freeze at the same iteration as the f64 oracle."""
    rng = np.random.RandomState(7)
    x = rng.randn(32, 4) * 0.01  # tiny margins -> tiny steps
    y = (rng.rand(32) > 0.5).astype(np.float64)
    w64, _, iters = mllib_oracle.run_gradient_descent(
        x, y, loss="logistic", step_size=0.01, num_iterations=50,
        reg_param=0.01,
    )
    assert iters < 50  # the early stop actually fired
    cfg = sgd.SGDConfig(
        num_iterations=50, step_size=0.01, mini_batch_fraction=1.0,
        reg_param=0.01, loss="logistic",
    )
    w32 = sgd.train_linear(x.astype(np.float32), y.astype(np.float32), cfg)
    np.testing.assert_allclose(w32, w64, rtol=0, atol=1e-6)


def test_convergence_tol_zero_disables_early_stop():
    rng = np.random.RandomState(7)
    x = rng.randn(32, 4) * 0.01
    y = (rng.rand(32) > 0.5).astype(np.float64)
    w_stop, _, iters = mllib_oracle.run_gradient_descent(
        x, y, loss="logistic", step_size=0.01, num_iterations=50,
    )
    w_full, _, iters_full = mllib_oracle.run_gradient_descent(
        x, y, loss="logistic", step_size=0.01, num_iterations=50,
        convergence_tol=0.0,
    )
    assert iters < iters_full == 50
    cfg = sgd.SGDConfig(
        num_iterations=50, step_size=0.01, loss="logistic",
        convergence_tol=0.0,
    )
    w32 = sgd.train_linear(x.astype(np.float32), y.astype(np.float32), cfg)
    np.testing.assert_allclose(w32, w_full, rtol=0, atol=1e-6)
    assert float(np.linalg.norm(w_stop - w_full)) > 0


def test_strict_threshold_at_zero_margin():
    """MLlib predicts 0.0 at exactly the threshold (strict >): an
    all-zero weight vector classifies everything as 0.0."""
    f = np.eye(3, dtype=np.float64)
    assert mllib_oracle.predict_logreg(f, np.zeros(3)).tolist() == [0, 0, 0]
    assert mllib_oracle.predict_svm(f, np.zeros(3)).tolist() == [0, 0, 0]
    clf = linear.LogisticRegressionClassifier()
    clf.weights = np.zeros(3, dtype=np.float32)
    assert clf.predict(f).tolist() == [0.0, 0.0, 0.0]


@pytest.mark.parametrize(
    "name,expected_preds,expected_acc",
    [
        ("dt", [0.0, 1.0, 1.0, 1.0], 0.75),
        ("dt-tpu", [0.0, 1.0, 1.0, 1.0], 0.75),
        ("rf", [0.0, 0.0, 0.0, 0.0], 0.5),
        ("rf-tpu", [0.0, 0.0, 0.0, 0.0], 0.5),
        ("gbt", [0.0, 1.0, 1.0, 1.0], 0.75),
    ],
)
def test_tree_families_fixture_regression(fixture_split, name,
                                          expected_preds, expected_acc):
    """The reference's commented-out ClassifierTest test3/test4 shape
    (default-config tree classifiers on the fixture split): no
    reference accuracy exists to match, so these pin OUR deterministic
    results as regression goldens — and the device-native tree
    implementations must agree with the host ones."""
    from eeg_dataanalysispackage_tpu.models import registry

    ftr, ttr, fte, tte = fixture_split
    clf = registry.create(name)
    clf.set_config({})
    clf.fit(ftr, ttr)
    preds = (np.asarray(clf.predict(fte)) > 0.5).astype(np.float64)
    assert preds.tolist() == expected_preds
    assert float((preds == tte).mean()) == expected_acc
