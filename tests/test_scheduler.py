"""Multi-tenant plan executor suite (ISSUE 10).

The acceptance pins:

- **IR parity** — a query run through ``ExecutionPlan`` +
  ``PlanExecutor`` produces statistics byte-identical to the (now
  shimmed) ``PipelineBuilder.execute`` path;
- **fault isolation** — a ``faults=``-injected failing plan and a
  forced mesh-unavailable plan run concurrently with a clean plan
  whose statistics, metrics scope, and run report are identical to a
  solo run;
- **crash-only** — SIGKILL mid-batch, restart, journal recovery
  resumes every unfinished plan to byte-identical statistics and
  never re-runs a completed one;
- **admission** — bounded queue, shed-with-evidence, queued-deadline
  fail-fast (the serve/batcher machinery, reused);
- **chaos** — the new ``scheduler.plan``/``scheduler.journal`` points:
  a p=0.2 soak over 8 concurrent plans resolves every plan with
  clean-twin statistics;
- **cross-tenant circuit evidence** — plan B fast-fails on an endpoint
  plan A opened, and both plans' crash reports name A as the
  contributor.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest

import _synthetic
from eeg_dataanalysispackage_tpu import obs
from eeg_dataanalysispackage_tpu.io import circuit, deadline as deadline_mod
from eeg_dataanalysispackage_tpu.io import remote
from eeg_dataanalysispackage_tpu.obs import chaos, domain as run_domain
from eeg_dataanalysispackage_tpu.pipeline import builder
from eeg_dataanalysispackage_tpu.scheduler import (
    PlanExecutor,
    PlanFailedError,
    PlanShedError,
)
from eeg_dataanalysispackage_tpu.scheduler import runtime as runtime_mod

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_ambient():
    """No leaked chaos plan or fault domain across tests — the same
    hygiene contract test_chaos pins for the global plan, extended to
    the domain stack."""
    assert chaos.active_plan() is None
    assert run_domain.current() is None
    yield
    chaos.uninstall()
    assert run_domain.current() is None


@pytest.fixture()
def session(tmp_path):
    return _synthetic.write_session(str(tmp_path), n_markers=60)


def _q(info, extra="", clf="logreg"):
    return (
        f"info_file={info}&fe=dwt-8&train_clf={clf}"
        "&config_step_size=1.0&config_num_iterations=20"
        "&config_mini_batch_fraction=1.0" + extra
    )


def _counters(result):
    """The plan's ISOLATED per-run counters (its domain's metrics
    child)."""
    return result.builder.run_metrics.snapshot()["counters"]


# -- IR parity ---------------------------------------------------------


def test_executor_matches_direct_builder(session, tmp_path):
    direct = builder.PipelineBuilder(_q(session)).execute()
    with PlanExecutor(max_concurrent=2) as ex:
        result = ex.submit(_q(session)).result(timeout=300)
    assert str(result.statistics) == str(direct)
    assert result.plan_id == "p0001"
    assert result.attempts == 1
    assert not result.recovered


def test_executor_fused_parity(session):
    fused_q = _q(session).replace("fe=dwt-8", "fe=dwt-8-fused")
    direct = builder.PipelineBuilder(fused_q).execute()
    with PlanExecutor() as ex:
        result = ex.submit(fused_q).result(timeout=300)
    assert str(result.statistics) == str(direct)


def test_invalid_query_rejected_before_journal(session, tmp_path):
    """Parse/validation errors surface at submit() and never touch
    the journal or the queue."""
    with PlanExecutor(journal_dir=str(tmp_path / "j")) as ex:
        with pytest.raises(ValueError, match="Missing classifier"):
            ex.submit(f"info_file={session}&fe=dwt-8")
        assert ex.journal.entries() == []


# -- admission control (the reused serve/batcher machinery) ------------


def test_shed_with_evidence(monkeypatch, session, tmp_path):
    release = threading.Event()

    def blocked_execute(plan, builder_, plan_id=None, fault_plan=None,
                        default_report_dir=None, gateway=None, **kw):
        assert release.wait(30), "test never released the worker"
        return f"done-{plan_id}"

    monkeypatch.setattr(runtime_mod, "execute_plan", blocked_execute)
    ex = PlanExecutor(
        max_concurrent=1, queue_depth=1,
        journal_dir=str(tmp_path / "j"),
    )
    try:
        h1 = ex.submit(_q(session))
        # the single worker pops h1 and blocks; h2 fills the depth-1
        # queue; h3 must shed AT THE DOOR with evidence
        deadline = time.monotonic() + 5.0
        while len(ex.queue) != 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        h2 = ex.submit(_q(session))
        with pytest.raises(
            PlanShedError, match="shed at admission.*depth 1"
        ):
            ex.submit(_q(session))
        # the shed is journaled as terminal evidence, never queued
        entry = ex.journal.entry("p0003")
        assert entry["state"] == "failed"
        assert "shed at admission" in entry["error"]
        release.set()
        assert h1.result(timeout=30).statistics == "done-p0001"
        assert h2.result(timeout=30).statistics == "done-p0002"
    finally:
        release.set()
        ex.close()


def test_queued_deadline_fails_fast(monkeypatch, session):
    release = threading.Event()

    def blocked_execute(plan, builder_, plan_id=None, fault_plan=None,
                        default_report_dir=None, gateway=None, **kw):
        assert release.wait(30)
        return f"done-{plan_id}"

    monkeypatch.setattr(runtime_mod, "execute_plan", blocked_execute)
    ex = PlanExecutor(max_concurrent=1, queue_depth=4)
    try:
        ex.submit(_q(session))  # blocks the worker
        h2 = ex.submit(_q(session), deadline_s=0.05)
        time.sleep(0.2)  # h2's budget dies in the queue
        release.set()
        with pytest.raises(
            deadline_mod.DeadlineExceededError, match="never executed"
        ):
            h2.result(timeout=30)
    finally:
        release.set()
        ex.close()


# -- retries + the scheduler.plan chaos point --------------------------


def test_scheduler_plan_chaos_absorbed_by_retry(session):
    clean = builder.PipelineBuilder(_q(session)).execute()
    before = obs.metrics.snapshot()["counters"]
    with PlanExecutor(max_attempts=3) as ex:
        result = ex.submit(
            _q(session, "&faults=scheduler.plan:once@1")
        ).result(timeout=300)
    assert str(result.statistics) == str(clean)
    assert result.attempts == 2  # attempt 1 chaos-failed, 2 clean
    after = obs.metrics.snapshot()["counters"]
    assert (
        after.get("chaos.fired.scheduler.plan", 0)
        - before.get("chaos.fired.scheduler.plan", 0)
    ) == 1
    assert (
        after.get("scheduler.retries", 0)
        - before.get("scheduler.retries", 0)
    ) == 1


def test_retry_budget_exhaustion_fails_with_history(session, tmp_path):
    with PlanExecutor(
        max_attempts=2, journal_dir=str(tmp_path / "j")
    ) as ex:
        h = ex.submit(_q(session, "&faults=scheduler.plan:every@1"))
        with pytest.raises(PlanFailedError, match="attempt 2"):
            h.result(timeout=300)
        entry = ex.journal.entry(h.plan_id)
    assert entry["state"] == "failed"
    assert entry["attempts"] == 2
    assert "retry budget" in entry["error"]


def test_journal_chaos_degrades_to_unjournaled(session, tmp_path):
    """scheduler.journal faults on EVERY write (both the in-journal
    retry attempts): the plan still completes with clean statistics —
    the journal records the run, it cannot kill it."""
    clean = builder.PipelineBuilder(_q(session)).execute()
    before = obs.metrics.snapshot()["counters"]
    with PlanExecutor(journal_dir=str(tmp_path / "j")) as ex:
        result = ex.submit(
            _q(session, "&faults=scheduler.journal:every@1")
        ).result(timeout=300)
        assert ex.journal.entries() == []  # every write degraded
    assert str(result.statistics) == str(clean)
    after = obs.metrics.snapshot()["counters"]
    assert (
        after.get("scheduler.journal_write_failed", 0)
        - before.get("scheduler.journal_write_failed", 0)
    ) >= 2


# -- the fault-isolation pin -------------------------------------------


def test_concurrent_fault_domains_are_isolated(session, tmp_path):
    """A chaos-degraded plan and a mesh-unavailable plan run
    concurrently with a clean plan; the clean plan's statistics,
    per-plan metrics scope, degradation history, and run report are
    identical to its solo run — fault domains don't leak."""
    clean_q = _q(session).replace("fe=dwt-8", "fe=dwt-8-fused-block")
    # dedup=false: this pin exercises the chaos firing INSIDE the
    # faulted plan's own ingest — prefix dedup would (correctly) let
    # it follow the clean plan's build and absorb the fault by never
    # reaching it (that interplay is pinned in tests/test_dedup.py)
    faulted_q = clean_q + "&faults=ingest.fused:once@1&dedup=false"
    # more devices than any host here has: mesh-unavailable -> the
    # ladder's top rung degrades to single-device, recorded
    mesh_q = _q(session, "&devices=64")

    with PlanExecutor(
        max_concurrent=3, report_root=str(tmp_path / "solo")
    ) as ex:
        solo = ex.submit(clean_q).result(timeout=300)
    solo_report = json.load(
        open(tmp_path / "solo" / solo.plan_id / "run_report.json")
    )

    with PlanExecutor(
        max_concurrent=3, report_root=str(tmp_path / "multi")
    ) as ex:
        h_clean = ex.submit(clean_q)
        h_fault = ex.submit(faulted_q)
        h_mesh = ex.submit(mesh_q)
        clean = h_clean.result(timeout=300)
        faulted = h_fault.result(timeout=300)
        meshed = h_mesh.result(timeout=300)

    # every plan resolved with the SAME statistics (chaos absorbed by
    # the ladder, mesh-unavailable degraded to the single-device path)
    assert str(clean.statistics) == str(solo.statistics)
    assert str(faulted.statistics) == str(solo.statistics)
    host_clean = builder.PipelineBuilder(_q(session)).execute()
    assert str(meshed.statistics) == str(host_clean)

    # the clean plan's ISOLATED telemetry shows no trace of its
    # neighbours' faults
    cc = _counters(clean)
    assert cc.get("pipeline.degraded", 0) == 0
    assert cc.get("pipeline.mesh_unavailable", 0) == 0
    assert not any(k.startswith("chaos.fired") for k in cc)
    assert clean.builder.degradation_history == []
    assert clean.builder.mesh_resolved is None

    # the faulted plan degraded INSIDE its own domain
    fc = _counters(faulted)
    assert fc.get("pipeline.degraded", 0) == 1
    assert fc.get("chaos.fired.ingest.fused", 0) == 1
    assert faulted.builder.degradation_history

    # the mesh plan degraded its mesh rung without touching anyone
    mc = _counters(meshed)
    assert mc.get("pipeline.mesh_unavailable", 0) == 1
    assert meshed.builder.mesh_resolved["rung"] == "single_device"
    assert "error" in meshed.builder.mesh_resolved
    assert clean.builder.run_metrics is not faulted.builder.run_metrics

    # run-report pin: the concurrent clean report tells the solo story
    clean_report = json.load(
        open(tmp_path / "multi" / clean.plan_id / "run_report.json")
    )
    assert (
        clean_report["statistics_sha256"]
        == solo_report["statistics_sha256"]
    )
    assert clean_report["degradation"] == []
    assert clean_report["chaos"] is None
    assert clean_report["mesh"] is None
    assert clean_report["plan_id"] == clean.plan_id
    # and the faulted neighbour's report carries ITS chaos accounting
    fault_report = json.load(
        open(tmp_path / "multi" / faulted.plan_id / "run_report.json")
    )
    assert fault_report["chaos"]["rules"]["ingest.fused"]["fired"] == 1
    assert fault_report["degradation"]


# -- the concurrent-plan chaos soak (satellite) ------------------------


@pytest.mark.chaos
def test_chaos_soak_eight_concurrent_plans(session):
    """p=0.2 scheduler.plan + scheduler.journal faults on 8 concurrent
    plans: every plan resolves and every plan's statistics equal the
    clean twin's."""
    clean = builder.PipelineBuilder(_q(session)).execute()
    with PlanExecutor(max_concurrent=4, max_attempts=6) as ex:
        handles = [
            ex.submit(_q(
                session,
                "&faults=scheduler.plan:p=0.2;scheduler.journal:p=0.2"
                f"&faults_seed={i}",
            ))
            for i in range(8)
        ]
        results = [h.result(timeout=600) for h in handles]
    assert len(results) == 8
    for r in results:
        assert str(r.statistics) == str(clean)
    # the soak genuinely injected (deterministic seeds; seed sweep
    # chosen so at least one plan retried)
    assert any(r.attempts > 1 for r in results)


# -- crash-only recovery (SIGKILL) -------------------------------------

_CRASH_CHILD = """
import os, signal, sys

sys.path.insert(0, {repo!r})
from eeg_dataanalysispackage_tpu.scheduler import PlanExecutor

journal_dir, qa, qb, qc = sys.argv[1:5]
ex = PlanExecutor(max_concurrent=1, journal_dir=journal_dir)
ex.submit(qa).result(timeout=600)   # plan 1 COMPLETES before the kill
ex.submit(qb)                        # plan 2: mid-batch or queued
ex.submit(qc)                        # plan 3: queued
os.kill(os.getpid(), signal.SIGKILL)
"""


@pytest.mark.chaos
def test_sigkill_recovery_resumes_unfinished_exactly_once(
    session, tmp_path
):
    """kill -9 mid-batch -> restart -> the journal resumes every
    unfinished plan to statistics byte-identical to uninterrupted
    twins; the completed plan's record is untouched and it is not
    re-run."""
    journal_dir = str(tmp_path / "journal")
    qa = _q(session)

    # B and C train long enough (fresh compile at the new static
    # iteration count + ~1.5e5 steps) that the child CANNOT finish
    # them in the instants between submit and SIGKILL — the kill is
    # genuinely mid-batch
    def _slow(step):
        return (
            f"info_file={session}&fe=dwt-8&train_clf=logreg"
            f"&config_step_size={step}&config_num_iterations=150000"
            "&config_mini_batch_fraction=1.0"
        )

    qb, qc = _slow("0.5"), _slow("0.25")

    child = tmp_path / "crash_child.py"
    child.write_text(_CRASH_CHILD.format(repo=_REPO))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, str(child), journal_dir, qa, qb, qc],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == -signal.SIGKILL, proc.stderr[-2000:]

    # the write-ahead journal survived the kill: 1 completed, 2
    # unfinished
    ex = PlanExecutor(max_concurrent=2, journal_dir=journal_dir)
    states = {
        e["plan_id"]: e["state"] for e in ex.journal.entries()
    }
    assert states["p0001"] == "completed"
    assert states["p0002"] == "submitted"
    assert states["p0003"] == "submitted"
    completed_record_before = open(
        os.path.join(journal_dir, "plan-p0001.json")
    ).read()

    # uninterrupted twins, run directly in THIS process
    twins = {
        q: str(builder.PipelineBuilder(q).execute())
        for q in (qa, qb, qc)
    }

    recovery = ex.recover()
    try:
        assert [e["plan_id"] for e in recovery["completed"]] == ["p0001"]
        assert recovery["failed"] == []
        resumed = {
            h.query: h.result(timeout=600)
            for h in recovery["resumed"]
        }
    finally:
        ex.close()
    assert set(resumed) == {qb, qc}
    for q, result in resumed.items():
        assert str(result.statistics) == twins[q], q
        assert result.recovered

    # exactly-once completion: the dead process's completed record is
    # byte-untouched (never re-run, never re-recorded) and carries the
    # twin statistics
    assert open(
        os.path.join(journal_dir, "plan-p0001.json")
    ).read() == completed_record_before
    assert recovery["completed"][0]["statistics"] == twins[qa]
    # the journal is now fully terminal
    ex2 = PlanExecutor(journal_dir=journal_dir)
    assert ex2.journal.unfinished() == []
    ex2.close()


# -- shared-cache single flight across plans (satellite) ---------------


def test_concurrent_plans_single_flight_feature_cache(
    session, tmp_path, monkeypatch
):
    """Two plans missing the same feature-cache entry: exactly one
    rebuild is KEPT (one store), the loser blocks on the single-flight
    guard and hits, and both plans' statistics are identical."""
    monkeypatch.delenv("EEG_TPU_NO_FEATURE_CACHE", raising=False)
    monkeypatch.setenv(
        "EEG_TPU_FEATURE_CACHE_DIR", str(tmp_path / "fc")
    )
    # dedup=false: prefix dedup sits ABOVE the feature cache and
    # would satisfy the second plan before it ever looks the entry up
    # (pinned in tests/test_dedup.py); this pin is about the cache's
    # own single-flight seam
    q = _q(session).replace("fe=dwt-8", "fe=dwt-8-fused") + "&dedup=false"
    before = obs.metrics.snapshot()["counters"]
    with PlanExecutor(max_concurrent=2) as ex:
        h1 = ex.submit(q)
        h2 = ex.submit(q)
        r1 = h1.result(timeout=300)
        r2 = h2.result(timeout=300)
    after = obs.metrics.snapshot()["counters"]
    assert str(r1.statistics) == str(r2.statistics)
    assert (
        after.get("feature_cache.store", 0)
        - before.get("feature_cache.store", 0)
    ) == 1
    assert (
        after.get("feature_cache.hit", 0)
        - before.get("feature_cache.hit", 0)
    ) >= 1


# -- cross-tenant circuit-breaker evidence (satellite) -----------------


@pytest.mark.chaos
def test_circuit_evidence_names_the_opening_plan(tmp_path, monkeypatch):
    """io/circuit state is process-global per endpoint BY DESIGN: plan
    B fast-fails on an endpoint plan A opened. Pinned here: B's
    failure (and both crash reports) name plan A's id as the
    contributor of the opening evidence."""
    monkeypatch.setenv("EEG_TPU_CIRCUIT_THRESHOLD", "1")
    monkeypatch.setenv("EEG_TPU_CIRCUIT_COOLDOWN", "600")
    circuit.reset()
    # a port nothing listens on: connection refused, fast
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    fs = remote.HttpFileSystem(
        retry=remote.RetryPolicy(
            max_attempts=2, timeout_s=2.0, backoff_s=0.01
        )
    )
    dead = f"http://127.0.0.1:{port}/info.txt"
    q = f"info_file={dead}&fe=dwt-8&train_clf=logreg"
    try:
        with PlanExecutor(
            max_concurrent=1, max_attempts=1, filesystem=fs,
            report_root=str(tmp_path / "reports"),
        ) as ex:
            ha = ex.submit(q)
            with pytest.raises(PlanFailedError):
                ha.result(timeout=120)
            hb = ex.submit(q)
            with pytest.raises(PlanFailedError) as excinfo:
                hb.result(timeout=120)
        # B's fast-fail carries A's tagged evidence
        assert "circuit open" in str(excinfo.value)
        assert "[plan p0001]" in str(excinfo.value)
        snap = circuit.snapshot()
        entry = next(iter(snap.values()))
        assert entry["state"] == "open"
        assert entry["contributing_plans"] == ["p0001"]
        # both tenants' crash reports embed the circuit block naming A
        for plan_id in ("p0001", "p0002"):
            crash = json.load(open(
                tmp_path / "reports" / plan_id / "crash_report.json"
            ))
            block = next(iter(crash["circuit"].values()))
            assert block["contributing_plans"] == ["p0001"]
    finally:
        circuit.reset()


# -- review-round regressions ------------------------------------------


def test_recovery_never_sheds(session, tmp_path):
    """Journal recovery re-admits past the depth check (the batcher's
    readmit rule): a backlog bigger than queue_depth must resume
    every unfinished plan, not mark the overflow terminally failed."""
    from eeg_dataanalysispackage_tpu.scheduler import PlanJournal

    journal_dir = str(tmp_path / "j")
    journal = PlanJournal(journal_dir)
    queries = {
        f"p{i:04d}": _q(session, f"&config_step_size={1.0 / i}")
        for i in range(1, 5)
    }
    for pid, q in queries.items():
        journal.record_submitted(pid, q)
    ex = PlanExecutor(
        max_concurrent=1, queue_depth=1, journal_dir=journal_dir
    )
    try:
        recovery = ex.recover()
        assert len(recovery["resumed"]) == 4  # depth 1 did not shed
        results = {
            h.plan_id: h.result(timeout=300)
            for h in recovery["resumed"]
        }
    finally:
        ex.close()
    twins = {
        pid: str(builder.PipelineBuilder(q).execute())
        for pid, q in queries.items()
    }
    for pid, r in results.items():
        assert str(r.statistics) == twins[pid]
    assert ex.journal.unfinished() == []


def test_closed_executor_refuses_submissions(session):
    from eeg_dataanalysispackage_tpu.serve.batcher import (
        ServiceClosedError,
    )

    ex = PlanExecutor(max_concurrent=1)
    ex.start()
    ex.close()
    with pytest.raises(ServiceClosedError, match="closed"):
        ex.submit(_q(session))


def test_new_executor_ids_never_clobber_a_journal(session, tmp_path):
    """A fresh executor over an existing journal seeds its id counter
    PAST the journal's records: submitting before (or without)
    recover() cannot mint a dead process's id and overwrite its
    exactly-once completion record."""
    journal_dir = str(tmp_path / "j")
    with PlanExecutor(journal_dir=journal_dir) as ex1:
        r1 = ex1.submit(_q(session)).result(timeout=300)
    record_before = open(
        os.path.join(journal_dir, f"plan-{r1.plan_id}.json")
    ).read()
    with PlanExecutor(journal_dir=journal_dir) as ex2:
        r2 = ex2.submit(_q(session)).result(timeout=300)
    assert r2.plan_id != r1.plan_id
    assert open(
        os.path.join(journal_dir, f"plan-{r1.plan_id}.json")
    ).read() == record_before


def test_close_fails_abandoned_queued_handles(monkeypatch, session):
    """close() must resolve every admitted future: a queued plan the
    workers never popped fails with ServiceClosedError instead of
    blocking its caller forever."""
    from eeg_dataanalysispackage_tpu.serve.batcher import (
        ServiceClosedError,
    )

    release = threading.Event()

    def blocked_execute(plan, builder_, plan_id=None, fault_plan=None,
                        default_report_dir=None, gateway=None, **kw):
        assert release.wait(30)
        return f"done-{plan_id}"

    monkeypatch.setattr(runtime_mod, "execute_plan", blocked_execute)
    ex = PlanExecutor(max_concurrent=1, queue_depth=4)
    h1 = ex.submit(_q(session))  # blocks the worker
    h2 = ex.submit(_q(session))  # queued, never popped
    # stop BEFORE releasing: the worker finishes h1 and exits at the
    # next loop check without ever popping h2 — deterministic
    ex._stop.set()
    release.set()
    ex.close()
    assert h1.result(timeout=30).statistics == "done-p0001"
    with pytest.raises(ServiceClosedError, match="abandoned"):
        h2.result(timeout=30)


def test_journal_entries_numeric_order_past_9999(tmp_path):
    """entries() sorts by the NUMERIC plan id: once the zero-padded
    counter outgrows 4 digits, 'plan-p10000' must not sort before
    'plan-p9999' (recovery resumes in submission order)."""
    from eeg_dataanalysispackage_tpu.scheduler import PlanJournal

    journal = PlanJournal(str(tmp_path / "j"))
    for pid in ("p10000", "p0002", "p9999", "p0010"):
        journal.record_submitted(pid, f"query-{pid}")
    ids = [e["plan_id"] for e in journal.entries()]
    assert ids == ["p0002", "p0010", "p9999", "p10000"]


def test_closed_executor_never_strands_a_submitted_record(
    session, tmp_path
):
    """A submit refused because the executor closed must leave NO
    'submitted' journal record: the caller was told the plan was
    never admitted, so a later recover() must not silently re-run
    it alongside the caller's resubmission."""
    from eeg_dataanalysispackage_tpu.serve.batcher import (
        ServiceClosedError,
    )

    journal_dir = str(tmp_path / "j")
    ex = PlanExecutor(max_concurrent=1, journal_dir=journal_dir)
    ex.start()
    ex.close()
    with pytest.raises(ServiceClosedError, match="closed"):
        ex.submit(_q(session))
    assert ex.journal.entries() == []


def test_run_backpressures_past_queue_depth(session):
    """run(): a batch bigger than queue_depth completes EVERY plan —
    a shed mid-batch is backpressure (wait for our own in-flight,
    retry), never silent loss of the already-admitted handles."""
    ex = PlanExecutor(max_concurrent=1, queue_depth=1)
    queries = [
        _q(session, f"&config_step_size={1.0 / i}") for i in range(1, 6)
    ]
    try:
        results = ex.run(queries, timeout_s=300)
    finally:
        ex.close()
    assert len(results) == 5
    twins = [str(builder.PipelineBuilder(q).execute()) for q in queries]
    assert [str(r.statistics) for r in results] == twins


def test_env_report_dir_is_per_plan_under_executor(
    session, tmp_path, monkeypatch
):
    """EEG_TPU_RUN_REPORT_DIR under the executor: each plan writes to
    its OWN <env_dir>/<plan_id>/ subtree — N tenants resolving the
    ambient env var to one directory would clobber each other's
    run_report.json (last atomic write wins). A solo run (no plan id)
    keeps the env dir itself."""
    from eeg_dataanalysispackage_tpu.obs import report as obs_report

    env_dir = tmp_path / "reports"
    monkeypatch.setenv(obs_report.ENV_REPORT_DIR, str(env_dir))
    ex = PlanExecutor(max_concurrent=2)
    try:
        handles = [ex.submit(_q(session)) for _ in range(2)]
        for h in handles:
            h.result(timeout=300)
    finally:
        ex.close()
    for h in handles:
        per_plan = env_dir / h.plan_id / "run_report.json"
        assert per_plan.exists(), f"missing {per_plan}"
    assert not (env_dir / "run_report.json").exists()
    # solo path unchanged: no plan id -> the env dir itself
    builder.PipelineBuilder(_q(session)).execute()
    assert (env_dir / "run_report.json").exists()


def test_compilation_monitor_attributes_by_plan_domain():
    """The process-wide jax.monitoring fan-out routes a compile event
    only into the monitor owned by the dispatching thread's plan
    domain; ownerless monitors (solo runs, bare construction) keep
    recording everything."""
    from eeg_dataanalysispackage_tpu.obs import domain as run_domain
    from eeg_dataanalysispackage_tpu.obs import report as obs_report

    event = obs_report._BACKEND_COMPILE_EVENT
    with run_domain.activate(run_domain.RunDomain(plan_id="pA")):
        mon_a = obs_report.CompilationMonitor().__enter__()
    with run_domain.activate(run_domain.RunDomain(plan_id="pB")):
        mon_b = obs_report.CompilationMonitor().__enter__()
    mon_free = obs_report.CompilationMonitor().__enter__()
    try:
        with run_domain.activate(run_domain.RunDomain(plan_id="pA")):
            obs_report._on_duration(event, 1.5)
    finally:
        mon_a.__exit__()
        mon_b.__exit__()
        mon_free.__exit__()
    assert mon_a.snapshot()["compilations"] == 1
    assert mon_b.snapshot()["compilations"] == 0  # not B's compile
    assert mon_free.snapshot()["compilations"] == 1  # ownerless: all
