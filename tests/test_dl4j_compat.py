"""DL4J architecture import (io/dl4j_compat.py).

The reference persists NNs as ModelSerializer zips
(NeuralNetworkClassifier.java:171-176); the weights are closed ND4J
bytes but configuration.json is plain Jackson JSON of the
MultiLayerConfiguration built from the config_* keys
(NeuralNetworkClassifier.java:96-130, 258-320). These tests pin the
inverse mapping across the 0.x encoding variants, the zip plumbing,
the classifier-seam refusal that names the importer, and an
import -> set_config -> fit round trip."""

import json
import zipfile

import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.io import dl4j_compat as dc
from eeg_dataanalysispackage_tpu.models import registry as clf_registry


def _conf_v08(n_layers=2):
    """0.8-style: one-key layer wrappers, activationFn @class,
    training globals cloned per layer."""
    confs = []
    for i in range(n_layers):
        last = i == n_layers - 1
        fields = {
            "nout": 2 if last else 20,
            "dropOut": 0.0 if last else 0.5,
            "activationFn": {
                "@class": (
                    "org.nd4j.linalg.activations.impl."
                    + ("ActivationSoftmax" if last else "ActivationReLU")
                )
            },
            "updater": "NESTEROVS",
            "learningRate": 0.1,
            "momentum": 0.5,
            "weightInit": "XAVIER",
        }
        if last:
            fields["lossFn"] = {
                "@class": (
                    "org.nd4j.linalg.lossfunctions.impl.LossMCXENT"
                )
            }
        confs.append(
            {
                "seed": 12345,
                "numIterations": 7,
                "optimizationAlgo": "CONJUGATE_GRADIENT",
                "layer": {("output" if last else "dense"): fields},
            }
        )
    return {"backprop": True, "pretrain": False, "confs": confs}


def test_v08_import_full_key_surface(tmp_path):
    p = tmp_path / "configuration.json"
    p.write_text(json.dumps(_conf_v08()))
    cfg = dc.import_dl4j_architecture(str(p))
    assert cfg["config_layer1_layer_type"] == "dense"
    assert cfg["config_layer1_n_out"] == "20"
    assert cfg["config_layer1_drop_out"] == "0.5"
    assert cfg["config_layer1_activation_function"] == "relu"
    assert cfg["config_layer2_layer_type"] == "output"
    assert cfg["config_layer2_n_out"] == "2"
    assert cfg["config_layer2_activation_function"] == "softmax"
    assert cfg["config_loss_function"] == "xent"
    assert cfg["config_seed"] == "12345"
    assert cfg["config_num_iterations"] == "7"
    assert cfg["config_optimization_algo"] == "conjugate_gradient"
    assert cfg["config_updater"] == "nesterovs"
    assert cfg["config_learning_rate"] == "0.1"
    assert cfg["config_momentum"] == "0.5"
    assert cfg["config_weight_init"] == "xavier"
    assert cfg["config_backprop"] == "true"
    assert cfg["config_pretrain"] == "false"


def test_pre07_string_activation_and_class_tagged_layers(tmp_path):
    """Older encodings: @class-tagged flat layers and bare-string
    activationFunction values."""
    doc = {
        "backprop": True,
        "pretrain": True,
        "confs": [
            {
                # pre-0.7: training globals on the CONF object, not
                # cloned into the layer (review finding)
                "seed": 11,
                "iterations": 5,
                "learningRate": 0.05,
                "momentum": 0.4,
                "updater": "SGD",
                "weightInit": "RELU",
                "optimizationAlgorithm": "LBFGS",
                "layer": {
                    "@class": (
                        "org.deeplearning4j.nn.conf.layers.AutoEncoder"
                    ),
                    "nOut": 16,
                    "dropout": 0.3,
                    "activationFunction": "sigmoid",
                }
            },
            {
                "layer": {
                    "@class": (
                        "org.deeplearning4j.nn.conf.layers.OutputLayer"
                    ),
                    "nOut": 2,
                    "activationFunction": "softmax",
                    "lossFunction": "NEGATIVELOGLIKELIHOOD",
                }
            },
        ],
    }
    p = tmp_path / "configuration.json"
    p.write_text(json.dumps(doc))
    cfg = dc.import_dl4j_architecture(str(p))
    assert cfg["config_layer1_layer_type"] == "auto_encoder"
    assert cfg["config_layer1_drop_out"] == "0.3"
    assert cfg["config_layer1_activation_function"] == "sigmoid"
    assert cfg["config_layer2_layer_type"] == "output"
    assert cfg["config_loss_function"] == "negativeloglikelihood"
    assert cfg["config_pretrain"] == "true"
    assert cfg["config_learning_rate"] == "0.05"
    assert cfg["config_updater"] == "sgd"
    assert cfg["config_momentum"] == "0.4"
    assert cfg["config_weight_init"] == "relu"
    assert cfg["config_optimization_algo"] == "lbfgs"

    # the ported pre-0.7 config must actually FIT (it carries every
    # key the classifier requires)
    rng = np.random.RandomState(2)
    X = rng.randn(48, 48)
    y = (X[:, 0] > 0).astype(np.float64)
    nn = clf_registry.create("nn")
    nn.set_config(dict(cfg, config_pretrain="false",
                       config_num_iterations="10"))
    nn.fit(X, y)
    assert np.isfinite(nn.predict(X)).all()


def test_zip_archive_and_refusal_seam(tmp_path):
    z = tmp_path / "model.zip"
    with zipfile.ZipFile(z, "w") as zf:
        zf.writestr("configuration.json", json.dumps(_conf_v08()))
        zf.writestr("coefficients.bin", b"\x00" * 64)  # opaque ND4J
    cfg = dc.import_dl4j_architecture(str(z))
    assert cfg["config_layer2_layer_type"] == "output"

    nn = clf_registry.create("nn")
    with pytest.raises(NotImplementedError, match="import_dl4j_architecture"):
        nn.load(str(z))

    # a zip with no configuration entry is refused with context
    z2 = tmp_path / "other.zip"
    with zipfile.ZipFile(z2, "w") as zf:
        zf.writestr("something.bin", b"x")
    with pytest.raises(ValueError, match="configuration.json"):
        dc.read_configuration_json(str(z2))


def test_import_set_config_fit_round_trip(tmp_path):
    """The ported architecture trains through the real classifier —
    the migration's actual end state."""
    doc = _conf_v08()
    p = tmp_path / "configuration.json"
    p.write_text(json.dumps(doc))
    cfg = dc.import_dl4j_architecture(str(p))
    cfg["config_num_iterations"] = "30"

    rng = np.random.RandomState(0)
    X = rng.randn(96, 48)
    y = (X[:, 0] > 0).astype(np.float64)
    nn = clf_registry.create("nn")
    nn.set_config(cfg)
    nn.fit(X, y)
    # predict returns P(target) — the reference's output.getDouble(0)
    acc = float(((nn.predict(X) > 0.5).astype(np.float64) == y).mean())
    assert acc > 0.7


def test_not_a_configuration_raises(tmp_path):
    p = tmp_path / "x.json"
    p.write_text(json.dumps({"weights": [1, 2, 3]}))
    with pytest.raises(ValueError, match="confs"):
        dc.import_dl4j_architecture(str(p))
    p2 = tmp_path / "bad_layer.json"
    p2.write_text(json.dumps({"confs": [{"layer": {"conv2d": {}}}]}))
    with pytest.raises(ValueError, match="layer type"):
        dc.import_dl4j_architecture(str(p2))
    with pytest.raises(ValueError, match="activation"):
        dc._enum("ActivationSwish", dc._ACTIVATIONS, "activation")
