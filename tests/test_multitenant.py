"""Multiplexed multi-tenant serving (serve/multiplex.py, ISSUE 16).

The acceptance bar: ONE resident compiled program serves any tenant
mix — each tenant's multiplexed predictions byte-identical to a solo
``InferenceService`` serving the same classifier (fused, mega, and
host rungs); adding or swapping a tenant triggers 0 XLA compiles;
tenant A's faults or failed swaps can never tear tenant B's traffic
(per-batch snapshot isolation, pinned under tenant-scoped chaos);
per-tenant quota sheds carry structured evidence into the gateway's
429 body.
"""

import numpy as np
import pytest

import _synthetic
from eeg_dataanalysispackage_tpu import obs
from eeg_dataanalysispackage_tpu.epochs.extractor import BalanceState
from eeg_dataanalysispackage_tpu.gateway.server import GatewayServer
from eeg_dataanalysispackage_tpu.io import provider
from eeg_dataanalysispackage_tpu.models import registry as clf_registry
from eeg_dataanalysispackage_tpu.obs import chaos
from eeg_dataanalysispackage_tpu.ops import quant
from eeg_dataanalysispackage_tpu.obs.report import CompilationMonitor
from eeg_dataanalysispackage_tpu.pipeline import builder
from eeg_dataanalysispackage_tpu.serve import (
    InferenceService,
    MultiplexedEngine,
    MultiplexedService,
    ServeConfig,
    ShedError,
    engine,
)
from eeg_dataanalysispackage_tpu.serve import batcher as batcher_mod
from eeg_dataanalysispackage_tpu.serve import multiplex
from eeg_dataanalysispackage_tpu.serve import pipeline as serve_pipeline
from eeg_dataanalysispackage_tpu.serve.engine import ServingEngine

_CONFIG = (
    "&config_num_iterations=20&config_step_size=1.0"
    "&config_mini_batch_fraction=1.0"
)

_NAMES = ("alice", "bob", "carol")


@pytest.fixture(scope="module")
def session(tmp_path_factory):
    """One synthetic session + one trained saved logreg + the kept
    epochs' raw windows — the shared substrate every tenant's model
    derives from."""
    tmp = tmp_path_factory.mktemp("multitenant_session")
    for i, (name, guessed) in enumerate(
        (("synth_00", 2), ("synth_01", 5))
    ):
        _synthetic.write_recording(
            str(tmp), name=name, n_markers=90, guessed=guessed, seed=i
        )
    info = str(tmp / "info.txt")
    with open(info, "w") as f:
        f.write("synth_00.eeg 2\nsynth_01.eeg 5\n")
    model = str(tmp / "model")
    builder.PipelineBuilder(
        f"info_file={info}&fe=dwt-8-fused&train_clf=logreg"
        f"&save_clf=true&save_name={model}{_CONFIG}"
    ).execute()
    odp = provider.OfflineDataProvider([info])
    balance = BalanceState()
    windows, resolutions = [], None
    for _rel, guessed, rec in odp.iter_recordings():
        ws, _ts, resolutions = engine.windows_from_recording(
            rec, odp.channel_indices_for(rec), guessed,
            pre=odp.pre, post=odp.post, balance=balance,
        )
        windows.extend(ws)
    return {
        "info": info,
        "model": model,
        "windows": windows,
        "resolutions": resolutions,
    }


def _tenant_clf(session, seed):
    """One tenant's model: the trained classifier, perturbed
    deterministically per tenant so every tenant has genuinely
    different weights (distinct margins make cross-tenant mixups
    visible)."""
    clf = clf_registry.create("logreg")
    clf.load(session["model"])
    if seed:
        r = np.random.default_rng(seed)
        clf.weights = (
            clf.weights
            + r.standard_normal(clf.weights.shape).astype(np.float32)
            * 0.05
        ).astype(np.float32)
        clf.intercept = float(r.standard_normal() * 0.01)
    return clf


@pytest.fixture(scope="module")
def tenants(session):
    return {
        name: _tenant_clf(session, seed)
        for seed, name in enumerate(_NAMES)
    }


def _mix(session):
    """A deterministic mixed-tenant assignment over the session's
    windows."""
    return [_NAMES[i % len(_NAMES)] for i in range(len(session["windows"]))]


# -- the per-tenant parity pin -------------------------------------------


@pytest.mark.parametrize("rung", ["auto", "fused"])
def test_multiplexed_parity_fused_and_mega(session, tenants, rung):
    """Each tenant's rows out of a mixed-tenant batch are byte-
    identical (predictions AND margins) to a solo engine serving that
    tenant alone — on the mega rung (auto resolves to mega on CPU)
    and the pinned fused rung."""
    mix = _mix(session)
    multi = MultiplexedEngine(tenants, capacity=64, engine_rung=rung)
    multi.warmup()
    if rung == "auto":
        assert multi.rung == "mega"
        assert multi.mega_record["used"] == "mega"
        assert multi.mega_record["gate"]["ok"] is True
    else:
        assert multi.rung == "fused"
    mp, mm = multi.execute(
        session["windows"], session["resolutions"], mix
    )
    for name, clf in tenants.items():
        solo = ServingEngine(clf, capacity=64, engine_rung=rung)
        solo.warmup()
        sp, sm = solo.execute(session["windows"], session["resolutions"])
        rows = [i for i, t in enumerate(mix) if t == name]
        np.testing.assert_array_equal(mp[rows], sp[rows])
        np.testing.assert_array_equal(mm[rows], sm[rows])


def test_multiplexed_parity_host_rung(session, tenants):
    """The host floor: per-tenant groups through each tenant's own
    ``predict`` produce exactly the solo host-rung answers."""
    mix = _mix(session)
    multi = MultiplexedEngine(tenants, capacity=64)
    multi._rung = "host"  # pin the floor (the post-degradation state)
    mp, mm = multi.execute(
        session["windows"], session["resolutions"], mix
    )
    assert mm is None
    for name, clf in tenants.items():
        solo = ServingEngine(clf, capacity=64)
        solo._rung = "host"
        sp, _ = solo.execute(session["windows"], session["resolutions"])
        rows = [i for i, t in enumerate(mix) if t == name]
        np.testing.assert_array_equal(mp[rows], sp[rows])


def test_within_bucket_identity_across_tenant_mixes(session, tenants):
    """A tenant's rows are bit-identical whatever tenant mix rides the
    bucket with them — the row-independence contract extended to the
    gathered weight columns."""
    multi = MultiplexedEngine(tenants, capacity=64)
    multi.warmup()
    windows = session["windows"][:12]
    res = session["resolutions"]
    mix = [_NAMES[i % 3] for i in range(12)]
    _, mixed_margins = multi.execute(windows, res, mix)
    for name in _NAMES:
        _, solo_margins = multi.execute(windows, res, [name] * 12)
        rows = [i for i, t in enumerate(mix) if t == name]
        np.testing.assert_array_equal(
            mixed_margins[rows], solo_margins[rows]
        )


def test_multiplexed_service_parity_with_solo_services(session, tenants):
    """Service-level end-to-end: the multiplexed service's per-tenant
    answers equal each tenant's solo InferenceService on the same
    windows."""
    mix = _mix(session)
    svc = MultiplexedService(tenants, config=ServeConfig(max_batch=64))
    svc.engine.warmup()
    with svc:
        results = svc.predict_all(
            session["windows"], session["resolutions"], mix
        )
    served = np.array([r.prediction for r in results])
    for name, clf in tenants.items():
        solo = InferenceService(clf, config=ServeConfig(max_batch=64))
        with solo:
            solo_results = solo.predict_all(
                session["windows"], session["resolutions"]
            )
        solo_preds = np.array([r.prediction for r in solo_results])
        rows = [i for i, t in enumerate(mix) if t == name]
        np.testing.assert_array_equal(served[rows], solo_preds[rows])


# -- zero-recompile tenant administration --------------------------------


def test_add_and_swap_tenant_trigger_zero_compiles(session, tenants):
    """The tentpole's economic pin: once warm, adding a tenant,
    swapping a tenant's weights, and serving any tenant mix all run
    on the one resident program — 0 XLA compiles, measured."""
    multi = MultiplexedEngine(tenants, capacity=64)
    multi.warmup()
    windows = session["windows"][:9]
    res = session["resolutions"]
    multi.execute(windows, res, [_NAMES[i % 3] for i in range(9)])
    newcomer = _tenant_clf(session, 77)
    replacement = _tenant_clf(session, 78)
    with CompilationMonitor() as monitor:
        lane = multi.add_tenant("dave", newcomer)
        displaced = multi.swap_model(replacement, tenant="bob")
        multi.execute(windows, res, ["dave", "bob", "alice"] * 3)
    snap = monitor.snapshot()
    if snap["available"]:
        assert snap["compilations"] == 0
    assert lane == 3
    assert displaced is tenants["bob"]
    assert multi.tenant_info("bob")["generation"] == 1
    # the swap landed: bob now serves the replacement's predictions
    solo = ServingEngine(replacement, capacity=64)
    solo.warmup()
    sp, _ = solo.execute(windows, res)
    mp, _ = multi.execute(windows, res, ["bob"] * 9)
    np.testing.assert_array_equal(mp, sp)


def test_remove_tenant_frees_lane_and_refuses_traffic(session, tenants):
    multi = MultiplexedEngine(tenants, capacity=64)
    displaced = multi.remove_tenant("bob")
    assert displaced is tenants["bob"]
    assert "bob" not in multi.tenants
    with pytest.raises(ValueError, match="unknown tenant 'bob'"):
        multi.execute(
            session["windows"][:1], session["resolutions"], ["bob"]
        )
    # the freed lane is reused by the next admission
    assert multi.add_tenant("erin", _tenant_clf(session, 79)) == 1
    # the last tenant cannot be removed
    multi.remove_tenant("erin")
    multi.remove_tenant("carol")
    with pytest.raises(ValueError, match="at least one tenant"):
        multi.remove_tenant("alice")


def test_solo_engine_refuses_tenant_keyed_swap(session, tenants):
    solo = ServingEngine(tenants["alice"], capacity=64)
    with pytest.raises(ValueError, match="MultiplexedEngine"):
        solo.swap_model(tenants["bob"], tenant="bob")


def test_multiplex_requires_fused_linear_family(session, tenants):
    f64 = _tenant_clf(session, 0)
    f64.weights = f64.weights.astype(np.float64)
    with pytest.raises(ValueError, match="not multiplexable"):
        MultiplexedEngine({"alice": f64}, capacity=64)
    with pytest.raises(ValueError, match="at least one tenant"):
        MultiplexedEngine({}, capacity=64)


# -- the isolation contract ----------------------------------------------


def test_tenant_scoped_chaos_leaves_other_tenants_pinned(
    session, tenants
):
    """``serve.batch.tenant.alice:p=0.2``: alice's rows retry or fail
    individually; bob's answers stay byte-identical to a bob-only
    solo service and bob's failure counters stay zero — the isolation
    contract under live fault injection."""
    solo = InferenceService(
        tenants["bob"], config=ServeConfig(max_batch=16)
    )
    with solo:
        baseline = np.array([
            r.prediction
            for r in solo.predict_all(
                session["windows"], session["resolutions"]
            )
        ])
    mix = [
        "alice" if i % 2 == 0 else "bob"
        for i in range(len(session["windows"]))
    ]
    # small batches: the tenant-scoped point is sampled once per
    # distinct tenant per batch, so many batches = enough draws for
    # seed 11 to fire (first firing lands on the 4th call)
    svc = MultiplexedService(
        {"alice": tenants["alice"], "bob": tenants["bob"]},
        config=ServeConfig(
            max_batch=4, max_attempts=6, retry_backoff_s=0.01
        ),
    )
    svc.engine.warmup()
    before = obs.metrics.snapshot()["counters"].get(
        "chaos.fired.serve.batch.tenant.alice", 0.0
    )
    bob_results = {}
    alice_outcomes = 0
    with chaos.faults("serve.batch.tenant.alice:p=0.2;seed=11"):
        with svc:
            futures = [
                (i, svc.submit(
                    w, session["resolutions"], tenant=mix[i],
                    deadline_s=30.0, block_s=30.0,
                ))
                for i, w in enumerate(session["windows"])
            ]
            for i, fut in futures:
                try:
                    result = fut.result(timeout=60.0)
                    if mix[i] == "bob":
                        bob_results[i] = result.prediction
                    else:
                        alice_outcomes += 1
                except batcher_mod.RequestFailedError:
                    # only alice's rows may fail (exhausted retries)
                    assert mix[i] == "alice"
                    alice_outcomes += 1
    fired = obs.metrics.snapshot()["counters"].get(
        "chaos.fired.serve.batch.tenant.alice", 0.0
    ) - before
    assert fired > 0  # the fault plan actually exercised the seam
    # every bob answer is byte-identical to the bob-only run
    assert len(bob_results) == sum(1 for t in mix if t == "bob")
    for i, prediction in bob_results.items():
        assert prediction == baseline[i]
    # every alice request resolved (answer or evidence — no hang)
    assert alice_outcomes == sum(1 for t in mix if t == "alice")
    block = svc.stats_block()
    assert block["tenants"]["bob"]["requests"]["failed"] == 0
    assert block["tenants"]["bob"]["requests"]["shed"] == 0
    assert block["tenants"]["bob"]["requests"]["completed"] == len(
        bob_results
    )


def test_failed_swap_on_one_tenant_tears_nothing(session, tenants):
    """A refused hot swap (wrong dtype/shape — the zero-recompile
    contract) on alice leaves the published stack untouched: bob's
    answers before and after are byte-identical, alice still serves
    her ORIGINAL model, and no generation advanced."""
    svc = MultiplexedService(
        {"alice": tenants["alice"], "bob": tenants["bob"]},
        config=ServeConfig(max_batch=16),
    )
    svc.engine.warmup()
    windows = session["windows"][:8]
    res = session["resolutions"]
    with svc:
        before_bob = [
            r.prediction
            for r in svc.predict_all(windows, res, "bob")
        ]
        before_alice = [
            r.prediction
            for r in svc.predict_all(windows, res, "alice")
        ]
        bad = _tenant_clf(session, 5)
        bad.weights = bad.weights.astype(np.float64)
        with pytest.raises(ValueError, match="not multiplexable"):
            svc.swap_tenant("alice", bad)
        wrong_shape = clf_registry.create("logreg")
        wrong_shape.weights = np.zeros(7, np.float32)
        with pytest.raises(ValueError, match="zero-recompile"):
            svc.swap_tenant("alice", wrong_shape)
        after_bob = [
            r.prediction
            for r in svc.predict_all(windows, res, "bob")
        ]
        after_alice = [
            r.prediction
            for r in svc.predict_all(windows, res, "alice")
        ]
    assert after_bob == before_bob
    assert after_alice == before_alice
    assert svc.engine.tenant_info("alice")["generation"] == 0


def test_tenant_quota_sheds_with_structured_evidence(session, tenants):
    """The noisy-neighbor guard: alice's burst sheds against HER
    quota — with her depth and oldest-age in the evidence — while bob
    still admits into the shared queue."""
    svc = MultiplexedService(
        {"alice": tenants["alice"], "bob": tenants["bob"]},
        config=ServeConfig(
            max_batch=16, queue_depth=64, tenant_quota=2
        ),
    )
    # admission without the serving loop: requests queue, nothing
    # drains — the quota boundary is exact and deterministic
    svc._accepting = True
    window, res = session["windows"][0], session["resolutions"]
    svc.submit(window, res, tenant="alice")
    svc.submit(window, res, tenant="alice")
    with pytest.raises(ShedError) as err:
        svc.submit(window, res, tenant="alice")
    evidence = err.value.evidence
    assert evidence["reason"] == "tenant_quota"
    assert evidence["tenant"] == "alice"
    assert evidence["tenant_depth"] == 2
    assert evidence["tenant_quota"] == 2
    assert evidence["oldest_age_s"] >= 0.0
    assert "alice" in str(err.value)
    # bob is untouched by alice's quota
    svc.submit(window, res, tenant="bob")
    block = svc.stats_block()
    assert block["tenants"]["alice"]["requests"]["shed"] == 1
    assert block["tenants"]["bob"]["requests"]["shed"] == 0


def test_mixed_tenants_share_one_batch_key(session):
    """Tenant is deliberately NOT in the coalescing key: compatible
    windows from different tenants fill ONE bucket (the cross-tenant
    fill economics of the tentpole)."""
    from eeg_dataanalysispackage_tpu.io import deadline as deadline_mod

    w, res = session["windows"][0], session["resolutions"]
    a = batcher_mod.Request(
        w, res, deadline_mod.Deadline(5.0), tenant="alice"
    )
    b = batcher_mod.Request(
        w, res, deadline_mod.Deadline(5.0), tenant="bob"
    )
    assert a.batch_key() == b.batch_key()


def test_mixed_tenant_requests_coalesce_into_shared_batches(
    session, tenants
):
    """Live proof: with a flush window, interleaved two-tenant traffic
    lands in shared buckets (mean batch size > 1)."""
    svc = MultiplexedService(
        {"alice": tenants["alice"], "bob": tenants["bob"]},
        config=ServeConfig(max_batch=16, flush_us=2000),
    )
    svc.engine.warmup()
    mix = [
        "alice" if i % 2 == 0 else "bob"
        for i in range(len(session["windows"]))
    ]
    with svc:
        svc.predict_all(session["windows"], session["resolutions"], mix)
    block = svc.stats_block()
    assert block["mean_batch_size"] > 1.0


# -- gateway hot path ----------------------------------------------------


@pytest.fixture()
def predict_gateway(session, tenants):
    svc = MultiplexedService(
        {"alice": tenants["alice"], "bob": tenants["bob"]},
        config=ServeConfig(max_batch=16, tenant_quota=2),
    )
    svc.engine.warmup()
    svc.start()
    gateway = GatewayServer(journal_dir=None, predict_service=svc)
    try:
        yield gateway, svc
    finally:
        svc.stop()


def _predict_body(session, tenant="alice"):
    import json

    return json.dumps({
        "tenant": tenant,
        "window": np.asarray(session["windows"][0]).tolist(),
        "resolutions": np.asarray(session["resolutions"]).tolist(),
    })


def test_gateway_predict_happy_path_and_stats(
    session, tenants, predict_gateway
):
    gateway, svc = predict_gateway
    code, payload = gateway.predict_payload(_predict_body(session))
    assert code == 200
    assert payload["tenant"] == "alice"
    assert payload["prediction"] in (0.0, 1.0)
    assert payload["margin"] is not None
    assert payload["batch_size"] >= 1
    # the served answer is the engine's answer
    solo = ServingEngine(tenants["alice"], capacity=16)
    solo.warmup()
    sp, _ = solo.execute(
        [session["windows"][0]], session["resolutions"]
    )
    assert payload["prediction"] == float(sp[0])
    code, stats = gateway.stats_payload()
    assert code == 200
    serve_block = stats["serve"]
    assert set(serve_block["tenants"]) == {"alice", "bob"}
    alice = serve_block["tenants"]["alice"]
    assert alice["requests"]["submitted"] >= 1
    assert alice["requests"]["completed"] >= 1
    assert {"lane", "generation", "requests", "latency_ms",
            "lifecycle"} <= set(alice)


def test_gateway_predict_idempotent_replay_and_conflict(
    session, predict_gateway
):
    gateway, _svc = predict_gateway
    body = _predict_body(session)
    code1, first = gateway.predict_payload(body, idempotency_key="k1")
    assert code1 == 200 and first["idempotent_replay"] is False
    code2, replay = gateway.predict_payload(body, idempotency_key="k1")
    assert code2 == 200 and replay["idempotent_replay"] is True
    assert replay["prediction"] == first["prediction"]
    assert replay["margin"] == first["margin"]
    # same key, different body: refused — honesty over convenience
    other = _predict_body(session, tenant="bob")
    code3, conflict = gateway.predict_payload(
        other, idempotency_key="k1"
    )
    assert code3 == 409
    assert conflict["idempotency_conflict"] is True


def test_gateway_predict_rejects_bad_requests(session, predict_gateway):
    gateway, _svc = predict_gateway
    code, payload = gateway.predict_payload("not json")
    assert code == 400 and "not JSON" in payload["error"]
    code, payload = gateway.predict_payload(
        _predict_body(session, tenant="ghost")
    )
    assert code == 400 and "unknown tenant" in payload["error"]
    code, payload = gateway.predict_payload('{"tenant": "alice"}')
    assert code == 400 and "window" in payload["error"]
    # no service attached: the gateway stays the pure plan front door
    bare = GatewayServer(journal_dir=None)
    code, payload = bare.predict_payload(_predict_body(session))
    assert code == 503


def test_gateway_predict_shed_carries_tenant_evidence(session, tenants):
    """429 body: the admission queue's structured per-tenant evidence
    (depth, quota, oldest-age), straight from the ShedError."""
    svc = MultiplexedService(
        {"alice": tenants["alice"]},
        config=ServeConfig(max_batch=16, queue_depth=64, tenant_quota=1),
    )
    svc._accepting = True  # queue admits, nothing drains
    gateway = GatewayServer(journal_dir=None, predict_service=svc)
    svc.submit(
        session["windows"][0], session["resolutions"], tenant="alice"
    )
    code, payload = gateway.predict_payload(_predict_body(session))
    assert code == 429
    assert payload["shed"] is True
    assert payload["tenant"] == "alice"
    evidence = payload["evidence"]
    assert evidence["reason"] == "tenant_quota"
    assert evidence["tenant_depth"] == 1
    assert evidence["tenant_quota"] == 1
    assert "oldest_age_s" in evidence


# -- tenant registry loading ---------------------------------------------


def test_parse_tenant_spec():
    spec = "alice=logreg@/m/a, bob=svm@/m/b"
    parsed = serve_pipeline.parse_tenant_spec(spec)
    assert parsed == {
        "alice": ("logreg", "/m/a"), "bob": ("svm", "/m/b"),
    }
    assert list(parsed) == ["alice", "bob"]  # order preserved
    for bad in (
        "", "alice", "alice=logreg", "alice@/m/a",
        "alice=logreg@/m/a,alice=svm@/m/b",
    ):
        with pytest.raises(ValueError):
            serve_pipeline.parse_tenant_spec(bad)


def test_load_tenants_and_from_saved(session):
    spec = (
        f"alice=logreg@{session['model']},"
        f"bob=logreg@{session['model']}"
    )
    loaded = serve_pipeline.load_tenants(spec)
    assert set(loaded) == {"alice", "bob"}
    assert loaded["alice"] is not loaded["bob"]
    np.testing.assert_array_equal(
        loaded["alice"].weights, loaded["bob"].weights
    )
    svc = MultiplexedService.from_saved(
        {
            "alice": ("logreg", session["model"]),
            "bob": ("logreg", session["model"]),
        },
        config=ServeConfig(max_batch=16),
    )
    with svc:
        r = svc.predict_window(
            session["windows"][0], session["resolutions"],
            tenant="bob",
        )
    assert r.prediction in (0.0, 1.0)


def test_runtime_tenant_onboarding_from_saved(session, tenants):
    svc = MultiplexedService(
        {"alice": tenants["alice"]}, config=ServeConfig(max_batch=16)
    )
    svc.engine.warmup()
    with svc:
        lane = svc.add_tenant_from_saved(
            "frank", "logreg", session["model"]
        )
        assert lane == 1
        r = svc.predict_window(
            session["windows"][0], session["resolutions"],
            tenant="frank",
        )
        assert r.prediction in (0.0, 1.0)
        svc.remove_tenant("frank")
        with pytest.raises(ValueError, match="unknown tenant"):
            svc.submit(
                session["windows"][0], session["resolutions"],
                tenant="frank",
            )


def test_serve_config_tenant_quota_from_query():
    config = serve_pipeline.serve_config_from_query(
        {"serve_tenant_quota": "8"}
    )
    assert config.tenant_quota == 8
    assert serve_pipeline.serve_config_from_query({}).tenant_quota is None


# -- stats & decision path -----------------------------------------------


def test_stats_block_schema(session, tenants):
    svc = MultiplexedService(tenants, config=ServeConfig(max_batch=16))
    svc.engine.warmup()
    with svc:
        svc.predict_all(
            session["windows"][:6], session["resolutions"],
            [_NAMES[i % 3] for i in range(6)],
        )
        block = svc.stats_block()
    # the solo block's schema survives unchanged...
    for key in ("mode", "rung", "mega", "requests", "latency_ms",
                "lifecycle"):
        assert key in block
    # ...plus the per-tenant attribution sub-block
    assert set(block["tenants"]) == set(_NAMES)
    assert block["resident_weight_bytes"] == 48 * 128 * 4
    for name in _NAMES:
        t = block["tenants"][name]
        assert t["requests"]["completed"] == 2
        assert t["latency_ms"]["n"] == 2
        assert t["latency_ms"]["p99"] >= t["latency_ms"]["p50"] >= 0
        assert t["lifecycle"] is None


def test_multiplex_accelerator_decision_harvest(tmp_path):
    """The pre-registered consolidation gate: no artifact -> per-
    tenant engines stand; a 16-tenant chip line at >= the flip ratio
    -> consolidate (data flips the decision, not code)."""
    import json

    root = tmp_path / "sweeps"
    decision = multiplex.accelerator_decision(str(root))
    assert decision["consolidate"] is False
    assert decision["ratio"] is None
    run = root / "20260101T000000Z"
    run.mkdir(parents=True)
    record = {
        "platform": "tpu",
        "serve": {"multitenant": {"levels": [
            {
                "tenants": 16,
                "multiplexed": {"preds_per_s": 5200.0},
                "solo_fleet": {"preds_per_s": 4100.0},
            },
        ]}},
    }
    (run / "serve_multitenant.json").write_text(json.dumps(record))
    decision = multiplex.accelerator_decision(str(root))
    assert decision["consolidate"] is True
    assert decision["ratio"] == round(5200.0 / 4100.0, 4)
    assert decision["threshold_ratio"] == multiplex.MULTIPLEX_FLIP_RATIO
    # below the flip ratio: the fleet stands
    record["serve"]["multitenant"]["levels"][0]["multiplexed"][
        "preds_per_s"
    ] = 3000.0
    (run / "serve_multitenant.json").write_text(json.dumps(record))
    assert multiplex.accelerator_decision(str(root))["consolidate"] is False


# -- the quantized weight stack (ISSUE 18) -------------------------------


def test_quantized_stack_gate_promotes_with_margin_parity(
    session, tenants
):
    """The warmup gate promotes int4 residency and every tenant's
    margins out of the quantized stack sit within the documented
    weights tolerance of the f32 engine's — with predictions equal
    wherever the f32 margin clears the tolerance band."""
    multi = MultiplexedEngine(
        tenants, capacity=64, weights_precision="int4"
    )
    multi.warmup()
    assert multi.weights_precision == "int4"
    rec = multi.weights_record
    assert rec["requested"] == "int4" and rec["used"] == "int4"
    gate = rec["gate"]
    assert gate["ok"] and gate["max_abs_dev"] <= gate["tolerance"]
    # 48/2 packed uint8 rows + 128 f32 per-lane scales: 3584 B, the
    # >= 4x VMEM-residency reduction the bench line records
    assert multi.resident_weight_bytes == 48 // 2 * 128 + 128 * 4
    f32 = MultiplexedEngine(tenants, capacity=64)
    f32.warmup()
    assert f32.resident_weight_bytes == 48 * 128 * 4
    windows = session["windows"][:12]
    res = session["resolutions"]
    mix = [_NAMES[i % 3] for i in range(12)]
    qp, qm = multi.execute(windows, res, mix)
    fp, fm = f32.execute(windows, res, mix)
    tol = quant.weights_gate_tolerance("int4", multi._w_host)
    assert float(np.max(np.abs(qm - fm))) <= tol
    clear = np.abs(fm) > tol
    np.testing.assert_array_equal(qp[clear], fp[clear])


def test_quantized_stack_forced_off_is_identical_to_f32(
    session, tenants, monkeypatch
):
    """The forced-off drill: EEG_TPU_WEIGHTS_GATE_TOL=0 shuts the
    gate, the engine publishes the f32 mirror (record says so — never
    silence), and served margins are BYTE-identical to a plain f32
    engine's."""
    monkeypatch.setenv("EEG_TPU_WEIGHTS_GATE_TOL", "0")
    multi = MultiplexedEngine(
        tenants, capacity=64, weights_precision="int4"
    )
    multi.warmup()
    assert multi.weights_precision == "f32"
    rec = multi.weights_record
    assert rec["requested"] == "int4" and rec["used"] == "f32"
    assert rec["gate"] is not None and rec["gate"]["ok"] is False
    assert multi.resident_weight_bytes == 48 * 128 * 4
    f32 = MultiplexedEngine(tenants, capacity=64)
    f32.warmup()
    windows = session["windows"][:12]
    res = session["resolutions"]
    mix = [_NAMES[i % 3] for i in range(12)]
    qp, qm = multi.execute(windows, res, mix)
    fp, fm = f32.execute(windows, res, mix)
    np.testing.assert_array_equal(qm, fm)
    np.testing.assert_array_equal(qp, fp)


def test_quantized_stack_zero_compile_admin_stays_quantized(
    session, tenants
):
    """The tentpole's economic pin survives quantization: add, swap,
    remove, and serve on the int4 stack are 0 XLA compiles (the
    re-pack is host-side numpy; the resident program's signature
    never changes), and the stack is STILL quantized afterwards."""
    multi = MultiplexedEngine(
        tenants, capacity=64, weights_precision="int4"
    )
    multi.warmup()
    assert multi.weights_precision == "int4"
    windows = session["windows"][:9]
    res = session["resolutions"]
    multi.execute(windows, res, [_NAMES[i % 3] for i in range(9)])
    newcomer = _tenant_clf(session, 81)
    replacement = _tenant_clf(session, 82)
    with CompilationMonitor() as monitor:
        multi.add_tenant("dave", newcomer)
        multi.swap_model(replacement, tenant="bob")
        multi.remove_tenant("dave")
        multi.execute(windows, res, ["bob", "alice", "carol"] * 3)
    snap = monitor.snapshot()
    if snap["available"]:
        assert snap["compilations"] == 0
    assert multi.weights_precision == "int4"
    assert multi.resident_weight_bytes == 48 // 2 * 128 + 128 * 4
    # the swap landed THROUGH the quantized stack: bob now tracks the
    # replacement's weights within the weights tolerance
    solo = ServingEngine(replacement, capacity=64)
    solo.warmup()
    sp, sm = solo.execute(windows, res)
    mp, mm = multi.execute(windows, res, ["bob"] * 9)
    tol = quant.weights_gate_tolerance("int4", multi._w_host)
    assert float(np.max(np.abs(mm - sm))) <= tol


def test_quantized_stack_runtime_degradation_to_f32_master(
    session, tenants
):
    """The crash-only seam: a faulting quant program serves its batch
    via the f32 MASTER mirror (byte-identical to a plain f32 engine,
    zero drops), and two consecutive failures retire the quantized
    stack for the engine's lifetime with the evidence recorded."""
    multi = MultiplexedEngine(
        tenants, capacity=64, engine_rung="fused",
        weights_precision="int4",
    )
    multi.warmup()
    assert multi.weights_precision == "int4"

    def boom(*a, **k):
        raise RuntimeError("injected quant fault")

    multi._multi_program_quant = boom
    f32 = MultiplexedEngine(tenants, capacity=64, engine_rung="fused")
    f32.warmup()
    windows = session["windows"][:6]
    res = session["resolutions"]
    mix = [_NAMES[i % 3] for i in range(6)]
    fp, fm = f32.execute(windows, res, mix)
    # first failure: served by the master mirror, not yet retired
    p1, m1 = multi.execute(windows, res, mix)
    np.testing.assert_array_equal(m1, fm)
    np.testing.assert_array_equal(p1, fp)
    assert multi.weights_precision == "int4"
    # second consecutive failure: the stack is retired
    p2, m2 = multi.execute(windows, res, mix)
    np.testing.assert_array_equal(m2, fm)
    assert multi.weights_precision == "f32"
    rec = multi.weights_record
    assert rec["used"] == "f32" and rec["degraded"] is True
    assert "injected quant fault" in rec["error"]
    # and the next batch runs the published f32 snapshot cleanly
    p3, m3 = multi.execute(windows, res, mix)
    np.testing.assert_array_equal(m3, fm)


def test_quantized_stack_service_stats_and_validation(session, tenants):
    """The service surface: stats_block carries the ACTIVE stack
    precision + the full weights record, and a junk weights_precision=
    is refused at construction."""
    svc = MultiplexedService(
        tenants, config=ServeConfig(max_batch=16),
        weights_precision="int4",
    )
    svc.engine.warmup()
    with svc:
        svc.predict_all(
            session["windows"][:3], session["resolutions"],
            list(_NAMES),
        )
        block = svc.stats_block()
    assert block["weights_precision"] == "int4"
    assert block["weights"]["requested"] == "int4"
    assert block["weights"]["used"] == "int4"
    assert block["weights"]["gate"]["ok"] is True
    assert block["resident_weight_bytes"] == 48 // 2 * 128 + 128 * 4
    with pytest.raises(ValueError, match="weights_precision="):
        MultiplexedEngine(tenants, capacity=64, weights_precision="fp8")
