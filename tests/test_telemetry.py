"""Structured run telemetry (obs/events.py + obs/report.py): span
nesting and thread-safety under the parallel-ingest pool, run-report
schema round-trip, the flight recorder's crash artifact on an injected
``faults=`` failure, per-run metrics scoping, and the pinned contract
that telemetry-on vs telemetry-off ClassificationStatistics are
bit-identical."""

import json
import os
import threading

import pytest

import _synthetic
from eeg_dataanalysispackage_tpu import obs
from eeg_dataanalysispackage_tpu.obs import events
from eeg_dataanalysispackage_tpu.obs import report as obs_report


# -- span recorder -------------------------------------------------------


def test_span_nesting_parents_and_attrs():
    rec = events.SpanRecorder(name="run")
    with events.recording(rec):
        with events.span("outer", kind="test") as outer:
            with events.span("inner") as inner:
                events.event("mark", x=1)
            assert inner["parent"] == outer["id"]
        assert outer["parent"] == rec.root["id"]
    spans = {s["name"]: s for s in rec.spans()}
    assert set(spans) == {"outer", "inner"}
    assert spans["outer"]["attrs"]["kind"] == "test"
    assert spans["inner"]["end"] >= spans["inner"]["start"]
    # the event landed on the innermost open span and in the ring
    assert spans["inner"]["events"][0]["name"] == "mark"
    assert [e["name"] for e in rec.recent_events()] == ["mark"]
    # root closed by the recording() exit
    assert rec.root["end"] is not None


def test_span_error_annotation():
    rec = events.SpanRecorder()
    with events.recording(rec):
        with pytest.raises(ValueError):
            with events.span("will-fail"):
                raise ValueError("boom")
    (span,) = rec.spans()
    assert span["attrs"]["error"] == "ValueError: boom"


def test_span_thread_safety():
    """Concurrent spans from many threads: per-thread stacks never
    cross, orphan threads parent onto the run root, nothing is lost."""
    rec = events.SpanRecorder()
    n_threads, per_thread = 8, 25
    barrier = threading.Barrier(n_threads)

    def work(tid):
        barrier.wait()
        for i in range(per_thread):
            with rec.span(f"t{tid}", i=i) as outer:
                with rec.span(f"t{tid}.child") as child:
                    assert child["parent"] == outer["id"]
                rec.event("tick")

    threads = [
        threading.Thread(target=work, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    spans = rec.spans()
    assert len(spans) == n_threads * per_thread * 2
    by_id = {s["id"]: s for s in spans}
    for s in spans:
        if s["name"].endswith(".child"):
            # child's parent is the same thread's outer span
            assert by_id[s["parent"]]["name"] == s["name"].rsplit(".", 1)[0]
        else:
            # outer spans from pool threads parent onto the root
            assert s["parent"] == rec.root["id"]
    summary = rec.summary()
    assert summary["dropped_spans"] == 0
    assert sum(v["count"] for v in summary["by_name"].values()) == len(spans)


def test_events_are_noop_without_recorder():
    events.uninstall()
    with events.span("nothing", a=1) as s:
        assert s is None
    events.event("nothing")  # must not raise
    assert events.active_recorder() is None


def test_jsonl_sink_round_trip(tmp_path):
    path = str(tmp_path / "spans.jsonl")
    rec = events.SpanRecorder(jsonl_path=path)
    with events.recording(rec):
        with events.span("a"):
            events.event("ev", k="v")
    lines = [json.loads(l) for l in open(path).read().splitlines()]
    kinds = [l["kind"] for l in lines]
    assert kinds.count("event") == 1
    assert kinds.count("span") == 2  # "a" + the root on finish()
    (ev,) = [l for l in lines if l["kind"] == "event"]
    assert ev["name"] == "ev" and ev["attrs"] == {"k": "v"}


def test_jsonl_sink_truncates_per_run_and_latches_on_finish(tmp_path):
    """A fixed report dir (EEG_TPU_RUN_REPORT_DIR) replaces the trace
    per run rather than interleaving runs, and a straggler thread
    finishing a span after finish() cannot reopen the closed sink."""
    path = str(tmp_path / "spans.jsonl")
    rec1 = events.SpanRecorder(jsonl_path=path)
    with rec1.span("first-run"):
        pass
    rec1.finish()
    rec2 = events.SpanRecorder(jsonl_path=path)
    with rec2.span("second-run"):
        pass
    rec2.finish()
    names = [
        json.loads(l)["name"] for l in open(path).read().splitlines()
    ]
    assert "second-run" in names and "first-run" not in names
    # post-finish span: retained in memory, but the sink stays closed
    with rec2.span("straggler"):
        pass
    assert "straggler" in {s["name"] for s in rec2.spans()}
    assert "straggler" not in [
        json.loads(l)["name"] for l in open(path).read().splitlines()
    ]


def test_staging_producer_error_event_lands_on_producer_span():
    import numpy as np

    from eeg_dataanalysispackage_tpu.io import staging

    def bad_batches():
        yield (np.zeros((2, 4), np.float32),)
        raise RuntimeError("poisoned batch")

    rec = events.SpanRecorder()
    with events.recording(rec):
        with pytest.raises(RuntimeError, match="poisoned batch"):
            for _ in staging.prefetch(bad_batches()):
                pass
    (ev,) = [
        e for e in rec.recent_events()
        if e["name"] == "staging.producer_error"
    ]
    assert ev["span_name"] == "staging.producer"
    assert ev["attrs"]["batches_staged"] == 1


# -- parallel-ingest spans ----------------------------------------------


def _write_multi_session(directory, n_files=4, n_markers=24):
    lines = []
    for i in range(n_files):
        name = f"synth_{i:02d}"
        _synthetic.write_recording(
            directory, name=name, n_markers=n_markers, guessed=2 + i,
            seed=i,
        )
        lines.append(f"{name}.eeg {2 + i}")
    info = os.path.join(directory, "info.txt")
    with open(info, "w") as f:
        f.write("\n".join(lines) + "\n")
    return info


def test_parallel_ingest_parse_spans(tmp_path):
    """The worker pool's per-recording parse spans are recorded
    thread-safely and parent onto the run root."""
    from eeg_dataanalysispackage_tpu.io import provider

    info = _write_multi_session(str(tmp_path), n_files=4)
    rec = events.SpanRecorder()
    with events.recording(rec):
        provider.OfflineDataProvider([info], workers=4).load()
    parse = [s for s in rec.spans() if s["name"] == "ingest.parse"]
    assert len(parse) == 4
    assert {s["attrs"]["file"] for s in parse} == {
        f"synth_{i:02d}.eeg" for i in range(4)
    }
    assert all(s["parent"] == rec.root["id"] for s in parse)
    assert all(s["attrs"].get("pooled") for s in parse)


# -- metrics scoping -----------------------------------------------------


def test_metrics_scope_isolates_runs():
    m = obs.Metrics()
    m.count("before_scope")
    with m.scope() as run1:
        m.count("pipeline.x", 2)
        m.gauge("g", 7.0)
    with m.scope() as run2:
        m.count("pipeline.x", 5)
    # each scope saw only its own window
    assert run1.snapshot()["counters"] == {"pipeline.x": 2}
    assert run1.snapshot()["gauges"] == {"g": 7.0}
    assert run2.snapshot()["counters"] == {"pipeline.x": 5}
    assert "before_scope" not in run1.snapshot()["counters"]
    # the global kept accumulating as the default sink
    assert m.snapshot()["counters"]["pipeline.x"] == 7


def test_metrics_reset():
    m = obs.Metrics()
    m.count("a", 3)
    m.gauge("b", 1.0)
    m.reset()
    assert m.snapshot() == {"counters": {}, "gauges": {}}
    m.count("a")  # still usable after reset
    assert m.snapshot()["counters"]["a"] == 1


# -- StageTimer min/max/mean --------------------------------------------


def test_stage_timer_min_max_mean():
    t = obs.StageTimer()
    import time as _time

    with t.stage("s"):
        _time.sleep(0.02)
    with t.stage("s"):
        pass
    d = t.as_dict()["s"]
    assert d["count"] == 2
    assert d["min_s"] <= d["mean_s"] <= d["max_s"]
    assert d["max_s"] >= 0.02
    assert abs(d["mean_s"] - d["seconds"] / 2) < 1e-9
    report = t.report()
    assert "mean" in report and "min" in report and "max" in report
    # deterministic alignment: every line same length
    lines = report.splitlines()
    assert len({len(l) for l in lines}) == 1


# -- pipeline integration ------------------------------------------------

_QUERY_TMPL = (
    "info_file={info}&fe=dwt-8-fused&train_clf=logreg&cache=false"
    "&config_num_iterations=5&config_step_size=1.0"
    "&config_mini_batch_fraction=1.0"
)


def _run_pipeline(query):
    from eeg_dataanalysispackage_tpu.pipeline import builder

    pb = builder.PipelineBuilder(query)
    return pb, pb.execute()


def test_run_report_schema_round_trip(tmp_path):
    (tmp_path / "d").mkdir()
    info = _synthetic.write_session(str(tmp_path / "d"), n_markers=48)
    report_dir = str(tmp_path / "report")
    query = _QUERY_TMPL.format(info=info) + f"&report={report_dir}"
    pb, statistics = _run_pipeline(query)

    path = os.path.join(report_dir, "run_report.json")
    assert os.path.exists(path)
    report = json.load(open(path))
    assert report["schema"] == obs_report.RUN_SCHEMA
    assert report["outcome"] == "ok"
    assert report["query"] == query
    assert report["wall_s"] > 0
    # stage totals present with the min/max/mean shape
    for stage in ("ingest", "train", "test"):
        entry = report["stages"][stage]
        assert entry["seconds"] > 0
        assert entry["min_s"] <= entry["mean_s"] <= entry["max_s"]
    # per-run metrics, not process history
    assert report["metrics"]["counters"]["pipeline.epochs_loaded"] > 0
    # span summary recorded the stage spans
    by_name = report["spans"]["by_name"]
    for name in ("stage.ingest", "stage.train", "stage.test",
                 "ingest.parse"):
        assert by_name[name]["count"] >= 1, name
    # backend attribution (CPU resolves the bare -fused to decode)
    assert report["backend"]["landed"] in ("decode", "xla", "block", "pallas")
    # cache attribution is schema-stable even for a cache=false run
    assert set(report["caches"]) == {
        "feature_cache", "plan_cache", "compile_cache_dir"
    }
    assert report["statistics_sha256"]
    assert report["accuracy"] == round(statistics.calc_accuracy(), 6)
    # spans.jsonl sink sits next to the report
    assert os.path.exists(os.path.join(report_dir, "spans.jsonl"))
    # telemetry was scoped to the run: nothing left installed
    assert events.active_recorder() is None


def test_telemetry_on_off_statistics_bit_identical(tmp_path):
    """The acceptance pin: enabling telemetry must not perturb the
    classification result in any way."""
    (tmp_path / "d").mkdir()
    info = _synthetic.write_session(str(tmp_path / "d"), n_markers=48)
    _, stats_off = _run_pipeline(_QUERY_TMPL.format(info=info))
    _, stats_on = _run_pipeline(
        _QUERY_TMPL.format(info=info)
        + f"&report={tmp_path / 'report'}"
    )
    assert str(stats_on) == str(stats_off)


def test_successful_chaos_run_report_carries_plan_accounting(tmp_path):
    """A chaos run the defenses absorb still succeeds — and its
    run_report.json must record the plan's per-rule firing counts
    (the report writes inside the fault scope)."""
    (tmp_path / "d").mkdir()
    info = _synthetic.write_session(str(tmp_path / "d"), n_markers=48)
    report_dir = str(tmp_path / "report")
    query = (
        _QUERY_TMPL.format(info=info)
        + f"&report={report_dir}"
        + "&faults=ingest.fused:once@1"  # absorbed by the ladder
    )
    _run_pipeline(query)
    report = json.load(
        open(os.path.join(report_dir, "run_report.json"))
    )
    assert report["outcome"] == "ok"
    assert report["chaos"]["rules"]["ingest.fused"]["fired"] == 1
    # bare -fused starts the CPU ladder at decode; the absorbed
    # failure lands one rung down
    assert report["backend"] == {
        "requested": "decode", "landed": "pallas",
    }
    assert report["degradation"][0]["from"] == "decode"


def test_crash_clears_stale_run_report_and_timers_reset(tmp_path):
    """The mirror lifecycle: success then crash into the same dir
    leaves only crash_report.json — and a reused builder's second
    run reports its own stage times, not accumulated ones."""
    (tmp_path / "d").mkdir()
    info = _synthetic.write_session(str(tmp_path / "d"), n_markers=48)
    report_dir = str(tmp_path / "report")
    from eeg_dataanalysispackage_tpu.obs import chaos
    from eeg_dataanalysispackage_tpu.pipeline import builder

    pb1, _ = _run_pipeline(
        _QUERY_TMPL.format(info=info) + f"&report={report_dir}"
    )
    first_ingest = pb1.timers.as_dict()["ingest"]
    # same builder re-executed: per-run timers, no accumulation
    pb1.execute()
    assert pb1.timers.as_dict()["ingest"]["count"] == \
        first_ingest["count"]
    # now a crashing run into the same directory
    pb2 = builder.PipelineBuilder(
        _QUERY_TMPL.format(info=info)
        + f"&report={report_dir}&degrade=false"
        + "&faults=ingest.fused:once@1"
    )
    with pytest.raises(chaos.ChaosInjectedError):
        pb2.execute()
    assert os.path.exists(os.path.join(report_dir, "crash_report.json"))
    assert not os.path.exists(
        os.path.join(report_dir, "run_report.json")
    )


def test_successful_run_clears_stale_crash_artifact(tmp_path):
    """Run 1 crashes into a fixed report dir; run 2 succeeds there —
    the stale crash_report.json must not survive next to a fresh
    outcome=ok report."""
    (tmp_path / "d").mkdir()
    info = _synthetic.write_session(str(tmp_path / "d"), n_markers=48)
    report_dir = str(tmp_path / "report")
    from eeg_dataanalysispackage_tpu.obs import chaos

    with pytest.raises(chaos.ChaosInjectedError):
        _run_pipeline(
            _QUERY_TMPL.format(info=info)
            + f"&report={report_dir}&degrade=false"
            + "&faults=ingest.fused:once@1"
        )
    assert os.path.exists(os.path.join(report_dir, "crash_report.json"))
    _run_pipeline(_QUERY_TMPL.format(info=info) + f"&report={report_dir}")
    assert not os.path.exists(
        os.path.join(report_dir, "crash_report.json")
    )
    assert os.path.exists(os.path.join(report_dir, "run_report.json"))


def test_resolve_report_dir_precedence(tmp_path, monkeypatch):
    """Explicit report= values beat EEG_TPU_RUN_REPORT_DIR; =true
    resolves next to result_path; =false opts out of everything."""
    monkeypatch.setenv(obs_report.ENV_REPORT_DIR, "/env-dir")
    assert obs_report.resolve_report_dir({"report": "/q-dir"}) == "/q-dir"
    assert obs_report.resolve_report_dir(
        {"report": "true", "result_path": "/out/res.txt"}
    ) == "/out"
    assert obs_report.resolve_report_dir({"report": "true"}) == "."
    assert obs_report.resolve_report_dir({"report": "false"}) is None
    assert obs_report.resolve_report_dir({}) == "/env-dir"
    monkeypatch.delenv(obs_report.ENV_REPORT_DIR)
    assert obs_report.resolve_report_dir({}) is None


def test_stage_timer_total_probe_does_not_poison():
    t = obs.StageTimer()
    assert t.total("never-ran") == 0.0
    assert t.as_dict() == {}  # the probe left no zero-count row


def test_flight_recorder_dumps_crash_report(tmp_path):
    """A chaos run that fails produces crash_report.json carrying the
    firing event and the degradation history (the acceptance
    criterion for the flight recorder)."""
    (tmp_path / "d").mkdir()
    info = _synthetic.write_session(str(tmp_path / "d"), n_markers=48)
    report_dir = str(tmp_path / "report")
    query = (
        _QUERY_TMPL.format(info=info)
        + f"&report={report_dir}"
        + f"&elastic=true&checkpoint_path={tmp_path / 'ckpt'}"
        + "&max_restarts=0"
        + "&faults=ingest.fused:once@1;device.step:once@1"
    )
    from eeg_dataanalysispackage_tpu.obs import chaos

    with pytest.raises(chaos.ChaosInjectedError):
        _run_pipeline(query)

    path = os.path.join(report_dir, "crash_report.json")
    assert os.path.exists(path)
    crash = json.load(open(path))
    assert crash["schema"] == obs_report.CRASH_SCHEMA
    assert crash["error"]["type"] == "ChaosInjectedError"
    assert "device.step" in crash["error"]["message"]
    # the firing events are in the flight-recorder ring, annotated
    # with the span they interrupted
    fired = [e for e in crash["events"] if e["name"] == "chaos.fired"]
    assert {e["attrs"]["point"] for e in fired} == {
        "ingest.fused", "device.step"
    }
    assert any(e["span_name"] == "stage.train" for e in fired)
    # degradation history: the injected fused failure stepped the run
    # down one rung (CPU ladder starts at decode) before training died
    assert crash["degradation"][0]["from"] == "decode"
    assert crash["backend"] == {
        "requested": "decode", "landed": "pallas",
    }
    # the chaos plan rode along with per-rule firing accounting
    assert crash["chaos"]["rules"]["device.step"]["fired"] == 1
    # no dangling recorder after the crash
    assert events.active_recorder() is None


def test_obs_report_tool_show_and_diff(tmp_path, capsys):
    """tools/obs_report.py renders and diffs real artifacts."""
    import sys

    sys.path.insert(
        0,
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools",
        ),
    )
    import obs_report as tool

    (tmp_path / "d").mkdir()
    info = _synthetic.write_session(str(tmp_path / "d"), n_markers=48)
    dir_a, dir_b = str(tmp_path / "a"), str(tmp_path / "b")
    _run_pipeline(_QUERY_TMPL.format(info=info) + f"&report={dir_a}")
    _run_pipeline(_QUERY_TMPL.format(info=info) + f"&report={dir_b}")
    a = os.path.join(dir_a, "run_report.json")
    b = os.path.join(dir_b, "run_report.json")

    assert tool.main(["show", a]) == 0
    out = capsys.readouterr().out
    assert "RUN report" in out and "stages:" in out

    assert tool.main(["diff", a, b]) == 0
    out = capsys.readouterr().out
    assert "statistics: IDENTICAL" in out
