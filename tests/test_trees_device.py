"""Device (XLA) forest growth vs the host reference grower."""

import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.models import registry, trees, trees_device


def _toy(n=400, d=6, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d)
    y = ((x[:, 0] + 0.5 * x[:, 2] - 0.25 * x[:, 4]) > 0).astype(np.float64)
    flip = rng.rand(n) < 0.05
    y[flip] = 1 - y[flip]
    return x, y


def test_single_tree_matches_host_exactly():
    """No feature subsetting: device and host growers must pick the
    same splits and predict identically on train and held-out data."""
    x, y = _toy()
    xt, yt = _toy(seed=1)

    host = trees.DecisionTreeClassifier(backend="host")
    host.set_config(
        {
            "config_max_bins": "16",
            "config_impurity": "gini",
            "config_max_depth": "4",
            "config_min_instances_per_node": "2",
        }
    )
    host.fit(x, y)

    dev = trees.DecisionTreeClassifier(backend="device")
    dev.set_config(host.config)
    dev.fit(x, y)

    np.testing.assert_array_equal(dev.predict(x), host.predict(x))
    np.testing.assert_array_equal(dev.predict(xt), host.predict(xt))
    # root split agreement pins the gain computation, not just outputs
    assert dev.trees[0]["feature"][0] == host.trees[0]["feature"][0]
    assert dev.trees[0]["threshold_bin"][0] == host.trees[0]["threshold_bin"][0]


def test_single_tree_matches_host_entropy_defaults():
    x, y = _toy(seed=2)
    host = trees.DecisionTreeClassifier(backend="host")
    host.set_config(
        {
            "config_max_bins": "8",
            "config_impurity": "entropy",
            "config_max_depth": "3",
            "config_min_instances_per_node": "1",
        }
    )
    host.fit(x, y)
    dev = trees.DecisionTreeClassifier(backend="device")
    dev.set_config(host.config)
    dev.fit(x, y)
    np.testing.assert_array_equal(dev.predict(x), host.predict(x))


def test_device_forest_accuracy_and_determinism():
    x, y = _toy(n=600)
    xt, yt = _toy(n=300, seed=3)
    cfg = {
        "config_max_bins": "16",
        "config_impurity": "gini",
        "config_max_depth": "5",
        "config_min_instances_per_node": "1",
        "config_num_trees": "20",
        "config_feature_subset": "sqrt",
    }
    a = trees.RandomForestClassifier(backend="device")
    a.set_config(cfg)
    a.fit(x, y)
    acc = (a.predict(xt) == yt).mean()
    assert acc > 0.85

    b = trees.RandomForestClassifier(backend="device")
    b.set_config(cfg)
    b.fit(x, y)
    np.testing.assert_array_equal(a.predict(xt), b.predict(xt))


def test_predict_forest_device_matches_host_walk():
    x, y = _toy()
    import jax.numpy as jnp

    edges = trees.compute_bin_edges(x, 16)
    binned = trees.bin_features(x, edges)
    masks = trees_device.draw_feature_masks(3, trees_device.n_heap_nodes(3), 6, 3)
    rng = np.random.RandomState(12345)
    boot = rng.randint(0, len(y), size=(3, len(y)))
    forest = trees_device.grow_forest(
        jnp.asarray(binned, jnp.int32),
        jnp.asarray(y.astype(np.int64), jnp.int32),
        jnp.asarray(boot, jnp.int32),
        jnp.asarray(masks),
        max_bins=16,
        impurity="gini",
        max_depth=4,
        min_instances=1,
    )
    dev_votes = np.asarray(
        trees_device.predict_forest(forest, jnp.asarray(binned, jnp.int32), 4)
    )
    host_arrays = trees_device.heap_to_host_arrays(forest)
    host_votes = np.stack(
        [trees._predict_tree(t, binned) for t in host_arrays]
    ).mean(axis=0)
    np.testing.assert_allclose(dev_votes, host_votes, atol=1e-6)


def test_device_backend_save_load_roundtrip(tmp_path):
    x, y = _toy()
    clf = trees.RandomForestClassifier(backend="device")
    clf.set_config(
        {
            "config_max_bins": "8",
            "config_impurity": "gini",
            "config_max_depth": "3",
            "config_min_instances_per_node": "1",
            "config_num_trees": "5",
            "config_feature_subset": "sqrt",
        }
    )
    clf.fit(x, y)
    path = str(tmp_path / "forest")
    clf.save(path)
    clf2 = trees.RandomForestClassifier()
    clf2.load(path)
    np.testing.assert_array_equal(clf2.predict(x), clf.predict(x))


def test_device_backend_rejects_deep_trees():
    x, y = _toy(n=100)
    clf = trees.DecisionTreeClassifier(backend="device")
    clf.set_config(
        {
            "config_max_bins": "8",
            "config_impurity": "gini",
            "config_max_depth": str(trees_device.MAX_DEVICE_DEPTH + 1),
            "config_min_instances_per_node": "1",
        }
    )
    with pytest.raises(ValueError, match="backend='host'"):
        clf.fit(x, y)


def test_unknown_config_backend_rejected():
    x, y = _toy(n=100)
    clf = trees.DecisionTreeClassifier()
    clf.set_config({"config_backend": "tpu"})
    with pytest.raises(ValueError, match="unknown tree backend"):
        clf.fit(x, y)
    with pytest.raises(ValueError, match="unknown tree backend"):
        trees.DecisionTreeClassifier(backend="Device")


def test_registry_tpu_variants():
    assert isinstance(registry.create("dt-tpu"), trees.DecisionTreeClassifier)
    rf = registry.create("rf-tpu")
    assert isinstance(rf, trees.RandomForestClassifier)
    assert rf.backend == "device"


def test_config_backend_key_selects_device():
    x, y = _toy(n=200)
    clf = trees.DecisionTreeClassifier()  # host default
    clf.set_config(
        {
            "config_max_bins": "8",
            "config_impurity": "gini",
            "config_max_depth": "3",
            "config_min_instances_per_node": "1",
            "config_backend": "device",
        }
    )
    clf.fit(x, y)
    # heap layout is the device grower's signature: left child of a
    # split root is node 1
    if clf.trees[0]["feature"][0] >= 0:
        assert clf.trees[0]["left"][0] == 1


def test_grow_forest_sharded_matches_unsharded():
    """Tree-parallel growth over the mesh produces the exact same
    forest as the single-device lax.map path, including when T is not
    a multiple of the mesh size (pad-with-repeats then trim)."""
    import jax
    import jax.numpy as jnp

    from eeg_dataanalysispackage_tpu.parallel import mesh as pmesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    x, y = _toy(n=200)
    edges = trees.compute_bin_edges(x, 16)
    binned = trees.bin_features(x, edges)
    yi = y.astype(np.int64)
    mesh = pmesh.make_mesh(8)
    for T in (8, 11):  # even and ragged tree counts
        rng = np.random.RandomState(12345)
        boot = rng.randint(0, len(y), size=(T, len(y)))
        masks = trees_device.draw_feature_masks(
            T, trees_device.n_heap_nodes(3), 6, 3
        )
        ref = trees_device.grow_forest(
            jnp.asarray(binned, jnp.int32),
            jnp.asarray(yi, jnp.int32),
            jnp.asarray(boot, jnp.int32),
            jnp.asarray(masks),
            max_bins=16,
            impurity="gini",
            max_depth=4,
            min_instances=1,
        )
        sharded = trees_device.grow_forest_sharded(
            binned,
            yi,
            boot,
            masks,
            mesh=mesh,
            max_bins=16,
            impurity="gini",
            max_depth=4,
            min_instances=1,
        )
        for k in ref:
            np.testing.assert_array_equal(
                np.asarray(ref[k]), np.asarray(sharded[k]), err_msg=k
            )


def test_regression_grower_matches_host_exactly():
    """Quantized residuals (multiples of 1/64, f32-exact sums): the
    device regression grower must choose the same splits and leaf
    means as the host _grow_regression_tree."""
    import jax.numpy as jnp

    rng = np.random.RandomState(4)
    n, d = 300, 5
    x = rng.randn(n, d)
    residual = rng.randint(-64, 65, size=n).astype(np.float64) / 64.0
    edges = trees.compute_bin_edges(x, 16)
    binned = trees.bin_features(x, edges)

    host = trees._grow_regression_tree(binned, residual, 16, 4, 1)
    host_arrays = host.to_arrays()
    dev = trees_device._grow_one_reg(
        jnp.asarray(binned, jnp.int32),
        jnp.asarray(residual, jnp.float32),
        max_bins=16,
        max_depth=4,
        min_instances=1,
    )
    dev_trees = trees_device.heap_to_host_arrays(
        {k: np.asarray(v)[None] for k, v in dev.items()}
    )
    got = trees._predict_tree(dev_trees[0], binned)
    want = trees._predict_tree(host_arrays, binned)
    np.testing.assert_allclose(got, want, rtol=0, atol=1e-6)


def test_gbt_device_matches_host_predictions():
    """Few boosting rounds on clean data: gbt-tpu and host gbt agree
    prediction-for-prediction (trajectel parity; the f32 device loop
    may diverge on pathological ties only)."""
    x, y = _toy(seed=9)
    cfg = {
        "config_num_iterations": "15",
        "config_learning_rate": "0.2",
        "config_max_depth": "3",
    }
    host = trees.GradientBoostedTreesClassifier()
    host.set_config(cfg)
    host.fit(x, y)
    dev = trees.GradientBoostedTreesClassifier(backend="device")
    dev.set_config(cfg)
    dev.fit(x, y)
    hp = host.predict(x)
    dp = dev.predict(x)
    assert (hp == dp).mean() >= 0.99
    assert (dp == y).mean() >= 0.9  # it actually learned


def test_gbt_tpu_registry_and_save_load(tmp_path):
    x, y = _toy(seed=11)
    clf = registry.create("gbt-tpu")
    clf.set_config({})  # MLlib defaults: 100 rounds, lr 0.1, depth 3
    clf.fit(x, y)
    acc = (clf.predict(x) == y).mean()
    assert acc >= 0.9
    path = str(tmp_path / "gbt")
    clf.save(path)
    clf2 = registry.create("gbt")  # host class loads device-grown trees
    clf2.load(path)
    np.testing.assert_array_equal(clf2.predict(x), clf.predict(x))


def test_gbt_device_rejects_deep_trees():
    clf = trees.GradientBoostedTreesClassifier(backend="device")
    clf.set_config({
        "config_num_iterations": "2",
        "config_learning_rate": "0.1",
        "config_max_depth": "13",
    })
    x, y = _toy(n=50)
    with pytest.raises(ValueError, match="max_depth"):
        clf.fit(x, y)


def test_device_linked_predict_matches_host_walk():
    """predict_linked_forest on the host tree format == the host
    per-tree walk, for both device- and host-grown forests."""
    import jax.numpy as jnp

    x, y = _toy(seed=13)
    for backend in ("host", "device"):
        clf = trees.RandomForestClassifier(backend=backend)
        clf.set_config({
            "config_max_bins": "16", "config_impurity": "gini",
            "config_max_depth": "4",
            "config_min_instances_per_node": "1",
            "config_num_trees": "9", "config_feature_subset": "all",
        })
        clf.fit(x, y)
        binned = trees.bin_features(x, clf.edges)
        votes_dev = np.asarray(
            trees_device.predict_linked_forest(
                *trees_device.host_trees_to_device(clf.trees),
                jnp.asarray(binned, jnp.int32),
            )
        )
        votes_host = np.stack(
            [trees._predict_tree(t, binned) for t in clf.trees]
        )
        np.testing.assert_array_equal(votes_dev, votes_host)


def test_chunked_linked_predict_matches_monolith():
    """The lax.map row-chunked form (the r4 worker-fault fallback
    probe) must be vote-identical to the monolithic walk, including
    when n is not a chunk multiple (rejected loudly)."""
    import jax.numpy as jnp

    x, y = _toy(seed=17, n=64)
    clf = trees.RandomForestClassifier(backend="host")
    clf.set_config({
        "config_max_bins": "16", "config_impurity": "gini",
        "config_max_depth": "4",
        "config_min_instances_per_node": "1",
        "config_num_trees": "7", "config_feature_subset": "all",
    })
    clf.fit(x, y)
    binned = jnp.asarray(trees.bin_features(x, clf.edges), jnp.int32)
    packed = trees_device.host_trees_to_device(clf.trees)
    mono = np.asarray(
        trees_device.predict_linked_forest(*packed, binned)
    )
    chunked = np.asarray(
        trees_device.predict_linked_forest_chunked(
            *packed, binned, row_chunk=16
        )
    )
    np.testing.assert_array_equal(mono, chunked)
    with pytest.raises(ValueError, match="multiple of row_chunk"):
        trees_device.predict_linked_forest_chunked(
            *packed, binned, row_chunk=48
        )


def test_rf_tpu_predict_routes_through_device(monkeypatch):
    """rf-tpu fit+predict agrees with the host forest walk of the
    same trees AND actually takes the device inference path (a
    routing regression would otherwise pass silently — both branches
    walk the same trees)."""
    x, y = _toy(seed=14)
    clf = registry.create("rf-tpu")
    clf.set_config({
        "config_max_bins": "16", "config_impurity": "gini",
        "config_max_depth": "4", "config_min_instances_per_node": "1",
        "config_num_trees": "7", "config_feature_subset": "all",
    })
    clf.fit(x, y)
    calls = {"n": 0}
    real = trees_device.predict_linked_forest

    def spy(*args, **kwargs):
        calls["n"] += 1
        return real(*args, **kwargs)

    pack_calls = {"n": 0}
    real_pack = trees_device.host_trees_to_device

    def pack_spy(*args, **kwargs):
        pack_calls["n"] += 1
        return real_pack(*args, **kwargs)

    monkeypatch.setattr(trees_device, "predict_linked_forest", spy)
    monkeypatch.setattr(trees_device, "host_trees_to_device", pack_spy)
    got = clf.predict(x)
    assert calls["n"] == 1, "rf-tpu predict did not take the device path"
    binned = trees.bin_features(x, clf.edges)
    votes = np.stack([trees._predict_tree(t, binned) for t in clf.trees])
    want = (votes.mean(axis=0) > 0.5).astype(np.float64)
    np.testing.assert_array_equal(got, want)
    # the packed forest is cached: a second predict walks again but
    # does NOT repack/re-upload the forest
    clf.predict(x)
    assert calls["n"] == 2
    assert pack_calls["n"] == 1
