"""Pallas feature-extraction kernel tests (interpret mode on CPU).

The kernel fuses slice -> cascade matmul -> channel concat -> L2
normalize in one pallas_call; on TPU it compiles to Mosaic (measured
~11.0M epochs/s on v5e-1; the XLA einsum default is ~29.3M — see
ops/dwt_pallas.py). Parity here is against the golden-pinned host path
and the XLA path.
"""

import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.features import registry, wavelet
from eeg_dataanalysispackage_tpu.ops import dwt as dwt_xla, dwt_pallas


def test_pallas_matches_xla_einsum():
    rng = np.random.RandomState(0)
    ep = rng.randn(37, 3, 750).astype(np.float32) * 50.0
    ref = np.asarray(dwt_xla.epoch_features(ep))
    pal = np.asarray(dwt_pallas.epoch_features_pallas(ep))
    assert pal.shape == (37, 48)
    np.testing.assert_allclose(pal, ref, atol=5e-7)


def test_pallas_matches_host_golden_path(fixture_dir):
    from eeg_dataanalysispackage_tpu.io import provider

    batch = provider.OfflineDataProvider(
        [fixture_dir + "/infoTrain.txt"]
    ).load()
    host = registry.create("dwt-8").extract_batch(batch.epochs)
    pal = registry.create("dwt-8-pallas").extract_batch(batch.epochs)
    assert pal.shape == (11, 48)
    # host is float64 bit-parity; pallas is f32 single-rounding
    np.testing.assert_allclose(pal, host, atol=5e-5)


def test_pallas_batch_not_multiple_of_tile():
    rng = np.random.RandomState(1)
    ep = rng.randn(5, 3, 750).astype(np.float32)
    out = np.asarray(dwt_pallas.epoch_features_pallas(ep, tile_b=4))
    ref = np.asarray(dwt_xla.epoch_features(ep))
    np.testing.assert_allclose(out, ref, atol=5e-7)


def test_pallas_window_validation():
    with pytest.raises(ValueError, match="exceeds epoch length"):
        dwt_pallas.epoch_features_pallas(
            np.zeros((2, 3, 600), np.float32), skip_samples=175, epoch_size=512
        )


def test_pallas_backend_registered():
    fe = registry.create("dwt-8-pallas")
    assert isinstance(fe, wavelet.WaveletTransform)
    assert fe.backend == "pallas"
    # generic family spelling too
    assert registry.create("dwt-4-pallas").name == 4


def test_pallas_selects_configured_channels():
    """Extra input channels must be reduced to the configured triplet,
    matching the host/xla backends (code-review finding)."""
    rng = np.random.RandomState(2)
    five = rng.randn(4, 5, 750) * 30.0
    host = wavelet.WaveletTransform(backend="host").extract_batch(five)
    pal = wavelet.WaveletTransform(backend="pallas").extract_batch(five)
    assert host.shape == pal.shape == (4, 48)
    np.testing.assert_allclose(pal, host, atol=5e-5)
