"""Fused-ingest kernels: Pallas irregular path + regular stimulus train.

Pins the ops/ingest_pallas.py kernel (interpret mode on CPU) and the
regular-stride static ingest against the established XLA device-ingest
path (itself pinned against the bit-exact host path in
tests/test_device_ingest.py).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from eeg_dataanalysispackage_tpu.ops import (  # noqa: E402
    device_ingest,
    dwt as dwt_xla,
    ingest_pallas,
)


def xla_reference_features(raw, res, positions):
    """Features via the XLA epocher + extractor (the pinned path)."""
    n = len(positions)
    cap = ((n + 63) // 64) * 64
    pos_pad = np.zeros(cap, np.int32)
    pos_pad[:n] = positions
    mask = np.zeros(cap, bool)
    mask[:n] = True
    epocher = device_ingest.make_device_epocher()
    epochs = epocher(
        jnp.asarray(np.pad(raw, ((0, 0), (0, 900)))),
        jnp.asarray(res),
        jnp.asarray(pos_pad),
        jnp.asarray(mask),
    )
    return np.asarray(dwt_xla.make_batched_extractor()(epochs))[:n]


@pytest.fixture(scope="module")
def fixture_raw():
    rng = np.random.RandomState(0)
    raw = rng.randint(-3000, 3000, size=(3, 120000), dtype=np.int16)
    res = np.array([0.1, 0.1, 0.2], np.float32)
    return raw, res


def test_pallas_matches_xla_ingest(fixture_raw):
    raw, res = fixture_raw
    rng = np.random.RandomState(1)
    positions = rng.choice(
        np.arange(200, raw.shape[1] - 800), size=41, replace=False
    ).astype(np.int64)  # unsorted on purpose: output must be input-order
    got = np.asarray(ingest_pallas.ingest_features_pallas(raw, res, positions))
    want = xla_reference_features(raw, res, positions)
    assert got.shape == want.shape == (41, 48)
    np.testing.assert_allclose(got, want, rtol=0, atol=5e-6)


def test_pallas_dense_markers_small_chunk(fixture_raw):
    """Markers denser than a tile's span: plan must split tiles
    correctly and windows near half-chunk boundaries must read across
    the two half blocks."""
    raw, res = fixture_raw
    positions = (100 + 173 * np.arange(300)).astype(np.int64)
    got = np.asarray(
        ingest_pallas.ingest_features_pallas(
            raw, res, positions, chunk=8192, tile_b=8
        )
    )
    want = xla_reference_features(raw, res, positions)
    np.testing.assert_allclose(got, want, rtol=0, atol=5e-6)


def test_pallas_single_epoch(fixture_raw):
    raw, res = fixture_raw
    got = np.asarray(
        ingest_pallas.ingest_features_pallas(
            raw, res, np.array([5000], dtype=np.int64)
        )
    )
    want = xla_reference_features(raw, res, np.array([5000]))
    np.testing.assert_allclose(got, want, rtol=0, atol=5e-6)


def test_plan_tiles_packing():
    positions = np.array([100, 900, 1700, 60000, 60800], dtype=np.int64)
    plan = ingest_pallas.plan_pallas_tiles(
        positions, chunk=65536, tile_b=4
    )
    # first three windows share a chunk; the 60000s pair starts a new
    # tile only if it overflows the first tile's aligned chunk —
    # 60800-100+800 <= 65536 so all five could fit but tile_b=4 splits
    assert plan.n_tiles == 2
    assert (plan.src_rows >= 0).sum() == 5
    # every offset in range for its chunk
    assert (plan.offsets >= 0).all()
    assert (plan.offsets <= plan.chunk - 800).all()


def test_plan_rejects_negative_start():
    with pytest.raises(ValueError):
        ingest_pallas.plan_pallas_tiles(np.array([50], dtype=np.int64))


def test_ingest_matrix_folds_baseline():
    """E applied to a raw window == baseline-correct + slice + cascade."""
    rng = np.random.RandomState(3)
    x = rng.randn(787).astype(np.float64) * 40
    E = device_ingest.ingest_matrix(window_len=800).astype(np.float64)
    got = np.pad(x, (0, 13)) @ E
    corrected = x[100:] - x[:100].mean()
    W = np.asarray(dwt_xla.cascade_matrix(8, 512, 16))
    want = corrected[175 : 175 + 512] @ W
    # E is stored float32 (the device operand dtype)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_regular_ingest_matches_xla(fixture_raw):
    raw, res = fixture_raw
    n, stride, first = 30, 800, 150
    ing = device_ingest.make_regular_ingest_featurizer(stride, n)
    got = np.asarray(ing(jnp.asarray(raw), jnp.asarray(res), first))
    positions = first + stride * np.arange(n)
    want = xla_reference_features(raw, res, positions)
    np.testing.assert_allclose(got, want, rtol=0, atol=5e-6)


def test_regular_ingest_rejects_overlapping_stride():
    with pytest.raises(ValueError):
        device_ingest.make_regular_ingest_featurizer(700, 10)


def _dc_heavy_fixture(n=30, stride=800, first=150, drift=0.0, tail=0):
    """Synthetic int16 stream with near-int16-range DC offsets and
    optional slow per-channel baseline drift across the recording."""
    rng = np.random.RandomState(0)
    dc = np.array([[1800], [-2200], [900]], np.float64)
    S = first - 100 + n * stride + 100 + tail
    t = np.linspace(0.0, 1.0, S)[None, :]
    wander = drift * np.array([[1.0], [-1.0], [0.5]]) * t
    raw = np.clip(
        rng.randint(-3000, 3000, size=(3, S)) + dc + wander,
        -32768, 32767,
    ).astype(np.int16)
    res = np.array([0.1, 0.1, 0.2], np.float32)
    return raw, res


@pytest.mark.parametrize("formulation", ["conv", "phase"])
def test_regular_ingest_formulations_dc_heavy(formulation):
    """The TPU-friendly formulations (no lane-unaligned reshape) must
    match the subtract-first reshape formulation to f32 tolerance with
    int16-range DC offsets — their DC proxies keep the two-term
    baseline from cancelling catastrophically (docs/ingest_kernel.md).
    ``tail`` gives the phase path its aligned-slab slack."""
    n, stride, first = 30, 800, 150
    raw, res = _dc_heavy_fixture(n, stride, first, tail=8192)
    ing_r = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="reshape"
    )
    ing_f = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation=formulation
    )
    a = np.asarray(ing_r(jnp.asarray(raw), jnp.asarray(res), first))
    b = np.asarray(ing_f(jnp.asarray(raw), jnp.asarray(res), first))
    np.testing.assert_allclose(b, a, rtol=0, atol=5e-6)


def test_regular_ingest_conv_drift_within_device_tolerance():
    """The conv formulation's single global DC proxy degrades under
    baseline drift (documented caveat) but must stay inside the
    framework's device-path tolerance (2e-4, the same bound the
    fused gather path is held to)."""
    n, stride, first = 30, 800, 150
    raw, res = _dc_heavy_fixture(n, stride, first, drift=2500.0, tail=8192)
    ing_r = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="reshape"
    )
    ing_c = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="conv"
    )
    a = np.asarray(ing_r(jnp.asarray(raw), jnp.asarray(res), first))
    b = np.asarray(ing_c(jnp.asarray(raw), jnp.asarray(res), first))
    np.testing.assert_allclose(b, a, rtol=0, atol=2e-4)


def test_regular_ingest_phase_guard_on_odd_stride():
    """Odd strides give group size 128 (GB-scale tables): auto must
    resolve away from phase and an explicit phase request must fail
    loudly instead of OOMing."""
    assert (
        device_ingest.resolve_regular_formulation("auto", 787)
        in ("reshape", "conv")  # cpu -> reshape; accelerator -> conv
    )
    with pytest.raises(ValueError):
        device_ingest.make_regular_ingest_featurizer(
            801, 10, formulation="phase"
        )


def test_regular_ingest_phase_exact_under_drift():
    """The phase formulation's per-row DC proxy is exactly invariant,
    so slow baseline wander (electrode drift) must NOT degrade it —
    unlike the conv path's single global proxy."""
    n, stride, first = 30, 800, 150
    raw, res = _dc_heavy_fixture(n, stride, first, drift=2500.0, tail=8192)
    ing_r = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="reshape"
    )
    ing_p = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="phase"
    )
    a = np.asarray(ing_r(jnp.asarray(raw), jnp.asarray(res), first))
    b = np.asarray(ing_p(jnp.asarray(raw), jnp.asarray(res), first))
    np.testing.assert_allclose(b, a, rtol=0, atol=5e-6)


@pytest.mark.parametrize("first", [150, 1000, 887, 3250, 4000])
def test_regular_ingest_phase_arbitrary_first_position(first):
    """Regression: phase table placement must be correct for ANY
    marker phase — first=1000 (start 900 >= stride) once misplaced
    every 4th window's taps because offsets past ROW were clamped to
    next-row offset 0 instead of off-ROW."""
    n, stride = 13, 800
    raw, res = _dc_heavy_fixture(n, stride, first, tail=16384)
    ing_r = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="reshape"
    )
    ing_p = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="phase"
    )
    a = np.asarray(ing_r(jnp.asarray(raw), jnp.asarray(res), first))
    b = np.asarray(ing_p(jnp.asarray(raw), jnp.asarray(res), first))
    np.testing.assert_allclose(b, a, rtol=0, atol=5e-6)


@pytest.mark.parametrize("stride", [800, 832, 896, 1024, 960])
def test_regular_ingest_phase_across_group_sizes(stride):
    """The phase formulation must be exact for every lane-tile group
    size its guard admits: stride 800 -> G=4 rows of 3200, 832 ->
    G=2, 896/1024 -> G=1, 960 -> G=2 — including windows crossing
    the row boundary at awkward phases."""
    from eeg_dataanalysispackage_tpu.ops.device_ingest import _phase_group

    assert _phase_group(stride) <= 4  # all admitted by the guard
    n, first = 11, 150 + (stride // 3)
    raw, res = _dc_heavy_fixture(
        n, stride, first, tail=4 * _phase_group(stride) * stride + 8192
    )
    ing_r = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="reshape"
    )
    ing_p = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="phase"
    )
    a = np.asarray(ing_r(jnp.asarray(raw), jnp.asarray(res), first))
    b = np.asarray(ing_p(jnp.asarray(raw), jnp.asarray(res), first))
    np.testing.assert_allclose(b, a, rtol=0, atol=5e-6)


def test_regular_ingest_phase_short_recording_falls_back():
    """A recording too short for the aligned slab still returns exact
    features via the reshape fallback."""
    n, stride, first = 4, 800, 150
    raw, res = _dc_heavy_fixture(n, stride, first, tail=0)
    ing_p = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="phase"
    )
    ing_r = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="reshape"
    )
    a = np.asarray(ing_r(jnp.asarray(raw), jnp.asarray(res), first))
    b = np.asarray(ing_p(jnp.asarray(raw), jnp.asarray(res), first))
    np.testing.assert_allclose(b, a, rtol=0, atol=5e-6)


def test_regular_ingest_rejects_unknown_formulation():
    with pytest.raises(ValueError):
        device_ingest.make_regular_ingest_featurizer(
            800, 10, formulation="cuda"
        )


def test_block_ingest_matches_gather_featurizer():
    """The 128-variant block-gather irregular path must match the
    gather+einsum featurizer to f32 tolerance on DC-heavy data, with
    every one of the 128 shift-residue classes exercised (positions
    step by a stride coprime to 128, so start % 128 cycles through
    all variants — a placement bug in any bank column fails here)."""
    rng = np.random.RandomState(7)
    n, cap = 128, 192
    dc = np.array([[1800], [-2200], [900]], np.int16)
    step = 901  # coprime to 128 -> all residues in 128 windows
    positions = (200 + step * np.arange(n)).astype(np.int32)
    assert len(set((positions - 100) % 128)) == 128
    S = int(positions.max()) + 2000
    raw = (rng.randint(-3000, 3000, size=(3, S)) + dc).astype(np.int16)
    res = np.array([0.1, 0.1, 0.2], np.float32)
    pos = np.zeros(cap, np.int32)
    pos[:n] = positions
    mask = np.zeros(cap, bool)
    mask[:n] = True
    gather = device_ingest.make_device_ingest_featurizer()
    block = device_ingest.make_block_ingest_featurizer()
    want = np.asarray(
        gather(jnp.asarray(raw), jnp.asarray(res), jnp.asarray(pos),
               jnp.asarray(mask))
    )
    got = np.asarray(
        block(jnp.asarray(raw), jnp.asarray(res), jnp.asarray(pos),
              jnp.asarray(mask))
    )
    assert got.shape == want.shape == (cap, 48)
    np.testing.assert_allclose(got, want, rtol=0, atol=5e-6)
    # padded rows zeroed in both
    assert np.abs(got[n:]).max() == 0.0


def test_block_ingest_start_edge_matches_gather():
    """Windows starting at the very first valid sample (position ==
    pre -> start 0, shift 0, block 0) match the gather path."""
    rng = np.random.RandomState(5)
    raw = rng.randint(-3000, 3000, size=(3, 6000)).astype(np.int16)
    res = np.array([0.1, 0.1, 0.2], np.float32)
    pos = np.array([100, 101, 227], np.int32)  # start 0, 1, 127
    mask = np.ones(3, bool)
    gather = device_ingest.make_device_ingest_featurizer()
    block = device_ingest.make_block_ingest_featurizer()
    want = np.asarray(
        gather(jnp.asarray(raw), jnp.asarray(res), jnp.asarray(pos),
               jnp.asarray(mask))
    )
    got = np.asarray(
        block(jnp.asarray(raw), jnp.asarray(res), jnp.asarray(pos),
              jnp.asarray(mask))
    )
    np.testing.assert_allclose(got, want, rtol=0, atol=5e-6)


def test_block_ingest_window_overhang_reads_zeros():
    """A window overhanging the end of the recording zero-pads (Java
    copyOfRange semantics), exactly like the gather path."""
    rng = np.random.RandomState(3)
    S = 4000
    raw = rng.randint(-3000, 3000, size=(3, S)).astype(np.int16)
    res = np.array([0.1, 0.1, 0.2], np.float32)
    pos = np.array([S - 300, 500], np.int32)  # first overhangs
    mask = np.ones(2, bool)
    gather = device_ingest.make_device_ingest_featurizer()
    block = device_ingest.make_block_ingest_featurizer()
    want = np.asarray(
        gather(jnp.asarray(raw), jnp.asarray(res), jnp.asarray(pos),
               jnp.asarray(mask))
    )
    got = np.asarray(
        block(jnp.asarray(raw), jnp.asarray(res), jnp.asarray(pos),
              jnp.asarray(mask))
    )
    np.testing.assert_allclose(got, want, rtol=0, atol=5e-6)


def test_provider_block_backend_matches_xla(fixture_dir):
    from eeg_dataanalysispackage_tpu.io import provider

    odp_x = provider.OfflineDataProvider([fixture_dir + "/infoTrain.txt"])
    fx, tx = odp_x.load_features_device()
    odp_b = provider.OfflineDataProvider([fixture_dir + "/infoTrain.txt"])
    fb, tb = odp_b.load_features_device(backend="block")
    assert fx.shape == fb.shape == (11, 48)
    np.testing.assert_array_equal(tx, tb)
    # both paths sit at the f32 ingest floor vs the f64 truth on the
    # real fixture (block 9.6e-5, gather 1.1e-4 measured); their
    # mutual deviation is that same noise, not a formulation error
    np.testing.assert_allclose(fb, fx, rtol=0, atol=5e-5)


def test_provider_pallas_backend_matches_xla(fixture_dir):
    """load_features_device(backend='pallas') returns the same rows
    (to f32 tolerance) and targets as the XLA gather backend on the
    reference fixture."""
    from eeg_dataanalysispackage_tpu.io import provider

    odp_x = provider.OfflineDataProvider([fixture_dir + "/infoTrain.txt"])
    fx, tx = odp_x.load_features_device()
    odp_p = provider.OfflineDataProvider([fixture_dir + "/infoTrain.txt"])
    fp, tp = odp_p.load_features_device(backend="pallas")
    assert fx.shape == fp.shape == (11, 48)
    np.testing.assert_array_equal(tx, tp)
    np.testing.assert_allclose(fp, fx, rtol=0, atol=5e-6)


def test_fused_pallas_pipeline_query_mode(fixture_dir, tmp_path):
    """fe=dwt-8-fused-pallas drives the whole query pipeline through
    the Pallas ingest kernel."""
    from eeg_dataanalysispackage_tpu.pipeline import builder

    result = tmp_path / "result.txt"
    q = (
        f"info_file={fixture_dir}/infoTrain.txt&fe=dwt-8-fused-pallas"
        f"&train_clf=logreg&result_path={result}"
    )
    stats = builder.PipelineBuilder(q).execute()
    assert stats.num_patterns == 11 - int(0.7 * 11)
    assert "Accuracy:" in result.read_text()


def test_fused_block_pipeline_query_mode(fixture_dir, tmp_path):
    """fe=dwt-8-fused-block drives the whole query pipeline through
    the block-gather ingest formulation."""
    from eeg_dataanalysispackage_tpu.pipeline import builder

    result = tmp_path / "result.txt"
    q = (
        f"info_file={fixture_dir}/infoTrain.txt&fe=dwt-8-fused-block"
        f"&train_clf=logreg&result_path={result}"
    )
    stats = builder.PipelineBuilder(q).execute()
    assert stats.num_patterns == 11 - int(0.7 * 11)
    assert "Accuracy:" in result.read_text()


def test_fused_generic_wavelet_index(fixture_dir, tmp_path):
    """The fused modes accept any registry wavelet (dwt-<i>-fused*),
    like the host fe= family; features match the host extractor for
    the same index to device-path tolerance."""
    from eeg_dataanalysispackage_tpu.features import wavelet
    from eeg_dataanalysispackage_tpu.io import provider

    odp = provider.OfflineDataProvider([fixture_dir + "/infoTrain.txt"])
    f4, _ = odp.load_features_device(wavelet_index=4)
    batch = provider.OfflineDataProvider(
        [fixture_dir + "/infoTrain.txt"]
    ).load()
    wt = wavelet.WaveletTransform(4, 512, 175, 16)
    host = np.stack(
        [wt.extract_features(e) for e in np.asarray(batch.epochs)]
    )
    np.testing.assert_allclose(f4, host, rtol=0, atol=5e-4)

    from eeg_dataanalysispackage_tpu.pipeline import builder

    result = tmp_path / "r.txt"
    stats = builder.PipelineBuilder(
        f"info_file={fixture_dir}/infoTrain.txt&fe=dwt-4-fused-block"
        f"&train_clf=logreg&result_path={result}"
    ).execute()
    assert stats.num_patterns == 4


def test_provider_rejects_unknown_backend(fixture_dir):
    from eeg_dataanalysispackage_tpu.io import provider

    odp = provider.OfflineDataProvider([fixture_dir + "/infoTrain.txt"])
    with pytest.raises(ValueError):
        odp.load_features_device(backend="cuda")


def test_regular_ingest_bounds_check(fixture_raw):
    """dynamic_slice would clamp out-of-range starts and silently
    shift every window; the wrapper must raise instead."""
    raw, res = fixture_raw
    ing = device_ingest.make_regular_ingest_featurizer(800, 10)
    with pytest.raises(ValueError):
        ing(jnp.asarray(raw[:, : 10 * 800]), jnp.asarray(res), 150)
    with pytest.raises(ValueError):
        ing(jnp.asarray(raw), jnp.asarray(res), 50)  # first < pre


def test_pallas_jit_key_is_bucketed(fixture_raw):
    """Different marker layouts of similar size must reuse the same
    compiled kernel: tile count and padded raw length are bucketed."""
    raw, res = fixture_raw
    pos_a = (200 + 900 * np.arange(40)).astype(np.int64)
    pos_b = (350 + 911 * np.arange(43)).astype(np.int64)
    window, chunk, tile_b = 800, 65536, 32
    for pos in (pos_a, pos_b):
        plan = ingest_pallas.plan_pallas_tiles(
            pos, window=window, chunk=chunk, tile_b=tile_b
        )
        assert plan.n_tiles <= 8  # both bucket to 8 tiles after padding
    before = ingest_pallas._ingest_tiles._cache_size()
    a = ingest_pallas.ingest_features_pallas(raw, res, pos_a)
    b = ingest_pallas.ingest_features_pallas(raw, res, pos_b)
    after = ingest_pallas._ingest_tiles._cache_size()
    assert after - before <= 1
    assert a.shape == (40, 48) and b.shape == (43, 48)


def test_pallas_window_overhangs_recording_end(fixture_raw):
    """Java copyOfRange zero-pads past the end; a marker whose window
    overhangs the recording must read zeros, exactly like the XLA
    epocher's padded path."""
    raw, res = fixture_raw
    S = raw.shape[1]
    positions = np.array([S - 300, 5000], dtype=np.int64)  # first overhangs
    got = np.asarray(ingest_pallas.ingest_features_pallas(raw, res, positions))
    want = xla_reference_features(raw, res, positions)
    np.testing.assert_allclose(got, want, rtol=0, atol=5e-6)


@pytest.mark.parametrize("kind", ["uniform", "clusters", "boundary"])
@pytest.mark.parametrize("seed", [11, 12])
def test_pallas_randomized_differential(fixture_raw, seed, kind):
    """Randomized differential check: each marker layout family
    (uniform, dense clusters with gaps, boundary-adjacent with
    duplicates) and random tile geometry must match the XLA path.
    Seeded — deterministic CI."""
    raw, res = fixture_raw
    rng = np.random.RandomState(seed)
    S = raw.shape[1]
    n = int(rng.randint(5, 120))
    if kind == "uniform":
        positions = rng.randint(100, S - 100, size=n)
    elif kind == "clusters":  # dense clusters with gaps
        n_centers = n // 10 + 1
        centers = rng.randint(200, S - 2000, size=n_centers)
        positions = np.concatenate(
            [c + rng.randint(0, 1500, size=10) for c in centers]
        )[:n]
        positions = np.clip(positions, 100, S - 100)
    else:  # boundary-adjacent + duplicates
        positions = np.concatenate([
            rng.randint(100, 400, size=n // 2 + 1),
            rng.randint(S - 900, S - 100, size=n // 2 + 1),
        ])[:n]
        positions[0] = positions[-1]  # duplicate
    assert len(positions) == n
    positions = positions.astype(np.int64)
    chunk = int(rng.choice([8192, 16384, 65536]))
    tile_b = int(rng.choice([4, 8, 32]))
    got = np.asarray(
        ingest_pallas.ingest_features_pallas(
            raw, res, positions, chunk=chunk, tile_b=tile_b
        )
    )
    want = xla_reference_features(raw, res, positions)
    np.testing.assert_allclose(got, want, rtol=0, atol=5e-6)


# -- aligned8 Pallas mode (the remote-compile-crash fix path) ---------
#
# Every dynamic lane slice in the aligned8 kernel lands on a sublane
# (8) boundary; the residual 0..7 shift goes through the 8-variant
# operator bank + one-hot select. Numerics follow the block
# formulation's two-term f32-safe shape, so the gate is the block
# path's 5e-5 (vs the exact kernel's 5e-6 subtract-first gate).


def test_pallas_aligned8_matches_xla_ingest(fixture_raw):
    raw, res = fixture_raw
    rng = np.random.RandomState(3)
    positions = rng.choice(
        np.arange(200, raw.shape[1] - 800), size=41, replace=False
    ).astype(np.int64)
    got = np.asarray(
        ingest_pallas.ingest_features_pallas(
            raw, res, positions, mode="aligned8"
        )
    )
    want = xla_reference_features(raw, res, positions)
    assert got.shape == want.shape == (41, 48)
    np.testing.assert_allclose(got, want, rtol=0, atol=5e-5)


def test_pallas_aligned8_covers_every_shift(fixture_raw):
    """One marker per residual shift 0..7 — each variant column of the
    bank must select correctly."""
    raw, res = fixture_raw
    positions = (4096 + 100 + np.arange(8) * (800 + 1)).astype(np.int64)
    assert sorted(set((p - 100) % 8 for p in positions)) == list(range(8))
    got = np.asarray(
        ingest_pallas.ingest_features_pallas(
            raw, res, positions, mode="aligned8"
        )
    )
    want = xla_reference_features(raw, res, positions)
    np.testing.assert_allclose(got, want, rtol=0, atol=5e-5)


def test_pallas_aligned8_small_chunk_and_overhang(fixture_raw):
    raw, res = fixture_raw
    S = raw.shape[1]
    positions = np.concatenate([
        (100 + 173 * np.arange(40)),
        [S - 300, 5000],  # overhanging window reads zeros
    ]).astype(np.int64)
    got = np.asarray(
        ingest_pallas.ingest_features_pallas(
            raw, res, positions, chunk=8192, tile_b=8, mode="aligned8"
        )
    )
    want = xla_reference_features(raw, res, positions)
    np.testing.assert_allclose(got, want, rtol=0, atol=5e-5)


@pytest.mark.parametrize("seed", [21, 22])
def test_pallas_aligned8_randomized_differential(fixture_raw, seed):
    raw, res = fixture_raw
    rng = np.random.RandomState(seed)
    S = raw.shape[1]
    n = int(rng.randint(5, 100))
    positions = rng.randint(100, S - 100, size=n).astype(np.int64)
    chunk = int(rng.choice([8192, 16384, 65536]))
    tile_b = int(rng.choice([4, 8, 32]))
    got = np.asarray(
        ingest_pallas.ingest_features_pallas(
            raw, res, positions, chunk=chunk, tile_b=tile_b, mode="aligned8"
        )
    )
    want = xla_reference_features(raw, res, positions)
    np.testing.assert_allclose(got, want, rtol=0, atol=5e-5)


def test_pallas_unknown_mode_raises(fixture_raw):
    raw, res = fixture_raw
    with pytest.raises(ValueError, match="unknown pallas ingest mode"):
        ingest_pallas.ingest_features_pallas(
            raw, res, np.array([5000]), mode="warp"
        )


# -- bank128 Pallas mode (the chip-proven formulation, round 4) -------
#
# The r4 chip bisect proved the axon remote compiler crashes on ANY
# dynamic lane slice (aligned or not) and on lane-split reshapes —
# the exact and aligned8 kernels each use one. bank128 uses neither:
# windows are cut as dynamic SUBLANE slices over rows-of-128, the
# in-row shift (0..127) goes through a 128-variant bank, and the
# select is the reshape-free mask/fold dot (probe s5b/s7, chip-run).
# Numerics are block-formulation two-term, so the gate is 5e-5.


def test_pallas_bank128_matches_xla_ingest(fixture_raw):
    raw, res = fixture_raw
    rng = np.random.RandomState(5)
    positions = rng.choice(
        np.arange(200, raw.shape[1] - 800), size=41, replace=False
    ).astype(np.int64)  # unsorted: output must be input-order
    got = np.asarray(
        ingest_pallas.ingest_features_pallas(
            raw, res, positions, mode="bank128"
        )
    )
    want = xla_reference_features(raw, res, positions)
    assert got.shape == want.shape == (41, 48)
    np.testing.assert_allclose(got, want, rtol=0, atol=5e-5)


def test_pallas_bank128_covers_every_shift(fixture_raw):
    """One marker per residual in-row shift 0..127 — every variant
    column of the 128-bank must select correctly (gcd(801, 128) = 1,
    so 128 consecutive markers at stride 801 hit every residue)."""
    raw, res = fixture_raw
    positions = (4096 + 100 + np.arange(128) * 801).astype(np.int64)
    assert len(set((p - 100) % 128 for p in positions)) == 128
    got = np.asarray(
        ingest_pallas.ingest_features_pallas(
            raw, res, positions, mode="bank128"
        )
    )
    want = xla_reference_features(raw, res, positions)
    np.testing.assert_allclose(got, want, rtol=0, atol=5e-5)


def test_pallas_bank128_small_chunk_and_overhang(fixture_raw):
    raw, res = fixture_raw
    S = raw.shape[1]
    positions = np.concatenate([
        (100 + 173 * np.arange(40)),
        [S - 300, 5000],  # overhanging window reads zeros
    ]).astype(np.int64)
    got = np.asarray(
        ingest_pallas.ingest_features_pallas(
            raw, res, positions, chunk=8192, tile_b=8, mode="bank128"
        )
    )
    want = xla_reference_features(raw, res, positions)
    np.testing.assert_allclose(got, want, rtol=0, atol=5e-5)


@pytest.mark.parametrize("seed", [31, 32])
def test_pallas_bank128_randomized_differential(fixture_raw, seed):
    raw, res = fixture_raw
    rng = np.random.RandomState(seed)
    S = raw.shape[1]
    n = int(rng.randint(5, 100))
    positions = rng.randint(100, S - 100, size=n).astype(np.int64)
    chunk = int(rng.choice([8192, 16384, 65536]))
    tile_b = int(rng.choice([4, 8, 32]))
    got = np.asarray(
        ingest_pallas.ingest_features_pallas(
            raw, res, positions, chunk=chunk, tile_b=tile_b, mode="bank128"
        )
    )
    want = xla_reference_features(raw, res, positions)
    np.testing.assert_allclose(got, want, rtol=0, atol=5e-5)


def test_pallas_bank128_adversarial_plan_boundaries(fixture_raw):
    """Adversarial tile plans (VERDICT r3 item 6): windows straddling
    half-chunk boundaries, duplicate markers clustered on one sample,
    and a first-possible-position window, all in one plan."""
    raw, res = fixture_raw
    half = 4096  # chunk 8192
    positions = np.concatenate([
        # straddle every half-chunk boundary in the first 8 halves:
        # window start (pos-100) lands 512 before each boundary, so
        # the 1024-sample slab crosses it
        np.arange(1, 9) * half + 100 - 512,
        np.full(7, 9 * half),  # pathological clustering: duplicates
        [100],  # earliest valid marker (window start 0)
    ]).astype(np.int64)
    got = np.asarray(
        ingest_pallas.ingest_features_pallas(
            raw, res, positions, chunk=8192, tile_b=4, mode="bank128"
        )
    )
    want = xla_reference_features(raw, res, positions)
    np.testing.assert_allclose(got, want, rtol=0, atol=5e-5)


def test_pallas_bank128_group_chunking():
    """More tiles than _BANK_MAX_TILES must route through the
    SMEM-sized group split (+ plan padding to a group multiple) and
    still match the reference in input order."""
    rng = np.random.RandomState(6)
    raw = rng.randint(-3000, 3000, size=(3, 120000), dtype=np.int16)
    res = np.array([0.1, 0.1, 0.2], np.float32)
    # dense markers + tiny tile_b force n_tiles > _BANK_MAX_TILES
    positions = (100 + np.arange(5000) * 20).astype(np.int64)
    plan = ingest_pallas.plan_pallas_tiles(
        positions, window=ingest_pallas.kernel_window("bank128"),
        chunk=8192, tile_b=2,
    )
    assert plan.n_tiles > ingest_pallas._BANK_MAX_TILES
    got = np.asarray(
        ingest_pallas.ingest_features_pallas(
            raw, res, positions, chunk=8192, tile_b=2, mode="bank128"
        )
    )
    want = xla_reference_features(raw, res, positions)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=0, atol=5e-5)


def test_pallas_bank128_bf16_within_bf16_envelope(fixture_raw):
    """The bf16-bank twin (MXU fast path: bf16 operands, f32
    accumulate, mean-centered BEFORE the cast so bf16 rounds
    residual-scale values) must stay inside the bf16 feature tier's
    5e-3 envelope vs the f32 gather reference."""
    raw, res = fixture_raw
    rng = np.random.RandomState(9)
    positions = rng.choice(
        np.arange(200, raw.shape[1] - 800), size=64, replace=False
    ).astype(np.int64)
    got = np.asarray(
        ingest_pallas.ingest_features_pallas(
            raw, res, positions, mode="bank128_bf16"
        )
    )
    want = xla_reference_features(raw, res, positions)
    np.testing.assert_allclose(got, want, rtol=0, atol=5e-3)
    # and the f32 bank twin agrees to the same envelope
    f32 = np.asarray(
        ingest_pallas.ingest_features_pallas(
            raw, res, positions, mode="bank128"
        )
    )
    np.testing.assert_allclose(got, f32, rtol=0, atol=5e-3)
    assert ingest_pallas.kernel_window(
        "bank128_bf16"
    ) == ingest_pallas.kernel_window("bank128")


def test_pallas_bank128_rejects_unaligned_chunk(fixture_raw):
    """Half-chunks must be whole 128-lane rows; anything else would
    silently misalign the BlockSpec fetches (review finding r4)."""
    raw, res = fixture_raw
    with pytest.raises(ValueError, match="chunk % 256"):
        ingest_pallas.ingest_features_pallas(
            raw, res, np.array([5000]), chunk=8320, mode="bank128"
        )


def test_bank128_banks_fold_algebra():
    """The fold matrix must reproduce yk - pk*colsum for every
    variant: push a one-hot masked synthetic through it and compare
    against the direct two-term combination."""
    Wvm, fold, slab_rows = ingest_pallas.bank128_banks()
    K = 16
    NVK = 128 * K
    assert Wvm.shape == (slab_rows * 128, NVK + 128)
    assert fold.shape == (NVK + 128, K)
    rng = np.random.RandomState(7)
    yv = rng.randn(5, NVK + 128).astype(np.float32)
    for row, v in enumerate([0, 1, 63, 127, 90]):
        mask = np.zeros(NVK + 128, np.float32)
        mask[v * K : (v + 1) * K] = 1.0
        mask[NVK + v] = 1.0
        got = (yv[row] * mask) @ fold
        want = yv[row, v * K : (v + 1) * K] + yv[row, NVK + v] * fold[NVK + v]
        np.testing.assert_allclose(got, want, rtol=0, atol=1e-5)


def test_default_ingest_mode_is_platform_aware(monkeypatch):
    from eeg_dataanalysispackage_tpu.ops import pallas_support

    monkeypatch.setattr(
        pallas_support, "default_interpret", lambda: True
    )
    assert pallas_support.default_ingest_mode() == "exact"
    monkeypatch.setattr(
        pallas_support, "default_interpret", lambda: False
    )
    assert pallas_support.default_ingest_mode() == "bank128"


# -- bank regular-ingest formulation (bank128 kernel, round 4) --------


@pytest.mark.parametrize("first", [150, 887, 3250])
def test_regular_ingest_bank_matches_reshape(first):
    """The regular train through the bank128 kernel must match the
    subtract-first reshape formulation to the block-formulation 5e-5
    envelope for arbitrary first positions."""
    rng = np.random.RandomState(41)
    n, stride = 64, 800
    S = 4000 + n * stride + 70000
    raw = rng.randint(-3000, 3000, size=(3, S), dtype=np.int16)
    res = np.array([0.1, 0.15, 0.2], np.float32)
    bank = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="bank"
    )
    ref = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="reshape"
    )
    fb = np.asarray(bank(jnp.asarray(raw), jnp.asarray(res), first))
    fr = np.asarray(ref(jnp.asarray(raw), jnp.asarray(res), first))
    assert fb.shape == fr.shape == (n, 48)
    np.testing.assert_allclose(fb, fr, rtol=0, atol=5e-5)


def test_regular_ingest_bank_odd_stride():
    """Odd strides force conv for phase/partial (G=128 guard); the
    bank formulation has no group-size constraint."""
    rng = np.random.RandomState(42)
    n, stride = 48, 999
    S = 4000 + n * stride + 70000
    raw = rng.randint(-3000, 3000, size=(3, S), dtype=np.int16)
    res = np.array([0.1, 0.1, 0.2], np.float32)
    with pytest.raises(ValueError, match="group"):
        device_ingest.make_regular_ingest_featurizer(
            stride, n, formulation="phase"
        )
    bank = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="bank"
    )
    ref = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="reshape"
    )
    fb = np.asarray(bank(jnp.asarray(raw), jnp.asarray(res), 200))
    fr = np.asarray(ref(jnp.asarray(raw), jnp.asarray(res), 200))
    np.testing.assert_allclose(fb, fr, rtol=0, atol=5e-5)


def test_regular_ingest_bank_traceable_under_outer_jit():
    """The bench times the featurizer inside jit(scan(...)); host
    tile planning must consume only concrete ints so tracing works
    (and never poison the table cache with tracers)."""
    rng = np.random.RandomState(43)
    n, stride = 32, 800
    S = 4000 + n * stride + 70000
    raw = rng.randint(-3000, 3000, size=(3, S), dtype=np.int16)
    res = np.array([0.1, 0.1, 0.2], np.float32)
    bank = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="bank"
    )

    @jax.jit
    def outer(raw_a, res_a):
        def body(acc, i):
            y = bank(raw_a, res_a + i.astype(jnp.float32) * 1e-12, 150)
            return acc + y.sum(), None

        acc, _ = jax.lax.scan(body, jnp.float32(0), jnp.arange(2))
        return acc

    traced = float(outer(jnp.asarray(raw), jnp.asarray(res)))
    # and the eager path still works after tracing (cache unpoisoned)
    eager = np.asarray(bank(jnp.asarray(raw), jnp.asarray(res), 150))
    assert np.isfinite(traced) and eager.shape == (n, 48)


# -- partial regular-ingest formulation (single-pass, round 3) --------


@pytest.mark.parametrize("first", [150, 1000, 887, 3250, 4000])
def test_regular_ingest_partial_arbitrary_first_position(first):
    """The partial formulation (one contraction per row against the
    concatenated [E4a|B4a|E4b|B4b] operator, neighbor partials
    combined) must match subtract-first reshape for any marker
    phase. No drift in this fixture, so the gate is tight."""
    n, stride = 13, 800
    raw, res = _dc_heavy_fixture(n, stride, first, tail=16384)
    ing_r = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="reshape"
    )
    ing_q = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="partial"
    )
    assert ing_q.formulation == "partial"
    a = np.asarray(ing_r(jnp.asarray(raw), jnp.asarray(res), first))
    b = np.asarray(ing_q(jnp.asarray(raw), jnp.asarray(res), first))
    np.testing.assert_allclose(b, a, rtol=0, atol=5e-6)


@pytest.mark.parametrize("stride", [800, 832, 896, 1024, 960])
def test_regular_ingest_partial_across_group_sizes(stride):
    from eeg_dataanalysispackage_tpu.ops.device_ingest import _phase_group

    assert _phase_group(stride) <= 4
    n, first = 11, 150 + (stride // 3)
    raw, res = _dc_heavy_fixture(
        n, stride, first, tail=4 * _phase_group(stride) * stride + 8192
    )
    ing_r = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="reshape"
    )
    ing_q = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="partial"
    )
    a = np.asarray(ing_r(jnp.asarray(raw), jnp.asarray(res), first))
    b = np.asarray(ing_q(jnp.asarray(raw), jnp.asarray(res), first))
    np.testing.assert_allclose(b, a, rtol=0, atol=5e-6)


def test_regular_ingest_partial_conv_class_under_drift():
    """The partial formulation's global DC proxy makes it conv-class
    under electrode drift: bounded by the documented 5e-5 envelope,
    NOT the phase formulation's exactness."""
    n, stride, first = 30, 800, 150
    raw, res = _dc_heavy_fixture(n, stride, first, drift=2500.0, tail=8192)
    ing_r = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="reshape"
    )
    ing_q = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="partial"
    )
    a = np.asarray(ing_r(jnp.asarray(raw), jnp.asarray(res), first))
    b = np.asarray(ing_q(jnp.asarray(raw), jnp.asarray(res), first))
    np.testing.assert_allclose(b, a, rtol=0, atol=5e-5)


def test_regular_ingest_partial_short_recording_falls_back():
    n, stride, first = 4, 800, 150
    raw, res = _dc_heavy_fixture(n, stride, first, tail=0)
    ing_q = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="partial"
    )
    ing_r = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="reshape"
    )
    a = np.asarray(ing_r(jnp.asarray(raw), jnp.asarray(res), first))
    b = np.asarray(ing_q(jnp.asarray(raw), jnp.asarray(res), first))
    np.testing.assert_allclose(b, a, rtol=0, atol=5e-6)


@pytest.mark.parametrize("formulation", ["phase", "partial"])
def test_regular_ingest_outer_jit_does_not_poison_cache(formulation):
    """Calling a phase/partial featurizer inside an OUTER jit (the
    driver dryrun's jit(vmap(...)) pattern) must not cache tracers:
    the lazily-built operator tables are cached as numpy, so a later
    plain call of the same module-globally-cached featurizer works.
    Regression for an UnexpectedTracerError found in round 3."""
    n, stride, first = 4, 800, 150
    raw, res = _dc_heavy_fixture(n, stride, first, tail=16384)
    ing = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation=formulation
    )
    # first use: under an outer trace
    under_jit = np.asarray(
        jax.jit(jax.vmap(lambda r: ing(r, jnp.asarray(res), first)))(
            jnp.asarray(raw)[None]
        )
    )[0]
    # second use: plain call — raised UnexpectedTracerError before
    plain = np.asarray(ing(jnp.asarray(raw), jnp.asarray(res), first))
    np.testing.assert_allclose(plain, under_jit, rtol=0, atol=1e-6)
