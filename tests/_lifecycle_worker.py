"""SIGKILL-mid-partial-fit worker for the lifecycle resume pin.

Run by tests/test_lifecycle.py as its own OS process:

    python _lifecycle_worker.py <ckpt_dir> <n_batches>

Feeds a DETERMINISTIC labeled feedback stream (``feedback_stream``,
shared with the parent test) through a LifecycleManager whose
featurizer is a pure identity over pre-made feature rows — no engine,
no model, just the partial-fit + checkpoint machinery the pin is
about. After each flushed batch it prints ``CKPT <batches>``; the
parent SIGKILLs it mid-stream, re-runs it over the SAME directory
(the manager restores the latest checkpointed carry + buffers and
``run`` resumes from ``batches_trained``), and compares the final
candidate weights byte-for-byte against an uninterrupted twin.
"""

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np

D = 48
BATCH = 8


def feedback_stream(n_batches: int, d: int = D, batch: int = BATCH):
    """The one true stream: batch b is a pure function of (7, b)."""
    rng = np.random.RandomState(7)
    out = []
    for _ in range(n_batches):
        rows = rng.randn(batch, d).astype(np.float32)
        labels = (rng.rand(batch) > 0.5).astype(np.float64)
        out.append((rows, labels))
    return out


def make_lifecycle(ckpt_dir: str):
    from eeg_dataanalysispackage_tpu.serve.lifecycle import (
        LifecycleConfig,
        LifecycleManager,
    )

    config = LifecycleConfig(
        adapt_batch=BATCH, adapt_iters=5, capacity=64,
        drift_window=16, gate_mode="off", gate_ratio=None,
        checkpoint_dir=ckpt_dir,
    )
    return LifecycleManager(
        None, config,
        featurize=lambda windows, _res: np.stack(
            [np.asarray(w, np.float32) for w in windows]
        ),
    )


def run(ckpt_dir: str, n_batches: int):
    """Feed batches ``batches_trained .. n_batches`` (resume-aware),
    one flush per batch, printing a CKPT marker after each."""
    lc = make_lifecycle(ckpt_dir)
    stream = feedback_stream(n_batches)
    res = np.ones(3, np.float32)
    lc.start()
    for b in range(lc.batches_trained, n_batches):
        rows, labels = stream[b]
        for i in range(len(rows)):
            lc.feedback(rows[i], res, float(labels[i]))
        assert lc.flush(timeout_s=60.0), "adapter did not go idle"
        print(f"CKPT {lc.batches_trained}", flush=True)
    lc.close(flush=True)
    return lc


if __name__ == "__main__":
    manager = run(sys.argv[1], int(sys.argv[2]))
    w = manager.candidate.w if manager.candidate is not None else None
    print(
        "W " + (w.astype(np.float32).tobytes().hex() if w is not None
                else "none"),
        flush=True,
    )
