"""The ``task=seizure`` workload end to end (docs/workloads.md).

Sliding windows -> configurable subband features -> cost-sensitive
training -> imbalanced-class statistics, plus the satellites: the
cross-config feature-cache poisoning pin, the fe_sweep= stacked
population (0 recompiles on new sweep points, vmap==looped parity),
the serve=true parity pin with the window-parameterized engine, and
the serve_threshold= knob.
"""

import os

import numpy as np
import pytest

import _synthetic
from eeg_dataanalysispackage_tpu.models import stats
from eeg_dataanalysispackage_tpu.pipeline import builder

_LINEAR_CONFIG = (
    "config_num_iterations=60&config_step_size=1.0"
    "&config_mini_batch_fraction=1.0"
)


@pytest.fixture(scope="module")
def info(tmp_path_factory):
    d = tmp_path_factory.mktemp("seizure_session")
    return _synthetic.write_seizure_session(
        str(d), n_files=2, n_samples=40000
    )


def _q(info, *parts):
    return "&".join([f"info_file={info}", "task=seizure"] + list(parts))


def _run(query):
    pb = builder.PipelineBuilder(query)
    return pb, pb.execute()


# ------------------------------------------------ end to end


def test_seizure_end_to_end_train(info, tmp_path):
    result = tmp_path / "res.txt"
    report_dir = tmp_path / "report"
    _, st = _run(_q(
        info, "fe=dwt-4:level=4:stats=energy,std", "window=512",
        "stride=256", "train_clf=logreg", _LINEAR_CONFIG,
        "cost_fp=1", "cost_fn=8", f"result_path={result}",
        f"report={report_dir}",
    ))
    # extended statistics rendered into result_path
    text = result.read_text()
    assert "Precision: " in text and "Recall: " in text
    assert "Expected cost (fp=1.0, fn=8.0): " in text
    assert st.extended_report and st.cost_fn == 8.0
    # the run report carries workload + classification blocks
    import json

    with open(report_dir / "run_report.json") as f:
        report = json.load(f)
    workload = report["workload"]
    assert workload["task"] == "seizure"
    assert workload["window"] == 512 and workload["stride"] == 256
    assert 0.0 < workload["class_ratio"] < 0.35
    assert workload["weight_pos"] == 8.0
    block = report["classification"]
    assert "expected_cost" in block and "recall" in block


def test_cost_sensitive_beats_unweighted_on_expected_cost(info):
    base = _q(
        info, "fe=dwt-4:level=4:stats=energy,std", "window=512",
        "stride=256", "train_clf=logreg", _LINEAR_CONFIG, "cache=false",
    )
    _, unweighted = _run(base)
    _, weighted = _run(base + "&cost_fp=1&cost_fn=8")
    assert weighted.expected_cost(1, 8) < unweighted.expected_cost(1, 8)
    assert weighted.recall() > (
        0.0 if np.isnan(unweighted.recall()) else unweighted.recall()
    )


def test_class_weight_balanced_and_errors(info):
    base = _q(
        info, "fe=dwt-4:level=2", "window=512", "stride=512",
        "train_clf=logreg", _LINEAR_CONFIG, "cache=false",
    )
    pb, st = _run(base + "&class_weight=balanced&report=false")
    assert st.extended_report
    with pytest.raises(ValueError, match="class_weight"):
        _run(base + "&class_weight=zap")
    with pytest.raises(ValueError, match="cost_fp=/cost_fn="):
        _run(base + "&cost_fp=-1")
    with pytest.raises(ValueError, match="unknown task"):
        _run(f"info_file={info}&task=zap&fe=dwt-8&train_clf=logreg")
    with pytest.raises(ValueError, match="-fused"):
        _run(_q(info, "fe=dwt-8-fused", "train_clf=logreg",
                _LINEAR_CONFIG))


def test_true_confusion_matrix_not_the_mllib_swap(info):
    """The seizure statistics must label fp/fn correctly — the MLlib
    report swap (a pinned P300 bug-as-behavior) would corrupt the
    recall/cost the workload is tuned against. With heavily
    pos-weighted training the model over-predicts positives: real
    false POSITIVES, zero/few false negatives."""
    _, st = _run(_q(
        info, "fe=dwt-4:level=4:stats=energy,std", "window=512",
        "stride=256", "train_clf=logreg", _LINEAR_CONFIG,
        "class_weight=50", "cache=false",
    ))
    # over-prediction lands on the fp side of the TRUE matrix
    assert st.false_positives >= st.false_negatives
    assert st.recall() >= 0.9
    # and the incremental sums are filled (confusion_only=False)
    assert st.class1_sum + st.class2_sum > 0


def test_fanout_legs_train_with_resolved_weights(info):
    """classifiers= fan-out re-derives its config from the query map;
    the resolved class weights must reach every leg (regression: the
    legs once trained unweighted and recall collapsed to 0)."""
    _, st = _run(_q(
        info, "fe=dwt-4:level=4:stats=energy,std", "window=512",
        "stride=256", "classifiers=logreg,svm", _LINEAR_CONFIG,
        "cost_fp=1", "cost_fn=8", "cache=false",
    ))
    assert set(st) == {"logreg", "svm"}
    for name, leg in st.items():
        assert leg.extended_report, name
        assert leg.recall() >= 0.9, (name, leg.recall())


# ------------------------------------------------ feature cache


def test_cache_hit_is_statistics_identical(info, tmp_path, monkeypatch):
    monkeypatch.delenv("EEG_TPU_NO_FEATURE_CACHE", raising=False)
    monkeypatch.setenv("EEG_TPU_FEATURE_CACHE_DIR", str(tmp_path / "fc"))
    from eeg_dataanalysispackage_tpu.io import feature_cache

    q = _q(
        info, "fe=dwt-4:level=4:stats=energy", "window=512",
        "stride=256", "train_clf=logreg", _LINEAR_CONFIG,
    )
    feature_cache.reset_stats()
    _, cold = _run(q)
    assert feature_cache.stats()["misses"] == 1
    _, warm = _run(q)
    assert feature_cache.stats()["hits"] == 1
    assert str(cold) == str(warm)


def test_cross_config_poisoning(info, tmp_path, monkeypatch):
    """A cached entry for one extractor config must NEVER satisfy a
    request for another: the key folds the full wavelet family /
    level / stat set (and the epoching geometry), so a ``dwt-8``
    entry cannot poison a ``dwt-4:level=4:stats=energy`` request."""
    monkeypatch.delenv("EEG_TPU_NO_FEATURE_CACHE", raising=False)
    monkeypatch.setenv("EEG_TPU_FEATURE_CACHE_DIR", str(tmp_path / "fc"))
    from eeg_dataanalysispackage_tpu.io import feature_cache

    def run_cfg(fe, window="window=512", stride="stride=256"):
        return _run(_q(
            info, f"fe={fe}", window, stride, "train_clf=logreg",
            _LINEAR_CONFIG,
        ))

    feature_cache.reset_stats()
    run_cfg("dwt-8:level=4:stats=energy")
    # every other config must MISS (different family, level, stats,
    # window, stride), never reuse the first entry
    run_cfg("dwt-4:level=4:stats=energy")
    run_cfg("dwt-8:level=3:stats=energy")
    run_cfg("dwt-8:level=4:stats=energy,std")
    run_cfg("dwt-8:level=4:stats=energy", window="window=768")
    run_cfg("dwt-8:level=4:stats=energy", stride="stride=128")
    s = feature_cache.stats()
    assert s["hits"] == 0 and s["misses"] == 6, s
    # and the keys really differ on disk (6 distinct entries)
    entries = os.listdir(str(tmp_path / "fc"))
    assert len([e for e in entries if e.endswith(".npz")]) == 6


def test_fused_key_and_seizure_key_never_collide(tmp_path):
    """The P300 fused path's extractor tuple and the seizure path's
    share the run_key scheme; their id tuples are structurally
    disjoint ('dwt-fused' vs 'seizure' heads)."""
    from eeg_dataanalysispackage_tpu.features import registry
    from eeg_dataanalysispackage_tpu.io import feature_cache, provider

    digests = [("a.eeg", 2, "d" * 64)]
    fused = feature_cache.run_key(
        digests, ("fz", "cz", "pz"), 100, 750,
        provider.fused_extractor_id(8),
    )
    fe = registry.create("dwt-8:level=4:stats=energy")
    seizure = feature_cache.run_key(
        digests, ("fz", "cz", "pz"), 100, 750,
        ("seizure", fe.cache_id(), 512, 256, 0.5),
    )
    assert fused != seizure


# ------------------------------------------------ populations


def test_fe_sweep_population_vmap_equals_looped(info):
    axes = (
        "fe_sweep=dwt-4:level=4:stats=energy,std"
        "|dwt-8:level=4:stats=energy,std"
    )
    base = _q(
        info, axes, "window=512", "stride=256", "train_clf=logreg",
        "sweep=cost_fn:1,8", _LINEAR_CONFIG, "cache=false",
    )
    _, vmapped = _run(base)
    _, looped = _run(base + "&population_mode=looped")
    assert len(vmapped) == 4  # 2 fe configs x 2 costs
    assert sorted(vmapped) == sorted(looped)
    assert str(vmapped) == str(looped)  # per-member byte parity
    assert vmapped.mode == "vmap" and looped.mode == "looped"
    # member statistics carry the extended block
    assert all(s.extended_report for s in vmapped.values())


def test_fe_sweep_zero_recompiles_on_new_sweep_points(info):
    """Feature matrices and costs are member-axis INPUTS: a second
    run with different fe configs and cost values (same cardinality)
    compiles nothing new."""
    from eeg_dataanalysispackage_tpu.obs.report import CompilationMonitor

    def run(fes, costs):
        return _run(_q(
            info, f"fe_sweep={fes}", "window=512", "stride=256",
            "train_clf=logreg", f"sweep=cost_fn:{costs}",
            _LINEAR_CONFIG, "cache=false", "report=false",
        ))

    run("dwt-4:level=4:stats=energy,std|dwt-8:level=4:stats=energy,std",
        "1,8")
    with CompilationMonitor() as monitor:
        run(
            "dwt-6:level=4:stats=energy,std"
            "|dwt-8:level=4:stats=energy,std",
            "2,16",
        )
    snap = monitor.snapshot()
    if snap["available"]:
        assert snap["compilations"] == 0, snap


def test_fe_sweep_mismatched_shapes_error(info):
    with pytest.raises(ValueError, match="agree on the feature"):
        _run(_q(
            info,
            "fe_sweep=dwt-4:level=4:stats=energy"
            "|dwt-4:level=4:stats=energy,std",
            "window=512", "stride=256", "train_clf=logreg",
            _LINEAR_CONFIG, "cache=false",
        ))


def test_fe_sweep_conflicts(info):
    with pytest.raises(ValueError, match="requires task=seizure"):
        _run(
            f"info_file={info}&fe_sweep=dwt-4:level=2|dwt-8:level=2"
            f"&fe=dwt-8&train_clf=logreg"
        )
    with pytest.raises(ValueError, match="linear family"):
        _run(_q(
            info, "fe_sweep=dwt-4:level=2|dwt-8:level=2",
            "window=512", "train_clf=nn", "cache=false",
        ))


# ------------------------------------------------ serving


@pytest.fixture(scope="module")
def saved_model(info, tmp_path_factory):
    path = str(tmp_path_factory.mktemp("seizure_model") / "model")
    _run(_q(
        info, "fe=dwt-4:level=4:stats=energy,std", "window=512",
        "stride=256", "train_clf=logreg", _LINEAR_CONFIG,
        "cost_fp=1", "cost_fn=8", "save_clf=true",
        f"save_name={path}", "cache=false",
    ))
    return path


def _load_q(info, saved_model, *parts):
    return _q(
        info, "fe=dwt-4:level=4:stats=energy,std", "window=512",
        "stride=256", "load_clf=logreg", f"load_name={saved_model}",
        "cache=false", *parts,
    )


def test_serve_statistics_identical_to_batch(info, saved_model):
    _, batch = _run(_load_q(info, saved_model))
    pb, served = _run(_load_q(info, saved_model, "serve=true"))
    assert str(served) == str(batch)  # byte-identical report
    assert served.extended_report


def test_serve_threshold_tunes_recall(info, saved_model):
    _, default = _run(_load_q(info, saved_model, "serve=true"))
    _, tuned = _run(_load_q(
        info, saved_model, "serve=true", "serve_threshold=-5.0"
    ))
    # a deeply negative margin threshold predicts positive more often:
    # recall can only go up (and here the stats must actually move)
    assert tuned.recall() >= default.recall()
    assert (
        tuned.true_positives + tuned.false_positives
        >= default.true_positives + default.false_positives
    )
    with pytest.raises(ValueError, match="must be a float"):
        _run(_load_q(
            info, saved_model, "serve=true", "serve_threshold=zap"
        ))


def test_serve_report_blocks(info, saved_model, tmp_path):
    report_dir = tmp_path / "serve_report"
    _run(_load_q(
        info, saved_model, "serve=true", f"report={report_dir}"
    ))
    import json

    with open(report_dir / "run_report.json") as f:
        report = json.load(f)
    assert report["workload"]["task"] == "seizure"
    assert report["serve"]["requests"]["completed"] > 0
    assert report["serve"]["mode"] == "host-extractor"
    assert report["serve"]["drained_cleanly"] is True
    assert report["classification"]["recall"] is not None


# ------------------------------------------------ P300 byte-stability


def test_p300_path_untouched_by_weight_knobs(tmp_path):
    """A P300 query (no task=) trains through the exact pre-knob
    program: weights default to 1.0 and the statistics text carries
    no extended block."""
    d = tmp_path / "p300"
    d.mkdir()
    info = _synthetic.write_session(str(d), n_markers=30)
    q = (
        f"info_file={info}&fe=dwt-8&train_clf=logreg"
        f"&{_LINEAR_CONFIG}"
    )
    st = builder.PipelineBuilder(q).execute()
    text = str(st)
    assert "Precision" not in text and "Expected cost" not in text
    assert st.extended_report is False
    # and the weighted engine at unit weights is bit-identical to the
    # unweighted program (the parity story behind the static flag)
    from eeg_dataanalysispackage_tpu.models import sgd

    rng = np.random.RandomState(0)
    x = rng.randn(40, 8).astype(np.float32)
    y = (rng.rand(40) > 0.5).astype(np.float32)
    cfg_plain = sgd.SGDConfig(num_iterations=30)
    cfg_unit = sgd.SGDConfig(
        num_iterations=30, weight_pos=1.0, weight_neg=1.0
    )
    assert not cfg_unit.weighted
    np.testing.assert_array_equal(
        sgd.train_linear(x, y, cfg_plain),
        sgd.train_linear(x, y, cfg_unit),
    )
