"""Parallel-ingest determinism (io/provider.py).

The ordered merge contract: any parse-pool size produces byte-for-byte
the same epoch batch — epoch order, targets, cross-file balance
counters, fused feature rows — as the sequential loop. Also covers
the configuration surface (EEG_TPU_INGEST_WORKERS /
EEG_TPU_PREFETCH_DEPTH / query params) and the chaos clamp."""

import os

import numpy as np
import pytest

import _synthetic
from eeg_dataanalysispackage_tpu.io import provider, staging
from eeg_dataanalysispackage_tpu.obs import chaos


def _session(directory, n_files=3, n_markers=24, missing=0):
    lines = []
    for i in range(n_files):
        name = f"synth_{i:02d}"
        guessed = 2 + (i % 7)
        _synthetic.write_recording(
            str(directory), name=name, n_markers=n_markers,
            guessed=guessed, seed=i,
        )
        lines.append(f"{name}.eeg {guessed}")
    for i in range(missing):
        # listed but absent triplets: must be skipped, not fatal
        lines.insert(1, f"ghost_{i}.eeg 4")
    info = os.path.join(str(directory), "info.txt")
    with open(info, "w") as f:
        f.write("\n".join(lines) + "\n")
    return info


def _java_epoch_sum(epochs):
    row_sums = np.cumsum(epochs, axis=-1)[..., -1]
    return float(np.cumsum(row_sums.reshape(-1))[-1])


def test_pool_sizes_produce_identical_batches(tmp_path):
    info = _session(tmp_path, n_files=4)
    batches = {}
    for workers in (1, 4):
        b = provider.OfflineDataProvider([info], workers=workers).load()
        batches[workers] = b
    b1, b4 = batches[1], batches[4]
    np.testing.assert_array_equal(b1.epochs, b4.epochs)
    np.testing.assert_array_equal(b1.targets, b4.targets)
    assert _java_epoch_sum(b1.epochs) == _java_epoch_sum(b4.epochs)


def test_pool_sizes_produce_identical_fused_features(tmp_path):
    info = _session(tmp_path, n_files=3)
    f1, t1 = provider.OfflineDataProvider(
        [info], workers=1
    ).load_features_device(backend="xla")
    f4, t4 = provider.OfflineDataProvider(
        [info], workers=4
    ).load_features_device(backend="xla")
    np.testing.assert_array_equal(np.asarray(f1), np.asarray(f4))
    np.testing.assert_array_equal(np.asarray(t1), np.asarray(t4))


def test_missing_files_skipped_in_parallel(tmp_path, caplog):
    import logging

    info = _session(tmp_path, n_files=3, missing=2)
    with caplog.at_level(
        logging.WARNING, logger="eeg_dataanalysispackage_tpu.io.provider"
    ):
        b4 = provider.OfflineDataProvider([info], workers=4).load()
    assert caplog.text.count("Did not load") == 2
    b1 = provider.OfflineDataProvider([info], workers=1).load()
    np.testing.assert_array_equal(b1.epochs, b4.epochs)
    np.testing.assert_array_equal(b1.targets, b4.targets)


def test_parse_error_surfaces_in_order(tmp_path):
    """A non-missing-file parse failure must still surface (at the
    file's in-order position), not hang or vanish in the pool."""
    info = _session(tmp_path, n_files=3)
    # break the middle file's header so parsing raises
    with open(str(tmp_path / "synth_01.vhdr"), "w") as f:
        f.write("BinaryFormat=NO_SUCH_FORMAT\n[Binary Infos]\n"
                "BinaryFormat=NO_SUCH_FORMAT\n")
    with pytest.raises(ValueError, match="Unsupported BinaryFormat"):
        provider.OfflineDataProvider([info], workers=4).load()


def test_worker_configuration(monkeypatch):
    monkeypatch.setenv(provider.ENV_INGEST_WORKERS, "7")
    assert provider.default_ingest_workers() == 7
    monkeypatch.setenv(provider.ENV_INGEST_WORKERS, "garbage")
    assert provider.default_ingest_workers() == 4
    monkeypatch.delenv(provider.ENV_INGEST_WORKERS)
    assert provider.default_ingest_workers() >= 1
    odp = provider.OfflineDataProvider(["x.txt"], workers=3)
    assert odp._workers == 3


def test_prefetch_depth_configuration(monkeypatch):
    monkeypatch.setenv(provider.ENV_PREFETCH_DEPTH, "5")
    assert provider.default_prefetch_depth() == 5
    assert staging.default_buffer_size() == 5
    monkeypatch.setenv(provider.ENV_PREFETCH_DEPTH, "bad")
    assert provider.default_prefetch_depth() == 2
    assert staging.default_buffer_size() == 2
    monkeypatch.delenv(provider.ENV_PREFETCH_DEPTH)
    assert staging.default_buffer_size() == 2


def test_prefetch_uses_env_default(monkeypatch):
    """staging.prefetch with buffer_size=None resolves the env knob
    (and still rejects nonsense explicit values)."""
    monkeypatch.setenv(staging.ENV_PREFETCH_DEPTH, "3")
    got = list(
        staging.prefetch(
            staging.minibatches(np.ones((6, 2), np.float32), batch_size=2)
        )
    )
    assert len(got) == 3
    with pytest.raises(ValueError, match="buffer_size"):
        list(staging.prefetch(iter([]), buffer_size=0))


def test_chaos_plan_forces_sequential_parse(tmp_path):
    """Deterministic chaos replay counts injection-point calls in
    order; an installed plan must clamp the pool to 1 worker."""
    odp = provider.OfflineDataProvider(["x.txt"], workers=8)
    assert odp._resolved_workers(8) == 8
    with chaos.faults("remote.request:p=0.5", seed=1):
        assert odp._resolved_workers(8) == 1
    assert odp._resolved_workers(8) == 8
