"""Native-RPC HDFS driver (io/remote.NativeHdfsFileSystem).

The reference reaches HDFS over the native Hadoop RPC protocol
(``Const.java:38-42`` — ``hdfs://localhost:8020``, the RPC port;
``OffLineDataProvider.java:90``). The repo's default driver is
WebHDFS (zero dependencies); ``HDFS_DRIVER=native`` routes the same
``hdfs://`` URIs through libhdfs for clusters with WebHDFS disabled.
These tests pin the driver selection, the URI -> (host, port, path)
mapping, the FileSystem-protocol semantics over a faked libhdfs
layer (the module-level ``_hadoop_connect`` seam), and the
actionable error when the native runtime is absent (which it is in
this image — no JVM)."""

import io

import numpy as np  # noqa: F401  (import parity with sibling tests)
import pytest

from eeg_dataanalysispackage_tpu.io import remote


class _FakeStream(io.BytesIO):
    def __init__(self, store, path):
        super().__init__()
        self._store, self._path = store, path

    def __exit__(self, *exc):
        self._store[self._path] = self.getvalue()
        return super().__exit__(*exc)


class _FakeHadoopFS:
    """Just enough of pyarrow.fs.HadoopFileSystem for the adapter."""

    def __init__(self):
        self.files = {}
        self.dirs = set()

    def get_file_info(self, paths):
        from pyarrow import fs as pafs

        if isinstance(paths, pafs.FileSelector):
            base = paths.base_dir.rstrip("/") + "/"
            names = sorted(
                {
                    base + k[len(base):].split("/", 1)[0]
                    for k in self.files
                    if k.startswith(base)
                }
            )
            if not names:
                raise FileNotFoundError(paths.base_dir)
            return [
                pafs.FileInfo(n, type=pafs.FileType.File) for n in names
            ]
        out = []
        for p in paths:
            if p in self.files:
                out.append(
                    pafs.FileInfo(
                        p, type=pafs.FileType.File, size=len(self.files[p])
                    )
                )
            elif p in self.dirs:
                out.append(pafs.FileInfo(p, type=pafs.FileType.Directory))
            else:
                out.append(pafs.FileInfo(p, type=pafs.FileType.NotFound))
        return out

    def delete_dir(self, p):
        prefix = p.rstrip("/")
        doomed = [
            k
            for k in self.files
            if k == prefix or k.startswith(prefix + "/")
        ]
        if not doomed and prefix not in self.dirs:
            raise FileNotFoundError(p)
        for k in doomed:
            del self.files[k]

    def open_input_stream(self, p):
        return io.BytesIO(self.files[p])

    def open_output_stream(self, p):
        return _FakeStream(self.files, p)


@pytest.fixture
def fake_connect(monkeypatch):
    calls = []
    fake = _FakeHadoopFS()

    def connect(host, port, user):
        calls.append((host, port, user))
        return fake

    monkeypatch.setattr(remote, "_hadoop_connect", connect)
    return fake, calls


def test_driver_selection(monkeypatch):
    monkeypatch.delenv("HDFS_DRIVER", raising=False)
    assert isinstance(
        remote.filesystem_for("hdfs://nn:8020/x"), remote.WebHdfsFileSystem
    )
    monkeypatch.setenv("HDFS_DRIVER", "native")
    assert isinstance(
        remote.filesystem_for("hdfs://nn:8020/x"),
        remote.NativeHdfsFileSystem,
    )
    monkeypatch.setenv("HDFS_DRIVER", "bogus")
    with pytest.raises(ValueError, match="HDFS_DRIVER"):
        remote.filesystem_for("hdfs://nn:8020/x")


def test_round_trip_and_authority_mapping(fake_connect):
    fake, calls = fake_connect
    fs = remote.NativeHdfsFileSystem(user="eeg")
    uri = "hdfs://namenode:9000/data/infoTrain.txt"
    assert not fs.exists(uri)
    fs.write_bytes(uri, b"a;b;c\n")
    assert fs.exists(uri)
    assert fs.read_bytes(uri) == b"a;b;c\n"
    assert fs.read_text(uri) == "a;b;c\n"
    # one cached connection, dialed with the URI's RPC authority
    assert calls == [("namenode", 9000, "eeg")]


def test_default_port_and_default_fs(fake_connect):
    fake, calls = fake_connect
    fs = remote.NativeHdfsFileSystem()
    fs.write_bytes("hdfs://nn/x", b"1")  # no port -> 8020 (Const.java:39)
    fs.write_bytes("hdfs:///y", b"2")  # default-FS form -> libhdfs 'default'
    assert [c[:2] for c in calls] == [("nn", 8020), ("default", 0)]


def test_directory_and_missing_semantics(fake_connect):
    fake, _ = fake_connect
    fake.dirs.add("/d")
    fs = remote.NativeHdfsFileSystem()
    with pytest.raises(IsADirectoryError):
        fs.read_bytes("hdfs://nn/d")
    with pytest.raises(FileNotFoundError):
        fs.read_bytes("hdfs://nn/nope")


def test_list_dir_and_mllib_dir_over_native_driver(
    fake_connect, monkeypatch
):
    """Directory listing (the capability MLlib model-dir reads need)
    and the full model-directory round trip over the native
    driver."""
    import numpy as np

    from eeg_dataanalysispackage_tpu.io import mllib_format as mf

    fake, _ = fake_connect
    fs = remote.NativeHdfsFileSystem()
    fs.write_bytes("hdfs://nn/m/metadata/part-00000", b"x")
    fs.write_bytes("hdfs://nn/m/data/part-r-0.gz.parquet", b"y")
    assert fs.list_dir("hdfs://nn/m") == ["data", "metadata"]
    assert fs.list_dir("hdfs://nn/m/metadata") == ["part-00000"]
    with pytest.raises(FileNotFoundError):
        fs.list_dir("hdfs://nn/nope")

    # full GLM round trip with hdfs:// routed to the native driver
    monkeypatch.setenv("HDFS_DRIVER", "native")
    w = np.arange(8.0)
    uri = "hdfs://nn/models/glm"
    mf.write_glm(uri, mf.GLM_LOGREG, w, intercept=0.5)
    assert mf.is_model_dir(uri)
    m = mf.read_glm(uri)
    np.testing.assert_array_equal(m.weights, w)
    assert m.intercept == 0.5


def test_non_hdfs_uri_rejected(fake_connect):
    fs = remote.NativeHdfsFileSystem()
    with pytest.raises(ValueError, match="hdfs://"):
        fs.read_bytes("http://x/y")


def test_missing_native_runtime_error_is_actionable():
    """No JVM/libhdfs in this image: the real connect must fail fast
    with the WebHDFS pointer, not an opaque loader error."""
    with pytest.raises(remote.RemoteIOError, match="WebHDFS"):
        remote._hadoop_connect("localhost", 1, None)
