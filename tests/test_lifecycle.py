"""Model lifecycle manager (serve/lifecycle.py): streaming
partial-fit, shadow-scored hot swap with rollback, drift detection
(ISSUE 15).

The acceptance bar: a service that stages a candidate, shadow-scores
it, and never promotes (gate off / gate reject) emits
ClassificationStatistics BYTE-IDENTICAL to a service that never had a
lifecycle at all — including under serve.swap/serve.adapt chaos; a
promoted candidate served online is byte-identical to the batch run
of its ``promoted.npz`` checkpoint; a SIGKILL'd adapter resumes its
checkpointed trajectory to byte-identical candidate weights; a failed
swap leaves the live model untouched; a wedged adapter discards its
candidate while live serving continues.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import _lifecycle_worker
import _synthetic
from eeg_dataanalysispackage_tpu import obs
from eeg_dataanalysispackage_tpu.epochs.extractor import BalanceState
from eeg_dataanalysispackage_tpu.io import provider
from eeg_dataanalysispackage_tpu.models import registry as clf_registry
from eeg_dataanalysispackage_tpu.models import stats
from eeg_dataanalysispackage_tpu.obs import chaos
from eeg_dataanalysispackage_tpu.pipeline import builder
from eeg_dataanalysispackage_tpu.pipeline.plan import (
    ExecutionPlan,
    PlanValidationError,
)
from eeg_dataanalysispackage_tpu.serve import (
    InferenceService,
    LifecycleConfig,
    ServeConfig,
    ServiceClosedError,
    engine,
    lifecycle as lifecycle_mod,
)

_CONFIG = (
    "&config_num_iterations=20&config_step_size=1.0"
    "&config_mini_batch_fraction=1.0"
)


@pytest.fixture(scope="module")
def session(tmp_path_factory):
    """One synthetic session + a trained, saved logreg model + the
    batch pipeline's features/predictions — the test_serve fixture
    shape, reused for the lifecycle pins."""
    tmp = tmp_path_factory.mktemp("lifecycle_session")
    for i, (name, guessed) in enumerate(
        (("synth_00", 2), ("synth_01", 5))
    ):
        _synthetic.write_recording(
            str(tmp), name=name, n_markers=90, guessed=guessed, seed=i
        )
    info = str(tmp / "info.txt")
    with open(info, "w") as f:
        f.write("synth_00.eeg 2\nsynth_01.eeg 5\n")
    model = str(tmp / "model")
    builder.PipelineBuilder(
        f"info_file={info}&fe=dwt-8-fused&train_clf=logreg"
        f"&save_clf=true&save_name={model}{_CONFIG}"
    ).execute()

    odp = provider.OfflineDataProvider([info])
    balance = BalanceState()
    windows, targets, resolutions = [], [], None
    for _rel, guessed, rec in odp.iter_recordings():
        ws, ts, resolutions = engine.windows_from_recording(
            rec, odp.channel_indices_for(rec), guessed,
            pre=odp.pre, post=odp.post, balance=balance,
        )
        windows.extend(ws)
        targets.append(ts)
    features, _t = provider.OfflineDataProvider(
        [info]
    ).load_features_device(wavelet_index=8, backend="xla")
    classifier = clf_registry.create("logreg")
    classifier.load(model)
    return {
        "info": info,
        "model": model,
        "windows": windows,
        "targets": np.concatenate(targets),
        "resolutions": resolutions,
        "batch_features": features,
        "batch_predictions": classifier.predict(features),
    }


def _feed_session(svc, session, repeats=1, flush=True):
    for _ in range(repeats):
        for w, y in zip(session["windows"], session["targets"]):
            svc.feedback(w, session["resolutions"], float(y))
    if flush:
        assert svc.lifecycle.flush(timeout_s=60.0)


# -- windowed statistics -------------------------------------------------


def test_windowed_statistics_cost_recall_and_window_bound():
    w = stats.WindowedStatistics(4, cost_fp=1.0, cost_fn=8.0)
    assert np.isnan(w.expected_cost()) and not w.full
    for pred, label in ((1, 1), (0, 0), (1, 0), (0, 1)):
        w.add(pred, label)
    assert w.full and w.counts() == (1, 1, 1, 1)
    assert w.expected_cost() == pytest.approx((1.0 + 8.0) / 4)
    assert w.recall() == pytest.approx(0.5)
    # sliding: four perfect outcomes push the errors out entirely
    for _ in range(4):
        w.add(1, 1)
    assert w.expected_cost() == 0.0 and w.recall() == 1.0
    assert w.seen == 8
    w.reset()
    assert w.n == 0 and w.seen == 8  # seen survives (drift pacing)


def test_parse_swap_gate_grammar():
    assert lifecycle_mod.parse_swap_gate("off") == ("off", None)
    assert lifecycle_mod.parse_swap_gate("cost") == ("cost", 1.0)
    assert lifecycle_mod.parse_swap_gate("cost:2.5") == ("cost", 2.5)
    for bad in ("banana", "cost:x", "cost:0", "cost:-1"):
        with pytest.raises(ValueError, match="swap_gate"):
            lifecycle_mod.parse_swap_gate(bad)


def test_plan_ir_lifecycle_knob_grammar():
    base = "info_file=i.txt&fe=dwt-8-fused&load_clf=logreg&load_name=m"
    plan = ExecutionPlan.parse(
        base + "&serve=true&adapt=true&swap_gate=cost:1.5"
        "&drift_window=32"
    )
    assert plan.adapt and plan.swap_gate == "cost:1.5"
    assert plan.drift_window == 32
    cases = (
        (base + "&serve=true&adapt=yes", "adapt= must be true or false"),
        (base + "&adapt=true", "requires serve=true"),
        (base + "&serve=true&swap_gate=cost", "requires adapt=true"),
        (base + "&serve=true&adapt=true&swap_gate=nope", "swap_gate"),
        (base + "&serve=true&adapt=true&drift_window=0", ">= 1"),
        (base + "&serve=true&drift_window=9", "requires adapt=true"),
    )
    for query, match in cases:
        with pytest.raises(PlanValidationError, match=match):
            ExecutionPlan.parse(query)
    # the knobs are semantic: an adapt plan is not the plain plan
    assert ExecutionPlan.parse(
        base + "&serve=true&adapt=true"
    ).canonical_key() != ExecutionPlan.parse(
        base + "&serve=true"
    ).canonical_key()


# -- the rollback pin (never-promoted == never-staged) -------------------


def test_adapt_no_swap_statistics_byte_identical(session, tmp_path):
    """The core pin: serve=true&adapt=true&swap_gate=off stages and
    shadow-scores a candidate on every trial yet emits statistics
    byte-identical to the plain serve run — and its run report
    carries the lifecycle block."""
    base = (
        f"info_file={session['info']}&fe=dwt-8-fused&serve=true"
        f"&load_clf=logreg&load_name={session['model']}"
    )
    plain = builder.PipelineBuilder(base).execute()
    report_dir = str(tmp_path / "report")
    adapted = builder.PipelineBuilder(
        base + "&adapt=true&swap_gate=off&drift_window=16"
        f"&adapt_batch=8&report={report_dir}"
    ).execute()
    assert str(adapted) == str(plain)
    with open(os.path.join(report_dir, "run_report.json")) as f:
        report = json.load(f)
    block = report["lifecycle"]
    assert block["enabled"] and block["swaps"] == 0
    assert block["feedback"]["received"] == len(session["windows"])
    assert block["feedback"]["batches"] >= 1
    assert block["candidate"] is not None  # staged + shadow-scored
    assert block["config"]["swap_gate"] == "off"
    # the lifecycle block lives at the top level ONLY — the serve
    # block does not carry a second copy of the same dict
    assert "lifecycle" not in report["serve"]
    # the adapt stage was timed
    assert report["stages"]["adapt"]["seconds"] > 0.0


def test_adapt_chaos_statistics_byte_identical(session):
    """The rollback pin under chaos: deterministic and probabilistic
    serve.adapt/serve.swap faults never touch the served statistics
    (the adapter retries; the request path is not involved)."""
    base = (
        f"info_file={session['info']}&fe=dwt-8-fused&serve=true"
        f"&load_clf=logreg&load_name={session['model']}"
        "&adapt=true&swap_gate=off&adapt_batch=8"
    )
    clean = builder.PipelineBuilder(base).execute()
    before = obs.metrics.snapshot()["counters"]
    chaosed = builder.PipelineBuilder(
        base + "&faults=serve.adapt:once@1"
    ).execute()
    after = obs.metrics.snapshot()["counters"]
    assert str(chaosed) == str(clean)
    assert after["chaos.fired.serve.adapt"] - before.get(
        "chaos.fired.serve.adapt", 0.0
    ) == 1
    # the failed chunk retried rather than forking the trajectory
    assert after["serve.adapt_failures"] - before.get(
        "serve.adapt_failures", 0.0
    ) == 1
    soaked = builder.PipelineBuilder(
        base + "&faults=serve.swap:p=0.2;serve.adapt:p=0.2"
    ).execute()
    assert str(soaked) == str(clean)


# -- promotion + the batch-parity pin ------------------------------------


def test_promotion_parity_and_bounded_retention(session, tmp_path):
    """A permissive gate promotes the candidate; the service then
    serves predictions byte-identical to the batch run of the
    promoted checkpoint — and promotion cleared the superseded
    candidate checkpoints (disk bounded by the live+candidate pair)."""
    ckpt = str(tmp_path / "lc")
    svc = InferenceService.from_saved(
        "logreg", session["model"],
        lifecycle=LifecycleConfig(
            adapt_batch=8, adapt_iters=10, drift_window=16,
            gate_mode="cost", gate_ratio=100.0, checkpoint_dir=ckpt,
            rollback=False,
        ),
    )
    before = obs.metrics.snapshot()["counters"].get("serve.swaps", 0.0)
    svc.start()
    try:
        _feed_session(svc, session, repeats=2)
        block = svc.lifecycle.block()
        assert block["swaps"] >= 1
        assert block["generation"] == block["swaps"]
        promoted_path = block["promoted_path"]
        assert promoted_path and os.path.exists(promoted_path)
        # bounded retention: each promotion cleared its superseded
        # trajectory (manager max_to_keep bounds the live candidate)
        assert block["checkpoint"]["steps"] <= 2
        assert sorted(os.listdir(ckpt)) == ["candidate", "promoted.npz"]
        results = svc.predict_all(
            session["windows"], session["resolutions"]
        )
    finally:
        svc.stop(drain=True)
    assert obs.metrics.snapshot()["counters"]["serve.swaps"] > before
    served = np.array([r.prediction for r in results])
    promoted = clf_registry.create("logreg")
    promoted.load(promoted_path)
    batch_preds = promoted.predict(session["batch_features"])
    np.testing.assert_array_equal(served, batch_preds)
    # statistics built the load_clf= way are therefore byte-identical
    s_served = stats.ClassificationStatistics.from_arrays(
        served, session["targets"], confusion_only=True
    )
    s_batch = stats.ClassificationStatistics.from_arrays(
        batch_preds, session["targets"], confusion_only=True
    )
    assert str(s_served) == str(s_batch)
    # the swap retriggered no serving recompile: the engine still
    # holds its original compiled program (weights are traced args)
    assert svc.engine.classifier.weights.dtype == np.float32


def test_failed_swap_leaves_live_model_untouched(session):
    """serve.swap chaos on every attempt: promotions keep failing,
    the live classifier OBJECT stays installed, the candidate is
    retained for the next gate pass, and the evidence is counted."""
    svc = InferenceService.from_saved(
        "logreg", session["model"],
        lifecycle=LifecycleConfig(
            adapt_batch=8, adapt_iters=10, drift_window=16,
            gate_mode="cost", gate_ratio=100.0,
        ),
    )
    live = svc.engine.classifier
    before = obs.metrics.snapshot()["counters"]
    svc.start()
    try:
        with chaos.faults("serve.swap:every@1"):
            _feed_session(svc, session, repeats=2)
        block = svc.lifecycle.block()
        assert block["swaps"] == 0
        assert block["swap_failures"] >= 1
        assert block["candidate"] is not None  # retained, not burned
        assert svc.engine.classifier is live
        # live serving unaffected
        r = svc.predict_window(
            session["windows"][0], session["resolutions"]
        )
        assert r.prediction == session["batch_predictions"][0]
    finally:
        svc.stop(drain=True)
    after = obs.metrics.snapshot()["counters"]
    assert after["serve.swap_failures"] > before.get(
        "serve.swap_failures", 0.0
    )
    assert after["chaos.fired.serve.swap"] > before.get(
        "chaos.fired.serve.swap", 0.0
    )


def test_rollback_on_regression_restores_previous_model(session):
    """A promoted model whose windowed cost regresses past the
    pre-swap record is rolled back: the previous classifier object is
    re-installed, the rollback is counted and event-visible."""
    svc = InferenceService.from_saved(
        "logreg", session["model"],
        lifecycle=LifecycleConfig(
            adapt_batch=8, adapt_iters=5, drift_window=8,
            gate_mode="off", gate_ratio=None,
        ),
    )
    before = obs.metrics.snapshot()["counters"].get(
        "serve.rollbacks", 0.0
    )
    svc.start()
    try:
        lc = svc.lifecycle
        original = svc.engine.classifier
        # stage a promotion by hand: a deliberately-broken model
        # (negated weights) with a perfect pre-swap record
        bad = lc._clone_with_weights(
            original, -np.asarray(original.weights), 0.0
        )
        previous = svc.engine.swap_model(bad)
        lc._previous = (previous, 0.0)
        assert svc.engine.classifier is bad
        _feed_session(svc, session)
        assert svc.engine.classifier is previous
        block = lc.block()
        assert block["rollbacks"] == 1
        assert block["rollback_armed"] is False
        # serving continues on the restored model
        r = svc.predict_window(
            session["windows"][0], session["resolutions"]
        )
        assert r.prediction == session["batch_predictions"][0]
    finally:
        svc.stop(drain=True)
    assert obs.metrics.snapshot()["counters"]["serve.rollbacks"] > before


def test_drift_detection_fires_on_windowed_regression(session):
    """Windowed expected cost past the baseline factor emits
    serve.drift (rate-limited to once per window span): label flips
    simulate electrode drift against the trained model."""
    svc = InferenceService.from_saved(
        "logreg", session["model"],
        lifecycle=LifecycleConfig(
            adapt_batch=8, adapt_iters=5, drift_window=8,
            gate_mode="off", gate_ratio=None, drift_factor=1.5,
        ),
    )
    before = obs.metrics.snapshot()["counters"].get("serve.drift", 0.0)
    svc.start()
    try:
        # first window: the true labels establish the baseline
        _feed_session(svc, session)
        assert svc.lifecycle.baseline_cost is not None
        # then the world shifts: flipped labels make every live
        # decision wrong — windowed cost -> ~1.0
        for w, y in zip(session["windows"], session["targets"]):
            svc.feedback(w, session["resolutions"], 1.0 - float(y))
        assert svc.lifecycle.flush(timeout_s=60.0)
        block = svc.lifecycle.block()
        assert block["drift_events"] >= 1
        assert block["live_window"]["expected_cost"] > (
            block["baseline_cost"]
        )
    finally:
        svc.stop(drain=True)
    assert obs.metrics.snapshot()["counters"]["serve.drift"] > before


# -- drain/wedge/shutdown races ------------------------------------------


def test_feedback_after_drain_raises_closed(session):
    svc = InferenceService.from_saved(
        "logreg", session["model"],
        lifecycle=LifecycleConfig(adapt_batch=8),
    )
    svc.start()
    svc.feedback(
        session["windows"][0], session["resolutions"],
        float(session["targets"][0]),
    )
    svc.stop(drain=True)
    with pytest.raises(ServiceClosedError, match="not accepting"):
        svc.feedback(
            session["windows"][0], session["resolutions"], 1.0
        )
    assert svc.lifecycle.state == "closed"


def test_submit_label_requires_lifecycle(session):
    with InferenceService.from_saved("logreg", session["model"]) as svc:
        with pytest.raises(ValueError, match="adapt=true"):
            svc.submit(
                session["windows"][0], session["resolutions"],
                label=1.0,
            )
        with pytest.raises(ValueError, match="adapt=true"):
            svc.feedback(
                session["windows"][0], session["resolutions"], 1.0
            )


def test_submit_label_feeds_the_adapter(session):
    svc = InferenceService.from_saved(
        "logreg", session["model"],
        lifecycle=LifecycleConfig(
            adapt_batch=4, gate_mode="off", gate_ratio=None
        ),
    )
    svc.start()
    try:
        futs = [
            svc.submit(
                session["windows"][i], session["resolutions"],
                block_s=5.0, label=float(session["targets"][i]),
            )
            for i in range(8)
        ]
        for f in futs:
            f.result(timeout=10.0)
        assert svc.lifecycle.flush(timeout_s=30.0)
        block = svc.lifecycle.block()
        assert block["feedback"]["received"] == 8
        assert block["feedback"]["batches"] >= 2
    finally:
        svc.stop(drain=True)


def test_stop_during_adaptation_no_deadlock(session):
    """The swap-vs-drain race: stop(drain=True) lands while feedback
    is queued and a promotion is imminent — the drain must complete
    (bounded), the adapter close cleanly, and the service end in a
    consistent state."""
    svc = InferenceService.from_saved(
        "logreg", session["model"],
        config=ServeConfig(drain_timeout_s=30.0),
        lifecycle=LifecycleConfig(
            adapt_batch=8, adapt_iters=10, drift_window=16,
            gate_mode="cost", gate_ratio=100.0, rollback=False,
        ),
    )
    svc.start()
    _feed_session(svc, session, repeats=2, flush=False)
    t0 = time.monotonic()
    drained = svc.stop(drain=True)
    assert drained is True
    assert time.monotonic() - t0 < 60.0
    assert svc.lifecycle.state == "closed"
    # whatever the shutdown/swap interleaving, the installed model is
    # a servable linear classifier of the live shape
    clf = svc.engine.classifier
    assert clf.weights is not None and clf.weights.dtype == np.float32


def test_wedged_adapter_discards_candidate_live_serving_continues(
    session,
):
    """The engine-wedge-mid-shadow race: a featurize call that never
    returns trips the lifecycle watchdog — the candidate is
    discarded, feedback drops (counted) instead of queueing forever,
    and the REQUEST path keeps answering untouched."""
    svc = InferenceService.from_saved(
        "logreg", session["model"],
        lifecycle=LifecycleConfig(
            adapt_batch=4, watchdog_s=0.3, gate_mode="off",
            gate_ratio=None,
        ),
    )
    release = threading.Event()

    def wedging_featurize(windows, _res):
        release.wait(30.0)
        return np.zeros((len(windows), 48), np.float32)

    before = obs.metrics.snapshot()["counters"].get(
        "serve.lifecycle_wedged", 0.0
    )
    svc.start()
    try:
        # first a healthy batch, so there is a real candidate to lose
        for i in range(4):
            svc.feedback(
                session["windows"][i], session["resolutions"],
                float(session["targets"][i]),
            )
        assert svc.lifecycle.flush(timeout_s=30.0)
        assert svc.lifecycle.block()["candidate"] is not None
        # then the wedge
        svc.lifecycle._featurize = wedging_featurize
        for i in range(4):
            svc.feedback(
                session["windows"][i], session["resolutions"],
                float(session["targets"][i]),
            )
        deadline = time.monotonic() + 10.0
        while (
            not svc.lifecycle.wedged.is_set()
            and time.monotonic() < deadline
        ):
            time.sleep(0.02)
        assert svc.lifecycle.wedged.is_set()
        block = svc.lifecycle.block()
        assert block["wedged"] and block["state"] == "wedged"
        assert block["candidate"] is None  # discarded
        # live serving continues on the untouched model
        r = svc.predict_window(
            session["windows"][0], session["resolutions"]
        )
        assert r.prediction == session["batch_predictions"][0]
        # feedback now drops with evidence instead of queueing
        assert svc.feedback(
            session["windows"][0], session["resolutions"], 1.0
        ) is False
        assert svc.lifecycle.block()["feedback"]["dropped"] >= 1
    finally:
        release.set()
        svc.stop(drain=True)
    after = obs.metrics.snapshot()["counters"]
    assert after["serve.lifecycle_wedged"] > before


# -- SIGKILL mid-partial-fit + resume ------------------------------------


def _run_worker(ckpt_dir, n_batches, kill_after=None):
    worker = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "_lifecycle_worker.py",
    )
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [sys.executable, worker, ckpt_dir, str(n_batches)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    if kill_after is None:
        out, err = proc.communicate(timeout=240)
        assert proc.returncode == 0, err[-2000:]
        return out
    seen = 0
    for line in proc.stdout:
        if line.startswith("CKPT"):
            seen += 1
            if seen >= kill_after:
                break
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait(timeout=60)
    proc.stdout.close()
    proc.stderr.close()
    return None


def test_sigkill_mid_partial_fit_resumes_byte_identical(tmp_path):
    """The resume pin: a SIGKILL'd adapter restores its checkpointed
    carry+buffers and replays the remaining feedback to candidate
    weights BYTE-IDENTICAL to an uninterrupted run (absolute
    iteration indices — the one true trajectory)."""
    n_batches = 6
    # the uninterrupted twin
    twin_out = _run_worker(str(tmp_path / "twin"), n_batches)
    twin_w = [
        line for line in twin_out.splitlines() if line.startswith("W ")
    ][-1]
    # the victim: SIGKILLed after its 3rd checkpoint, mid-stream
    killed_dir = str(tmp_path / "killed")
    _run_worker(killed_dir, n_batches, kill_after=3)
    # a checkpoint survived the kill
    steps = os.listdir(os.path.join(killed_dir, "candidate"))
    assert any(s.startswith("step_") for s in steps)
    # resume over the same directory: restores + replays the rest
    resumed_out = _run_worker(killed_dir, n_batches)
    resumed_w = [
        line for line in resumed_out.splitlines()
        if line.startswith("W ")
    ][-1]
    assert resumed_w == twin_w
    assert twin_w != "W none"


def test_partial_fit_surface_matches_monolithic_trajectory():
    """models/sgd.partial_fit_linear over chunks replays the exact
    monolithic _run_sgd trajectory on a fixed matrix (the absolute-
    iteration-index seam the lifecycle builds on)."""
    from eeg_dataanalysispackage_tpu.models import sgd

    rng = np.random.RandomState(3)
    x = rng.randn(32, 8).astype(np.float32)
    y = (rng.rand(32) > 0.5).astype(np.float32)
    config = sgd.SGDConfig(
        num_iterations=30, step_size=0.5, convergence_tol=0.0
    )
    whole = sgd.train_linear(x, y, config)
    carry = sgd.partial_fit_carry(8)
    mask = np.ones(32, np.float32)
    for t0 in range(0, 30, 10):
        carry = sgd.partial_fit_linear(
            carry, t0, x, y, config, 10, sample_mask=mask
        )
    np.testing.assert_array_equal(np.asarray(carry[0]), whole)
