"""The serve-path megakernel + the int8 precision rung (PR 12).

The acceptance bar: the mega rung's served predictions are
bit-identical to the fused twin's (and the batch pipeline's) on the
same epochs, a window's margin is bit-identical whatever batch it
rides in (within one capacity bucket), a failing mega program
degrades to fused without dropping requests, and the int8 rung ships
gate-protected — a forced-zero-tolerance run auto-disables and pins
byte-identical-to-f32 statistics, and int8 cache entries can never
serve an f32/bf16-class request.
"""

import numpy as np
import pytest

import _synthetic
from eeg_dataanalysispackage_tpu.io import feature_cache, provider
from eeg_dataanalysispackage_tpu.models import registry as clf_registry
from eeg_dataanalysispackage_tpu.obs import chaos
from eeg_dataanalysispackage_tpu.ops import (
    decode_ingest,
    device_ingest,
    quant,
    serve_mega,
)
from eeg_dataanalysispackage_tpu.pipeline import builder
from eeg_dataanalysispackage_tpu.serve import (
    InferenceService,
    ServeConfig,
    engine,
)

_CONFIG = (
    "&config_num_iterations=20&config_step_size=1.0"
    "&config_mini_batch_fraction=1.0"
)

_C, _PRE, _POST = 3, 100, 750
_WIN = _PRE + _POST


@pytest.fixture(scope="module")
def session(tmp_path_factory):
    """One synthetic two-file session + a trained, saved logreg model
    (the serve test fixture's shape)."""
    tmp = tmp_path_factory.mktemp("serve_mega_session")
    for i, (name, guessed) in enumerate(
        (("synth_00", 2), ("synth_01", 5))
    ):
        _synthetic.write_recording(
            str(tmp), name=name, n_markers=90, guessed=guessed, seed=i
        )
    info = str(tmp / "info.txt")
    with open(info, "w") as f:
        f.write("synth_00.eeg 2\nsynth_01.eeg 5\n")
    model = str(tmp / "model")
    builder.PipelineBuilder(
        f"info_file={info}&fe=dwt-8-fused&train_clf=logreg"
        f"&save_clf=true&save_name={model}&cache=false{_CONFIG}"
    ).execute()
    classifier = clf_registry.create("logreg")
    classifier.load(model)
    return {"info": info, "model": model, "classifier": classifier}


def _windows(n, seed=0):
    rng = np.random.RandomState(seed)
    return [
        (
            rng.randint(-3000, 3000, size=(_C, _WIN))
            + np.asarray([12000, -9000, 6000])[:, None]
        ).astype(np.int16)
        for _ in range(n)
    ]


_RES = np.full(_C, 0.1, np.float32)


def _fused_margins(windows, weights, capacity):
    """Reference margins through the engine's fused featurizer on the
    engine's own stream layout."""
    featurizer = device_ingest.make_device_ingest_featurizer(
        wavelet_index=8, epoch_size=512, skip_samples=175,
        feature_size=16, channels=(1, 2, 3), pre=_PRE, post=_POST,
    )
    stream = np.zeros((_C, capacity * _WIN), np.int16)
    for i, w in enumerate(windows):
        stream[:, i * _WIN:(i + 1) * _WIN] = w
    positions = np.arange(capacity, dtype=np.int32) * _WIN + _PRE
    mask = np.zeros(capacity, bool)
    mask[: len(windows)] = True
    feats = np.asarray(featurizer(stream, _RES, positions, mask))
    return feats[: len(windows)] @ weights


def _mega_margins(windows, weights, capacity, lowering):
    import jax

    prog = serve_mega.make_serve_mega_program(
        n_channels=_C, pre=_PRE, post=_POST, capacity=capacity,
        lowering=lowering, interpret=True, donate=False,
    )
    stride = serve_mega.padded_stride(_PRE, _POST)
    stream = serve_mega.stage_mega_stream(
        windows, _C, _WIN, stride, capacity
    )
    weights = np.asarray(weights, np.float32)
    return np.asarray(prog(jax.device_put(stream), _RES, weights))


# -- kernel parity -------------------------------------------------------


@pytest.mark.parametrize("lowering", ["xla", "pallas"])
@pytest.mark.parametrize("capacity", [64, 128])
def test_mega_margins_match_fused_across_buckets(lowering, capacity):
    """Both lowerings' margins sit inside the documented gate against
    the fused program's, for every capacity bucket — the ladder-rung
    parity class the warmup gate enforces."""
    rng = np.random.RandomState(1)
    weights = rng.randn(_C * 16).astype(np.float32)
    for n in (1, 3, capacity):
        windows = _windows(n, seed=n)
        ref = _fused_margins(windows, weights, capacity)
        got = _mega_margins(windows, weights, capacity, lowering)
        dev = float(np.max(np.abs(got[:n] - ref)))
        assert dev <= serve_mega.MEGA_GATE_TOL, (lowering, capacity, n, dev)
        # padded capacity rows are exactly zero (zero stream, guarded
        # normalize) — nothing leaks across requests
        assert np.all(got[n:] == 0.0)


@pytest.mark.parametrize("lowering", ["xla", "pallas"])
def test_mega_bit_identical_within_bucket(lowering):
    """One window's margin is BYTE-equal whatever batch it rides in:
    row-independent compute through one compiled program per bucket —
    the contract that keeps served statistics byte-identical to the
    batch path across batch-size jitter."""
    rng = np.random.RandomState(2)
    weights = rng.randn(_C * 16).astype(np.float32)
    windows = _windows(7, seed=7)
    batch = _mega_margins(windows, weights, 64, lowering)
    for i, w in enumerate(windows):
        solo = _mega_margins([w], weights, 64, lowering)
        assert solo[0] == batch[i]


def test_mega_program_rejects_bad_geometry():
    with pytest.raises(ValueError, match="pre >= 1"):
        serve_mega.make_serve_mega_program(
            n_channels=_C, pre=0, post=512, capacity=64,
            lowering="xla", interpret=True, donate=False,
        )
    with pytest.raises(ValueError, match="multiple"):
        serve_mega._mega_program(
            8, 512, 175, 16, _C, _PRE, _POST, 60, "xla", True, False
        )
    with pytest.raises(ValueError, match="lowering"):
        serve_mega.make_serve_mega_program(
            n_channels=_C, pre=_PRE, post=_POST, capacity=64,
            lowering="cuda", interpret=True, donate=False,
        )


# -- the engine rung ladder ----------------------------------------------


def test_engine_promotes_mega_and_matches_fused(session):
    """On CPU the auto rung resolves to mega (the XLA twin), the
    warmup parity gate passes, and served predictions are
    bit-identical to a fused-pinned twin service's."""
    windows = _windows(12, seed=3)
    with InferenceService(
        session["classifier"], engine_rung="auto"
    ) as mega_svc:
        assert mega_svc.engine.rung == "mega"
        record = mega_svc.engine.mega_record
        assert record["used"] == "mega" and record["gate"]["ok"]
        mega = [
            r.prediction
            for r in mega_svc.predict_all(windows, _RES)
        ]
    with InferenceService(
        session["classifier"], engine_rung="fused"
    ) as fused_svc:
        assert fused_svc.engine.rung == "fused"
        # a fused-pinned engine records no mega candidacy
        assert fused_svc.engine.mega_record is None
        fused = [
            r.prediction
            for r in fused_svc.predict_all(windows, _RES)
        ]
    assert mega == fused
    # the stats block carries the rung + the mega record
    block = mega_svc.stats_block()
    assert block["rung"] == "mega"
    assert block["mega"]["used"] == "mega"


def test_engine_mega_gate_refusal_serves_fused(session, monkeypatch):
    """A forced-impossible tolerance refuses the rung at warmup: the
    engine serves the fused program with the gate evidence recorded —
    never a silent numerics change."""
    monkeypatch.setenv("EEG_TPU_MEGA_GATE_TOL", "0")
    svc = InferenceService(session["classifier"], engine_rung="mega")
    svc.start()
    try:
        assert svc.engine.rung == "fused"
        record = svc.engine.mega_record
        assert record["used"] == "fused"
        assert record["gate"] is not None and not record["gate"]["ok"]
        r = svc.predict_window(_windows(1)[0], _RES)
        assert r.prediction in (0.0, 1.0)
    finally:
        svc.stop(drain=True)


def test_engine_mega_failure_degrades_to_fused_without_drop(session):
    """A mega program that breaks mid-residency steps the engine down
    to fused and the triggering batch is still answered — the ladder
    degrades, requests never drop."""
    eng = engine.ServingEngine(
        session["classifier"], capacity=8, engine_rung="mega"
    )
    eng.warmup()
    assert eng.rung == "mega"

    calls = {"n": 0}

    def broken(*a, **k):
        calls["n"] += 1
        raise RuntimeError("mega backend broke")

    eng._mega_program = broken
    eng._degrade_after = 1  # first failure latches (deterministic)
    windows = _windows(3, seed=5)
    predictions, margins = eng.execute(windows, _RES)
    assert calls["n"] == 1
    assert eng.rung == "fused"
    assert len(predictions) == 3 and margins is not None
    assert eng.mega_record["used"] == "fused"
    assert "error" in eng.mega_record
    # and the fused rung keeps serving
    predictions2, _ = eng.execute(windows, _RES)
    np.testing.assert_array_equal(predictions, predictions2)


def test_engine_rung_validation(session):
    with pytest.raises(ValueError, match="engine_rung"):
        engine.ServingEngine(
            session["classifier"], engine_rung="turbo"
        )


def test_chaos_soak_clean_on_mega_rung(session):
    """faults=serve.batch against a mega-rung service: every request
    resolves and the drain completes (the no-wedge contract holds on
    the new rung)."""
    windows = _windows(10, seed=9)
    svc = InferenceService(
        session["classifier"], engine_rung="mega",
        config=ServeConfig(max_attempts=4, retry_backoff_s=0.01),
    )
    with chaos.faults("serve.batch:p=0.2;serve.request:p=0.1;seed=3"):
        svc.start()
        assert svc.engine.rung == "mega"
        futures = [
            svc.submit(windows[i % len(windows)], _RES, deadline_s=10.0,
                       block_s=10.0)
            for i in range(40)
        ]
        outcomes = []
        for fut in futures:
            try:
                outcomes.append(fut.result(timeout=30.0).prediction)
            except Exception as e:  # resolution-with-evidence is clean
                outcomes.append(type(e).__name__)
        drained = svc.stop(drain=True)
    assert len(outcomes) == 40
    assert drained
    # chaos is absorbed by retries, not by a rung change
    assert svc.engine.rung == "mega"


def test_accelerator_decision_paths(tmp_path):
    """No artifact -> fused with the absence recorded; a chip sweep
    beating the pre-registered ratio -> mega; cpu_fallback artifacts
    are skipped (the PR 9 decision-path pattern)."""
    import json

    empty = tmp_path / "empty"
    empty.mkdir()
    d = serve_mega.accelerator_decision(root=str(empty))
    assert d["rung"] == "fused" and "no on-chip" in d["reason"]

    def write(root, platform, mega, fused):
        rd = root / "r9"
        rd.mkdir(parents=True, exist_ok=True)
        (rd / "serve_mega.json").write_text(json.dumps({
            "platform": platform,
            "serve": {"mega_vs_fused": {"sweep": [
                {"concurrency": 16,
                 "mega": {"preds_per_s": mega},
                 "fused": {"preds_per_s": fused}},
            ]}},
        }) + "\n")

    chip = tmp_path / "chip"
    write(chip, "tpu", 3000.0, 1000.0)
    d = serve_mega.accelerator_decision(root=str(chip))
    assert d["rung"] == "mega" and d["ratio"] == 3.0

    slow = tmp_path / "slow"
    write(slow, "tpu", 1000.0, 990.0)
    d = serve_mega.accelerator_decision(root=str(slow))
    assert d["rung"] == "fused"

    cpu = tmp_path / "cpu"
    write(cpu, "cpu_fallback", 9000.0, 1.0)
    d = serve_mega.accelerator_decision(root=str(cpu))
    assert d["rung"] == "fused" and d["source"] is None


# -- the int8 precision rung ---------------------------------------------


def test_int8_quantize_roundtrip_properties():
    """Per-(row, channel, subband) scales, the arithmetic error bound,
    exact zero preservation, and determinism."""
    rng = np.random.RandomState(0)
    rows = rng.randn(32, 48).astype(np.float32)
    rows /= np.linalg.norm(rows, axis=1, keepdims=True)
    rows[5] = 0.0  # a masked/padded row
    dq, scales = decode_ingest.quantize_dequantize_int8(rows, 16)
    dq = np.asarray(dq)
    scales = np.asarray(scales)
    n_groups = len(decode_ingest.subband_group_bounds(16))
    assert scales.shape == (n_groups, 32, 3)
    # worst-case rounding error is scale/2 per group
    x = rows.reshape(32, 3, 16)
    d = np.abs(np.asarray(dq).reshape(32, 3, 16) - x)
    for gi, (lo, hi) in enumerate(decode_ingest.subband_group_bounds(16)):
        bound = scales[gi][:, :, None] / 2 + 1e-7
        assert np.all(d[:, :, lo:hi] <= bound)
    assert np.all(dq[5] == 0.0)
    dq2, _ = decode_ingest.quantize_dequantize_int8(rows, 16)
    np.testing.assert_array_equal(dq, np.asarray(dq2))


def test_int8_quantize_is_row_independent():
    """Scales are per ROW: a row's dequantized features are byte-equal
    whatever batch it rides in — a served request's int8 margin can
    never depend on concurrent traffic (the mega rung's within-bucket
    contract, held by the int8 rung too)."""
    rng = np.random.RandomState(1)
    rows = rng.randn(8, 48).astype(np.float32)
    # a LOUD neighbour: 100x amplitude — under batch-wide scales this
    # row would stretch everyone's quantization grid
    rows[3] *= 100.0
    dq_batch, _ = decode_ingest.quantize_dequantize_int8(rows, 16)
    dq_batch = np.asarray(dq_batch)
    for i in range(8):
        dq_solo, _ = decode_ingest.quantize_dequantize_int8(
            rows[i:i + 1], 16
        )
        np.testing.assert_array_equal(
            np.asarray(dq_solo)[0], dq_batch[i]
        )


def test_subband_group_bounds():
    assert decode_ingest.subband_group_bounds(16) == (
        (0, 1), (1, 2), (2, 4), (4, 8), (8, 16)
    )
    assert decode_ingest.subband_group_bounds(1) == ((0, 1),)
    with pytest.raises(ValueError):
        decode_ingest.subband_group_bounds(0)


def test_int8_decode_featurizer_within_gate():
    """The int8 decode rung's rows deviate from f32 by less than the
    documented gate on realistic DC-offset signal, and the gate record
    says so."""
    rng = np.random.RandomState(3)
    S = 16384
    raw = (
        rng.randint(-3000, 3000, size=(3, S))
        + np.asarray([15000, -12000, 9000])[:, None]
    ).astype(np.int16)
    res = np.full(3, 0.1, np.float32)
    positions = (np.arange(24, dtype=np.int64) * 600 + _PRE)
    cap = 64
    pos = np.zeros(cap, np.int32)
    pos[:24] = positions
    mask = np.zeros(cap, bool)
    mask[:24] = True
    f32 = decode_ingest.make_decode_ingest_featurizer(precision="f32")(
        raw, res, pos, mask
    )
    i8 = decode_ingest.make_decode_ingest_featurizer(precision="int8")(
        raw, res, pos, mask
    )
    gate = decode_ingest.feature_precision_gate(
        np.asarray(i8)[mask], np.asarray(f32)[mask], precision="int8"
    )
    assert gate["ok"], gate
    assert 0.0 < gate["max_abs_dev"] <= decode_ingest.INT8_GATE_TOL
    assert gate["precision"] == "int8"


def test_int8_gate_tolerance_env(monkeypatch):
    monkeypatch.setenv("EEG_TPU_INT8_GATE_TOL", "0.5")
    assert decode_ingest.precision_gate_tolerance("int8") == 0.5
    monkeypatch.setenv("EEG_TPU_INT8_GATE_TOL", "zero")
    assert (
        decode_ingest.precision_gate_tolerance("int8")
        == decode_ingest.INT8_GATE_TOL
    )
    with pytest.raises(ValueError, match="no accuracy gate"):
        decode_ingest.precision_gate_tolerance("f32")


def test_int8_extractor_id_and_cache_class_separation(
    session, tmp_path, monkeypatch
):
    """int8 keys its own cache entries: an f32 entry can never serve
    an int8 request, a bf16 entry can never serve int8, and vice
    versa — the cross-class miss matrix extended to the new rung."""
    assert provider.fused_extractor_id(8, "int8") == (
        provider.fused_extractor_id(8) + ("int8",)
    )
    ids = {
        p: provider.fused_extractor_id(8, p)
        for p in ("f32", "bf16", "int8", "int4")
    }
    assert len(set(ids.values())) == 4

    monkeypatch.delenv("EEG_TPU_NO_FEATURE_CACHE", raising=False)
    monkeypatch.setenv(
        "EEG_TPU_FEATURE_CACHE_DIR", str(tmp_path / "fc")
    )
    odp = provider.OfflineDataProvider([session["info"]])
    keys = {
        p: odp.prepare_fused_run(ids[p]).key
        for p in ("f32", "bf16", "int8", "int4")
    }
    assert len(set(keys.values())) == 4
    cache = feature_cache.open_cache()
    cache.store(
        keys["f32"], np.ones((4, 48), np.float32), np.zeros(4)
    )
    # the f32 entry hits only its own class
    assert cache.lookup(keys["f32"]) is not None
    assert cache.lookup(keys["int8"]) is None
    assert cache.lookup(keys["bf16"]) is None
    assert cache.lookup(keys["int4"]) is None
    cache.store(
        keys["int8"], np.full((4, 48), 2.0, np.float32), np.zeros(4)
    )
    cache.store(
        keys["int4"], np.full((4, 48), 3.0, np.float32), np.zeros(4)
    )
    hit = cache.lookup(keys["int8"])
    assert hit is not None and float(hit[0][0, 0]) == 2.0
    # the two quantized classes never serve each other either
    i4_hit = cache.lookup(keys["int4"])
    assert i4_hit is not None and float(i4_hit[0][0, 0]) == 3.0
    # and the quantized entries never leak into the f32 class
    f32_hit = cache.lookup(keys["f32"])
    assert f32_hit is not None and float(f32_hit[0][0, 0]) == 1.0


def test_int8_pipeline_auto_disable_pins_f32_statistics(
    session, monkeypatch
):
    """The acceptance pin: a forced-zero-tolerance int8 run
    auto-disables and produces statistics byte-identical to the f32
    run; an un-forced run records its gate decision (with the
    gate_seconds attribution)."""
    q = (
        f"info_file={session['info']}&train_clf=logreg&cache=false"
        f"{_CONFIG}"
    )
    pb_f32 = builder.PipelineBuilder(q + "&fe=dwt-8-fused-decode")
    s_f32 = pb_f32.execute()

    provider.reset_gate_memo()
    pb_i8 = builder.PipelineBuilder(
        q + "&fe=dwt-8-fused&precision=int8"
    )
    s_i8 = pb_i8.execute()
    rec = pb_i8.precision_resolved
    assert rec["requested"] == "int8" and rec["used"] == "int8"
    assert rec["gate"]["ok"] and rec["gate"]["gate_seconds"] > 0.0

    monkeypatch.setenv("EEG_TPU_INT8_GATE_TOL", "0")
    pb_off = builder.PipelineBuilder(
        q + "&fe=dwt-8-fused&precision=int8"
    )
    s_off = pb_off.execute()
    assert pb_off.precision_resolved["used"] == "f32"
    assert not pb_off.precision_resolved["gate"]["ok"]
    assert str(s_off) == str(s_f32)
    del s_i8  # gate-passing statistics live in their own class


def test_precision_gate_memo_replays(session):
    """The hoisted gate: re-gating the same content in one process
    replays the memoized decision (cached=True, gate_seconds=0) —
    the double-featurize runs once."""
    provider.reset_gate_memo()
    odp = provider.OfflineDataProvider([session["info"]])
    prepared = odp.prepare_fused_run(
        provider.fused_extractor_id(8, "bf16")
    )
    digest = prepared.digests[0][2]
    first = odp.precision_gate_check(
        prepared.recordings, 8, precision="bf16", content_key=digest
    )
    assert first["cached"] is False and first["gate_seconds"] > 0.0
    second = odp.precision_gate_check(
        prepared.recordings, 8, precision="bf16", content_key=digest
    )
    assert second["cached"] is True and second["gate_seconds"] == 0.0
    assert second["max_abs_dev"] == first["max_abs_dev"]
    # no content key (or a tolerance change) never replays stale
    third = odp.precision_gate_check(
        prepared.recordings, 8, precision="bf16"
    )
    assert third["cached"] is False


def test_engine_int8_warmup_gate_records(session):
    """The serving engine's int8 rung gates at warmup like bf16: the
    decision (and auto-disable under a forced-zero tolerance) lands
    in the precision record."""
    svc = InferenceService(
        session["classifier"], precision="int8",
        config=ServeConfig(max_batch=16),
    )
    svc.start()
    try:
        rec = svc.engine.precision_record
        assert rec["requested"] == "int8"
        assert rec["used"] == "int8" and rec["gate"]["ok"]
        r = svc.predict_window(_windows(1)[0], _RES)
        assert r.prediction in (0.0, 1.0)
    finally:
        svc.stop(drain=True)


def test_engine_int8_gate_auto_disables(session, monkeypatch):
    monkeypatch.setenv("EEG_TPU_INT8_GATE_TOL", "0")
    svc = InferenceService(
        session["classifier"], precision="int8",
        config=ServeConfig(max_batch=16),
    )
    svc.start()
    try:
        rec = svc.engine.precision_record
        assert rec["used"] == "f32" and not rec["gate"]["ok"]
        # a gated-off int8 engine is an EFFECTIVE-f32 engine: since
        # ISSUE 18 un-pinned quantized engines from fused, it attempts
        # (and on CPU earns) the mega rung at the f32 parity bound
        assert svc.engine.rung == "mega"
        assert svc.engine.mega_record["precision"] == "f32"
    finally:
        svc.stop(drain=True)
    assert svc.stats_block()["precision"]["used"] == "f32"


# -- the int4 precision rung (ISSUE 18) ----------------------------------


def test_int4_decode_featurizer_within_gate():
    """The bottom rung's rows deviate from f32 by less than the int4
    gate on realistic DC-offset signal — and coarser than int8's on
    the SAME signal, pinning the ladder's ordering."""
    rng = np.random.RandomState(3)
    S = 16384
    raw = (
        rng.randint(-3000, 3000, size=(3, S))
        + np.asarray([15000, -12000, 9000])[:, None]
    ).astype(np.int16)
    res = np.full(3, 0.1, np.float32)
    positions = (np.arange(24, dtype=np.int64) * 600 + _PRE)
    cap = 64
    pos = np.zeros(cap, np.int32)
    pos[:24] = positions
    mask = np.zeros(cap, bool)
    mask[:24] = True
    f32 = decode_ingest.make_decode_ingest_featurizer(precision="f32")(
        raw, res, pos, mask
    )
    i4 = decode_ingest.make_decode_ingest_featurizer(precision="int4")(
        raw, res, pos, mask
    )
    gate = decode_ingest.feature_precision_gate(
        np.asarray(i4)[mask], np.asarray(f32)[mask], precision="int4"
    )
    assert gate["ok"], gate
    assert 0.0 < gate["max_abs_dev"] <= quant.INT4_GATE_TOL
    assert gate["precision"] == "int4"
    i8 = decode_ingest.make_decode_ingest_featurizer(precision="int8")(
        raw, res, pos, mask
    )
    dev_i8 = float(np.max(np.abs(np.asarray(i8) - np.asarray(f32))))
    assert gate["max_abs_dev"] > dev_i8


def test_int4_pipeline_auto_disable_pins_f32_statistics(
    session, monkeypatch
):
    """The ISSUE 18 acceptance pin, int4 edition: a forced-zero-
    tolerance run auto-disables and produces statistics byte-identical
    to the f32 run; an un-forced run records its gate decision."""
    q = (
        f"info_file={session['info']}&train_clf=logreg&cache=false"
        f"{_CONFIG}"
    )
    pb_f32 = builder.PipelineBuilder(q + "&fe=dwt-8-fused-decode")
    s_f32 = pb_f32.execute()

    provider.reset_gate_memo()
    pb_i4 = builder.PipelineBuilder(
        q + "&fe=dwt-8-fused&precision=int4"
    )
    s_i4 = pb_i4.execute()
    rec = pb_i4.precision_resolved
    assert rec["requested"] == "int4" and rec["used"] == "int4"
    assert rec["gate"]["ok"] and rec["gate"]["gate_seconds"] > 0.0

    monkeypatch.setenv("EEG_TPU_INT4_GATE_TOL", "0")
    pb_off = builder.PipelineBuilder(
        q + "&fe=dwt-8-fused&precision=int4"
    )
    s_off = pb_off.execute()
    assert pb_off.precision_resolved["used"] == "f32"
    assert not pb_off.precision_resolved["gate"]["ok"]
    assert str(s_off) == str(s_f32)
    del s_i4  # gate-passing statistics live in their own class


def _mega_int4_margins(windows, weights, capacity):
    import jax

    prog = serve_mega.make_serve_mega_program(
        n_channels=_C, pre=_PRE, post=_POST, capacity=capacity,
        lowering="xla", interpret=True, donate=False,
        precision="int4",
    )
    stride = serve_mega.padded_stride(_PRE, _POST)
    stream = serve_mega.stage_mega_stream(
        windows, _C, _WIN, stride, capacity
    )
    return np.asarray(prog(
        jax.device_put(stream), _RES,
        np.asarray(weights, np.float32),
    ))


def test_mega_int4_bit_identical_within_bucket():
    """Per-ROW quantization keeps the mega contract on the int4 rung:
    a window's int4 margin is byte-equal whatever batch it rides in —
    a loud neighbour cannot stretch its quantization grid."""
    rng = np.random.RandomState(2)
    weights = rng.randn(_C * 16).astype(np.float32)
    windows = _windows(7, seed=7)
    windows[3] = (windows[3].astype(np.int32) * 10).clip(
        -32768, 32767
    ).astype(np.int16)  # the loud neighbour
    batch = _mega_int4_margins(windows, weights, 64)
    for i, w in enumerate(windows):
        solo = _mega_int4_margins([w], weights, 64)
        assert solo[0] == batch[i]
    # padded rows stay exactly zero on the quantized rung too
    assert np.all(batch[len(windows):] == 0.0)


def test_engine_int4_attempts_mega_and_matches_fused_twin(session):
    """ISSUE 18's satellite: quantized-feature engines attempt the
    mega rung (built at the EFFECTIVE precision, judged at the rung's
    own tolerance) instead of the PR 12 hard-pin to fused — and the
    promoted engine's predictions match a fused-pinned int4 twin's."""
    windows = _windows(12, seed=5)
    with InferenceService(
        session["classifier"], precision="int4", engine_rung="auto",
    ) as mega_svc:
        assert mega_svc.engine.precision_record["used"] == "int4"
        record = mega_svc.engine.mega_record
        assert record is not None and record["precision"] == "int4"
        assert record["used"] == "mega" and record["gate"]["ok"]
        # judged at the rung's own tolerance, not the f32 parity bound
        assert record["gate"]["tolerance"] == max(
            serve_mega.mega_gate_tolerance(),
            quant.int4_gate_tolerance(),
        )
        mega = [
            r.prediction
            for r in mega_svc.predict_all(windows, _RES)
        ]
    with InferenceService(
        session["classifier"], precision="int4", engine_rung="fused",
    ) as fused_svc:
        assert fused_svc.engine.rung == "fused"
        fused = [
            r.prediction
            for r in fused_svc.predict_all(windows, _RES)
        ]
    assert mega == fused


def test_engine_bf16_stays_pinned_to_fused(session):
    """The un-pin stops at bf16: its cascade runs bfloat16 OPERANDS,
    so there is no bf16 mega twin to gate — the engine records no
    mega candidacy at all."""
    with InferenceService(
        session["classifier"], precision="bf16", engine_rung="auto",
    ) as svc:
        assert svc.engine.rung == "fused"
        assert svc.engine.mega_record is None


# -- the serve_flush_us coalescing window --------------------------------


class _CountingExecutor:
    def __init__(self):
        self.batches = []

    def __call__(self, windows, resolutions):
        self.batches.append(len(windows))
        return np.zeros(len(windows)), None


def test_flush_window_fills_buckets():
    """With serve_flush_us set, queued compatible requests fill the
    bucket before dispatch: 8 near-simultaneous requests land in ONE
    batch instead of racing the dispatcher."""
    from eeg_dataanalysispackage_tpu.serve import batcher as batcher_mod

    ex = _CountingExecutor()
    mb = batcher_mod.MicroBatcher(
        ex, max_batch=8, queue_depth=32, coalesce_s=0.0,
        flush_us=300_000,
    )
    reqs = [
        batcher_mod.Request(
            np.zeros((3, 850), np.int16), _RES,
            __import__(
                "eeg_dataanalysispackage_tpu.io.deadline",
                fromlist=["Deadline"],
            ).Deadline(10.0),
        )
        for _ in range(8)
    ]
    for r in reqs:
        mb.queue.offer(r)
    mb.start()
    try:
        for r in reqs:
            r.future.result(timeout=5.0)
    finally:
        mb.stop()
    assert ex.batches == [8]


def test_flush_window_stops_at_key_boundary():
    """The fill predicate counts the head-key RUN, not raw queue
    length: a full queue of mixed keys must not satisfy (or starve)
    the window — the pop stops at the key boundary anyway, so the
    wait ends the moment the HEAD run fills."""
    from eeg_dataanalysispackage_tpu.io.deadline import Deadline
    from eeg_dataanalysispackage_tpu.serve import batcher as batcher_mod

    ex = _CountingExecutor()
    mb = batcher_mod.MicroBatcher(
        ex, max_batch=4, queue_depth=32, coalesce_s=0.0,
        flush_us=150_000,
    )
    res_b = np.full(3, 2.0, np.float32)
    # 4 head-key requests interleaved with 4 of another key: the head
    # run fills to max_batch=4, so the first dispatch is the full
    # head-key bucket and the second the full other-key bucket
    reqs = []
    for i in range(8):
        reqs.append(batcher_mod.Request(
            np.zeros((3, 850), np.int16),
            _RES if i % 2 == 0 else res_b,
            Deadline(10.0),
        ))
    # queue them head-key-run-hostile: alternating keys
    for r in reqs:
        mb.queue.offer(r)
    mb.start()
    try:
        for r in reqs:
            r.future.result(timeout=5.0)
    finally:
        mb.stop()
    # alternating keys mean singleton head runs: every dispatch is a
    # 1-batch, and crucially the flush window did NOT treat the full
    # mixed queue as a filled bucket nor hang waiting on it
    assert ex.batches == [1] * 8


def test_flush_default_zero_is_todays_behavior():
    """flush_us=0 (the default) never enters the fill-wait path: the
    batcher pops whatever is queued the moment it looks — exactly the
    pre-knob dispatch."""
    from eeg_dataanalysispackage_tpu.serve import batcher as batcher_mod

    mb = batcher_mod.MicroBatcher(
        _CountingExecutor(), max_batch=8, queue_depth=32
    )
    assert mb.flush_s == 0.0
    with pytest.raises(ValueError, match="flush_us"):
        batcher_mod.MicroBatcher(
            _CountingExecutor(), max_batch=8, queue_depth=32,
            flush_us=-1,
        )


def test_serve_flush_query_knob(session, monkeypatch):
    """serve_flush_us= reaches the ServeConfig (query wins over env;
    env sets the process default), and the serve stats block records
    it."""
    from eeg_dataanalysispackage_tpu.serve import (
        pipeline as serve_pipeline,
    )

    cfg = serve_pipeline.serve_config_from_query(
        {"serve_flush_us": "500"}
    )
    assert cfg.flush_us == 500
    monkeypatch.setenv("EEG_TPU_SERVE_FLUSH_US", "250")
    cfg = serve_pipeline.serve_config_from_query({})
    assert cfg.flush_us == 250
    cfg = serve_pipeline.serve_config_from_query(
        {"serve_flush_us": "0"}
    )
    assert cfg.flush_us == 0
    monkeypatch.setenv("EEG_TPU_SERVE_FLUSH_US", "junk")
    assert serve_pipeline.default_flush_us() == 0

    svc = InferenceService(
        session["classifier"], config=ServeConfig(flush_us=200)
    )
    svc.start()
    try:
        svc.predict_window(_windows(1)[0], _RES)
    finally:
        svc.stop(drain=True)
    assert svc.stats_block()["flush_us"] == 200


def test_serve_pipeline_statistics_identical_with_flush(session):
    """serve=true with a flush window produces byte-identical
    statistics to the batch load_clf= run — the knob reschedules
    dispatch, never results."""
    base = (
        f"info_file={session['info']}&fe=dwt-8-fused"
        f"&load_clf=logreg&load_name={session['model']}"
    )
    batch = builder.PipelineBuilder(base).execute()
    served = builder.PipelineBuilder(
        base + "&serve=true&serve_flush_us=2000"
    ).execute()
    assert str(served) == str(batch)
