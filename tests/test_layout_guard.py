"""Guards for the fast device layouts and platform-correct formulation.

Round-2 real-chip A/B runs showed the headline featurizer is one
input-layout mistake away from a 5.6x collapse (`einsum_2d.json`: the
same geometry contracted as a flattened (B*C, T) 2-D matmul measured
8.37 M eps vs 46.8 M for the batched rank-3 einsum). These tests pin
the fast shapes structurally:

- the jitted extractor the provider/staging arrays feed must lower to
  ONE rank-3 ``dot_general`` applied directly to the input operand —
  no flattening reshape, no transpose of the epochs tensor before the
  contraction (the exact HLO the 46.8 M eps measurement compiled to);
- ``formulation='auto'`` for the fused regular-ingest path must
  re-resolve per platform (the ADVICE r2 cache bug: an lru_cache
  keyed on the literal 'auto' pinned the first platform's choice),
  picking the lane-tile-aligned ``phase`` form on accelerators and
  ``reshape`` on CPU;
- the block irregular-ingest featurizer's capacity chunking (HBM
  bound for long recordings, ADVICE r2) must be bit-compatible with
  the unchunked body.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.ops import device_ingest, dwt


# -- the 5.6x layout cliff: structural HLO guard ----------------------


def _lowered_text(shape):
    ex = dwt.make_batched_extractor()
    return ex.lower(jax.ShapeDtypeStruct(shape, jnp.float32)).as_text()


@pytest.mark.parametrize(
    "shape",
    [
        (11, 3, 750),  # provider.load() fixture batch (epochs/extractor)
        (1024, 3, 750),  # staging.prefetch_epochs minibatch shape
        (64, 3, 1000),  # streaming window shape (parallel/streaming)
    ],
)
def test_extractor_lowers_to_rank3_dot_on_the_input(shape):
    """The contraction must be a single batched rank-3 dot_general
    taking the input operand DIRECTLY — the formulation that measured
    46.8 M eps — not the flattened 2-D matmul that measured 5.6x
    slower on the same chip."""
    B, C, T = shape
    txt = _lowered_text(shape)

    # exactly the fast contraction: (B, C, T) x (T, K) -> (B, C, K),
    # applied to %arg0 itself (no reshape/transpose in between)
    fast = re.search(
        rf"dot_general %arg0, .*contracting_dims = \[2\] x \[0\].*"
        rf"tensor<{B}x{C}x{T}xf32>, tensor<{T}x16xf32>",
        txt,
    )
    assert fast, f"rank-3 dot_general on the input not found:\n{txt}"

    # the slow formulation's signature: epochs flattened to (B*C, T)
    assert f"tensor<{B * C}x{T}xf32>" not in txt, (
        "extractor lowered through the flattened (B*C, T) layout — "
        "the einsum_2d formulation measured 5.6x slower on chip"
    )

    # nothing may relayout the big operand before the contraction
    assert not re.search(
        rf"transpose .*tensor<{B}x{C}x{T}xf32>", txt
    ), "input operand transposed before the contraction"


def test_wavelet_xla_backend_routes_through_guarded_extractor():
    """WaveletTransform(backend='xla') — the object the pipeline hands
    provider arrays to — uses make_batched_extractor, so the HLO guard
    above covers the production path."""
    from eeg_dataanalysispackage_tpu.features import wavelet

    fe = wavelet.WaveletTransform(8, 512, 175, 16, backend="xla")
    epochs = np.random.RandomState(0).randn(8, 3, 750).astype(np.float32)
    feats = fe.extract_batch(epochs)
    assert feats.shape == (8, 48)
    # the cached jit closure is the guarded extractor’s output
    assert fe._jit_cache is not None


# -- 'auto' formulation: per-platform re-resolution -------------------


class _FakeDevice:
    def __init__(self, platform):
        self.platform = platform


def test_auto_formulation_reresolves_after_platform_switch(monkeypatch):
    """ADVICE r2: lru_cache keyed on the literal 'auto' pinned the
    first platform's resolution. The wrapper resolves BEFORE the
    cache, so the same 'auto' call yields phase on an accelerator and
    reshape on CPU within one process."""
    stride, n = 800, 16

    monkeypatch.setattr(
        jax, "devices", lambda *a, **k: [_FakeDevice("tpu")]
    )
    ing_tpu = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="auto"
    )
    assert ing_tpu.formulation == "phase"

    monkeypatch.setattr(
        jax, "devices", lambda *a, **k: [_FakeDevice("cpu")]
    )
    ing_cpu = device_ingest.make_regular_ingest_featurizer(
        stride, n, formulation="auto"
    )
    assert ing_cpu.formulation == "reshape"
    assert ing_cpu is not ing_tpu

    # concrete names cache-hit as before, independent of platform
    assert (
        device_ingest.make_regular_ingest_featurizer(
            stride, n, formulation="phase"
        )
        is ing_tpu
    )


def test_auto_picks_conv_for_odd_strides_on_accelerator(monkeypatch):
    """Odd strides give phase group size 128 (GB-scale tables): auto
    must fall to conv, not phase."""
    monkeypatch.setattr(
        jax, "devices", lambda *a, **k: [_FakeDevice("tpu")]
    )
    assert device_ingest.resolve_regular_formulation("auto", 801) == "conv"
    assert device_ingest.resolve_regular_formulation("auto", 800) == "phase"


# -- block-ingest capacity chunking -----------------------------------


def _random_case(rng, cap, n_samples=40_000):
    raw = rng.randint(-3000, 3000, size=(3, n_samples)).astype(np.int16)
    res = np.array([0.1, 0.1, 0.1], np.float32)
    positions = np.sort(
        rng.randint(100, n_samples - 900, size=cap)
    ).astype(np.int32)
    mask = rng.rand(cap) < 0.9
    return raw, res, positions, mask


def test_block_chunking_matches_unchunked():
    """lax.map over position chunks (HBM bound for long recordings)
    must reproduce the single-chunk body exactly — including a
    capacity that is NOT a multiple of the chunk size."""
    rng = np.random.RandomState(42)
    cap = 192
    raw, res, positions, mask = _random_case(rng, cap)

    whole = device_ingest.make_block_ingest_featurizer()  # cap << 32768
    chunked = device_ingest.make_block_ingest_featurizer(chunk_epochs=100)
    assert whole is not chunked

    out_whole = np.asarray(whole(raw, res, positions, mask))
    out_chunked = np.asarray(chunked(raw, res, positions, mask))
    assert out_whole.shape == (cap, 48)
    np.testing.assert_allclose(out_whole, out_chunked, rtol=0, atol=1e-6)
    # masked rows stay zero through the chunked path too
    assert np.all(out_chunked[~mask] == 0.0)


def test_block_chunking_exact_multiple():
    rng = np.random.RandomState(7)
    cap = 128
    raw, res, positions, mask = _random_case(rng, cap)
    whole = device_ingest.make_block_ingest_featurizer()
    chunked = device_ingest.make_block_ingest_featurizer(chunk_epochs=64)
    np.testing.assert_allclose(
        np.asarray(whole(raw, res, positions, mask)),
        np.asarray(chunked(raw, res, positions, mask)),
        rtol=0,
        atol=1e-6,
    )
