"""Online inference service (serve/): parity, micro-batching,
admission control, deadlines, watchdog, drain, and chaos (ISSUE 6).

The acceptance bar: served predictions are bit-identical to the batch
pipeline's on the same epochs; under ``serve.request``/``serve.batch``
faults the service sheds or degrades but never wedges — every request
resolves (answer, shed, or deadline-exceeded with evidence) and the
graceful drain completes.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

import _synthetic
from eeg_dataanalysispackage_tpu import obs
from eeg_dataanalysispackage_tpu.io import deadline as deadline_mod
from eeg_dataanalysispackage_tpu.io import provider
from eeg_dataanalysispackage_tpu.models import registry as clf_registry
from eeg_dataanalysispackage_tpu.obs import chaos
from eeg_dataanalysispackage_tpu.pipeline import builder
from eeg_dataanalysispackage_tpu.serve import (
    InferenceService,
    RequestFailedError,
    ServeConfig,
    ServiceClosedError,
    ServiceWedgedError,
    ShedError,
    engine,
)
from eeg_dataanalysispackage_tpu.epochs.extractor import BalanceState

_CONFIG = (
    "&config_num_iterations=20&config_step_size=1.0"
    "&config_mini_batch_fraction=1.0"
)


@pytest.fixture(scope="module")
def session(tmp_path_factory):
    """One synthetic two-file session + a trained, saved logreg model
    + the batch pipeline's own predictions for every kept epoch."""
    tmp = tmp_path_factory.mktemp("serve_session")
    for i, (name, guessed) in enumerate(
        (("synth_00", 2), ("synth_01", 5))
    ):
        _synthetic.write_recording(
            str(tmp), name=name, n_markers=90, guessed=guessed, seed=i
        )
    info = str(tmp / "info.txt")
    with open(info, "w") as f:
        f.write("synth_00.eeg 2\nsynth_01.eeg 5\n")
    model = str(tmp / "model")
    builder.PipelineBuilder(
        f"info_file={info}&fe=dwt-8-fused&train_clf=logreg"
        f"&save_clf=true&save_name={model}{_CONFIG}"
    ).execute()

    odp = provider.OfflineDataProvider([info])
    balance = BalanceState()
    windows, targets, resolutions = [], [], None
    for _rel, guessed, rec in odp.iter_recordings():
        ws, ts, resolutions = engine.windows_from_recording(
            rec, odp.channel_indices_for(rec), guessed,
            pre=odp.pre, post=odp.post, balance=balance,
        )
        windows.extend(ws)
        targets.append(ts)
    features, feat_targets = provider.OfflineDataProvider(
        [info]
    ).load_features_device(wavelet_index=8, backend="xla")
    classifier = clf_registry.create("logreg")
    classifier.load(model)
    return {
        "info": info,
        "model": model,
        "windows": windows,
        "targets": np.concatenate(targets),
        "resolutions": resolutions,
        "batch_features": features,
        "batch_predictions": classifier.predict(features),
    }


def _service(session, **config_kwargs) -> InferenceService:
    return InferenceService.from_saved(
        "logreg", session["model"],
        config=ServeConfig(**config_kwargs) if config_kwargs else None,
    )


_WINDOW = np.zeros((3, 850), np.int16)
_RES = np.ones(3, np.float32)


# -- the parity contract -------------------------------------------------


def test_served_predictions_bit_identical_to_batch(session):
    """The acceptance pin: every epoch served through the online path
    predicts exactly what the batch fused pipeline predicts for it."""
    with _service(session) as svc:
        results = svc.predict_all(
            session["windows"], session["resolutions"]
        )
    served = np.array([r.prediction for r in results])
    np.testing.assert_array_equal(served, session["batch_predictions"])
    # the window extraction targeted the same epochs the batch path
    # featurized (same count, same balance decisions)
    assert len(session["windows"]) == len(session["batch_features"])


def test_serve_pipeline_statistics_identical_to_load_clf(
    session, tmp_path
):
    """serve=true produces byte-identical ClassificationStatistics to
    the batch load_clf= run on the same inputs, and its run report
    carries the serve block."""
    base = (
        f"info_file={session['info']}&fe=dwt-8-fused"
        f"&load_clf=logreg&load_name={session['model']}"
    )
    batch = builder.PipelineBuilder(base).execute()
    report_dir = str(tmp_path / "report")
    pb = builder.PipelineBuilder(
        base + f"&serve=true&report={report_dir}"
    )
    served = pb.execute()
    assert str(served) == str(batch)
    with open(os.path.join(report_dir, "run_report.json")) as f:
        report = json.load(f)
    block = report["serve"]
    assert block["requests"]["completed"] == len(session["windows"])
    assert block["requests"]["shed"] == 0
    assert block["drained_cleanly"] is True
    assert block["latency_ms"]["p50"] > 0.0
    assert block["latency_ms"]["p99"] >= block["latency_ms"]["p50"]
    # per-request spans + batch spans landed in the span summary
    by_name = report["spans"]["by_name"]
    assert by_name["serve.request"]["count"] == len(session["windows"])
    assert by_name["serve.batch"]["count"] >= 1
    # and the serve stage is in the timings
    assert report["stages"]["serve"]["seconds"] > 0.0


def test_serve_pipeline_conflicts(session):
    q = f"info_file={session['info']}&fe=dwt-8-fused&serve=true"
    with pytest.raises(ValueError, match="cannot combine"):
        builder.PipelineBuilder(q + "&train_clf=logreg").execute()
    with pytest.raises(ValueError, match="cannot combine"):
        builder.PipelineBuilder(
            q + f"&load_clf=logreg&load_name={session['model']}"
            "&elastic=true"
        ).execute()
    with pytest.raises(ValueError, match="requires load_clf"):
        builder.PipelineBuilder(q).execute()
    with pytest.raises(ValueError, match="dwt-<i>-fused"):
        builder.PipelineBuilder(
            f"info_file={session['info']}&fe=dwt-8&serve=true"
            f"&load_clf=logreg&load_name={session['model']}"
        ).execute()
    # explicitly-DISABLED knobs are no-ops, not conflicts (review
    # regression: the check judges enabling conditions, not key
    # presence)
    st = builder.PipelineBuilder(
        q + f"&load_clf=logreg&load_name={session['model']}"
        "&elastic=false&save_clf=false&cv=1"
    ).execute()
    assert st.calc_accuracy() >= 0.0


# -- micro-batching ------------------------------------------------------


def test_concurrent_submits_coalesce_into_batches(session):
    """Concurrent requests share compiled-program dispatches: the
    batch counter stays well below the request counter."""
    with _service(session, coalesce_s=0.02) as svc:
        windows = session["windows"]
        futs = [
            svc.submit(
                windows[i % len(windows)], session["resolutions"],
                block_s=5.0,
            )
            for i in range(64)
        ]
        results = [f.result(timeout=30.0) for f in futs]
    block = svc.stats_block()
    assert block["requests"]["completed"] == 64
    assert block["batches"] < 64
    assert any(r.batch_size > 1 for r in results)
    # coalesced results still match the batch path per-window
    for i, r in enumerate(results):
        expected = session["batch_predictions"][i % len(windows)]
        assert r.prediction == expected


def test_single_request_and_full_batch_share_one_program(session):
    """Static capacity: batch sizes 1 and N reuse one executable (no
    retrace under bursty load)."""
    eng = engine.ServingEngine(
        _loaded_classifier(session), capacity=8
    )
    p1, _ = eng.execute([session["windows"][0]], session["resolutions"])
    p8, _ = eng.execute(session["windows"][:8], session["resolutions"])
    assert p1.shape == (1,) and p8.shape == (8,)
    np.testing.assert_array_equal(p8[:1], p1)
    np.testing.assert_array_equal(
        p8, session["batch_predictions"][:8]
    )


def _loaded_classifier(session):
    c = clf_registry.create("logreg")
    c.load(session["model"])
    return c


# -- admission control ---------------------------------------------------


def test_admission_shed_with_evidence(session):
    with _service(
        session, max_batch=2, queue_depth=1, coalesce_s=0.2
    ) as svc:
        before = obs.metrics.snapshot()["counters"].get(
            "serve.shed", 0.0
        )
        shed = 0
        for _ in range(16):
            try:
                svc.submit(_WINDOW, _RES)
            except ShedError as e:
                shed += 1
                assert "queue at depth 1" in str(e)
        assert shed > 0
        after = obs.metrics.snapshot()["counters"]["serve.shed"]
        assert after - before == shed
        assert svc.stats_block()["requests"]["shed"] >= shed


def test_blocking_submit_cooperates_with_backpressure(session):
    """block_s turns shedding into bounded waiting: a cooperative
    producer never sheds while the consumer keeps up."""
    with _service(session, queue_depth=4) as svc:
        futs = [
            svc.submit(
                session["windows"][i % len(session["windows"])],
                session["resolutions"], block_s=10.0,
            )
            for i in range(32)
        ]
        for f in futs:
            f.result(timeout=30.0)
    assert svc.stats_block()["requests"]["shed"] == 0


# -- deadlines -----------------------------------------------------------


def test_deadline_expired_in_queue_fails_fast(session):
    """A request whose budget dies while queued is failed with the
    time it waited, not executed into a useless answer."""
    block = threading.Event()
    svc = _service(session, watchdog_s=30.0)
    real_execute = svc.batcher._execute
    svc.batcher._execute = lambda *a: (block.wait(30), real_execute(*a))[1]
    svc.start()
    try:
        # first request occupies the batcher; the second's 1 ms budget
        # dies in the queue behind it
        f1 = svc.submit(_WINDOW, _RES, deadline_s=60.0)
        f2 = svc.submit(_WINDOW, _RES, deadline_s=0.001)
        time.sleep(0.1)
        block.set()
        f1.result(timeout=30.0)
        with pytest.raises(
            deadline_mod.DeadlineExceededError, match="admission queue"
        ):
            f2.result(timeout=30.0)
        assert svc.stats_block()["requests"]["deadline_exceeded"] == 1
    finally:
        block.set()
        svc.stop(drain=False)


# -- the watchdog --------------------------------------------------------


def test_watchdog_fails_wedged_requests_fast(session):
    """A wedged batcher costs callers watchdog_s, not forever: every
    pending request resolves with evidence and new submissions are
    rejected until restart."""
    wedge = threading.Event()
    svc = _service(session, watchdog_s=0.3, drain_timeout_s=0.5)
    svc.batcher._execute = lambda *a, **k: wedge.wait(60) and None
    svc.start()
    try:
        fut = svc.submit(_WINDOW, _RES)
        with pytest.raises(ServiceWedgedError, match="heartbeat"):
            fut.result(timeout=10.0)
        with pytest.raises(ServiceWedgedError):
            svc.submit(_WINDOW, _RES)
        block = svc.stats_block()
        assert block["watchdog_trips"] == 1
        assert block["wedged"] is True
        # a request that lands in the queue AFTER the trip (a
        # submitter that was blocked in offer at trip time) is still
        # swept and failed — the watchdog keeps resolving, not
        # one-shot (review regression)
        late = batcher_mod_request(svc)
        svc.batcher.queue.readmit(late)
        with pytest.raises(ServiceWedgedError, match="tripped earlier"):
            late.future.result(timeout=5.0)
    finally:
        wedge.set()
        svc.stop(drain=False)


def batcher_mod_request(svc):
    from eeg_dataanalysispackage_tpu.io import deadline as dmod
    from eeg_dataanalysispackage_tpu.serve import batcher as bmod

    return bmod.Request(
        window=_WINDOW, resolutions=_RES, deadline=dmod.Deadline(30.0)
    )


# -- graceful drain ------------------------------------------------------


def test_graceful_drain_completes_in_flight_rejects_new(session):
    svc = _service(session)
    svc.start()
    futs = [
        svc.submit(
            session["windows"][i], session["resolutions"], block_s=5.0
        )
        for i in range(16)
    ]
    drained = svc.stop(drain=True)
    assert drained is True
    # everything admitted before the drain completed with answers
    for i, f in enumerate(futs):
        assert f.result(timeout=1.0).prediction == (
            session["batch_predictions"][i]
        )
    with pytest.raises(ServiceClosedError, match="not accepting"):
        svc.submit(_WINDOW, _RES)
    assert svc.stats_block()["drained_cleanly"] is True


# -- chaos ---------------------------------------------------------------


def test_chaos_serve_faults_retry_to_clean_statistics(session):
    """Deterministic single faults on both serve points are absorbed
    by the retry machinery: statistics identical to the clean run,
    firings and retries visible in metrics."""
    q = (
        f"info_file={session['info']}&fe=dwt-8-fused&serve=true"
        f"&load_clf=logreg&load_name={session['model']}"
    )
    clean = builder.PipelineBuilder(q).execute()
    before = obs.metrics.snapshot()["counters"]
    chaosed = builder.PipelineBuilder(
        q + "&faults=serve.request:once@5;serve.batch:once@2"
    ).execute()
    after = obs.metrics.snapshot()["counters"]
    assert str(chaosed) == str(clean)
    assert after["chaos.fired.serve.request"] - before.get(
        "chaos.fired.serve.request", 0.0
    ) == 1
    assert after["chaos.fired.serve.batch"] - before.get(
        "chaos.fired.serve.batch", 0.0
    ) == 1
    assert after["serve.retries"] > before.get("serve.retries", 0.0)


def test_chaos_exhausted_retries_fail_with_history_not_wedge(session):
    """A point that fires on EVERY attempt exhausts the retry budget:
    the request fails with its attempt history — it never hangs, and
    the service keeps serving afterwards."""
    with _service(session, max_attempts=2) as svc:
        with chaos.faults("serve.request:every@1"):
            fut = svc.submit(session["windows"][0], session["resolutions"])
            with pytest.raises(RequestFailedError, match="attempt 2"):
                fut.result(timeout=10.0)
        # chaos gone: the same service answers again (no wedge, no
        # poisoned state)
        r = svc.predict_window(
            session["windows"][0], session["resolutions"]
        )
        assert r.prediction == session["batch_predictions"][0]
        assert svc.stats_block()["requests"]["failed"] == 1


def test_chaos_soak_every_request_resolves(session):
    """The no-wedge contract under probabilistic faults: every
    submitted request resolves one way or another and the drain
    completes."""
    resolved = failures = 0
    with chaos.faults("serve.request:p=0.2;serve.batch:p=0.2;seed=11"):
        with _service(
            session, max_attempts=4, retry_backoff_s=0.01
        ) as svc:
            futs = []
            for i in range(40):
                try:
                    futs.append(svc.submit(
                        session["windows"][i % len(session["windows"])],
                        session["resolutions"],
                        deadline_s=10.0, block_s=10.0,
                    ))
                except ShedError:
                    resolved += 1
            for f in futs:
                try:
                    f.result(timeout=20.0)
                    resolved += 1
                except (RequestFailedError,
                        deadline_mod.DeadlineExceededError):
                    resolved += 1
                    failures += 1
    assert resolved == 40  # nothing hung, nothing vanished
    assert svc.stats_block()["drained_cleanly"] is True


# -- engine edges --------------------------------------------------------


def test_engine_rejects_bad_shapes(session):
    eng = engine.ServingEngine(_loaded_classifier(session), capacity=4)
    # capacity buckets up to the batch planner's multiple (64): the
    # program shape must match the batch path's for bit-parity
    assert eng.capacity == 64
    with pytest.raises(ValueError, match="shape"):
        eng.execute([np.zeros((3, 10), np.int16)], _RES)
    with pytest.raises(ValueError, match="capacity"):
        eng.execute([_WINDOW] * 65, _RES)
    preds, margins = eng.execute([], _RES)
    assert preds.shape == (0,)


def test_engine_degrades_to_host_floor_on_persistent_failure(session):
    """The serving arm of the degradation ladder: persistent fused-
    program failures step the engine down to the host featurize+
    predict floor — the service keeps answering instead of dying,
    and the step-down is counted and latched."""
    eng = engine.ServingEngine(_loaded_classifier(session))
    calls = {"n": 0}
    real_program = eng._program

    def flaky(*args):
        calls["n"] += 1
        raise RuntimeError("device backend broke mid-residency")

    eng._program = flaky
    before = obs.metrics.snapshot()["counters"].get(
        "serve.degraded_to_host", 0.0
    )
    # failure 1: surfaces (the batcher's retry job)
    with pytest.raises(RuntimeError, match="mid-residency"):
        eng.execute(session["windows"][:4], session["resolutions"])
    # failure 2: crosses the threshold — the engine lands on the host
    # floor and ANSWERS
    preds, margins = eng.execute(
        session["windows"][:4], session["resolutions"]
    )
    assert eng.rung == "host"
    assert margins is None
    assert preds.shape == (4,)
    after = obs.metrics.snapshot()["counters"]["serve.degraded_to_host"]
    assert after - before == 1
    # host-floor predictions agree with the fused path's on this
    # session (tolerance-level features, identical decisions)
    np.testing.assert_array_equal(
        preds, session["batch_predictions"][:4]
    )
    # latched: later batches stay on the floor, no fused re-attempts
    n_calls = calls["n"]
    eng.execute(session["windows"][4:8], session["resolutions"])
    assert calls["n"] == n_calls
    eng._program = real_program


def test_engine_host_fallback_for_non_linear(session, tmp_path):
    """Non-linear classifiers serve through the fused featurizer plus
    their own host predict — same parity contract, different mode."""
    dt = clf_registry.create("dt")
    dt.set_config({"config_max_depth": "3", "config_max_bins": "8",
                   "config_impurity": "gini",
                   "config_min_instances_per_node": "1"})
    feats = session["batch_features"]
    dt.fit(feats, session["targets"])
    eng = engine.ServingEngine(dt, capacity=8)
    assert eng.mode == "featurize+host"
    preds, margins = eng.execute(
        session["windows"][:8], session["resolutions"]
    )
    assert margins is None
    np.testing.assert_array_equal(preds, dt.predict(feats[:8]))


# -- the accuracy-gated bf16 serving path (PR 8) -------------------------


def test_engine_bf16_warmup_gate_passes_and_serves(session):
    """precision=bf16 gates at warmup (synthetic DC-stressed windows
    vs the f32 program) and, inside the documented tolerance, serves
    through the bf16 featurizer with predictions matching the batch
    pipeline's on the fixture epochs."""
    eng = engine.ServingEngine(
        _loaded_classifier(session), capacity=8, precision="bf16"
    )
    eng.warmup()
    rec = eng.precision_record
    assert rec is not None and rec["requested"] == "bf16"
    assert rec["used"] == "bf16" and rec["gate"]["ok"]
    assert rec["gate"]["max_abs_dev"] <= rec["gate"]["tolerance"]
    preds, _ = eng.execute(
        session["windows"][:8], session["resolutions"]
    )
    # integer decisions survive the bf16 feature deviation
    np.testing.assert_array_equal(
        preds, session["batch_predictions"][:8]
    )


def test_engine_bf16_gate_auto_disables(session, monkeypatch):
    """Above the gate the engine swaps to the f32 program BEFORE any
    traffic — served predictions are then the f32 path's exactly, and
    the serve stats block records the decision."""
    monkeypatch.setenv("EEG_TPU_BF16_GATE_TOL", "0")
    svc = InferenceService.from_saved(
        "logreg", session["model"], precision="bf16",
        config=ServeConfig(max_batch=8),
    )
    rec = svc.engine.precision_record
    assert rec["used"] == "f32" and rec["gate"]["ok"] is False
    with svc:
        fut = svc.submit(
            session["windows"][0], session["resolutions"]
        )
        assert fut.result(timeout=5.0).prediction == (
            session["batch_predictions"][0]
        )
    assert svc.stats_block()["precision"]["used"] == "f32"


def test_engine_precision_validation(session):
    with pytest.raises(ValueError, match="precision"):
        engine.ServingEngine(
            _loaded_classifier(session), precision="f16"
        )
