"""The networked plan service (gateway/, ISSUE 11).

The acceptance pins:

- **wire contract** — POST a query string, get a plan id; GET status
  through the queued/running/terminal state machine with attempt
  history; GET the finished statistics + run_report.json; DELETE
  cancels-if-queued; shed-with-evidence is HTTP 429; percent-encoded
  query values round-trip through the decode shim;
- **idempotency** — a submission carrying ``X-Idempotency-Key`` is
  retry-safe: a re-submit while the plan runs REJOINS it (same plan
  id, nothing enqueued), a re-submit after it finished REPLAYS the
  journaled outcome (completed plans exactly-once, failed plans
  return the journaled failure), and a cancel releases the key;
- **crash-only** — a REAL SIGKILL mid-plan: restart the gateway over
  the same journal, recovery resumes the unfinished plan under its
  original id, keyed re-submits return the original ids, the
  completed plan's record is byte-untouched, and the resumed plan's
  statistics are byte-identical to an uninterrupted twin;
- **mixed journal states** — recover() over completed + failed +
  unfinished records re-runs ONLY the unfinished one; keyed
  re-submits of each class return the journaled outcome.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

import _synthetic
from eeg_dataanalysispackage_tpu.gateway import GatewayServer
from eeg_dataanalysispackage_tpu.obs import chaos, domain as run_domain
from eeg_dataanalysispackage_tpu.pipeline import builder
from eeg_dataanalysispackage_tpu.scheduler import (
    PlanCancelledError,
    PlanExecutor,
    dedup as dedup_mod,
)
from eeg_dataanalysispackage_tpu.scheduler import runtime as runtime_mod
from eeg_dataanalysispackage_tpu.scheduler.journal import PlanJournal

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_ambient():
    assert chaos.active_plan() is None
    assert run_domain.current() is None
    dedup_mod.reset()
    yield
    dedup_mod.reset()
    chaos.uninstall()
    assert run_domain.current() is None


@pytest.fixture()
def session(tmp_path):
    return _synthetic.write_session(str(tmp_path), n_markers=60)


def _q(info, extra="", clf="logreg", fe="dwt-8"):
    return (
        f"info_file={info}&fe={fe}&train_clf={clf}"
        "&config_step_size=1.0&config_num_iterations=20"
        "&config_mini_batch_fraction=1.0" + extra
    )


def _request(url, body=None, method="GET", headers=None, timeout=60):
    req = urllib.request.Request(
        url,
        data=body.encode() if body is not None else None,
        method=method, headers=headers or {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _await(base, plan_id, deadline_s=300):
    start = time.monotonic()
    while True:
        _, status = _request(f"{base}/plans/{plan_id}")
        if status.get("state") in ("completed", "failed", "cancelled"):
            return status
        assert time.monotonic() - start < deadline_s, status
        time.sleep(0.05)


def _sha(text):
    import hashlib

    return hashlib.sha256(str(text).encode()).hexdigest()


# -- the wire contract -------------------------------------------------


def test_http_lifecycle_end_to_end(session, tmp_path):
    """POST -> status -> report over real loopback HTTP, statistics
    byte-identical to the direct builder run; the operator surface
    (list/stats/healthz) sees the plan."""
    direct = builder.PipelineBuilder(_q(session)).execute()
    with GatewayServer(
        journal_dir=str(tmp_path / "journal"),
        report_root=str(tmp_path / "reports"),
    ) as gw:
        code, health = _request(f"{gw.url}/healthz")
        assert code == 200 and health["ok"] and health["journal"]

        code, payload = _request(
            f"{gw.url}/plans", body=_q(session), method="POST",
        )
        assert code == 201
        plan_id = payload["plan_id"]
        final = _await(gw.url, plan_id)
        assert final["state"] == "completed"
        assert final["attempts"] == 1
        assert final["query"] == _q(session)

        code, report = _request(f"{gw.url}/plans/{plan_id}/report")
        assert code == 200
        assert report["statistics"] == str(direct)
        assert report["statistics_sha256"] == _sha(direct)
        # the per-plan run_report.json rides the payload
        assert report["run_report"]["plan_id"] == plan_id
        assert report["run_report"]["gateway"]["via"] == "http"

        code, listing = _request(f"{gw.url}/plans")
        assert code == 200
        assert [p["plan_id"] for p in listing["plans"]] == [plan_id]
        code, stats = _request(f"{gw.url}/stats")
        assert code == 200
        assert "dedup" in stats and "scheduler" in stats

        assert _request(f"{gw.url}/plans/nope")[0] == 404
        assert _request(f"{gw.url}/nothing")[0] == 404


def test_invalid_query_is_400_and_never_journaled(session, tmp_path):
    with GatewayServer(journal_dir=str(tmp_path / "journal")) as gw:
        code, payload = _request(
            f"{gw.url}/plans",
            body="fe=dwt-8&train_clf=logreg",  # no input files
            method="POST",
        )
        assert code == 400
        assert "error" in payload
        assert _request(f"{gw.url}/plans")[1]["plans"] == []
        assert _request(f"{gw.url}/plans", body="", method="POST")[0] \
            == 400


def test_percent_encoded_query_roundtrips_over_http(tmp_path):
    """A network-submitted seizure query with %3A/%3D/%2C escapes in
    its fe= value decodes at the front door and runs identically to
    the decoded query submitted in-process."""
    os.makedirs(str(tmp_path / "seiz"))
    info = _synthetic.write_seizure_session(str(tmp_path / "seiz"))
    decoded_fe = "dwt-4:level=3:stats=energy,std"
    encoded_fe = "dwt-4%3Alevel%3D3%3Astats%3Denergy%2Cstd"
    suffix = (
        "&window=512&stride=256&train_clf=logreg"
        "&config_num_iterations=20&config_step_size=1.0"
        "&config_mini_batch_fraction=1.0"
    )
    direct = builder.PipelineBuilder(
        f"info_file={info}&task=seizure&fe={decoded_fe}" + suffix
    ).execute()
    with GatewayServer(journal_dir=str(tmp_path / "journal")) as gw:
        code, payload = _request(
            f"{gw.url}/plans",
            body=f"info_file={info}&task=seizure&fe={encoded_fe}"
            + suffix,
            method="POST",
        )
        assert code == 201
        final = _await(gw.url, payload["plan_id"])
        assert final["state"] == "completed"
        # the journal/IR currency is the DECODED string
        assert f"fe={decoded_fe}" in final["query"]
        _, report = _request(
            f"{gw.url}/plans/{payload['plan_id']}/report"
        )
        assert report["statistics"] == str(direct)


# -- admission, cancel, idempotency (deterministic worker stubs) -------


@pytest.fixture()
def blocked_runtime(monkeypatch):
    """Replace plan execution with an event-gated stub so queue/state
    interleavings are deterministic."""
    release = threading.Event()
    started = threading.Event()

    def blocked_execute(plan, builder_, plan_id=None, fault_plan=None,
                        default_report_dir=None, gateway=None, **kw):
        started.set()
        assert release.wait(60), "test never released the worker"
        return f"done-{plan_id}"

    monkeypatch.setattr(runtime_mod, "execute_plan", blocked_execute)
    yield started, release
    release.set()


def test_shed_is_429_with_evidence(session, tmp_path, blocked_runtime):
    started, release = blocked_runtime
    with GatewayServer(
        journal_dir=str(tmp_path / "journal"),
        max_concurrent=1, queue_depth=1,
    ) as gw:
        _request(f"{gw.url}/plans", body=_q(session), method="POST")
        assert started.wait(30)
        _, queued = _request(
            f"{gw.url}/plans", body=_q(session), method="POST"
        )
        code, payload = _request(
            f"{gw.url}/plans", body=_q(session), method="POST",
            headers={"X-Idempotency-Key": "shed-key"},
        )
        assert code == 429
        assert payload["shed"] and "depth" in payload["error"]
        shed_id = payload["plan_id"]
        # the shed is journaled as terminal failure, with evidence
        _, status = _request(f"{gw.url}/plans/{shed_id}")
        assert status["state"] == "failed"
        release.set()
        # drain the queued plan so the retry below races nothing —
        # its terminal state means the worker popped it and the
        # queue has room again
        _await(gw.url, queued["plan_id"])
        # the key was NOT burned by the shed: a retry runs fresh
        code, retry = _request(
            f"{gw.url}/plans", body=_q(session), method="POST",
            headers={"X-Idempotency-Key": "shed-key"},
        )
        assert code == 201
        assert retry["plan_id"] != shed_id
        _await(gw.url, retry["plan_id"])


def test_idempotent_rejoin_while_running(session, tmp_path,
                                         blocked_runtime):
    started, release = blocked_runtime
    with GatewayServer(
        journal_dir=str(tmp_path / "journal"), max_concurrent=1,
    ) as gw:
        code1, p1 = _request(
            f"{gw.url}/plans", body=_q(session), method="POST",
            headers={"X-Idempotency-Key": "k-live"},
        )
        assert code1 == 201
        assert started.wait(30)
        # same key while running: REJOIN — 200, original id, nothing
        # enqueued
        code2, p2 = _request(
            f"{gw.url}/plans", body=_q(session), method="POST",
            headers={"X-Idempotency-Key": "k-live"},
        )
        assert code2 == 200
        assert p2["plan_id"] == p1["plan_id"]
        assert p2["idempotent_replay"]
        release.set()
        final = _await(gw.url, p1["plan_id"])
        assert final["state"] == "completed"
        # after completion: REPLAY from the journal, still the
        # original id, attempts untouched (nothing re-ran)
        code3, p3 = _request(
            f"{gw.url}/plans", body=_q(session), method="POST",
            headers={"X-Idempotency-Key": "k-live"},
        )
        assert code3 == 200
        assert p3["plan_id"] == p1["plan_id"]
        journal = PlanJournal(str(tmp_path / "journal"))
        assert journal.entry(p1["plan_id"])["state"] == "completed"
        assert len(journal.entries()) == 1


def test_cancel_if_queued(session, tmp_path, blocked_runtime):
    started, release = blocked_runtime
    with GatewayServer(
        journal_dir=str(tmp_path / "journal"),
        max_concurrent=1, queue_depth=4,
    ) as gw:
        _, running = _request(
            f"{gw.url}/plans", body=_q(session), method="POST",
        )
        assert started.wait(30)
        _, queued = _request(
            f"{gw.url}/plans", body=_q(session), method="POST",
            headers={"X-Idempotency-Key": "k-cancel"},
        )
        # held from admission, like a real submitter's handle (the
        # cancelled ticket itself is evicted once journaled)
        handle = gw.executor.handle(queued["plan_id"])
        code, payload = _request(
            f"{gw.url}/plans/{queued['plan_id']}", method="DELETE",
        )
        assert code == 200 and payload["cancelled"]
        _, status = _request(f"{gw.url}/plans/{queued['plan_id']}")
        assert status["state"] == "cancelled"
        # a running plan is NOT torn down
        code, payload = _request(
            f"{gw.url}/plans/{running['plan_id']}", method="DELETE",
        )
        assert code == 409 and not payload["cancelled"]
        # the cancel released the key: a re-submit runs FRESH
        code, fresh = _request(
            f"{gw.url}/plans", body=_q(session), method="POST",
            headers={"X-Idempotency-Key": "k-cancel"},
        )
        assert code == 201
        assert fresh["plan_id"] != queued["plan_id"]
        release.set()
        assert _await(gw.url, running["plan_id"])["state"] == "completed"
        assert _await(gw.url, fresh["plan_id"])["state"] == "completed"
        # the handle held from admission carries the typed error
        with pytest.raises(PlanCancelledError):
            handle.result(timeout=1)


# -- recovery ----------------------------------------------------------


def test_restart_with_mixed_journal_states(session, tmp_path):
    """recover() at startup over completed + failed + unfinished
    records: only the unfinished plan re-runs; an idempotency-keyed
    re-submit of each class returns the journaled outcome."""
    journal_dir = str(tmp_path / "journal")
    q_ok = _q(session)
    q_fail = _q(session, "&faults=scheduler.plan:every@1")
    q_unfinished = _q(session, clf="svm")

    with GatewayServer(
        journal_dir=journal_dir, max_concurrent=1, max_attempts=1,
    ) as gw:
        _, ok = _request(
            f"{gw.url}/plans", body=q_ok, method="POST",
            headers={"X-Idempotency-Key": "k-ok"},
        )
        assert _await(gw.url, ok["plan_id"])["state"] == "completed"
        _, failed = _request(
            f"{gw.url}/plans", body=q_fail, method="POST",
            headers={"X-Idempotency-Key": "k-fail"},
        )
        assert _await(gw.url, failed["plan_id"])["state"] == "failed"
    # a dead process's write-ahead record: submitted, never finished
    PlanJournal(journal_dir).record_submitted(
        "p0099", q_unfinished,
        meta={"idempotency_key": "k-unfinished"},
    )
    ok_record = open(
        os.path.join(journal_dir, f"plan-{ok['plan_id']}.json")
    ).read()
    failed_record = open(
        os.path.join(journal_dir, f"plan-{failed['plan_id']}.json")
    ).read()
    twin = builder.PipelineBuilder(q_unfinished).execute()

    with GatewayServer(journal_dir=journal_dir, max_concurrent=1) as gw:
        # recovery resumed ONLY the unfinished record, original id
        assert [
            h.plan_id for h in gw.recovery["resumed"]
        ] == ["p0099"]
        assert [
            e["plan_id"] for e in gw.recovery["completed"]
        ] == [ok["plan_id"]]
        # keyed re-submit of each class
        code, r_ok = _request(
            f"{gw.url}/plans", body=q_ok, method="POST",
            headers={"X-Idempotency-Key": "k-ok"},
        )
        assert (code, r_ok["plan_id"]) == (200, ok["plan_id"])
        assert r_ok["state"] == "completed"
        code, r_fail = _request(
            f"{gw.url}/plans", body=q_fail, method="POST",
            headers={"X-Idempotency-Key": "k-fail"},
        )
        assert (code, r_fail["plan_id"]) == (200, failed["plan_id"])
        assert r_fail["state"] == "failed"
        _, fail_report = _request(
            f"{gw.url}/plans/{failed['plan_id']}/report"
        )
        assert "chaos" in (fail_report["error"] or "")
        code, r_unf = _request(
            f"{gw.url}/plans", body=q_unfinished, method="POST",
            headers={"X-Idempotency-Key": "k-unfinished"},
        )
        assert (code, r_unf["plan_id"]) == (200, "p0099")
        final = _await(gw.url, "p0099")
        assert final["state"] == "completed"
        _, report = _request(f"{gw.url}/plans/p0099/report")
        assert report["statistics"] == str(twin)

    # terminal records byte-untouched: completed exactly-once, failed
    # never re-run
    assert open(
        os.path.join(journal_dir, f"plan-{ok['plan_id']}.json")
    ).read() == ok_record
    assert open(
        os.path.join(journal_dir, f"plan-{failed['plan_id']}.json")
    ).read() == failed_record


def test_plan_admin_cli_audits_journal_and_gateway(session, tmp_path):
    """tools/plan_admin.py: list renders the journal table (offline
    and against a live gateway), show prints one plan's journaled
    statistics, tail exits after the requested record count."""
    journal_dir = str(tmp_path / "journal")
    admin = os.path.join(_REPO, "tools", "plan_admin.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")

    def run_admin(*args):
        proc = subprocess.run(
            [sys.executable, admin, *args],
            env=env, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        return proc.stdout

    with GatewayServer(journal_dir=journal_dir) as gw:
        _, payload = _request(
            f"{gw.url}/plans", body=_q(session), method="POST",
            headers={"X-Idempotency-Key": "k-admin"},
        )
        plan_id = payload["plan_id"]
        _await(gw.url, plan_id)
        live = run_admin("list", "--gateway", gw.url)
        assert plan_id in live and "completed" in live
    out = run_admin("list", "--journal", journal_dir)
    assert plan_id in out and "completed" in out and "k-admin" in out
    out = run_admin("show", plan_id, "--journal", journal_dir)
    assert "state    completed" in out
    assert "idempotency_key k-admin" in out
    assert "statistics" in out
    out = run_admin(
        "tail", "--journal", journal_dir, "--count", "1",
        "--interval", "0.1",
    )
    assert plan_id in out


_KILL_CHILD = """
import json, os, signal, sys, time, urllib.request

sys.path.insert(0, {repo!r})
from eeg_dataanalysispackage_tpu.gateway import GatewayServer

journal_dir, qa, qb = sys.argv[1:4]
gw = GatewayServer(journal_dir=journal_dir, max_concurrent=1)
gw.start()


def post(body, key):
    req = urllib.request.Request(
        gw.url + "/plans", data=body.encode(), method="POST",
        headers={{"X-Idempotency-Key": key}},
    )
    return json.loads(urllib.request.urlopen(req).read())


pa = post(qa, "key-a")["plan_id"]
while True:
    with urllib.request.urlopen(gw.url + "/plans/" + pa) as r:
        if json.loads(r.read())["state"] == "completed":
            break
    time.sleep(0.05)
post(qb, "key-b")
os.kill(os.getpid(), signal.SIGKILL)
"""


@pytest.mark.chaos
def test_sigkilled_gateway_honors_idempotency_keys(session, tmp_path):
    """The acceptance pin: SIGKILL the gateway mid-plan, restart it
    over the same journal — keyed re-submits return the ORIGINAL plan
    ids, the completed plan is exactly-once (record byte-untouched,
    nothing re-run), and the resumed plan's statistics are
    byte-identical to an uninterrupted twin."""
    journal_dir = str(tmp_path / "journal")
    qa = _q(session)
    # fresh compile at a big static iteration count: the kill lands
    # provably mid-plan (same sizing as the executor's SIGKILL pin)
    qb = (
        f"info_file={session}&fe=dwt-8&train_clf=logreg"
        "&config_step_size=0.5&config_num_iterations=150000"
        "&config_mini_batch_fraction=1.0"
    )
    child = tmp_path / "kill_child.py"
    child.write_text(_KILL_CHILD.format(repo=_REPO))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, str(child), journal_dir, qa, qb],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == -signal.SIGKILL, proc.stderr[-2000:]

    journal = PlanJournal(journal_dir)
    states = {e["plan_id"]: e["state"] for e in journal.entries()}
    assert states == {"p0001": "completed", "p0002": "submitted"}
    completed_before = open(
        os.path.join(journal_dir, "plan-p0001.json")
    ).read()
    twins = {
        q: str(builder.PipelineBuilder(q).execute()) for q in (qa, qb)
    }

    with GatewayServer(journal_dir=journal_dir, max_concurrent=1) as gw:
        assert [h.plan_id for h in gw.recovery["resumed"]] == ["p0002"]
        # retried submits with the clients' keys: original ids back
        code, ra = _request(
            f"{gw.url}/plans", body=qa, method="POST",
            headers={"X-Idempotency-Key": "key-a"},
        )
        assert (code, ra["plan_id"]) == (200, "p0001")
        assert ra["idempotent_replay"]
        code, rb = _request(
            f"{gw.url}/plans", body=qb, method="POST",
            headers={"X-Idempotency-Key": "key-b"},
        )
        assert (code, rb["plan_id"]) == (200, "p0002")
        assert _await(gw.url, "p0002", deadline_s=600)["state"] \
            == "completed"
        _, report_a = _request(f"{gw.url}/plans/p0001/report")
        _, report_b = _request(f"{gw.url}/plans/p0002/report")
    assert report_a["statistics"] == twins[qa]
    assert report_b["statistics"] == twins[qb]
    # exactly-once: the dead gateway's completed record is
    # byte-untouched
    assert open(
        os.path.join(journal_dir, "plan-p0001.json")
    ).read() == completed_before


def test_idempotency_key_reuse_with_different_query_is_409(
        session, tmp_path, blocked_runtime):
    started, release = blocked_runtime
    with GatewayServer(
        journal_dir=str(tmp_path / "journal"), max_concurrent=1,
    ) as gw:
        code, p1 = _request(
            f"{gw.url}/plans", body=_q(session), method="POST",
            headers={"X-Idempotency-Key": "k-conflict"},
        )
        assert code == 201
        assert started.wait(30)
        # live ticket, DIFFERENT body under the same key: conflict,
        # not a silent rejoin to a plan the client did not send
        code, err = _request(
            f"{gw.url}/plans", body=_q(session, clf="svm"),
            method="POST", headers={"X-Idempotency-Key": "k-conflict"},
        )
        assert code == 409
        assert err["idempotency_conflict"]
        release.set()
        _await(gw.url, p1["plan_id"])
        # journaled terminal record, different body: still 409
        code, err = _request(
            f"{gw.url}/plans", body=_q(session, clf="svm"),
            method="POST", headers={"X-Idempotency-Key": "k-conflict"},
        )
        assert code == 409
        assert err["idempotency_conflict"]
        # the ORIGINAL body replays the journaled outcome
        code, p2 = _request(
            f"{gw.url}/plans", body=_q(session), method="POST",
            headers={"X-Idempotency-Key": "k-conflict"},
        )
        assert (code, p2["plan_id"]) == (200, p1["plan_id"])


def test_keyed_resubmit_racing_recover_runs_once(
        session, tmp_path, monkeypatch):
    # a dead process's write-ahead record, key journaled with it
    jdir = str(tmp_path / "journal")
    PlanJournal(jdir).record_submitted(
        "p0001", _q(session), meta={"idempotency_key": "k-race"},
    )
    runs = []
    release = threading.Event()

    def counting_execute(plan, builder_, plan_id=None, fault_plan=None,
                         default_report_dir=None, gateway=None, **kw):
        runs.append(plan_id)
        assert release.wait(60)
        return f"done-{plan_id}"

    monkeypatch.setattr(runtime_mod, "execute_plan", counting_execute)
    with PlanExecutor(max_concurrent=1, journal_dir=jdir) as ex:
        # the client's retry lands BEFORE the operator's recover():
        # re-admitted under the ORIGINAL id
        h1 = ex.submit(_q(session), idempotency_key="k-race")
        assert h1.plan_id == "p0001"
        # recover() must NOT re-admit the same record a second time
        recovery = ex.recover()
        assert [h.plan_id for h in recovery["resumed"]] == ["p0001"]
        assert recovery["resumed"][0].replayed
        release.set()
        assert h1.result(60).plan_id == "p0001"
        recovery["resumed"][0].result(60)
    assert runs == ["p0001"]  # one ticket, one execution


def test_completed_tickets_evicted_once_journaled(session, tmp_path):
    with PlanExecutor(
        max_concurrent=1, journal_dir=str(tmp_path / "journal"),
    ) as ex:
        h = ex.submit(_q(session), idempotency_key="k-evict")
        stats = str(h.result(300).statistics)
        # eviction happens just after the future resolves
        deadline = time.monotonic() + 10
        while h.plan_id in ex.live_ids():
            assert time.monotonic() < deadline
            time.sleep(0.01)
        # the journal still serves status and the keyed replay —
        # nothing re-executes, the outcome is byte-identical
        assert ex.status(h.plan_id)["state"] == "completed"
        h2 = ex.submit(_q(session), idempotency_key="k-evict")
        assert h2.replayed and h2.plan_id == h.plan_id
        assert str(h2.result(10).statistics) == stats
