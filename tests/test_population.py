"""Population training engine (models/population.py + the vmapped
programs in parallel/population.py + pipeline wiring).

The ISSUE-5 contracts:

- ``cv=1&seeds=1`` (no sweep) is statistics-identical to the plain
  ``train_clf=`` split — the population engine is a strict
  generalization, not a new code path with new numerics;
- every member of a single-fold population is statistics-identical to
  the sequential ``train_clf=`` run with that member's
  hyperparameters (per-member bit-parity vs sequential runs);
- the vmapped engine and its looped twin produce byte-identical
  per-member statistics for the same member set (multi-fold included);
- sweep axes are DYNAMIC: new grid values retrigger zero compiles;
- a chaos plan and a population coexist (faults= clamps cleanly and
  the run stays deterministic);
- cold cache-enabled runs read each recording file exactly once (the
  PR3-review double-read, eliminated);
- the run report carries the population block and population.member
  spans.

Hermetic throughout (tests/_synthetic.py).
"""

import json
import os

import numpy as np
import pytest

import _synthetic
from eeg_dataanalysispackage_tpu.io import feature_cache, sources
from eeg_dataanalysispackage_tpu.models import population, stats
from eeg_dataanalysispackage_tpu.pipeline import builder
from eeg_dataanalysispackage_tpu.utils import java_compat


def _session(directory, n_files=2, n_markers=50):
    lines = []
    for i in range(n_files):
        name = f"synth_{i:02d}"
        guessed = 2 + i
        _synthetic.write_recording(
            str(directory), name=name, n_markers=n_markers,
            guessed=guessed, seed=i,
        )
        lines.append(f"{name}.eeg {guessed}")
    info = os.path.join(str(directory), "info.txt")
    with open(info, "w") as f:
        f.write("\n".join(lines) + "\n")
    return info


@pytest.fixture(scope="module")
def info(tmp_path_factory):
    return _session(tmp_path_factory.mktemp("pop_session"))


_LINEAR_CONFIG = (
    "config_num_iterations=12&config_step_size=1.0"
    "&config_mini_batch_fraction=1.0&config_reg_param=0.01"
)

_NN_CONFIG = (
    "config_seed=7&config_num_iterations=8&config_learning_rate=0.1"
    "&config_momentum=0.9&config_weight_init=xavier"
    "&config_updater=nesterovs"
    "&config_optimization_algo=stochastic_gradient_descent"
    "&config_pretrain=false&config_backprop=true"
    "&config_loss_function=xent"
    "&config_layer1_layer_type=dense&config_layer1_n_out=6"
    "&config_layer1_drop_out=0.0"
    "&config_layer1_activation_function=relu"
    "&config_layer2_layer_type=output&config_layer2_n_out=2"
    "&config_layer2_drop_out=0.0"
    "&config_layer2_activation_function=softmax"
)


def _q(info, *parts):
    return "&".join([f"info_file={info}", "fe=dwt-8-fused", *parts])


def _run(query):
    return builder.PipelineBuilder(query).execute()


# ------------------------------------------------ parity contracts


def test_cv1_seeds1_statistics_identical_to_plain_split(info):
    plain = _run(_q(info, "train_clf=logreg", _LINEAR_CONFIG))
    pop = _run(
        _q(info, "train_clf=logreg", _LINEAR_CONFIG, "cv=1", "seeds=1",
           "sweep=lr:1.0")
    )
    assert isinstance(pop, stats.PopulationStatistics)
    assert list(pop) == ["f0.s42.lr1"]
    assert str(pop["f0.s42.lr1"]) == str(plain)


def test_members_bit_parity_vs_sequential_train_clf_runs(info):
    """Every single-fold member == the train_clf= run with that
    member's hyperparameters (svm: the one linear classifier whose
    config surface exposes the reg axis)."""
    pop = _run(
        _q(info, "train_clf=svm", _LINEAR_CONFIG,
           "sweep=lr:1.0,0.5;reg:0.0,0.01")
    )
    assert len(pop) == 4
    for lr in (1.0, 0.5):
        for reg in (0.0, 0.01):
            label = f"f0.s42.lr{lr:g}.reg{reg:g}"
            sequential = _run(
                _q(
                    info, "train_clf=svm",
                    "config_num_iterations=12",
                    f"config_step_size={lr}",
                    "config_mini_batch_fraction=1.0",
                    f"config_reg_param={reg}",
                )
            )
            assert str(pop[label]) == str(sequential), label


def test_vmapped_equals_looped_multi_fold(info):
    base = _q(info, "train_clf=logreg", _LINEAR_CONFIG, "cv=3",
              "seeds=2", "sweep=lr:1.0,0.5")
    vm = _run(base)
    lo = _run(base + "&population_mode=looped")
    assert vm.mode == "vmap" and lo.mode == "looped"
    assert list(vm) == list(lo)
    assert len(vm) == 12  # 3 folds x 2 seeds x 2 lr points
    for label in vm:
        assert str(vm[label]) == str(lo[label]), label
    # the rendered report (the result_path artifact) is byte-equal:
    # mode is deliberately absent from the text
    assert str(vm) == str(lo)


def test_vmapped_equals_looped_multi_fold_minibatch(info):
    """mini_batch_fraction < 1 makes the seed axis LIVE (per-member
    Bernoulli sample streams). Both engines must draw the streams
    from the same mask-shaped formulation — a row-gathering looped
    path would draw different masks and silently break parity (the
    review finding this pins)."""
    base = _q(
        info, "train_clf=logreg", "config_num_iterations=12",
        "config_step_size=1.0", "config_mini_batch_fraction=0.5",
        "cv=2", "seeds=2",
    )
    vm = _run(base)
    lo = _run(base + "&population_mode=looped")
    assert list(vm) == list(lo) and len(vm) == 4
    for label in vm:
        assert str(vm[label]) == str(lo[label]), label
    # the live seed axis really produces distinct members per fold
    assert str(vm["f0.s42"]) != str(vm["f0.s43"]) or str(
        vm["f1.s42"]
    ) != str(vm["f1.s43"])


def test_nn_population_vmap_equals_looped(info):
    base = _q(info, "train_clf=nn", _NN_CONFIG, "seeds=2",
              "sweep=lr:0.1,0.05")
    vm = _run(base)
    lo = _run(base + "&population_mode=looped")
    assert vm.mode == "vmap" and lo.mode == "looped"
    assert list(vm) == list(lo)
    assert len(vm) == 4
    for label in vm:
        assert str(vm[label]) == str(lo[label]), label


def test_nn_multi_fold_falls_back_to_looped(info):
    pop = _run(_q(info, "train_clf=nn", _NN_CONFIG, "cv=2"))
    assert pop.mode == "looped"  # vmap requested, fallback recorded
    assert len(pop) == 2


# ------------------------------------------------ fold semantics


def test_kfold_partitions_every_row_once():
    spec = population.PopulationSpec(cv=4)
    folds = population.folds_for(spec, 103)
    seen = np.concatenate([test for _, test in folds])
    assert sorted(seen.tolist()) == list(range(103))
    for train, test in folds:
        assert len(np.intersect1d(train, test)) == 0
        assert len(train) + len(test) == 103


def test_mc_fold0_is_the_plain_split():
    spec = population.PopulationSpec(cv=3, cv_mode="mc")
    folds = population.folds_for(spec, 40)
    train, test = java_compat.train_test_split_indices(40, seed=1)
    assert folds[0][0].tolist() == train
    assert folds[0][1].tolist() == test
    assert len(folds) == 3


def test_cv_larger_than_rows_is_an_error():
    with pytest.raises(ValueError, match="exceeds"):
        population.folds_for(population.PopulationSpec(cv=9), 5)


# ------------------------------------------------ compile behavior


def test_sweep_values_do_not_retrigger_compiles():
    """The grid axes are dynamic member-axis inputs: after one
    vmapped run, a second run with DIFFERENT lr/reg values (same
    cardinality) must compile nothing new."""
    from eeg_dataanalysispackage_tpu.models import linear
    from eeg_dataanalysispackage_tpu.obs.report import CompilationMonitor

    rng = np.random.RandomState(0)
    features = rng.randn(90, 48).astype(np.float32)
    targets = (rng.rand(90) > 0.5).astype(np.float64)

    def run(lr_a, lr_b, reg):
        spec = population.PopulationSpec(
            cv=2, seeds=2,
            sweep=(("lr", (lr_a, lr_b)), ("reg", (reg,))),
        )
        result, block = population.run_population(
            "logreg", linear.LogisticRegressionClassifier, {},
            features, targets, spec,
        )
        return result, block

    run(1.0, 0.5, 0.0)  # warms the member-shape programs
    with CompilationMonitor() as monitor:
        result, block = run(0.9, 0.25, 0.015)
    snap = monitor.snapshot()
    if snap["available"]:
        assert snap["compilations"] == 0, snap
    assert len(result) == 8
    assert block["members"] == 8 and block["mode"] == "vmap"


# ------------------------------------------------ chaos coexistence


def test_population_coexists_with_chaos_plan(info):
    """A fault plan (which clamps the ingest pool for deterministic
    replay) plus a population run: the degradation ladder absorbs the
    injected fused failure and the member statistics stay
    deterministic across identical runs."""
    from eeg_dataanalysispackage_tpu import obs

    q = _q(
        info, "train_clf=logreg", _LINEAR_CONFIG, "cv=2", "seeds=2",
        "faults=ingest.fused:once@1", "cache=false",
    )
    before = obs.metrics.snapshot()["counters"].get(
        "pipeline.degraded", 0.0
    )
    a = _run(q)
    after = obs.metrics.snapshot()["counters"].get(
        "pipeline.degraded", 0.0
    )
    assert after > before  # the injected failure really degraded a rung
    b = _run(q)
    assert str(a) == str(b)
    assert len(a) == 4


# ------------------------------------------------ pipeline wiring


def test_population_rejects_conflicts(info):
    for extra, match in (
        (("train_clf=logreg", "cv=2", "elastic=true",
          "checkpoint_path=/tmp/x"), "elastic"),
        (("train_clf=logreg", "cv=2", "save_clf=true",
          "save_name=/tmp/x"), "save_clf"),
        (("load_clf=logreg", "load_name=/tmp/x", "cv=2"), "load_clf"),
        (("train_clf=dt", "cv=2"), "SGD family"),
    ):
        with pytest.raises(ValueError, match=match):
            _run(_q(info, _LINEAR_CONFIG, *extra))


def test_population_param_validation(info):
    for extra, match in (
        (("sweep=momentum:0.9",), "sweep= axis"),
        (("sweep=lr:0.1;lr:0.2",), "twice"),
        (("sweep=lr:abc",), "non-numeric"),
        (("sweep=lr:0.5,0.5",), "repeats"),
        (("cv_mode=bogus", "cv=2"), "cv_mode"),
        (("population_mode=turbo", "cv=2"), "population_mode"),
        (("cv=0",), "cv="),
    ):
        with pytest.raises(ValueError, match=match):
            _run(_q(info, "train_clf=logreg", _LINEAR_CONFIG, *extra))


def test_fanout_routes_sgd_legs_through_population(info, tmp_path):
    report_dir = tmp_path / "report"
    fan = _run(
        _q(info, "classifiers=logreg,dt", _LINEAR_CONFIG, "cv=2",
           "config_max_bins=16", "config_impurity=gini",
           "config_max_depth=4", "config_min_instances_per_node=1",
           f"report={report_dir}")
    )
    assert isinstance(fan["logreg"], stats.PopulationStatistics)
    assert len(fan["logreg"]) == 2
    assert isinstance(fan["dt"], stats.ClassificationStatistics)
    report = json.loads((report_dir / "run_report.json").read_text())
    legs = report["population"]["legs"]
    assert set(legs) == {"logreg"}
    assert legs["logreg"]["members"] == 2


def test_run_report_population_block_and_member_spans(info, tmp_path):
    report_dir = tmp_path / "report"
    pop = _run(
        _q(info, "train_clf=logreg", _LINEAR_CONFIG, "cv=2", "seeds=2",
           f"report={report_dir}")
    )
    report = json.loads((report_dir / "run_report.json").read_text())
    block = report["population"]
    assert block["members"] == 4 == len(pop)
    assert block["mode"] == "vmap"
    assert block["shape"]["folds"] == 2
    assert len(block["accuracy"]) == 4
    assert block["summary"]["best"] in block["accuracy"]
    by_name = report["spans"]["by_name"]
    assert by_name["population.member"]["count"] == 4
    assert by_name["population.logreg"]["count"] == 1


def test_population_result_path_text(info, tmp_path):
    result_path = tmp_path / "out.txt"
    pop = _run(
        _q(info, "train_clf=logreg", _LINEAR_CONFIG, "cv=2",
           f"result_path={result_path}")
    )
    text = result_path.read_text()
    assert text == str(pop) + "\n"
    assert text.startswith("population: 2 members")
    assert "best member:" in text and "member: f1.s42" in text


# ------------------------------------------------ single-read contract


class _CountingFS(sources.LocalFileSystem):
    def __init__(self):
        self.reads = {}

    def _note(self, path):
        self.reads[path] = self.reads.get(path, 0) + 1

    def read_bytes(self, path):
        self._note(path)
        return super().read_bytes(path)

    def read_text(self, path):
        self._note(path)
        return super().read_text(path)


def test_cold_cache_run_reads_each_file_exactly_once(
    tmp_path, monkeypatch
):
    """The acceptance criterion: digest + parse share one physical
    read per file on a cold cache-enabled run (and the warm run's
    digest pass reads once too), with bit-identical statistics."""
    from eeg_dataanalysispackage_tpu import obs

    monkeypatch.delenv(feature_cache.ENV_DISABLE, raising=False)
    monkeypatch.setenv(feature_cache.ENV_DIR, str(tmp_path / "fcache"))
    feature_cache.reset_stats()
    info = _session(tmp_path, n_files=2, n_markers=30)
    q = _q(info, "train_clf=logreg", _LINEAR_CONFIG)

    fs = _CountingFS()
    before = obs.metrics.snapshot()["counters"].get(
        "ingest.file_reads", 0.0
    )
    cold = builder.PipelineBuilder(q, filesystem=fs).execute()
    multi = {p: c for p, c in fs.reads.items() if c != 1}
    assert not multi, f"files read more than once on a cold run: {multi}"
    # 2 recordings x (vhdr, vmrk, eeg) + info.txt
    assert len(fs.reads) == 7
    after = obs.metrics.snapshot()["counters"].get(
        "ingest.file_reads", 0.0
    )
    assert after - before == 6  # the metric counts triplet file reads

    fs_warm = _CountingFS()
    warm = builder.PipelineBuilder(q, filesystem=fs_warm).execute()
    multi = {p: c for p, c in fs_warm.reads.items() if c != 1}
    assert not multi, f"files read more than once on a warm run: {multi}"
    assert feature_cache.stats()["hits"] >= 1
    assert str(cold) == str(warm)
