"""The int4 precision rung + quantized weight stack units
(ops/quant.py, ISSUE 18).

The acceptance bar: int4 feature quantization carries the int8 core's
invariants verbatim (per-row scales, exact-zero rows, determinism) at
qmax 7; the nibble wire format round-trips exactly and equals the
in-graph quantize→dequantize; the masked full-lane quantizer (the
mega kernel's spelling) is numerically identical to the reshape core;
the weight stack packs per-lane and dequantizes bit-exactly back to
its grid; and every gate tolerance honours its env override with the
logged-never-silent fallback.
"""

import json
import math

import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.ops import decode_ingest, quant


# -- the int4 feature rung -----------------------------------------------


def test_int4_quantize_roundtrip_properties():
    """Per-(row, channel, subband) scales at qmax 7, the arithmetic
    error bound, exact zero preservation, and determinism — the int8
    core's invariants transferred to the bottom rung."""
    rng = np.random.RandomState(0)
    rows = rng.randn(32, 48).astype(np.float32)
    rows /= np.linalg.norm(rows, axis=1, keepdims=True)
    rows[5] = 0.0
    dq, scales = quant.quantize_dequantize_int4(rows, 16)
    dq = np.asarray(dq)
    scales = np.asarray(scales)
    n_groups = len(decode_ingest.subband_group_bounds(16))
    assert scales.shape == (n_groups, 32, 3)
    x = rows.reshape(32, 3, 16)
    d = np.abs(dq.reshape(32, 3, 16) - x)
    for gi, (lo, hi) in enumerate(
        decode_ingest.subband_group_bounds(16)
    ):
        bound = scales[gi][:, :, None] / 2 + 1e-7
        assert np.all(d[:, :, lo:hi] <= bound)
    assert np.all(dq[5] == 0.0)
    dq2, _ = quant.quantize_dequantize_int4(rows, 16)
    np.testing.assert_array_equal(dq, np.asarray(dq2))


def test_int4_quantize_is_row_independent():
    """Per-ROW scales: a loud neighbour never stretches another row's
    quantization grid — the batch-invariance contract the cache and
    the serve bucket pins rely on."""
    rng = np.random.RandomState(1)
    rows = rng.randn(8, 48).astype(np.float32)
    rows[3] *= 100.0
    dq_batch, _ = quant.quantize_dequantize_int4(rows, 16)
    dq_batch = np.asarray(dq_batch)
    for i in range(8):
        dq_solo, _ = quant.quantize_dequantize_int4(rows[i:i + 1], 16)
        np.testing.assert_array_equal(
            np.asarray(dq_solo)[0], dq_batch[i]
        )


def test_int4_pack_unpack_roundtrip_exact():
    rng = np.random.RandomState(2)
    q = rng.randint(-7, 8, size=(5, 48)).astype(np.int32)
    packed = quant.pack_int4_rows(q)
    assert packed.dtype == np.uint8 and packed.shape == (5, 24)
    # +8 storage: every wire byte's nibbles sit in [1, 15] — a zero
    # byte is provably corruption, never data
    assert (packed & 0xF).min() >= 1 and (packed >> 4).min() >= 1
    np.testing.assert_array_equal(quant.unpack_int4_rows(packed), q)


def test_int4_pack_rejects_bad_input():
    with pytest.raises(ValueError, match="even"):
        quant.pack_int4_rows(np.zeros((2, 3), np.int32))
    with pytest.raises(ValueError, match="out of"):
        quant.pack_int4_rows(np.full((1, 2), 8, np.int32))
    with pytest.raises(ValueError, match="out of"):
        quant.pack_int4_rows(np.full((1, 2), -8, np.int32))


def test_int4_packed_wire_equals_in_graph():
    """The host wire format (quantize_int4_packed →
    dequantize_int4_packed) reproduces the in-graph round trip
    byte-for-byte — what a cache stores is exactly what the program
    computes."""
    rng = np.random.RandomState(3)
    rows = rng.randn(16, 48).astype(np.float32)
    rows /= np.linalg.norm(rows, axis=1, keepdims=True)
    in_graph, _ = quant.quantize_dequantize_int4(rows, 16)
    packed, scales = quant.quantize_int4_packed(rows, 16)
    wire = quant.dequantize_int4_packed(packed, scales, 16)
    np.testing.assert_array_equal(np.asarray(in_graph), wire)


def test_masked_quantizer_matches_reshape_core():
    """The mega kernel's full-lane masked spelling
    (subband_lane_masks + masked_quantize_dequantize) is numerically
    identical to the grouped-reshape cores at both qmax values — the
    lane-layout twin the in-kernel rung relies on."""
    rng = np.random.RandomState(4)
    rows = rng.randn(12, 48).astype(np.float32)
    rows /= np.linalg.norm(rows, axis=1, keepdims=True)
    masks = quant.subband_lane_masks(3, 16)
    # disjoint and complete over the (C, K) lane layout
    assert np.array_equal(
        sum(np.asarray(m) for m in masks), np.ones(48, np.float32)
    )
    masked_i8 = np.asarray(
        quant.masked_quantize_dequantize(rows, masks, 127.0)
    )
    core_i8 = np.asarray(
        decode_ingest.quantize_dequantize_int8(rows, 16)[0]
    )
    np.testing.assert_array_equal(masked_i8, core_i8)
    masked_i4 = np.asarray(
        quant.masked_quantize_dequantize(rows, masks, quant.INT4_QMAX)
    )
    core_i4 = np.asarray(quant.quantize_dequantize_int4(rows, 16)[0])
    np.testing.assert_array_equal(masked_i4, core_i4)


def test_int4_gate_tolerance_env(monkeypatch):
    monkeypatch.setenv("EEG_TPU_INT4_GATE_TOL", "0.5")
    assert quant.int4_gate_tolerance() == 0.5
    monkeypatch.setenv("EEG_TPU_INT4_GATE_TOL", "zero")
    assert quant.int4_gate_tolerance() == quant.INT4_GATE_TOL
    monkeypatch.delenv("EEG_TPU_INT4_GATE_TOL", raising=False)
    assert quant.int4_gate_tolerance() == quant.INT4_GATE_TOL
    assert (
        decode_ingest.precision_gate_tolerance("int4")
        == quant.INT4_GATE_TOL
    )


# -- the quantized weight stack ------------------------------------------


def _stack(d=48, lanes=128, seed=7):
    rng = np.random.RandomState(seed)
    w = (rng.randn(d, lanes) * 0.3).astype(np.float32)
    w[:, 5] = 0.0  # an empty lane (a freed tenant slot)
    return w


@pytest.mark.parametrize("precision,qmax", [("int8", 127.0),
                                            ("int4", 7.0)])
def test_weight_stack_quantize_roundtrip(precision, qmax):
    """Per-lane symmetric scales; the dequantized stack sits within
    scale/2 of the master per weight; an empty lane dequantizes to
    exactly zero."""
    w = _stack()
    packed, scales = quant.quantize_weight_stack(w, precision)
    assert scales.shape == (128,) and scales.dtype == np.float32
    np.testing.assert_allclose(
        np.maximum(np.max(np.abs(w), axis=0) / qmax, 1e-30), scales,
        rtol=1e-6,
    )
    dq = np.asarray(
        quant.dequantize_weight_stack(packed, scales, precision, 48)
    )
    assert dq.shape == w.shape
    assert np.all(np.abs(dq - w) <= scales[None, :] / 2 + 1e-7)
    assert np.all(dq[:, 5] == 0.0)


def test_weight_stack_int4_interleave_exact():
    """int4 packing is row-pairwise (2i low nibble, 2i+1 high): the
    dequantized stack lands every weight back on its OWN grid point —
    bit-exact against an independent per-element requantization."""
    w = _stack(seed=8)
    packed, scales = quant.quantize_weight_stack(w, "int4")
    assert packed.shape == (24, 128) and packed.dtype == np.uint8
    q = np.clip(np.rint(w / scales[None, :]), -7, 7)
    dq = np.asarray(
        quant.dequantize_weight_stack(packed, scales, "int4", 48)
    )
    np.testing.assert_array_equal(dq, (q * scales[None, :]).astype(
        np.float32
    ))


def test_weight_stack_int4_rejects_odd_rows():
    with pytest.raises(ValueError, match="even row count"):
        quant.quantize_weight_stack(np.zeros((7, 128), np.float32),
                                    "int4")


def test_weight_stack_scales_are_per_lane():
    """Cross-lane isolation: scaling ONE lane's weights 100x moves
    only that lane's scale and dequantized column — a swap_model on
    tenant A can never move tenant B's margins."""
    w = _stack(seed=9)
    loud = w.copy()
    loud[:, 3] *= 100.0
    _, s_base = quant.quantize_weight_stack(w, "int4")
    p_loud, s_loud = quant.quantize_weight_stack(loud, "int4")
    changed = s_base != s_loud
    assert changed[3] and changed.sum() == 1
    dq_base = np.asarray(
        quant.dequantize_weight_stack(
            *quant.quantize_weight_stack(w, "int4")[:2], "int4", 48
        )
    )
    dq_loud = np.asarray(
        quant.dequantize_weight_stack(p_loud, s_loud, "int4", 48)
    )
    other = np.arange(128) != 3
    np.testing.assert_array_equal(
        dq_base[:, other], dq_loud[:, other]
    )


def test_resident_weight_bytes_reduction():
    """The VMEM-residency arithmetic on the real (48, 128) geometry:
    f32 24576 B; int8 6656 B (3.69x); int4 3584 B (6.86x) — only int4
    clears the 4x bar, which is why the quant bench serves it."""
    w = _stack()
    f32_bytes = w.nbytes
    assert f32_bytes == 24576
    i8 = quant.resident_weight_bytes(
        *quant.quantize_weight_stack(w, "int8")
    )
    i4 = quant.resident_weight_bytes(
        *quant.quantize_weight_stack(w, "int4")
    )
    assert i8 == 48 * 128 + 128 * 4 == 6656
    assert i4 == 24 * 128 + 128 * 4 == 3584
    assert f32_bytes / i8 < 4.0 < f32_bytes / i4


def test_weights_gate_tolerance_envelope_and_env(monkeypatch):
    """The derived envelope (headroom * sqrt(d) * s_max / 2) tracks
    the stack's own magnitude; the env override is ABSOLUTE and 0
    forces the gate shut."""
    w = _stack()
    tol = quant.weights_gate_tolerance("int4", w)
    s_max = np.max(np.abs(w)) / 7.0
    expected = (
        quant.WEIGHTS_GATE_HEADROOM * math.sqrt(48) * s_max / 2.0
    )
    assert tol == pytest.approx(expected, rel=1e-6)
    # smaller weights -> tighter gate, automatically
    assert quant.weights_gate_tolerance("int4", w * 0.01) < tol
    monkeypatch.setenv("EEG_TPU_WEIGHTS_GATE_TOL", "0.25")
    assert quant.weights_gate_tolerance("int4", w) == 0.25
    monkeypatch.setenv("EEG_TPU_WEIGHTS_GATE_TOL", "0")
    assert quant.weights_gate_tolerance("int4", w) == 0.0
    monkeypatch.setenv("EEG_TPU_WEIGHTS_GATE_TOL", "junk")
    assert quant.weights_gate_tolerance("int4", w) == pytest.approx(
        expected, rel=1e-6
    )


# -- the accelerator decision path ---------------------------------------


def _stage_quant_artifact(root, name, platform, qps, fps, tenants=16):
    d = root / name
    d.mkdir(parents=True, exist_ok=True)
    (d / "serve_multitenant_quant.json").write_text(json.dumps({
        "variant": "serve_multitenant_quant",
        "platform": platform,
        "serve": {"multitenant_quant": {
            "tenants": tenants,
            "weights_precision": "int4",
            "quant": {"preds_per_s": qps},
            "f32": {"preds_per_s": fps},
        }},
    }) + "\n")


def test_accelerator_decision_flips_on_chip_evidence(tmp_path):
    _stage_quant_artifact(tmp_path, "r1", "tpu", 980.0, 1000.0)
    d = quant.accelerator_decision(root=str(tmp_path))
    assert d["quantize_stack"] is True
    assert d["ratio"] == pytest.approx(0.98)
    assert d["weights_precision"] == "int4"
    assert d["threshold_ratio"] == quant.WEIGHTS_QUANT_FLIP_RATIO
    assert "r1" in d["source"]


def test_accelerator_decision_holds_below_threshold(tmp_path):
    _stage_quant_artifact(tmp_path, "r1", "tpu", 500.0, 1000.0)
    d = quant.accelerator_decision(root=str(tmp_path))
    assert d["quantize_stack"] is False and d["ratio"] == 0.5


def test_accelerator_decision_ignores_cpu_and_absent(tmp_path):
    # no artifact at all
    d = quant.accelerator_decision(root=str(tmp_path / "empty"))
    assert d["quantize_stack"] is False and d["source"] is None
    # a CPU-fallback artifact is not chip evidence
    _stage_quant_artifact(tmp_path, "r1", "cpu_fallback", 2000.0,
                          1000.0)
    d = quant.accelerator_decision(root=str(tmp_path))
    assert d["quantize_stack"] is False and d["source"] is None
