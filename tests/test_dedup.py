"""Cross-tenant plan-prefix dedup (ISSUE 11).

The acceptance pins:

- **canonicalization** — ``ExecutionPlan.canonical_key`` is
  order-insensitive over the typed fields and blind to every
  non-semantic knob (faults, worker counts, artifact paths);
  ``prefix_key`` names only the ingest+featurize half, so classifier
  suffix changes share it and feature-config changes split it;
- **single-flight value sharing** — two tenants whose plans share a
  canonical prefix compute it ONCE (one feature-cache store, one read
  pass), with per-plan leader/follower attribution in each plan's
  isolated metrics and run report, and BOTH plans' statistics
  byte-identical to their solo unshared runs;
- **isolation under leader failure** — chaos in the leader's fault
  domain abandons the entry; the follower is promoted, computes its
  own prefix, and lands clean-twin statistics (time, never
  correctness);
- **opt-outs** — ``dedup=false`` / ``EEG_TPU_NO_PREFIX_DEDUP=1``
  restore fully independent builds.
"""

import json
import threading
import time

import numpy as np
import pytest

import _synthetic
from eeg_dataanalysispackage_tpu import obs
from eeg_dataanalysispackage_tpu.io import deadline as deadline_mod
from eeg_dataanalysispackage_tpu.obs import chaos, domain as run_domain
from eeg_dataanalysispackage_tpu.pipeline import builder
from eeg_dataanalysispackage_tpu.pipeline.plan import ExecutionPlan
from eeg_dataanalysispackage_tpu.scheduler import PlanExecutor
from eeg_dataanalysispackage_tpu.scheduler import dedup as dedup_mod


@pytest.fixture(autouse=True)
def _fresh_registry():
    """Process-global registry: every test starts and ends empty."""
    dedup_mod.reset()
    assert chaos.active_plan() is None
    assert run_domain.current() is None
    yield
    dedup_mod.reset()
    chaos.uninstall()


@pytest.fixture()
def session(tmp_path):
    return _synthetic.write_session(str(tmp_path), n_markers=60)


def _q(info, extra="", clf="logreg", fe="dwt-8-fused"):
    return (
        f"info_file={info}&fe={fe}&train_clf={clf}"
        "&config_step_size=1.0&config_num_iterations=20"
        "&config_mini_batch_fraction=1.0" + extra
    )


# -- canonicalization --------------------------------------------------


def test_canonical_key_is_order_insensitive(session):
    a = ExecutionPlan.parse(_q(session))
    b = ExecutionPlan.parse(
        f"train_clf=logreg&fe=dwt-8-fused&info_file={session}"
        "&config_num_iterations=20&config_step_size=1.0"
        "&config_mini_batch_fraction=1.0"
    )
    assert a.canonical_key() == b.canonical_key()
    assert a.prefix_key() == b.prefix_key()


def test_canonical_key_ignores_non_semantic_knobs(session):
    base = ExecutionPlan.parse(_q(session))
    for extra in (
        "&ingest_workers=4", "&prefetch=7",
        "&faults=ingest.fused:once@1", "&report=false",
        "&overlap=true",
    ):
        assert ExecutionPlan.parse(
            _q(session, extra)
        ).canonical_key() == base.canonical_key(), extra


def test_canonical_key_splits_on_semantic_knobs(session):
    base = ExecutionPlan.parse(_q(session))
    for extra in (
        "&config_step_size=0.5", "&precision=bf16", "&cache=false",
    ):
        assert ExecutionPlan.parse(
            _q(session, extra)
        ).canonical_key() != base.canonical_key(), extra
    assert ExecutionPlan.parse(
        _q(session, clf="svm")
    ).canonical_key() != base.canonical_key()


def test_prefix_key_shared_across_classifier_suffixes(session):
    a = ExecutionPlan.parse(_q(session, clf="logreg"))
    b = ExecutionPlan.parse(
        _q(session, "&config_reg_param=0.1", clf="svm")
    )
    assert a.canonical_key() != b.canonical_key()
    assert a.prefix_key() == b.prefix_key()


def test_prefix_key_splits_on_featurize_knobs(session, tmp_path):
    base = ExecutionPlan.parse(_q(session))
    for extra, fe in (
        ("", "dwt-8-fused-block"),
        ("&precision=bf16", "dwt-8-fused"),
    ):
        other = ExecutionPlan.parse(_q(session, extra, fe=fe))
        assert other.prefix_key() != base.prefix_key(), (extra, fe)
    import os as _os

    _os.makedirs(str(tmp_path / "other"))
    other_session = _synthetic.write_session(
        str(tmp_path / "other"), n_markers=60
    )
    assert ExecutionPlan.parse(
        _q(other_session)
    ).prefix_key() != base.prefix_key()


def test_serve_plans_have_no_prefix(session):
    plan = ExecutionPlan.parse(
        f"info_file={session}&fe=dwt-8&serve=true&load_clf=logreg"
        "&result_path=/tmp/x"
    )
    assert plan.prefix_key() is None
    assert not dedup_mod.eligible(plan)


def test_host_p300_path_not_deduped(session):
    # fe=dwt-8 (host epoch-batch path) never materializes the fused
    # feature matrix the registry shares
    assert not dedup_mod.eligible(ExecutionPlan.parse(_q(session, fe="dwt-8")))
    assert dedup_mod.eligible(ExecutionPlan.parse(_q(session)))


def test_opt_outs(session, monkeypatch):
    plan = ExecutionPlan.parse(_q(session, "&dedup=false"))
    assert not plan.dedup
    assert not dedup_mod.eligible(plan)
    monkeypatch.setenv(dedup_mod.ENV_DISABLE, "1")
    assert not dedup_mod.eligible(ExecutionPlan.parse(_q(session)))


# -- the registry protocol ---------------------------------------------


def test_leader_follower_value_sharing():
    registry = dedup_mod.PrefixRegistry()
    value = (np.ones((4, 2)), np.zeros(4))
    leader = registry.acquire("k1", "pA")
    assert leader.role == "leader"
    got = {}

    def follow():
        claim = registry.acquire("k1", "pB")
        got["claim"] = claim

    t = threading.Thread(target=follow)
    t.start()
    time.sleep(0.05)  # follower parked on the building entry
    leader.publish(value, meta={"precision_used": "f32"})
    t.join(timeout=10)
    claim = got["claim"]
    assert claim.role == "follower"
    assert claim.leader_plan == "pA"
    assert claim.meta == {"precision_used": "f32"}
    assert claim.bytes_saved == value[0].nbytes + value[1].nbytes
    np.testing.assert_array_equal(claim.value[0], value[0])
    # published arrays are frozen: no tenant can mutate another's
    with pytest.raises(ValueError):
        claim.value[0][0, 0] = 5.0
    stats = registry.stats()
    assert stats["leads"] == 1 and stats["hits"] == 1
    assert stats["hit_ratio"] == 0.5


def test_abandoned_leader_promotes_follower():
    registry = dedup_mod.PrefixRegistry()
    leader = registry.acquire("k1", "pA")
    got = {}

    def follow():
        got["claim"] = registry.acquire("k1", "pB")

    t = threading.Thread(target=follow)
    t.start()
    time.sleep(0.05)
    leader.settle()  # unpublished leader in a finally: abandons
    t.join(timeout=10)
    claim = got["claim"]
    assert claim.role == "leader"
    assert claim.leader_failed
    assert registry.stats()["leader_failures"] == 1


def test_follower_wait_honours_deadline():
    registry = dedup_mod.PrefixRegistry()
    registry.acquire("k1", "pA")  # building, never published
    with deadline_mod.deadline_scope(deadline_mod.Deadline(0.15)):
        with pytest.raises(deadline_mod.DeadlineExceededError):
            registry.acquire("k1", "pB")


def test_ready_entries_are_lru_bounded():
    registry = dedup_mod.PrefixRegistry(capacity=2)
    for i in range(3):
        claim = registry.acquire(f"k{i}", f"p{i}")
        claim.publish((np.zeros(1),))
    stats = registry.stats()
    assert stats["entries"] == 2 and stats["evictions"] == 1
    # k0 (oldest) evicted: a new claim on it leads again
    assert registry.acquire("k0", "pX").role == "leader"
    assert registry.acquire("k1", "pY").role == "follower"


# -- end to end through the executor -----------------------------------


def _sha(statistics):
    import hashlib

    return hashlib.sha256(str(statistics).encode()).hexdigest()


def test_shared_prefix_pair_computes_once(session, tmp_path):
    """The acceptance pin: a shared-prefix pair computes the
    ingest+featurize prefix exactly once (store==1, the follower a
    dedup hit) and BOTH plans' statistics are byte-identical to their
    solo unshared runs."""
    pre_solo = obs.metrics.snapshot()["counters"]
    solo_a = builder.PipelineBuilder(
        _q(session, "&dedup=false")
    ).execute()
    # reads one solo build costs (3 per recording: .eeg/.vhdr/.vmrk)
    reads_per_build = int(
        obs.metrics.snapshot()["counters"].get("ingest.file_reads", 0)
        - pre_solo.get("ingest.file_reads", 0)
    )
    solo_b = builder.PipelineBuilder(
        _q(session, "&config_reg_param=0.1&dedup=false", clf="svm")
    ).execute()

    dedup_mod.reset()
    before = obs.metrics.snapshot()["counters"]
    with PlanExecutor(
        max_concurrent=2, report_root=str(tmp_path / "reports")
    ) as ex:
        h_a = ex.submit(_q(session))
        h_b = ex.submit(
            _q(session, "&config_reg_param=0.1", clf="svm")
        )
        r_a = h_a.result(timeout=300)
        r_b = h_b.result(timeout=300)
    after = obs.metrics.snapshot()["counters"]

    assert _sha(r_a.statistics) == _sha(solo_a)
    assert _sha(r_b.statistics) == _sha(solo_b)
    stats = dedup_mod.stats()
    assert stats["leads"] == 1 and stats["hits"] == 1
    # exactly one read+featurize pass between the two plans: the
    # deduped pair read precisely what ONE solo build reads
    assert reads_per_build > 0
    assert int(
        after.get("ingest.file_reads", 0)
        - before.get("ingest.file_reads", 0)
    ) == reads_per_build

    # per-plan attribution: one leader block, one follower block
    # naming the leader, in the plans' OWN reports
    blocks = {}
    for r in (r_a, r_b):
        report = json.load(open(
            tmp_path / "reports" / r.plan_id / "run_report.json"
        ))
        blocks[r.plan_id] = report["dedup"]
        assert report["dedup"] is not None
    roles = {b["role"] for b in blocks.values()}
    assert roles == {"leader", "follower"}
    follower = next(
        b for b in blocks.values() if b["role"] == "follower"
    )
    leader = next(b for b in blocks.values() if b["role"] == "leader")
    assert follower["leader_plan"] in blocks
    assert blocks[follower["leader_plan"]]["role"] == "leader"
    assert follower["bytes_saved"] > 0
    assert follower["seconds_saved"] >= 0
    assert leader["build_seconds"] > 0
    assert follower["prefix_key"] == leader["prefix_key"]


def test_dedup_false_builds_independently(session):
    dedup_mod.reset()
    with PlanExecutor(max_concurrent=2) as ex:
        h1 = ex.submit(_q(session, "&dedup=false"))
        h2 = ex.submit(_q(session, "&dedup=false"))
        r1 = h1.result(timeout=300)
        r2 = h2.result(timeout=300)
    assert str(r1.statistics) == str(r2.statistics)
    stats = dedup_mod.stats()
    assert stats["leads"] == 0 and stats["hits"] == 0
    assert r1.builder.dedup_resolved is None


def test_leader_failure_promotes_follower_end_to_end(session):
    """Leader failure must cost the follower time, never correctness:
    with the prefix claim held by a doomed leader, a clean plan parks,
    is promoted when the leader abandons, computes its OWN prefix, and
    lands clean-twin statistics — with the promotion recorded in its
    dedup block. Deterministic: the test itself plays the doomed
    leader (holding the claim through the real registry), so the
    interleaving cannot race."""
    solo = builder.PipelineBuilder(_q(session, "&dedup=false")).execute()
    dedup_mod.reset()
    key = ExecutionPlan.parse(_q(session)).prefix_key()
    waits_before = obs.metrics.snapshot()["counters"].get(
        "dedup.wait", 0
    )
    doomed = dedup_mod.registry().acquire(key, "pDOOMED")
    assert doomed.role == "leader"
    with PlanExecutor(max_concurrent=1) as ex:
        h = ex.submit(_q(session))
        # the clean plan must be parked behind the building entry
        # before the leader dies (delta: the counter is cumulative
        # across the process)
        deadline = time.monotonic() + 30
        while (
            obs.metrics.snapshot()["counters"].get("dedup.wait", 0)
            <= waits_before
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        assert not h.done
        doomed.settle()  # the leader's finally: abandon
        r = h.result(timeout=300)
    assert _sha(r.statistics) == _sha(solo)
    stats = dedup_mod.stats()
    assert stats["leads"] == 2  # the doomed claim + the promotion
    assert stats["leader_failures"] == 1
    assert r.builder.dedup_resolved["role"] == "leader"
    assert r.builder.dedup_resolved.get("promoted_after_leader_failure")


def test_leader_chaos_failure_never_corrupts_follower(session):
    """The chaos flavor, end to end through the executor: a
    faults=-killed leader plan (degrade=false, so the fused failure is
    terminal) and a clean plan race for one prefix; whatever the
    interleaving, the clean plan's statistics are byte-identical to
    solo and nothing corrupt was shared (no publish from the failed
    build)."""
    solo = builder.PipelineBuilder(_q(session, "&dedup=false")).execute()
    dedup_mod.reset()
    with PlanExecutor(max_concurrent=2, max_attempts=1) as ex:
        h_leader = ex.submit(
            _q(session, "&faults=ingest.fused:every@1&degrade=false")
        )
        # the chaos plan claims first (else the clean plan could lead
        # and the chaos plan FOLLOW — absorbing its own fault by never
        # reaching the ingest it fires in)
        deadline = time.monotonic() + 30
        while (
            dedup_mod.stats()["leads"] == 0
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        h_follower = ex.submit(_q(session))
        with pytest.raises(Exception):
            h_leader.result(timeout=300)
        r = h_follower.result(timeout=300)
    assert _sha(r.statistics) == _sha(solo)
    # the failed build never published: every lead was a fresh build
    stats = dedup_mod.stats()
    assert stats["hits"] == 0


def test_seizure_prefix_dedup(tmp_path):
    """The seizure workload's sliding+subband prefix dedups the same
    way: two cost points over one session share one featurize pass."""
    import os as _os

    _os.makedirs(str(tmp_path / "seiz"))
    info = _synthetic.write_seizure_session(str(tmp_path / "seiz"))
    q = (
        f"info_file={info}&task=seizure&fe=dwt-4:level=3:stats=energy"
        "&window=512&stride=256&train_clf=logreg"
        "&config_num_iterations=20&config_step_size=1.0"
        "&config_mini_batch_fraction=1.0&cost_fp=1"
    )
    solo_a = builder.PipelineBuilder(
        q + "&cost_fn=1&dedup=false"
    ).execute()
    solo_b = builder.PipelineBuilder(
        q + "&cost_fn=8&dedup=false"
    ).execute()
    dedup_mod.reset()
    with PlanExecutor(max_concurrent=2) as ex:
        h_a = ex.submit(q + "&cost_fn=1")
        h_b = ex.submit(q + "&cost_fn=8")
        r_a = h_a.result(timeout=300)
        r_b = h_b.result(timeout=300)
    assert _sha(r_a.statistics) == _sha(solo_a)
    assert _sha(r_b.statistics) == _sha(solo_b)
    stats = dedup_mod.stats()
    assert stats["leads"] == 1 and stats["hits"] == 1


def test_dedup_sits_above_the_feature_cache(session, tmp_path,
                                            monkeypatch):
    """A follower never reaches the feature cache at all: with the
    cache live, the pair keeps ONE store and the follower records
    neither a cache hit nor a miss in its isolated scope."""
    monkeypatch.delenv("EEG_TPU_NO_FEATURE_CACHE", raising=False)
    monkeypatch.setenv(
        "EEG_TPU_FEATURE_CACHE_DIR", str(tmp_path / "fc")
    )
    dedup_mod.reset()
    before = obs.metrics.snapshot()["counters"]
    with PlanExecutor(max_concurrent=2) as ex:
        h1 = ex.submit(_q(session))
        h2 = ex.submit(
            _q(session, "&config_reg_param=0.1", clf="svm")
        )
        r1 = h1.result(timeout=300)
        r2 = h2.result(timeout=300)
    after = obs.metrics.snapshot()["counters"]
    assert int(
        after.get("feature_cache.store", 0)
        - before.get("feature_cache.store", 0)
    ) == 1
    follower = next(
        r for r in (r1, r2)
        if r.builder.dedup_resolved["role"] == "follower"
    )
    counters = follower.builder.run_metrics.snapshot()["counters"]
    assert counters.get("feature_cache.hit", 0) == 0
    assert counters.get("feature_cache.miss", 0) == 0
    assert counters.get("dedup.hit") == 1


def test_obs_report_renders_and_diffs_dedup_blocks(tmp_path, capsys):
    """tools/obs_report.py surfaces the new blocks: show prints the
    leader/follower attribution and the gateway provenance; diff
    flags a dedup-role difference between two reports."""
    import importlib.util
    import os as _os

    spec = importlib.util.spec_from_file_location(
        "obs_report_tool",
        _os.path.join(
            _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
            "tools", "obs_report.py",
        ),
    )
    obs_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(obs_report)

    base = {
        "schema": "eeg-tpu-run-report/1", "plan_id": "p0001",
        "query": "q",
        "outcome": "ok", "stages": {}, "metrics": {},
        "statistics_sha256": "s",
    }
    leader = dict(base, dedup={
        "role": "leader", "prefix_key": "abc123", "rows": 60,
        "build_seconds": 0.5,
    }, gateway={"via": "http", "idempotency_key": "k1",
                "client": "127.0.0.1"})
    follower = dict(base, plan_id="p0002", dedup={
        "role": "follower", "prefix_key": "abc123", "rows": 60,
        "leader_plan": "p0001", "bytes_saved": 9000,
        "seconds_saved": 0.5,
    })
    pa = tmp_path / "a.json"
    pb = tmp_path / "b.json"
    pa.write_text(json.dumps(leader))
    pb.write_text(json.dumps(follower))

    obs_report.show(str(pa))
    out = capsys.readouterr().out
    assert "role=leader" in out and "build_s=0.5" in out
    assert "via=http" in out and "idempotency_key=k1" in out
    obs_report.show(str(pb))
    out = capsys.readouterr().out
    assert "role=follower" in out and "leader=p0001" in out
    assert "bytes_saved=9000" in out
    obs_report.diff(str(pa), str(pb))
    out = capsys.readouterr().out
    assert "dedup" in out
