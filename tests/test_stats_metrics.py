"""The extended ClassificationStatistics metrics (models/stats.py).

Hand-computed confusion-matrix fixtures, degenerate cases, and the
byte-stability pin for the P300 report surface: an extended-metrics
refactor that perturbs one byte of the reference-format ``__str__``
breaks report parity for every existing query string.
"""

import math

import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.models import stats


def make(tp, tn, fp, fn):
    return stats.ClassificationStatistics(tp=tp, tn=tn, fp=fp, fn=fn)


# ------------------------------------------------ hand-computed fixtures


def test_hand_computed_confusion_matrix():
    # tp=6, tn=80, fp=4, fn=10 -> worked by hand
    s = make(6, 80, 4, 10)
    assert s.num_patterns == 100
    assert s.calc_accuracy() == pytest.approx(0.86)
    assert s.precision() == pytest.approx(6 / 10)
    assert s.recall() == pytest.approx(6 / 16)
    assert s.specificity() == pytest.approx(80 / 84)
    p, r = 0.6, 0.375
    assert s.f1() == pytest.approx(2 * p * r / (p + r))
    assert s.balanced_accuracy() == pytest.approx((6 / 16 + 80 / 84) / 2)


def test_expected_cost_hand_computed():
    s = make(6, 80, 4, 10)
    # unit costs: (4 + 10) / 100
    assert s.expected_cost() == pytest.approx(0.14)
    # asymmetric: fp=1, fn=8 -> (4*1 + 10*8) / 100
    assert s.expected_cost(1.0, 8.0) == pytest.approx(0.84)
    # configured costs are the defaults
    s.cost_fp, s.cost_fn = 2.0, 5.0
    assert s.expected_cost() == pytest.approx((4 * 2 + 10 * 5) / 100)


def test_from_arrays_extended_metrics_match_incremental():
    rng = np.random.RandomState(3)
    real = (rng.rand(200) > 0.6).astype(np.float64)
    exp = (rng.rand(200) > 0.8).astype(np.float64)
    batched = stats.ClassificationStatistics.from_arrays(real, exp)
    inc = stats.ClassificationStatistics()
    for r, e in zip(real, exp):
        inc.add(r, e)
    for metric in ("precision", "recall", "f1", "balanced_accuracy"):
        assert getattr(batched, metric)() == getattr(inc, metric)()


# ------------------------------------------------ degenerate cases


def test_no_positives_at_all():
    """No positive patterns and none predicted: recall/precision/F1
    are undefined (NaN, the accuracy convention) — not 0, not 1."""
    s = make(0, 50, 0, 0)
    assert math.isnan(s.precision())
    assert math.isnan(s.recall())
    assert math.isnan(s.f1())
    assert math.isnan(s.balanced_accuracy())
    assert s.specificity() == 1.0
    assert s.expected_cost() == 0.0


def test_all_positives():
    s = make(30, 0, 0, 0)
    assert s.precision() == 1.0
    assert s.recall() == 1.0
    assert s.f1() == 1.0
    assert math.isnan(s.specificity())
    assert math.isnan(s.balanced_accuracy())
    assert s.expected_cost(3.0, 7.0) == 0.0


def test_all_missed_positives():
    s = make(0, 0, 0, 10)
    assert s.recall() == 0.0
    assert math.isnan(s.precision())  # predicted none positive
    assert math.isnan(s.f1())  # p + r undefined
    assert s.expected_cost(1.0, 8.0) == pytest.approx(8.0)


def test_empty_statistics():
    s = make(0, 0, 0, 0)
    assert math.isnan(s.calc_accuracy())
    assert math.isnan(s.expected_cost())
    assert math.isnan(s.precision())


# ------------------------------------------------ report byte-stability


#: the EXACT reference-format report for tp=2 tn=3 fp=1 fn=1 with
#: incremental sums — byte-pinned: the P300 surface must not move
_P300_REPORT = (
    "Number of patterns: 7\n"
    "True positives: 2\n"
    "True negatives: 3\n"
    "False positives: 1\n"
    "False negatives: 1\n"
    "Accuracy: 71.42857142857143%\n"
    "MSE: 0.2857142857142857\n"
    "Non-targets: 1.0\n"
    "Targets: 2.0\n"
)


def test_p300_report_text_is_byte_unchanged():
    """The default (non-extended) ``__str__`` must render the exact
    reference format — no extended lines, no reordering, no
    whitespace drift. This is the string every existing P300
    ``result_path`` report and report_sha256 pin is built from."""
    real = np.array([1.0, 1.0, 0.0, 0.0, 0.0, 1.0, 0.0])
    exp = np.array([1.0, 1.0, 0.0, 0.0, 0.0, 0.0, 1.0])
    s = stats.ClassificationStatistics.from_arrays(real, exp)
    assert str(s) == _P300_REPORT
    assert s.extended_report is False


def test_extended_report_appends_only():
    """The extended block strictly APPENDS to the reference format:
    the leading reference-format lines stay byte-identical."""
    real = np.array([1.0, 1.0, 0.0, 0.0, 0.0, 1.0, 0.0])
    exp = np.array([1.0, 1.0, 0.0, 0.0, 0.0, 0.0, 1.0])
    s = stats.ClassificationStatistics.from_arrays(real, exp)
    stats.mark_extended(s, cost_fp=1.0, cost_fn=8.0)
    text = str(s)
    assert text.startswith(_P300_REPORT)
    assert "Precision: " in text
    assert "Recall: " in text
    assert "Expected cost (fp=1.0, fn=8.0): " in text


def test_mark_extended_recurses_containers():
    fan = stats.FanOutStatistics()
    fan["logreg"] = make(1, 2, 3, 4)
    pop = stats.PopulationStatistics()
    pop["f0.s1"] = make(4, 3, 2, 1)
    fan_and_pop = stats.FanOutStatistics()
    fan_and_pop["svm"] = pop
    stats.mark_extended(fan, cost_fp=2.0, cost_fn=3.0)
    stats.mark_extended(fan_and_pop, cost_fp=2.0, cost_fn=3.0)
    assert fan["logreg"].extended_report
    assert fan["logreg"].cost_fn == 3.0
    assert fan_and_pop["svm"]["f0.s1"].extended_report
    assert "Precision: " in str(fan_and_pop)


def test_extended_summary_block():
    s = make(6, 80, 4, 10)
    stats.mark_extended(s, cost_fp=1.0, cost_fn=8.0)
    block = s.extended_summary()
    assert block["expected_cost"] == pytest.approx(0.84)
    assert block["recall"] == pytest.approx(0.375)
    assert block["cost_fn"] == 8.0
