"""Driver-contract hardening in bench.py (no device work here).

Pins the round-5 resilience pieces: the chip-evidence harvester that
embeds dated silicon records in every bench line (VERDICT r4
weakness 1 — three rounds of cpu_fallback BENCH artifacts while real
chip numbers sat in sweep_results), its timestamp provenance rules
(self-stamped payloads beat git-rewritten file mtimes), and the
advisory collection lock that keeps a driver-launched bench from
racing a staged chip collection for the tunnel (concurrent tunnel
use is the documented wedge class — tools/tunnel_watch.sh).

Also pins the ISSUE-1 attribution contract: the parent exports one
persistent compile-cache dir to every child (utils/compile_cache,
jax-free in the parent), and each variant payload carries
``plan_cache`` hit/miss counters and the active ``compile_cache``
directory, so a BENCH-trajectory speedup is attributable to warm
plans/compiles vs kernel changes. The variant-payload test is the
one test here that runs real (CPU) device work — a tiny
``block_ingest`` measurement through tools/ingest_bench.run."""

import importlib.util
import json
import os
import time

import pytest

_spec = importlib.util.spec_from_file_location(
    "bench",
    os.path.join(os.path.dirname(os.path.dirname(__file__)), "bench.py"),
)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


@pytest.fixture
def sweep_root(tmp_path, monkeypatch):
    """A fake repo root with a sweep_results tree; bench reads
    everything relative to _REPO_ROOT."""
    root = tmp_path
    (root / "tools" / "sweep_results").mkdir(parents=True)
    monkeypatch.setattr(bench, "_REPO_ROOT", str(root))
    return root / "tools" / "sweep_results"


def _write(p, payload):
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(json.dumps(payload) + "\n")


def test_chip_evidence_empty_without_artifacts(sweep_root):
    assert bench._chip_evidence() == {}


def test_chip_evidence_prefers_payload_timestamp(sweep_root):
    """A self-stamped artifact wins over a later-mtime unstamped one:
    git checkouts rewrite mtimes, payload stamps survive."""
    _write(
        sweep_root / "r4" / "bench_full.json",
        {"value": 1.0, "unit": "epochs/s", "variants": {}},
    )
    # the payload stamp is OLDER than the unstamped file's mtime —
    # exactly the fresh-clone case (checkout rewrote the r4 mtime to
    # "now"); the self-stamped record must still win outright
    _write(
        sweep_root / "r5" / "bench_early.json",
        {
            "value": 2.0,
            "unit": "epochs/s",
            "variants": {"einsum": {"epochs_per_s": 2.0}},
            "recorded_utc": "2020-01-01T00:00:00Z",
        },
    )
    late = time.time() + 60
    os.utime(sweep_root / "r4" / "bench_full.json", (late, late))
    ev = bench._chip_evidence()
    assert ev["bench"]["value"] == 2.0
    assert ev["bench"]["timestamp_source"] == "payload"
    assert ev["bench"]["recorded_utc"] == "2020-01-01T00:00:00Z"
    assert ev["bench"]["variants_epochs_per_s"] == {"einsum": 2.0}


def test_chip_evidence_skips_cpu_fallback_and_empty(sweep_root):
    _write(
        sweep_root / "r4" / "bench_full.json",
        {"value": 3.0, "platform": "cpu_fallback"},
    )
    (sweep_root / "r4" / "bench_other.json").write_text("")
    assert "bench" not in bench._chip_evidence()


def test_chip_evidence_ties_break_deterministically(sweep_root):
    """Equal stamps (post-clone mtimes) resolve by path order — the
    later round directory wins, regardless of glob order."""
    for rnd, v in (("r2", 1.0), ("r4b", 2.0), ("r4", 3.0)):
        _write(sweep_root / rnd / "bench_full.json", {"value": v, "variants": {}})
        t = 1700000000
        os.utime(sweep_root / rnd / "bench_full.json", (t, t))
    assert bench._chip_evidence()["bench"]["value"] == 2.0  # r4b


def test_parity_evidence_requires_tpu_platform(sweep_root):
    _write(sweep_root / "r4" / "parity.json", {"platform": "cpu"})
    assert "parity" not in bench._chip_evidence()
    _write(
        sweep_root / "r4" / "parity.json",
        {"platform": "tpu", "epoch_sum_bit_exact": True},
    )
    assert bench._chip_evidence()["parity"]["epoch_sum_bit_exact"] is True


def test_collection_lock_yields_the_tunnel(sweep_root, monkeypatch):
    monkeypatch.delenv("BENCH_IGNORE_COLLECT_LOCK", raising=False)
    assert not bench._collection_in_progress()
    lock = sweep_root / "r5" / "COLLECTING.lock"
    lock.parent.mkdir(parents=True, exist_ok=True)
    lock.write_text("")
    assert bench._collection_in_progress()
    # the collection's own bench invocations opt out
    monkeypatch.setenv("BENCH_IGNORE_COLLECT_LOCK", "1")
    assert not bench._collection_in_progress()
    # stale locks (crashed collection) do not block forever
    monkeypatch.delenv("BENCH_IGNORE_COLLECT_LOCK", raising=False)
    old = time.time() - 4 * 3600
    os.utime(lock, (old, old))
    assert not bench._collection_in_progress()


def test_collection_script_lock_lifecycle(tmp_path):
    """Sourcing the staged list (with every python invocation
    stubbed) must hold COLLECTING.lock for the duration — refreshed
    by the run() wrapper — and remove it at the end, leaving the
    hygiene MISSING.txt behind. Pins the tunnel mutual-exclusion
    machinery end to end in bash, the way tunnel_watch.sh drives it."""
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = tmp_path
    script = f"""
set -u
cd {repo}
OUT={out}
log() {{ :; }}
run() {{ name=$1; t=$2; shift 2
  [ -f "$OUT/COLLECTING.lock" ] || echo "NOLOCK $name" >> "$OUT/violations"
  echo '{{}}' > "$OUT/$name.json"
}}
source <(sed 's|python |true python |g' tools/collect_chip_runs_r4b.sh)
"""
    r = subprocess.run(
        ["bash", "-c", script], capture_output=True, text=True, timeout=60
    )
    assert r.returncode == 0, r.stderr[-500:]
    assert not (out / "violations").exists(), (
        out / "violations").read_text()
    # lock released at the end; hygiene ledger written
    assert not (out / "COLLECTING.lock").exists()
    assert (out / "MISSING.txt").exists()
    # every staged run produced its artifact (evidence hygiene)
    assert (out / "bench_early.json").exists()
    assert (out / "bench_full.json").exists()


def test_parent_exports_compile_cache_to_children():
    """bench.py never imports jax (resilience contract) but must
    still hand every child one persistent compile-cache dir via the
    environment, so a repeat bench run reads serialized executables
    instead of re-paying the 10-14 min fused-program compiles."""
    assert bench._COMPILE_CACHE_DIR
    env = bench._cpu_env()
    assert env["JAX_COMPILATION_CACHE_DIR"] == bench._COMPILE_CACHE_DIR
    # trivial sub-second CPU compiles are not worth persisting
    assert float(env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]) > 0


def test_compile_cache_resolution_precedence(monkeypatch):
    from eeg_dataanalysispackage_tpu.utils import compile_cache as cc

    monkeypatch.delenv(cc.ENV_DISABLE, raising=False)
    monkeypatch.setenv(cc.ENV_DIR, "/pkg-dir")
    monkeypatch.setenv(cc.ENV_JAX_DIR, "/jax-std-dir")
    assert cc.resolve_cache_dir("/explicit") == "/explicit"
    assert cc.resolve_cache_dir() == "/pkg-dir"
    monkeypatch.delenv(cc.ENV_DIR)
    assert cc.resolve_cache_dir() == "/jax-std-dir"
    monkeypatch.delenv(cc.ENV_JAX_DIR)
    assert cc.resolve_cache_dir()  # per-user scratch default
    # the kill switch beats everything, including an explicit path
    monkeypatch.setenv(cc.ENV_DISABLE, "1")
    assert cc.resolve_cache_dir("/explicit") is None
    assert cc.prime_env("/somewhere") is None


def test_variant_payload_carries_cache_attribution_fields():
    """Every variant JSON line records the host-plan cache counters
    and the compile-cache directory in effect (None = caching off) —
    the fields that let a BENCH trajectory attribute a throughput
    move to warm plans/compiles instead of guessing. block_ingest
    exercises a real planner, so its misses must be nonzero."""
    import importlib.util as iu

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = iu.spec_from_file_location(
        "ingest_bench", os.path.join(repo, "tools", "ingest_bench.py")
    )
    ib = iu.module_from_spec(spec)
    spec.loader.exec_module(ib)

    payload = ib.run("block_ingest", 64, 2)
    assert set(payload["plan_cache"]) == {"hits", "misses"}
    assert payload["plan_cache"]["misses"] >= 1
    assert payload["compile_cache"] is None or isinstance(
        payload["compile_cache"], str
    )

    # schema-stable on variants that never plan, too
    payload2 = ib.run("einsum", 64, 2)
    assert set(payload2["plan_cache"]) == {"hits", "misses"}


def test_collect_propagates_cache_attribution_fields(monkeypatch):
    """The parent's variant whitelist must carry the child's
    plan_cache/compile_cache fields into the published line."""
    monkeypatch.setattr(bench, "_VARIANTS_CPU", {"einsum": (8, 2)})
    monkeypatch.setattr(
        bench,
        "_run_variant",
        lambda name, platform, n, iters: {
            "epochs_per_s": 1.0,
            "bytes_per_epoch": 12000,
            "n": n,
            "plan_cache": {"hits": 3, "misses": 1},
            "compile_cache": "/tmp/cc",
        },
    )
    v = bench._collect("cpu_fallback")["variants"]["einsum"]
    assert v["plan_cache"] == {"hits": 3, "misses": 1}
    assert v["compile_cache"] == "/tmp/cc"


def test_population_variants_in_both_tables():
    """The population pair (ISSUE 5) rides every bench artifact, on
    TPU and on the CPU fallback, through the pipeline_bench child."""
    for table in (bench._VARIANTS_TPU, bench._VARIANTS_CPU):
        assert "population_vmap" in table
        assert "population_looped" in table
        # the pair must measure the SAME synthetic session
        assert table["population_vmap"] == table["population_looped"]


def test_collect_propagates_population_field(monkeypatch):
    """A population line's member table and summary must survive the
    parent's field whitelist into the published artifact — the
    vmapped-vs-looped comparison is only auditable from the artifact
    if both lines carry their stages and population blocks."""
    pop = {
        "members": 16,
        "mode": "vmap",
        "summary": {"best": "f0.s42.lr1", "best_accuracy": 0.5},
    }
    monkeypatch.setattr(
        bench, "_VARIANTS_CPU",
        {"einsum": (8, 2), "population_vmap": (800, 2)},
    )
    monkeypatch.setattr(
        bench,
        "_run_variant",
        lambda name, platform, n, iters: {
            "epochs_per_s": 1.0,
            "bytes_per_epoch": 12000,
            "n": n,
            "wall_s": 1.0,
            "stages": {"train": {"seconds": 0.5, "count": 1}},
            "report_sha256": "abc",
            **({"population": pop} if name.startswith("population") else {}),
        },
    )
    v = bench._collect("cpu_fallback")["variants"]["population_vmap"]
    assert v["population"] == pop
    assert v["stages"]["train"]["seconds"] == 0.5
    assert v["report_sha256"] == "abc"


def test_pipeline_bench_routes_population_variants():
    """bench._run_variant must hand population_* to the pipeline
    child (they time whole query runs), not the kernel bench."""
    import inspect

    src = inspect.getsource(bench._run_variant)
    assert '"pipeline_e2e", "population_"' in src or (
        "population_" in src and "pipeline_bench.py" in src
    )


def test_probe_respects_lock_before_touching_the_tunnel(
    sweep_root, monkeypatch
):
    """_tpu_available must short-circuit on the lock without spawning
    the probe subprocess (the probe itself dials the tunnel)."""
    lock = sweep_root / "r5" / "COLLECTING.lock"
    lock.parent.mkdir(parents=True, exist_ok=True)
    lock.write_text("")
    monkeypatch.delenv("BENCH_IGNORE_COLLECT_LOCK", raising=False)
    monkeypatch.delenv("BENCH_FORCE_CPU", raising=False)

    def boom(*a, **k):  # pragma: no cover - the assertion
        raise AssertionError("probe subprocess launched under lock")

    monkeypatch.setattr(bench.subprocess, "Popen", boom)
    assert bench._tpu_available() is False


def test_serve_variant_in_both_tables():
    """The serving benchmark (ISSUE 6) rides every bench artifact, on
    TPU and on the CPU fallback, through the serve_bench child."""
    for table in (bench._VARIANTS_TPU, bench._VARIANTS_CPU):
        assert "serve_bench" in table


def test_serve_bench_routes_to_serve_child():
    """bench._run_variant must hand serve_* to the serving child (it
    drives the resident service), not the kernel bench."""
    import inspect

    src = inspect.getsource(bench._run_variant)
    assert "serve_" in src and "serve_bench.py" in src


def test_collect_propagates_serve_field(monkeypatch):
    """The serve line's sweep/parity/chaos block must survive the
    parent's field whitelist into the published artifact — the p50/p99
    + predictions/sec acceptance numbers live there."""
    serve_block = {
        "sweep": [{"concurrency": 4, "p50_ms": 1.0, "p99_ms": 2.0,
                   "preds_per_s": 100.0}],
        "parity": {"bit_identical": True},
        "chaos": {"chaos_clean": True},
    }
    monkeypatch.setattr(
        bench, "_VARIANTS_CPU",
        {"einsum": (8, 2), "serve_bench": (400, 2)},
    )
    monkeypatch.setattr(
        bench,
        "_run_variant",
        lambda name, platform, n, iters: {
            "epochs_per_s": 1.0,
            "bytes_per_epoch": 5100,
            "n": n,
            "wall_s": 1.0,
            **({"serve": serve_block} if name == "serve_bench" else {}),
        },
    )
    v = bench._collect("cpu_fallback")["variants"]["serve_bench"]
    assert v["serve"] == serve_block


def test_decode_variant_payload_carries_gather_baseline():
    """The decode_ingest line must carry the bandwidth/transfer
    attribution (bytes_per_s, h2d_bytes) and the same-machine
    gather-baseline ratio block — the fields the irregular-ingest-gap
    claim is audited from."""
    import importlib.util as iu

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = iu.spec_from_file_location(
        "ingest_bench", os.path.join(repo, "tools", "ingest_bench.py")
    )
    ib = iu.module_from_spec(spec)
    spec.loader.exec_module(ib)

    payload = ib.run("decode_ingest", 64, 2)
    assert payload["bytes_per_s"] == pytest.approx(
        payload["epochs_per_s"] * payload["bytes_per_epoch"], rel=1e-3
    )
    assert payload["h2d_bytes"] > 0
    gb = payload["gather_baseline"]
    assert gb["same_machine_eps"] > 0
    # the ratio pair shares one best-of-2 discipline, back-to-back
    assert gb["vs_same_machine"] == pytest.approx(
        gb["decode_eps_best"] / gb["same_machine_eps"], rel=1e-2
    )
    assert gb["chip_r05_eps"] == 54800.0
    assert payload["formulation"] in ("slice", "bank128")
    # the kernel parity spot check gated the number
    assert payload["parity_max_abs_dev"] <= 5e-5


def test_collect_propagates_pr8_attribution_fields(monkeypatch):
    """bytes_per_s / h2d_bytes / gather_baseline / precision /
    overlap / plateau must survive the parent's field whitelist into
    the published artifact."""
    gb = {"same_machine_eps": 30000.0, "vs_same_machine": 9.0}
    monkeypatch.setattr(
        bench, "_VARIANTS_CPU",
        {"einsum": (8, 2), "decode_ingest": (64, 2),
         "pipeline_e2e_bf16": (100, 2)},
    )
    monkeypatch.setattr(
        bench,
        "_run_variant",
        lambda name, platform, n, iters: {
            "epochs_per_s": 1.0,
            "bytes_per_epoch": 4500,
            "n": n,
            "bytes_per_s": 4500.0,
            "h2d_bytes": 123,
            **({"gather_baseline": gb} if name == "decode_ingest"
               else {}),
            **({"precision": {"used": "bf16"}, "overlap": True,
                "plateau": {"vs_pr5_cold": 1.2}}
               if name == "pipeline_e2e_bf16" else {}),
        },
    )
    v = bench._collect("cpu_fallback")["variants"]
    assert v["decode_ingest"]["gather_baseline"] == gb
    assert v["decode_ingest"]["bytes_per_s"] == 4500.0
    assert v["decode_ingest"]["h2d_bytes"] == 123
    assert v["pipeline_e2e_bf16"]["precision"] == {"used": "bf16"}
    assert v["pipeline_e2e_bf16"]["overlap"] is True
    assert v["pipeline_e2e_bf16"]["plateau"] == {"vs_pr5_cold": 1.2}


def test_pr8_variants_in_both_tables_and_routing():
    """decode_ingest rides the kernel child; the overlap/bf16 twins
    ride the pipeline child; all present on TPU and CPU tables."""
    import inspect

    for table in (bench._VARIANTS_TPU, bench._VARIANTS_CPU):
        for name in (
            "decode_ingest", "pipeline_e2e_overlap", "pipeline_e2e_bf16",
        ):
            assert name in table, name
    src = inspect.getsource(bench._run_variant)
    # pipeline_e2e_* prefix routing covers the new twins
    assert '"pipeline_e2e' in src
    # decode_ingest falls through to the kernel bench
    assert "ingest_bench.py" in src


def test_collect_normalizes_the_plateau_block(monkeypatch):
    """The published cold line's plateau block carries the
    machine-normalized comparison (cold/einsum now vs the committed
    pr5 ratio) — raw eps across artifacts measures machine load, not
    the code."""
    monkeypatch.setattr(
        bench, "_VARIANTS_CPU",
        {"einsum": (8, 2), "pipeline_e2e_cold": (100, 2)},
    )
    monkeypatch.setattr(
        bench,
        "_run_variant",
        lambda name, platform, n, iters: {
            "epochs_per_s": 100000.0 if name == "einsum" else 1000.0,
            "bytes_per_epoch": 6000,
            "n": n,
            **({"plateau": {"pr5_cold_eps": 1393.4,
                            "pr5_einsum_eps": 180386.0,
                            "cold_eps": 1000.0,
                            "vs_pr5_cold": 0.718}}
               if name == "pipeline_e2e_cold" else {}),
        },
    )
    plateau = bench._collect("cpu_fallback")["variants"][
        "pipeline_e2e_cold"
    ]["plateau"]
    assert plateau["einsum_eps_now"] == 100000.0
    assert plateau["normalized_ratio"] == 0.01  # 1000/100000
    assert plateau["pr5_normalized_ratio"] == round(
        1393.4 / 180386.0, 5
    )
    assert plateau["beats_pr5_plateau_normalized"] is True


def test_strict_json_sanitizes_non_finite_floats():
    """The PR 12 artifact contract: bare NaN/Infinity tokens (a
    Python json extension, not JSON) must never reach a bench line —
    BENCH_pr8's seizure precision/f1 members choked every strict
    consumer. Non-finite floats serialize as null, round-trip under a
    constant-rejecting parser, and the allow_nan=False backstop
    raises at the writer if one ever slips the sanitizer."""
    from eeg_dataanalysispackage_tpu.utils import strict_json

    payload = {
        "seizure": {
            "members": [
                {"precision": float("nan"), "f1": float("inf"),
                 "recall": 0.5},
            ],
            "tuple": (float("-inf"), 1.0),
        },
        "ok": 1.25,
    }
    clean = strict_json.sanitize(payload)
    assert clean["seizure"]["members"][0]["precision"] is None
    assert clean["seizure"]["members"][0]["f1"] is None
    assert clean["seizure"]["members"][0]["recall"] == 0.5
    assert clean["seizure"]["tuple"] == [None, 1.0]

    def boom(token):  # pragma: no cover - the assertion
        raise AssertionError(f"non-strict token {token!r} in output")

    line = strict_json.dumps(payload)
    parsed = json.loads(line, parse_constant=boom)
    assert parsed["seizure"]["members"][0]["precision"] is None
    assert parsed["ok"] == 1.25
    # ints and strings pass through untouched
    assert strict_json.sanitize({"n": 3, "s": "NaN"}) == {
        "n": 3, "s": "NaN"
    }


def test_artifact_writers_route_through_strict_json():
    """Every artifact-emitting entry point dumps through
    utils/strict_json — the seizure-NaN class cannot regress by a
    writer forgetting to sanitize."""
    import inspect

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert "strict_json" in inspect.getsource(bench.main)
    for tool in ("pipeline_bench.py", "serve_bench.py"):
        with open(os.path.join(repo, "tools", tool)) as f:
            src = f.read()
        assert "strict_json.dumps" in src, tool


def test_serve_mega_and_int8_variants_in_both_tables_and_routing():
    """The megakernel family (PR 12) rides every bench artifact: the
    serve_mega mega-vs-fused sweep through the serve child, the int8
    cold twin through the pipeline child."""
    import inspect

    for table in (bench._VARIANTS_TPU, bench._VARIANTS_CPU):
        assert "serve_mega" in table
        assert "pipeline_e2e_int8" in table
        # the mega family measures the same session as serve_bench —
        # the pair is directly comparable from one artifact
        assert table["serve_mega"] == table["serve_bench"]
        assert table["pipeline_e2e_int8"] == table["pipeline_e2e_bf16"]
    src = inspect.getsource(bench._run_variant)
    assert "serve_" in src and "serve_bench.py" in src
    # serve_mega compiles through Mosaic on chip: slow-compile class
    assert "serve_mega" in bench._VARIANT_TIMEOUTS


def test_collect_propagates_serve_mega_field(monkeypatch):
    """The serve_mega line's mega_vs_fused sweep + parity + int8-gate
    block must survive the parent's field whitelist into the
    published artifact — the mega/fused attribution the acceptance
    criteria read."""
    serve_block = {
        "mega_vs_fused": {
            "sweep": [{"concurrency": 16,
                       "mega": {"preds_per_s": 200.0, "p99_ms": 5.0},
                       "fused": {"preds_per_s": 100.0, "p99_ms": 9.0},
                       "preds_speedup": 2.0}],
            "parity": {"bit_identical": True,
                       "vs_batch_bit_identical": True},
            "bucket_identical": True,
            "mega_rung": "mega",
        },
        "int8_gate": {"requested": "int8", "used": "int8"},
    }
    monkeypatch.setattr(
        bench, "_VARIANTS_CPU",
        {"einsum": (8, 2), "serve_mega": (400, 2)},
    )
    monkeypatch.setattr(
        bench,
        "_run_variant",
        lambda name, platform, n, iters: {
            "epochs_per_s": 1.0,
            "bytes_per_epoch": 5100,
            "n": n,
            "wall_s": 1.0,
            **({"serve": serve_block} if name == "serve_mega" else {}),
        },
    )
    v = bench._collect("cpu_fallback")["variants"]["serve_mega"]
    assert v["serve"] == serve_block


def test_serve_lifecycle_variant_in_both_tables_and_routing():
    """The model lifecycle manager (ISSUE 15) rides every bench
    artifact: the serve_lifecycle swap-under-load sweep + parity pins
    through the serve child, sized like the serve_bench line it
    extends (the pair is directly comparable from one artifact)."""
    import inspect

    for table in (bench._VARIANTS_TPU, bench._VARIANTS_CPU):
        assert "serve_lifecycle" in table
        assert table["serve_lifecycle"] == table["serve_bench"]
    src = inspect.getsource(bench._run_variant)
    assert "serve_" in src and "serve_bench.py" in src


def test_collect_propagates_serve_lifecycle_field(monkeypatch):
    """The serve_lifecycle line's sweep + parity pins + lifecycle
    block must survive the parent's field whitelist into the
    published artifact — the no-swap/promoted-parity and
    swap/rollback/drift attribution the acceptance criteria read."""
    serve_block = {
        "sweep": [{
            "concurrency": 16,
            "steady": {"preds_per_s": 100.0, "p99_ms": 5.0},
            "under_adapt": {"preds_per_s": 90.0, "p99_ms": 6.0},
            "swaps_during": 2,
            "p99_ratio": 1.2,
        }],
        "no_swap_parity": {"bit_identical": True, "swaps": 0},
        "promoted_parity": {"swapped": True, "bit_identical": True},
        "lifecycle": {
            "swaps": 2, "rollbacks": 0, "drift_events": 0,
            "state": "live",
        },
        "chaos": {"chaos_clean": True,
                  "live_untouched_on_failed_swap": True},
    }
    monkeypatch.setattr(
        bench, "_VARIANTS_CPU",
        {"einsum": (8, 2), "serve_lifecycle": (400, 2)},
    )
    monkeypatch.setattr(
        bench,
        "_run_variant",
        lambda name, platform, n, iters: {
            "epochs_per_s": 1.0,
            "bytes_per_epoch": 5100,
            "n": n,
            "wall_s": 1.0,
            **(
                {"serve": serve_block}
                if name == "serve_lifecycle" else {}
            ),
        },
    )
    v = bench._collect("cpu_fallback")["variants"]["serve_lifecycle"]
    assert v["serve"] == serve_block


def test_serve_multitenant_variant_in_both_tables_and_routing():
    """The multiplexed multi-tenant engine (ISSUE 16) rides every
    bench artifact through the serve child, sized like the
    serve_bench line it extends (the pair is directly comparable
    from one artifact) and in the slow-compile timeout class (it
    warms the multi-tenant fused AND mega programs cold)."""
    import inspect

    for table in (bench._VARIANTS_TPU, bench._VARIANTS_CPU):
        assert "serve_multitenant" in table
        assert table["serve_multitenant"] == table["serve_bench"]
    src = inspect.getsource(bench._run_variant)
    assert "serve_" in src and "serve_bench.py" in src
    assert "serve_multitenant" in bench._VARIANT_TIMEOUTS


def test_collect_propagates_serve_multitenant_field(monkeypatch):
    """The serve_multitenant line's levels + parity + compile pins
    must survive the parent's field whitelist into the published
    artifact — the exact block multiplex.accelerator_decision
    harvests from staged chip runs."""
    serve_block = {
        "multitenant": {
            "levels": [{
                "tenants": 16,
                "multiplexed": {"preds_per_s": 5200.0, "p99_ms": 4.0},
                "solo_fleet": {"preds_per_s": 4100.0, "p99_ms": 6.0},
                "ratio": 1.268,
            }],
            "parity": {"bit_identical": True, "mismatches": 0},
            "compiles": {"scaling": 0, "scaling_zero_ok": True},
            "swap": {"compiles": 0, "generation": 1},
            "resident": {"multiplexed_bytes": 24576},
        },
    }
    monkeypatch.setattr(
        bench, "_VARIANTS_CPU",
        {"einsum": (8, 2), "serve_multitenant": (400, 2)},
    )
    monkeypatch.setattr(
        bench,
        "_run_variant",
        lambda name, platform, n, iters: {
            "epochs_per_s": 1.0,
            "bytes_per_epoch": 5100,
            "n": n,
            "wall_s": 1.0,
            **(
                {"serve": serve_block}
                if name == "serve_multitenant" else {}
            ),
        },
    )
    v = bench._collect("cpu_fallback")["variants"]["serve_multitenant"]
    assert v["serve"] == serve_block


def test_int4_and_quant_stack_variants_in_both_tables_and_routing():
    """The int4 rung + quantized weight stack (ISSUE 18) ride every
    bench artifact: the pipeline_e2e_int4 cold twin sized like the
    other precision rungs through the pipeline child, the
    serve_multitenant_quant quant-vs-f32 twin through the serve child
    in the slow-compile class (it warms FOUR programs cold: the quant
    and f32 engines' fused and packed/mega twins)."""
    import inspect

    for table in (bench._VARIANTS_TPU, bench._VARIANTS_CPU):
        assert "pipeline_e2e_int4" in table
        assert "serve_multitenant_quant" in table
        # every precision rung's cold twin is sized identically —
        # the ladder is directly comparable from one artifact
        assert table["pipeline_e2e_int4"] == table["pipeline_e2e_bf16"]
        assert table["pipeline_e2e_int4"] == table["pipeline_e2e_int8"]
    src = inspect.getsource(bench._run_variant)
    assert "pipeline_e2e" in src and "serve_" in src
    assert "serve_multitenant_quant" in bench._VARIANT_TIMEOUTS


def test_collect_propagates_serve_multitenant_quant_field(monkeypatch):
    """The serve_multitenant_quant line's quant-vs-f32 twin + parity +
    residency block must survive the parent's field whitelist into the
    published artifact — the exact block quant.accelerator_decision
    harvests from staged chip runs."""
    serve_block = {
        "multitenant_quant": {
            "tenants": 16,
            "weights_precision": "int4",
            "quant": {"preds_per_s": 5100.0, "p99_ms": 4.2},
            "f32": {"preds_per_s": 5000.0, "p99_ms": 4.0},
            "ratio": 1.02,
            "parity": {"within_tolerance": True,
                       "max_abs_margin_dev": 0.01},
            "resident": {"f32_bytes": 24576, "quant_bytes": 3584,
                         "reduction": 6.857},
            "admin": {"compiles": 0, "compiles_zero_ok": True,
                      "still_quantized": True},
        },
    }
    monkeypatch.setattr(
        bench, "_VARIANTS_CPU",
        {"einsum": (8, 2), "serve_multitenant_quant": (400, 2)},
    )
    monkeypatch.setattr(
        bench,
        "_run_variant",
        lambda name, platform, n, iters: {
            "epochs_per_s": 1.0,
            "bytes_per_epoch": 5100,
            "n": n,
            "wall_s": 1.0,
            **(
                {"serve": serve_block}
                if name == "serve_multitenant_quant" else {}
            ),
        },
    )
    v = bench._collect("cpu_fallback")["variants"][
        "serve_multitenant_quant"
    ]
    assert v["serve"] == serve_block


def test_plan_service_variant_in_both_tables_and_routing():
    """The networked plan service (ISSUE 11) rides every bench
    artifact, sized identically on TPU and the CPU fallback, through
    the pipeline_bench child."""
    import inspect

    for table in (bench._VARIANTS_TPU, bench._VARIANTS_CPU):
        assert "plan_service" in table
        # same synthetic session shape as the executor line it
        # fronts — the pair is directly comparable from one artifact
        assert table["plan_service"] == table["scheduler_multi"]
    src = inspect.getsource(bench._run_variant)
    assert '"plan_service"' in src and "pipeline_bench.py" in src


def test_collect_propagates_plan_service_field(monkeypatch):
    """The plan_service line's dedup-pair / idempotency / soak block
    must survive the parent's field whitelist into the published
    artifact — the exactly-once and common-subplan claims are only
    auditable from the artifact if the block rides the line."""
    block = {
        "pair": {
            "stores": 1,
            "dedup": {"leads": 1, "hits": 1, "hit_ratio": 0.5},
            "statistics_identical_to_solo": True,
            "idempotent_resubmit": {
                "http": 200, "same_plan_id": True, "replayed": True,
            },
        },
        "soak": {
            "submits_per_s": 42.0, "all_resolved": True,
            "statistics_identical": True, "sheds": 0,
        },
    }
    monkeypatch.setattr(
        bench, "_VARIANTS_CPU",
        {"einsum": (8, 2), "plan_service": (2000, 4)},
    )
    monkeypatch.setattr(
        bench,
        "_run_variant",
        lambda name, platform, n, iters: {
            "epochs_per_s": 1.0,
            "bytes_per_epoch": 6000,
            "n": n,
            "wall_s": 1.0,
            "report_sha256": "abc",
            **({"plan_service": block}
               if name == "plan_service" else {}),
        },
    )
    v = bench._collect("cpu_fallback")["variants"]["plan_service"]
    assert v["plan_service"] == block
    assert v["report_sha256"] == "abc"


def test_multiproc_variant_in_both_tables_and_whitelist(monkeypatch):
    """The pod variant (ISSUE 14) rides both tables, and its
    multiproc block (parity verdict, members/sec ratio, degraded-
    coordinator evidence) survives the parent's field whitelist into
    the artifact."""
    for table in (bench._VARIANTS_TPU, bench._VARIANTS_CPU):
        assert "population_multiproc" in table
        # the pod run and its single-process twin measure the same
        # synthetic session as the population pair
        assert table["population_multiproc"] == table["population_vmap"]

    block = {
        "processes": 2,
        "parity_sha_ok": True,
        "members_per_s": 10.0,
        "twin_members_per_s": 12.0,
        "degraded_coordinator": {
            "rung": "single_host", "error_present": True,
            "parity_ok": True,
        },
    }
    monkeypatch.setattr(
        bench, "_VARIANTS_CPU",
        {"einsum": (8, 2), "population_multiproc": (800, 2)},
    )
    monkeypatch.setattr(
        bench,
        "_run_variant",
        lambda name, platform, n, iters: {
            "epochs_per_s": 1.0,
            "bytes_per_epoch": 12000,
            "n": n,
            "wall_s": 1.0,
            "report_sha256": "abc",
            **(
                {"multiproc": block, "mesh": {"rung": "pod"},
                 "members_per_s": 10.0}
                if name == "population_multiproc" else {}
            ),
        },
    )
    v = bench._collect("cpu_fallback")["variants"]["population_multiproc"]
    assert v["multiproc"] == block
    assert v["mesh"] == {"rung": "pod"}
    assert v["members_per_s"] == 10.0


def test_gateway_fleet_in_both_tables_and_routing():
    """The replicated-fleet benchmark (ISSUE 17) rides every bench
    artifact, on TPU and the CPU fallback — the replicas are
    CPU-forced child processes either way — through the pipeline
    child."""
    import inspect

    for table in (bench._VARIANTS_TPU, bench._VARIANTS_CPU):
        assert "gateway_fleet" in table
        # deliberately small on BOTH tables: the line pins failover
        # (takeover sha, exactly-once audit, drain), and the heavy
        # plan's kill window is sized in iterations whose unit cost
        # scales with the session — a plan_service-sized session
        # would stretch the twin and takeover re-run into minutes
        assert table["gateway_fleet"] == (400, 2)
    src = inspect.getsource(bench._run_variant)
    assert '"gateway_"' in src and "pipeline_bench.py" in src


def test_collect_propagates_fleet_field(monkeypatch):
    """The gateway_fleet line's failover block (takeover sha parity,
    zero-double-execution audit, drain exit codes) must survive the
    parent's field whitelist into the published artifact — the
    crash-only failover claim is audited from it."""
    block = {
        "replicas": 3,
        "takeover": {
            "plan_id": "p0001",
            "completed_by": "gw-b",
            "takeover_recorded": True,
            "sha_identical_to_twin": True,
        },
        "journal_audit": {
            "terminal_records": 4, "corrupt": 0, "leftover_leases": 0,
        },
        "zero_double_executions": True,
        "drain_exit_codes": [0, 0],
    }
    monkeypatch.setattr(
        bench, "_VARIANTS_CPU",
        {"einsum": (8, 2), "gateway_fleet": (2000, 4)},
    )
    monkeypatch.setattr(
        bench,
        "_run_variant",
        lambda name, platform, n, iters: {
            "epochs_per_s": 1.0,
            "bytes_per_epoch": 6000,
            "n": n,
            "wall_s": 1.0,
            "report_sha256": "abc",
            **({"fleet": block} if name == "gateway_fleet" else {}),
        },
    )
    v = bench._collect("cpu_fallback")["variants"]["gateway_fleet"]
    assert v["fleet"] == block
    assert v["report_sha256"] == "abc"


def test_fleet_placement_in_both_tables_and_routing():
    """The device-aware placement benchmark (ISSUE 20) rides every
    bench artifact, on TPU and the CPU fallback — both phases force a
    virtual CPU host either way — through the pipeline child."""
    import inspect

    for table in (bench._VARIANTS_TPU, bench._VARIANTS_CPU):
        assert "fleet_placement" in table
        # same small-session reasoning as gateway_fleet: the line
        # pins scheduling (makespan ratio, sha parity, the lease
        # audit), which a bigger session stretches without sharpening
        assert table["fleet_placement"] == (400, 2)
    src = inspect.getsource(bench._run_variant)
    assert '"fleet_"' in src and "pipeline_bench.py" in src


def test_collect_propagates_placement_field(monkeypatch):
    """The fleet_placement line's block (makespan ratio vs the
    disabled twin, sha parity, zero-double-held audit) must survive
    the parent's field whitelist into the published artifact — the
    placement claim is audited from it."""
    block = {
        "replicas": 3,
        "makespan_ratio": 0.9,
        "placement_no_slower": True,
        "sha_parity": True,
        "zero_double_held": True,
        "gang_fully_leased": True,
        "placed": {"makespan_s": 9.0, "drain_exit_codes": [0, 0, 0]},
        "disabled": {"makespan_s": 10.0},
    }
    monkeypatch.setattr(
        bench, "_VARIANTS_CPU",
        {"einsum": (8, 2), "fleet_placement": (400, 2)},
    )
    monkeypatch.setattr(
        bench,
        "_run_variant",
        lambda name, platform, n, iters: {
            "epochs_per_s": 1.0,
            "bytes_per_epoch": 6000,
            "n": n,
            "wall_s": 1.0,
            "report_sha256": "abc",
            **(
                {"placement": block}
                if name == "fleet_placement" else {}
            ),
        },
    )
    v = bench._collect("cpu_fallback")["variants"]["fleet_placement"]
    assert v["placement"] == block
    assert v["report_sha256"] == "abc"


def test_smoke_gates_fleet_placement():
    """The e2e smoke suite runs the fleet_placement child and gates
    on its placement block (ISSUE 20): the check exists, is wired
    into run(), and refuses a line with no block, a slower placed
    makespan, a sha drift, or a failed lease audit."""
    import importlib.util as iu

    spec = iu.spec_from_file_location(
        "e2e_smoke",
        os.path.join(
            os.path.dirname(os.path.dirname(__file__)),
            "tools", "e2e_smoke.py",
        ),
    )
    smoke = iu.module_from_spec(spec)
    spec.loader.exec_module(smoke)

    failures = []
    smoke._check_placement({}, failures)
    assert failures and "no placement block" in failures[0]

    good = {
        "placement": {
            "makespan_ratio": 0.9,
            "placement_no_slower": True,
            "sha_parity": True,
            "zero_double_held": True,
            "gang_fully_leased": True,
            "placed": {
                "all_completed": True, "drained_cleanly": True,
                "makespan_s": 9.0,
                "sha_identical": {"gang": True, "small": True},
                "device_audit": {"gang_leased_ordinals": list(range(8))},
            },
            "disabled": {
                "all_completed": True, "drained_cleanly": True,
                "makespan_s": 10.0,
                "sha_identical": {"gang": True, "small": True},
            },
        },
    }
    failures = []
    smoke._check_placement(good, failures)
    assert failures == []

    bad = json.loads(json.dumps(good))
    bad["placement"]["placement_no_slower"] = False
    bad["placement"]["zero_double_held"] = False
    failures = []
    smoke._check_placement(bad, failures)
    assert len(failures) == 2
    import inspect

    src = inspect.getsource(smoke.run)
    assert "fleet_placement" in src and "_check_placement" in src
