"""WebHDFS adapter: protocol semantics + provider/pipeline over hdfs://.

The reference's storage is HDFS — ``Const.java:38-39`` hard-codes
``hdfs://localhost:8020`` and every data path dials it
(``OffLineDataProvider.java:90``). These tests run a mock namenode +
datanode pair (one real ``http.server`` playing both roles, with the
namenode 307-redirecting OPEN/CREATE to datanode URLs exactly like the
WebHDFS REST contract) and drive the full client: GETFILESTATUS-driven
chunked OPEN reads with offset/length, the CREATE two-step write,
redirect-free HttpFS-style gateways, transient-failure retries, and
the provider + pipeline end-to-end with ``info_file=hdfs://...``.
"""

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.io import provider, remote


class _Store:
    def __init__(self):
        self.files = {}
        self.fail_next = 0  # respond 500 to this many requests
        self.no_redirect = False  # HttpFS-style: serve directly
        self.requests = []


class _Handler(BaseHTTPRequestHandler):
    """One server playing namenode (redirects) and datanode (data).

    Datanode URLs are the same host with ``/dn`` prefixed — the client
    must follow the Location verbatim, like a real cluster where the
    datanode is a different authority.
    """

    store: _Store
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):
        pass

    def _send(self, status, body=b"", headers=()):
        self.send_response(status)
        for k, v in headers:
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def _parse(self):
        parts = urllib.parse.urlsplit(self.path)
        q = dict(urllib.parse.parse_qsl(parts.query))
        path = parts.path
        is_dn = path.startswith("/dn")
        if is_dn:
            path = path[len("/dn") :]
        assert path.startswith("/webhdfs/v1"), path
        return is_dn, path[len("/webhdfs/v1") :], q

    def _fail_injected(self):
        if self.store.fail_next > 0:
            self.store.fail_next -= 1
            self._send(500)
            return True
        return False

    def do_GET(self):
        is_dn, hpath, q = self._parse()
        self.store.requests.append(("GET", self.path))
        if self._fail_injected():
            return
        op = q.get("op")
        data = self.store.files.get(hpath)
        if op == "GETFILESTATUS":
            if data is None:
                body = json.dumps(
                    {"RemoteException": {"exception": "FileNotFoundException"}}
                ).encode()
                self._send(404, body)
                return
            body = json.dumps(
                {"FileStatus": {"length": len(data), "type": "FILE"}}
            ).encode()
            self._send(200, body)
            return
        if op == "LISTSTATUS":
            prefix = hpath.rstrip("/") + "/"
            children = sorted(
                {
                    k[len(prefix):].split("/", 1)[0]
                    for k in self.store.files
                    if k.startswith(prefix)
                }
            )
            if not children and data is None:
                body = json.dumps(
                    {"RemoteException": {"exception": "FileNotFoundException"}}
                ).encode()
                self._send(404, body)
                return
            body = json.dumps(
                {
                    "FileStatuses": {
                        "FileStatus": [
                            {"pathSuffix": c, "type": "FILE"}
                            for c in children
                        ]
                    }
                }
            ).encode()
            self._send(200, body)
            return
        if op == "OPEN":
            if data is None:
                self._send(404)
                return
            if not is_dn and not self.store.no_redirect:
                loc = f"http://{self.headers['Host']}/dn{self.path}"
                self._send(307, headers=[("Location", loc)])
                return
            off = int(q.get("offset", 0))
            ln = int(q.get("length", len(data) - off))
            self._send(200, data[off : off + ln])
            return
        self._send(400)

    def do_DELETE(self):
        _is_dn, hpath, q = self._parse()
        self.store.requests.append(("DELETE", self.path))
        if self._fail_injected():
            return
        if q.get("op") != "DELETE":
            self._send(400)
            return
        prefix = hpath.rstrip("/")
        doomed = [
            k
            for k in self.store.files
            if k == prefix or k.startswith(prefix + "/")
        ]
        for k in doomed:
            del self.store.files[k]
        self._send(
            200, json.dumps({"boolean": bool(doomed)}).encode()
        )

    def do_PUT(self):
        is_dn, hpath, q = self._parse()
        self.store.requests.append(("PUT", self.path))
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if self._fail_injected():
            return
        if q.get("op") != "CREATE":
            self._send(400)
            return
        if self.store.no_redirect:
            # HttpFS-style: first body-less PUT is accepted, the
            # second PUT carries data=true + the bytes
            if q.get("data") == "true":
                self.store.files[hpath] = body
            self._send(201)
            return
        if not is_dn:
            loc = f"http://{self.headers['Host']}/dn{self.path}"
            self._send(307, headers=[("Location", loc)])
            return
        self.store.files[hpath] = body
        self._send(201)


@pytest.fixture()
def namenode():
    store = _Store()
    handler = type("Handler", (_Handler,), {"store": store})
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    authority = f"127.0.0.1:{httpd.server_address[1]}"
    try:
        yield authority, store
    finally:
        httpd.shutdown()
        httpd.server_close()


def _fs(**kw):
    kw.setdefault(
        "retry", remote.RetryPolicy(max_attempts=4, timeout_s=5.0, backoff_s=0.01)
    )
    return remote.WebHdfsFileSystem(**kw)


def test_exists_read_write_roundtrip(namenode):
    auth, store = namenode
    fs = _fs()
    uri = f"hdfs://{auth}/data/a.bin"
    assert not fs.exists(uri)
    fs.write_bytes(uri, b"on the cluster")
    assert store.files["/data/a.bin"] == b"on the cluster"
    assert fs.exists(uri)
    assert fs.read_bytes(uri) == b"on the cluster"
    assert fs.read_text(uri) == "on the cluster"


def test_redirect_hops_to_datanode(namenode):
    """OPEN and CREATE both bounce namenode->datanode; the client
    follows Location verbatim."""
    auth, store = namenode
    fs = _fs()
    fs.write_bytes(f"hdfs://{auth}/x", b"z" * 10)
    assert fs.read_bytes(f"hdfs://{auth}/x") == b"z" * 10
    dn_puts = [p for m, p in store.requests if m == "PUT" and p.startswith("/dn")]
    dn_gets = [p for m, p in store.requests if m == "GET" and p.startswith("/dn")]
    assert dn_puts and dn_gets  # data flowed through the datanode role


def test_chunked_open_reads_use_offset_length(namenode):
    auth, store = namenode
    payload = bytes(range(256)) * 100  # 25600 B
    store.files["/big.bin"] = payload
    fs = _fs(chunk_size=10_000)
    assert fs.read_bytes(f"hdfs://{auth}/big.bin") == payload
    opens = [
        p for m, p in store.requests if m == "GET" and "op=OPEN" in p
        and not p.startswith("/dn")
    ]
    assert len(opens) == 3  # ceil(25600/10000) namenode OPENs
    assert "offset=10000" in opens[1] and "offset=20000" in opens[2]


def test_read_range(namenode):
    auth, store = namenode
    store.files["/blk"] = bytes(range(200))
    assert _fs().read_range(f"hdfs://{auth}/blk", 20, 7) == bytes(range(20, 27))


def test_missing_file_raises_filenotfound(namenode):
    auth, _ = namenode
    with pytest.raises(FileNotFoundError):
        _fs().read_bytes(f"hdfs://{auth}/nope")


def test_transient_500s_retried(namenode):
    auth, store = namenode
    store.files["/flaky"] = b"q" * 50
    store.fail_next = 2
    assert _fs().read_bytes(f"hdfs://{auth}/flaky") == b"q" * 50


def test_retry_budget_exhausts_loudly(namenode):
    auth, store = namenode
    store.files["/dead"] = b"x"
    store.fail_next = 99
    with pytest.raises(remote.RemoteIOError, match="after 4 attempts"):
        _fs().read_bytes(f"hdfs://{auth}/dead")


def test_httpfs_gateway_without_redirects(namenode):
    """Gateways (HttpFS) answer directly: CREATE takes data=true on the
    second PUT, OPEN serves bytes with no Location hop."""
    auth, store = namenode
    store.no_redirect = True
    fs = _fs()
    uri = f"hdfs://{auth}/gw.bin"
    fs.write_bytes(uri, b"direct body")
    assert store.files["/gw.bin"] == b"direct body"
    assert fs.read_bytes(uri) == b"direct body"


def test_endpoint_override_maps_rpc_authority(namenode):
    """Real clusters: hdfs:// URIs carry the RPC port (8020) while
    WebHDFS lives on the HTTP port — endpoint= rewrites the authority
    (the Const.java:38-39 shape, pointed at a live gateway)."""
    auth, store = namenode
    store.files["/data/x"] = b"mapped"
    fs = _fs(endpoint=f"http://{auth}")
    assert fs.read_bytes("hdfs://localhost:8020/data/x") == b"mapped"


def test_default_fs_uri_without_endpoint_fails_fast(namenode):
    """hdfs:///path (no authority) must not silently dial
    localhost:80 — it raises unless an endpoint is configured."""
    auth, store = namenode
    with pytest.raises(ValueError, match="no authority"):
        _fs().read_bytes("hdfs:///data/x")
    store.files["/data/x"] = b"df"
    assert _fs(endpoint=f"http://{auth}").read_bytes("hdfs:///data/x") == b"df"


def test_endpoint_env_var_reaches_scheme_routed_instances(namenode, monkeypatch):
    """filesystem_for('hdfs://...') takes no kwargs; WEBHDFS_ENDPOINT
    lets those instances reach a gateway whose HTTP authority differs
    from the URI's RPC one (the real-cluster 8020-vs-9870 split)."""
    auth, store = namenode
    store.files["/data/env"] = b"via env"
    monkeypatch.setenv("WEBHDFS_ENDPOINT", f"http://{auth}")
    monkeypatch.setenv("WEBHDFS_USER", "envuser")
    fs = remote.filesystem_for("hdfs://namenode.invalid:8020/data/env")
    fs.retry = remote.RetryPolicy(max_attempts=2, timeout_s=5.0, backoff_s=0.01)
    assert fs.read_bytes("hdfs://namenode.invalid:8020/data/env") == b"via env"
    assert any("user.name=envuser" in p for _, p in store.requests)


def test_relative_location_header_resolved(namenode):
    """A proxy answering with a relative Location (RFC 7231) must be
    followed, resolved against the current hop's URL."""
    auth, store = namenode
    store.files["/rel"] = b"relative ok"

    base_handler = type(
        "RelHandler",
        (_Handler,),
        {"store": store, "do_GET": _relative_redirect_get},
    )
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), base_handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        rel_auth = f"127.0.0.1:{httpd.server_address[1]}"
        assert _fs().read_bytes(f"hdfs://{rel_auth}/rel") == b"relative ok"
    finally:
        httpd.shutdown()
        httpd.server_close()


def _relative_redirect_get(self):
    is_dn, hpath, q = self._parse()
    self.store.requests.append(("GET", self.path))
    data = self.store.files.get(hpath)
    if q.get("op") == "GETFILESTATUS":
        body = json.dumps(
            {"FileStatus": {"length": len(data), "type": "FILE"}}
        ).encode()
        self._send(200, body)
        return
    if not is_dn:
        self._send(307, headers=[("Location", f"/dn{self.path}")])
        return
    off = int(q.get("offset", 0))
    ln = int(q.get("length", len(data) - off))
    self._send(200, data[off : off + ln])


def test_user_name_param(namenode):
    auth, store = namenode
    store.files["/u"] = b"1"
    fs = _fs(user="eegupdate")
    fs.read_bytes(f"hdfs://{auth}/u")
    assert any("user.name=eegupdate" in p for _, p in store.requests)


def test_non_webhdfs_responder_stays_in_ioerror_contract(server_like_plain):
    """A 200 from something that isn't WebHDFS (captive portal) maps to
    RemoteIOError, not a leaked JSONDecodeError."""
    auth = server_like_plain
    with pytest.raises(remote.RemoteIOError, match="unparseable"):
        _fs().exists(f"hdfs://{auth}/anything")


@pytest.fixture()
def server_like_plain():
    class Plain(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *args):
            pass

        def do_GET(self):
            body = b"<html>welcome to the portal</html>"
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Plain)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        yield f"127.0.0.1:{httpd.server_address[1]}"
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_directory_read_raises_isadirectory(namenode):
    """Reading a DIRECTORY status object mirrors LocalFileSystem's
    IsADirectoryError instead of silently returning b''."""
    auth, store = namenode

    dir_handler = type(
        "DirHandler", (_Handler,), {"store": store, "do_GET": _dir_status_get}
    )
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), dir_handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        dauth = f"127.0.0.1:{httpd.server_address[1]}"
        with pytest.raises(IsADirectoryError):
            _fs().read_bytes(f"hdfs://{dauth}/models/")
    finally:
        httpd.shutdown()
        httpd.server_close()


def _dir_status_get(self):
    body = json.dumps(
        {"FileStatus": {"length": 0, "type": "DIRECTORY"}}
    ).encode()
    self._send(200, body)


def test_filesystem_for_routes_hdfs():
    assert isinstance(
        remote.filesystem_for("hdfs://localhost:8020/x"),
        remote.WebHdfsFileSystem,
    )


# -- end to end over the reference fixtures ---------------------------


def _serve_fixture(store, fixture_dir):
    for name in (
        "infoTrain.txt",
        "DoD/DoD2015_01.eeg",
        "DoD/DoD2015_01.vhdr",
        "DoD/DoD2015_01.vmrk",
    ):
        with open(f"{fixture_dir}/{name}", "rb") as f:
            store.files[f"/data/{name}"] = f.read()


def test_provider_over_hdfs_matches_local(namenode, fixture_dir):
    auth, store = namenode
    _serve_fixture(store, fixture_dir)
    batch_hdfs = provider.OfflineDataProvider(
        [f"hdfs://{auth}/data/infoTrain.txt"], filesystem=_fs(chunk_size=1 << 20)
    ).load()
    batch_local = provider.OfflineDataProvider(
        [f"{fixture_dir}/infoTrain.txt"]
    ).load()
    np.testing.assert_array_equal(batch_hdfs.epochs, batch_local.epochs)
    np.testing.assert_array_equal(batch_hdfs.targets, batch_local.targets)


def test_pipeline_over_hdfs_end_to_end(namenode, fixture_dir, tmp_path):
    """info_file=hdfs://... through the full query DSL — the literal
    reference flow (Const.java:38-39 + OffLineDataProvider.java:90),
    with scheme routing picking WebHdfsFileSystem automatically."""
    from eeg_dataanalysispackage_tpu.pipeline import builder

    auth, store = namenode
    _serve_fixture(store, fixture_dir)
    result_path = str(tmp_path / "result.txt")
    builder.PipelineBuilder(
        f"info_file=hdfs://{auth}/data/infoTrain.txt&fe=dwt-8"
        f"&train_clf=logreg&result_path={result_path}"
    ).execute()
    assert "Accuracy" in open(result_path).read()


def test_model_save_load_over_hdfs(namenode):
    """Classifier persistence on HDFS — the reference's
    model.save(sc, 'hdfs://...') flow
    (LogisticRegressionClassifier.java:144-152)."""
    from eeg_dataanalysispackage_tpu.models.linear import (
        LogisticRegressionClassifier,
    )

    auth, store = namenode
    rng = np.random.RandomState(0)
    feats = rng.randn(40, 48).astype(np.float32)
    ys = (feats[:, 0] > 0).astype(np.float64)
    clf = LogisticRegressionClassifier()
    clf.set_config({})
    clf.fit(feats, ys)
    clf.save(f"hdfs://{auth}/models/logreg")
    assert "/models/logreg.npz" in store.files

    clf2 = LogisticRegressionClassifier()
    clf2.load(f"hdfs://{auth}/models/logreg")
    np.testing.assert_array_equal(clf2.weights, clf.weights)


def test_mllib_model_dir_save_load_over_hdfs(namenode, tmp_path):
    """MLlib model DIRECTORIES on HDFS, both directions: export
    uploads every file through the filesystem seam; load detects the
    remote directory via LISTSTATUS, localizes it, and predicts
    identically — the reference's literal model.save/load-
    on-the-namenode flow for artifacts its Spark jobs also read."""
    from eeg_dataanalysispackage_tpu.io import mllib_format as mf
    from eeg_dataanalysispackage_tpu.models.linear import (
        LogisticRegressionClassifier,
    )

    auth, store = namenode
    rng = np.random.RandomState(1)
    feats = rng.randn(40, 48).astype(np.float64)
    ys = (feats[:, 0] > 0).astype(np.float64)
    clf = LogisticRegressionClassifier()
    clf.set_config({})
    clf.fit(feats, ys)
    uri = f"hdfs://{auth}/models/mllib_logreg"
    clf.export_mllib_dir(uri)
    assert "/models/mllib_logreg/metadata/part-00000" in store.files
    assert any(
        k.startswith("/models/mllib_logreg/data/part-r-")
        for k in store.files
    )

    assert mf.is_model_dir(uri)
    clf2 = LogisticRegressionClassifier()
    clf2.load(uri)
    np.testing.assert_array_equal(clf2.predict(feats), clf.predict(feats))
    # a non-model hdfs path still routes to the npz reader
    assert not mf.is_model_dir(f"hdfs://{auth}/models/nothing_here")

    # RE-export to the same URI (retrain flow): the previous export's
    # files must be replaced, not accumulated — a stale second data
    # part would corrupt every reader (review finding)
    clf.fit(feats * 2.0, ys)
    clf.export_mllib_dir(uri)
    parts = [
        k
        for k in store.files
        if k.startswith("/models/mllib_logreg/data/part-r-")
    ]
    assert len(parts) == 1
    clf3 = LogisticRegressionClassifier()
    clf3.load(uri)
    np.testing.assert_array_equal(clf3.weights, np.asarray(clf.weights, np.float64))


def test_pipeline_save_load_model_over_hdfs(namenode, fixture_dir, tmp_path):
    """save_clf/load_clf with an hdfs:// save_name through the query
    DSL — the reference's literal models-on-HDFS flow
    (LogisticRegressionClassifier.java:144-152 against Const.java's
    hdfs:// endpoint)."""
    from eeg_dataanalysispackage_tpu.pipeline import builder

    auth, store = namenode
    _serve_fixture(store, fixture_dir)
    model_uri = f"hdfs://{auth}/models/pipeline-logreg"
    r1 = str(tmp_path / "r1.txt")
    builder.PipelineBuilder(
        f"info_file=hdfs://{auth}/data/infoTrain.txt&fe=dwt-8"
        f"&train_clf=logreg&save_clf=true&save_name={model_uri}"
        f"&result_path={r1}"
    ).execute()
    assert "/models/pipeline-logreg.npz" in store.files
    r2 = str(tmp_path / "r2.txt")
    stats = builder.PipelineBuilder(
        f"info_file=hdfs://{auth}/data/infoTrain.txt&fe=dwt-8"
        f"&load_clf=logreg&load_name={model_uri}&result_path={r2}"
    ).execute()
    assert stats.num_patterns == 11  # load branch tests on ALL data
    assert "Accuracy" in open(r2).read()


def test_raw_channel_text_export_over_hdfs(namenode, fixture_dir):
    """The reference's HadoopLoadingTest.tryRAWEEG flow
    (HadoopLoadingTest.java:56-119) over the WebHDFS protocol: read a
    recording channel from hdfs://, write it back as saveAsTextFile-
    format text (Double.toString lines) to hdfs://, and re-parse what
    the cluster stored."""
    from eeg_dataanalysispackage_tpu.io import brainvision, export

    auth, store = namenode
    _serve_fixture(store, fixture_dir)
    fs = _fs(chunk_size=1 << 20)
    rec = brainvision.load_recording(
        f"hdfs://{auth}/data/DoD/DoD2015_01.eeg", filesystem=fs
    )
    channel = rec.read_channels([2])[0]  # channel 3, 0-indexed

    # "/Dod" (not "DoD") mirrors the reference's own output path
    # literal (HadoopLoadingTest.java: outputFileLocation = ... + "/Dod")
    out_uri = f"hdfs://{auth}/data/Dod/raw.txt"
    export.write_channel_text(channel, out_uri)  # scheme-routed write
    assert "/data/Dod/raw.txt" in store.files

    lines = store.files["/data/Dod/raw.txt"].decode("ascii").splitlines()
    assert len(lines) == channel.shape[0]
    np.testing.assert_array_equal(
        np.array([float(x) for x in lines]), channel.astype(np.float64)
    )
