"""Host->device prefetch staging (io/staging.py)."""

import jax
import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.io import staging
from eeg_dataanalysispackage_tpu.parallel import mesh as pmesh, train as ptrain


def test_minibatches_shapes_and_remainder():
    x = np.arange(10).reshape(10, 1)
    y = np.arange(10)
    got = list(staging.minibatches(x, y, batch_size=4))
    assert [b[0].shape[0] for b in got] == [4, 4, 2]
    np.testing.assert_array_equal(np.concatenate([b[1] for b in got]), y)
    dropped = list(staging.minibatches(x, y, batch_size=4, drop_remainder=True))
    assert [b[0].shape[0] for b in dropped] == [4, 4]


def test_minibatches_rejects_misaligned():
    with pytest.raises(ValueError, match="misaligned"):
        list(staging.minibatches(np.ones(4), np.ones(5), batch_size=2))


def test_prefetch_matches_direct_staging():
    rng = np.random.RandomState(0)
    x = rng.randn(9, 3).astype(np.float32)
    y = rng.randn(9).astype(np.float32)
    got = list(
        staging.prefetch(staging.minibatches(x, y, batch_size=4))
    )
    assert len(got) == 3
    for (gx, gy), start in zip(got, range(0, 9, 4)):
        assert isinstance(gx, jax.Array)
        np.testing.assert_array_equal(np.asarray(gx), x[start : start + 4])
        np.testing.assert_array_equal(np.asarray(gy), y[start : start + 4])


def test_prefetch_propagates_source_errors():
    def bad():
        yield (np.ones(2),)
        raise RuntimeError("boom in loader")

    it = staging.prefetch(bad())
    next(it)
    with pytest.raises(RuntimeError, match="boom in loader"):
        next(it)


def test_prefetch_early_stop_does_not_hang():
    it = staging.prefetch(
        staging.minibatches(np.ones((100, 2)), batch_size=1), buffer_size=2
    )
    next(it)
    it.close()  # consumer abandons; producer must unblock


def test_prefetch_early_stop_cancels_producer():
    pulled = []

    def source():
        for i in range(10_000):
            pulled.append(i)
            yield (np.full(2, i, np.float32),)

    it = staging.prefetch(source(), buffer_size=2)
    next(it)
    it.close()
    # the producer must stop near where the consumer left off, not
    # stage the remaining ~10k batches during close()
    assert len(pulled) <= 8


def test_prefetch_sharded_feeds_train_step():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    mesh = pmesh.make_mesh(8)
    rng = np.random.RandomState(0)
    epochs = rng.randn(21, 3, 750).astype(np.float32)  # not /8: pad+mask
    targets = (rng.rand(21) > 0.5).astype(np.float32)

    init_state, train_step = ptrain.make_train_step(mesh)
    state = init_state(jax.random.PRNGKey(0))
    seen = 0
    for ep, lb, mask in staging.prefetch_epochs(
        epochs, targets, batch_size=8, mesh=mesh
    ):
        assert ep.shape[0] % 8 == 0
        state, loss = train_step(state, ep, lb, mask)
        seen += int(np.asarray(mask).sum())
    assert seen == 21
    assert np.isfinite(float(loss))


def test_prefetch_consumer_watchdog_detects_dead_producer(monkeypatch):
    """ISSUE-6 satellite regression: a producer thread that dies
    WITHOUT delivering its poison sentinel (here: its own failure
    handling fails) must not hang the consumer forever — the timed
    ``queue.get`` + liveness check fails the consumer fast and emits
    ``staging.producer_dead``."""
    from eeg_dataanalysispackage_tpu.obs import events

    recorded = []

    def exploding_event(name, **attrs):
        recorded.append(name)
        if name == "staging.producer_error":
            # kill the producer inside its OWN failure path: the
            # poison sentinel is never delivered — exactly the class
            # of death the watchdog exists for
            raise RuntimeError("failure handling failed too")

    monkeypatch.setattr(events, "event", exploding_event)

    def source():
        yield (np.ones(2, np.float32),)
        raise RuntimeError("source died")

    it = staging.prefetch(source(), buffer_size=2, watchdog_poll_s=0.05)
    next(it)  # batch 1 flows
    with pytest.raises(staging.ProducerDiedError, match="died without"):
        next(it)
    assert "staging.producer_dead" in recorded


def test_prefetch_watchdog_tolerates_slow_producer():
    """The liveness check must not misfire on a producer that is
    merely slow: a stage taking several poll intervals still
    delivers."""
    import time

    def source():
        yield (np.ones(2, np.float32),)
        time.sleep(0.3)  # several watchdog polls
        yield (np.full(2, 2.0, np.float32),)

    got = list(staging.prefetch(source(), watchdog_poll_s=0.05))
    assert len(got) == 2
    np.testing.assert_array_equal(np.asarray(got[1][0]), [2.0, 2.0])


def test_prefetch_undelivered_producer_error_is_logged(caplog):
    """The silent-loss fix: a producer that dies after the consumer
    walked away can no longer vanish — the stop-aware put gives up
    (stop is already set, so the poisoned sentinel is undeliverable)
    and the error is logged when the consumer joins."""
    import logging
    import threading

    stop_seen = threading.Event()

    def source():
        yield (np.ones(2, np.float32),)
        # block until the consumer has closed (set stop), then fail:
        # delivery is impossible, so the error must hit the log
        stop_seen.wait(5.0)
        raise RuntimeError("boom after close")

    it = staging.prefetch(source(), buffer_size=1)
    next(it)  # consume batch 1; batch 2 fills the buffer
    with caplog.at_level(logging.WARNING,
                         logger="eeg_dataanalysispackage_tpu.io.staging"):
        import time

        stop_seen.set()
        # the producer is now raising; its poisoned sentinel cannot
        # enter the full buffer, so it polls until close() sets stop
        time.sleep(0.2)
        it.close()
    assert "never delivered" in caplog.text
    assert "boom after close" in caplog.text
