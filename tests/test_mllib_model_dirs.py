"""MLlib model-directory interchange (io/mllib_format.py).

The reference persists models with MLlib's own ``model.save``
(LogisticRegressionClassifier.java:144-152, DecisionTreeClassifier
.java:156-165 with its ``file://`` prefix): parquet data + JSON
metadata directories. These tests pin that a directory in that
format — built by the module's own format-1.0 writer, whose schema
follows the layout documented in the module docstring — loads
drop-in through the classifiers' ``load()`` seam and predicts with
MLlib's semantics (f64 margins, strict-greater thresholds, Vote/Sum
ensemble combining), plus the native-npz compatibility edges around
the new intercept/threshold state.
"""

import json
import os

import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.io import mllib_format as mf
from eeg_dataanalysispackage_tpu.models.linear import (
    LogisticRegressionClassifier,
    SVMClassifier,
)
from eeg_dataanalysispackage_tpu.models.trees import (
    DecisionTreeClassifier,
    GradientBoostedTreesClassifier,
    RandomForestClassifier,
)

RNG = np.random.RandomState(7)


def _features(n=64, d=48):
    return RNG.randn(n, d) * 2.0


# ------------------------------------------------------------- GLM


def test_glm_dir_round_trip(tmp_path):
    w = RNG.randn(48)
    d = str(tmp_path / "m")
    mf.write_glm(d, mf.GLM_LOGREG, w, intercept=0.25, threshold=0.5)
    m = mf.read_glm(d)
    assert m.model_class == mf.GLM_LOGREG
    np.testing.assert_array_equal(m.weights, w)  # f64 bit round-trip
    assert m.intercept == 0.25
    assert m.threshold == 0.5
    assert m.num_features == 48 and m.num_classes == 2


def test_logreg_loads_mllib_dir_and_predicts_like_mllib(tmp_path):
    w = RNG.randn(48)
    b = 0.3
    d = str(tmp_path / "m")
    mf.write_glm(d, mf.GLM_LOGREG, w, intercept=b, threshold=0.5)
    clf = LogisticRegressionClassifier()
    clf.load(d)
    X = _features()
    # LogisticRegressionModel.predictPoint: sigmoid(x.w + b) > 0.5,
    # i.e. margin > 0, all in doubles
    want = ((X @ w + b) > 0.0).astype(np.float64)
    np.testing.assert_array_equal(clf.predict(X), want)


def test_logreg_honors_probability_threshold(tmp_path):
    w = RNG.randn(48)
    d = str(tmp_path / "m")
    mf.write_glm(d, mf.GLM_LOGREG, w, intercept=0.0, threshold=0.7)
    clf = LogisticRegressionClassifier()
    clf.load(d)
    X = _features()
    prob = 1.0 / (1.0 + np.exp(-(X @ w)))
    np.testing.assert_array_equal(
        clf.predict(X), (prob > 0.7).astype(np.float64)
    )


def test_svm_threshold_is_a_margin(tmp_path):
    w = RNG.randn(48)
    d = str(tmp_path / "m")
    mf.write_glm(d, mf.GLM_SVM, w, intercept=-0.1, threshold=1.5)
    clf = SVMClassifier()
    clf.load(d)
    X = _features()
    want = ((X @ w - 0.1) > 1.5).astype(np.float64)
    np.testing.assert_array_equal(clf.predict(X), want)


def test_glm_class_mismatch_raises(tmp_path):
    d = str(tmp_path / "m")
    mf.write_glm(d, mf.GLM_SVM, RNG.randn(48))
    with pytest.raises(ValueError, match="SVMModel"):
        LogisticRegressionClassifier().load(d)


def test_cleared_threshold_refused(tmp_path):
    d = str(tmp_path / "m")
    mf.write_glm(d, mf.GLM_LOGREG, RNG.randn(48), threshold=None)
    with pytest.raises(ValueError, match="cleared threshold"):
        LogisticRegressionClassifier().load(d)


@pytest.mark.parametrize(
    "compression,use_dictionary,page_version",
    [
        ("gzip", True, "1.0"),  # what Spark 1.6 actually wrote
        ("snappy", False, "1.0"),
        ("none", True, "2.0"),
    ],
)
def test_glm_reader_is_encoding_robust(
    tmp_path, compression, use_dictionary, page_version
):
    """Different deployments wrote different parquet encodings
    (codec/dictionary/page-version vary by Spark config); the reader
    must be indifferent. Rewrites the data file with each encoding
    and asserts a bit-identical read."""
    import pyarrow.parquet as pq

    w = RNG.randn(48)
    d = str(tmp_path / "m")
    mf.write_glm(d, mf.GLM_LOGREG, w, intercept=1.5, threshold=0.5)
    data_dir = os.path.join(d, "data")
    f = [
        os.path.join(data_dir, p)
        for p in os.listdir(data_dir)
        if p.endswith(".parquet")
    ][0]
    table = pq.read_table(f)
    pq.write_table(
        table,
        f,
        compression=compression,
        use_dictionary=use_dictionary,
        data_page_version=page_version,
    )
    m = mf.read_glm(d)
    np.testing.assert_array_equal(m.weights, w)
    assert m.intercept == 1.5


def test_sparse_vector_decoding():
    v = {
        "type": 0,
        "size": 6,
        "indices": [1, 4],
        "values": [2.5, -1.0],
    }
    np.testing.assert_array_equal(
        mf._vector_to_np(v), [0.0, 2.5, 0.0, 0.0, -1.0, 0.0]
    )


def test_npz_back_compat_without_interchange_fields(tmp_path):
    """Model archives from before the intercept/threshold fields load
    with the structural zeros native training implies."""
    import io as _io

    w = RNG.randn(48).astype(np.float32)
    buf = _io.BytesIO()
    np.savez(
        buf,
        weights=w,
        config=json.dumps({}),
        kind="LogisticRegressionClassifier",
    )
    p = str(tmp_path / "old.npz")
    with open(p, "wb") as f:
        f.write(buf.getvalue())
    clf = LogisticRegressionClassifier()
    clf.load(p)
    assert clf.intercept == 0.0 and clf.margin_threshold == 0.0
    X = _features().astype(np.float32)
    np.testing.assert_array_equal(
        clf.predict(X),
        (np.asarray(X @ w) > 0.0).astype(np.float64),
    )


def test_npz_round_trips_interchange_fields(tmp_path):
    d = str(tmp_path / "m")
    mf.write_glm(d, mf.GLM_SVM, RNG.randn(48), intercept=0.5, threshold=0.25)
    clf = SVMClassifier()
    clf.load(d)
    p = str(tmp_path / "native")
    clf.save(p)
    clf2 = SVMClassifier()
    clf2.load(p)
    assert clf2.intercept == 0.5
    assert clf2.margin_threshold == 0.25
    X = _features()
    np.testing.assert_array_equal(clf2.predict(X), clf.predict(X))


# ------------------------------------------------------------ trees


def _manual_tree():
    """Depth-2 stump pair: root on feature 3 @ 0.0; left child leaf
    -> 0; right child splits feature 10 @ 1.0 into 1 / 0."""
    return {
        "feature": np.array([3, 0, 10, 0, 0]),
        "threshold": np.array([0.0, np.inf, 1.0, np.inf, np.inf]),
        "left": np.array([1, 1, 3, 3, 4]),
        "right": np.array([2, 1, 4, 3, 4]),
        "leaf": np.array([False, True, False, True, True]),
        "predict": np.array([0.0, 0.0, 0.0, 1.0, 0.0]),
    }


def _manual_tree_predict(X):
    out = np.zeros(len(X))
    right = X[:, 3] > 0.0
    out[right & (X[:, 10] <= 1.0)] = 1.0
    return out


def test_dt_dir_round_trip_and_predict(tmp_path):
    d = str(tmp_path / "dt")
    mf.write_tree_ensemble(d, mf.TREE_DT, [_manual_tree()])
    clf = DecisionTreeClassifier()
    # the reference passes "file://" + path (DecisionTreeClassifier
    # .java:164); the importer strips it
    clf.load("file://" + d)
    X = _features()
    np.testing.assert_array_equal(clf.predict(X), _manual_tree_predict(X))


def test_rf_vote_combining(tmp_path):
    t1 = _manual_tree()
    t0 = _manual_tree()
    t0["predict"] = np.zeros(5)  # always votes 0
    talways = _manual_tree()
    talways["predict"] = np.array([0.0, 1.0, 0.0, 1.0, 1.0])  # votes 1
    d = str(tmp_path / "rf")
    mf.write_tree_ensemble(d, mf.TREE_RF, [t1, t0, talways])
    clf = RandomForestClassifier()
    clf.load(d)
    X = _features()
    votes = _manual_tree_predict(X) + 0.0 + 1.0
    np.testing.assert_array_equal(
        clf.predict(X), (votes > 1.5).astype(np.float64)
    )


def test_rf_vote_tie_goes_to_class_one(tmp_path):
    """Spark 1.6 ``predictByVoting`` takes ``maxBy`` over a
    ``mutable.HashMap`` whose iteration order for the binary keys
    {0, 1} is fixed by the hash table (key 1's bucket iterates before
    key 0's; see MLlibTreeEnsemble.predict), so an exact weighted tie
    deterministically predicts class 1.0 — independent of tree order
    (ADVICE divergence). Pinned in BOTH tree orders."""
    t1 = _manual_tree()
    t1["predict"] = np.ones(5)  # always votes 1
    t0 = _manual_tree()
    t0["predict"] = np.zeros(5)  # always votes 0
    X = _features(8)

    for name, trees in (("rf_1_first", [t1, t0]),
                        ("rf_0_first", [t0, t1])):
        d = str(tmp_path / name)
        mf.write_tree_ensemble(d, mf.TREE_RF, trees)
        clf = RandomForestClassifier()
        clf.load(d)
        np.testing.assert_array_equal(clf.predict(X), np.ones(8))


def test_gbt_sum_combining(tmp_path):
    # regression trees emitting margins; Sum with treeWeights, label
    # = 1 iff weighted sum > 0 (GradientBoostedTreesModel predict)
    t = _manual_tree()
    t["predict"] = np.array([0.0, -1.0, 0.0, 2.0, -1.0])
    d = str(tmp_path / "gbt")
    mf.write_tree_ensemble(
        d, mf.TREE_GBT, [t, t], tree_weights=[1.0, 0.25]
    )
    clf = GradientBoostedTreesClassifier()
    clf.load(d)
    X = _features()
    per = np.where(
        X[:, 3] > 0.0, np.where(X[:, 10] <= 1.0, 2.0, -1.0), -1.0
    )
    want = ((1.25 * per) > 0.0).astype(np.float64)
    np.testing.assert_array_equal(clf.predict(X), want)


def test_tree_class_mismatch_raises(tmp_path):
    d = str(tmp_path / "rf")
    mf.write_tree_ensemble(d, mf.TREE_RF, [_manual_tree()])
    with pytest.raises(ValueError, match="RandomForestModel"):
        DecisionTreeClassifier().load(d)


def test_imported_tree_save_is_explicit(tmp_path):
    d = str(tmp_path / "dt")
    mf.write_tree_ensemble(d, mf.TREE_DT, [_manual_tree()])
    clf = DecisionTreeClassifier()
    clf.load(d)
    with pytest.raises(ValueError, match="export_mllib_dir"):
        clf.save(str(tmp_path / "native"))
    # explicit re-export round-trips
    d2 = str(tmp_path / "dt2")
    clf.export_mllib_dir(d2)
    clf2 = DecisionTreeClassifier()
    clf2.load(d2)
    X = _features()
    np.testing.assert_array_equal(clf2.predict(X), clf.predict(X))


def test_categorical_split_refused(tmp_path):
    d = str(tmp_path / "dt")
    mf.write_tree_ensemble(d, mf.TREE_DT, [_manual_tree()])
    # rewrite the parquet with a categorical featureType
    import pyarrow.parquet as pq

    data_dir = os.path.join(d, "data")
    f = [
        os.path.join(data_dir, p)
        for p in os.listdir(data_dir)
        if p.endswith(".parquet")
    ][0]
    rows = pq.read_table(f).to_pylist()
    for r in rows:
        if r["split"] is not None:
            r["split"]["featureType"] = 1
    import pyarrow as pa

    pq.write_table(
        pa.Table.from_pylist(rows, schema=pq.read_table(f).schema), f
    )
    with pytest.raises(NotImplementedError, match="categorical"):
        mf.read_tree_ensemble(d)


def test_reader_error_paths(tmp_path):
    """Every refusal branch raises its documented error, not an
    accidental KeyError/IndexError."""
    # empty metadata dir
    d0 = tmp_path / "empty"
    (d0 / "metadata").mkdir(parents=True)
    with pytest.raises(FileNotFoundError, match="metadata part"):
        mf.read_metadata(str(d0))
    # metadata present but blank
    (d0 / "metadata" / "part-00000").write_text("\n\n")
    with pytest.raises(ValueError, match="empty metadata"):
        mf.read_metadata(str(d0))
    # wrong class for each reader
    d1 = str(tmp_path / "dt")
    mf.write_tree_ensemble(d1, mf.TREE_DT, [_manual_tree()])
    with pytest.raises(ValueError, match="not a GLM"):
        mf.read_glm(d1)
    d2 = str(tmp_path / "glm")
    mf.write_glm(d2, mf.GLM_LOGREG, RNG.randn(4))
    with pytest.raises(ValueError, match="not an MLlib tree"):
        mf.read_tree_ensemble(d2)
    # multi-row GLM data refuses
    import pyarrow.parquet as pq

    data_dir = os.path.join(d2, "data")
    f = [
        os.path.join(data_dir, p)
        for p in os.listdir(data_dir)
        if p.endswith(".parquet")
    ][0]
    t = pq.read_table(f)
    import pyarrow as pa

    pq.write_table(pa.concat_tables([t, t]), f)
    with pytest.raises(ValueError, match="single row"):
        mf.read_glm(d2)
    # unknown vector type tag
    with pytest.raises(ValueError, match="type tag"):
        mf._vector_to_np({"type": 7, "values": [1.0]})
    # unknown combining strategy
    with pytest.raises(ValueError, match="combining"):
        mf._normalize_combining("median")
    # DT must hold exactly one tree
    with pytest.raises(ValueError, match="exactly one"):
        mf.write_tree_ensemble(
            str(tmp_path / "x"), mf.TREE_DT, [_manual_tree()] * 2
        )
    # treeWeights length mismatch
    d3 = str(tmp_path / "rf")
    mf.write_tree_ensemble(
        d3, mf.TREE_RF, [_manual_tree()], tree_weights=[1.0]
    )
    meta = mf.read_metadata(d3)
    meta["metadata"]["treeWeights"] = [1.0, 2.0]
    with open(os.path.join(d3, "metadata", "part-00000"), "w") as fh:
        fh.write(json.dumps(meta))
    with pytest.raises(ValueError, match="treeWeights"):
        mf.read_tree_ensemble(d3)
    # internal node with a null split record
    d4 = str(tmp_path / "nosplit")
    mf.write_tree_ensemble(d4, mf.TREE_DT, [_manual_tree()])
    data_dir = os.path.join(d4, "data")
    f4 = [
        os.path.join(data_dir, p)
        for p in os.listdir(data_dir)
        if p.endswith(".parquet")
    ][0]
    rows = pq.read_table(f4).to_pylist()
    for r in rows:
        r["split"] = None
    pq.write_table(
        pa.Table.from_pylist(rows, schema=pq.read_table(f4).schema), f4
    )
    with pytest.raises(ValueError, match="no split"):
        mf.read_tree_ensemble(d4)


def test_is_model_dir_detection(tmp_path):
    assert not mf.is_model_dir(str(tmp_path))
    d = str(tmp_path / "m")
    mf.write_glm(d, mf.GLM_LOGREG, RNG.randn(4))
    assert mf.is_model_dir(d)
    assert mf.is_model_dir("file://" + d)
    # a directory with an empty metadata dir is not a model dir
    os.makedirs(str(tmp_path / "x" / "metadata"))
    assert not mf.is_model_dir(str(tmp_path / "x"))


def test_native_npz_load_still_works_beside_dirs(tmp_path):
    """A trained-and-saved native model loads unchanged through the
    same seam that detects MLlib dirs."""
    X = _features(128).astype(np.float64)
    y = (X[:, 0] > 0).astype(np.float64)
    clf = LogisticRegressionClassifier()
    clf.set_config({})
    clf.fit(X, y)
    p = str(tmp_path / "native")
    clf.save(p)
    clf2 = LogisticRegressionClassifier()
    clf2.load(p)
    np.testing.assert_array_equal(clf2.predict(X), clf.predict(X))


def test_fit_after_import_replaces_the_imported_model(tmp_path):
    """Training must clear imported MLlib state (review finding:
    stale _mllib/intercept/threshold silently shadowing fresh
    training)."""
    X = _features(128)
    y = (X[:, 0] > 0).astype(np.float64)

    d = str(tmp_path / "glm")
    mf.write_glm(d, mf.GLM_LOGREG, RNG.randn(48), intercept=2.3, threshold=0.7)
    clf = LogisticRegressionClassifier()
    clf.load(d)
    clf.set_config({})
    clf.fit(X.astype(np.float64), y)
    assert clf.intercept == 0.0 and clf.margin_threshold == 0.0
    # native semantics: f32 margin > 0, no imported intercept
    np.testing.assert_array_equal(
        clf.predict(X),
        (
            np.asarray(X.astype(np.float32) @ clf.weights) > 0.0
        ).astype(np.float64),
    )

    d2 = str(tmp_path / "dt")
    always_one = _manual_tree()
    always_one["predict"] = np.array([0.0, 1.0, 0.0, 1.0, 1.0])
    mf.write_tree_ensemble(d2, mf.TREE_DT, [always_one])
    tclf = DecisionTreeClassifier()
    tclf.load(d2)
    tclf.set_config({})
    tclf.fit(X, y)
    assert tclf._mllib is None
    assert not np.all(tclf.predict(X) == 1.0)  # not the imported stump


def test_logreg_threshold_extremes_import_as_constant_classifiers(
    tmp_path,
):
    """setThreshold(1.0)/(0.0) are legal MLlib states meaning
    always-0 / always-1; they must import, not ZeroDivisionError
    (review finding)."""
    w = RNG.randn(48)
    X = _features()
    for thr, const in ((1.0, 0.0), (0.0, 1.0)):
        d = str(tmp_path / f"t{thr}")
        mf.write_glm(d, mf.GLM_LOGREG, w, threshold=thr)
        clf = LogisticRegressionClassifier()
        clf.load(d)
        np.testing.assert_array_equal(
            clf.predict(X), np.full(len(X), const)
        )


def test_multiclass_models_refused(tmp_path):
    """Binary-only consumers refuse multiclass artifacts instead of
    silently collapsing labels (review finding)."""
    d = str(tmp_path / "glm3")
    mf.write_glm(d, mf.GLM_LOGREG, RNG.randn(96), num_classes=3)
    with pytest.raises(NotImplementedError, match="multinomial"):
        LogisticRegressionClassifier().load(d)

    t = _manual_tree()
    t["predict"] = np.array([0.0, 2.0, 0.0, 1.0, 0.0])  # class-2 leaf
    d2 = str(tmp_path / "dt3")
    mf.write_tree_ensemble(d2, mf.TREE_DT, [t])
    with pytest.raises(NotImplementedError, match="multiclass"):
        mf.read_tree_ensemble(d2)
    # GBT margins are NOT class labels: arbitrary leaf values stay
    # legal on the sum path
    d3 = str(tmp_path / "gbt_margin")
    mf.write_tree_ensemble(d3, mf.TREE_GBT, [t])
    assert mf.read_tree_ensemble(d3).combining == "sum"


def test_export_mllib_dir_glm_round_trip(tmp_path):
    """Reverse migration: a natively-trained GLM exports to a
    format-1.0 directory that loads back bit-equivalently."""
    X = _features(128).astype(np.float64)
    y = (X[:, 0] > 0).astype(np.float64)
    clf = LogisticRegressionClassifier()
    clf.set_config({})
    clf.fit(X, y)
    d = str(tmp_path / "exported")
    clf.export_mllib_dir(d)
    m = mf.read_glm(d)
    assert m.model_class == mf.GLM_LOGREG
    np.testing.assert_array_equal(
        m.weights, np.asarray(clf.weights, np.float64)
    )
    assert m.threshold == 0.5  # margin 0 -> probability 0.5
    clf2 = LogisticRegressionClassifier()
    clf2.load(d)
    np.testing.assert_array_equal(clf2.predict(X), clf.predict(X))


def test_export_mllib_dir_trees_predict_identically(tmp_path):
    """DT/RF/GBT export maps binned splits back to real bin edges;
    the exported model must predict identically on fresh data (the
    (lo, hi] bin semantics make the mapping exact)."""
    X = _features(256)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    X_test = _features(512)
    cases = [
        (DecisionTreeClassifier, {
            "config_max_depth": "4", "config_max_bins": "16",
            "config_min_instances_per_node": "1",
            "config_impurity": "gini",
        }),
        (RandomForestClassifier, {
            "config_max_depth": "4", "config_max_bins": "16",
            "config_min_instances_per_node": "1",
            "config_impurity": "gini", "config_num_trees": "7",
            "config_feature_subset": "sqrt",
        }),
        (GradientBoostedTreesClassifier, {
            "config_num_iterations": "12",
            "config_learning_rate": "0.2", "config_max_depth": "3",
        }),
    ]
    for cls, config in cases:
        clf = cls()
        clf.set_config(config)
        clf.fit(X, y)
        d = str(tmp_path / cls.__name__)
        clf.export_mllib_dir(d)
        loaded = cls()
        loaded.load(d)
        assert loaded._mllib.model_class == cls._mllib_class
        np.testing.assert_array_equal(
            loaded.predict(X_test),
            clf.predict(X_test),
            err_msg=cls.__name__,
        )


def test_export_counts_only_reachable_nodes(tmp_path):
    """Device-grown heap trees carry unreachable padded slots
    (fixed-size arrays, feature = -1); metadata numNodes must count
    the DFS-reachable nodes Spark will reconstruct, or its load-time
    assert rejects the directory (review finding)."""
    # stump + 4 unreachable heap-padding slots
    padded = {
        "feature": np.array([3, 0, 0, 0, 0, 0, 0]),
        "threshold": np.array([0.0] + [np.inf] * 6),
        "left": np.array([1, 1, 2, 3, 4, 5, 6]),
        "right": np.array([2, 1, 2, 3, 4, 5, 6]),
        "leaf": np.array([False, True, True, True, True, True, True]),
        "predict": np.array([0.0, 0.0, 1.0, 0.0, 0.0, 0.0, 0.0]),
    }
    d = str(tmp_path / "dt")
    mf.write_tree_ensemble(d, mf.TREE_DT, [padded])
    assert mf.read_metadata(d)["numNodes"] == 3
    ens = mf.read_tree_ensemble(d)
    assert len(ens.trees[0]["leaf"]) == 3
    X = _features()
    want = (X[:, 3] > 0.0).astype(np.float64)
    np.testing.assert_array_equal(ens.predict(X), want)


def test_device_backend_export_round_trips(tmp_path):
    """The rf-tpu whole-forest grower's heap arrays (the other
    producer of padded slots) export and load back with identical
    predictions."""
    X = _features(256)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    clf = RandomForestClassifier(backend="device")
    clf.set_config(
        {
            "config_max_depth": "4", "config_max_bins": "16",
            "config_min_instances_per_node": "1",
            "config_impurity": "gini", "config_num_trees": "5",
            "config_feature_subset": "sqrt",
        }
    )
    clf.fit(X, y)
    d = str(tmp_path / "rf_dev")
    clf.export_mllib_dir(d)
    X_test = _features(512)
    loaded = RandomForestClassifier()
    loaded.load(d)
    np.testing.assert_array_equal(
        loaded.predict(X_test), clf.predict(X_test)
    )


def test_reexport_preserves_combining(tmp_path):
    """'An imported model re-exports as-is' includes the combining
    strategy (review finding: Average was silently rewritten)."""
    t = _manual_tree()
    t["predict"] = np.array([0.0, -1.0, 0.0, 2.0, -1.0])
    d = str(tmp_path / "avg")
    mf.write_tree_ensemble(
        d, mf.TREE_RF, [t, t], combining="Average"
    )
    clf = RandomForestClassifier()
    clf.load(d)
    assert clf._mllib.combining == "average"
    d2 = str(tmp_path / "re")
    clf.export_mllib_dir(d2)
    meta = mf.read_metadata(d2)
    assert meta["metadata"]["combiningStrategy"] == "Average"
    X = _features()
    np.testing.assert_array_equal(
        mf.read_tree_ensemble(d2).predict(X), clf._mllib.predict(X)
    )


def test_export_of_imported_model_is_stable(tmp_path):
    d = str(tmp_path / "src")
    mf.write_tree_ensemble(d, mf.TREE_DT, [_manual_tree()])
    clf = DecisionTreeClassifier()
    clf.load(d)
    d2 = str(tmp_path / "re")
    clf.export_mllib_dir(d2)
    X = _features()
    clf2 = DecisionTreeClassifier()
    clf2.load(d2)
    np.testing.assert_array_equal(clf2.predict(X), clf.predict(X))


def test_pipeline_save_clf_in_mllib_format(tmp_path, fixture_dir):
    """Query-level reverse migration: save_clf=true&
    config_model_format=mllib writes a Spark-loadable directory that
    a second load_clf query consumes."""
    from eeg_dataanalysispackage_tpu.pipeline import builder

    d = str(tmp_path / "spark_model")
    r1 = str(tmp_path / "r1.txt")
    builder.PipelineBuilder(
        f"info_file={fixture_dir}/infoTrain.txt&fe=dwt-8"
        f"&train_clf=logreg&save_clf=true&save_name={d}"
        f"&config_model_format=mllib&result_path={r1}"
    ).execute()
    assert mf.is_model_dir(d)
    assert mf.read_glm(d).model_class == mf.GLM_LOGREG
    r2 = str(tmp_path / "r2.txt")
    stats = builder.PipelineBuilder(
        f"info_file={fixture_dir}/infoTrain.txt&fe=dwt-8"
        f"&load_clf=logreg&load_name={d}&result_path={r2}"
    ).execute()
    assert stats is not None and os.path.exists(r2)


def test_nn_refuses_mllib_format_at_config_time():
    """The refusal fires at set_config — before the pipeline's train
    stage — so a doomed query cannot burn a full NN training run."""
    from eeg_dataanalysispackage_tpu.models import registry as clf_registry

    nn = clf_registry.create("nn")
    with pytest.raises(NotImplementedError, match="DL4J"):
        nn.set_config({"config_model_format": "mllib"})


def test_explicit_mllib_resave_of_imported_model(tmp_path):
    """With the explicit format key, re-saving an imported model is
    exactly what the user asked for — allowed (review finding),
    unlike the bare save() which still refuses."""
    d = str(tmp_path / "src")
    mf.write_tree_ensemble(d, mf.TREE_DT, [_manual_tree()])
    clf = DecisionTreeClassifier()
    clf.load(d)
    clf.set_config({"config_model_format": "mllib"})
    d2 = str(tmp_path / "re")
    clf.save(d2)
    X = _features()
    clf2 = DecisionTreeClassifier()
    clf2.load(d2)
    np.testing.assert_array_equal(clf2.predict(X), clf.predict(X))


def test_remote_uri_export_uploads_through_modelfiles(monkeypatch):
    """A remote save_name routes every model-dir file through the
    pluggable filesystem instead of silently creating a junk local
    directory named after the URI (review finding)."""
    from eeg_dataanalysispackage_tpu.io import modelfiles

    uploaded = {}
    monkeypatch.setattr(
        modelfiles,
        "write_model_bytes",
        lambda path, data: uploaded.__setitem__(path, data),
    )
    mf.write_glm(
        "gs://bucket/models/logreg", mf.GLM_LOGREG, RNG.randn(8)
    )
    names = sorted(uploaded)
    assert "gs://bucket/models/logreg/metadata/part-00000" in names
    assert any(
        n.startswith("gs://bucket/models/logreg/data/part-r-")
        and n.endswith(".gz.parquet")
        for n in names
    )
    assert "gs://bucket/models/logreg/metadata/_SUCCESS" in names
    assert "gs://bucket/models/logreg/data/_SUCCESS" in names
    assert not os.path.exists("gs:")  # no junk local dir


def test_glm_parquet_embeds_spark_row_metadata(tmp_path):
    """Spark 1.6's ``GLMClassificationModel.SaveLoadV1_0.loadData``
    pattern-matches ``Row(weights: Vector, ...)``; without the
    VectorUDT ``udt`` entry in the
    ``org.apache.spark.sql.parquet.row.metadata`` footer key the row
    deserializes as a plain struct and throws MatchError on the
    cluster (ADVICE, medium). Tree exports stay footer-free (NodeData
    has no UDT)."""
    import pyarrow.parquet as pq

    d = str(tmp_path / "glm")
    mf.write_glm(d, mf.GLM_LOGREG, RNG.randn(8))
    (part,) = [
        n
        for n in os.listdir(os.path.join(d, "data"))
        if n.startswith("part-")
    ]
    meta = pq.read_schema(os.path.join(d, "data", part)).metadata
    schema = json.loads(
        meta[b"org.apache.spark.sql.parquet.row.metadata"]
    )
    fields = {f["name"]: f for f in schema["fields"]}
    assert list(fields) == ["weights", "intercept", "threshold"]
    wt = fields["weights"]["type"]
    assert wt["type"] == "udt"
    assert wt["class"] == "org.apache.spark.mllib.linalg.VectorUDT"
    assert [f["name"] for f in wt["sqlType"]["fields"]] == [
        "type", "size", "indices", "values",
    ]
    assert fields["intercept"]["type"] == "double"
    # our own reader still round-trips the tagged file
    np.testing.assert_equal(mf.read_glm(d).weights.shape, (8,))

    d2 = str(tmp_path / "tree")
    mf.write_tree_ensemble(d2, mf.TREE_DT, [_manual_tree()])
    (part2,) = [
        n
        for n in os.listdir(os.path.join(d2, "data"))
        if n.startswith("part-")
    ]
    tmeta = pq.read_schema(os.path.join(d2, "data", part2)).metadata
    assert not tmeta or (
        b"org.apache.spark.sql.parquet.row.metadata" not in tmeta
    )


def test_remote_export_refuses_stale_uuid_parts(monkeypatch):
    """A listing-capable filesystem WITHOUT recursive delete: a
    directory Spark itself wrote holds uuid-suffixed part files
    (part-r-00000-<uuid>.gz.parquet) that deterministic naming never
    overwrites — the export must refuse before uploading anything,
    not silently coexist into a corrupt concatenated model (ADVICE,
    low). Our own previous export (matching names) still overwrites."""
    from eeg_dataanalysispackage_tpu.io import modelfiles

    uploaded = {}
    monkeypatch.setattr(
        modelfiles,
        "write_model_bytes",
        lambda path, data: uploaded.__setitem__(path, data),
    )

    class SparkWrittenFs:
        def list_dir(self, path):
            if path.endswith("/data"):
                return [
                    "part-r-00000-8bba3c02-bf4c-4bde.gz.parquet",
                    "_SUCCESS",
                ]
            return ["part-00000", "_SUCCESS"]

    monkeypatch.setattr(
        modelfiles, "_fs_for", lambda p: SparkWrittenFs()
    )
    with pytest.raises(IOError, match="part files"):
        mf.write_glm("hdfs://nn/models/m", mf.GLM_LOGREG, RNG.randn(8))
    assert not uploaded  # refused before the first upload

    class OurOwnExportFs:
        def list_dir(self, path):
            if path.endswith("/data"):
                return ["part-r-00000.gz.parquet", "_SUCCESS"]
            return ["part-00000", "_SUCCESS"]

    monkeypatch.setattr(
        modelfiles, "_fs_for", lambda p: OurOwnExportFs()
    )
    mf.write_glm("hdfs://nn/models/m", mf.GLM_LOGREG, RNG.randn(8))
    assert any(p.endswith(".gz.parquet") for p in uploaded)

    class FreshTargetFs:  # no dir yet: FileNotFoundError is fine
        def list_dir(self, path):
            raise FileNotFoundError(path)

    monkeypatch.setattr(
        modelfiles, "_fs_for", lambda p: FreshTargetFs()
    )
    mf.write_glm("hdfs://nn/models/fresh", mf.GLM_LOGREG, RNG.randn(8))


def test_pipeline_load_clf_from_mllib_dir(tmp_path, fixture_dir):
    """End-to-end drop-in: ``load_clf=logreg&load_name=<mllib dir>``
    through the full query pipeline (PipelineBuilder.java:261-278
    load branch), on the reference fixture recording."""
    from eeg_dataanalysispackage_tpu.pipeline import builder

    d = str(tmp_path / "mllib_model")
    mf.write_glm(d, mf.GLM_LOGREG, RNG.randn(48) * 0.1, intercept=0.05)
    result = str(tmp_path / "res.txt")
    stats = builder.PipelineBuilder(
        f"info_file={fixture_dir}/infoTrain.txt&fe=dwt-8"
        f"&load_clf=logreg&load_name={d}&result_path={result}"
    ).execute()
    assert stats is not None
    assert os.path.exists(result)
