"""MLlib 1.6.2 tree parity: oracle pins + production-path bounds.

Closes VERDICT r3 "Missing #2 / Next #5": ``models/mllib_tree_oracle``
is the float64 emulation of Spark MLlib 1.6.2's tree stack
(``DecisionTreeClassifier.java:99-127``,
``RandomForestClassifier.java:101-135``), and this file

1. regression-pins the JVM RNG tower the oracle re-implements
   (java.util.Random, Spark XORShiftRandom + scala MurmurHash3,
   commons-math Well19937c + Poisson sampler),
2. unit-tests the split sketch against hand-computed cases,
3. asserts the production host grower (``models/trees``) is
   *bit-identical* to the oracle — same trees, same predictions —
   across randomized datasets and the reference fixture (the
   production path adopted MLlib's sketch thresholds, ``(lo, hi]``
   bin semantics, and gain association order in round 4),
4. pins the oracle's fixture predictions (the reproducible contract —
   no JVM runs here; same posture as test_mllib_accuracy_parity.py),
5. bounds the production RF's divergence from MLlib semantics: the
   bootstrap differs by construction (multinomial index resampling +
   numpy subset RNG vs Poisson weights + XORShift reservoir — a
   documented, partition-layout-*independent* design; the JVM's own
   RF output depends on the submitting cluster's core count, see the
   oracle module docstring), so RF parity is statistical, not exact.
"""

import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.features import wavelet
from eeg_dataanalysispackage_tpu.io import provider
from eeg_dataanalysispackage_tpu.models import mllib_tree_oracle as oracle
from eeg_dataanalysispackage_tpu.models import trees
from eeg_dataanalysispackage_tpu.utils import java_compat


# ------------------------------------------------------------------
# 1. RNG tower regression pins
# ------------------------------------------------------------------


def test_java_random_next_long_stream():
    jr = oracle.JavaRandom(12345)
    assert [jr.next_long() for _ in range(3)] == [
        6674089274190705457,
        -1236052134575208584,
        -3078921119283744887,
    ]


def test_scala_murmur3_and_xorshift_seed_hash():
    # the exact message Spark 1.6 hashes: 8 seed bytes big-endian in a
    # ByteBuffer.allocate(Long.SIZE = 64) -> 56 trailing zeros
    data = (12345).to_bytes(8, "big") + b"\x00" * 56
    assert oracle.scala_murmur3_bytes(data, 0x3C074A61) == -211718472
    assert oracle.XORShiftRandom.hash_seed(42) == -3557431703312098865


def test_xorshift_double_stream():
    x = oracle.XORShiftRandom(42)
    got = [x.next_double() for _ in range(4)]
    want = [
        0.6661236774413726,
        0.8583151351252906,
        0.9139963682495181,
        0.8664942556157945,
    ]
    assert got == want  # exact float64


def test_well19937c_streams():
    w = oracle.Well19937c(12346)  # BaggedPoint seed 12345 + 0 + 1
    assert [w.next(32) for _ in range(4)] == [
        2988933519,
        3711201989,
        1956579469,
        153950386,
    ]
    w2 = oracle.Well19937c(12346)
    assert [w2.next_double() for _ in range(3)] == [
        0.6959153244507543,
        0.4555516546345406,
        0.1841541832175031,
    ]


def test_poisson_sampler_exact_stream_and_statistics():
    w = oracle.Well19937c(12346)
    first = [oracle.poisson_sample(w) for _ in range(20)]
    assert first == [1, 0, 3, 1, 0, 1, 1, 1, 1, 1, 0, 0, 1, 2, 1, 0, 0, 1, 1, 0]
    draws = np.array([oracle.poisson_sample(w) for _ in range(20000)])
    # Poisson(1): mean 1, var 1
    assert abs(draws.mean() - 1.0) < 0.03
    assert abs(draws.var() - 1.0) < 0.06


def test_reservoir_sample_range():
    # d=48 features, k=7 (ceil(sqrt(48))), first nextLong of
    # new Random(12345) — the first node's subset draw in MLlib order
    got = oracle.reservoir_sample_range(48, 7, 6674089274190705457)
    assert got == [33, 28, 2, 3, 26, 15, 23]  # reservoir order, unsorted
    assert oracle.reservoir_sample_range(5, 7, 99) == [0, 1, 2, 3, 4]


# ------------------------------------------------------------------
# 2. Split sketch unit tests
# ------------------------------------------------------------------


def test_sketch_few_distinct_returns_all_values():
    got = oracle.find_splits_for_continuous_feature(
        np.array([1.0, 1.0, 2.0, 2.0, 3.0]), num_splits=6
    )
    assert got.tolist() == [1.0, 2.0, 3.0]


def test_sketch_stride_walk_hand_case():
    # 8 samples over 4 distinct values, 2 splits -> stride 8/3;
    # cumulative counts 2,4,6,8 vs targets 2.667, 5.333:
    #   idx1: |2-2.667|=0.667 < |4-2.667|=1.333 -> NO (prev not closer)
    #   wait: emits when previousGap < currentGap -> at idx1 0.667<1.333
    #   -> emit value[0]=10, target->5.333
    #   idx2: |4-5.333|=1.333 == |6-5.333|=0.667? 1.333>0.667 -> no emit
    #   idx3: |6-5.333|=0.667 < |8-5.333|=2.667 -> emit value[2]=30
    samples = np.array([10.0, 10.0, 20.0, 20.0, 30.0, 30.0, 40.0, 40.0])
    got = oracle.find_splits_for_continuous_feature(samples, num_splits=2)
    assert got.tolist() == [10.0, 30.0]


def test_sketch_skewed_counts():
    # 3 distinct values, 3 allowed splits -> "possibleSplits <=
    # numSplits" branch returns every distinct value
    samples = np.array([5.0] * 90 + [6.0] * 5 + [7.0] * 5)
    got = oracle.find_splits_for_continuous_feature(samples, num_splits=3)
    assert got.tolist() == [5.0, 6.0, 7.0]
    # num_splits=2 forces the stride walk: stride 100/3; cumulative
    # counts 90, 95, 100 vs targets 33.3, 66.7 emit 5.0 then 6.0
    got2 = oracle.find_splits_for_continuous_feature(samples, num_splits=2)
    assert got2.tolist() == [5.0, 6.0]


def test_find_splits_bins_max_possible_bins():
    # maxPossibleBins = min(maxBins, numExamples): 7 rows -> 6 splits
    rng = np.random.RandomState(0)
    X = rng.randn(7, 3)
    th = oracle.find_splits_bins(X, max_bins=32)
    assert all(len(t) == 6 for t in th)


def test_bin_semantics_equality_goes_left():
    th = [np.array([1.0, 2.0])]
    X = np.array([[0.5], [1.0], [1.5], [2.0], [2.5]])
    binned = oracle.bin_features_mllib(X, th)
    assert binned[:, 0].tolist() == [0, 0, 1, 1, 2]
    # production path agrees (side='left' + observed-value thresholds)
    edges = np.array([[1.0, 2.0]])
    assert trees.bin_features(X, edges)[:, 0].tolist() == [0, 0, 1, 1, 2]


# ------------------------------------------------------------------
# 3. Production DT is bit-identical to the oracle
# ------------------------------------------------------------------


def assert_same_tree(clf: trees.DecisionTreeClassifier, root) -> None:
    """Walk the production flat-array tree and the oracle's linked
    tree together: same split features, same threshold *values*
    (production stores bin indices into the sketch edges), same leaf
    predictions, same shape."""
    arrays = clf.trees[0]

    def walk(node_id: int, onode) -> None:
        feat = int(arrays["feature"][node_id])
        if onode.is_leaf or onode.left is None:
            assert feat < 0, f"production splits where oracle has a leaf"
            assert float(arrays["prediction"][node_id]) == onode.predict
            return
        assert feat == onode.split_feature
        thr = float(clf.edges[feat][int(arrays["threshold_bin"][node_id])])
        assert thr == onode.split_threshold  # exact float64
        walk(int(arrays["left"][node_id]), onode.left)
        walk(int(arrays["right"][node_id]), onode.right)

    walk(0, root)


@pytest.mark.parametrize("trial", range(12))
def test_production_dt_bit_matches_oracle(trial):
    rng = np.random.RandomState(100 + trial)
    n = int(rng.choice([7, 11, 40, 120]))
    d = int(rng.choice([3, 8, 20]))
    X = rng.randn(n, d)
    y = ((X[:, 0] + 0.3 * rng.randn(n)) > 0).astype(float)
    if y.sum() in (0, n):
        y[0] = 1 - y[0]
    mb = int(rng.choice([4, 8, 32]))
    md = int(rng.choice([2, 5, 8]))
    imp = str(rng.choice(["gini", "entropy"]))
    mi = int(rng.choice([1, 3]))
    root = oracle.oracle_decision_tree(
        X, y, max_bins=mb, impurity=imp, max_depth=md, min_instances=mi
    )
    clf = trees.DecisionTreeClassifier()
    clf.set_config(
        {
            "config_max_bins": str(mb),
            "config_impurity": imp,
            "config_max_depth": str(md),
            "config_min_instances_per_node": str(mi),
        }
    )
    clf.fit(X, y)
    Xt = rng.randn(80, d)
    np.testing.assert_array_equal(clf.predict(Xt), oracle.predict_tree(root, Xt))
    np.testing.assert_array_equal(clf.predict(X), oracle.predict_tree(root, X))
    assert_same_tree(clf, root)  # structure, not just predictions


# ------------------------------------------------------------------
# 4. Fixture pins (ClassifierTest.java corpus: 7 train / 4 test)
# ------------------------------------------------------------------


@pytest.fixture(scope="module")
def fixture_split(fixture_dir):
    batch = provider.OfflineDataProvider(
        [fixture_dir + "/infoTrain.txt"]
    ).load()
    fe = wavelet.WaveletTransform(8, 512, 175, 16, backend="host")
    feats = fe.extract_batch(batch.epochs)
    perm = java_compat.java_shuffle_indices(len(batch.targets), seed=1)
    f = feats[perm]
    t = np.asarray(batch.targets, dtype=np.float64)[perm]
    return f[:7], t[:7], f[7:], t[7:]


def test_oracle_dt_fixture_pin(fixture_split):
    ftr, ttr, fte, tte = fixture_split
    root = oracle.oracle_decision_tree(ftr, ttr)  # MLlib defaults
    # a single split separates the 7-point train set perfectly
    assert oracle.tree_depth(root) == 1
    assert oracle.tree_node_count(root) == 3
    assert root.split_feature == 43
    assert root.split_threshold == 0.028324138692985303  # observed value
    np.testing.assert_array_equal(oracle.predict_tree(root, ftr), ttr)
    assert oracle.predict_tree(root, fte).tolist() == [0.0, 1.0, 1.0, 1.0]
    assert float((oracle.predict_tree(root, fte) == tte).mean()) == 0.75
    # entropy / shallow variant takes the same root split
    root_e = oracle.oracle_decision_tree(
        ftr, ttr, impurity="entropy", max_depth=3, max_bins=8
    )
    assert root_e.split_feature == 43
    assert root_e.split_threshold == 0.028324138692985303
    assert oracle.predict_tree(root_e, fte).tolist() == [0.0, 1.0, 1.0, 1.0]


def test_production_dt_fixture_equals_oracle(fixture_split):
    ftr, ttr, fte, tte = fixture_split
    root = oracle.oracle_decision_tree(ftr, ttr)
    clf = trees.DecisionTreeClassifier()
    clf.set_config({})
    clf.fit(ftr, ttr)
    np.testing.assert_array_equal(
        clf.predict(ftr), oracle.predict_tree(root, ftr)
    )
    np.testing.assert_array_equal(
        clf.predict(fte), oracle.predict_tree(root, fte)
    )
    # the production tree stores the same split as a bin index into
    # the sketch thresholds for feature 43
    assert clf.trees[0]["feature"][0] == 43
    assert clf.edges[43][clf.trees[0]["threshold_bin"][0]] == root.split_threshold
    assert_same_tree(clf, root)


def test_oracle_rf_fixture_pin(fixture_split):
    ftr, ttr, fte, tte = fixture_split
    roots = oracle.oracle_random_forest(ftr, ttr, num_trees=100)  # defaults
    assert len(roots) == 100
    np.testing.assert_array_equal(oracle.predict_forest(roots, ftr), ttr)
    assert oracle.predict_forest(roots, fte).tolist() == [0.0, 0.0, 1.0, 0.0]
    depths = np.bincount([oracle.tree_depth(r) for r in roots])
    assert depths.tolist() == [9, 70, 19, 2]


# ------------------------------------------------------------------
# 5. Production RF divergence bound (statistical, by construction)
# ------------------------------------------------------------------


def test_production_rf_fixture_divergence_bound(fixture_split):
    ftr, ttr, fte, tte = fixture_split
    roots = oracle.oracle_random_forest(ftr, ttr, num_trees=100)
    clf = trees.RandomForestClassifier()
    clf.set_config({})
    clf.fit(ftr, ttr)
    o_all = np.concatenate(
        [oracle.predict_forest(roots, ftr), oracle.predict_forest(roots, fte)]
    )
    p_all = np.concatenate([clf.predict(ftr), clf.predict(fte)])
    # both resampling designs agree on every training point and on
    # >= 3 of the 4 test points of the shipped corpus (measured:
    # 10/11; the disagreement is one genuinely ambiguous test point)
    np.testing.assert_array_equal(p_all[:7], o_all[:7])
    assert (p_all == o_all).mean() >= 10 / 11 - 1e-12


def test_production_rf_synthetic_divergence_bound():
    agrees, acc_deltas = [], []
    for trial in range(6):
        rng = np.random.RandomState(500 + trial)
        X = rng.randn(60, 12)
        y = ((X[:, 0] + 0.5 * X[:, 1] + 0.4 * rng.randn(60)) > 0).astype(float)
        Xt = rng.randn(200, 12)
        yt = ((Xt[:, 0] + 0.5 * Xt[:, 1]) > 0).astype(float)
        roots = oracle.oracle_random_forest(X, y, num_trees=20)
        clf = trees.RandomForestClassifier()
        clf.set_config(
            {
                "config_max_bins": "32",
                "config_impurity": "gini",
                "config_max_depth": "5",
                "config_min_instances_per_node": "1",
                "config_num_trees": "20",
                "config_feature_subset": "auto",
            }
        )
        clf.fit(X, y)
        po = oracle.predict_forest(roots, Xt)
        pp = clf.predict(Xt)
        agrees.append(float((po == pp).mean()))
        acc_deltas.append(abs(float((po == yt).mean()) - float((pp == yt).mean())))
    # same learning problem, different (documented) resampling RNG:
    # the two forests agree on the vast majority of points and reach
    # statistically indistinguishable accuracy
    assert np.mean(agrees) >= 0.9, agrees
    assert max(acc_deltas) <= 0.06, acc_deltas
