"""Content-addressed feature cache (io/feature_cache.py) + the
classifiers= shared-feature fan-out (pipeline/builder.py).

The contract under test (ISSUE 3): cached and uncached runs of the
same query produce bit-identical ClassificationStatistics; editing a
recording's bytes invalidates its run's entry; a corrupt/truncated
entry is a miss, never a crash; and a fan-out run's per-classifier
statistics match the corresponding single-classifier runs exactly.
Everything is hermetic (tests/_synthetic.py)."""

import glob
import os

import numpy as np
import pytest

import _synthetic
from eeg_dataanalysispackage_tpu.io import feature_cache
from eeg_dataanalysispackage_tpu.models import stats
from eeg_dataanalysispackage_tpu.pipeline import builder


def _session(directory, n_files=2, n_markers=30):
    """Multi-file synthetic session; returns the info.txt path."""
    lines = []
    for i in range(n_files):
        name = f"synth_{i:02d}"
        guessed = 2 + i
        _synthetic.write_recording(
            str(directory), name=name, n_markers=n_markers,
            guessed=guessed, seed=i,
        )
        lines.append(f"{name}.eeg {guessed}")
    info = os.path.join(str(directory), "info.txt")
    with open(info, "w") as f:
        f.write("\n".join(lines) + "\n")
    return info


def _query(info, classifier="train_clf=logreg", **extra):
    parts = [
        f"info_file={info}", "fe=dwt-8-fused", classifier,
        "config_num_iterations=10", "config_step_size=1.0",
        "config_mini_batch_fraction=1.0",
    ]
    parts += [f"{k}={v}" for k, v in extra.items()]
    return "&".join(parts)


def _stats_equal(a, b):
    assert str(a) == str(b)
    assert (a.true_positives, a.true_negatives, a.false_positives,
            a.false_negatives, a.mse, a.class1_sum, a.class2_sum) == (
        b.true_positives, b.true_negatives, b.false_positives,
        b.false_negatives, b.mse, b.class1_sum, b.class2_sum,
    )


@pytest.fixture
def cache_env(tmp_path, monkeypatch):
    """Opt back into the cache (conftest disables it hermetically)
    with a per-test directory; counters zeroed."""
    monkeypatch.delenv(feature_cache.ENV_DISABLE, raising=False)
    cache_dir = tmp_path / "fcache"
    monkeypatch.setenv(feature_cache.ENV_DIR, str(cache_dir))
    feature_cache.reset_stats()
    yield cache_dir
    feature_cache.reset_stats()


# ------------------------------------------------------- cache core


def test_cached_vs_uncached_statistics_bit_identical(tmp_path, cache_env):
    info = _session(tmp_path)
    s_cold = builder.PipelineBuilder(_query(info)).execute()
    after_cold = feature_cache.stats()
    assert after_cold["hits"] == 0
    assert after_cold["misses"] == 1
    assert glob.glob(str(cache_env / "*.npz"))  # the entry was stored

    s_warm = builder.PipelineBuilder(_query(info)).execute()
    after_warm = feature_cache.stats()
    assert after_warm["hits"] == 1
    assert after_warm["misses"] == 1
    _stats_equal(s_cold, s_warm)


def test_eeg_content_change_invalidates(tmp_path, cache_env):
    info = _session(tmp_path)
    builder.PipelineBuilder(_query(info)).execute()
    assert feature_cache.stats()["misses"] == 1

    # flip one sample byte: a new content digest, so a new key
    eeg = str(tmp_path / "synth_00.eeg")
    with open(eeg, "r+b") as f:
        f.seek(100)
        b = f.read(1)
        f.seek(100)
        f.write(bytes([b[0] ^ 0xFF]))
    s_changed = builder.PipelineBuilder(_query(info)).execute()
    st = feature_cache.stats()
    assert st["misses"] == 2 and st["hits"] == 0
    assert s_changed.num_patterns > 0
    # the changed content now has its own warm entry
    builder.PipelineBuilder(_query(info)).execute()
    assert feature_cache.stats()["hits"] == 1


def test_corrupt_entry_is_a_miss_not_a_crash(tmp_path, cache_env):
    info = _session(tmp_path)
    s_cold = builder.PipelineBuilder(_query(info)).execute()
    (entry,) = glob.glob(str(cache_env / "*.npz"))
    with open(entry, "wb") as f:
        f.write(b"not an npz at all")
    s_rebuilt = builder.PipelineBuilder(_query(info)).execute()
    st = feature_cache.stats()
    assert st["corrupt"] == 1
    assert st["hits"] == 0 and st["misses"] == 2
    _stats_equal(s_cold, s_rebuilt)
    # the rebuild re-stored a good entry
    builder.PipelineBuilder(_query(info)).execute()
    assert feature_cache.stats()["hits"] == 1


def test_truncated_entry_is_a_miss(tmp_path, cache_env):
    info = _session(tmp_path)
    builder.PipelineBuilder(_query(info)).execute()
    (entry,) = glob.glob(str(cache_env / "*.npz"))
    data = open(entry, "rb").read()
    with open(entry, "wb") as f:
        f.write(data[: len(data) // 2])  # a crash-mid-copy survivor
    s = builder.PipelineBuilder(_query(info)).execute()
    assert feature_cache.stats()["corrupt"] == 1
    assert s.num_patterns > 0


def test_cache_false_opts_a_run_out(tmp_path, cache_env):
    info = _session(tmp_path)
    builder.PipelineBuilder(_query(info, cache="false")).execute()
    st = feature_cache.stats()
    assert st == {
        "hits": 0, "misses": 0, "corrupt": 0, "cross_process_waits": 0,
    }
    assert not glob.glob(str(cache_env / "*.npz"))


def test_guessed_number_is_part_of_the_key(tmp_path, cache_env):
    """Same bytes, different guess -> different targets -> new key."""
    info = _session(tmp_path, n_files=1)
    builder.PipelineBuilder(_query(info)).execute()
    with open(info, "w") as f:
        f.write("synth_00.eeg 5\n")
    builder.PipelineBuilder(_query(info)).execute()
    st = feature_cache.stats()
    assert st["misses"] == 2 and st["hits"] == 0


def test_precision_class_is_part_of_the_key(tmp_path, cache_env):
    """Same bytes, different precision rung -> its OWN cache class:
    four cold runs across the f32/bf16/int8/int4 ladder store four
    entries (no cross-class hit), and each rung's warm re-run hits
    only its own entry — the 4-way miss matrix at the builder level."""
    info = _session(tmp_path, n_files=1)
    ladder = ("f32", "bf16", "int8", "int4")
    for p in ladder:
        builder.PipelineBuilder(_query(info, precision=p)).execute()
    st = feature_cache.stats()
    assert st["misses"] == len(ladder) and st["hits"] == 0
    assert len(glob.glob(str(cache_env / "*.npz"))) == len(ladder)
    for p in ladder:
        builder.PipelineBuilder(_query(info, precision=p)).execute()
    st = feature_cache.stats()
    assert st["misses"] == len(ladder) and st["hits"] == len(ladder)


def test_disabled_globally_without_dir(tmp_path, monkeypatch):
    monkeypatch.setenv(feature_cache.ENV_DISABLE, "1")
    assert feature_cache.open_cache() is None


# ------------------------------------------------- classifier fan-out


def test_fanout_matches_single_classifier_runs(tmp_path):
    info = _session(tmp_path)
    fan = builder.PipelineBuilder(
        _query(info, classifier="classifiers=logreg,svm,dt")
    ).execute()
    assert isinstance(fan, stats.FanOutStatistics)
    assert list(fan) == ["logreg", "svm", "dt"]
    for name in ("logreg", "svm", "dt"):
        single = builder.PipelineBuilder(
            _query(info, classifier=f"train_clf={name}")
        ).execute()
        _stats_equal(fan[name], single)


def test_fanout_result_path_report(tmp_path):
    info = _session(tmp_path)
    result = str(tmp_path / "report.txt")
    fan = builder.PipelineBuilder(
        _query(info, classifier="classifiers=logreg,svm",
               result_path=result)
    ).execute()
    text = open(result).read()
    assert text.startswith("classifier: logreg\n")
    assert "classifier: svm\n" in text
    assert str(fan["logreg"]) in text


def test_fanout_host_fe_path(tmp_path):
    """classifiers= composes with the reference-shaped host fe= path
    (one extraction pass shared), and matches the single run."""
    info = _session(tmp_path)

    def q(classifier):
        return (
            f"info_file={info}&fe=dwt-8&{classifier}"
            "&config_num_iterations=10&config_step_size=1.0"
            "&config_mini_batch_fraction=1.0"
        )

    fan = builder.PipelineBuilder(q("classifiers=logreg")).execute()
    single = builder.PipelineBuilder(q("train_clf=logreg")).execute()
    _stats_equal(fan["logreg"], single)


@pytest.mark.parametrize(
    "classifier,match",
    [
        ("classifiers=logreg&train_clf=svm", "exactly one"),
        ("classifiers=logreg&load_clf=svm", "exactly one"),
        ("classifiers=logreg&save_clf=true", "save_clf"),
        ("classifiers=logreg&elastic=true", "elastic"),
        ("classifiers=,", "comma-separated"),
    ],
)
def test_fanout_rejects_conflicts(tmp_path, classifier, match):
    info = _session(tmp_path, n_files=1)
    with pytest.raises(ValueError, match=match):
        builder.PipelineBuilder(_query(info, classifier=classifier)).execute()


def test_fanout_unknown_classifier_uses_reference_error(tmp_path):
    info = _session(tmp_path, n_files=1)
    with pytest.raises(ValueError, match="Unsupported classifier"):
        builder.PipelineBuilder(
            _query(info, classifier="classifiers=nosuch")
        ).execute()


# -- single-flight rebuild guard (ISSUE 10 satellite) ------------------


def test_single_flight_one_rebuild_kept(tmp_path):
    """Two threads racing the same missing key: the leader rebuilds
    and stores; the follower blocks in begin_build, its post-wait
    lookup hits the leader's entry, and exactly one rebuild is KEPT —
    deterministic interleaving via events, no sleeps on the assert
    path."""
    import threading

    cache = feature_cache.FeatureCache(str(tmp_path / "fc"))
    key = "a" * 40
    features = np.ones((4, 3), np.float32)
    targets = np.zeros(4, np.float64)

    leader_building = threading.Event()
    leader_may_store = threading.Event()
    builds, results, waited_flags = [], {}, {}

    def leader():
        slot = cache.begin_build(key)
        try:
            assert cache.lookup(key) is None  # genuine miss
            leader_building.set()
            assert leader_may_store.wait(10)
            builds.append("leader")
            cache.store(key, features, targets)
            results["leader"] = (features, targets)
        finally:
            slot.release()
        waited_flags["leader"] = slot.waited

    def follower():
        assert leader_building.wait(10)
        leader_may_store.set()
        # blocks until the leader releases; the entry exists by then
        slot = cache.begin_build(key)
        try:
            hit = cache.lookup(key)
            assert hit is not None, "follower must revalidate-hit"
            results["follower"] = hit
        finally:
            slot.release()
        waited_flags["follower"] = slot.waited

    t1 = threading.Thread(target=leader)
    t2 = threading.Thread(target=follower)
    t1.start()
    t2.start()
    t1.join(timeout=15)
    t2.join(timeout=15)
    assert not t1.is_alive() and not t2.is_alive()

    assert builds == ["leader"]  # exactly one rebuild kept
    assert waited_flags == {"leader": False, "follower": True}
    np.testing.assert_array_equal(results["follower"][0], features)
    np.testing.assert_array_equal(results["follower"][1], targets)


def test_single_flight_release_is_idempotent_and_unblocks(tmp_path):
    cache = feature_cache.FeatureCache(str(tmp_path / "fc"))
    slot = cache.begin_build("k" * 40)
    slot.release()
    slot.release()  # double release must not corrupt the flight set
    # the key is free again: a fresh acquisition does not wait
    slot2 = cache.begin_build("k" * 40)
    assert not slot2.waited
    slot2.release()


def test_single_flight_wait_honours_ambient_deadline(tmp_path):
    """A deadline-bearing plan queued behind another tenant's rebuild
    fails fast: begin_build's wait re-checks the ambient deadline
    scope instead of blocking unboundedly past the budget."""
    import threading

    from eeg_dataanalysispackage_tpu.io import deadline as deadline_mod

    cache = feature_cache.FeatureCache(str(tmp_path / "fc"))
    key = "d" * 40
    leader_slot = cache.begin_build(key)
    outcome = {}

    def waiter():
        with deadline_mod.deadline_scope(deadline_mod.Deadline(0.2)):
            try:
                slot = cache.begin_build(key)
            except deadline_mod.DeadlineExceededError as e:
                outcome["error"] = e
            else:  # pragma: no cover - the failure mode under test
                slot.release()
                outcome["error"] = None

    t = threading.Thread(target=waiter)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive(), "waiter blocked past its deadline"
    assert isinstance(outcome["error"], deadline_mod.DeadlineExceededError)
    leader_slot.release()
    # the key is free again for deadline-free builders
    slot = cache.begin_build(key)
    assert not slot.waited
    slot.release()


def test_try_begin_build_nonblocking(tmp_path):
    """try_begin_build: None while another builder holds the key (the
    store-only caller skips instead of queuing), a real slot when
    free."""
    cache = feature_cache.FeatureCache(str(tmp_path / "fc"))
    key = "t" * 40
    held = cache.begin_build(key)
    assert cache.try_begin_build(key) is None  # no wait, no slot
    held.release()
    slot = cache.try_begin_build(key)
    assert slot is not None and not slot.waited
    slot.release()


# ------------------------------------------------ cross-process lock
# (ISSUE 14 satellite: N local processes cold-starting the same
# session must not each pay the same rebuild — begin_build's
# single-flight extends across processes via a best-effort O_EXCL
# lock file; a foreign process is simulated by creating the lock out
# of band.)


def test_begin_build_waits_on_foreign_lock_then_proceeds(tmp_path):
    import threading
    import time as _time

    feature_cache.reset_stats()
    cache = feature_cache.FeatureCache(str(tmp_path / "fc"))
    key = "x" * 40
    os.makedirs(cache.directory, exist_ok=True)
    lock = cache._lock_path_for(key)
    with open(lock, "w") as f:
        f.write("99999")  # a live foreign builder

    got = {}

    def builder_thread():
        slot = cache.begin_build(key)
        got["t"] = _time.monotonic()
        slot.release()

    t = threading.Thread(target=builder_thread)
    t0 = _time.monotonic()
    t.start()
    _time.sleep(0.3)
    assert "t" not in got, "did not wait on the foreign lock"
    os.unlink(lock)  # the foreign builder finishes
    t.join(timeout=10)
    assert not t.is_alive()
    assert got["t"] - t0 >= 0.3
    assert feature_cache.stats()["cross_process_waits"] == 1
    # released cleanly: our own lock is gone too
    assert not os.path.exists(lock)


def test_begin_build_stops_waiting_when_entry_lands(tmp_path):
    """The foreign builder stored the entry: the waiter stops polling
    and its revalidating lookup hits — no rebuild, lock still
    foreign-held."""
    feature_cache.reset_stats()
    cache = feature_cache.FeatureCache(str(tmp_path / "fc"))
    features = np.ones((4, 8), np.float32)
    targets = np.zeros(4, np.float64)
    key = "y" * 40
    lock = cache._lock_path_for(key)
    os.makedirs(cache.directory, exist_ok=True)
    with open(lock, "w") as f:
        f.write("99999")
    cache.store(key, features, targets)  # the foreign store lands
    slot = cache.begin_build(key)  # returns promptly, lock-free
    hit = cache.lookup(key)
    assert hit is not None
    slot.release()
    assert os.path.exists(lock)  # not ours to break
    os.unlink(lock)


def test_stale_foreign_lock_is_broken(tmp_path, monkeypatch):
    """A dead holder's lock (older than the timeout) is broken and
    taken over instead of stalling every later run."""
    monkeypatch.setenv(feature_cache.ENV_LOCK_TIMEOUT, "0.2")
    cache = feature_cache.FeatureCache(str(tmp_path / "fc"))
    key = "z" * 40
    lock = cache._lock_path_for(key)
    os.makedirs(cache.directory, exist_ok=True)
    with open(lock, "w") as f:
        f.write("99999")
    old = os.path.getmtime(lock) - 5.0
    os.utime(lock, (old, old))
    slot = cache.begin_build(key)  # breaks the stale lock, owns a new one
    assert os.path.exists(lock)
    with open(lock) as f:
        assert f.read() == str(os.getpid())
    slot.release()
    assert not os.path.exists(lock)


def test_try_begin_build_respects_fresh_foreign_lock(tmp_path, monkeypatch):
    monkeypatch.setenv(feature_cache.ENV_LOCK_TIMEOUT, "30")
    cache = feature_cache.FeatureCache(str(tmp_path / "fc"))
    key = "w" * 40
    lock = cache._lock_path_for(key)
    os.makedirs(cache.directory, exist_ok=True)
    with open(lock, "w") as f:
        f.write("99999")
    assert cache.try_begin_build(key) is None  # fresh foreign holder
    old = os.path.getmtime(lock) - 60.0
    os.utime(lock, (old, old))
    slot = cache.try_begin_build(key)  # stale -> broken and taken
    assert slot is not None
    slot.release()


def test_foreign_lock_wait_deadline_fallback(tmp_path, monkeypatch):
    """A budget-bearing plan polling a foreign lock proceeds lock-free
    the moment its ambient deadline expires — the lock only saves
    redundant work, so dying on it would be worse than rebuilding."""
    import time as _time

    from eeg_dataanalysispackage_tpu.io import deadline as deadline_mod

    monkeypatch.setenv(feature_cache.ENV_LOCK_TIMEOUT, "30")
    cache = feature_cache.FeatureCache(str(tmp_path / "fc"))
    key = "v" * 40
    lock = cache._lock_path_for(key)
    os.makedirs(cache.directory, exist_ok=True)
    with open(lock, "w") as f:
        f.write("99999")
    t0 = _time.monotonic()
    with deadline_mod.deadline_scope(deadline_mod.Deadline(0.3)):
        slot = cache.begin_build(key)
    assert _time.monotonic() - t0 < 5.0
    assert slot._lock_path is None  # proceeding without the lock
    slot.release()
    assert os.path.exists(lock)  # the foreign lock was left alone
    os.unlink(lock)
