"""Edge-case parity semantics not covered by the reference fixtures.

Pin the Java behaviors found during review: Arrays.copyOfRange
zero-padding past the end of a recording, trailing-space info.txt
lines, and stale channel-index reuse across files of a run.
"""

import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.epochs import extractor
from eeg_dataanalysispackage_tpu.io import provider, sources


def make_vhdr(channels=("Fz", "Cz", "Pz"), resolution="0.1"):
    lines = [
        "Brain Vision Data Exchange Header File Version 1.0",
        "",
        "[Common Infos]",
        "DataFile=x.eeg",
        "MarkerFile=x.vmrk",
        "DataFormat=BINARY",
        "DataOrientation=MULTIPLEXED",
        f"NumberOfChannels={len(channels)}",
        "SamplingInterval=1000",
        "",
        "[Binary Infos]",
        "BinaryFormat=INT_16",
        "",
        "[Channel Infos]",
    ]
    for i, ch in enumerate(channels):
        lines.append(f"Ch{i+1}={ch},,{resolution},µV")
    return "\n".join(lines).encode()


def make_vmrk(positions_stimuli):
    lines = ["[Common Infos]", "DataFile=x.eeg", "", "[Marker Infos]"]
    for i, (pos, stim) in enumerate(positions_stimuli):
        lines.append(f"Mk{i+1}=Stimulus,S{stim:>3},{pos},1,0")
    return "\n".join(lines).encode()


def make_recording_fs(path_base, n_samples, positions_stimuli, channels=("Fz", "Cz", "Pz")):
    rng = np.random.RandomState(0)
    data = rng.randint(-1000, 1000, size=(n_samples, len(channels))).astype("<i2")
    fs = sources.InMemoryFileSystem()
    fs.write_bytes(path_base + ".vhdr", make_vhdr(channels))
    fs.write_bytes(path_base + ".vmrk", make_vmrk(positions_stimuli))
    fs.write_bytes(path_base + ".eeg", data.tobytes())
    return fs, data


def test_end_of_recording_window_zero_padded():
    """A marker whose window runs past the end is kept zero-padded,
    exactly as Arrays.copyOfRange does (from <= length, to beyond)."""
    n = 1000
    fs, data = make_recording_fs("rec", n, [(600, 1)])  # window [500, 1350)
    odp = provider.OfflineDataProvider(["rec.eeg", "1"], filesystem=fs)
    batch = odp.load()
    assert batch.epochs.shape == (1, 3, 750)
    # samples past the recording end are exactly zero minus baseline
    pad_region = batch.epochs[0, :, n - 600 :]  # beyond original length
    base_region = batch.epochs[0, :, : n - 600]
    assert np.all(pad_region == pad_region[:, :1])  # constant = -baseline
    assert not np.all(base_region == base_region[:, :1])


def test_window_starting_past_end_dropped():
    n = 1000
    fs, _ = make_recording_fs("rec", n, [(1200, 1)])  # from=1100 > length
    odp = provider.OfflineDataProvider(["rec.eeg", "1"], filesystem=fs)
    assert len(odp.load()) == 0


def test_window_from_equals_length_kept_all_zero():
    n = 1000
    fs, _ = make_recording_fs("rec", n, [(1100, 1)])  # from=1000 == length
    odp = provider.OfflineDataProvider(["rec.eeg", "1"], filesystem=fs)
    batch = odp.load()
    assert batch.epochs.shape == (1, 3, 750)
    assert np.all(batch.epochs == 0.0)


def test_info_txt_trailing_space_line_skipped():
    files = sources.parse_info_txt("A/a.eeg \nB/b.eeg 5\n \n")
    assert files == {"B/b.eeg": 5}


def test_info_txt_double_space_raises():
    # 'A/a.eeg  3' -> parts[1] == '' -> NumberFormatException in Java
    with pytest.raises(ValueError):
        sources.parse_info_txt("A/a.eeg  3\n")


def test_stale_channel_index_reused_across_files():
    """File 2 lacks 'fz'; the reference reuses the index resolved for
    file 1 (instance-field FZIndex), not channel 0."""
    fs1, d1 = make_recording_fs("a", 2000, [(500, 1)], channels=("EOG", "Fz", "Cz", "Pz"))
    fs2, d2 = make_recording_fs("b", 2000, [(500, 2)], channels=("X0", "X1", "Cz", "Pz"))
    fs = sources.InMemoryFileSystem({**fs1.files, **fs2.files})
    fs.write_bytes("info.txt", b"a.eeg 1\nb.eeg 1\n")
    odp = provider.OfflineDataProvider(["info.txt"], filesystem=fs)
    batch = odp.load()
    assert len(batch) == 2
    # second epoch's first channel must come from column 1 (stale fz
    # index from file 1), not column 0
    win = d2[400:1250, 1].astype(np.float32) * np.float32(0.1)
    expected = extractor.baseline_correct_f32(win.astype(np.float64)[None, None], 100)
    np.testing.assert_array_equal(
        batch.epochs[1, 0], expected[0, 0, 100:].astype(np.float64)
    )


def test_balance_state_spans_files():
    """Balance counters are global across an info.txt run."""
    fs1, _ = make_recording_fs("a", 3000, [(500, 1), (700, 2)])
    fs2, _ = make_recording_fs("b", 3000, [(500, 2), (700, 1)])
    fs = sources.InMemoryFileSystem({**fs1.files, **fs2.files})
    fs.write_bytes("info.txt", b"a.eeg 1\nb.eeg 1\n")
    batch = provider.OfflineDataProvider(["info.txt"], filesystem=fs).load()
    # file a: target kept (T1), non-target kept (N1);
    # file b: non-target kept (T1>=N1 -> N2), target kept (T<=N)
    assert batch.targets.tolist() == [1.0, 0.0, 0.0, 1.0]
