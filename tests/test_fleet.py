"""Replicated gateway fleet suite (gateway/fleet.py +
scheduler/lease.py).

The acceptance pins:

- **lease-race matrix** — two replicas race one record and exactly one
  claims; a stale lease is broken ONLY past the timeout AND with a
  provably dead holder pid; a replica unlinks only its OWN lease;
- **journal hardening** — corrupt records are quarantined to
  ``plan-<id>.json.corrupt`` (counted), a refused directory fsync is
  counted;
- **crash-only failover** — three REAL replica processes over one
  shared journal; the in-flight holder is SIGKILLed and a survivor
  completes its plan under the original id with byte-identical
  statistics, exactly once;
- **graceful drain** — a real SIGTERM makes a replica stop accepting
  (503), hand queued leases back to the fleet, finish in-flight work,
  and exit 0.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

import _synthetic
from eeg_dataanalysispackage_tpu import obs
from eeg_dataanalysispackage_tpu.gateway import FleetReplica
from eeg_dataanalysispackage_tpu.obs import chaos, domain as run_domain
from eeg_dataanalysispackage_tpu.pipeline import builder
from eeg_dataanalysispackage_tpu.scheduler import lease as lease_mod
from eeg_dataanalysispackage_tpu.scheduler.executor import PlanExecutor
from eeg_dataanalysispackage_tpu.scheduler.journal import PlanJournal

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_ambient():
    assert chaos.active_plan() is None
    assert run_domain.current() is None
    yield
    chaos.uninstall()
    assert run_domain.current() is None


@pytest.fixture(autouse=True)
def _fast_lease(monkeypatch):
    """A 1s break threshold so staleness is testable; individual tests
    that need a different value override the env themselves."""
    monkeypatch.setenv(lease_mod.ENV_LEASE_TIMEOUT, "1")


@pytest.fixture()
def session(tmp_path):
    return _synthetic.write_session(str(tmp_path), n_markers=60)


def _q(info, extra="", clf="logreg"):
    return (
        f"info_file={info}&fe=dwt-8&train_clf={clf}"
        "&config_step_size=1.0&config_num_iterations=20"
        "&config_mini_batch_fraction=1.0" + extra
    )


def _request(url, body=None, method="GET", headers=None, timeout=60):
    req = urllib.request.Request(
        url, data=body.encode() if body is not None else None,
        method=method, headers=headers or {},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _await(base, plan_id, deadline_s=300):
    start = time.monotonic()
    while time.monotonic() - start < deadline_s:
        _, payload = _request(f"{base}/plans/{plan_id}")
        if payload.get("state") in ("completed", "failed", "cancelled"):
            return payload["state"]
        time.sleep(0.05)
    raise AssertionError(f"{plan_id} never reached a terminal state")


def _stale_lease(journal_dir, plan_id, holder="gw-dead", pid=999999,
                 age_s=100.0, token=""):
    """A dead replica's lease: unknown pid, heartbeat long past the
    break threshold. ``token`` is the holder pid's start token (empty
    = pre-token lease; liveness is then pid-only)."""
    os.makedirs(journal_dir, exist_ok=True)
    path = os.path.join(journal_dir, f"plan-{plan_id}.lease")
    with open(path, "w") as f:
        f.write(f"{holder}\n{pid}\n{token}\n")
    old = time.time() - age_s
    os.utime(path, (old, old))
    return path


# -- lease-race matrix -------------------------------------------------


def test_two_replicas_race_exactly_one_claims(tmp_path):
    """N threads across two replica identities hammer one plan id:
    exactly one PlanLease is ever granted; every loser reads
    FOREIGN_HELD (never None, never a second lease)."""
    a = lease_mod.LeaseDir(str(tmp_path), holder="gw-a")
    b = lease_mod.LeaseDir(str(tmp_path), holder="gw-b")
    outcomes = []
    barrier = threading.Barrier(8)

    def race(directory):
        barrier.wait()
        outcomes.append(directory.try_claim("p0001"))

    threads = [
        threading.Thread(target=race, args=(d,))
        for d in (a, b) for _ in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wins = [o for o in outcomes if isinstance(o, lease_mod.PlanLease)]
    # one replica won; its OWN extra threads may share the held object
    # (same-process re-claim), the OTHER replica always reads foreign
    assert wins
    assert len({id(w) for w in wins}) == 1
    assert len({w.holder for w in wins}) == 1
    losses = [o for o in outcomes if not isinstance(o, lease_mod.PlanLease)]
    assert all(o is lease_mod.FOREIGN_HELD for o in losses)
    # at LEAST every thread of the losing replica lost (a winning-side
    # thread racing the claim registration may also read foreign)
    assert len(losses) >= 4
    # exactly one lease file, naming the winner
    with open(os.path.join(str(tmp_path), "plan-p0001.lease")) as f:
        assert f.readline().strip() == wins[0].holder


def test_stale_break_needs_timeout_and_dead_pid(tmp_path):
    """The break matrix: (old heartbeat, live pid) and (fresh
    heartbeat, dead pid) both stay FOREIGN_HELD; only (old heartbeat,
    dead pid) is broken and re-claimed."""
    d = lease_mod.LeaseDir(str(tmp_path), holder="gw-b")

    # live pid (this process), heartbeat far past the threshold
    _stale_lease(str(tmp_path), "p0001", holder="gw-a", pid=os.getpid())
    assert d.try_claim("p0001") is lease_mod.FOREIGN_HELD

    # dead pid, fresh heartbeat
    _stale_lease(str(tmp_path), "p0002", age_s=0.0)
    assert d.try_claim("p0002") is lease_mod.FOREIGN_HELD

    # dead pid AND old heartbeat: broken, claimed, counted
    before = lease_mod.stats()
    _stale_lease(str(tmp_path), "p0003")
    lease = d.try_claim("p0003", takeover=True)
    assert isinstance(lease, lease_mod.PlanLease)
    assert lease.holder == "gw-b"
    after = lease_mod.stats()
    assert after["breaks"] == before["breaks"] + 1
    assert after["takeovers"] == before["takeovers"] + 1


def test_release_unlinks_only_own_lease(tmp_path):
    """A holder whose lease was broken and re-taken by a peer must NOT
    unlink the peer's live claim (the BuildSlot.release rule)."""
    a = lease_mod.LeaseDir(str(tmp_path), holder="gw-a")
    lease = a.try_claim("p0001")
    assert isinstance(lease, lease_mod.PlanLease)
    # a peer broke the (by then stale) lease and re-claimed
    with open(lease.path, "w") as f:
        f.write(f"gw-b\n{os.getpid()}\n")
    a.release("p0001")
    assert os.path.exists(lease.path)
    with open(lease.path) as f:
        assert f.readline().strip() == "gw-b"
    # ... while releasing an owned lease does unlink it
    lease2 = a.try_claim("p0002")
    a.release("p0002")
    assert not os.path.exists(lease2.path)


def test_own_reclaim_returns_held_object(tmp_path):
    """Two threads of ONE replica claiming the same id share the held
    lease — a replica must never read itself as foreign."""
    d = lease_mod.LeaseDir(str(tmp_path), holder="gw-a")
    first = d.try_claim("p0001")
    second = d.try_claim("p0001")
    assert first is second


def test_racing_breakers_break_exactly_once(tmp_path):
    """Many threads across four replica identities race ONE stale
    lease: the break happens exactly once (break guard + atomic
    rename-capture), exactly one fresh claim is granted, and the lease
    file names that winner — never the double-execution interleaving
    A-unlink, A-create, B-unlink(-A's-fresh-lease), B-create."""
    _stale_lease(str(tmp_path), "p0001")
    dirs = [
        lease_mod.LeaseDir(str(tmp_path), holder=f"gw-{i}")
        for i in range(4)
    ]
    before = lease_mod.stats()
    outcomes = []
    lock = threading.Lock()
    barrier = threading.Barrier(8)

    def race(directory):
        barrier.wait()
        out = directory.try_claim("p0001", takeover=True)
        with lock:
            outcomes.append(out)

    threads = [
        threading.Thread(target=race, args=(d,))
        for d in dirs for _ in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wins = [o for o in outcomes if isinstance(o, lease_mod.PlanLease)]
    assert wins
    assert len({id(w) for w in wins}) == 1
    assert len({w.holder for w in wins}) == 1
    after = lease_mod.stats()
    assert after["breaks"] == before["breaks"] + 1
    with open(os.path.join(str(tmp_path), "plan-p0001.lease")) as f:
        assert f.readline().strip() == wins[0].holder
    # no break machinery left behind (guards, captured inodes)
    leftovers = [
        n for n in os.listdir(str(tmp_path))
        if ".breaking" in n or ".broken." in n
    ]
    assert leftovers == []


def test_pid_reuse_detected_by_start_token(tmp_path):
    """A recycled pid must not strand a plan: the lease records the
    holder pid's start token, so a live unrelated process wearing a
    dead holder's pid still reads as dead — while the genuinely live
    holder (matching token) keeps its claim."""
    token = lease_mod._pid_start_token(os.getpid())
    if token is None:
        pytest.skip("no /proc start token on this platform")
    d = lease_mod.LeaseDir(str(tmp_path), holder="gw-b")
    # this process's live pid but ANOTHER process's start token: the
    # recorded holder is dead, its pid recycled — breakable
    _stale_lease(str(tmp_path), "p0001", holder="gw-a",
                 pid=os.getpid(), token="1")
    assert isinstance(d.try_claim("p0001"), lease_mod.PlanLease)
    # same pid with the MATCHING token: genuinely alive, never broken
    _stale_lease(str(tmp_path), "p0002", holder="gw-a",
                 pid=os.getpid(), token=token)
    assert d.try_claim("p0002") is lease_mod.FOREIGN_HELD


def test_stale_break_guard_from_dead_breaker_is_cleared(tmp_path):
    """A breaker that died mid-break leaves its guard file behind; the
    next breaker captures the dead guard atomically and completes the
    break instead of wedging forever."""
    d = lease_mod.LeaseDir(str(tmp_path), holder="gw-b")
    path = _stale_lease(str(tmp_path), "p0001")
    guard = path + ".breaking"
    with open(guard, "w") as f:
        f.write("gw-dead\n999999\n\n")
    old = time.time() - 100
    os.utime(guard, (old, old))
    assert isinstance(d.try_claim("p0001"), lease_mod.PlanLease)
    assert not os.path.exists(guard)


def test_live_break_guard_defers_to_the_breaker(tmp_path):
    """A fresh guard held by a LIVE breaker means the takeover is
    already owned: a second breaker stands down (FOREIGN_HELD) and the
    stale lease is left for the guard holder."""
    d = lease_mod.LeaseDir(str(tmp_path), holder="gw-b")
    path = _stale_lease(str(tmp_path), "p0001")
    guard = path + ".breaking"
    with open(guard, "w") as f:
        f.write(f"gw-a\n{os.getpid()}\n\n")
    assert d.try_claim("p0001") is lease_mod.FOREIGN_HELD
    assert os.path.exists(path)
    assert os.path.exists(guard)


def test_heartbeat_failure_counted_not_fatal(tmp_path):
    """fleet.heartbeat chaos: the beat is skipped and counted; the
    lease simply ages toward breakability."""
    d = lease_mod.LeaseDir(str(tmp_path), holder="gw-a")
    lease = d.try_claim("p0001")
    before = lease_mod.stats()
    with chaos.faults("fleet.heartbeat:p=1.0"):
        assert lease.heartbeat() is False
    after = lease_mod.stats()
    assert after["heartbeat_failures"] == before["heartbeat_failures"] + 1
    assert lease.heartbeat() is True


def test_lease_claim_chaos_counted_not_fatal(tmp_path):
    """fleet.lease chaos: the claim attempt fails without telling the
    caller anything about ownership (None, counted) — the scan loop
    just retries next tick."""
    d = lease_mod.LeaseDir(str(tmp_path), holder="gw-a")
    before = lease_mod.stats()
    with chaos.faults("fleet.lease:p=1.0"):
        assert d.try_claim("p0001") is None
    after = lease_mod.stats()
    assert after["claim_failures"] == before["claim_failures"] + 1
    assert isinstance(d.try_claim("p0001"), lease_mod.PlanLease)


# -- journal hardening (satellites 1 + 2) ------------------------------


def test_corrupt_journal_record_quarantined(tmp_path):
    """A corrupt record must not wedge the scan loop: it is moved
    aside to plan-<id>.json.corrupt, counted, and entries() keeps
    going."""
    journal = PlanJournal(str(tmp_path))
    journal.record_submitted("p0001", "q1", meta={})
    with open(os.path.join(str(tmp_path), "plan-p0002.json"), "w") as f:
        f.write("{ not json")
    before = obs.metrics.snapshot()["counters"].get(
        "scheduler.journal_corrupt", 0
    )
    entries = journal.entries()
    assert [e["plan_id"] for e in entries] == ["p0001"]
    assert os.path.exists(
        os.path.join(str(tmp_path), "plan-p0002.json.corrupt")
    )
    assert not os.path.exists(
        os.path.join(str(tmp_path), "plan-p0002.json")
    )
    after = obs.metrics.snapshot()["counters"].get(
        "scheduler.journal_corrupt", 0
    )
    assert after == before + 1
    # entry() takes the same path
    with open(os.path.join(str(tmp_path), "plan-p0003.json"), "w") as f:
        f.write("also not json")
    assert journal.entry("p0003") is None
    assert os.path.exists(
        os.path.join(str(tmp_path), "plan-p0003.json.corrupt")
    )


def test_journal_dir_fsync_refusal_counted(tmp_path, monkeypatch):
    """A directory fsync the filesystem refuses is counted — the
    durability gap is visible, not silent."""
    journal = PlanJournal(str(tmp_path))
    monkeypatch.setattr(
        "eeg_dataanalysispackage_tpu.checkpoint.manager._fsync_directory",
        lambda directory: False,
    )
    before = obs.metrics.snapshot()["counters"].get(
        "scheduler.journal_dir_fsync_failed", 0
    )
    journal.record_submitted("p0001", "q", meta={})
    after = obs.metrics.snapshot()["counters"].get(
        "scheduler.journal_dir_fsync_failed", 0
    )
    assert after == before + 1
    # the record itself still landed (fsync is belt-and-braces)
    assert journal.entry("p0001")["state"] == "submitted"


# -- in-process fleet semantics ----------------------------------------


def test_takeover_executes_orphan_byte_identical(session, tmp_path):
    """A dead replica's write-ahead record (stale lease, dead pid) is
    claimed by a peer's scan loop and executed to completion under the
    ORIGINAL id with statistics byte-identical to a direct run, with
    the takeover attributed in the journal meta."""
    journal_dir = str(tmp_path / "journal")
    query = _q(session)
    twin = str(builder.PipelineBuilder(query).execute())

    journal = PlanJournal(journal_dir)
    journal.record_submitted(
        "p0001", query, meta={"idempotency_key": "k1"}
    )
    _stale_lease(journal_dir, "p0001")

    replica = FleetReplica(
        journal_dir=journal_dir, replica_id="gw-b",
        scan_interval_s=0.05,
    )
    replica.start()
    try:
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            entry = journal.entry("p0001")
            if entry and entry["state"] != "submitted":
                break
            time.sleep(0.05)
        assert entry["state"] == "completed"
        assert entry["statistics"] == twin
        assert entry["meta"]["fleet"] == {
            "replica": "gw-b", "takeover": True,
        }
        # keyed re-submit rejoins/replays the original id — the key
        # was journaled by the dead process, not by this replica
        code, payload = replica.server.submit_query(
            query, idempotency_key="k1"
        )
        assert code == 200
        assert payload["plan_id"] == "p0001"
        assert payload["idempotent_replay"] is True
    finally:
        replica.close()
    assert not os.path.exists(
        os.path.join(journal_dir, "plan-p0001.lease")
    )


def test_fresh_ids_never_collide_across_replicas(session, tmp_path):
    """Two replicas over one journal mint from identical local
    counters; the lease doubles as the cross-process id allocator, so
    both submissions land distinct ids and both complete."""
    journal_dir = str(tmp_path / "journal")
    a = FleetReplica(journal_dir=journal_dir, replica_id="gw-a",
                     scan_interval_s=5.0)
    b = FleetReplica(journal_dir=journal_dir, replica_id="gw-b",
                     scan_interval_s=5.0)
    a.start()
    b.start()
    try:
        _, pa = a.server.submit_query(_q(session) + "&dedup=false")
        _, pb = b.server.submit_query(_q(session) + "&dedup=false")
        assert pa["plan_id"] != pb["plan_id"]
        journal = PlanJournal(journal_dir)
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            states = {
                e["plan_id"]: e["state"] for e in journal.entries()
            }
            if len(states) == 2 and all(
                s != "submitted" for s in states.values()
            ):
                break
            time.sleep(0.05)
        assert states == {
            pa["plan_id"]: "completed", pb["plan_id"]: "completed",
        }
        # each completed by its accepting replica, no takeover
        for pid, rid in ((pa["plan_id"], "gw-a"), (pb["plan_id"], "gw-b")):
            meta = journal.entry(pid)["meta"]["fleet"]
            assert meta == {"replica": rid, "takeover": False}
    finally:
        a.close()
        b.close()


def test_fresh_id_skips_peer_record_when_claim_unavailable(
    session, tmp_path,
):
    """fleet.lease chaos makes every claim return None; a peer's
    journal record under the would-be fresh id must STILL be detected
    and skipped — overwriting it would erase a served result and
    resurface it as 'submitted' for the whole fleet to re-run."""
    journal_dir = str(tmp_path / "journal")
    ex = PlanExecutor(journal_dir=journal_dir, max_concurrent=1)
    ex.leases = lease_mod.LeaseDir(journal_dir, holder="gw-a")
    # a peer journals p0001 AFTER this executor seeded its id counter
    peer = PlanJournal(journal_dir)
    peer.record_completed("p0001", "peer-query", "peer-stats")
    try:
        with chaos.faults("fleet.lease:p=1.0"):
            handle = ex.submit(_q(session))
        assert handle.plan_id == "p0002"
        handle.result(timeout=300)
    finally:
        ex.close()
    # the peer's served result is untouched, ours landed beside it
    assert peer.entry("p0001")["statistics"] == "peer-stats"
    assert peer.entry("p0002")["state"] == "completed"


def test_concurrent_new_key_registers_exactly_one_plan(
    session, tmp_path,
):
    """Two replicas receive the SAME previously-unseen idempotency key
    at the same instant: the key-scoped registration lease serializes
    them — exactly one plan id is minted for the key and the journal
    audit shows exactly one record."""
    journal_dir = str(tmp_path / "journal")
    a = FleetReplica(journal_dir=journal_dir, replica_id="gw-a",
                     scan_interval_s=5.0)
    b = FleetReplica(journal_dir=journal_dir, replica_id="gw-b",
                     scan_interval_s=5.0)
    a.start()
    b.start()
    query = _q(session)
    results = {}
    barrier = threading.Barrier(2)

    def go(name, replica):
        barrier.wait()
        results[name] = replica.server.submit_query(
            query, idempotency_key="race-key"
        )
    try:
        threads = [
            threading.Thread(target=go, args=(n, r))
            for n, r in (("a", a), ("b", b))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        ids = {payload["plan_id"] for _, payload in results.values()}
        assert len(ids) == 1, results
        (plan_id,) = ids
        journal = PlanJournal(journal_dir)
        assert [e["plan_id"] for e in journal.entries()] == [plan_id]
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            entry = journal.entry(plan_id)
            if entry["state"] != "submitted":
                break
            time.sleep(0.05)
        assert entry["state"] == "completed"
        # the registration claim never outlives the write-ahead record
        assert not [
            n for n in os.listdir(journal_dir) if n.startswith("key-")
        ]
    finally:
        a.close()
        b.close()


def test_key_claim_degrades_after_wait_budget(session, tmp_path):
    """A peer that took the key registration claim and then never
    journaled its binding (died mid-registration, pre-timeout) must
    not wedge submissions: past the wait budget the submit degrades to
    a best-effort mint, counted."""
    journal_dir = str(tmp_path / "journal")
    ex = PlanExecutor(journal_dir=journal_dir, max_concurrent=1)
    ex.leases = lease_mod.LeaseDir(journal_dir, holder="gw-a")
    ex.key_claim_wait_s = 0.2
    # a live foreign registrant that never journals its binding
    peer = lease_mod.LeaseDir(journal_dir, holder="gw-peer")
    assert isinstance(
        peer.try_claim(lease_mod.key_claim_id("k-stuck")),
        lease_mod.PlanLease,
    )
    before = obs.metrics.snapshot()["counters"].get(
        "scheduler.key_claim_degraded", 0
    )
    try:
        handle = ex.submit(_q(session), idempotency_key="k-stuck")
        handle.result(timeout=300)
    finally:
        ex.close()
    after = obs.metrics.snapshot()["counters"].get(
        "scheduler.key_claim_degraded", 0
    )
    assert after == before + 1
    assert PlanJournal(journal_dir).entry(
        handle.plan_id
    )["state"] == "completed"


def test_keyed_resubmit_of_peer_held_plan_names_owner(session, tmp_path):
    """A keyed re-submit of a plan a LIVE peer holds must not
    double-execute: the gateway answers 200 with the original id and
    the owner hint."""
    journal_dir = str(tmp_path / "journal")
    query = _q(session)
    journal = PlanJournal(journal_dir)
    journal.record_submitted(
        "p0001", query, meta={"idempotency_key": "k1"}
    )
    # a LIVE peer's lease (this process's pid, fresh heartbeat)
    _stale_lease(journal_dir, "p0001", holder="gw-a",
                 pid=os.getpid(), age_s=0.0)

    replica = FleetReplica(
        journal_dir=journal_dir, replica_id="gw-b",
        scan_interval_s=0.05,
    )
    replica.start()
    try:
        code, payload = replica.server.submit_query(
            query, idempotency_key="k1"
        )
        assert code == 200
        assert payload["plan_id"] == "p0001"
        assert payload["idempotent_replay"] is True
        assert payload["owner"] == "gw-a"
        # the scan loop must also have refused it (live holder)
        assert journal.entry("p0001")["state"] == "submitted"
    finally:
        replica.close()
    # gw-b never owned the lease, so it must still be gw-a's
    with open(os.path.join(journal_dir, "plan-p0001.lease")) as f:
        assert f.readline().strip() == "gw-a"


def test_drain_releases_queued_finishes_inflight(session, tmp_path):
    """drain(): new submissions 503, queued plans handed back to the
    fleet (journal 'submitted', lease gone), the in-flight plan
    finished — and a peer then completes the released plan."""
    journal_dir = str(tmp_path / "journal")
    slow = (
        f"info_file={session}&fe=dwt-8&train_clf=logreg"
        "&config_step_size=0.5&config_num_iterations=1500000"
        "&config_mini_batch_fraction=1.0"
    )
    a = FleetReplica(
        journal_dir=journal_dir, replica_id="gw-a",
        scan_interval_s=5.0, max_concurrent=1,
    )
    a.start()
    _, inflight = a.server.submit_query(slow)
    _, queued = a.server.submit_query(_q(session))
    outcome = {}

    def _drain():
        outcome.update(a.drain(timeout_s=300.0))

    t = threading.Thread(target=_drain)
    t.start()
    try:
        while not a.server.draining:
            time.sleep(0.01)
        code, payload = a.server.submit_query(_q(session))
        assert code == 503
        assert payload["draining"] is True
    finally:
        t.join(timeout=300)
    assert not t.is_alive()
    assert outcome["finished"] == [inflight["plan_id"]]
    assert outcome["released"] == [queued["plan_id"]]
    journal = PlanJournal(journal_dir)
    assert journal.entry(inflight["plan_id"])["state"] == "completed"
    assert journal.entry(queued["plan_id"])["state"] == "submitted"
    assert not os.path.exists(
        os.path.join(journal_dir, f"plan-{queued['plan_id']}.lease")
    )
    # a peer picks the released plan up without any staleness wait
    b = FleetReplica(
        journal_dir=journal_dir, replica_id="gw-b",
        scan_interval_s=0.05,
    )
    b.start()
    try:
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            entry = journal.entry(queued["plan_id"])
            if entry["state"] != "submitted":
                break
            time.sleep(0.05)
        assert entry["state"] == "completed"
        assert entry["meta"]["fleet"]["replica"] == "gw-b"
    finally:
        b.close()


def test_healthz_liveness_vs_readyz_readiness(session, tmp_path):
    """/healthz answers 200 whenever the process is alive; /readyz
    turns 503 the moment the journal directory stops being writable —
    the alive-but-unroutable split."""
    journal_dir = str(tmp_path / "journal")
    replica = FleetReplica(
        journal_dir=journal_dir, replica_id="gw-a",
        scan_interval_s=5.0,
    )
    host, port = replica.start()
    base = f"http://{host}:{port}"
    try:
        code, payload = _request(f"{base}/healthz")
        assert code == 200 and payload["ok"] is True
        code, payload = _request(f"{base}/readyz")
        assert code == 200 and payload["ready"] is True
        assert payload["replica"] == "gw-a"

        # break the journal dir out from under the replica (a regular
        # file where the directory was — the probe's O_EXCL create
        # fails even for root, unlike a chmod)
        os.rename(journal_dir, journal_dir + ".gone")
        with open(journal_dir, "w") as f:
            f.write("not a directory")
        try:
            code, payload = _request(f"{base}/readyz")
            assert code == 503
            assert payload["ready"] is False
            assert any(
                "journal" in r for r in payload["reasons"]
            )
            # still ALIVE — a restart loop would be the wrong fix
            code, _ = _request(f"{base}/healthz")
            assert code == 200
        finally:
            os.unlink(journal_dir)
            os.rename(journal_dir + ".gone", journal_dir)
        code, _ = _request(f"{base}/readyz")
        assert code == 200
    finally:
        replica.close()


def test_stats_and_list_carry_fleet_attribution(session, tmp_path):
    """/stats grows the fleet block (replica id, lease counters) and
    /plans rows name a peer owner for peer-held records."""
    journal_dir = str(tmp_path / "journal")
    journal = PlanJournal(journal_dir)
    journal.record_submitted("p0777", _q(session), meta={})
    _stale_lease(journal_dir, "p0777", holder="gw-peer",
                 pid=os.getpid(), age_s=0.0)
    replica = FleetReplica(
        journal_dir=journal_dir, replica_id="gw-a",
        scan_interval_s=5.0,
    )
    host, port = replica.start()
    base = f"http://{host}:{port}"
    try:
        _, stats = _request(f"{base}/stats")
        fleet = stats["fleet"]
        assert fleet["replica"] == "gw-a"
        assert fleet["draining"] is False
        assert set(fleet) >= {
            "claims", "takeovers", "breaks", "heartbeats",
            "heartbeat_failures", "claim_failures", "held_leases",
        }
        _, listing = _request(f"{base}/plans")
        row = next(
            p for p in listing["plans"] if p["plan_id"] == "p0777"
        )
        assert row["owner"] == "gw-peer"
        _, status = _request(f"{base}/plans/p0777")
        assert status["owner"] == "gw-peer"
    finally:
        replica.close()


def test_plan_admin_fleet_view(session, tmp_path, capsys):
    """tools/plan_admin.py fleet: leases joined to records, staleness
    and unleased-submitted rows called out."""
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        import plan_admin
    finally:
        sys.path.pop(0)
    journal_dir = str(tmp_path / "journal")
    journal = PlanJournal(journal_dir)
    journal.record_submitted("p0001", _q(session), meta={})
    _stale_lease(journal_dir, "p0001")  # dead holder, old heartbeat
    journal.record_submitted("p0002", _q(session), meta={})

    rc = plan_admin.main(["fleet", "--journal", journal_dir])
    out = capsys.readouterr().out
    assert rc == 0
    assert "p0001" in out and "STALE" in out and "gw-dead" in out
    assert "p0002" in out and "unleased" in out
    assert "1 stale" in out and "1 unleased" in out


# -- the real-process acceptance pins ----------------------------------


def _spawn_replica(replica_id, journal_dir, env):
    proc = subprocess.Popen(
        [
            sys.executable, "-m",
            "eeg_dataanalysispackage_tpu.gateway",
            "--port", "0", "--journal-dir", journal_dir,
            "--max-concurrent", "1", "--drain-timeout-s", "300",
            "--fleet", "--replica-id", replica_id,
        ],
        env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True,
    )
    line = proc.stdout.readline()
    assert "listening on " in line, line
    return proc, line.split("listening on ", 1)[1].split()[0]


def _fleet_env(trace_dir=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["EEG_TPU_LEASE_TIMEOUT_S"] = "1"
    env["EEG_TPU_FLEET_SCAN_INTERVAL_S"] = "0.1"
    env.pop("EEG_TPU_FAULTS", None)
    env.pop("EEG_TPU_RUN_REPORT_DIR", None)
    if trace_dir is not None:
        env["EEG_TPU_TRACE_DIR"] = trace_dir
    else:
        env.pop("EEG_TPU_TRACE_DIR", None)
    return env


def _get_text(url, timeout=30):
    """GET a non-JSON endpoint (/metrics is Prometheus text)."""
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


@pytest.mark.chaos
def test_kill_one_of_three_replicas_peer_completes(session, tmp_path,
                                                   capsys):
    """THE fleet acceptance pin: 3 real replica processes over one
    journal; SIGKILL the one executing a plan; a survivor breaks the
    dead lease, completes the plan under its original id with
    statistics byte-identical to an uninterrupted twin, exactly once;
    a keyed re-submit to the third replica replays it; the survivors
    then drain cleanly on real SIGTERM. The observability half
    (ISSUE 19): the trace minted at submit SURVIVES the kill — the
    takeover segment continues the same trace id, and ``plan_admin
    trace`` stitches the dead holder's and the survivor's segments
    into ONE tree with the takeover boundary visible; the survivors'
    /metrics exposition carries the takeover."""
    journal_dir = str(tmp_path / "journal")
    trace_dir = str(tmp_path / "traces")
    heavy = (
        f"info_file={session}&fe=dwt-8&train_clf=logreg"
        "&config_step_size=0.5&config_num_iterations=1500000"
        "&config_mini_batch_fraction=1.0"
    )
    twin = str(builder.PipelineBuilder(heavy).execute())
    env = _fleet_env(trace_dir=trace_dir)

    procs, urls = [], []
    try:
        for rid in ("gw-a", "gw-b", "gw-c"):
            proc, url = _spawn_replica(rid, journal_dir, env)
            procs.append(proc)
            urls.append(url)
        for url in urls:
            deadline = time.monotonic() + 120
            while True:
                code, _ = _request(f"{url}/readyz", timeout=5)
                if code == 200:
                    break
                assert time.monotonic() < deadline, f"{url} not ready"
                time.sleep(0.1)

        code, payload = _request(
            f"{urls[0]}/plans", body=heavy, method="POST",
            headers={"X-Idempotency-Key": "fleet-pin",
                     "X-Trace-Id": "fleet-pin-trace"},
        )
        assert code == 201, payload
        plan_id = payload["plan_id"]
        # the inbound trace id is honored, not re-minted
        assert payload["trace_id"] == "fleet-pin-trace"

        # kill the holder provably mid-execution
        deadline = time.monotonic() + 240
        while True:
            _, status = _request(f"{urls[0]}/plans/{plan_id}")
            if status.get("state") == "running":
                break
            assert status.get("state") not in ("completed", "failed"), (
                "plan finished before the kill — raise the iteration "
                "count"
            )
            assert time.monotonic() < deadline
            time.sleep(0.02)
        procs[0].kill()
        assert procs[0].wait(timeout=60) == -signal.SIGKILL

        # a survivor completes it under the ORIGINAL id
        assert _await(urls[1], plan_id, deadline_s=300) == "completed"
        entry = PlanJournal(journal_dir).entry(plan_id)
        assert entry["statistics"] == twin
        fleet_meta = entry["meta"]["fleet"]
        assert fleet_meta["takeover"] is True
        assert fleet_meta["replica"] in ("gw-b", "gw-c")

        # exactly-once across the fleet: one terminal record, and the
        # survivors' own completion counters sum to exactly this one
        # execution
        entries = PlanJournal(journal_dir).entries()
        assert [e["plan_id"] for e in entries] == [plan_id]
        completed = 0
        for url in urls[1:]:
            _, stats = _request(f"{url}/stats")
            completed += int(
                stats["scheduler"].get("scheduler.completed", 0)
            )
            assert stats["fleet"]["replica"] in ("gw-b", "gw-c")
        assert completed == 1

        # the survivors' /metrics exposition (ISSUE 19): build_info
        # names the replica, the completion and takeover counters sum
        # across the fleet to exactly this one taken-over execution
        scraped_completed = scraped_takeovers = 0
        for rid, url in zip(("gw-b", "gw-c"), urls[1:]):
            code, text = _get_text(f"{url}/metrics")
            assert code == 200
            assert f'eeg_tpu_build_info{{replica="{rid}"}} 1' in text
            for line in text.splitlines():
                if line.startswith("eeg_tpu_scheduler_completed_total "):
                    scraped_completed += int(float(line.split()[1]))
                if line.startswith("eeg_tpu_lease_takeovers_total "):
                    scraped_takeovers += int(float(line.split()[1]))
        assert scraped_completed == 1
        assert scraped_takeovers == 1

        # keyed re-submit to the OTHER survivor: replayed, original id
        code, payload = _request(
            f"{urls[2]}/plans", body=heavy, method="POST",
            headers={"X-Idempotency-Key": "fleet-pin"},
        )
        assert code == 200
        assert payload["plan_id"] == plan_id
        assert payload["idempotent_replay"] is True

        # graceful close-out: REAL SIGTERM, both survivors exit 0
        for proc in procs[1:]:
            proc.send_signal(signal.SIGTERM)
        for proc in procs[1:]:
            assert proc.wait(timeout=120) == 0
        assert not [
            n for n in os.listdir(journal_dir)
            if n.endswith(".lease")
        ]

        # THE trace pin (ISSUE 19): plan_admin stitches the dead
        # holder's segment and the survivor's takeover segment into
        # ONE tree under the submit-time trace id, takeover boundary
        # annotated — the kill shows up as a seam in one trace, not
        # as two unrelated traces
        sys.path.insert(0, os.path.join(_REPO, "tools"))
        try:
            import plan_admin
        finally:
            sys.path.pop(0)
        rc = plan_admin.main([
            "trace", plan_id, "--journal", journal_dir,
            "--trace-dir", trace_dir,
        ])
        out = capsys.readouterr().out
        assert rc == 0, out
        assert "trace fleet-pin-trace" in out
        assert "2 segment(s)" in out
        assert "segment gw-a" in out
        takeover_replica = fleet_meta["replica"]
        assert f"segment {takeover_replica}" in out
        assert "TAKEOVER boundary" in out
        # the victim's segment died mid-span — the stitcher must say
        # so rather than invent an end
        assert "UNFINISHED" in out
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()


@pytest.mark.chaos
def test_sigterm_drain_real_process(session, tmp_path):
    """The drain satellite against a real process: SIGTERM mid-plan →
    the in-flight plan FINISHES, the queued plan is handed back
    (journal 'submitted', lease released), exit code 0."""
    journal_dir = str(tmp_path / "journal")
    slow = (
        f"info_file={session}&fe=dwt-8&train_clf=logreg"
        "&config_step_size=0.5&config_num_iterations=1500000"
        "&config_mini_batch_fraction=1.0"
    )
    proc, url = _spawn_replica("gw-a", journal_dir, _fleet_env())
    try:
        deadline = time.monotonic() + 120
        while True:
            code, _ = _request(f"{url}/readyz", timeout=5)
            if code == 200:
                break
            assert time.monotonic() < deadline
            time.sleep(0.1)
        _, inflight = _request(
            f"{url}/plans", body=slow, method="POST"
        )
        _, queued = _request(
            f"{url}/plans", body=_q(session), method="POST"
        )
        # SIGTERM once the slow plan is genuinely running
        deadline = time.monotonic() + 240
        while True:
            _, status = _request(
                f"{url}/plans/{inflight['plan_id']}"
            )
            if status.get("state") == "running":
                break
            assert time.monotonic() < deadline
            time.sleep(0.02)
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=300) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
    journal = PlanJournal(journal_dir)
    assert journal.entry(inflight["plan_id"])["state"] == "completed"
    assert journal.entry(queued["plan_id"])["state"] == "submitted"
    assert not os.path.exists(
        os.path.join(journal_dir, f"plan-{queued['plan_id']}.lease")
    )


# -- device-aware placement + pod routing ------------------------------


def test_readyz_flags_exhausted_device_pool(tmp_path, monkeypatch):
    """/readyz turns 503 with evidence when every pool ordinal is
    held elsewhere AND plans are waiting on them — a replica that can
    accept but never place is unroutable; /metrics and /stats carry
    the device-pool gauges either way."""
    from eeg_dataanalysispackage_tpu.scheduler import placement

    monkeypatch.setenv(placement.ENV_DEVICE_POOL, "1")
    journal_dir = str(tmp_path / "journal")
    os.makedirs(journal_dir)
    replica = FleetReplica(
        journal_dir=journal_dir, replica_id="gw-a",
        scan_interval_s=5.0,
    )
    host, port = replica.start()
    base = f"http://{host}:{port}"
    peer_leases = lease_mod.LeaseDir(journal_dir, holder="gw-peer")
    peer_pool = placement.DevicePool(peer_leases, size=1)
    try:
        code, payload = _request(f"{base}/readyz")
        assert code == 200 and payload["ready"] is True

        # the peer holds the only ordinal and a plan waits on it
        blocker = peer_pool.admit(
            "blocker",
            {"devices": 1, "hosts": 1, "memory_class": "light"},
        )
        assert isinstance(blocker, placement.DeviceGrant)
        assert peer_pool.admit(
            "waiter",
            {"devices": 1, "hosts": 1, "memory_class": "light"},
        ) is None

        code, payload = _request(f"{base}/readyz")
        assert code == 503 and payload["ready"] is False
        reason = " ".join(payload["reasons"])
        assert "device pool exhausted" in reason
        assert "waiter" in reason  # names the starving plan

        # still ALIVE, and the exposition carries the pool state
        code, _ = _request(f"{base}/healthz")
        assert code == 200
        code, text = _get_text(f"{base}/metrics")
        assert code == 200
        assert "eeg_tpu_fleet_devices_held" in text
        assert "eeg_tpu_fleet_devices_free 0" in text
        assert "eeg_tpu_fleet_plans_waiting_placement 1" in text
        code, stats = _request(f"{base}/stats")
        pool_block = stats["fleet"]["device_pool"]
        assert pool_block["size"] == 1
        assert pool_block["free"] == 0
        assert pool_block["oldest_waiting"] == "waiter"

        # freeing the ordinal restores readiness
        blocker.release()
        peer_pool.clear_waiting("waiter")
        code, _ = _request(f"{base}/readyz")
        assert code == 200
    finally:
        replica.close()


def test_pod_assist_enlists_peer_byte_identical(session, tmp_path,
                                                monkeypatch):
    """The pod routing acceptance: a ``processes=2`` plan submitted
    through the fleet completes via pod-assist — the winning replica
    drives its own process-0 member, a peer replica claims the
    ``assist:`` rank lease and contributes the rank-1 worker — with
    statistics byte-identical to the solo single-process run."""
    journal_dir = str(tmp_path / "journal")
    extra = "&cv=2&sweep=lr:1.0,0.5&cache=false"
    twin = str(builder.PipelineBuilder(_q(session, extra)).execute())
    # the members bootstrap their own fresh processes; give each the
    # pinned 2-virtual-device host the pod parity suite runs on
    monkeypatch.setenv(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=2"
    )
    before = obs.metrics.snapshot()["counters"]

    a = FleetReplica(journal_dir=journal_dir, replica_id="gw-a",
                     scan_interval_s=0.05)
    b = FleetReplica(journal_dir=journal_dir, replica_id="gw-b",
                     scan_interval_s=0.05)
    a.start()
    b.start()
    journal = PlanJournal(journal_dir)
    try:
        code, payload = a.server.submit_query(
            _q(session, extra + "&processes=2")
        )
        assert code == 201, payload
        plan_id = payload["plan_id"]
        deadline = time.monotonic() + 600
        entry = None
        while time.monotonic() < deadline:
            entry = journal.entry(plan_id)
            if entry and entry["state"] in ("completed", "failed"):
                break
            time.sleep(0.1)
        assert entry is not None and entry["state"] == "completed", (
            entry
        )
        assert entry["statistics"] == twin
        after = obs.metrics.snapshot()["counters"]
        delta = lambda k: after.get(k, 0) - before.get(k, 0)  # noqa: E731
        assert delta("fleet.pod_assist_requests") >= 1
        assert delta("fleet.pod_assist_completed") >= 1
        # the peer really contributed a rank, and nothing degraded
        assert delta("fleet.pod_assist_workers") >= 1
        assert delta("fleet.pod_assist_degraded") == 0
        # the assist record never outlives its run
        assert journal.assist_entries() == []
    finally:
        a.close()
        b.close()
    leftover = [
        n for n in os.listdir(journal_dir)
        if n.startswith("assist-") and n.endswith(".lease")
    ]
    assert leftover == []


def test_sigkilled_pod_coordinator_degrades_not_wedges(
        session, tmp_path, monkeypatch):
    """A coordinator pod process that dies (SIGKILL, no goodbye) must
    degrade the plan down the existing pod ladder — inline execution,
    single-host rung, byte-identical statistics — never wedge the
    fleet or leave the assist record behind."""
    from eeg_dataanalysispackage_tpu.parallel import pod as pod_mod

    journal_dir = str(tmp_path / "journal")
    twin = str(builder.PipelineBuilder(_q(session)).execute())
    real_spawn = pod_mod.spawn_pod_member
    killed = []

    def spawn_then_sigkill(*args, **kwargs):
        child = real_spawn(*args, **kwargs)
        child.kill()
        killed.append(child)
        return child

    monkeypatch.setattr(pod_mod, "spawn_pod_member", spawn_then_sigkill)
    before = obs.metrics.snapshot()["counters"]
    replica = FleetReplica(journal_dir=journal_dir, replica_id="gw-a",
                           scan_interval_s=0.05)
    replica.start()
    journal = PlanJournal(journal_dir)
    try:
        code, payload = replica.server.submit_query(
            _q(session, "&processes=2")
        )
        assert code == 201, payload
        plan_id = payload["plan_id"]
        deadline = time.monotonic() + 300
        entry = None
        while time.monotonic() < deadline:
            entry = journal.entry(plan_id)
            if entry and entry["state"] in ("completed", "failed"):
                break
            time.sleep(0.1)
        assert killed, "the coordinator member was never spawned"
        assert entry is not None and entry["state"] == "completed", (
            entry
        )
        # the ladder's parity pin: degraded == solo, byte-identical
        assert entry["statistics"] == twin
        after = obs.metrics.snapshot()["counters"]
        assert after.get("fleet.pod_assist_degraded", 0) \
            > before.get("fleet.pod_assist_degraded", 0)
        assert journal.assist_entries() == []
    finally:
        replica.close()


def test_dead_coordinators_assist_record_cleared_by_peer(tmp_path):
    """A SIGKILLed coordinator's podassist record must not make every
    peer scan try to staff a pod nobody coordinates: a provably dead
    writer (pid + start token) is cleared on the next scan pass."""
    journal_dir = str(tmp_path / "journal")
    replica = FleetReplica(journal_dir=journal_dir, replica_id="gw-b",
                           scan_interval_s=5.0)
    journal = PlanJournal(journal_dir)
    try:
        journal.record_assist(
            "p0001", "127.0.0.1:45555", 2, holder="gw-dead",
            pid=999999, start_token="", query="info_file=/x",
        )
        assert len(journal.assist_entries()) == 1
        spawned = replica.pod_assist.scan_assists()
        assert spawned == []
        assert journal.assist_entries() == []
    finally:
        replica.close()
