"""Multi-host runtime surface, exercised single-process.

Real DCN needs multiple hosts; what is testable hermetically is the
single-process degeneration (the same code paths a laptop run takes)
plus the 2-D hosts x data mesh structure itself: an 8-device CPU mesh
reshaped to (2, 4) stands in for 2 hosts x 4 chips, and the flagship
train step must produce the same result sharded over both axes as it
does single-device.
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from eeg_dataanalysispackage_tpu.parallel import (
    distributed,
    mesh as pmesh,
    streaming,
    train as ptrain,
)


@pytest.fixture(scope="module")
def devices8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    return jax.devices()[:8]


def test_initialize_single_process_noop():
    distributed.initialize()  # no coordinator configured -> no-op
    assert jax.process_count() == 1


def test_hybrid_mesh_degenerates_single_process(devices8):
    mesh = distributed.hybrid_mesh()
    assert mesh.axis_names == (distributed.DCN_AXIS, pmesh.DATA_AXIS)
    assert mesh.shape[distributed.DCN_AXIS] == 1
    assert mesh.shape[pmesh.DATA_AXIS] == jax.local_device_count()


def test_hybrid_mesh_rejects_bad_ici_shape():
    with pytest.raises(ValueError, match="local devices"):
        distributed.hybrid_mesh(ici_shape=(3,))


def test_batch_spec_covers_dcn_and_data_axes(devices8):
    mesh = distributed.hybrid_mesh()
    spec = distributed.batch_spec(mesh)
    assert spec == P((distributed.DCN_AXIS, pmesh.DATA_AXIS))
    data_only = pmesh.make_mesh(8)
    assert distributed.batch_spec(data_only) == P(pmesh.DATA_AXIS)
    time_only = pmesh.make_mesh(8, axes=(pmesh.TIME_AXIS,))
    with pytest.raises(ValueError, match="no data-parallel axis"):
        distributed.batch_spec(time_only)


def test_stage_global_batch_single_process(devices8):
    mesh = distributed.hybrid_mesh()
    x = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
    arr = distributed.stage_global_batch(x, mesh)
    assert arr.shape == (16, 3)
    np.testing.assert_array_equal(np.asarray(arr), x)
    # the leading axis is sharded over hosts*data
    assert arr.sharding.spec == distributed.batch_spec(mesh)


def test_replicate_across_hosts_single_process(devices8):
    mesh = distributed.hybrid_mesh()
    params = {"w": np.ones((4, 2), np.float32), "b": np.zeros(2, np.float32)}
    rep = distributed.replicate_across_hosts(params, mesh)
    assert rep["w"].sharding.is_fully_replicated
    np.testing.assert_array_equal(np.asarray(rep["w"]), params["w"])


def test_train_step_on_hosts_by_data_mesh(devices8):
    """Flagship train step over a 2-D (2 hosts x 4 chips) mesh matches
    the single-device result — the sharding layout a 2-host pod run
    would use, minus the DCN wire."""
    mesh2d = Mesh(
        np.array(devices8).reshape(2, 4),
        (distributed.DCN_AXIS, pmesh.DATA_AXIS),
    )
    rng = np.random.RandomState(0)
    epochs = rng.randn(24, 3, 750).astype(np.float32)
    labels = (rng.rand(24) > 0.5).astype(np.float32)

    init_state, train_step = ptrain.make_train_step()
    state0 = init_state(jax.random.PRNGKey(0))
    mask = np.ones(24, np.float32)
    state_ref, loss_ref = train_step(state0, epochs, labels, mask)

    sharding = NamedSharding(mesh2d, distributed.batch_spec(mesh2d))
    ep = jax.device_put(epochs, sharding)
    lb = jax.device_put(labels, sharding)
    mk = jax.device_put(mask, sharding)
    state0b = init_state(jax.random.PRNGKey(0))
    state0b = {
        "params": jax.device_put(
            state0b["params"], NamedSharding(mesh2d, P())
        ),
        "opt": state0b["opt"],
    }
    state_dist, loss_dist = train_step(state0b, ep, lb, mk)

    np.testing.assert_allclose(float(loss_dist), float(loss_ref), atol=1e-6)
    for k in state_ref["params"]:
        np.testing.assert_allclose(
            np.asarray(state_dist["params"][k]),
            np.asarray(state_ref["params"][k]),
            atol=1e-5,
        )


def test_two_process_gloo_collectives():
    """Real multi-process validation: two OS processes bootstrap via
    distributed.initialize, build a hosts x data hybrid mesh, stage
    process-local shards into one global batch, and run cross-process
    collectives (gloo) — the CPU stand-in for the DCN path the same
    code takes on a multi-host TPU pod."""
    import json
    import os
    import socket
    import subprocess
    import sys

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    worker = os.path.join(os.path.dirname(__file__), "_distributed_worker.py")
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(pid),
            XLA_FLAGS="--xla_force_host_platform_device_count=2",
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, worker],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        for p in procs:  # reap stragglers if a peer failed or hung
            if p.poll() is None:
                p.kill()
                p.wait()

    # global batch rows: [0..5]+0 (proc 0), [0..5]+10 (proc 1)
    for o in outs:
        assert o["procs"] == 2 and o["devices"] == 4
        assert o["mesh"] == {"hosts": 2, "data": 2}
        assert o["total"] == 15.0 + 75.0
        assert o["wsum"] == 6.0
        assert o["grad"] == [26.0, 30.0, 34.0]  # global column sums

    # the full train step and the sequence-parallel streaming
    # extractor must agree across processes and with a single-process
    # run of the identical code on the same global data
    rng = np.random.RandomState(0)
    epochs_global = rng.randn(4, 3, 750).astype(np.float32)
    labels_global = (rng.rand(4) > 0.5).astype(np.float32)
    init_state, train_step = ptrain.make_train_step()
    _, ref_loss = train_step(
        init_state(jax.random.PRNGKey(0)),
        epochs_global,
        labels_global,
        np.ones(4, np.float32),
    )

    rng2 = np.random.RandomState(1)
    sig_global = rng2.randn(2, 2048).astype(np.float32) * 30.0
    tmesh = pmesh.make_mesh(4, axes=(pmesh.TIME_AXIS,))
    extract = streaming.make_streaming_extractor(tmesh, window=512, stride=256)
    ref_feats = extract(streaming.stage_recording(sig_global, tmesh))
    ref_sum = float(np.asarray(ref_feats).sum())

    assert outs[0]["loss"] == outs[1]["loss"]
    assert outs[0]["stream_sum"] == outs[1]["stream_sum"]
    np.testing.assert_allclose(outs[0]["loss"], float(ref_loss), rtol=1e-5)
    assert outs[0]["stream_shape"] == list(ref_feats.shape) == [8, 32]
    np.testing.assert_allclose(outs[0]["stream_sum"], ref_sum, rtol=1e-5)

    # sequence-parallel marker ingest: each worker verified the
    # DCN-crossing halo against the single-device featurizer itself
    for o in outs:
        # 4 markers -> 3 kept (the order-dependent balance scan drops
        # the last non-target once non-targets outnumber targets)
        assert o["ingest_rows"] == 3
        assert o["ingest_dev"] <= 5e-6, o["ingest_dev"]
