"""Double-buffered ingest/compute overlap (io/staging stage_fn +
provider overlap mode + parallel/train.train_over_recordings).

The contract: overlap reschedules work onto the staging producer
thread, it never changes results — bit-identical epoch order and
values at any prefetch depth — and every staging safety property
(poison delivery, stop-aware shutdown, the consumer watchdog, the
``staging.producer`` chaos point) applies to the overlapped producer
unchanged.
"""

import threading
import time

import numpy as np
import pytest

import _synthetic
from eeg_dataanalysispackage_tpu import obs
from eeg_dataanalysispackage_tpu.io import provider, staging
from eeg_dataanalysispackage_tpu.pipeline import builder


# -- staging.prefetch stage_fn semantics --------------------------------


def test_stage_fn_preserves_order_at_any_depth():
    items = list(range(40))
    want = [i * 10 for i in items]
    for depth in (1, 2, 7):
        got = list(
            staging.prefetch(
                iter(items), stage_fn=lambda i: i * 10,
                buffer_size=depth,
            )
        )
        assert got == want, depth


def test_stage_fn_error_surfaces_at_consumer():
    """A failing featurize on the producer thread is poison, not a
    lost batch: the consumer sees the original error in order."""

    def boom(i):
        if i == 3:
            raise RuntimeError("featurize died")
        return i

    out = []
    with pytest.raises(RuntimeError, match="featurize died"):
        for v in staging.prefetch(iter(range(10)), stage_fn=boom):
            out.append(v)
    assert out == [0, 1, 2]


def test_stage_fn_consumer_stop_releases_producer():
    """An early-exiting consumer must stop the producer at its next
    check instead of letting it stage the rest of the source."""
    staged = []

    def record(i):
        staged.append(i)
        return i

    gen = staging.prefetch(
        iter(range(1000)), stage_fn=record, buffer_size=2
    )
    assert next(gen) == 0
    gen.close()  # consumer walks away
    time.sleep(0.3)
    assert len(staged) < 20  # bounded by the in-flight buffer, not 1000


def test_stage_fn_slow_producer_does_not_trip_watchdog():
    """A producer merely slower than the watchdog poll is NOT a dead
    producer: the timed get retries while the thread is alive."""

    def slow(i):
        time.sleep(0.12)
        return i

    got = list(
        staging.prefetch(
            iter(range(4)), stage_fn=slow, buffer_size=1,
            watchdog_poll_s=0.05,
        )
    )
    assert got == [0, 1, 2, 3]


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_stage_fn_dead_producer_fails_consumer_fast(monkeypatch):
    """A producer thread that dies without delivering its sentinel
    (its own failure path failed) must surface as ProducerDiedError,
    never an infinite block — the watchdog applies to stage_fn
    producers unchanged."""
    # sabotage the delivery machinery itself: the producer's poison
    # never reaches the queue, so only the watchdog can save the
    # consumer
    monkeypatch.setattr(
        staging, "_Poison",
        staging._END.__class__,  # constructing it raises TypeError
    )

    def boom(i):
        raise RuntimeError("undeliverable")

    with pytest.raises(staging.ProducerDiedError):
        list(
            staging.prefetch(
                iter(range(3)), stage_fn=boom, watchdog_poll_s=0.05,
            )
        )


# -- provider overlap parity -------------------------------------------


@pytest.fixture()
def session(tmp_path):
    """A 3-recording session: overlap is about recording K+1 vs K,
    so a multi-file run is the thing to pin."""
    lines = []
    for i in range(3):
        name = f"ov_{i:02d}"
        guessed = 2 + i
        _synthetic.write_recording(
            str(tmp_path), name=name, n_markers=60,
            guessed=guessed, seed=i,
        )
        lines.append(f"{name}.eeg {guessed}")
    info = tmp_path / "info.txt"
    info.write_text("\n".join(lines) + "\n")
    return str(info)


def _load(info, overlap, **kwargs):
    odp = provider.OfflineDataProvider([info])
    return odp.load_features_device(
        backend="decode", overlap=overlap, **kwargs
    )


def test_overlap_features_bit_identical(session, monkeypatch):
    f_serial, t_serial = _load(session, overlap=False)
    for depth in ("1", "2", "5"):
        monkeypatch.setenv(staging.ENV_PREFETCH_DEPTH, depth)
        f_ov, t_ov = _load(session, overlap=True)
        assert np.array_equal(f_serial, f_ov), depth
        assert np.array_equal(t_serial, t_ov), depth


def test_overlap_env_default(session, monkeypatch):
    """EEG_TPU_OVERLAP=1 turns the overlapped path on process-wide;
    results stay bit-identical (the metric proves the path ran)."""
    f_serial, _ = _load(session, overlap=None)
    monkeypatch.setenv(provider.ENV_OVERLAP, "1")
    before = obs.metrics.snapshot()["counters"].get(
        "ingest.overlap_runs", 0.0
    )
    f_ov, _ = _load(session, overlap=None)
    after = obs.metrics.snapshot()["counters"].get(
        "ingest.overlap_runs", 0.0
    )
    assert after == before + 1
    assert np.array_equal(f_serial, f_ov)


def test_overlap_query_statistics_identical(session):
    q = (
        f"info_file={session}&fe=dwt-8-fused-decode&train_clf=logreg"
        "&cache=false&config_step_size=1.0&config_num_iterations=40"
        "&config_mini_batch_fraction=1.0"
    )
    s_off = builder.PipelineBuilder(q + "&overlap=false").execute()
    pb = builder.PipelineBuilder(q + "&overlap=true")
    s_on = pb.execute()
    assert str(s_on) == str(s_off)
    assert pb.overlap_resolved is True
    with pytest.raises(ValueError, match="overlap="):
        builder.PipelineBuilder(q + "&overlap=maybe").execute()


@pytest.mark.chaos
def test_overlap_staging_producer_chaos_parity(session):
    """faults=staging.producer under overlap: the injected failure
    surfaces through the prefetch poison, the ladder absorbs it on
    the next rung, and the statistics are identical to the clean
    overlapped run — the chaos-parity contract extended to the
    overlap path."""
    q = (
        f"info_file={session}&fe=dwt-8-fused-decode&train_clf=logreg"
        "&overlap=true&cache=false&config_step_size=1.0"
        "&config_num_iterations=40&config_mini_batch_fraction=1.0"
    )
    clean = builder.PipelineBuilder(q).execute()
    before = obs.metrics.snapshot()["counters"]
    faulted = builder.PipelineBuilder(
        q + "&faults=staging.producer:once@1"
    ).execute()
    after = obs.metrics.snapshot()["counters"]
    assert str(faulted) == str(clean)
    assert (
        after.get("chaos.fired.staging.producer", 0.0)
        - before.get("chaos.fired.staging.producer", 0.0)
    ) == 1
    assert (
        after.get("pipeline.degraded", 0.0)
        - before.get("pipeline.degraded", 0.0)
    ) >= 1


# -- overlapped raw-stream training ------------------------------------


def _training_recordings(n_rec=3, n_markers=40, stride=750):
    rng = np.random.RandomState(7)
    out = []
    for r in range(n_rec):
        S = 200 + n_markers * stride + 1000
        raw = rng.randint(
            -3000, 3000, size=(3, S), dtype=np.int16
        )
        positions = np.clip(
            np.arange(n_markers, dtype=np.int64) * stride + 200
            + rng.randint(-200, 200, size=n_markers),
            100, S - 800,
        )
        cap = ((n_markers + 63) // 64) * 64
        pos = np.zeros(cap, np.int32)
        pos[:n_markers] = positions
        mask = np.zeros(cap, bool)
        mask[:n_markers] = True
        labels = np.zeros(cap, np.float32)
        labels[:n_markers] = rng.randint(0, 2, size=n_markers)
        res = np.array([0.1, 0.1, 0.2], np.float32)
        out.append((raw, res, pos, mask, labels))
    return out


def test_train_over_recordings_overlap_parity():
    """Recording K+1's decode+featurize on the producer thread while
    K's step runs: same losses, same final params as the serial twin
    at any buffer size — and no use-after-donate corruption (values
    would differ if a donated ping/pong buffer were re-read)."""
    import jax

    from eeg_dataanalysispackage_tpu.parallel import train as ptrain

    recs = _training_recordings()
    init_state, step = ptrain.make_feature_train_step(
        donate_state=False
    )

    def run(overlap, buffer_size=None):
        state = init_state(jax.random.PRNGKey(0))
        return ptrain.train_over_recordings(
            state, step, recs, overlap=overlap,
            buffer_size=buffer_size,
        )

    state_serial, losses_serial = run(False)
    for depth in (1, 2):
        state_ov, losses_ov = run(True, buffer_size=depth)
        assert losses_ov == losses_serial, depth
        for k in state_serial["params"]:
            assert np.array_equal(
                np.asarray(state_serial["params"][k]),
                np.asarray(state_ov["params"][k]),
            ), (depth, k)


def test_train_over_recordings_runs_on_producer_thread():
    """The overlap path's featurize genuinely executes off the
    consumer thread (the double-buffering claim, observed)."""
    from eeg_dataanalysispackage_tpu.parallel import train as ptrain

    main_thread = threading.current_thread().name
    seen = []
    stage = ptrain.make_decode_feature_stage(donate_stream=False)

    def spy(item):
        seen.append(threading.current_thread().name)
        return stage(item)

    out = list(
        staging.prefetch(
            iter(_training_recordings(n_rec=2)), stage_fn=spy
        )
    )
    assert len(out) == 2
    assert all(name != main_thread for name in seen)
    assert all(name.startswith("eeg-tpu-prefetch") for name in seen)
