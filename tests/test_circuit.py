"""Circuit breaker for remote endpoints (io/circuit.py): state
machine unit tests plus integration through HttpFileSystem against a
hermetic failing server."""

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from eeg_dataanalysispackage_tpu import obs
from eeg_dataanalysispackage_tpu.io import circuit, remote


class _Clock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


def _breaker(threshold=3, cooldown=10.0):
    clock = _Clock()
    return circuit.CircuitBreaker(
        "http://ep", threshold=threshold, cooldown_s=cooldown, clock=clock
    ), clock


def test_opens_after_consecutive_failures_only():
    cb, _ = _breaker(threshold=3)
    for _ in range(2):
        cb.allow()
        cb.record_failure(IOError("x"))
    cb.allow()
    cb.record_success()  # resets the consecutive count
    for _ in range(2):
        cb.allow()
        cb.record_failure(IOError("x"))
    assert cb.state == circuit.CLOSED
    cb.record_failure(IOError("third consecutive"))
    assert cb.state == circuit.OPEN


def test_open_fails_fast_with_evidence():
    cb, _ = _breaker(threshold=2)
    cb.record_failure(IOError("first budget"))
    cb.record_failure(IOError("second budget"))
    with pytest.raises(circuit.CircuitOpenError) as ei:
        cb.allow()
    msg = str(ei.value)
    assert "2 exhausted retry budgets" in msg
    assert "first budget" in msg and "second budget" in msg
    # CircuitOpenError is an IOError: existing remote-failure handling
    # catches it unchanged
    assert isinstance(ei.value, IOError)


def test_half_open_probe_closes_on_success():
    cb, clock = _breaker(threshold=1, cooldown=5.0)
    cb.record_failure(IOError("x"))
    with pytest.raises(circuit.CircuitOpenError):
        cb.allow()
    clock.now = 5.1
    cb.allow()  # the probe goes through
    assert cb.state == circuit.HALF_OPEN
    with pytest.raises(circuit.CircuitOpenError):
        cb.allow()  # concurrent callers keep failing fast mid-probe
    cb.record_success()
    assert cb.state == circuit.CLOSED
    cb.allow()  # closed again: calls flow


def test_half_open_probe_failure_reopens():
    cb, clock = _breaker(threshold=1, cooldown=5.0)
    cb.record_failure(IOError("x"))
    clock.now = 5.1
    cb.allow()
    cb.record_failure(IOError("still down"))
    assert cb.state == circuit.OPEN
    with pytest.raises(circuit.CircuitOpenError):
        cb.allow()  # cooldown clock restarted
    clock.now = 10.3
    cb.allow()  # next probe window


def test_threshold_zero_disables():
    cb = circuit.CircuitBreaker("http://ep", threshold=0)
    for _ in range(10):
        cb.record_failure(IOError("x"))
        cb.allow()  # never opens


def test_registry_shares_per_endpoint():
    circuit.reset()
    try:
        a = circuit.breaker_for("http://one:80")
        b = circuit.breaker_for("http://one:80")
        c = circuit.breaker_for("http://two:80")
        assert a is b and a is not c
    finally:
        circuit.reset()


# -- half-open behavior under concurrent callers ------------------------


def _race_allow(cb, n_threads=8):
    """Fire ``allow()`` from n threads behind a barrier; returns
    (admitted, fast_failed) counts."""
    barrier = threading.Barrier(n_threads)
    admitted, failed = [], []
    lock = threading.Lock()

    def caller():
        barrier.wait()
        try:
            cb.allow()
        except circuit.CircuitOpenError:
            with lock:
                failed.append(1)
        else:
            with lock:
                admitted.append(1)

    threads = [threading.Thread(target=caller) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return len(admitted), len(failed)


def test_half_open_admits_exactly_one_probe_under_concurrency():
    """Exactly ONE concurrent caller wins the half-open probe slot;
    the rest fail fast instead of stampeding the recovering endpoint."""
    cb, clock = _breaker(threshold=1, cooldown=5.0)
    cb.record_failure(IOError("down"))
    clock.now = 5.1
    admitted, failed = _race_allow(cb, n_threads=8)
    assert admitted == 1
    assert failed == 7
    assert cb.state == circuit.HALF_OPEN


def test_concurrent_probe_success_closes_exactly_once():
    cb, clock = _breaker(threshold=1, cooldown=5.0)
    cb.record_failure(IOError("down"))
    clock.now = 5.1
    admitted, _ = _race_allow(cb)
    assert admitted == 1
    before = obs.metrics.snapshot()["counters"].get("circuit.closed", 0.0)
    cb.record_success()  # the winner's probe came back
    after = obs.metrics.snapshot()["counters"]["circuit.closed"]
    assert after - before == 1  # one transition, not one per loser
    assert cb.state == circuit.CLOSED
    # closed again: every caller flows
    admitted, failed = _race_allow(cb)
    assert (admitted, failed) == (8, 0)


def test_concurrent_probe_failure_reopens_exactly_once():
    cb, clock = _breaker(threshold=1, cooldown=5.0)
    cb.record_failure(IOError("down"))
    clock.now = 5.1
    admitted, _ = _race_allow(cb)
    assert admitted == 1
    before = obs.metrics.snapshot()["counters"].get("circuit.opened", 0.0)
    cb.record_failure(IOError("probe failed"))
    after = obs.metrics.snapshot()["counters"]["circuit.opened"]
    assert after - before == 1
    assert cb.state == circuit.OPEN
    # the cooldown clock restarted: everyone fails fast again until
    # the next window, where again exactly one probes
    admitted, failed = _race_allow(cb)
    assert (admitted, failed) == (0, 8)
    clock.now = 10.3
    admitted, failed = _race_allow(cb)
    assert (admitted, failed) == (1, 7)


# -- integration through HttpFileSystem --------------------------------


class _FlakyHandler(BaseHTTPRequestHandler):
    store: dict

    def log_message(self, *args):
        pass

    def do_GET(self):
        self.store["requests"] += 1
        if self.store["down"]:
            self.send_response(503)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        body = b"alive"
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


@pytest.fixture()
def flaky_server():
    store = {"down": True, "requests": 0}
    handler = type("H", (_FlakyHandler,), {"store": store})
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        yield f"http://127.0.0.1:{httpd.server_address[1]}", store
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_breaker_wraps_http_filesystem(flaky_server, monkeypatch):
    base, store = flaky_server
    monkeypatch.setenv("EEG_TPU_CIRCUIT_THRESHOLD", "2")
    monkeypatch.setenv("EEG_TPU_CIRCUIT_COOLDOWN", "0.2")
    circuit.reset()
    try:
        fs = remote.HttpFileSystem(
            retry=remote.RetryPolicy(
                max_attempts=2, timeout_s=5.0, backoff_s=0.01
            )
        )
        before = obs.metrics.snapshot()["counters"]
        # two exhausted budgets (2 attempts each) open the circuit
        for _ in range(2):
            with pytest.raises(remote.RemoteIOError, match="after 2 attempts"):
                fs.read_bytes(f"{base}/x.bin")
        assert store["requests"] == 4
        # open: fail fast, no request leaves the process
        with pytest.raises(circuit.CircuitOpenError, match="circuit open"):
            fs.read_bytes(f"{base}/x.bin")
        assert store["requests"] == 4
        after = obs.metrics.snapshot()["counters"]
        assert after.get("circuit.opened", 0) - before.get(
            "circuit.opened", 0
        ) == 1
        assert after.get("circuit.fast_fail", 0) > before.get(
            "circuit.fast_fail", 0.0
        )
        # endpoint recovers; after the cooldown the half-open probe
        # closes the circuit and calls flow again
        store["down"] = False
        import time

        time.sleep(0.25)
        assert fs.read_bytes(f"{base}/x.bin") == b"alive"
        assert fs.read_bytes(f"{base}/x.bin") == b"alive"
    finally:
        circuit.reset()


# -- plan-tagged evidence + snapshot (ISSUE 10 satellite) --------------


def test_evidence_is_plan_tagged_inside_a_domain():
    """Failures recorded while a plan's fault domain is active carry
    the plan id; snapshot() aggregates the contributors — the
    cross-tenant attribution both plans' reports embed
    (docs/resilience.md)."""
    from eeg_dataanalysispackage_tpu.obs import domain as run_domain

    b = circuit.CircuitBreaker("http://snap.example:1", threshold=2)
    with run_domain.activate(run_domain.RunDomain(plan_id="pA")):
        b.record_failure(IOError("boom 1"))
    b.record_failure(IOError("boom 2"))  # outside any domain: untagged
    snap = b.snapshot()
    assert snap["state"] == "open"
    assert snap["consecutive_failures"] == 2
    assert snap["evidence"][0] == "[plan pA] OSError: boom 1"
    assert snap["evidence"][1] == "OSError: boom 2"
    assert snap["contributing_plans"] == ["pA"]
    # the fast-fail message a SECOND tenant sees carries the tag too
    with pytest.raises(circuit.CircuitOpenError, match=r"\[plan pA\]"):
        b.allow()


def test_registry_snapshot_is_schema_stable():
    circuit.reset()
    assert circuit.snapshot() == {}
    b = circuit.breaker_for("http://reg.example:9870")
    b.record_failure(IOError("x"))
    snap = circuit.snapshot()
    assert set(snap) == {"http://reg.example:9870"}
    assert snap["http://reg.example:9870"]["total_failures"] == 1
    circuit.reset()
