"""Remote filesystem: retry/resume semantics + provider/pipeline over HTTP.

The mock server is a real ``http.server`` on 127.0.0.1 with failure
injection (transient 500s, mid-body truncation, Range-ignoring mode),
so the full client machinery — bounded retries with backoff, chunked
ranged reads, resume-after-drop, 404 skip — is exercised hermetically.
The end-to-end tests serve the reference BrainVision fixtures and run
the provider and the whole pipeline with ``info_file=http://...``
(the reference's HDFS-borne flow, OffLineDataProvider.java:90).
"""

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.io import provider, remote, sources


class _Store:
    """Shared state between the test and the handler threads."""

    def __init__(self):
        self.files = {}
        self.fail_next = 0  # respond 500 to this many requests
        self.truncate_next = 0  # send half the promised body, then drop
        self.ignore_range = False  # pretend Range is not supported
        self.unknown_total = False  # Content-Range: bytes x-y/* (RFC 7233)
        self.no_head = False  # 405 on HEAD (object stores without HEAD)
        self.requests = []


class _Handler(BaseHTTPRequestHandler):
    store: _Store
    protocol_version = "HTTP/1.1"  # keep-alive: exercises conn reuse

    def log_message(self, *args):  # silence
        pass

    def _object(self):
        return self.store.files.get(self.path)

    def _common(self, method: str):
        self.store.requests.append((method, self.path))
        if self.store.fail_next > 0:
            self.store.fail_next -= 1
            self.send_response(500)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return None
        data = self._object()
        if data is None:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return None
        return data

    def do_HEAD(self):
        if self.store.no_head:
            self.store.requests.append(("HEAD", self.path))
            self.send_response(405)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        data = self._common("HEAD")
        if data is None:
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()

    def do_GET(self):
        data = self._common("GET")
        if data is None:
            return
        rng = self.headers.get("Range")
        if rng and not self.store.ignore_range:
            spec = rng.split("=")[1]
            start_s, end_s = spec.split("-")
            start = int(start_s)
            end = min(int(end_s), len(data) - 1) if end_s else len(data) - 1
            if start >= len(data):
                self.send_response(416)
                self.send_header("Content-Range", f"bytes */{len(data)}")
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            body = data[start : end + 1]
            total = "*" if self.store.unknown_total else str(len(data))
            self.send_response(206)
            self.send_header("Content-Range", f"bytes {start}-{end}/{total}")
        else:
            body = data
            self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if self.store.truncate_next > 0 and len(body) > 1:
            self.store.truncate_next -= 1
            self.wfile.write(body[: len(body) // 2])
            self.wfile.flush()
            self.connection.close()
            return
        self.wfile.write(body)

    def do_PUT(self):
        self.store.requests.append(("PUT", self.path))
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if self.store.fail_next > 0:
            self.store.fail_next -= 1
            self.send_response(503)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        self.store.files[self.path] = body
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()


@pytest.fixture()
def server():
    store = _Store()
    handler = type("Handler", (_Handler,), {"store": store})
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        yield base, store
    finally:
        httpd.shutdown()
        httpd.server_close()


def _fast_retry():
    return remote.RetryPolicy(max_attempts=4, timeout_s=5.0, backoff_s=0.01)


def _fs(base, **kw):
    return remote.HttpFileSystem(base_url=base, retry=_fast_retry(), **kw)


def test_basic_read_write_exists(server):
    base, store = server
    fs = _fs(base)
    assert not fs.exists(f"{base}/a.txt")
    fs.write_bytes(f"{base}/a.txt", b"hello remote")
    assert fs.exists(f"{base}/a.txt")
    assert fs.read_bytes(f"{base}/a.txt") == b"hello remote"
    assert fs.read_text(f"{base}/a.txt") == "hello remote"


def test_missing_object_raises_filenotfound(server):
    base, _ = server
    with pytest.raises(FileNotFoundError):
        _fs(base).read_bytes(f"{base}/nope.bin")


def test_chunked_ranged_read_reassembles(server):
    base, store = server
    payload = bytes(range(256)) * 1000  # 256000 B
    store.files["/blob.bin"] = payload
    fs = _fs(base, chunk_size=10_000)
    assert fs.read_bytes(f"{base}/blob.bin") == payload
    gets = [p for m, p in store.requests if m == "GET"]
    assert len(gets) == 26  # ceil(256000 / 10000)


def test_transient_500s_are_retried(server):
    base, store = server
    store.files["/flaky.bin"] = b"x" * 100
    store.fail_next = 2
    assert _fs(base).read_bytes(f"{base}/flaky.bin") == b"x" * 100


def test_retry_budget_exhausts_loudly(server):
    base, store = server
    store.files["/dead.bin"] = b"x"
    store.fail_next = 99
    with pytest.raises(remote.RemoteIOError, match="after 4 attempts"):
        _fs(base).read_bytes(f"{base}/dead.bin")


def test_mid_body_truncation_resumes(server):
    base, store = server
    payload = np.random.RandomState(0).bytes(50_000)
    store.files["/drop.bin"] = payload
    store.truncate_next = 2  # first two chunk bodies die halfway
    fs = _fs(base, chunk_size=20_000)
    assert fs.read_bytes(f"{base}/drop.bin") == payload


def test_server_without_range_support(server):
    base, store = server
    payload = b"y" * 30_000
    store.files["/whole.bin"] = payload
    store.ignore_range = True
    fs = _fs(base, chunk_size=1_000)
    assert fs.read_bytes(f"{base}/whole.bin") == payload


def test_read_range_block_read(server):
    base, store = server
    store.files["/blk.bin"] = bytes(range(200))
    assert _fs(base).read_range(f"{base}/blk.bin", 10, 5) == bytes(
        range(10, 15)
    )


def test_empty_object(server):
    base, store = server
    store.files["/empty.bin"] = b""
    fs = _fs(base)
    assert fs.exists(f"{base}/empty.bin")
    assert fs.read_bytes(f"{base}/empty.bin") == b""


def test_unknown_total_content_range(server):
    """'Content-Range: bytes x-y/*' (RFC 7233 unknown length): the
    short-chunk / 416-at-EOF heuristics still reassemble the object."""
    base, store = server
    for size in (25_000, 30_000):  # short-final-chunk and exact-multiple
        store.files["/u.bin"] = np.random.RandomState(size).bytes(size)
        store.unknown_total = True
        fs = _fs(base, chunk_size=10_000)
        assert fs.read_bytes(f"{base}/u.bin") == store.files["/u.bin"]


def test_headless_endpoint_exists_including_empty(server):
    base, store = server
    store.no_head = True
    store.files["/some.bin"] = b"data"
    store.files["/empty.bin"] = b""
    fs = _fs(base)
    assert fs.exists(f"{base}/some.bin")
    assert fs.exists(f"{base}/empty.bin")  # 416 on 1-byte probe = exists
    assert not fs.exists(f"{base}/nope.bin")


def test_connection_reuse_across_chunks(server):
    base, store = server
    store.files["/r.bin"] = b"q" * 50_000
    fs = _fs(base, chunk_size=10_000)
    fs.read_bytes(f"{base}/r.bin")
    assert len(fs._conns) == 1  # one keep-alive conn, reused 5x
    conn = next(iter(fs._conns.values()))
    fs.read_bytes(f"{base}/r.bin")
    assert next(iter(fs._conns.values())) is conn


def test_gcs_uri_maps_to_endpoint(server):
    base, store = server
    store.files["/bucket/obj.txt"] = b"in the bucket"
    fs = remote.GcsFileSystem(endpoint=base, retry=_fast_retry())
    assert fs.read_bytes("gs://bucket/obj.txt") == b"in the bucket"
    assert fs.exists("gs://bucket/obj.txt")


def test_gcs_token_sets_bearer_header(server):
    base, store = server
    store.files["/b/o"] = b"z"
    fs = remote.GcsFileSystem(endpoint=base, token="tok123", retry=_fast_retry())
    assert fs.headers["Authorization"] == "Bearer tok123"
    assert fs.read_bytes("gs://b/o") == b"z"


def test_filesystem_for_routing():
    assert isinstance(
        remote.filesystem_for("http://x/info.txt"), remote.HttpFileSystem
    )
    assert isinstance(
        remote.filesystem_for("gs://b/info.txt"), remote.GcsFileSystem
    )
    assert isinstance(
        remote.filesystem_for("/local/info.txt"), sources.LocalFileSystem
    )
    assert isinstance(
        remote.filesystem_for("file:///local/info.txt"),
        sources.LocalFileSystem,
    )


def test_local_file_uri_tolerated(tmp_path):
    p = tmp_path / "x.txt"
    p.write_bytes(b"local")
    fs = sources.LocalFileSystem()
    assert fs.exists(f"file://{p}")
    assert fs.read_bytes(f"file://{p}") == b"local"


# -- end to end over the reference fixtures ---------------------------


def _serve_fixture(store, fixture_dir):
    names = [
        "infoTrain.txt",
        "DoD/DoD2015_01.eeg",
        "DoD/DoD2015_01.vhdr",
        "DoD/DoD2015_01.vmrk",
    ]
    for name in names:
        with open(f"{fixture_dir}/{name}", "rb") as f:
            store.files[f"/data/{name}"] = f.read()


def test_provider_over_http_matches_local(server, fixture_dir):
    base, store = server
    _serve_fixture(store, fixture_dir)
    fs = _fs(base, chunk_size=1 << 20)
    batch_http = provider.OfflineDataProvider(
        [f"{base}/data/infoTrain.txt"], filesystem=fs
    ).load()
    batch_local = provider.OfflineDataProvider(
        [f"{fixture_dir}/infoTrain.txt"]
    ).load()
    np.testing.assert_array_equal(batch_http.epochs, batch_local.epochs)
    np.testing.assert_array_equal(batch_http.targets, batch_local.targets)


def test_provider_over_http_default_routing(server, fixture_dir):
    """No explicit filesystem: the URI scheme selects HttpFileSystem."""
    base, store = server
    _serve_fixture(store, fixture_dir)
    batch = provider.OfflineDataProvider([f"{base}/data/infoTrain.txt"]).load()
    assert batch.epochs.shape[0] > 0


def test_provider_over_http_skips_missing_files(server, fixture_dir):
    base, store = server
    _serve_fixture(store, fixture_dir)
    info = store.files["/data/infoTrain.txt"] + b"missing/gone.eeg 3 1\n"
    store.files["/data/infoTrain.txt"] = info
    fs = _fs(base)
    batch = provider.OfflineDataProvider(
        [f"{base}/data/infoTrain.txt"], filesystem=fs
    ).load()
    assert batch.epochs.shape == (11, 3, 750)  # the missing file skipped


def test_pipeline_over_http_end_to_end(server, fixture_dir, tmp_path):
    """info_file=http://... through the full pipeline query DSL."""
    from eeg_dataanalysispackage_tpu.pipeline import builder

    base, store = server
    _serve_fixture(store, fixture_dir)
    result_path = str(tmp_path / "result.txt")
    builder.PipelineBuilder(
        f"info_file={base}/data/infoTrain.txt&fe=dwt-8&train_clf=logreg"
        f"&result_path={result_path}"
    ).execute()
    text = open(result_path).read()
    assert "Accuracy" in text


def test_fused_pallas_pipeline_over_http(server, fixture_dir, tmp_path):
    """Round-2 features compose: the remote object-store filesystem
    feeding the fully fused Pallas ingest mode, end to end through the
    query DSL — raw bytes come over HTTP ranged reads, features come
    out of one Pallas kernel."""
    from eeg_dataanalysispackage_tpu.pipeline import builder

    base, store = server
    _serve_fixture(store, fixture_dir)
    result_path = str(tmp_path / "result.txt")
    stats = builder.PipelineBuilder(
        f"info_file={base}/data/infoTrain.txt&fe=dwt-8-fused-pallas"
        f"&train_clf=logreg&result_path={result_path}"
    ).execute()
    assert stats.num_patterns == 4
    assert "Accuracy" in open(result_path).read()


def test_model_save_load_over_http(server):
    """Classifier save/load routes through the remote filesystem for
    URI paths (the reference persists models on HDFS —
    LogisticRegressionClassifier.java:144-152)."""
    from eeg_dataanalysispackage_tpu.models.linear import (
        LogisticRegressionClassifier,
    )

    base, store = server
    rng = np.random.RandomState(0)
    feats = rng.randn(40, 48).astype(np.float32)
    ys = (feats[:, 0] > 0).astype(np.float64)

    clf = LogisticRegressionClassifier()
    clf.set_config({})
    clf.fit(feats, ys)
    clf.save(f"{base}/models/logreg")
    assert "/models/logreg.npz" in store.files

    clf2 = LogisticRegressionClassifier()
    clf2.load(f"{base}/models/logreg")
    np.testing.assert_array_equal(clf2.weights, clf.weights)


def test_nn_save_load_over_http(server):
    from eeg_dataanalysispackage_tpu.models import nn

    base, store = server
    rng = np.random.RandomState(0)
    feats = rng.randn(24, 48).astype(np.float32)
    ys = (feats[:, 0] > 0).astype(np.float64)
    cfg = {
        "config_seed": "1", "config_num_iterations": "3",
        "config_learning_rate": "0.05", "config_momentum": "0.9",
        "config_weight_init": "xavier", "config_updater": "nesterovs",
        "config_optimization_algo": "stochastic_gradient_descent",
        "config_loss_function": "xent",
        "config_pretrain": "false", "config_backprop": "true",
        "config_layer1_layer_type": "dense",
        "config_layer1_n_out": "8",
        "config_layer1_drop_out": "0",
        "config_layer1_activation_function": "relu",
        "config_layer2_layer_type": "output",
        "config_layer2_n_out": "2",
        "config_layer2_drop_out": "0",
        "config_layer2_activation_function": "softmax",
    }
    clf = nn.NeuralNetworkClassifier()
    clf.set_config(cfg)
    clf.fit(feats, ys)
    before = clf.predict(feats)
    clf.save(f"{base}/models/net.bin")
    assert "/models/net.bin" in store.files

    clf2 = nn.NeuralNetworkClassifier()
    clf2.load(f"{base}/models/net.bin")
    np.testing.assert_allclose(clf2.predict(feats), before, rtol=1e-6)


def test_model_load_missing_remote_raises(server):
    from eeg_dataanalysispackage_tpu.models.linear import (
        LogisticRegressionClassifier,
    )

    base, _ = server
    with pytest.raises(FileNotFoundError):
        LogisticRegressionClassifier().load(f"{base}/models/nope")


def test_pipeline_save_load_model_over_http(server, fixture_dir, tmp_path):
    """save_clf/load_clf with an http:// save_name through the query
    DSL: the trained model persists to the object store and a second
    pipeline run loads it back (the reference's HDFS model flow)."""
    from eeg_dataanalysispackage_tpu.pipeline import builder

    base, store = server
    _serve_fixture(store, fixture_dir)
    model_uri = f"{base}/models/pipeline-logreg"
    r1 = str(tmp_path / "r1.txt")
    builder.PipelineBuilder(
        f"info_file={base}/data/infoTrain.txt&fe=dwt-8&train_clf=logreg"
        f"&save_clf=true&save_name={model_uri}&result_path={r1}"
    ).execute()
    assert "/models/pipeline-logreg.npz" in store.files
    r2 = str(tmp_path / "r2.txt")
    stats = builder.PipelineBuilder(
        f"info_file={base}/data/infoTrain.txt&fe=dwt-8&load_clf=logreg"
        f"&load_name={model_uri}&result_path={r2}"
    ).execute()
    assert stats.num_patterns == 11  # load branch tests on ALL data
    assert "Accuracy" in open(r2).read()


def test_retry_policy_full_jitter_opt_in():
    """Full jitter (satellite of ISSUE 2): opt-in uniform-[0, wait)
    backoff so concurrent workers desynchronize; the default stays
    deterministic for reproducibility."""
    deterministic = remote.RetryPolicy(backoff_s=0.5, max_backoff_s=4.0)
    assert [deterministic.sleep_for(a) for a in range(4)] == [
        0.5, 1.0, 2.0, 4.0
    ]
    jittered = remote.RetryPolicy(
        backoff_s=0.5, max_backoff_s=4.0, jitter="full"
    )
    waits = [jittered.sleep_for(2) for _ in range(50)]
    assert all(0.0 <= w <= 2.0 for w in waits)
    assert len(set(waits)) > 1  # actually random, not a constant
    with pytest.raises(ValueError, match="jitter"):
        remote.RetryPolicy(jitter="half")


# -- deadline-aware retries (ISSUE-6 satellite) -------------------------


def test_retry_stops_when_deadline_cannot_cover_backoff(server):
    """With an ambient deadline whose remaining budget cannot cover
    the next backoff sleep, the ladder aborts NOW — raising with the
    attempt history — instead of sleeping past the deadline."""
    from eeg_dataanalysispackage_tpu.io import circuit, deadline

    base, store = server
    store.files["/dead.bin"] = b"x"
    store.fail_next = 99
    circuit.reset()
    try:
        fs = remote.HttpFileSystem(
            base_url=base,
            # a backoff the 0.2 s budget can never cover: the ladder
            # must stop after attempt 1 of 4
            retry=remote.RetryPolicy(
                max_attempts=4, timeout_s=5.0, backoff_s=30.0
            ),
        )
        n_before = len(store.requests)
        with deadline.deadline_scope(deadline.Deadline(0.2)):
            with pytest.raises(
                remote.RemoteIOError,
                match=r"aborted after 1/4 attempts.*deadline budget",
            ) as ei:
                fs.read_bytes(f"{base}/dead.bin")
        # the attempt history rides in the error
        assert "attempt 1: RemoteIOError" in str(ei.value)
        # exactly one request left the process — no 30 s sleep, no
        # further attempts
        assert len(store.requests) - n_before == 1
    finally:
        circuit.reset()


def test_spent_deadline_refuses_the_first_attempt(server):
    from eeg_dataanalysispackage_tpu.io import circuit, deadline

    base, store = server
    store.files["/a.bin"] = b"x"
    circuit.reset()
    try:
        n_before = len(store.requests)
        with deadline.deadline_scope(deadline.Deadline(0.0)):
            with pytest.raises(remote.RemoteIOError, match="not attempted"):
                _fs(base).read_bytes(f"{base}/a.bin")
        assert len(store.requests) == n_before  # nothing hit the wire
    finally:
        circuit.reset()


def test_no_deadline_scope_keeps_classic_retry_behavior(server):
    base, store = server
    store.files["/flaky.bin"] = b"x" * 10
    store.fail_next = 2
    assert _fs(base).read_bytes(f"{base}/flaky.bin") == b"x" * 10


def test_deadline_nesting_tightest_wins():
    from eeg_dataanalysispackage_tpu.io import deadline

    class Clock:
        now = 0.0

        def __call__(self):
            return self.now

    clock = Clock()
    outer = deadline.Deadline(10.0, clock=clock)
    inner = deadline.Deadline(1.0, clock=clock)
    assert deadline.active_deadline() is None
    with deadline.deadline_scope(outer):
        assert deadline.active_deadline() is outer
        with deadline.deadline_scope(inner):
            assert deadline.active_deadline() is inner
            assert deadline.active_deadline().can_cover(0.5)
            assert not deadline.active_deadline().can_cover(2.0)
        assert deadline.active_deadline() is outer
    assert deadline.active_deadline() is None
    clock.now = 1.5
    assert inner.expired and not outer.expired
    with pytest.raises(deadline.DeadlineExceededError):
        inner.raise_if_expired("probe")


def test_spent_deadline_does_not_leak_the_half_open_probe_slot(server):
    """Review regression: the spent-budget fast-fail must run BEFORE
    breaker.allow() — otherwise a hurried caller claims the one
    half-open probe slot, raises without recording an outcome, and the
    breaker can never be probed again for the life of the process."""
    import time as time_mod

    from eeg_dataanalysispackage_tpu.io import circuit, deadline

    base, store = server
    store.files["/x.bin"] = b"alive"
    circuit.reset()
    try:
        endpoint = base  # authority key used by breaker_for
        cb = circuit.breaker_for(endpoint)
        cb.threshold, cb.cooldown_s = 1, 0.05
        cb.record_failure(IOError("down"))
        assert cb.state == circuit.OPEN
        time_mod.sleep(0.06)  # cooldown elapsed: probe window open
        # a caller with a spent budget must NOT consume the probe slot
        with deadline.deadline_scope(deadline.Deadline(0.0)):
            with pytest.raises(remote.RemoteIOError, match="not attempted"):
                _fs(base).read_bytes(f"{base}/x.bin")
        # an unhurried caller can still probe, and the probe closes
        # the circuit
        assert _fs(base).read_bytes(f"{base}/x.bin") == b"alive"
        assert cb.state == circuit.CLOSED
    finally:
        circuit.reset()
