"""Device-ingest path (ops/device_ingest.py) vs the bit-exact host path."""

import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.epochs import extractor
from eeg_dataanalysispackage_tpu.io import brainvision
from eeg_dataanalysispackage_tpu.ops import device_ingest


@pytest.fixture(scope="module")
def recording(fixture_dir):
    return brainvision.load_recording(fixture_dir + "/DoD/DoD_2015_02.eeg")


def _fzczpz(rec):
    return [rec.header.channel_index(n) for n in ("fz", "cz", "pz")]


def test_matches_host_extractor_on_fixture(recording):
    idx = _fzczpz(recording)
    host = extractor.extract_epochs(
        recording.read_channels(idx), recording.markers, guessed_number=4
    )
    epochs, plan = device_ingest.ingest_recording(recording, 4, idx)

    assert plan.n_kept == len(host) == 27
    np.testing.assert_array_equal(plan.targets, host.targets)
    np.testing.assert_array_equal(plan.stimulus_indices, host.stimulus_indices)
    assert int(plan.targets.sum()) == 13

    got = np.asarray(epochs)[plan.mask]
    assert got.shape == host.epochs.shape
    # f32 device path vs f64-carried host path: f32-rounding tolerance
    np.testing.assert_allclose(got, host.epochs, rtol=0, atol=2e-4)
    # padded rows are zeroed
    assert not np.asarray(epochs)[~plan.mask].any()


def test_balance_state_spans_recordings(recording):
    idx = _fzczpz(recording)
    shared = extractor.BalanceState()
    _, plan1 = device_ingest.ingest_recording(
        recording, 4, idx, balance=shared
    )
    counters_after_first = (shared.n_targets, shared.n_nontargets)
    _, plan2 = device_ingest.ingest_recording(
        recording, 4, idx, balance=shared
    )
    assert counters_after_first[0] > 0
    # second pass starts from the first pass's counters, so retention
    # differs from a fresh scan (the reference's cross-file semantics)
    fresh = device_ingest.plan_ingest(
        recording.markers, 4, recording.num_samples
    )
    assert plan2.n_kept != fresh.n_kept or not np.array_equal(
        plan2.targets, fresh.targets
    )


def test_zero_pad_and_validity_semantics():
    # synthetic 2-channel recording with windows at the edges
    S, pre, post = 1200, 100, 750
    rng = np.random.RandomState(0)
    raw = rng.randint(-1000, 1000, size=(2, S)).astype(np.int16)
    res = np.array([0.1, 0.5], dtype=np.float32)

    # start<0 invalid; start==S valid (all zero-pad); tail zero-pads.
    # Classes alternate so the balance scan keeps every valid window.
    markers = [
        brainvision.Marker("Mk1", "Stimulus", "S  1", 50),  # start<0: drop
        brainvision.Marker("Mk2", "Stimulus", "S  1", 100),  # start==0
        brainvision.Marker("Mk3", "Stimulus", "S  2", 900),  # tail pads
        brainvision.Marker("Mk4", "Stimulus", "S  1", S + pre),  # start==S
        brainvision.Marker("Mk5", "Stimulus", "S  4", S + pre + 1),  # drop
    ]
    plan = device_ingest.plan_ingest(markers, guessed_number=1, n_samples=S)
    assert plan.n_kept == 3
    np.testing.assert_array_equal(plan.stimulus_indices, [0, 1, 0])
    np.testing.assert_array_equal(plan.targets, [1.0, 0.0, 1.0])

    epochs = np.asarray(
        device_ingest.make_device_epocher(pre, post)(
            raw, res, plan.positions, plan.mask
        )
    )

    # host reference on the scaled channels
    channels = (raw.astype(np.float32) * res[:, None]).astype(np.float64)
    windows, valid = extractor.gather_windows(
        channels, np.array([m.position for m in markers]), pre, post
    )
    host = extractor.baseline_correct_f32(windows, pre)[..., pre:]
    np.testing.assert_allclose(
        epochs[plan.mask], host.astype(np.float32), rtol=0, atol=2e-4
    )
    # the all-zero-pad window (start==S) is exactly zero
    np.testing.assert_array_equal(epochs[2], 0.0)


def test_raw_int16_rejects_non_int16(fixture_dir):
    rec = brainvision.load_recording(fixture_dir + "/DoD/DoD2015_01.eeg")
    float_rec = brainvision.Recording(
        rec.header, rec.markers, rec._raw.astype(np.float32)
    )
    with pytest.raises(TypeError, match="INT_16"):
        float_rec.raw_int16([0])


def test_capacity_bucketing():
    plan = device_ingest.plan_ingest(
        [brainvision.Marker("Mk1", "Stimulus", "S  1", 500)],
        guessed_number=1,
        n_samples=10_000,
    )
    assert plan.capacity == 64 and plan.n_kept == 1
    assert plan.positions.dtype == np.int32


def test_non_int16_recording_falls_back_to_scaled_channels(fixture_dir):
    rec = brainvision.load_recording(fixture_dir + "/DoD/DoD_2015_02.eeg")
    idx = _fzczpz(rec)
    # same recording re-expressed as pre-scaled float32 (resolution
    # folded in, headers claiming unit resolution)
    scaled = (
        rec._raw[:, idx].astype(np.float32)
        * rec.resolutions(idx)[None, :]
    )
    chans = [
        brainvision.ChannelInfo(c.number, c.name, c.reference, 1.0, c.units)
        for c in rec.header.channels
    ]
    hdr = brainvision.Header(
        rec.header.data_file, rec.header.marker_file, rec.header.data_format,
        rec.header.orientation, len(idx), rec.header.sampling_interval_us,
        "IEEE_FLOAT_32", [chans[i] for i in idx],
    )
    float_rec = brainvision.Recording(hdr, rec.markers, scaled)

    int_epochs, int_plan = device_ingest.ingest_recording(rec, 4, idx)
    f_epochs, f_plan = device_ingest.ingest_recording(
        float_rec, 4, [0, 1, 2]
    )
    assert f_plan.n_kept == int_plan.n_kept == 27
    np.testing.assert_allclose(
        np.asarray(f_epochs), np.asarray(int_epochs), rtol=0, atol=2e-4
    )


def test_provider_load_features_device_matches_host_path(fixture_dir):
    from eeg_dataanalysispackage_tpu.features import registry as fe_registry
    from eeg_dataanalysispackage_tpu.io import provider

    odp = provider.OfflineDataProvider([fixture_dir + "/infoTrain.txt"])
    feats, targets = odp.load_features_device()
    assert feats.shape == (11, 48) and feats.dtype == np.float32
    assert int(targets.sum()) == 5

    host_batch = provider.OfflineDataProvider(
        [fixture_dir + "/infoTrain.txt"]
    ).load()
    host_feats = fe_registry.create("dwt-8").extract_batch(host_batch.epochs)
    np.testing.assert_array_equal(targets, host_batch.targets)
    # end-to-end f32 chain (f32 ingest feeding f32 DWT) vs the
    # f64-carried host epochs: deviation is ingest-level (~1e-4), not
    # the 5e-6 of the DWT alone on identical inputs
    np.testing.assert_allclose(feats, host_feats, rtol=0, atol=5e-4)


def test_provider_load_features_device_empty_run(tmp_path):
    from eeg_dataanalysispackage_tpu.io import provider

    info = tmp_path / "info.txt"
    info.write_text("missing/a.eeg 1\n")
    feats, targets = provider.OfflineDataProvider(
        [str(info)]
    ).load_features_device()
    assert feats.shape == (0, 48) and targets.shape == (0,)


def test_stage_raw_buckets_sample_axis(recording):
    idx = _fzczpz(recording)
    raw, res, n_samples = device_ingest.stage_raw(recording, idx)
    assert n_samples == recording.num_samples
    assert raw.shape[1] % 16384 == 0 and raw.shape[1] >= n_samples
    assert raw.dtype == np.int16
    assert not raw[:, n_samples:].any()  # zero tail
    # two recordings of different true lengths land in the same
    # compiled bucket -> one jit trace serves both
    shorter_len = raw.shape[1] - 16384 + 1  # smallest length in bucket
    shorter = brainvision.Recording(
        recording.header, recording.markers,
        recording._raw[:shorter_len],
    )
    raw2, _, n2 = device_ingest.stage_raw(shorter, idx)
    assert n2 == shorter_len
    assert raw2.shape == raw.shape


def test_fused_pipeline_query_mode(fixture_dir, tmp_path):
    """fe=dwt-8-fused runs the whole query pipeline on the device
    fast path: train/save, then load/test, result file written."""
    from eeg_dataanalysispackage_tpu.pipeline import builder

    result = tmp_path / "result.txt"
    save_dir = tmp_path / "clf"
    q = (
        f"info_file={fixture_dir}/infoTrain.txt&fe=dwt-8-fused"
        f"&train_clf=logreg&save_clf=true&save_name={save_dir}"
        f"&result_path={result}"
    )
    pb = builder.PipelineBuilder(q)
    stats_train = pb.execute()
    assert stats_train.num_patterns == 11 - int(0.7 * 11)
    assert "Accuracy:" in result.read_text()

    q_load = (
        f"info_file={fixture_dir}/infoTrain.txt&fe=dwt-8-fused"
        f"&load_clf=logreg&load_name={save_dir}"
    )
    stats_load = builder.PipelineBuilder(q_load).execute()
    assert stats_load.num_patterns == 11  # load mode: all shuffled data


def test_default_fused_backend_is_platform_aware(monkeypatch):
    """Bare -fused resolves per platform: block on accelerators (21x
    the element gather on the r4 chip), decode on CPU (the slice-scan
    window cut — ~8.6x the element gather there)."""

    class _Dev:
        def __init__(self, platform):
            self.platform = platform

    monkeypatch.setattr(
        device_ingest.jax, "devices", lambda: [_Dev("cpu")]
    )
    assert device_ingest.default_fused_backend() == "decode"
    monkeypatch.setattr(
        device_ingest.jax, "devices", lambda: [_Dev("tpu")]
    )
    assert device_ingest.default_fused_backend() == "block"


def test_fused_xla_suffix_forces_gather_backend(fixture_dir, tmp_path,
                                                monkeypatch):
    """fe=dwt-8-fused-xla pins the element-gather backend regardless
    of platform default; bare -fused consults the default."""
    from eeg_dataanalysispackage_tpu.io import provider as provider_mod
    from eeg_dataanalysispackage_tpu.pipeline import builder

    seen = []
    orig = provider_mod.OfflineDataProvider.load_features_device

    def spy(self, *a, **kw):
        seen.append(kw.get("backend"))
        return orig(self, *a, **kw)

    monkeypatch.setattr(
        provider_mod.OfflineDataProvider, "load_features_device", spy
    )
    # pin the platform default so the test is green on any host (the
    # conftest forces CPU, but don't depend on it); the builder
    # resolves via this module-level function at run time
    monkeypatch.setattr(
        device_ingest, "default_fused_backend", lambda: "xla"
    )
    result = tmp_path / "r.txt"
    for fe, want in (("dwt-8-fused-xla", "xla"),
                     ("dwt-8-fused", "xla")):  # pinned default = xla
        q = (
            f"info_file={fixture_dir}/infoTrain.txt&fe={fe}"
            f"&train_clf=logreg&result_path={result}"
        )
        builder.PipelineBuilder(q).execute()
        assert seen[-1] == want


def test_fused_pipeline_matches_host_pipeline_split(fixture_dir, tmp_path):
    """The fused mode uses the same seed-1 shuffle + 70/30 split as
    the reference path, so the two modes test on the same rows."""
    from eeg_dataanalysispackage_tpu.pipeline import builder

    q_host = (
        f"info_file={fixture_dir}/infoTrain.txt&fe=dwt-8-tpu"
        "&train_clf=logreg"
    )
    q_fused = (
        f"info_file={fixture_dir}/infoTrain.txt&fe=dwt-8-fused"
        "&train_clf=logreg"
    )
    s_host = builder.PipelineBuilder(q_host).execute()
    s_fused = builder.PipelineBuilder(q_fused).execute()
    assert s_host.num_patterns == s_fused.num_patterns
