"""Synthetic BrainVision recordings for hermetic pipeline tests.

The reference fixture set (/root/reference/test-data) is not always
present; the chaos suite must run everywhere, so it fabricates a
small but structurally faithful guess-the-number session: INT_16
multiplexed .eeg + .vhdr/.vmrk siblings + an info.txt, with Fz/Cz/Pz
among the channels and stimulus markers spaced so every epoch window
is in range.
"""

import os

import numpy as np

CHANNELS = ("Fz", "Cz", "Pz", "Oz")  # one extra channel to exercise selection
RESOLUTION = 0.1


def write_recording(
    directory: str,
    name: str = "synth_01",
    n_markers: int = 48,
    guessed: int = 2,
    seed: int = 0,
    marker_stride: int = 1000,
):
    """Write <name>.eeg/.vhdr/.vmrk under ``directory``; returns the
    .eeg path. Stimulus numbers cycle 1..9 so a balanced target /
    non-target split exists for any guessed number."""
    rng = np.random.RandomState(seed)
    n_ch = len(CHANNELS)
    n_samples = 200 + n_markers * marker_stride + 900
    raw = rng.randint(-3000, 3000, size=(n_samples, n_ch)).astype("<i2")
    eeg = os.path.join(directory, name + ".eeg")
    with open(eeg, "wb") as f:
        f.write(raw.tobytes())

    vhdr = [
        "Brain Vision Data Exchange Header File Version 1.0",
        "[Common Infos]",
        f"DataFile={name}.eeg",
        f"MarkerFile={name}.vmrk",
        "DataFormat=BINARY",
        "DataOrientation=MULTIPLEXED",
        f"NumberOfChannels={n_ch}",
        "SamplingInterval=1000",
        "[Binary Infos]",
        "BinaryFormat=INT_16",
        "[Channel Infos]",
    ] + [
        f"Ch{i + 1}={ch},,{RESOLUTION},uV" for i, ch in enumerate(CHANNELS)
    ]
    with open(os.path.join(directory, name + ".vhdr"), "w") as f:
        f.write("\n".join(vhdr) + "\n")

    vmrk = ["Brain Vision Data Exchange Marker File, Version 1.0",
            "[Marker Infos]"]
    for i in range(n_markers):
        stim = (i % 9) + 1
        pos = 200 + i * marker_stride
        vmrk.append(f"Mk{i + 1}=Stimulus,S  {stim},{pos},1,0")
    with open(os.path.join(directory, name + ".vmrk"), "w") as f:
        f.write("\n".join(vmrk) + "\n")
    return eeg


def write_session(directory: str, guessed: int = 2, **kwargs) -> str:
    """One-recording session: returns the info.txt path."""
    write_recording(directory, guessed=guessed, **kwargs)
    info = os.path.join(directory, "info.txt")
    with open(info, "w") as f:
        f.write(f"synth_01.eeg {guessed}\n")
    return info
