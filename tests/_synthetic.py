"""Synthetic BrainVision recordings for hermetic pipeline tests.

The reference fixture set (/root/reference/test-data) is not always
present; the chaos suite must run everywhere, so it fabricates a
small but structurally faithful guess-the-number session: INT_16
multiplexed .eeg + .vhdr/.vmrk siblings + an info.txt, with Fz/Cz/Pz
among the channels and stimulus markers spaced so every epoch window
is in range.
"""

import os

import numpy as np

CHANNELS = ("Fz", "Cz", "Pz", "Oz")  # one extra channel to exercise selection
RESOLUTION = 0.1


def write_recording(
    directory: str,
    name: str = "synth_01",
    n_markers: int = 48,
    guessed: int = 2,
    seed: int = 0,
    marker_stride: int = 1000,
):
    """Write <name>.eeg/.vhdr/.vmrk under ``directory``; returns the
    .eeg path. Stimulus numbers cycle 1..9 so a balanced target /
    non-target split exists for any guessed number."""
    rng = np.random.RandomState(seed)
    n_ch = len(CHANNELS)
    n_samples = 200 + n_markers * marker_stride + 900
    raw = rng.randint(-3000, 3000, size=(n_samples, n_ch)).astype("<i2")
    eeg = os.path.join(directory, name + ".eeg")
    with open(eeg, "wb") as f:
        f.write(raw.tobytes())

    vhdr = [
        "Brain Vision Data Exchange Header File Version 1.0",
        "[Common Infos]",
        f"DataFile={name}.eeg",
        f"MarkerFile={name}.vmrk",
        "DataFormat=BINARY",
        "DataOrientation=MULTIPLEXED",
        f"NumberOfChannels={n_ch}",
        "SamplingInterval=1000",
        "[Binary Infos]",
        "BinaryFormat=INT_16",
        "[Channel Infos]",
    ] + [
        f"Ch{i + 1}={ch},,{RESOLUTION},uV" for i, ch in enumerate(CHANNELS)
    ]
    with open(os.path.join(directory, name + ".vhdr"), "w") as f:
        f.write("\n".join(vhdr) + "\n")

    vmrk = ["Brain Vision Data Exchange Marker File, Version 1.0",
            "[Marker Infos]"]
    for i in range(n_markers):
        stim = (i % 9) + 1
        pos = 200 + i * marker_stride
        vmrk.append(f"Mk{i + 1}=Stimulus,S  {stim},{pos},1,0")
    with open(os.path.join(directory, name + ".vmrk"), "w") as f:
        f.write("\n".join(vmrk) + "\n")
    return eeg


def write_session(directory: str, guessed: int = 2, **kwargs) -> str:
    """One-recording session: returns the info.txt path."""
    write_recording(directory, guessed=guessed, **kwargs)
    info = os.path.join(directory, "info.txt")
    with open(info, "w") as f:
        f.write(f"synth_01.eeg {guessed}\n")
    return info


def write_continuous_recording(
    directory: str,
    name: str = "seiz_01",
    n_samples: int = 60000,
    seizure_intervals=((12000, 16000), (38000, 41000)),
    seed: int = 0,
    base_amplitude: int = 600,
    seizure_gain: float = 2.5,
):
    """Write a continuous recording with annotated seizure intervals.

    The signal is broadband noise; inside each annotated interval the
    amplitude scales by ``seizure_gain`` and a low-frequency
    oscillation rides on top — enough structure that per-subband
    energy features separate the classes *imperfectly* (the
    cost-sensitive training knobs need an actual precision/recall
    trade-off to act on, not a separable toy). Intervals land in the
    .vmrk as ``Seizure,on`` / ``Seizure,off`` marker pairs
    (epochs/sliding.py's annotation convention). Returns the .eeg
    path.
    """
    rng = np.random.RandomState(seed)
    n_ch = len(CHANNELS)
    sig = rng.randn(n_samples, n_ch) * base_amplitude
    t = np.arange(n_samples, dtype=np.float64)
    for lo, hi in seizure_intervals:
        burst = rng.randn(hi - lo, n_ch) * base_amplitude * seizure_gain
        wave = (
            0.8 * base_amplitude * seizure_gain
            * np.sin(2 * np.pi * t[lo:hi] / 180.0)
        )
        sig[lo:hi] = burst + wave[:, None]
    raw = np.clip(sig, -32000, 32000).astype("<i2")
    eeg = os.path.join(directory, name + ".eeg")
    with open(eeg, "wb") as f:
        f.write(raw.tobytes())

    vhdr = [
        "Brain Vision Data Exchange Header File Version 1.0",
        "[Common Infos]",
        f"DataFile={name}.eeg",
        f"MarkerFile={name}.vmrk",
        "DataFormat=BINARY",
        "DataOrientation=MULTIPLEXED",
        f"NumberOfChannels={n_ch}",
        "SamplingInterval=1000",
        "[Binary Infos]",
        "BinaryFormat=INT_16",
        "[Channel Infos]",
    ] + [
        f"Ch{i + 1}={ch},,{RESOLUTION},uV" for i, ch in enumerate(CHANNELS)
    ]
    with open(os.path.join(directory, name + ".vhdr"), "w") as f:
        f.write("\n".join(vhdr) + "\n")

    vmrk = ["Brain Vision Data Exchange Marker File, Version 1.0",
            "[Marker Infos]"]
    mk = 1
    vmrk.append(f"Mk{mk}=New Segment,,0,1,0")
    mk += 1
    for lo, hi in seizure_intervals:
        vmrk.append(f"Mk{mk}=Seizure,on,{lo},1,0")
        mk += 1
        vmrk.append(f"Mk{mk}=Seizure,off,{hi},1,0")
        mk += 1
    with open(os.path.join(directory, name + ".vmrk"), "w") as f:
        f.write("\n".join(vmrk) + "\n")
    return eeg


def write_seizure_session(
    directory: str,
    n_files: int = 1,
    n_samples: int = 60000,
    seed: int = 0,
    **kwargs,
) -> str:
    """An ``n_files``-recording continuous session with annotated
    seizure intervals; returns the info.txt path. The info.txt guessed
    number is irrelevant to the seizure task (labels come from the
    interval annotations) but keeps the manifest format identical to
    the P300 one, so one provider reads both workloads."""
    lines = []
    explicit_intervals = kwargs.pop("seizure_intervals", None)
    for i in range(n_files):
        name = f"seiz_{i:02d}"
        span = n_samples
        intervals = explicit_intervals or (
            (int(span * 0.2), int(span * 0.27)),
            (int(span * 0.63), int(span * 0.68)),
        )
        write_continuous_recording(
            directory,
            name=name,
            n_samples=n_samples,
            seizure_intervals=intervals,
            seed=seed + i,
            **kwargs,
        )
        lines.append(f"{name}.eeg 1")
    info = os.path.join(directory, "info.txt")
    with open(info, "w") as f:
        f.write("\n".join(lines) + "\n")
    return info
