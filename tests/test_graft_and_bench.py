"""Driver-contract and extractor-formulation tests."""

import importlib.util
import os

import jax
import numpy as np
import pytest


def load_graft():
    spec = importlib.util.spec_from_file_location(
        "graft_entry", os.path.join(os.path.dirname(__file__), "..", "__graft_entry__.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_entry_compiles_and_runs():
    mod = load_graft()
    fn, args = mod.entry()
    out = np.asarray(jax.jit(fn)(*args))
    assert out.shape == (8,)
    assert np.isfinite(out).all()
    assert ((out >= 0) & (out <= 1)).all()


def test_dryrun_multichip_8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    load_graft().dryrun_multichip(8)


def test_matmul_and_conv_formulations_agree():
    from eeg_dataanalysispackage_tpu.ops import dwt as dwt_xla

    x = np.random.RandomState(0).randn(16, 3, 1000).astype(np.float32) * 30
    mm = dwt_xla.make_batched_extractor(method="matmul")
    cv = dwt_xla.make_batched_extractor(method="conv")
    np.testing.assert_allclose(
        np.asarray(mm(x)), np.asarray(cv(x)), rtol=0, atol=5e-5
    )


def test_cascade_matrix_is_exact_linearization():
    from eeg_dataanalysispackage_tpu.ops import dwt as dwt_xla, dwt_host

    K = dwt_xla.cascade_matrix(8, 512, 16)
    sig = np.random.RandomState(1).randn(512)
    direct = dwt_host.dwt_coefficients(sig, 8, 16)
    via_matrix = sig @ K
    np.testing.assert_allclose(via_matrix, direct, rtol=0, atol=1e-12)


def test_train_step_learns_on_fixture(fixture_dir):
    """The flagship DP train step drives loss down on real data."""
    from eeg_dataanalysispackage_tpu.io import provider
    from eeg_dataanalysispackage_tpu.parallel import mesh as pmesh, train as ptrain

    batch = provider.OfflineDataProvider([fixture_dir + "/infoTrain.txt"]).load()
    mesh = pmesh.make_mesh(min(8, len(jax.devices())))
    init_state, train_step = ptrain.make_train_step(mesh, learning_rate=0.1)
    state = init_state(jax.random.PRNGKey(0))
    ep, lb, mask = ptrain.stage_batch(batch.epochs, batch.targets, mesh)
    losses = []
    for _ in range(60):
        state, loss = train_step(state, ep, lb, mask)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    probs = np.asarray(
        ptrain.forward_step(state["params"], ep.astype(np.float32))
    )[: len(batch)]
    acc = ((probs > 0.5).astype(float) == batch.targets).mean()
    assert acc >= 0.7


def test_bench_cpu_fallback_contract():
    """bench.py must print ONE parseable JSON line with the headline
    and the fused-ingest/train-step variants even with no TPU
    (BENCH_FORCE_CPU=1) — the driver-artifact contract."""
    import json
    import subprocess
    import sys

    repo = os.path.join(os.path.dirname(__file__), "..")
    # per-variant child timeout small enough that all 5 worst-case
    # children still finish inside this test's own 580s deadline
    env = dict(os.environ, BENCH_FORCE_CPU="1", BENCH_RUN_TIMEOUT="100")
    env.pop("JAX_PLATFORMS", None)  # bench manages its own children env
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        capture_output=True,
        text=True,
        timeout=580,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-1500:]
    lines = proc.stdout.strip().splitlines()
    assert len(lines) == 1
    payload = json.loads(lines[0])
    assert payload["unit"] == "epochs/s"
    assert payload["value"] > 0
    assert payload["platform"] == "cpu_fallback"
    # CPU timings must never carry a TPU-HBM roofline claim
    # (VERDICT r3 weak #6): the field is TPU-platform-only, both at
    # the headline and inside every variant
    assert "pct_of_hbm_roofline" not in payload
    for v in ("einsum", "einsum_bf16", "regular_ingest", "pallas_ingest",
              "train_step"):
        assert payload["variants"][v]["epochs_per_s"] > 0, payload
        assert "pct_of_hbm_roofline" not in payload["variants"][v], payload
