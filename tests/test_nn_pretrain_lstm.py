"""NN completeness: greedy layerwise pretraining and the real LSTM.

Pins the two DL4J behaviors the round-1 build stubbed
(NeuralNetworkClassifier.java:126-137 pretrain,
:258-320 graves_lstm layer switch): pretrain=true must actually move
the pretrainable layers' weights before backprop, and graves_lstm
must be a genuine recurrent cell whose output depends on the whole
sequence, not a dense stand-in.
"""

import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.models import nn

BASE = {
    "config_seed": "7",
    "config_num_iterations": "60",
    "config_learning_rate": "0.05",
    "config_momentum": "0.9",
    "config_weight_init": "xavier",
    "config_updater": "sgd",
    "config_optimization_algo": "stochastic_gradient_descent",
    "config_pretrain": "false",
    "config_backprop": "true",
    "config_loss_function": "xent",
}


def layer(i, ltype, n_out, act, drop="0.0"):
    return {
        f"config_layer{i}_layer_type": ltype,
        f"config_layer{i}_n_out": str(n_out),
        f"config_layer{i}_drop_out": drop,
        f"config_layer{i}_activation_function": act,
    }


def make_data(n=128, d=12, seed=3):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d).astype(np.float64)
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(np.float64)
    return x, y


def fit_nn(cfg, x, y):
    clf = nn.NeuralNetworkClassifier()
    clf.set_config(cfg)
    clf.fit(x, y)
    return clf


def kernel(clf, i):
    return np.asarray(clf.params["params"][f"layer{i}"]["kernel"])


# -- pretraining -------------------------------------------------------


@pytest.mark.parametrize("ltype", ["auto_encoder", "rbm"])
def test_pretrain_changes_initial_weights(ltype):
    """With backprop=false, fit() == (init + pretrain). pretrain=true
    must move the pretrainable layer's weights; the output layer,
    which is never pretrained, must stay at its initializer values."""
    x, y = make_data()
    cfg = dict(BASE, config_pretrain="false", config_backprop="false")
    cfg.update(layer(1, ltype, 8, "sigmoid"))
    cfg.update(layer(2, "output", 2, "softmax"))
    frozen = fit_nn(cfg, x, y)

    cfg2 = dict(cfg, config_pretrain="true")
    pre = fit_nn(cfg2, x, y)

    # same seed -> identical initial draws; pretraining moved layer 1
    assert not np.allclose(kernel(frozen, 1), kernel(pre, 1))
    np.testing.assert_array_equal(kernel(frozen, 2), kernel(pre, 2))


def test_pretrain_ae_reduces_reconstruction_error():
    x, y = make_data()
    cfg = dict(BASE, config_pretrain="false", config_backprop="false")
    cfg.update(layer(1, "auto_encoder", 8, "sigmoid"))
    cfg.update(layer(2, "output", 2, "softmax"))
    frozen = fit_nn(cfg, x, y)
    pre = fit_nn(dict(cfg, config_pretrain="true"), x, y)

    def recon_err(w, b):
        z = 1.0 / (1.0 + np.exp(-(x.astype(np.float32) @ w + b)))
        # linear decode through the tied weights (visible bias ~ 0
        # at init; compare apples to apples without it)
        r = z @ w.T
        return float(np.mean((r - x) ** 2))

    b1 = np.asarray(frozen.params["params"]["layer1"]["bias"])
    b2 = np.asarray(pre.params["params"]["layer1"]["bias"])
    assert recon_err(kernel(pre, 1), b2) < recon_err(kernel(frozen, 1), b1)


def test_pretrain_then_backprop_still_learns():
    x, y = make_data(n=200)
    cfg = dict(BASE, config_pretrain="true", config_num_iterations="300",
               config_updater="nesterovs", config_learning_rate="0.1")
    cfg.update(layer(1, "auto_encoder", 16, "sigmoid"))
    cfg.update(layer(2, "output", 2, "softmax"))
    clf = fit_nn(cfg, x, y)
    preds = (clf.predict(x) > 0.5).astype(np.float64)
    assert (preds == y).mean() > 0.8


def test_pretrain_stacked_layers_both_move():
    """Greedy = layer 2 pretrains on layer 1's pretrained output."""
    x, y = make_data()
    cfg = dict(BASE, config_pretrain="false", config_backprop="false")
    cfg.update(layer(1, "auto_encoder", 10, "sigmoid"))
    cfg.update(layer(2, "rbm", 6, "sigmoid"))
    cfg.update(layer(3, "output", 2, "softmax"))
    frozen = fit_nn(cfg, x, y)
    pre = fit_nn(dict(cfg, config_pretrain="true"), x, y)
    assert not np.allclose(kernel(frozen, 1), kernel(pre, 1))
    assert not np.allclose(kernel(frozen, 2), kernel(pre, 2))
    np.testing.assert_array_equal(kernel(frozen, 3), kernel(pre, 3))


def test_backprop_false_without_pretrain_keeps_init():
    """DL4J model.fit with pretrain=false, backprop=false trains
    nothing at all."""
    x, y = make_data()
    cfg = dict(BASE, config_pretrain="false", config_backprop="false")
    cfg.update(layer(1, "dense", 8, "relu"))
    cfg.update(layer(2, "output", 2, "softmax"))
    a = fit_nn(cfg, x, y)
    b = fit_nn(cfg, x, y)
    np.testing.assert_array_equal(kernel(a, 1), kernel(b, 1))


# -- graves_lstm -------------------------------------------------------


def lstm_cfg(extra=None):
    cfg = dict(BASE, config_num_iterations="40")
    cfg.update(layer(1, "graves_lstm", 8, "tanh"))
    cfg.update(layer(2, "output", 2, "softmax"))
    if extra:
        cfg.update(extra)
    return cfg


def test_lstm_trains_on_flat_features():
    """The reference's only shipped shape: (batch, 48) flat features
    run the cell for one step and classify."""
    x, y = make_data()
    clf = fit_nn(lstm_cfg(), x, y)
    out = clf.predict(x)
    assert out.shape == (len(x),)
    assert np.all((out >= 0) & (out <= 1))
    # a real LSTM cell: input and recurrent gate kernels present
    gates = set(clf.params["params"]["layer1"].keys())
    assert {"ii", "if", "ig", "io", "hi", "hf", "hg", "ho"} <= gates


def test_lstm_depends_on_sequence_history_dense_does_not():
    """Two sequences with identical final timesteps but different
    histories: a dense stack (per-timestep affine + last-step output
    read) cannot tell them apart; a real LSTM must."""
    rng = np.random.RandomState(0)
    n, t, d = 16, 6, 12
    seq_a = rng.randn(n, t, d).astype(np.float64)
    seq_b = np.array(seq_a)
    seq_b[:, :-1] = rng.randn(n, t - 1, d)  # same last step, new history
    y = (rng.rand(n) > 0.5).astype(np.float64)

    lstm = fit_nn(lstm_cfg({"config_backprop": "false"}), seq_a[:, 0], y)
    out_a = lstm_forward(lstm, seq_a)
    out_b = lstm_forward(lstm, seq_b)
    assert not np.allclose(out_a, out_b)

    dense_cfg = dict(BASE, config_backprop="false")
    dense_cfg.update(layer(1, "dense", 8, "tanh"))
    dense_cfg.update(layer(2, "output", 2, "softmax"))
    dense = fit_nn(dense_cfg, seq_a[:, 0], y)
    np.testing.assert_array_equal(
        lstm_forward(dense, seq_a), lstm_forward(dense, seq_b)
    )


def lstm_forward(clf, seq):
    import jax.numpy as jnp

    model = clf._build()
    return np.asarray(
        model.apply(clf.params, jnp.asarray(seq, jnp.float32), train=False)
    )


def test_lstm_sequence_training_learns_order():
    """Net-new TPU capability: train on (batch, time, features)
    sequences where only the order carries the label."""
    rng = np.random.RandomState(1)
    n, t = 120, 8
    base = rng.randn(n, t, 4).astype(np.float64)
    ramp = np.linspace(-1, 1, t)[None, :, None]
    y = (rng.rand(n) > 0.5).astype(np.float64)
    # label 1: rising ramp on channel 0; label 0: falling
    base[:, :, 0] = np.where(y[:, None] > 0, ramp[0, :, 0], -ramp[0, :, 0])
    cfg = lstm_cfg({
        "config_num_iterations": "200",
        "config_updater": "adam",
        "config_learning_rate": "0.02",
    })
    clf = nn.NeuralNetworkClassifier()
    clf.set_config(cfg)
    clf.fit(base, y)
    preds = (lstm_forward(clf, base)[:, 0] > 0.5).astype(np.float64)
    assert (preds == y).mean() > 0.9


def test_lstm_save_load_roundtrip(tmp_path):
    x, y = make_data()
    clf = fit_nn(lstm_cfg(), x, y)
    p = str(tmp_path / "lstm_model")
    clf.save(p)
    clf2 = nn.NeuralNetworkClassifier()
    clf2.load(p)
    np.testing.assert_array_equal(clf.predict(x), clf2.predict(x))


# -- optimization_algo -------------------------------------------------


@pytest.mark.parametrize(
    "algo", ["lbfgs", "conjugate_gradient", "line_gradient_descent"]
)
def test_optimization_algos_learn(algo):
    """config_optimization_algo is functional: each second-order /
    line-search algorithm trains to high accuracy on a separable
    problem (DL4J: NeuralNetworkClassifier.java:246-255)."""
    x, y = make_data(n=200)
    cfg = dict(BASE, config_optimization_algo=algo,
               config_num_iterations="80")
    cfg.update(layer(1, "dense", 8, "tanh"))
    cfg.update(layer(2, "output", 2, "softmax"))
    clf = fit_nn(cfg, x, y)
    preds = (clf.predict(x) > 0.5).astype(np.float64)
    assert (preds == y).mean() > 0.85, algo


def test_unknown_optimization_algo_falls_back_silently():
    """DL4J's parseOptimizationAlgo silently falls back to SGD."""
    x, y = make_data()
    cfg = dict(BASE, config_optimization_algo="quantum_annealing")
    cfg.update(layer(1, "dense", 8, "tanh"))
    cfg.update(layer(2, "output", 2, "softmax"))
    clf = fit_nn(cfg, x, y)  # must not raise
    assert clf.params is not None


def test_lbfgs_beats_few_iteration_sgd():
    """On a smooth convex-ish objective, 30 L-BFGS steps should reach
    a lower loss than 30 plain-SGD steps from the same init."""
    x, y = make_data(n=150)

    def final_loss(algo):
        cfg = dict(BASE, config_optimization_algo=algo,
                   config_updater="sgd", config_num_iterations="30",
                   config_learning_rate="0.05")
        cfg.update(layer(1, "dense", 8, "tanh"))
        cfg.update(layer(2, "output", 2, "softmax"))
        clf = fit_nn(cfg, x, y)
        p = np.clip(clf.predict(x), 1e-7, 1 - 1e-7)
        return float(-np.mean(y * np.log(p) + (1 - y) * np.log1p(-p)))

    assert final_loss("lbfgs") < final_loss("stochastic_gradient_descent")


def test_pretrain_honors_optimization_algo():
    """AE pretraining uses the configured algorithm: lbfgs pretraining
    must produce different layer-1 weights than sgd pretraining from
    the same init (RBM layers stay first-order — CD-1 has no scalar
    objective to line-search)."""
    x, y = make_data()
    base = dict(BASE, config_pretrain="true", config_backprop="false")
    base.update(layer(1, "auto_encoder", 8, "sigmoid"))
    base.update(layer(2, "output", 2, "softmax"))
    sgd = fit_nn(dict(base), x, y)
    lb = fit_nn(dict(base, config_optimization_algo="lbfgs"), x, y)
    assert not np.allclose(kernel(sgd, 1), kernel(lb, 1))
