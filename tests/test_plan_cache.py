"""The fused-ingest hot-path caches and buffer-donation contracts.

Pins the ISSUE-1 perf machinery: the shared host gather-plan cache
(``ops/plan_cache``) that the block/Pallas planners memoize through
(same marker layout -> the SAME plan object, zero re-planning; any
input change -> a rebuild), the alignment-classed block featurizer's
parity with the traced formulations it replaces, and the
``donate_argnums`` threading through the jitted extractor / train-step
entry points (a donated buffer must actually be invalidated, and the
opt-out must actually keep it alive)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from eeg_dataanalysispackage_tpu.ops import (
    device_ingest,
    dwt as dwt_xla,
    ingest_pallas,
    plan_cache,
)


@pytest.fixture(autouse=True)
def _fresh_caches():
    """Counter/entry isolation: every test sees a cold plan cache."""
    plan_cache.clear()
    yield
    plan_cache.clear()


def _irregular_case(n=40, seed=3, cap=64, S=60_000):
    rng = np.random.RandomState(seed)
    raw = rng.randint(-3000, 3000, size=(3, S)).astype(np.int16)
    res = np.array([0.1, 0.1, 0.2], np.float32)
    positions = np.sort(
        rng.choice(np.arange(200, S - 900), size=n, replace=False)
    ).astype(np.int32)
    pos = np.zeros(cap, np.int32)
    pos[:n] = positions
    mask = np.zeros(cap, bool)
    mask[:n] = True
    return raw, res, pos, mask


# ---------------------------------------------------------- the memo


def test_digest_keys_on_content_shape_dtype_and_extra():
    a = np.arange(8, dtype=np.int32)
    base = plan_cache.digest(a, extra=("geom", 512))
    assert base == plan_cache.digest(a.copy(), extra=("geom", 512))
    changed = a.copy()
    changed[3] += 1
    assert plan_cache.digest(changed, extra=("geom", 512)) != base
    assert plan_cache.digest(a.astype(np.int64), extra=("geom", 512)) != base
    assert plan_cache.digest(a.reshape(2, 4), extra=("geom", 512)) != base
    assert plan_cache.digest(a, extra=("geom", 513)) != base


def test_get_or_build_hits_and_evicts(monkeypatch):
    monkeypatch.setenv("EEG_TPU_PLAN_CACHE_SIZE", "2")
    c = plan_cache.cache("unit")
    builds = []

    def builder(tag):
        builds.append(tag)
        return {"plan": tag}

    first = c.get_or_build("k1", lambda: builder("k1"))
    # hit: the SAME object comes back, the builder does not run again
    assert c.get_or_build("k1", lambda: builder("k1-again")) is first
    assert builds == ["k1"]
    assert c.stats() == {"hits": 1, "misses": 1, "entries": 1}
    # capacity 2: a third key evicts the least-recently-used one
    c.get_or_build("k2", lambda: builder("k2"))
    c.get_or_build("k3", lambda: builder("k3"))
    rebuilt = c.get_or_build("k1", lambda: builder("k1-rebuilt"))
    assert rebuilt is not first and rebuilt["plan"] == "k1-rebuilt"


def test_stats_aggregate_is_schema_stable():
    s = plan_cache.stats()
    # zeros before any planner runs — the bench field relies on this
    # (named caches persist in the registry; clear() zeroes them)
    assert s["hits"] == 0 and s["misses"] == 0
    assert all(
        c["hits"] == 0 and c["misses"] == 0 and c["entries"] == 0
        for c in s["caches"].values()
    )


def test_per_cache_capacity_override():
    """A cache created with its own capacity ignores the shared
    default bound — how the MB-scale block-class operator cache stays
    small while layout-plan caches keep the roomy default."""
    c = plan_cache.cache("unit_capped", capacity=1)
    c.get_or_build("a", lambda: "A")
    c.get_or_build("b", lambda: "B")  # evicts "a"
    assert c.get_or_build("a", lambda: "A2") == "A2"
    assert c.stats()["entries"] == 1


def test_save_and_load_file_roundtrip(tmp_path):
    """Cross-process persistence (the bench child warm start): the
    saved plans load into a cold registry and the next lookup is a
    HIT — the counter behavior that finally lets a recorded
    block_ingest line show hits > 0."""
    path = str(tmp_path / "plans.pkl")
    c = plan_cache.cache("unit_persist")
    plan = {"rows": np.arange(6, dtype=np.int32)}
    c.get_or_build("layout-1", lambda: plan)
    assert plan_cache.save_file(path) == path

    plan_cache.clear()
    assert plan_cache.load_file(path) == 1
    got = plan_cache.cache("unit_persist").get_or_build(
        "layout-1", lambda: {"rows": "rebuilt"}
    )
    np.testing.assert_array_equal(got["rows"], plan["rows"])
    # a warm load counts as neither hit nor miss; the lookup is a hit
    assert plan_cache.cache("unit_persist").stats() == {
        "hits": 1, "misses": 0, "entries": 1,
    }


def test_load_file_preserves_capacity_override(tmp_path):
    """A warm start must not recreate a deliberately small cache (the
    MB-scale operator tables' capacity=16) at the roomy shared
    default — the capacity rides along in the persisted payload."""
    path = str(tmp_path / "plans.pkl")
    c = plan_cache.cache("unit_cap_persist", capacity=3)
    c.get_or_build("k", lambda: "v")
    plan_cache.save_file(path)
    # simulate a fresh process: the registry has never seen the name
    with plan_cache._registry_lock:
        del plan_cache._registry["unit_cap_persist"]
    assert plan_cache.load_file(path) == 1
    assert plan_cache.cache("unit_cap_persist").capacity == 3


def test_load_file_tolerates_missing_and_corrupt(tmp_path, monkeypatch):
    monkeypatch.delenv(plan_cache.ENV_FILE, raising=False)
    assert plan_cache.load_file(str(tmp_path / "nope.pkl")) == 0
    bad = tmp_path / "bad.pkl"
    bad.write_bytes(b"\x80garbage")
    assert plan_cache.load_file(str(bad)) == 0
    assert plan_cache.save_file(None) is None  # persistence off: no-op


# --------------------------------------- the block-class gather plan


def test_block_class_plan_cache_hit_and_miss():
    raw, _res, pos, mask = _irregular_case()
    kw = dict(wavelet_index=8, epoch_size=512, skip_samples=175,
              feature_size=16)
    p1 = device_ingest.cached_block_class_plan(
        pos, mask, raw.shape[1], **kw
    )
    p2 = device_ingest.cached_block_class_plan(
        pos, mask, raw.shape[1], **kw
    )
    assert p2 is p1  # same layout -> the cached plan object, re-planned
    stats = plan_cache.cache("block_class_plan").stats()
    assert stats["hits"] == 1 and stats["misses"] == 1
    # any marker moves -> a different key -> a rebuild
    moved = pos.copy()
    moved[0] += 1
    p3 = device_ingest.cached_block_class_plan(
        moved, mask, raw.shape[1], **kw
    )
    assert p3 is not p1
    # ... and so does a different staged length (the clip boundary)
    p4 = device_ingest.cached_block_class_plan(
        pos, mask, raw.shape[1] + 128, **kw
    )
    assert p4 is not p1
    assert plan_cache.cache("block_class_plan").stats()["misses"] == 3


def test_pallas_tile_plan_cache_hit_and_miss():
    _raw, _res, pos, mask = _irregular_case()
    positions = pos[mask]
    p1 = ingest_pallas.cached_plan_pallas_tiles(positions)
    assert ingest_pallas.cached_plan_pallas_tiles(positions) is p1
    stats = plan_cache.cache("pallas_tile_plan").stats()
    assert stats["hits"] == 1 and stats["misses"] == 1
    moved = positions.copy()
    moved[-1] += 7
    assert ingest_pallas.cached_plan_pallas_tiles(moved) is not p1
    # geometry participates in the key (same layout, different tiling)
    assert ingest_pallas.cached_plan_pallas_tiles(
        positions, tile_b=16
    ) is not p1


# ------------------------------------ classed block featurizer parity


def test_classed_block_matches_gather_all_residues():
    """The alignment-classed formulation must match the gather+einsum
    featurizer with every one of the 128 shift-residue classes
    populated (positions step by a stride coprime to 128) on DC-heavy
    data — a misplaced class operator or row_of slot fails here."""
    rng = np.random.RandomState(7)
    n, cap = 128, 192
    dc = np.array([[1800], [-2200], [900]], np.int16)
    step = 901  # coprime to 128 -> all residues in 128 windows
    positions = (200 + step * np.arange(n)).astype(np.int32)
    assert len(set((positions - 100) % 128)) == 128
    S = int(positions.max()) + 2000
    raw = (rng.randint(-3000, 3000, size=(3, S)) + dc).astype(np.int16)
    res = np.array([0.1, 0.1, 0.2], np.float32)
    pos = np.zeros(cap, np.int32)
    pos[:n] = positions
    mask = np.zeros(cap, bool)
    mask[:n] = True
    gather = device_ingest.make_device_ingest_featurizer()
    classed = device_ingest.make_classed_block_ingest_featurizer()
    want = np.asarray(
        gather(jnp.asarray(raw), jnp.asarray(res), jnp.asarray(pos),
               jnp.asarray(mask))
    )
    got = np.asarray(classed(jnp.asarray(raw), res, pos, mask))
    assert got.shape == want.shape == (cap, 48)
    np.testing.assert_allclose(got, want, rtol=0, atol=5e-6)
    # padded rows zeroed
    assert np.abs(got[n:]).max() == 0.0
    # the call planned exactly once
    assert plan_cache.cache("block_class_plan").stats()["misses"] == 1


def test_classed_block_edges_and_overhang_match_gather():
    """Window at start 0 and a window overhanging the recording end
    (Java copyOfRange zero-pad semantics) through the classed path."""
    rng = np.random.RandomState(5)
    S = 6000
    raw = rng.randint(-3000, 3000, size=(3, S)).astype(np.int16)
    res = np.array([0.1, 0.1, 0.2], np.float32)
    pos = np.array([100, 101, 227, S - 300], np.int32)
    mask = np.ones(4, bool)
    gather = device_ingest.make_device_ingest_featurizer()
    classed = device_ingest.make_classed_block_ingest_featurizer()
    want = np.asarray(
        gather(jnp.asarray(raw), jnp.asarray(res), jnp.asarray(pos),
               jnp.asarray(mask))
    )
    got = np.asarray(classed(jnp.asarray(raw), res, pos, mask))
    np.testing.assert_allclose(got, want, rtol=0, atol=5e-6)


def test_classed_block_chunked_matches_whole():
    """The lax.map chunked path (classes x slots > chunk_epochs) is
    numerically identical to the single-shot program."""
    raw, res, pos, mask = _irregular_case(n=50, cap=64)
    whole = device_ingest.make_classed_block_ingest_featurizer()
    chunked = device_ingest.make_classed_block_ingest_featurizer(
        chunk_epochs=64
    )
    a = np.asarray(whole(jnp.asarray(raw), res, pos, mask))
    b = np.asarray(chunked(jnp.asarray(raw), res, pos, mask))
    np.testing.assert_allclose(b, a, rtol=0, atol=1e-6)


def test_classed_block_rejects_oversized_window_at_build():
    """Same guard as the traced featurizer: a window that cannot fit
    the 8-block slab at the worst in-block shift fails deterministically
    at BUILD time, never as a data-dependent numpy broadcast error
    when an unluckily-aligned marker shows up (review finding)."""
    with pytest.raises(ValueError, match="8-block slab"):
        device_ingest.make_classed_block_ingest_featurizer(
            epoch_size=640
        )
    with pytest.raises(ValueError, match="8-block slab"):
        device_ingest.plan_block_classes(
            np.array([220], np.int32), np.array([True]), 5000,
            epoch_size=640,
        )


def test_block_class_operator_tables_shared_across_layouts():
    """The MB-scale Wc/Mc operator tables are keyed on the class SET
    + geometry, not the marker layout: two distinct layouts with the
    same in-block shifts share one table object, keeping per-layout
    cache entries KB-scale (review finding)."""
    _raw, _res, pos, mask = _irregular_case()
    p1 = device_ingest.cached_block_class_plan(pos, mask, 60_000)
    # +128 samples: every block index moves, every in-block shift
    # (and so the class set) stays identical
    shifted = np.where(mask, pos + 128, pos).astype(pos.dtype)
    p2 = device_ingest.cached_block_class_plan(shifted, mask, 60_000)
    assert p2 is not p1  # different layout -> different plan
    assert p2.Wc is p1.Wc and p2.Mc is p1.Mc  # shared operators


# --------------------------------------------------- buffer donation


def test_compact_extractor_donation_is_numerically_invisible():
    """donate_epochs changes buffer lifetime only, never values. The
    (B, C, 512) -> (B, 48) shapes never alias, so whether the backend
    can actually retire the donated buffer is platform-dependent (CPU
    warns 'not usable' and keeps it; TPU reuses the HBM) — the
    portable contract is that the default call leaves the batch
    usable and the donated call computes the identical result."""
    ex_keep = dwt_xla.make_compact_extractor()
    ex_don = dwt_xla.make_compact_extractor(donate_epochs=True)
    x = jnp.asarray(np.random.RandomState(0)
                    .randn(8, 3, 512).astype(np.float32))
    want = np.asarray(ex_keep(x))
    assert not x.is_deleted()  # default: caller keeps the batch
    got = np.asarray(ex_don(x))
    np.testing.assert_array_equal(got, want)


def test_feature_train_step_donates_state_by_default():
    from eeg_dataanalysispackage_tpu.parallel import train as ptrain

    rng = np.random.RandomState(0)
    feats = jnp.asarray(rng.randn(16, 48).astype(np.float32))
    labels = jnp.asarray((rng.rand(16) > 0.5).astype(np.float32))
    mask = jnp.ones(16, jnp.float32)

    init, step = ptrain.make_feature_train_step()
    state = init(jax.random.PRNGKey(0))
    donated_leaf = state["params"]["w1"]
    state2, loss = step(state, feats, labels, mask)
    assert np.isfinite(float(loss))
    assert donated_leaf.is_deleted()  # old params freed, not resident
    assert not state2["params"]["w1"].is_deleted()

    # opt-out keeps the old state alive (A/B comparison use)
    init, step_keep = ptrain.make_feature_train_step(donate_state=False)
    state = init(jax.random.PRNGKey(0))
    kept_leaf = state["params"]["w1"]
    step_keep(state, feats, labels, mask)
    assert not kept_leaf.is_deleted()


def test_train_step_donation_preserves_the_update():
    """Donation must be invisible to the math: the donated and
    non-donated steps produce identical losses and params from the
    same start."""
    from eeg_dataanalysispackage_tpu.parallel import train as ptrain

    rng = np.random.RandomState(1)
    epochs = rng.randn(8, 3, 1000).astype(np.float32)
    labels = (rng.rand(8) > 0.5).astype(np.float32)
    mask = np.ones(8, np.float32)

    init_k, step_keep = ptrain.make_train_step(donate_state=False)
    state = init_k(jax.random.PRNGKey(2))
    ref = state
    losses_keep = []
    for _ in range(3):
        state, loss = step_keep(
            state, jnp.asarray(epochs), jnp.asarray(labels),
            jnp.asarray(mask),
        )
        losses_keep.append(float(loss))

    init_d, step_don = ptrain.make_train_step(
        donate_state=True, donate_epochs=True
    )
    dstate = init_d(jax.random.PRNGKey(2))
    losses_don = []
    for _ in range(3):
        batch = jnp.asarray(epochs)  # fresh batch each step: donatable
        dstate, loss = step_don(
            dstate, batch, jnp.asarray(labels), jnp.asarray(mask)
        )
        losses_don.append(float(loss))
    np.testing.assert_allclose(losses_don, losses_keep, rtol=1e-6)
    for k in dstate["params"]:
        np.testing.assert_allclose(
            np.asarray(dstate["params"][k]),
            np.asarray(state["params"][k]),
            rtol=0, atol=1e-7,
        )
    del ref
