"""Worker process for the two-process pod-pipeline test.

Launched by tests/test_pod_pipeline.py with a full pipeline query on
argv (carrying ``processes=2&coordinator=...&process_id=N``). Runs the
REAL pipeline path — ``PipelineBuilder.execute`` bootstraps the pod
inside ``_resolve_pod``, partitions the recordings, exchanges features
over the loopback-DCN, and trains the population member axis over the
hybrid mesh — then prints one JSON line: the statistics sha256, the
mesh block, and the compiled-HLO collective assertions (the PR 9
pattern: the cross-process all-gathers must exist in the compiled
programs, not just in intent).
"""

import hashlib
import json
import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")

import jax

jax.config.update("jax_platforms", "cpu")
# no gloo config here, deliberately: distributed.initialize sets the
# CPU collectives implementation itself once the preflight passes, so
# the pipeline works on CPU pods without per-caller jax.config setup

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    query = sys.argv[1]
    from eeg_dataanalysispackage_tpu.pipeline import builder

    pb = builder.PipelineBuilder(query)
    statistics = pb.execute()
    out = {
        "sha": hashlib.sha256(str(statistics).encode()).hexdigest(),
        "mesh": pb.mesh_resolved,
        "procs": int(jax.process_count()),
        "devices": int(jax.device_count()),
        "degradation": pb.degradation_history,
    }
    if (pb.mesh_resolved or {}).get("rung") == "pod":
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from eeg_dataanalysispackage_tpu.parallel import (
            distributed,
            mesh as pmesh,
            pod as pod_mod,
        )

        mesh = distributed.hybrid_mesh()
        # the feature exchange's replicate program: its all-gather is
        # THE collective that ships each host's rows over DCN
        out["exchange_allgather"] = "all-gather" in (
            pod_mod.exchange_collective_hlo(mesh, 64, 48)
        )
        # the population weight all-gather over the pod member spec
        # ((hosts, data) — hosts outermost): lowered on the same mesh
        # and sharding the pipeline's sharded engine used
        rep = jax.jit(
            lambda w: w, out_shardings=NamedSharding(mesh, P())
        )
        txt = rep.lower(
            jax.ShapeDtypeStruct(
                (4, 48),
                jnp.float32,
                sharding=NamedSharding(
                    mesh, P((distributed.DCN_AXIS, pmesh.DATA_AXIS), None)
                ),
            )
        ).compile().as_text()
        out["weight_allgather"] = "all-gather" in txt
    print(json.dumps(out))


if __name__ == "__main__":
    main()
