"""The r4b decision helper (tools/r4b_decisions.py) against
synthetic artifacts: the pre-registered thresholds from
docs/chip_playbook.md must map measured numbers to the right
actions, and missing artifacts must read PENDING — the tool is the
post-recovery bookkeeping, so its verdicts need pinning before the
chip window, not after."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "r4b_decisions.py")


def _run(d):
    r = subprocess.run(
        [sys.executable, TOOL, str(d)],
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert r.returncode == 0, r.stderr
    return r.stdout


def _write(d, name, payload):
    with open(os.path.join(d, f"{name}.json"), "w") as f:
        f.write(json.dumps(payload) + "\n")


def test_all_pending_on_empty_dir(tmp_path):
    out = _run(tmp_path)
    assert out.count("PENDING") >= 10


def test_flip_thresholds(tmp_path):
    d = str(tmp_path)
    # bank128 at 3x block -> flip to pallas default
    _write(d, "bank128_131k", {"epochs_per_s": 3.5e6})
    # regular bank beats partial -> flip auto to bank
    _write(d, "regular_bank", {"epochs_per_s": 6.1e6})
    # einsum_512 at roofline -> compact headline
    _write(d, "einsum_512", {"epochs_per_s": 9.0e7, "pct_of_hbm_roofline": 68.0})
    # compact-bf16 short of roofline -> record, no flip
    _write(
        d, "einsum_512_bf16",
        {"epochs_per_s": 9.5e7, "pct_of_hbm_roofline": 36.0},
    )
    # rf retry ok -> transient
    _write(d, "rf_predict_retry", {"epochs_per_s": 2.5e5})
    # train at 262k recovered -> dispatch amortization
    _write(d, "train_step_262k", {"epochs_per_s": 4.0e7})
    out = _run(d)
    assert "FLIP default_fused_backend" in out
    assert "FLIP resolve_regular_formulation" in out
    assert "make compact-resident the headline" in out
    assert "failed to compound" in out
    assert "transient" in out
    assert "dispatch amortization confirmed" in out


def test_keep_thresholds(tmp_path):
    d = str(tmp_path)
    _write(d, "bank128_32k", {"epochs_per_s": 1.5e6})  # only 1.3x block
    _write(d, "regular_bank", {"epochs_per_s": 4.0e6})  # < partial 5.40M
    _write(d, "einsum_512", {"epochs_per_s": 5.0e7, "pct_of_hbm_roofline": 38.0})
    _write(d, "train_step_262k", {"epochs_per_s": 2.5e7})  # no recovery
    out = _run(d)
    assert "keep block default" in out
    assert "keep partial/phase" in out
    assert "full-width stands" in out
    assert "read cost_train" in out


def test_empty_artifacts_stay_pending(tmp_path):
    (tmp_path / "einsum_512.json").write_text("")  # hygiene case
    out = _run(tmp_path)
    assert "einsum_512" in out
    # the empty file must not parse as a number
    for line in out.splitlines():
        if line.startswith("einsum_512 "):
            assert "PENDING" in line
