"""Benchmark: epochs/sec through dwt-8 feature extraction on device.

The BASELINE.json headline metric: (3ch x 1000samp) epochs through the
batched eegdsp-parity DWT feature extractor (slice [175,687) -> 6-level
db10 cascade -> 48-dim L2-normalized features), target >= 50,000
epochs/sec on one TPU v5e chip. Prints exactly one JSON line.

Beyond the headline, the same line carries the fused-ingest and
train-step variants (tools/ingest_bench.py) with HBM-roofline context:

  einsum          f32 epochs resident in HBM -> features (headline)
  einsum_bf16     bf16-resident twin of the headline
  regular_ingest  fused int16 ingest, fixed-SOA stimulus train ->
                  features (formulation auto: phase on TPU)
  block_ingest    fused int16 ingest, irregular markers -> features
                  via tile-row gathers + the 128-variant operator
                  bank (XLA-only; no element gather)
  train_step      f32 epochs -> features -> MLP fwd/bwd/update
  train_step_raw  int16 stream -> fused ingest -> features -> MLP
                  fwd/bwd/update (training at int16 bytes/epoch)
  train_step_block  int16 stream + IRREGULAR markers -> block-gather
                  fused ingest -> features -> MLP fwd/bwd/update
  pallas_ingest   fused int16 ingest, irregular marker positions ->
                  features (ops/ingest_pallas.py kernel)

Resilience contract (round-1 BENCH artifact died rc=1 on a single
``Unable to initialize backend 'axon': UNAVAILABLE``): the parent
process never touches JAX. It probes the TPU backend in a subprocess
(tools/probe_tpu.py — device enumeration AND one jitted op, so a
tunnel that lists devices but cannot compile is caught here instead
of burning every variant's timeout); each variant then runs in its
own fresh child with its own deadline, and a variant failure is
recorded in the payload instead of killing the artifact. If the TPU
is not available, the same measurements run on CPU and the JSON line
says so via ``"platform": "cpu_fallback"`` — a parseable, honest
number instead of a dead artifact.

Probe design vs the axon tunnel's observed failure modes: ONE
generous probe (default 420 s, ``BENCH_PROBE_TIMEOUT``) instead of
round 2's five short timeout-killed attempts — a healthy-but-cold
tunnel inits well inside the budget, a down-but-failing-fast tunnel
surfaces UNAVAILABLE by itself at ~25 min (we stop waiting at the
budget), and killing a probe mid-init is the known tunnel-wedging
event, so fewer, longer probes strictly reduce wedge exposure.
"""

import json
import os
import subprocess
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _REPO_ROOT)

BASELINE_EPOCHS_PER_SEC = 50_000.0

# One generous probe (see docstring): healthy cold init is ~1-2 min,
# and short timeout-killed probes are the tunnel-wedging event.
_PROBE_TIMEOUT_S = int(os.environ.get("BENCH_PROBE_TIMEOUT", 420))
# One real-chip measurement (includes ~20-40s first compile).
_RUN_TIMEOUT_S = int(os.environ.get("BENCH_RUN_TIMEOUT", 420))
# Total wall budget for the variant loop: the headline always runs;
# a further variant starts only if it could finish inside the budget.
# Keeps the whole artifact comfortably under driver patience so the
# parent is never killed mid-variant (which loses the JSON line and
# can wedge the tunnel).
# Default scales with the per-variant timeout AND the variant count
# (budget ~ one timeout per variant), capped at 40 min to stay under
# driver patience — real variants run 1-3 min each (sweep evidence),
# so the cap only bites if several variants hit their full timeout;
# BENCH_TOTAL_BUDGET overrides.
_N_VARIANTS = 8  # asserted against the variant tables below
_TOTAL_BUDGET_S = int(
    os.environ.get(
        "BENCH_TOTAL_BUDGET",
        min(2400, max(1500, _N_VARIANTS * _RUN_TIMEOUT_S)),
    )
)

# (n_epochs, iters) per variant: TPU-sized vs CPU-fallback-sized.
# BENCH_BATCH / BENCH_ITERS override the headline (einsum) sizing,
# e.g. to fit a smaller chip.
_VARIANTS_TPU = {
    "einsum": (
        int(os.environ.get("BENCH_BATCH", 262144)),
        int(os.environ.get("BENCH_ITERS", 50)),
    ),
    # the bf16 twin runs at 2x the headline batch: the r4 chip batch
    # curve (39.8% @131k, 55.7% @262k, 69.8% @524k of roofline)
    # showed the 2-byte stream needs the larger dispatch to amortize
    "einsum_bf16": (
        2 * int(os.environ.get("BENCH_BATCH", 262144)),
        int(os.environ.get("BENCH_ITERS", 50)),
    ),
    "regular_ingest": (262144, 20),
    "block_ingest": (32768, 10),
    "train_step": (131072, 20),
    "train_step_raw": (131072, 20),
    "train_step_block": (32768, 10),
    # last (longest fresh compile): the bank128 kernel, the one
    # formulation that compiles through the axon remote helper
    "pallas_ingest": (131072, 20),
}
_VARIANTS_CPU = {
    "einsum": (8192, 5),
    "einsum_bf16": (8192, 3),
    "regular_ingest": (8192, 3),
    "block_ingest": (2048, 2),
    "train_step": (8192, 3),
    "train_step_raw": (4096, 2),
    "train_step_block": (2048, 2),
    "pallas_ingest": (2048, 2),
}
assert len(_VARIANTS_TPU) == len(_VARIANTS_CPU) == _N_VARIANTS


def _tpu_available() -> bool:
    """One generous kill-averse probe: device enumeration + a jitted
    op on a real accelerator platform (tools/probe_tpu.py prints one
    JSON line and returns on its own; the subprocess timeout is a
    last resort, not the schedule)."""
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        return False
    proc = subprocess.Popen(
        [
            sys.executable,
            os.path.join(_REPO_ROOT, "tools", "probe_tpu.py"),
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
    )
    deadline = time.monotonic() + _PROBE_TIMEOUT_S
    while proc.poll() is None and time.monotonic() < deadline:
        time.sleep(2)
    if proc.poll() is None:
        # Budget exhausted while the probe is still mid device-init:
        # ABANDON it, never kill it — SIGKILLing an axon process
        # mid-init is the known tunnel-wedging event. The orphan
        # finishes (or errors) on its own and exits.
        print(
            f"bench: TPU probe still initializing after "
            f"{_PROBE_TIMEOUT_S}s; abandoning it (no kill) and "
            f"falling back to CPU",
            file=sys.stderr,
        )
        return False
    stdout = proc.stdout.read() if proc.stdout else ""
    try:
        out = json.loads(stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        print(f"bench: unparseable probe output: {stdout[-200:]}",
              file=sys.stderr)
        return False
    ok = bool(out.get("ok")) and out.get("platform") in ("axon", "tpu")
    if not ok:
        print(f"bench: TPU unavailable ({out})", file=sys.stderr)
    return ok


def _cpu_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # axon hook never registers
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _run_variant(variant: str, platform: str, n: int, iters: int) -> dict:
    """Run one variant in a fresh child; returns its parsed JSON."""
    if platform == "tpu":
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    else:
        env = _cpu_env()
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(_REPO_ROOT, "tools", "ingest_bench.py"),
            variant,
            str(n),
            str(iters),
        ],
        timeout=_RUN_TIMEOUT_S,
        capture_output=True,
        text=True,
        env=env,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"variant {variant} rc={proc.returncode}\n{proc.stderr[-1500:]}"
        )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _collect(platform: str) -> dict:
    sizes = _VARIANTS_TPU if platform == "tpu" else _VARIANTS_CPU
    variants: dict = {}
    start = time.monotonic()
    for idx, (name, (n, iters)) in enumerate(sizes.items()):
        remaining = _TOTAL_BUDGET_S - (time.monotonic() - start)
        if idx > 0 and remaining < _RUN_TIMEOUT_S:
            variants[name] = {"error": "skipped: total budget exhausted"}
            continue
        try:
            r = _run_variant(name, platform, n, iters)
            variants[name] = {
                "epochs_per_s": r["epochs_per_s"],
                "bytes_per_epoch": r["bytes_per_epoch"],
            }
            # present only for TPU timings (ingest_bench omits it on
            # CPU so fallback output can't be misread as a roofline)
            if "pct_of_hbm_roofline" in r:
                variants[name]["pct_of_hbm_roofline"] = r[
                    "pct_of_hbm_roofline"
                ]
            if "formulation" in r:
                variants[name]["formulation"] = r["formulation"]
        except (RuntimeError, subprocess.TimeoutExpired, ValueError,
                KeyError) as e:
            variants[name] = {"error": str(e)[:300]}
    if "epochs_per_s" not in variants.get("einsum", {}):
        raise RuntimeError(f"headline variant failed: {variants}")
    eps = variants["einsum"]["epochs_per_s"]
    payload = {
        "metric": (
            "epochs/sec (3ch×1000samp) through dwt-8 feature extraction"
        ),
        "value": eps,
        "unit": "epochs/s",
        "vs_baseline": round(eps / BASELINE_EPOCHS_PER_SEC, 3),
        "variants": variants,
    }
    if "pct_of_hbm_roofline" in variants["einsum"]:
        payload["pct_of_hbm_roofline"] = variants["einsum"][
            "pct_of_hbm_roofline"
        ]
    if platform != "tpu":
        payload["platform"] = "cpu_fallback"
    return payload


def main() -> None:
    if _tpu_available():
        try:
            payload = _collect("tpu")
        except (RuntimeError, subprocess.TimeoutExpired, ValueError) as e:
            print(f"bench: TPU run failed ({e}); CPU fallback", file=sys.stderr)
            payload = _collect("cpu")
    else:
        payload = _collect("cpu")
    print(json.dumps(payload))


if __name__ == "__main__":
    main()
