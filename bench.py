"""Benchmark: epochs/sec through dwt-8 feature extraction on device.

The BASELINE.json headline metric: (3ch x 1000samp) epochs through the
batched eegdsp-parity DWT feature extractor (slice [175,687) -> 6-level
db10 cascade -> 48-dim L2-normalized features), target >= 50,000
epochs/sec on one TPU v5e chip. Prints exactly one JSON line.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_EPOCHS_PER_SEC = 50_000.0


def main() -> None:
    from eeg_dataanalysispackage_tpu.ops import dwt as dwt_xla

    # 262144 epochs x 3x1000 f32 = 3.1 GB in HBM; measured ~6% more
    # throughput than 131072 on v5e (39.7M vs 37.4M epochs/s)
    batch = int(os.environ.get("BENCH_BATCH", 262144))
    iters = int(os.environ.get("BENCH_ITERS", 50))

    extract = dwt_xla.make_batched_extractor(
        wavelet_index=8, epoch_size=512, skip_samples=175, feature_size=16
    )

    key = jax.random.PRNGKey(0)
    epochs = jax.random.normal(key, (batch, 3, 1000), dtype=jnp.float32) * 50.0

    # The axon tunnel does not synchronize on block_until_ready, so the
    # iteration loop runs inside one jitted lax.scan and the timing is
    # closed by fetching a scalar that depends on every iteration.
    @jax.jit
    def bench_loop(x):
        def body(acc, i):
            y = extract(x + i.astype(jnp.float32))
            return acc + y.sum(), None

        acc, _ = jax.lax.scan(body, jnp.float32(0), jnp.arange(iters))
        return acc

    float(bench_loop(epochs))  # warmup + compile

    start = time.perf_counter()
    checksum = float(bench_loop(epochs))
    elapsed = time.perf_counter() - start
    assert np.isfinite(checksum), "non-finite checksum"

    eps = batch * iters / elapsed
    print(
        json.dumps(
            {
                "metric": "epochs/sec (3ch×1000samp) through dwt-8 feature extraction",
                "value": round(eps, 1),
                "unit": "epochs/s",
                "vs_baseline": round(eps / BASELINE_EPOCHS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
