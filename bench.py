"""Benchmark: epochs/sec through dwt-8 feature extraction on device.

The BASELINE.json headline metric: (3ch x 1000samp) epochs through the
batched eegdsp-parity DWT feature extractor (slice [175,687) -> 6-level
db10 cascade -> 48-dim L2-normalized features), target >= 50,000
epochs/sec on one TPU v5e chip. Prints exactly one JSON line.

Resilience contract (round-1 BENCH artifact died rc=1 on a single
``Unable to initialize backend 'axon': UNAVAILABLE``): the parent
process never touches JAX. It probes the TPU backend in a
timeout-guarded subprocess with bounded backoff; when the backend
comes up, the measurement itself runs in a fresh child with its own
deadline. If the TPU never becomes available within the retry budget,
the same measurement runs on CPU and the JSON line says so via
``"platform": "cpu_fallback"`` — a parseable, honest number instead of
a dead artifact.
"""

import json
import os
import subprocess
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _REPO_ROOT)

BASELINE_EPOCHS_PER_SEC = 50_000.0

# Backend probe schedule: attempt, then sleep; total budget ~4 min.
_PROBE_TIMEOUT_S = 75
_PROBE_SLEEPS_S = (10, 20, 40, 60)
# One real-chip measurement (includes ~20-40s first compile).
_RUN_TIMEOUT_S = int(os.environ.get("BENCH_RUN_TIMEOUT", 420))


def _probe_tpu_once() -> bool:
    """True iff a fresh interpreter can enumerate the axon devices."""
    try:
        proc = subprocess.run(
            [
                sys.executable,
                "-c",
                "import jax; d = jax.devices(); "
                "print(d[0].platform, len(d))",
            ],
            timeout=_PROBE_TIMEOUT_S,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        return False
    return proc.returncode == 0


def _tpu_available() -> bool:
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        return False
    for i, sleep_s in enumerate((*_PROBE_SLEEPS_S, 0)):
        if _probe_tpu_once():
            return True
        print(
            f"bench: TPU probe {i + 1} failed; "
            f"retrying in {sleep_s}s" if sleep_s else "bench: TPU unavailable",
            file=sys.stderr,
        )
        if sleep_s:
            time.sleep(sleep_s)
    return False


def _cpu_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # axon hook never registers
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _run_child(platform: str) -> dict:
    """Run the measurement in a fresh child; returns the parsed JSON."""
    if platform == "tpu":
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    else:
        env = _cpu_env()
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child"],
        timeout=_RUN_TIMEOUT_S,
        capture_output=True,
        text=True,
        env=env,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"bench child rc={proc.returncode}\n{proc.stderr[-2000:]}"
        )
    # last stdout line is the JSON payload
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _measure() -> dict:
    """The measurement body (child process; JAX is safe to touch here)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from eeg_dataanalysispackage_tpu.ops import dwt as dwt_xla

    platform = jax.devices()[0].platform
    on_tpu = platform not in ("cpu",)

    # 262144 epochs x 3x1000 f32 = 3.1 GB in HBM; measured ~6% more
    # throughput than 131072 on v5e (39.7M vs 37.4M epochs/s). CPU
    # fallback uses a small batch so the artifact stays fast.
    batch = int(os.environ.get("BENCH_BATCH", 262144 if on_tpu else 8192))
    iters = int(os.environ.get("BENCH_ITERS", 50 if on_tpu else 5))

    extract = dwt_xla.make_batched_extractor(
        wavelet_index=8, epoch_size=512, skip_samples=175, feature_size=16
    )

    key = jax.random.PRNGKey(0)
    epochs = jax.random.normal(key, (batch, 3, 1000), dtype=jnp.float32) * 50.0

    # The axon tunnel does not synchronize on block_until_ready, so the
    # iteration loop runs inside one jitted lax.scan and the timing is
    # closed by fetching a scalar that depends on every iteration.
    @jax.jit
    def bench_loop(x):
        def body(acc, i):
            y = extract(x + i.astype(jnp.float32))
            return acc + y.sum(), None

        acc, _ = jax.lax.scan(body, jnp.float32(0), jnp.arange(iters))
        return acc

    float(bench_loop(epochs))  # warmup + compile

    start = time.perf_counter()
    checksum = float(bench_loop(epochs))
    elapsed = time.perf_counter() - start
    assert np.isfinite(checksum), "non-finite checksum"

    eps = batch * iters / elapsed
    payload = {
        "metric": "epochs/sec (3ch×1000samp) through dwt-8 feature extraction",
        "value": round(eps, 1),
        "unit": "epochs/s",
        "vs_baseline": round(eps / BASELINE_EPOCHS_PER_SEC, 3),
    }
    if not on_tpu:
        payload["platform"] = "cpu_fallback"
    return payload


def main() -> None:
    if _tpu_available():
        try:
            payload = _run_child("tpu")
        except (RuntimeError, subprocess.TimeoutExpired, ValueError) as e:
            print(f"bench: TPU run failed ({e}); CPU fallback", file=sys.stderr)
            payload = _run_child("cpu")
    else:
        payload = _run_child("cpu")
    print(json.dumps(payload))


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        print(json.dumps(_measure()))
    else:
        main()
