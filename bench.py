"""Benchmark: epochs/sec through dwt-8 feature extraction on device.

The BASELINE.json headline metric: (3ch x 1000samp) epochs through the
batched eegdsp-parity DWT feature extractor (slice [175,687) -> 6-level
db10 cascade -> 48-dim L2-normalized features), target >= 50,000
epochs/sec on one TPU v5e chip. Prints exactly one JSON line.

Beyond the headline, the same line carries the fused-ingest and
train-step variants (tools/ingest_bench.py) with HBM-roofline context:

  einsum          f32 epochs resident in HBM -> features (headline)
  einsum_bf16     bf16-resident twin of the headline
  einsum_512      compact-resident (B, C, 512) twin (honest 6144
                  B/epoch); einsum_512_bf16: its bf16 form (3072 B)
  regular_ingest  fused int16 ingest, fixed-SOA stimulus train ->
                  features (formulation auto: phase on TPU)
  block_ingest    fused int16 ingest, irregular markers -> features
                  via tile-row gathers + the 128-variant operator
                  bank (XLA-only; no element gather)
  decode_ingest   fused int16 ingest, irregular markers -> features
                  via the decode rung (ops/decode_ingest.py): windows
                  cut by dynamic slices in split tiled scans (CPU) or
                  the bank128 VMEM kernel (accelerators); the line's
                  ``gather_baseline`` block records the same-machine
                  element-gather throughput and the decode/gather
                  ratio — the irregular-ingest-gap headline
  train_step      f32 epochs -> features -> MLP fwd/bwd/update
  train_step_512  the train step over compact-resident (B, C, 512)
                  epochs (honest 6144 B/epoch)
  train_step_raw  int16 stream -> fused ingest -> features -> MLP
                  fwd/bwd/update (training at int16 bytes/epoch)
  train_step_block  int16 stream + IRREGULAR markers -> block-gather
                  fused ingest -> features -> MLP fwd/bwd/update
  pallas_ingest   fused int16 ingest, irregular marker positions ->
                  features (ops/ingest_pallas.py kernel)
  pipeline_e2e_cold / _warm / _fanout5
                  whole-pipeline wall time over a hermetic synthetic
                  session (tools/pipeline_bench.py): cold feature
                  cache, warm feature cache (populated by a separate
                  process), and a 5-classifier shared-feature fan-out
                  — the end-to-end numbers the kernel epochs/s lines
                  never captured, meaningful even on cpu_fallback
                  (the wins are host-side)
  pipeline_e2e_overlap / _bf16
                  the cold query's two knobs, each isolating one
                  variable against pipeline_e2e_cold: overlap=true
                  (double-buffered ingest/compute — report_sha256
                  equality is the bit-identical-statistics pin) and
                  precision=bf16 (the accuracy-gated bfloat16 feature
                  path — the line's ``precision`` block records the
                  gate decision)
  population_vmap / population_looped
                  a 16-member population (cv=4 x a 2x2 lr/reg grid,
                  models/population.py) trained as one vmapped
                  program vs the same members dispatched sequentially;
                  each line carries the stages breakdown (the train-
                  stage delta is the engine's win) and the per-member
                  accuracy table, with report_sha256 equality across
                  the pair proving per-member statistics parity
  population_sharded
                  the identical member set with the MEMBER axis
                  sharded over a device mesh (devices=8 through the
                  pipeline's mesh family; a virtual 8-device host
                  platform on the CPU fallback) — the line's ``mesh``
                  block records rung/shape/per-device member counts
                  and ``members_per_s`` the member-axis rate;
                  population_vmap from the same run is its
                  same-machine single-device twin and the
                  report_sha256 pair pins sharded==vmap statistics
  population_multiproc
                  the identical member set as a 2-PROCESS loopback
                  pod (tools/pipeline_bench.py: processes=2 over a
                  gloo coordinator — per-host partitioned ingest
                  feeding the global member axis over the DCN
                  stand-in) vs its single-process twin in an equally
                  fresh process; the line's ``multiproc`` block
                  carries both members/sec rates, the statistics-
                  parity sha verdict, the pod mesh block
                  ({processes, process_id, coordinator, dcn_shape}),
                  and the degraded-coordinator run's rung + parity
  sharded_ingest  fused int16 ingest with the recording time-sharded
                  over an (up to) 8-device mesh
                  (parallel/sharded_ingest.py ring-halo epoching);
                  the line's ``mesh`` block records the compiled
                  collective-permute count and the same-machine
                  single-device twin eps + ratio
  seizure_e2e     the continuous-EEG seizure workload (task=seizure,
                  docs/workloads.md): sliding-window epoching over an
                  annotated synthetic session, per-subband wavelet
                  features, cost-sensitive logreg — the line's
                  ``seizure`` block records windows/sec, the class
                  ratio, and recall/expected-cost at the configured
                  asymmetric costs (tools/pipeline_bench.py)
  serve_bench     the resident online inference service (serve/):
                  p50/p99 latency and sustained predictions/sec at
                  swept concurrency through the micro-batching front
                  end, with the served-vs-batch parity pin, the
                  admission-control shed probe, and a chaos soak
                  (serve.request/serve.batch faults) all recorded in
                  the line's ``serve`` block (tools/serve_bench.py);
                  every sweep level carries its engine rung and its
                  own mean_batch_size
  serve_mega      the serve-path megakernel family (ops/serve_mega.py
                  via tools/serve_bench.py): mega vs fused swept
                  back-to-back in ONE process at concurrency 1/4/16 —
                  per-level preds/sec + p99 pairs with rung
                  attribution, the mega-vs-fused and mega-vs-batch
                  prediction parity pins, the within-bucket margin
                  bit-identity pin, the engine's mega warmup-gate
                  record, and the int8 rung's gate decision
  serve_lifecycle the model lifecycle manager (serve/lifecycle.py
                  via tools/serve_bench.py): each concurrency level
                  swept steady-state then again with a feedback feeder
                  racing it (partial-fit chunks + a gated promotion
                  land mid-traffic) — per-level p50/p99 + preds/sec
                  pairs with the across-promotion p99 ratio, the
                  no-swap and promoted==batch parity pins, and the
                  serve.swap/serve.adapt chaos soak
  serve_multitenant
                  the multiplexed multi-tenant engine
                  (serve/multiplex.py via tools/serve_bench.py): at
                  each tenant level 1/4/16, ONE resident service
                  carrying N tenant models vs a fleet of N solo
                  services over the same models, back-to-back at
                  concurrency 16 — per-level preds/sec + p50/p99
                  pairs with the ratio, the per-tenant
                  multiplexed-vs-solo parity pin, the 0-compile
                  scaling and hot-swap pins, and the resident weight
                  bytes (one stacked matrix vs N engines)
  pipeline_e2e_int8
                  the cold query with precision=int8 (per-subband
                  feature quantization behind the per-run gate — the
                  rung below bf16; the line's ``precision`` block
                  records the decision + gate_seconds)
  pipeline_e2e_int4
                  the cold query with precision=int4 (nibble-packed
                  feature rows, two per byte, per-(channel, subband)
                  group scales — the bottom rung of the ladder, same
                  per-run gate machinery with the widest envelope)
  serve_multitenant_quant
                  the quantized tenant weight stack
                  (weights_precision=int4 on serve/multiplex.py via
                  tools/serve_bench.py): 16 tenants through the
                  packed int4 stack + per-lane scales vs the same 16
                  through the f32 multiplexed twin at concurrency 16
                  — preds/sec pair + ratio, per-tenant margin parity
                  within the weights gate tolerance, the
                  resident-weight-bytes reduction (>=4x), and the
                  0-compile add/swap/remove pin on the live
                  quantized stack

Resilience contract (round-1 BENCH artifact died rc=1 on a single
``Unable to initialize backend 'axon': UNAVAILABLE``): the parent
process never touches JAX. It probes the TPU backend in a subprocess
(tools/probe_tpu.py — device enumeration AND one jitted op, so a
tunnel that lists devices but cannot compile is caught here instead
of burning every variant's timeout); each variant then runs in its
own fresh child with its own deadline, and a variant failure is
recorded in the payload instead of killing the artifact. If the TPU
is not available, the same measurements run on CPU and the JSON line
says so via ``"platform": "cpu_fallback"`` — a parseable, honest
number instead of a dead artifact.

Probe design vs the axon tunnel's observed failure modes: ONE
generous probe (default 420 s, ``BENCH_PROBE_TIMEOUT``) instead of
round 2's five short timeout-killed attempts — a healthy-but-cold
tunnel inits well inside the budget, a down-but-failing-fast tunnel
surfaces UNAVAILABLE by itself at ~25 min (we stop waiting at the
budget), and killing a probe mid-init is the known tunnel-wedging
event, so fewer, longer probes strictly reduce wedge exposure.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

_REPO_ROOT = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _REPO_ROOT)

# Persistent compile cache for every child this driver spawns: the
# parent never imports jax (resilience contract), but it exports
# JAX_COMPILATION_CACHE_DIR (utils/compile_cache.prime_env — jax-free)
# so probe and variant children all read/write one repo-local cache
# and a repeat bench run is warm. BENCH_NO_COMPILE_CACHE /
# EEG_TPU_NO_COMPILE_CACHE opt out; each variant line records the
# directory actually in effect as its ``compile_cache`` field.
from eeg_dataanalysispackage_tpu.utils import compile_cache as _compile_cache

if os.environ.get("BENCH_NO_COMPILE_CACHE"):
    os.environ.setdefault(_compile_cache.ENV_DISABLE, "1")
_COMPILE_CACHE_DIR = _compile_cache.prime_env(
    os.path.join(_REPO_ROOT, ".jax_compile_cache")
)
# Cross-process gather-plan persistence (ops/plan_cache.save_file /
# load_file): every variant runs in its own fresh child, so without
# this file each recorded block_ingest/pallas_ingest line showed
# ``plan_cache hits: 0`` unconditionally — cache effectiveness was
# structurally unmeasurable. Children load it before timing and save
# the union after; a REPEAT bench run (and later variants sharing a
# layout) report real hit counts. BENCH_NO_PLAN_CACHE_FILE opts out.
if not os.environ.get("BENCH_NO_PLAN_CACHE_FILE"):
    os.environ.setdefault(
        "EEG_TPU_PLAN_CACHE_FILE",
        os.path.join(_REPO_ROOT, ".jax_compile_cache", "plan_cache.pkl"),
    )

BASELINE_EPOCHS_PER_SEC = 50_000.0

# One generous probe (see docstring): healthy cold init is ~1-2 min,
# and short timeout-killed probes are the tunnel-wedging event.
_PROBE_TIMEOUT_S = int(os.environ.get("BENCH_PROBE_TIMEOUT", 420))
# One real-chip measurement (includes ~20-40s first compile).
_RUN_TIMEOUT_S = int(os.environ.get("BENCH_RUN_TIMEOUT", 420))
# Fresh chip compiles of the fused-ingest programs ran 10-14 min in
# the r4 sweep (tools/sweep_results/r4/watch.log; worst observed
# 888s), so the fused variants get a wider deadline with real
# headroom, never below the general timeout (raising
# BENCH_RUN_TIMEOUT past it must not shrink the slow variants'
# budget). With a warm persistent compile cache
# (tools/ingest_bench.py) they finish in ~1-2 min and the headroom
# is never spent.
_SLOW_COMPILE_TIMEOUT_S = max(
    int(os.environ.get("BENCH_SLOW_TIMEOUT", 1200)), _RUN_TIMEOUT_S
)
_VARIANT_TIMEOUTS = {
    "regular_ingest": _SLOW_COMPILE_TIMEOUT_S,
    "train_step_raw": _SLOW_COMPILE_TIMEOUT_S,
    "pallas_ingest": _SLOW_COMPILE_TIMEOUT_S,
    # decode routes to the bank128 Pallas kernel on accelerators —
    # same fresh-compile class as pallas_ingest
    "decode_ingest": _SLOW_COMPILE_TIMEOUT_S,
    # the serve megakernel compiles through Mosaic on accelerators —
    # same fresh-compile class
    "serve_mega": _SLOW_COMPILE_TIMEOUT_S,
    # the lifecycle child warms FOUR services (each compiling the
    # fused program cold) plus the partial-fit chunk program and a
    # full adapt pipeline run — same fresh-compile class
    "serve_lifecycle": _SLOW_COMPILE_TIMEOUT_S,
    # the multitenant child compiles the multi-tenant fused AND mega
    # programs cold, then drives six sweeps (multiplexed + fleet at
    # three tenant levels) — same fresh-compile class
    "serve_multitenant": _SLOW_COMPILE_TIMEOUT_S,
    # the quantized-stack child compiles the packed-weights fused AND
    # mega lowerings cold on top of the f32 multiplexed twin — same
    # fresh-compile class
    "serve_multitenant_quant": _SLOW_COMPILE_TIMEOUT_S,
    # four fresh pipeline processes (2 pod workers + twin + degraded
    # run) in one child — the wall is ~4 population_vmap runs
    "population_multiproc": _SLOW_COMPILE_TIMEOUT_S,
    # five fresh processes (3 gateway replicas + 2 twins), each
    # compiling cold, plus the lease-timeout failover wait — same
    # fresh-compile class
    "gateway_fleet": _SLOW_COMPILE_TIMEOUT_S,
    # eight fresh processes (3 replicas x 2 phases + 2 twins), each
    # compiling cold, plus the gang's placement wait — same
    # fresh-compile class
    "fleet_placement": _SLOW_COMPILE_TIMEOUT_S,
}
# Total wall budget for the variant loop: the headline always runs;
# a further variant starts only if it could finish inside the budget
# (per-variant deadline, see the skip check). Default sums the
# per-variant deadlines, capped at 50 min to stay under driver
# patience — on a warm compile cache everything fits easily; on a
# cold cache the tail variants may be budget-skipped (recorded as
# such, artifact intact). BENCH_TOTAL_BUDGET overrides.
_N_VARIANTS = 34  # asserted against the variant tables below
_TOTAL_BUDGET_S = int(
    os.environ.get(
        "BENCH_TOTAL_BUDGET",
        min(
            3000,
            sum(_VARIANT_TIMEOUTS.values())
            + (_N_VARIANTS - len(_VARIANT_TIMEOUTS)) * _RUN_TIMEOUT_S,
        ),
    )
)

# (n_epochs, iters) per variant: TPU-sized vs CPU-fallback-sized.
# BENCH_BATCH / BENCH_ITERS override the headline (einsum) sizing,
# e.g. to fit a smaller chip.
_VARIANTS_TPU = {
    "einsum": (
        int(os.environ.get("BENCH_BATCH", 262144)),
        int(os.environ.get("BENCH_ITERS", 50)),
    ),
    # the bf16 twin runs at 2x the headline batch: the r4 chip batch
    # curve (39.8% @131k, 55.7% @262k, 69.8% @524k of roofline)
    # showed the 2-byte stream needs the larger dispatch to amortize
    "einsum_bf16": (
        2 * int(os.environ.get("BENCH_BATCH", 262144)),
        int(os.environ.get("BENCH_ITERS", 50)),
    ),
    # compact-resident layouts (honest bytes: 6144 f32 / 3072 bf16
    # per epoch) — the armed headline candidates (VERDICT r4 item 7);
    # bf16 at 2x batch for the same dispatch-amortization reason as
    # einsum_bf16
    "einsum_512": (
        int(os.environ.get("BENCH_BATCH", 262144)),
        int(os.environ.get("BENCH_ITERS", 50)),
    ),
    "einsum_512_bf16": (
        2 * int(os.environ.get("BENCH_BATCH", 262144)),
        int(os.environ.get("BENCH_ITERS", 50)),
    ),
    "regular_ingest": (262144, 20),
    "block_ingest": (32768, 10),
    # the decode rung (bank128 routing on chip); its line also times
    # the element-gather rung on the same data for the ratio
    "decode_ingest": (131072, 20),
    "train_step": (131072, 20),
    # the compact train twin at the headline batch (honest 6144
    # B/epoch step read)
    "train_step_512": (262144, 30),
    "train_step_raw": (131072, 20),
    "train_step_block": (32768, 10),
    # last (longest fresh compile): the bank128 kernel, the one
    # formulation that compiles through the axon remote helper
    "pallas_ingest": (131072, 20),
    # whole-pipeline wall time (tools/pipeline_bench.py): (markers per
    # file, file count) — parse + fused featurize + train + test over
    # the hermetic synthetic session; cold vs warm isolates the
    # feature cache, fanout5 amortizes one ingest over 5 classifiers
    "pipeline_e2e_cold": (2000, 4),
    "pipeline_e2e_warm": (2000, 4),
    "pipeline_e2e_fanout5": (2000, 4),
    # the cold query's overlap=true / precision=bf16 twins (each
    # isolates one knob against pipeline_e2e_cold)
    "pipeline_e2e_overlap": (2000, 4),
    "pipeline_e2e_bf16": (2000, 4),
    # the int8 precision rung's cold twin (per-subband feature
    # quantization behind the per-run gate)
    "pipeline_e2e_int8": (2000, 4),
    # the int4 rung's cold twin (nibble-packed feature rows, widest
    # gate envelope on the ladder)
    "pipeline_e2e_int4": (2000, 4),
    # population training engine (markers per file, file count): 16
    # SGD members as one vmapped program vs the same members looped,
    # plus the member axis sharded over the device mesh
    "population_vmap": (800, 2),
    "population_looped": (800, 2),
    "population_sharded": (800, 2),
    # the 2-process loopback pod vs its single-process twin
    # (tools/pipeline_bench.py population_multiproc): per-host
    # partitioned ingest feeding the global member axis, parity sha +
    # members/sec ratio + the degraded-coordinator run on the line
    "population_multiproc": (800, 2),
    # time-sharded fused ingest over the mesh (epochs, iters) with
    # its same-machine single-device twin on the line
    "sharded_ingest": (32768, 10),
    # the continuous-EEG seizure workload (samples per file, file
    # count — tools/pipeline_bench.py seizure_e2e): sliding windows +
    # subband features + cost-sensitive training; the line records
    # windows/sec, class ratio, recall and expected cost
    "seizure_e2e": (120000, 2),
    # online inference service (markers per file, file count):
    # latency/throughput sweep + parity pin + chaos soak
    "serve_bench": (2000, 2),
    # the serve-path megakernel vs its fused twin, back-to-back in
    # one process (per-level rung attribution + parity pins)
    "serve_mega": (2000, 2),
    # the model lifecycle manager (serve/lifecycle.py): swap under
    # load (steady vs under-adapt p50/p99 per level, swaps counted on
    # the line), the no-swap + promoted==batch parity pins, and the
    # serve.swap/serve.adapt chaos soak
    "serve_lifecycle": (2000, 2),
    # the multiplexed multi-tenant engine vs the solo fleet it
    # replaces, per tenant level (parity + 0-compile pins on the
    # line; multiplex.accelerator_decision harvests the 16-tenant
    # level from staged runs)
    "serve_multitenant": (2000, 2),
    # the multi-tenant plan executor (markers per file, file count —
    # tools/pipeline_bench.py scheduler_multi): 4 plans sequential vs
    # concurrent over shared caches, per-plan isolated attribution,
    # the single-flight store pin, and the kill-and-resume scenario
    "scheduler_multi": (2000, 4),
    # the networked plan service (tools/pipeline_bench.py
    # plan_service): shared-prefix pair over loopback HTTP (one
    # prefix build, statistics byte-identical to solo), idempotent
    # re-submit replay, many-client chaos soak with submits/sec
    "plan_service": (2000, 4),
    # the 16-tenant quantized (int4 packed + per-lane scales) weight
    # stack vs the f32 multiplexed twin: preds/sec ratio, per-tenant
    # margin parity, resident-weight-bytes reduction, and the
    # 0-compile add/swap/remove pin on the quantized stack
    "serve_multitenant_quant": (2000, 2),
    # the replicated gateway fleet (tools/pipeline_bench.py
    # gateway_fleet): 3 real replica processes over one shared
    # journal, SIGKILL the in-flight holder, takeover sha pinned
    # byte-identical to an uninterrupted twin, zero-double-execution
    # audit, SIGTERM drain of the survivors (all CPU-forced children
    # — the line measures failover, not chip throughput). Small
    # session on purpose: per-SGD-iteration cost scales with the
    # session, and the heavy plan's kill window is sized in
    # iterations — a big session turns the twin + takeover re-run
    # into minutes without sharpening any failover pin
    "gateway_fleet": (400, 2),
    # device-aware fleet placement (tools/pipeline_bench.py
    # fleet_placement): the same 3-replica fleet run twice over a
    # forced-8-virtual-device host — device pool on vs off — with one
    # whole-pool gang plan + 4 single-device plans. The line carries
    # the makespan ratio, per-plan sha parity against fresh-process
    # twins, and the live zero-double-held device-lease audit. Same
    # small session reasoning as gateway_fleet.
    "fleet_placement": (400, 2),
}
_VARIANTS_CPU = {
    "einsum": (8192, 5),
    "einsum_bf16": (8192, 3),
    "einsum_512": (8192, 3),
    "einsum_512_bf16": (8192, 3),
    "regular_ingest": (8192, 3),
    "block_ingest": (2048, 2),
    "decode_ingest": (8192, 5),
    "train_step": (8192, 3),
    "train_step_512": (8192, 3),
    "train_step_raw": (4096, 2),
    "train_step_block": (2048, 2),
    "pallas_ingest": (2048, 2),
    "pipeline_e2e_cold": (2000, 4),
    "pipeline_e2e_warm": (2000, 4),
    "pipeline_e2e_fanout5": (2000, 4),
    "pipeline_e2e_overlap": (2000, 4),
    "pipeline_e2e_bf16": (2000, 4),
    "pipeline_e2e_int8": (2000, 4),
    "pipeline_e2e_int4": (2000, 4),
    "population_vmap": (800, 2),
    "population_looped": (800, 2),
    "population_sharded": (800, 2),
    "population_multiproc": (800, 2),
    "sharded_ingest": (2048, 2),
    "seizure_e2e": (60000, 2),
    "serve_bench": (400, 2),
    "serve_mega": (400, 2),
    "serve_lifecycle": (400, 2),
    "serve_multitenant": (400, 2),
    "serve_multitenant_quant": (400, 2),
    "scheduler_multi": (2000, 4),
    "plan_service": (2000, 4),
    "gateway_fleet": (400, 2),
    "fleet_placement": (400, 2),
}
assert len(_VARIANTS_TPU) == len(_VARIANTS_CPU) == _N_VARIANTS


class _Abandoned(RuntimeError):
    """A child overran its deadline and was abandoned (never killed —
    SIGKILLing an axon process mid-compile/init is the known
    tunnel-wedging event). The orphan may still hold the device, so
    the caller must not start further device work."""


def _wait_or_abandon(proc, deadline_s: float) -> bool:
    """Poll ``proc`` until exit or deadline; True = exited, False =
    still running (abandoned — the caller must NOT kill it)."""
    deadline = time.monotonic() + deadline_s
    while proc.poll() is None and time.monotonic() < deadline:
        time.sleep(2)
    return proc.poll() is not None


def _collection_in_progress() -> bool:
    """True iff a staged chip collection (tools/tunnel_watch.sh +
    collect_chip_runs*.sh) holds a fresh advisory lock. Two processes
    on the tunnel at once is the documented wedge class, so a
    concurrently-running collection wins and this bench takes the CPU
    fallback — whose chip_evidence field carries the very numbers the
    collection is producing. Stale locks (>3 h — longer than any
    collection pass) are ignored; the collection's own bench
    invocations opt out via BENCH_IGNORE_COLLECT_LOCK."""
    if os.environ.get("BENCH_IGNORE_COLLECT_LOCK") == "1":
        return False
    import glob

    # both homes: committed sweep dirs AND tunnel_watch.sh's default
    # /tmp output dir (its usage line suggests /tmp/tunnel_watch)
    patterns = [
        os.path.join(
            _REPO_ROOT, "tools", "sweep_results", "*", "COLLECTING.lock"
        ),
        "/tmp/tunnel_watch*/COLLECTING.lock",
    ]
    for lock in (p for pat in patterns for p in glob.glob(pat)):
        try:
            age = time.time() - os.path.getmtime(lock)
        except OSError:
            continue
        if age < 3 * 3600:
            print(
                f"bench: chip collection in progress ({lock}, "
                f"{int(age)}s old); yielding the tunnel and falling "
                f"back to CPU",
                file=sys.stderr,
            )
            return True
    return False


def _tpu_available() -> bool:
    """One generous kill-averse probe: device enumeration + a jitted
    op on a real accelerator platform (tools/probe_tpu.py prints one
    JSON line and returns on its own; the subprocess timeout is a
    last resort, not the schedule)."""
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        return False
    if _collection_in_progress():
        return False
    proc = subprocess.Popen(
        [
            sys.executable,
            os.path.join(_REPO_ROOT, "tools", "probe_tpu.py"),
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
    )
    if not _wait_or_abandon(proc, _PROBE_TIMEOUT_S):
        # Budget exhausted while the probe is still mid device-init:
        # abandoned, never killed. The orphan finishes (or errors) on
        # its own and exits.
        print(
            f"bench: TPU probe still initializing after "
            f"{_PROBE_TIMEOUT_S}s; abandoning it (no kill) and "
            f"falling back to CPU",
            file=sys.stderr,
        )
        return False
    stdout = proc.stdout.read() if proc.stdout else ""
    try:
        out = json.loads(stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        print(f"bench: unparseable probe output: {stdout[-200:]}",
              file=sys.stderr)
        return False
    ok = bool(out.get("ok")) and out.get("platform") in ("axon", "tpu")
    if not ok:
        print(f"bench: TPU unavailable ({out})", file=sys.stderr)
    return ok


def _cpu_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # axon hook never registers
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _variant_deadline(variant: str, platform: str) -> int:
    """Per-variant deadline: the slow-compile table reflects the
    remote chip compiler's observed 10-14 min fused-program compiles;
    CPU-fallback compiles are local and fast, so it applies on TPU
    only (otherwise a small BENCH_TOTAL_BUDGET would budget-skip CPU
    variants the old flat deadline measured fine)."""
    if platform == "tpu":
        return _VARIANT_TIMEOUTS.get(variant, _RUN_TIMEOUT_S)
    return _RUN_TIMEOUT_S


def _run_variant(variant: str, platform: str, n: int, iters: int) -> dict:
    """Run one variant in a fresh child; returns its parsed JSON.

    Deadline semantics mirror the probe's: a child past its deadline
    is ABANDONED, never killed — SIGKILLing an axon process
    mid-compile is the known tunnel-wedging event. Output rides
    through temp files so an abandoned child can keep writing without
    blocking anyone."""
    if platform == "tpu":
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    else:
        env = _cpu_env()
    deadline_s = _variant_deadline(variant, platform)
    out_f = tempfile.NamedTemporaryFile(
        mode="w+", suffix=f".{variant}.out", delete=False
    )
    err_f = tempfile.NamedTemporaryFile(
        mode="w+", suffix=f".{variant}.err", delete=False
    )
    # pipeline_e2e_* and population_* time whole query runs
    # (tools/pipeline_bench.py, where n/iters are markers-per-file/
    # file-count); serve_bench drives the resident inference service
    # (tools/serve_bench.py, same n/iters meaning); everything else
    # is a kernel variant through tools/ingest_bench.py
    if variant.startswith(
        ("pipeline_e2e", "population_", "seizure_", "scheduler_",
         "plan_service", "gateway_", "fleet_")
    ):
        script = "pipeline_bench.py"
    elif variant.startswith("serve_"):
        script = "serve_bench.py"
    else:
        script = "ingest_bench.py"
    try:
        proc = subprocess.Popen(
            [
                sys.executable,
                os.path.join(_REPO_ROOT, "tools", script),
                variant,
                str(n),
                str(iters),
            ],
            stdout=out_f,
            stderr=err_f,
            text=True,
            env=env,
        )
        if not _wait_or_abandon(proc, deadline_s):
            err_f.seek(0)
            partial = err_f.read()[-500:]
            raise _Abandoned(
                f"variant {variant} still running after {deadline_s}s; "
                f"abandoned (not killed). stderr tail: {partial}"
            )
        out_f.seek(0)
        err_f.seek(0)
        stdout, stderr = out_f.read(), err_f.read()
    finally:
        # the orphan's writes survive the unlink (fd stays valid);
        # the parent just stops tracking the files
        out_f.close()
        err_f.close()
        os.unlink(out_f.name)
        os.unlink(err_f.name)
    if proc.returncode != 0:
        raise RuntimeError(
            f"variant {variant} rc={proc.returncode}\n{stderr[-1500:]}"
        )
    lines = stdout.strip().splitlines()
    if not lines:
        raise RuntimeError(
            f"variant {variant} rc=0 but printed no JSON line; "
            f"stderr tail: {stderr[-500:]}"
        )
    return json.loads(lines[-1])


def _chip_evidence() -> dict:
    """Freshest on-chip bench + parity records from
    ``tools/sweep_results/*/``, with timestamp and provenance.

    VERDICT r4 weakness 1: three rounds in a row the driver's own
    round-end ``bench.py`` run hit a dead tunnel and recorded
    ``cpu_fallback`` while real measured-silicon numbers sat in the
    sweep artifacts. This embeds the most recent on-chip
    driver-format bench payload (and parity record) as a dated
    supplementary field so the round-end artifact is never blind to
    measured silicon. Only artifacts produced behind a successful TPU
    probe land in ``sweep_results`` (tools/tunnel_watch.sh gates the
    collection on the probe), and cpu_fallback payloads are skipped
    explicitly."""
    import glob

    base = os.path.join(_REPO_ROOT, "tools", "sweep_results")

    def _stamp(path, rec):
        """(ISO timestamp, source) — the payload's own recorded_utc
        when present (bench.py stamps its output since r5), else the
        file mtime. mtime is a FALLBACK only: these artifacts are
        git-tracked, so a clone/checkout rewrites mtimes; the ISO
        string sorts correctly either way and ties break on path
        (round dirs sort r2 < r4 < r4b), keeping selection
        deterministic."""
        if isinstance(rec.get("recorded_utc"), str):
            return rec["recorded_utc"], "payload"
        return (
            time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime(os.path.getmtime(path))
            ),
            "file_mtime",
        )

    def _freshest(pattern, want):
        best = None
        best_key = None
        for path in glob.glob(os.path.join(base, "*", pattern)):
            try:
                if os.path.getsize(path) == 0:
                    continue
                with open(path) as f:
                    rec = json.loads(f.read().strip().splitlines()[-1])
            except (OSError, ValueError, IndexError):
                continue
            if not want(rec):
                continue
            stamp, src = _stamp(path, rec)
            # payload-stamped records outrank mtime-stamped ones
            # OUTRIGHT: after a clone, every unstamped artifact's
            # mtime is checkout time, which would otherwise outrank a
            # genuinely newer self-stamped record
            key = (src == "payload", stamp, path)
            if best_key is None or key > best_key:
                best, best_key = (stamp, path, rec, src), key
        return best

    evidence: dict = {}
    bench = _freshest(
        "bench*.json",
        lambda r: r.get("platform") != "cpu_fallback" and "value" in r,
    )
    if bench is not None:
        stamp, path, rec, stamp_src = bench
        entry = {
            "source": os.path.relpath(path, _REPO_ROOT),
            "recorded_utc": stamp,
            "timestamp_source": stamp_src,
            "platform": "tpu",
            "value": rec.get("value"),
            "unit": rec.get("unit"),
            "vs_baseline": rec.get("vs_baseline"),
            "variants_epochs_per_s": {
                k: v["epochs_per_s"]
                for k, v in rec.get("variants", {}).items()
                if isinstance(v, dict) and "epochs_per_s" in v
            },
        }
        if "pct_of_hbm_roofline" in rec:
            entry["pct_of_hbm_roofline"] = rec["pct_of_hbm_roofline"]
        evidence["bench"] = entry
    parity = _freshest(
        "parity.json", lambda r: r.get("platform") in ("tpu", "axon")
    )
    if parity is not None:
        stamp, path, rec, stamp_src = parity
        evidence["parity"] = {
            "source": os.path.relpath(path, _REPO_ROOT),
            "recorded_utc": stamp,
            "timestamp_source": stamp_src,
            "epoch_sum_bit_exact": rec.get("epoch_sum_bit_exact"),
            "host_feature_sum_bit_exact": rec.get(
                "host_feature_sum_bit_exact"
            ),
        }
    return evidence


def _collect(platform: str) -> dict:
    sizes = _VARIANTS_TPU if platform == "tpu" else _VARIANTS_CPU
    variants: dict = {}
    start = time.monotonic()
    for idx, (name, (n, iters)) in enumerate(sizes.items()):
        remaining = _TOTAL_BUDGET_S - (time.monotonic() - start)
        if idx > 0 and remaining < _variant_deadline(name, platform):
            variants[name] = {"error": "skipped: total budget exhausted"}
            continue
        try:
            r = _run_variant(name, platform, n, iters)
            variants[name] = {
                "epochs_per_s": r["epochs_per_s"],
                "bytes_per_epoch": r["bytes_per_epoch"],
                # the effective batch, verbatim from the child: the
                # bf16 twin deliberately runs at 2x BENCH_BATCH (r4
                # dispatch-amortization finding), so the label alone
                # must not be read as the batch
                "n": r.get("n", n),
            }
            # present only for TPU timings (ingest_bench omits it on
            # CPU so fallback output can't be misread as a roofline)
            if "pct_of_hbm_roofline" in r:
                variants[name]["pct_of_hbm_roofline"] = r[
                    "pct_of_hbm_roofline"
                ]
            if "formulation" in r:
                variants[name]["formulation"] = r["formulation"]
            # attribution fields (ISSUE 1/3): host-plan + feature
            # cache counters and the persistent compile cache dir in
            # effect for the child, so a BENCH-trajectory speedup is
            # attributable to warm plans/features/compiles vs kernel
            # changes; wall_s/accuracy/classifiers carry the
            # pipeline_e2e family's whole-run context, and stages
            # (ISSUE 4) the per-stage wall breakdown behind wall_s
            for extra_field in (
                "plan_cache", "compile_cache", "feature_cache",
                "wall_s", "classifiers", "accuracy", "report_sha256",
                "stages", "population", "serve", "seizure",
                # PR 8 attribution: bandwidth + h2d transfer bytes on
                # every ingest/pipeline line, the decode line's
                # gather-baseline ratio block, the bf16 gate decision,
                # the overlap flag, and the kernel parity deviation
                "bytes_per_s", "h2d_bytes", "gather_baseline",
                "precision", "overlap", "parity_max_abs_dev",
                "plateau",
                # multi-device scale-out attribution: the mesh block
                # (rung, shape, per-device member counts, the
                # sharded_ingest twin ratio) and the member-axis rate
                "mesh", "members_per_s",
                # the pod family's block: 2-process parity verdict,
                # members/sec vs the single-process twin, and the
                # degraded-coordinator evidence
                "multiproc",
                # the multi-tenant executor line: sequential-vs-
                # concurrent walls, per-plan cache attribution, the
                # single-flight and crash-recovery pins
                "scheduler",
                # the networked plan service line: the HTTP dedup
                # pair, the idempotent-resubmit replay, and the
                # many-client soak (submits/sec, hit ratio, isolation)
                "plan_service",
                # the replicated fleet line: takeover attribution +
                # sha parity vs the uninterrupted twin, the journal
                # exactly-once audit, and the survivors' drain codes
                "fleet",
                # the device-aware placement line: makespan ratio vs
                # the pool-disabled twin, per-plan sha parity, and
                # the zero-double-held device-lease audit
                "placement",
            ):
                if extra_field in r:
                    variants[name][extra_field] = r[extra_field]
        except _Abandoned as e:
            # the orphan may still hold the device/tunnel: launching
            # more device children would race it (concurrent tunnel
            # use is the wedge class the no-kill policy avoids), so
            # the rest of the loop is skipped, artifact intact
            variants[name] = {"error": str(e)[:300]}
            for later, _ in list(sizes.items())[idx + 1 :]:
                variants[later] = {
                    "error": "skipped: prior variant abandoned and may "
                    "still hold the device"
                }
            break
        except (RuntimeError, ValueError, KeyError) as e:
            variants[name] = {"error": str(e)[:300]}
    if "epochs_per_s" not in variants.get("einsum", {}):
        raise RuntimeError(f"headline variant failed: {variants}")
    eps = variants["einsum"]["epochs_per_s"]
    # machine-normalized plateau: the cold child embedded the
    # committed BENCH_pr5 reference values; dividing both cold
    # numbers by their artifact's einsum headline removes machine
    # speed from the comparison (this box's load swings 2-4x between
    # runs — a raw-eps plateau claim would measure the weather, not
    # the code; tools/e2e_smoke.py gates the same normalized form)
    cold = variants.get("pipeline_e2e_cold", {})
    plateau = cold.get("plateau")
    if plateau and plateau.get("pr5_einsum_eps") and eps:
        # the artifact-level headline as extra context; the child's
        # own ADJACENT einsum probe (tools/pipeline_bench.py) is the
        # authoritative normalization and is never overwritten here
        plateau["einsum_eps_now"] = eps
        ratio_pr5 = plateau["pr5_cold_eps"] / plateau["pr5_einsum_eps"]
        plateau.setdefault(
            "normalized_ratio", round(cold["epochs_per_s"] / eps, 5)
        )
        plateau.setdefault("pr5_normalized_ratio", round(ratio_pr5, 5))
        plateau.setdefault(
            "beats_pr5_plateau_normalized",
            bool(plateau["normalized_ratio"] > ratio_pr5),
        )
    payload = {
        "metric": (
            "epochs/sec (3ch×1000samp) through dwt-8 feature extraction"
        ),
        "value": eps,
        "unit": "epochs/s",
        "vs_baseline": round(eps / BASELINE_EPOCHS_PER_SEC, 3),
        "variants": variants,
    }
    if "pct_of_hbm_roofline" in variants["einsum"]:
        payload["pct_of_hbm_roofline"] = variants["einsum"][
            "pct_of_hbm_roofline"
        ]
    if platform != "tpu":
        payload["platform"] = "cpu_fallback"
    # self-stamp: downstream provenance (the chip_evidence harvester
    # reading a committed copy of this artifact) must not depend on
    # git-rewritten file mtimes
    payload["recorded_utc"] = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
    )
    # dated chip provenance rides along on EVERY artifact (VERDICT r4:
    # a round-end tunnel outage must not erase the round's silicon
    # evidence); on a live-TPU run it is still useful history
    evidence = _chip_evidence()
    if evidence:
        payload["chip_evidence"] = evidence
    return payload


def main() -> None:
    if _tpu_available():
        try:
            payload = _collect("tpu")
        except (RuntimeError, ValueError) as e:
            print(f"bench: TPU run failed ({e}); CPU fallback", file=sys.stderr)
            payload = _collect("cpu")
    else:
        payload = _collect("cpu")
    # strict JSON at the artifact boundary: children already sanitize
    # their own lines, but the published payload must never carry a
    # bare NaN/Infinity token either (utils/strict_json — non-finite
    # floats serialize as null; pinned in tests/test_bench_contract.py)
    from eeg_dataanalysispackage_tpu.utils import strict_json

    print(strict_json.dumps(payload))


if __name__ == "__main__":
    main()
