#!/usr/bin/env bash
# Launcher parity with the reference's spark.sh / README deployment
# block (spark-submit --class cz.zcu.kiv.Main ... '<query string>'
# with -Dlogfile.name=<log>): run the pipeline from a query string.
#
#   ./run.sh 'info_file=test-data/info.txt&fe=dwt-8&train_clf=logreg&result_path=result.txt'
#
# LOGFILE_NAME is the -Dlogfile.name analogue (obs.configure_logging).
set -euo pipefail
cd "$(dirname "$0")"
exec python -m eeg_dataanalysispackage_tpu.pipeline.cli "$@"
