#!/usr/bin/env bash
# Launcher parity with the reference's spark.sh / README deployment
# block (spark-submit --class cz.zcu.kiv.Main ... '<query string>'
# with -Dlogfile.name=<log>): run the pipeline from a query string.
#
#   ./run.sh 'info_file=test-data/info.txt&fe=dwt-8&train_clf=logreg&result_path=result.txt'
#
# LOGFILE_NAME is the -Dlogfile.name analogue (obs.configure_logging).
set -euo pipefail
cd "$(dirname "$0")"

# Persistent XLA compile cache (docs/caches.md): repeat runs of the
# same query read serialized executables instead of re-paying the
# fused-program compiles (10-14 min on a fresh chip in the r4 sweep).
# Respect an explicit EEG_TPU_COMPILE_CACHE_DIR / JAX standard var;
# EEG_TPU_NO_COMPILE_CACHE=1 opts out (pipeline/builder.py honors it).
if [ "${EEG_TPU_NO_COMPILE_CACHE:-0}" != "1" ]; then
  export EEG_TPU_COMPILE_CACHE_DIR="${EEG_TPU_COMPILE_CACHE_DIR:-${JAX_COMPILATION_CACHE_DIR:-$PWD/.jax_compile_cache}}"
fi

exec python -m eeg_dataanalysispackage_tpu.pipeline.cli "$@"
