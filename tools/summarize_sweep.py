"""Merge a sweep/watcher output directory into one markdown table.

Reads every ``*.json`` in the given directory (one JSON line per file,
the format ``tools/ingest_bench.py`` / ``bench.py`` /
``tpu_parity_check.py`` / ``cost_report.py`` emit), and prints a
BASELINE.md-ready markdown table plus a short parity/bench digest —
the post-recovery bookkeeping (`BASELINE.md` "Achieved" rows,
`docs/ingest_kernel.md` Measured table) without hand-transcription.

Usage: python tools/summarize_sweep.py [/tmp/tunnel_watch]
"""

import json
import os
import sys


def _load(path: str):
    """Parse every JSON line of a file (most tools print exactly one;
    cost_report prints one per program — all are kept)."""
    try:
        with open(path) as f:
            lines = [ln.strip() for ln in f if ln.strip()]
    except OSError:
        return None
    out = []
    for ln in lines:
        if ln.startswith("{"):
            try:
                out.append(json.loads(ln))
            except ValueError:
                pass
    return out or None


def main() -> None:
    d = sys.argv[1] if len(sys.argv) > 1 else "/tmp/tunnel_watch"
    if not os.path.isdir(d):
        sys.exit(f"no such directory: {d}")
    names = sorted(
        f[:-5] for f in os.listdir(d) if f.endswith(".json")
    )
    if not names:
        sys.exit(f"no *.json in {d}")

    bench_rows = []
    cost_rows = []
    other = {}
    for name in names:
        docs = _load(os.path.join(d, f"{name}.json"))
        if not docs:
            other.setdefault(name, []).append("EMPTY (see .err)")
            continue
        for doc in docs:
            if "epochs_per_s" in doc:
                bench_rows.append((name, doc))
            elif "bytes_accessed_per_epoch" in doc or (
                "program" in doc and "error" in doc
            ):
                cost_rows.append(doc)
            else:
                other.setdefault(name, []).append(doc)

    if bench_rows:
        print("## Measured variants\n")
        print(
            "| artifact | variant | epochs/s | % HBM roofline |"
            " formulation | platform |"
        )
        print("|---|---|---|---|---|---|")
        for name, doc in bench_rows:
            eps = doc.get("epochs_per_s")
            eps_s = f"{eps / 1e6:.2f} M" if eps and eps > 1e5 else f"{eps}"
            print(
                f"| {name} | {doc.get('variant', '')} | {eps_s} "
                f"| {doc.get('pct_of_hbm_roofline', '')} "
                f"| {doc.get('formulation', '')} "
                f"| {doc.get('platform', '')} |"
            )
        print()

    if cost_rows:
        print("## Cost model (bytes/epoch, compiled)\n")
        print("| program | bytes/epoch | design | ratio | flops/epoch |")
        print("|---|---|---|---|---|")
        for doc in cost_rows:
            if "error" in doc:
                err = doc["error"][:60].replace("|", "/").replace("\n", " ")
                print(f"| {doc['program']} | ERROR: {err} ||||")
                continue
            print(
                f"| {doc['program']} | {doc['bytes_accessed_per_epoch']} "
                f"| {doc['design_bytes_per_epoch']} "
                f"| {doc['bytes_ratio']} | {doc['flops_per_epoch']} |"
            )
        print()

    for name, docs in other.items():
        print(f"## {name}\n")
        print("```json")
        for doc in docs:
            print(json.dumps(doc, indent=1)[:2000])
        print("```\n")


if __name__ == "__main__":
    main()
