#!/usr/bin/env python3
"""Run a pipeline query under a chaos fault spec and diff against the
fault-free baseline.

The operational form of the chaos-parity acceptance test
(docs/resilience.md): the same query runs twice — once clean, once
with ``faults=<spec>`` injected — and the two
``ClassificationStatistics`` are diffed. Exit 0 = parity (the
resilience machinery absorbed every injected fault); exit 1 = the
runs diverged; exit 2 = the chaos run died outright.

Usage::

    python tools/chaos_run.py 'info_file=...&fe=dwt-8-fused&train_clf=logreg' \
        --faults 'remote.request:p=0.2;ingest.fused:once@1' [--seed 3]

Add ``elastic=true&checkpoint_path=<dir>`` to the query when the spec
injects ``device.step`` errors — mid-train recovery needs the
checkpointed train path. A fresh checkpoint dir per run is required
for a fair diff (pass it in the query; this tool clones the query and
appends ``-chaos`` to the checkpoint path for the faulted run).
"""

import argparse
import difflib
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from eeg_dataanalysispackage_tpu import obs  # noqa: E402
from eeg_dataanalysispackage_tpu.pipeline import builder  # noqa: E402


def _with_param(query: str, name: str, value: str) -> str:
    params = [p for p in query.split("&") if not p.startswith(name + "=")]
    params.append(f"{name}={value}")
    return "&".join(params)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("query", help="pipeline query string (no faults= in it)")
    ap.add_argument("--faults", required=True, help="chaos fault spec")
    ap.add_argument("--seed", type=int, default=0, help="fault-plan seed")
    args = ap.parse_args(argv)

    query_map = builder.get_query_map(args.query)
    if builder.get_raw_param(args.query, "faults"):
        ap.error("put the spec in --faults, not in the query")
    # an exported EEG_TPU_FAULTS would contaminate the "fault-free"
    # baseline through the builder's env fallback — the diff would be
    # meaningless
    import os

    from eeg_dataanalysispackage_tpu.obs import chaos

    if os.environ.pop(chaos.ENV_SPEC, None):
        print(f"(ignoring exported {chaos.ENV_SPEC} for both runs)")
    # the feature cache would make the diff vacuous: the baseline
    # stores the feature matrix, the faulted run hits it and skips the
    # very ingest paths the spec injects into — both runs must
    # exercise the real pipeline
    os.environ["EEG_TPU_NO_FEATURE_CACHE"] = "1"

    print(f"== baseline (no faults) ==", flush=True)
    baseline = builder.PipelineBuilder(args.query).execute()
    base_text = str(baseline)
    print(base_text)

    chaos_query = _with_param(
        _with_param(args.query, "faults", args.faults),
        "faults_seed",
        str(args.seed),
    )
    if "checkpoint_path" in query_map:
        # a warm checkpoint dir would make the chaos run resume the
        # baseline's training instead of running its own
        chaos_query = _with_param(
            chaos_query, "checkpoint_path",
            query_map["checkpoint_path"] + "-chaos",
        )

    before = obs.metrics.snapshot()["counters"]
    print(f"\n== chaos run (faults={args.faults!r}, seed={args.seed}) ==",
          flush=True)
    try:
        chaotic = builder.PipelineBuilder(chaos_query).execute()
    except Exception as e:
        print(f"CHAOS RUN DIED: {type(e).__name__}: {e}")
        return 2
    chaos_text = str(chaotic)
    print(chaos_text)

    after = obs.metrics.snapshot()["counters"]
    events = {
        k: after[k] - before.get(k, 0.0)
        for k in sorted(after)
        if after[k] != before.get(k, 0.0)
        and k.split(".")[0] in ("chaos", "circuit", "elastic", "pipeline")
    }
    print("\n== resilience events ==")
    print(json.dumps(events, indent=2, sort_keys=True))

    if base_text == chaos_text:
        print("\nPARITY: statistics identical under injected faults")
        return 0
    print("\nDIVERGED: statistics differ under injected faults")
    sys.stdout.writelines(
        difflib.unified_diff(
            base_text.splitlines(keepends=True),
            chaos_text.splitlines(keepends=True),
            "baseline", "chaos",
        )
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
