# Targeted round-4b list: what still needs chip time after the full
# r4 sweep (tools/sweep_results/r4) landed. Callers define
# `run name timeout cmd...` first (tools/tunnel_watch.sh).
#
# bank128 is the chip-proven Pallas ingest formulation (probe s7 +
# the n=4096 production run: compiled, parity 2.7e-7). The 131072
# run's compile coincided with the tunnel dying, so re-establish at
# 32768 (single SMEM group, one kernel shape) before the 131072
# 3-group program. The 524288 einsum rows chase the bf16 batch-curve
# finding (dispatch amortization: 69.8% at 524k) for the f32
# headline too. rf_predict faulted the TPU worker once (r4) - one
# retry distinguishes transient from reproducible.
# Advisory collection lock: a concurrently-launched bench.py (the
# driver's round-end run) must not race this sequential collection
# for the tunnel — concurrent tunnel use is the documented wedge
# class. bench.py sees a fresh lock and takes its CPU fallback
# (which embeds the chip evidence this very collection produces);
# the collection's own bench invocations opt out via
# BENCH_IGNORE_COLLECT_LOCK.
touch "$OUT/COLLECTING.lock"
export BENCH_IGNORE_COLLECT_LOCK=1
trap 'rm -f "$OUT/COLLECTING.lock"' EXIT
# refresh the lock at every staged run: the run timeouts sum to ~7 h,
# well past bench.py's 3 h staleness cutoff, so a once-only touch
# would go stale mid-collection (review finding). Wrapping the
# watcher-provided run() keeps the refresh in THIS sourced file —
# tunnel_watch.sh itself is never edited while a live watcher shell
# is part-way through reading it.
eval "orig_$(declare -f run)"
run() { touch "$OUT/COLLECTING.lock"; orig_run "$@"; }

# FIRST in any healthy window (VERDICT r4 weakness 1): a
# driver-format bench artifact with platform=tpu, budget-bounded so
# it records the fast-compiling headline rows and budget-skips the
# cold fused programs rather than burning the window on their
# 10-14 min compiles (they get the full-budget bench_full at the
# end, behind the warmed cache). bench.py embeds this artifact as
# dated chip_evidence in every later bench run, including the
# driver's round-end one.
# probe budget tightened to 240s: the watcher's own probe succeeded
# seconds ago, so a healthy-tunnel init is warm; the budget is only
# the re-init cost, not a cold-tunnel wait
BENCH_PROBE_TIMEOUT=240 BENCH_TOTAL_BUDGET=480 run bench_early 2400 python bench.py
BENCH_PALLAS_MODE=bank128 run bank128_32k 1200 \
  python tools/ingest_bench.py pallas_ingest 32768 10
run einsum_524k 600 python tools/ingest_bench.py einsum 524288 50
# sliced headline: reads 512 of 1000 columns if the subrange read
# fuses; an honest win shows as >100% of roofline at counted bytes
run einsum_sliced 600 python tools/ingest_bench.py einsum_sliced 262144 50
# compact-resident epochs (B, C, 512) at honest 6144 B/epoch - the
# feature-only storage layout's headline
run einsum_512 600 python tools/ingest_bench.py einsum_512 262144 50
# compact x bf16 compound (3072 B/epoch): if both effects hold at the
# 524k dispatch-amortized batch, this is the absolute headline
# candidate (~180M eps at the bf16 twin's 69.8% roofline)
run einsum_512_bf16 600 python tools/ingest_bench.py einsum_512_bf16 524288 50
BENCH_PALLAS_MODE=bank128 run bank128_131k 1800 \
  python tools/ingest_bench.py pallas_ingest 131072 20
run rf_predict_retry 900 python tools/ingest_bench.py rf_predict 262144 10
# if the retry faults the worker again, the lax.map row-chunked form
# separates size-dependent faults from construct faults
BENCH_RF_ROW_CHUNK=8192 run rf_predict_chunked 900 \
  python tools/ingest_bench.py rf_predict 262144 10
BENCH_PALLAS_MODE=bank128 BENCH_TILE_B=64 run bank128_131k_b64 1800 \
  python tools/ingest_bench.py pallas_ingest 131072 20
# the bf16 bank twin: if the f32 bank measures MXU-bound (6.7M
# HIGHEST MACs/epoch), bf16 operands + f32 accumulate are the 4-8x
# unlock; parity gate 5e-3 (bf16 tier envelope, measured 1.9e-3)
BENCH_PALLAS_MODE=bank128_bf16 run bank128_bf16_131k 1800 \
  python tools/ingest_bench.py pallas_ingest 131072 20
# the regular train through the bank128 kernel vs partial's 5.40M:
# the head-to-head that decides whether auto flips to bank
BENCH_FORMULATION=bank run regular_bank 1800 \
  python tools/ingest_bench.py regular_ingest 262144 20
# training straight from the int16 stream via the bank kernel
# (fused regular featurizer inside the SGD step) vs phase's 4.59M
BENCH_FORMULATION=bank run train_raw_bank 1800 \
  python tools/ingest_bench.py train_step_raw 131072 20
# IRREGULAR-stream training through the bank kernel vs
# train_step_block's 1.34M (positions concrete at step build)
run train_bank 1800 python tools/ingest_bench.py train_step_bank 32768 10
# train-step batch curve (VERDICT r4 weakness 6): the 35.4% r4 row
# ran at 131k while the headline ran at 262k; the bf16 batch curve
# showed exactly this dispatch-amortization signature (39.8% @131k
# -> 69.8% @524k), so measure the same step at 262k before blaming
# program bytes
run train_step_262k 900 python tools/ingest_bench.py train_step 262144 30
# the compact train twin: halves the step's dominant read; with
# einsum_512 it decides whether the whole pipeline (features AND
# training) moves to the compact residency
run train_step_512 900 python tools/ingest_bench.py train_step_512 262144 30
# train-step roofline diagnosis (VERDICT r4 weakness 6: 35.4% vs the
# feature-only 69.6%): XLA's own cost model on the train_step /
# feature_step programs — bytes_ratio >> 1 localizes the gap to
# program traffic (optimizer state, loss tail), ~1 means dispatch
run cost_train 1800 python tools/cost_report.py 131072
# warm the persistent compile cache for the driver's bench.py run:
# same shapes bench.py uses for its slowest-compiling variants
BENCH_FORMULATION=phase run warm_regular 1200 \
  python tools/ingest_bench.py regular_ingest 262144 20
run warm_train_raw 1200 python tools/ingest_bench.py train_step_raw 131072 20
BENCH_TOTAL_BUDGET=1800 run bench_full 3600 python bench.py

# evidence hygiene (VERDICT r4 item 9): every chip claim needs its
# raw artifact — flag any run whose JSON came out empty so a number
# can never be cited without a file behind it
: > "$OUT/MISSING.txt"
for f in "$OUT"/*.json; do
  [ -e "$f" ] || continue  # unexpanded glob (no artifacts at all)
  [ -s "$f" ] || basename "$f" >> "$OUT/MISSING.txt"
done
log "hygiene: $(wc -l < "$OUT/MISSING.txt") empty artifacts: $(tr '\n' ' ' < "$OUT/MISSING.txt")"
rm -f "$OUT/COLLECTING.lock"
