# Targeted round-4b list: what still needs chip time after the full
# r4 sweep (tools/sweep_results/r4) landed. Callers define
# `run name timeout cmd...` first (tools/tunnel_watch.sh).
#
# bank128 is the chip-proven Pallas ingest formulation (probe s7 +
# the n=4096 production run: compiled, parity 2.7e-7). The 131072
# run's compile coincided with the tunnel dying, so re-establish at
# 32768 (single SMEM group, one kernel shape) before the 131072
# 3-group program. The 524288 einsum rows chase the bf16 batch-curve
# finding (dispatch amortization: 69.8% at 524k) for the f32
# headline too. rf_predict faulted the TPU worker once (r4) - one
# retry distinguishes transient from reproducible.
BENCH_PALLAS_MODE=bank128 run bank128_32k 1200 \
  python tools/ingest_bench.py pallas_ingest 32768 10
run einsum_524k 600 python tools/ingest_bench.py einsum 524288 50
# sliced headline: reads 512 of 1000 columns if the subrange read
# fuses; an honest win shows as >100% of roofline at counted bytes
run einsum_sliced 600 python tools/ingest_bench.py einsum_sliced 262144 50
# compact-resident epochs (B, C, 512) at honest 6144 B/epoch - the
# feature-only storage layout's headline
run einsum_512 600 python tools/ingest_bench.py einsum_512 262144 50
BENCH_PALLAS_MODE=bank128 run bank128_131k 1800 \
  python tools/ingest_bench.py pallas_ingest 131072 20
run rf_predict_retry 900 python tools/ingest_bench.py rf_predict 262144 10
# if the retry faults the worker again, the lax.map row-chunked form
# separates size-dependent faults from construct faults
BENCH_RF_ROW_CHUNK=8192 run rf_predict_chunked 900 \
  python tools/ingest_bench.py rf_predict 262144 10
BENCH_PALLAS_MODE=bank128 BENCH_TILE_B=64 run bank128_131k_b64 1800 \
  python tools/ingest_bench.py pallas_ingest 131072 20
# the bf16 bank twin: if the f32 bank measures MXU-bound (6.7M
# HIGHEST MACs/epoch), bf16 operands + f32 accumulate are the 4-8x
# unlock; parity gate 5e-3 (bf16 tier envelope, measured 1.9e-3)
BENCH_PALLAS_MODE=bank128_bf16 run bank128_bf16_131k 1800 \
  python tools/ingest_bench.py pallas_ingest 131072 20
# the regular train through the bank128 kernel vs partial's 5.40M:
# the head-to-head that decides whether auto flips to bank
BENCH_FORMULATION=bank run regular_bank 1800 \
  python tools/ingest_bench.py regular_ingest 262144 20
# training straight from the int16 stream via the bank kernel
# (fused regular featurizer inside the SGD step) vs phase's 4.59M
BENCH_FORMULATION=bank run train_raw_bank 1800 \
  python tools/ingest_bench.py train_step_raw 131072 20
# IRREGULAR-stream training through the bank kernel vs
# train_step_block's 1.34M (positions concrete at step build)
run train_bank 1800 python tools/ingest_bench.py train_step_bank 32768 10
# warm the persistent compile cache for the driver's bench.py run:
# same shapes bench.py uses for its slowest-compiling variants
BENCH_FORMULATION=phase run warm_regular 1200 \
  python tools/ingest_bench.py regular_ingest 262144 20
run warm_train_raw 1200 python tools/ingest_bench.py train_step_raw 131072 20
BENCH_TOTAL_BUDGET=1800 run bench_full 3600 python bench.py
