# Targeted round-4b list: what still needs chip time after the full
# r4 sweep (tools/sweep_results/r4) landed. Callers define
# `run name timeout cmd...` first (tools/tunnel_watch.sh).
#
# bank128 is the chip-proven Pallas ingest formulation (probe s7 +
# the n=4096 production run: compiled, parity 2.7e-7). The 131072
# run's compile coincided with the tunnel dying, so re-establish at
# 32768 (single SMEM group, one kernel shape) before the 131072
# 3-group program.
BENCH_PALLAS_MODE=bank128 run bank128_32k 1200 \
  python tools/ingest_bench.py pallas_ingest 32768 10
BENCH_PALLAS_MODE=bank128 run bank128_131k 1800 \
  python tools/ingest_bench.py pallas_ingest 131072 20
BENCH_PALLAS_MODE=bank128 BENCH_TILE_B=64 run bank128_131k_b64 1800 \
  python tools/ingest_bench.py pallas_ingest 131072 20
