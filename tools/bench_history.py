"""Render the BENCH_r*.json round history as one table.

Usage: python tools/bench_history.py [repo_root]

One row per round artifact: platform, headline value, vs_baseline,
roofline, per-variant epochs/s, and (round 5+) the embedded dated
chip_evidence — the at-a-glance evolution of the driver contract
across rounds, without opening five JSON files.
"""

import glob
import json
import os
import sys


def main() -> None:
    root = sys.argv[1] if len(sys.argv) > 1 else "."
    paths = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))
    if not paths:
        sys.exit(f"no BENCH_r*.json under {root}")
    for p in paths:
        try:
            with open(p) as f:
                raw = f.read()
            wrapper = json.loads(raw)
            # the driver wraps the bench line: {"n","cmd","rc","tail"}
            # with the payload as the last JSON line of "tail"
            if "tail" in wrapper and "value" not in wrapper:
                doc = None
                for ln in reversed(wrapper["tail"].splitlines()):
                    if ln.lstrip().startswith("{"):
                        doc = json.loads(ln)
                        break
                if doc is None:
                    print(
                        f"{os.path.basename(p)}: rc={wrapper.get('rc')} "
                        f"no payload line; tail: "
                        f"{wrapper['tail'][-120:]!r}"
                    )
                    continue
            else:
                doc = wrapper
        except (OSError, ValueError, IndexError) as e:
            print(f"{os.path.basename(p)}: unreadable ({e})")
            continue
        plat = doc.get("platform", "tpu")
        head = doc.get("value")
        line = (
            f"{os.path.basename(p)}: platform={plat} "
            f"headline={head/1e6:.2f}M eps" if head else
            f"{os.path.basename(p)}: platform={plat} headline=?"
        )
        if "vs_baseline" in doc:
            line += f" ({doc['vs_baseline']}x target)"
        if "pct_of_hbm_roofline" in doc:
            line += f" {doc['pct_of_hbm_roofline']}% roofline"
        print(line)
        for name, v in doc.get("variants", {}).items():
            if isinstance(v, dict) and "epochs_per_s" in v:
                extra = (
                    f" {v['pct_of_hbm_roofline']}%"
                    if "pct_of_hbm_roofline" in v
                    else ""
                )
                print(
                    f"    {name:18s} {v['epochs_per_s']/1e6:9.3f}M eps"
                    f"{extra}"
                )
            elif isinstance(v, dict) and "error" in v:
                print(f"    {name:18s} ERROR {v['error'][:60]}")
        ce = doc.get("chip_evidence", {})
        if ce.get("bench"):
            b = ce["bench"]
            print(
                f"    chip_evidence: {b['value']/1e6:.2f}M eps "
                f"({b.get('vs_baseline')}x) from {b['source']} "
                f"@ {b['recorded_utc']} [{b.get('timestamp_source')}]"
            )
        if ce.get("parity"):
            pr = ce["parity"]
            print(
                f"    chip parity: epoch_sum_bit_exact="
                f"{pr.get('epoch_sum_bit_exact')} "
                f"feature_sum_bit_exact="
                f"{pr.get('host_feature_sum_bit_exact')} "
                f"({pr['source']})"
            )


if __name__ == "__main__":
    main()
