"""Probe the constructs for a bank128 Pallas ingest kernel on chip.

Round-4 bisect (tools/sweep_results/r4/pallas_bisect.json) proved the
remote compile helper crashes on ANY dynamic-offset lane slice from
VMEM (aligned or not: k4 and k4b), while scalar-prefetch block
indexing, int16 convert, VMEM scratch and HIGHEST dots all compile.
The fix path must therefore cut epoch windows with dynamic SUBLANE
(row) slices over a rows-of-128 layout, absorbing the in-row shift
with a 128-variant operator bank (the block_ingest trick from
ops/device_ingest.py, moved into VMEM). Each step below is one
construct of that kernel, tiny shapes, compiled+run in sequence.
"""
import json
import os
import sys
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

print("platform:", jax.devices()[0].platform, flush=True)

if os.environ.get("PROBE_INTERPRET") == "1":
    # hermetic CPU smoke of the same probe bodies (tests the Python,
    # not Mosaic)
    import functools
    pl.pallas_call = functools.partial(pl.pallas_call, interpret=True)

C = 3          # channels
R = 16         # 128-lane rows per channel chunk
R2 = R // 2    # rows per half-chunk
B = 4          # epochs per tile
SLAB = 8       # rows per epoch slab (8*128=1024 >= 787+127)
K = 64         # probe feature width (multiple of lanes not needed)


def step(name, fn, expect=None):
    try:
        out = np.asarray(fn())
        s = float(out.sum())
        ok = expect is None or abs(s - expect) < 1e-3 * max(1.0, abs(expect))
        print(json.dumps({"step": name, "ok": bool(ok), "sum": s,
                          "expect": expect}), flush=True)
    except Exception as e:
        msg = f"{type(e).__name__}: {e}"
        print(json.dumps({"step": name, "ok": False,
                          "error": msg[:400]}), flush=True)


# s1: dynamic SUBLANE slice from an input ref (the k4 mirror, rows not
# lanes) — the load-bearing construct
def s1():
    def kernel(off_ref, x_ref, o_ref):
        o_ref[:] = x_ref[pl.ds(off_ref[0], 8), :]
    off = jnp.array([37], jnp.int32)
    x = jnp.arange(64 * 128, dtype=jnp.float32).reshape(64, 128)
    gs = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=(1,),
        in_specs=[pl.BlockSpec((64, 128), lambda i, off: (0, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i, off: (0, 0)),
    )
    out = pl.pallas_call(
        kernel, grid_spec=gs,
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
    )(off, x)
    return out


def _s1_expect():
    x = np.arange(64 * 128, dtype=np.float64).reshape(64, 128)
    return float(x[37:45].sum())


# s2: dynamic sublane slice on the MIDDLE dim of a 3D VMEM scratch
# (the slab cut: chunk_ref[c, ds(b, 8), :])
def s2():
    def kernel(off_ref, x_ref, o_ref, ch_ref):
        ch_ref[:, :, :] = x_ref[:].astype(jnp.float32) * 2.0
        for c in range(C):
            o_ref[c, :, :] = ch_ref[c, pl.ds(off_ref[c], SLAB), :]
    off = jnp.array([0, 3, 8], jnp.int32)
    x = jnp.ones((C, R, 128), jnp.int16)
    gs = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=(1,),
        in_specs=[pl.BlockSpec((C, R, 128), lambda i, off: (0, 0, 0))],
        out_specs=pl.BlockSpec((C, SLAB, 128), lambda i, off: (0, 0, 0)),
        scratch_shapes=[pltpu.VMEM((C, R, 128), jnp.float32)],
    )
    return pl.pallas_call(
        kernel, grid_spec=gs,
        out_shape=jax.ShapeDtypeStruct((C, SLAB, 128), jnp.float32),
    )(off, x)


# s3: 3D int16 input block via scalar-prefetched index on the row dim
# (the half-chunk fetch in rows-of-128 layout)
def s3():
    def kernel(hi_ref, a_ref, b_ref, o_ref):
        del hi_ref
        o_ref[:, :R2, :] = a_ref[:].astype(jnp.float32)
        o_ref[:, R2:, :] = b_ref[:].astype(jnp.float32) * 10.0
    hi = jnp.array([2], jnp.int32)
    x = jnp.ones((C, 8 * R2, 128), jnp.int16)
    gs = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=(1,),
        in_specs=[
            pl.BlockSpec((C, R2, 128), lambda i, hi: (0, hi[0], 0)),
            pl.BlockSpec((C, R2, 128), lambda i, hi: (0, hi[0] + 1, 0)),
        ],
        out_specs=pl.BlockSpec((C, R, 128), lambda i, hi: (0, 0, 0)),
    )
    return pl.pallas_call(
        kernel, grid_spec=gs,
        out_shape=jax.ShapeDtypeStruct((C, R, 128), jnp.float32),
    )(hi, x, x)


# s4: write an (SLAB,128) slab into one leading index of a 3D scratch,
# then read the whole scratch back reshaped (B*C, SLAB*128) for a
# HIGHEST dot — the xa accumulation + contraction shape
def s4():
    def kernel(off_ref, x_ref, w_ref, o_ref, xa_ref):
        for i in range(B * C):
            xa_ref[i, :, :] = x_ref[pl.ds(off_ref[i % B], SLAB), :]
        flat = xa_ref[:].reshape(B * C, SLAB * 128)
        o_ref[:] = lax.dot_general(
            flat, w_ref[:], (((1,), (0,)), ((), ())),
            precision=lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )
    off = jnp.array([0, 1, 2, 3], jnp.int32)
    x = jnp.ones((R * 2, 128), jnp.float32)
    w = jnp.full((SLAB * 128, K), 0.5, jnp.float32)
    gs = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=(1,),
        in_specs=[
            pl.BlockSpec((R * 2, 128), lambda i, off: (0, 0)),
            pl.BlockSpec((SLAB * 128, K), lambda i, off: (0, 0)),
        ],
        out_specs=pl.BlockSpec((B * C, K), lambda i, off: (0, 0)),
        scratch_shapes=[pltpu.VMEM((B * C, SLAB, 128), jnp.float32)],
    )
    return pl.pallas_call(
        kernel, grid_spec=gs,
        out_shape=jax.ShapeDtypeStruct((B * C, K), jnp.float32),
    )(off, x, w)


# s5: one-hot shift select on the VPU (iota compare + mul-sum), the
# bank-select construct, fed from a dot result
def s5():
    NV = 8
    def kernel(sh_ref, y_ref, o_ref):
        # shifts ride in VMEM as a (B, 1) int32 operand: SMEM scalar
        # refs only allow scalar loads on TPU, and the one-hot needs
        # the whole vector
        onehot = (
            sh_ref[:]
            == lax.broadcasted_iota(jnp.int32, (B, NV), 1)
        ).astype(jnp.float32)
        yb = y_ref[:].reshape(B, NV, K)
        o_ref[:] = jnp.sum(yb * onehot[:, :, None], axis=1)
    sh = jnp.array([[0], [3], [7], [1]], jnp.int32)
    y = jnp.ones((B, NV * K), jnp.float32)
    return pl.pallas_call(
        kernel,
        in_specs=[
            pl.BlockSpec((B, 1), lambda: (0, 0)),
            pl.BlockSpec((B, NV * K), lambda: (0, 0)),
        ],
        out_specs=pl.BlockSpec((B, K), lambda: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, K), jnp.float32),
    )(sh, y)


# s6: mini end-to-end bank kernel: int16 rows in, per-epoch dynamic
# sublane slab cut, mean-center, flatten, one HIGHEST dot against a
# bank, one-hot select — every construct of the real bank128 kernel
def s6():
    NV = 4
    KK = 16
    def kernel(blk_ref, x_ref, sh_ref, wv_ref, o_ref, ch_ref, xa_ref):
        ch_ref[:, :, :] = x_ref[:].astype(jnp.float32) * 0.5
        for e in range(B):
            for c in range(C):
                xa_ref[e * C + c, :, :] = ch_ref[c, pl.ds(blk_ref[e], SLAB), :]
        flat = xa_ref[:].reshape(B * C, SLAB * 128)
        d = jnp.mean(flat, axis=1, keepdims=True)
        yv = lax.dot_general(
            flat - d, wv_ref[:], (((1,), (0,)), ((), ())),
            precision=lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )  # (B*C, NV*KK)
        onehot = (
            sh_ref[:]
            == lax.broadcasted_iota(jnp.int32, (B, NV), 1)
        ).astype(jnp.float32)
        yb = yv.reshape(B, C, NV, KK)
        o_ref[:] = jnp.sum(
            yb * onehot[:, None, :, None], axis=2
        ).reshape(B, C * KK)
    blk = jnp.array([0, 2, 5, 8], jnp.int32)
    sh = jnp.array([[0], [1], [3], [2]], jnp.int32)
    x = jnp.ones((C, R, 128), jnp.int16)
    wv = jnp.ones((SLAB * 128, NV * KK), jnp.float32)
    gs = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=(1,),
        in_specs=[
            pl.BlockSpec((C, R, 128), lambda i, blk: (0, 0, 0)),
            pl.BlockSpec((B, 1), lambda i, blk: (0, 0)),
            pl.BlockSpec((SLAB * 128, NV * KK), lambda i, blk: (0, 0)),
        ],
        out_specs=pl.BlockSpec((B, C * KK), lambda i, blk: (0, 0)),
        scratch_shapes=[
            pltpu.VMEM((C, R, 128), jnp.float32),
            pltpu.VMEM((B * C, SLAB, 128), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel, grid_spec=gs,
        out_shape=jax.ShapeDtypeStruct((B, C * KK), jnp.float32),
    )(blk, x, sh, wv)


# s5a: the iota-compare mask ALONE (no reshape) — splits s5's crash
# between the mask build and the (B, NV*K) -> (B, NV, K) lane-split
# reshape
def s5a():
    NV = 8
    def kernel(sh_ref, o_ref):
        o_ref[:] = (
            sh_ref[:]
            == lax.broadcasted_iota(jnp.int32, (B, NV * K), 1) // K
        ).astype(jnp.float32)
    sh = jnp.array([[0], [3], [7], [1]], jnp.int32)
    return pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec((B, 1), lambda: (0, 0))],
        out_specs=pl.BlockSpec((B, NV * K), lambda: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, NV * K), jnp.float32),
    )(sh)


# s5b: reshape-free select — lane-iota mask * y, then a STATIC 0/1
# fold matrix dot collapses the strided variant groups (MXU, no
# relayout). The production select if s5's reshape is the crasher.
def s5b():
    NV = 8
    fold = np.zeros((NV * K, K), np.float32)
    for v in range(NV):
        fold[v * K : (v + 1) * K, :] = np.eye(K, dtype=np.float32)
    def kernel(sh_ref, y_ref, f_ref, o_ref):
        mask = (
            sh_ref[:]
            == lax.broadcasted_iota(jnp.int32, (B, NV * K), 1) // K
        ).astype(jnp.float32)
        o_ref[:] = lax.dot_general(
            y_ref[:] * mask, f_ref[:], (((1,), (0,)), ((), ())),
            precision=lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )
    sh = jnp.array([[0], [3], [7], [1]], jnp.int32)
    y = jnp.arange(B * NV * K, dtype=jnp.float32).reshape(B, NV * K)
    return pl.pallas_call(
        kernel,
        in_specs=[
            pl.BlockSpec((B, 1), lambda: (0, 0)),
            pl.BlockSpec((B, NV * K), lambda: (0, 0)),
            pl.BlockSpec((NV * K, K), lambda: (0, 0)),
        ],
        out_specs=pl.BlockSpec((B, K), lambda: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, K), jnp.float32),
    )(sh, y, jnp.asarray(fold))


def _s5b_expect():
    y = np.arange(B * NV_ * K, dtype=np.float64).reshape(B, NV_ * K)
    sh = [0, 3, 7, 1]
    return float(sum(y[b, sh[b] * K : (sh[b] + 1) * K].sum()
                     for b in range(B)))


NV_ = 8


# s7: mini bank kernel, production constructs only: dynamic sublane
# slab cut + mean center + bank dot + reshape-free mask/fold select,
# output (B*C, K) rows (the (B, C*K) packing happens outside in XLA)
def s7():
    NV = 4
    KK = 16
    fold = np.zeros((NV * KK, KK), np.float32)
    for v in range(NV):
        fold[v * KK : (v + 1) * KK, :] = np.eye(KK, dtype=np.float32)
    def kernel(blk_ref, x_ref, sh_ref, wv_ref, f_ref, o_ref,
               ch_ref, xa_ref):
        ch_ref[:, :, :] = x_ref[:].astype(jnp.float32) * 0.5
        for e in range(B):
            for c in range(C):
                xa_ref[e * C + c, :, :] = ch_ref[c, pl.ds(blk_ref[e], SLAB), :]
        flat = xa_ref[:].reshape(B * C, SLAB * 128)
        d = jnp.mean(flat, axis=1, keepdims=True)
        yv = lax.dot_general(
            flat - d, wv_ref[:], (((1,), (0,)), ((), ())),
            precision=lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )  # (B*C, NV*KK)
        mask = (
            sh_ref[:]
            == lax.broadcasted_iota(jnp.int32, (B * C, NV * KK), 1) // KK
        ).astype(jnp.float32)
        o_ref[:] = lax.dot_general(
            yv * mask, f_ref[:], (((1,), (0,)), ((), ())),
            precision=lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )
    blk = jnp.array([0, 2, 5, 8], jnp.int32)
    # per-ROW shifts (epoch's shift repeated for each channel row)
    sh = jnp.asarray(
        np.repeat([0, 1, 3, 2], C)[:, None].astype(np.int32)
    )
    x = jnp.ones((C, R, 128), jnp.int16)
    wv = jnp.ones((SLAB * 128, NV * KK), jnp.float32)
    gs = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1, grid=(1,),
        in_specs=[
            pl.BlockSpec((C, R, 128), lambda i, blk: (0, 0, 0)),
            pl.BlockSpec((B * C, 1), lambda i, blk: (0, 0)),
            pl.BlockSpec((SLAB * 128, NV * KK), lambda i, blk: (0, 0)),
            pl.BlockSpec((NV * KK, KK), lambda i, blk: (0, 0)),
        ],
        out_specs=pl.BlockSpec((B * C, KK), lambda i, blk: (0, 0)),
        scratch_shapes=[
            pltpu.VMEM((C, R, 128), jnp.float32),
            pltpu.VMEM((B * C, SLAB, 128), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel, grid_spec=gs,
        out_shape=jax.ShapeDtypeStruct((B * C, KK), jnp.float32),
    )(blk, x, sh, wv, jnp.asarray(fold))


if __name__ == "__main__":
    steps = [
        ("s1_dyn_sublane_input", s1, _s1_expect()),
        ("s2_dyn_sublane_scratch_3d", s2, None),
        ("s3_3d_block_fetch", s3, None),
        ("s4_slab_write_reshape_dot", s4, None),
        ("s5_onehot_select", s5, None),
        ("s5a_iota_mask", s5a, None),
        ("s5b_mask_fold_select", s5b, _s5b_expect()),
        ("s6_mini_bank_kernel", s6, None),
        ("s7_mini_bank_maskfold", s7, None),
    ]
    for name, fn, expect in steps:
        step(name, fn, expect)
    print("done", flush=True)
